# Capability parity with the reference Makefile (test/coverage/doc/install)
# plus the native-library build.

PYTHON ?= python

.PHONY: test coverage doc install native clean bench milestone-corpus dryrun lint-check trace-check race-check meter-check obs-check fault-check chaos-check perf-check serve-check stream-check flywheel-check soak-check scope-check promote-check endure-check scene-check

test: lint-check trace-check race-check meter-check obs-check fault-check chaos-check perf-check stream-check serve-check flywheel-check soak-check scope-check promote-check endure-check scene-check
	$(PYTHON) -m pytest tests/ -q

# Static-analysis gate (runs FIRST: it needs no jax, no device and ~2 s):
# disco-lint walks disco_tpu/, bench.py and __graft_entry__.py and enforces
# the repo's contracts as AST rules — fence discipline (DL001), batched
# readbacks (DL002), complex-safe transfers (DL003), atomic-only artifact
# writes (DL004), jax-free serve client / lazy-jax CLIs (DL005), reference
# citations (DL006), traced-float literals (DL007), never-SIGKILL (DL008),
# registered obs kinds / chaos seams (DL009/DL010), explicit scan unroll
# in the bit-exactness-gated modules (DL011), fused-magnitude /
# precision-seam discipline (DL012: no abs(stft(...)), no bfloat16
# literals outside ops/), registered thread primitives (DL015:
# Thread/Timer targets and Lock creations outside the disco-race
# role/lock registries), and seam-routed fused-solver selection (DL016:
# no direct fused_mwf_*/rank1_gevd_fused calls or 'fused' literal
# comparisons outside ops/ and the beam/filters.py dispatch table).
# Zero unsuppressed findings, and every
# suppression must carry a justification (DL000).
# Hermetic by construction: the linter is stdlib-only and never touches
# the chip claim (doc/source/static_analysis.rst).
lint-check:
	$(PYTHON) -m disco_tpu.analysis.cli

# Program-contract gate (the eighth gate, right after lint: both are cheap
# and hermetic, so they fail fast before the heavy gates): disco-trace
# traces the canonical hot-path programs on declared abstract inputs and
# diffs their structural fingerprints (primitive multiset + sequence hash,
# avals, scan unroll parameters, host-callback presence, dtype hygiene)
# against the goldens committed under disco_tpu/analysis/golden/; runs the
# retrace-budget workload (every counted_jit label held to an exact
# per-label program count — the mu=1 trap, caught behaviorally); verifies
# declared donation survives into the lowered modules' input-output
# aliasing; and asserts the serve scheduler's CPU step IS the offline
# jitted entry point.  The goldens include the disco-chain programs
# (tango_clip_fused / streaming_clip_fused: the whole clip as ONE program
# with no spectrogram escaping the output avals, and the step-1
# fused-vs-eigh pair).  CPU forced twice over (env here + ensure_cpu in the
# checker): tracing must never claim the tunneled chip
# (doc/source/static_analysis.rst, "Program-level contracts").
trace-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.analysis.trace.cli

# Thread-contract gate (the thirteenth gate, right after trace-check —
# hermetic and stdlib-only like lint, so it fails fast before the heavy
# gates): disco-race builds a call graph rooted at the declared thread-role
# registry (race/roles.py) and enforces the concurrency invariants that
# lived only in docstrings until PR 13 — every Thread/Timer/executor/signal
# spawn resolves to a registered role (DR001), jax-touching code reachable
# only from jax_ok roles (DR002: the single-chip-claim contract), signal
# handlers restricted to the flag-set allowlist (DR003: the PR 3
# handler-in-lock bug class, now structural), no blocking calls under a
# held lock (DR004), every lock registered + the global lock-acquisition
# graph acyclic (DR005/DR006), no cross-role unlocked shared writes
# (DR007), and the committed concurrency manifest
# (disco_tpu/analysis/golden/threads.json) reproduced bit-identically
# (DR008; `disco-race --update` after a REVIEWED topology change).  Zero
# unsuppressed findings; every waiver justified (DR000).  No jax import
# anywhere in the analyzer (pinned by test) — never touches the chip claim
# (doc/source/static_analysis.rst, "Thread contracts").
race-check:
	$(PYTHON) -m disco_tpu.analysis.race.cli

# Cost-manifest gate (the fourteenth gate, right after race-check — cheap
# and hermetic like trace-check, whose abstract tracing it reuses):
# disco-meter walks every canonical hot-path program's jaxpr with the
# analytic cost model (analysis/meter/costmodel.py) and diffs the
# resulting manifests — flops, HBM traffic with per-iteration scan-carry
# accounting and VMEM-resident fused islands at boundary cost, boundary
# bytes, peak-live-bytes, per-primitive-class breakdown, an EXPLICIT
# unmodeled bucket — against the goldens committed under
# disco_tpu/analysis/golden/cost/; enforces the declared budgets (the
# unmodeled-traffic ceiling, and the fused step-2 AND batch-in-lanes
# step-1 solves each modeling strictly fewer HBM bytes than their
# separate-stage eigh paths — the solve-fusion and disco-chain theses as
# hard inequalities); and keeps the trace catalog and the
# manifest directory in exact sync (a program added without a manifest
# fails, as does a stale manifest).  `disco-meter --update` after a
# REVIEWED cost change (doc/source/observability.rst, "Reading the
# roofline").
meter-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.analysis.meter.cli

# Telemetry gates (run before the suite so drift fails fast):
# 1. the bench trajectory must not regress between the last two committed
#    rounds (disco_tpu.cli.obs compare exits 1 on a >5% headline RTF drop);
#    the two newest BENCH_r*.json are picked up automatically so the gate
#    never goes stale when a new round's artifact lands;
# 2. the JSONL event schema the obs subsystem emits must validate
#    (tests/test_obs.py -k schema re-emits every producer and re-reads it).
obs-check:
	$(PYTHON) -m disco_tpu.cli.obs compare $$(ls BENCH_r*.json | sort | tail -2)
	$(PYTHON) -m pytest tests/test_obs.py -q -k "schema"

# Fault-tolerance gate: inject a node dropout + a NaN z on a synthetic CPU
# scene, assert finite degraded-mode output and the expected obs fault
# events (disco_tpu/fault/check.py).  CPU forced: a bare python run would
# otherwise claim the tunneled chip (environment contract).
fault-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= $(PYTHON) -m disco_tpu.fault.check

# Crash-safety gate: interrupt a miniature corpus run at injected crash
# seams (mid-write / between-clips), resume it, and assert the artifact
# tree is byte-identical to an uninterrupted run with corrupt partials
# requeued (disco_tpu/runs/check.py).  Zero SIGKILLs by construction —
# crashes are simulated in-process (environment contract).
chaos-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= $(PYTHON) -m disco_tpu.runs.check

# Corpus-throughput-engine gate: run the miniature corpus through the
# pipelined prefetch/dispatch/readback engine AND the sequential escape
# hatch on CPU, assert byte-identical artifact trees, one batched readback
# per chunk (device_get_batches), the overlap gauges recorded, the fused
# kernels (spec+mag STFT, folded covariances, the VMEM-resident rank-1
# GEVD-MWF solve in interpret mode) at parity with the unfused reference
# formulations, the step-1 fused K×F batch at parity with the
# separate-stage eigh step-1 on both impl lanes, and that bench.py still
# prints exactly ONE JSON line now carrying corpus_clips_per_s, the
# solve-lane provenance and the disco-chain lanes (rtf_chained_clip /
# rtf_fused_step1 with their stage_ms rows)
# (disco_tpu/enhance/check.py).
perf-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= $(PYTHON) -m disco_tpu.enhance.check

# Super-tick gate: the scanned multi-block streaming driver
# (streaming_tango_scan) must be bit-identical to the per-block host loop —
# fault-free, under z_avail holds spanning super-tick edges, through state
# continuation and a non-multiple-of-N tail — and a super-tick serve
# scheduler must satisfy the readback-count invariant (device_get_batches
# == super-ticks: fenced dispatches per block <= 1/N + the per-block tail).
# Hermetic: CPU, compile cache off, one JAX process, zero SIGKILLs
# (disco_tpu/enhance/stream_check.py).
stream-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.enhance.stream_check

# Online-serving gate: run the enhancement server in-process on CPU with
# >=4 concurrent numpy-only streaming clients over loopback and assert the
# serve contract: every session's output bit-identical to the offline
# streaming_tango run, ONE batched readback per scheduler tick, a graceful
# drain with zero truncated/lost frames + atomic session checkpoints that
# resume bit-exactly, chaos crashes (serve_tick / mid_write) that never
# corrupt a delivered frame or a checkpoint, and the chained
# (domain="time") lane bit-matching the offline streaming_clip_fused twin
# with continuation state (disco_tpu/serve/check.py).
# Hermetic like perf-check: compile cache off, loopback only, one JAX
# process, zero SIGKILLs.
serve-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.serve.check

# Flywheel gate (the tenth gate): close the serve→train loop end to end —
# loopback serve traffic with the corpus tap on (zero drops, the
# one-batched-readback-per-tick invariant intact), clean shard digests
# verified through the manifest ledger, an injected mid_write chaos crash
# that must leave NO torn shard at a final path (and a planted truncated
# shard the dataset must skip loudly), deterministic + ledger-resumable
# dataset replay, then data-parallel CRNN training on the 8-virtual-device
# mesh with loss parity vs the single-device oracle (bit-exact on the
# 1-device mesh; documented MESH_LOSS_RTOL across shards) and the
# ChunkPrefetcher batch-feed overlap gauges + explicit epochs_done
# checkpoint field pinned (disco_tpu/flywheel/check.py).  Hermetic: CPU
# forced, 8 virtual devices, compile cache off, loopback only, one JAX
# process, zero SIGKILLs.
flywheel-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PYTHON) -m disco_tpu.flywheel.check

# Chaos-soak gate (the eleventh gate): disco-soak composes the existing
# fault primitives — chaos seams, protocol truncation, hard connection
# drops, slow clients, injected TRANSPORT_ERRORS through the scheduler's
# fakeable dispatch hook — into >= 5 seeded randomized multi-fault
# campaigns against a loopback server on CPU and asserts the survival
# invariants after every run: no torn session checkpoint or tap shard,
# no delivered frame lost or duplicated, every parked session reattached
# bit-exact vs offline streaming_tango, recovery within the declared tick
# bound, and a byte-stable per-seed event summary (the first seed literally
# runs twice and the summaries must match byte for byte).  The final seed
# adds the crash leg: a parked session's checkpoint survives a ChaosCrash
# server death and resumes bit-exact on a fresh server via its resume
# token.  Hermetic: CPU, loopback only, compile cache off, one JAX
# process, zero SIGKILLs (disco_tpu/runs/soak.py).
soak-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.runs.soak

# Causal-scope gate (the twelfth gate): disco-scope runs a loopback serve
# cycle with causal tracing, the flight recorder and the corpus tap all
# armed and asserts (1) every delivered frame reconstructs a COMPLETE
# causal chain client_block → enqueue → dispatch → readback → deliver →
# tap with intact parent links, bit-exact outputs, and a pre-span client
# served unchanged with zero spans; (2) the read-only `status` protocol
# frame agrees with the counters registry exactly and the SLO evaluator
# judges it; (3) an injected transport fault quarantines the session and
# produces a byte-stable flight-recorder dump naming the failing span,
# after which the stream still finishes bit-exact.  Hermetic: CPU,
# loopback only, compile cache off, one JAX process, zero SIGKILLs
# (disco_tpu/obs/scope.py).
scope-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.obs.scope

# Live-promotion gate (the fifteenth gate): drill the canary/gate/rollback
# ladder on a loopback CPU server — a worse-on-purpose candidate is staged
# against a live incumbent, canaried onto a fraction of the model-mask
# sessions at an atomic block boundary, fails the SDR gate and rolls back
# with every delivered frame of every session bit-exact against the
# per-generation offline oracle and a flight-recorder demotion dump naming
# the failing metric; a good candidate dropped into the watch directory
# auto-stages, passes the SDR+SLO gate and promotes (ACTIVE pointer flip,
# model_promotions / weight_generation / tap_to_promotion_ms recorded); a
# ChaosCrash at the dispatch thread's pre_swap seam mid-rollout leaves no
# torn weight file, checkpoint or pointer and the restarted server settles
# the interrupted rollout from the ledger, resumes the checkpointed canary
# bit-exact and still promotes a fresh candidate; mid_canary / post_gate
# crashes kill the controller thread alone — serving continues bit-exact
# and a fresh controller's ledger replay rolls the orphan back.  Hermetic:
# CPU, loopback only, compile cache off, one JAX process, zero SIGKILLs
# (disco_tpu/promote/check.py).
promote-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.promote.check

# Endurance gate (the sixteenth gate): disco-endure runs the WHOLE flywheel
# co-resident — loopback serving, the corpus tap, the resident trainer
# interleaving train-step slices on the dispatch thread, and the promotion
# controller — through >= 3 full tap→train→publish→canary→promote
# generations over ONE shared store/tap/ledger tree, crashing each
# component at its seams along the way (mid_epoch, pre_publish,
# between_generations, pre_swap, mid_canary) and asserting after every
# restart: delivered frames bit-exact vs offline streaming_tango, a
# monotone promoted-generation lineage with no torn weight file or
# checkpoint, trainer ledger resume with ZERO re-consumed shard-epoch
# units, recovery to the next promotion within a paced-round bound (never
# wall-clock), the serve SLO green throughout, and a byte-stable summary.
# Hermetic: CPU, loopback only, compile cache off, one JAX process, zero
# SIGKILLs (disco_tpu/runs/endure.py).
endure-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.runs.endure

# Scenario-factory gate (the seventeenth gate): disco-scenes must hold the
# batched-simulation contract end to end — shoebox_rirs_batched at parity
# with the inlined float64 NumPy image-source oracle AND bit-close to the
# per-scene shoebox_rirs path under vmap; a B>=8 scene batch simulated as
# exactly ONE fenced dispatch per (max_order, rir_len) bucket (readback +
# retrace accounting, the one-program-per-bucket budget); dynamic scenes'
# overlap-add crossfade strictly smoother than a hard RIR switch at segment
# edges; the batched disco-gen writer crash-resumed at a chaos seam to a
# byte-identical dataset tree (the per-scene (seed, rir_id, stream)
# reseeding discipline); and SceneStream's seeded draws deterministic,
# ledger-resumable mid-epoch, and emitting the registered scene events at
# both the "scenes" and "datagen" stages.  Hermetic: CPU, compile cache
# off, one JAX process, zero SIGKILLs (disco_tpu/scenes/check.py).
scene-check:
	env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= DISCO_TPU_COMPILE_CACHE=off \
	    $(PYTHON) -m disco_tpu.scenes.check

coverage:
	$(PYTHON) -m coverage run --branch --source=disco_tpu -m pytest tests/ -q
	$(PYTHON) -m coverage html

doc:
	$(PYTHON) -m sphinx -b html doc/source doc/build/html

install:
	$(PYTHON) -m pip install -e .

native:
	g++ -O3 -shared -fPIC -pthread disco_tpu/native/fastloader.cpp \
	    -o disco_tpu/native/libfastloader.so
	g++ -O3 -shared -fPIC -pthread disco_tpu/native/fastwav.cpp \
	    -o disco_tpu/native/libfastwav.so

bench:
	$(PYTHON) bench.py

dryrun:  # multi-chip sharding validation on 8 virtual CPU devices
	XLA_FLAGS=--xla_force_host_platform_device_count=8 $(PYTHON) -c \
	    "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

# full generate→mix→train→enhance pipeline on self-generated corpus data,
# reporting oracle vs trained-CRNN TANGO deltas (VERDICT round-1 item 5)
milestone-corpus:
	$(PYTHON) -m disco_tpu.milestones_corpus

clean:
	rm -rf build dist *.egg-info htmlcov .coverage doc/build
	rm -f disco_tpu/native/libfastloader.so disco_tpu/native/libfastwav.so
	find . -name __pycache__ -type d -exec rm -rf {} +
