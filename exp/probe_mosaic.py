"""Bisect which Pallas construct the Mosaic lowering rejects on this chip.

The fused covariance kernel compiles in interpret mode but returns
UNIMPLEMENTED from the real TPU compiler; this ladder isolates the
offending construct (run with the repo root on sys.path, one claim cycle).
"""
import sys

sys.path.insert(0, "/root/repo")

import json
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

B, C, T, Fp = 1, 4, 130, 128


def run_case(name, kernel, n_out, out_dims, in_specs, out_specs, args):
    import time as _t

    t0 = _t.time()
    try:
        outs = pl.pallas_call(
            kernel,
            grid=(B, 1),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=[jax.ShapeDtypeStruct(d, jnp.float32) for d in out_dims],
        )(*args)
        jax.block_until_ready(outs)
        v = float(jnp.ravel(outs[0])[0])
        r = {"ok": True, "v": round(v, 4), "s": round(_t.time() - t0, 1)}
    except Exception as e:
        r = {"ok": False, "error": f"{type(e).__name__}: {e}"[:160], "s": round(_t.time() - t0, 1)}
    # incremental JSONL on stderr: a hang on a later case must not lose
    # the earlier verdicts (round-5 lesson: probe_jacobi hung >9 min silent)
    print(json.dumps({name: r}), file=sys.stderr, flush=True)
    return r


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, C, T, Fp)).astype(np.float32))
    m = jnp.asarray(rng.standard_normal((B, T, Fp)).astype(np.float32))

    spec4 = pl.BlockSpec((1, C, T, Fp), lambda b, f: (b, 0, 0, f))
    spec3 = pl.BlockSpec((1, T, Fp), lambda b, f: (b, 0, f))
    ospec = pl.BlockSpec((1, C, C, Fp), lambda b, f: (b, 0, 0, f))
    oshape = (B, C, C, Fp)
    results = {}

    def k_copy(x_ref, o_ref):
        o_ref[0, 0, 0, :] = x_ref[0, 0, 0, :]

    results["copy_lane_row"] = run_case(
        "copy", k_copy, 1, [oshape], [spec4], [ospec], (x,))

    def k_reduce(x_ref, o_ref):
        o_ref[0, 0, 0, :] = jnp.sum(x_ref[0, 0], axis=0)

    results["sublane_reduce_store"] = run_case(
        "reduce", k_reduce, 1, [oshape], [spec4], [ospec], (x,))

    def k_reduce_all(x_ref, o_ref):
        for c in range(C):
            for d in range(C):
                o_ref[0, c, d, :] = jnp.sum(x_ref[0, c] * x_ref[0, d], axis=0)

    results["pairwise_loop"] = run_case(
        "pairloop", k_reduce_all, 1, [oshape], [spec4], [ospec], (x,))

    def k_mask3d(x_ref, m_ref, o_ref):
        w = m_ref[0] * m_ref[0]
        o_ref[0, 0, 0, :] = jnp.sum(w * x_ref[0, 0], axis=0)

    results["mask3d_input"] = run_case(
        "mask3d", k_mask3d, 1, [oshape], [spec4, spec3], [ospec], (x, m))

    def k_4out(x_ref, o1, o2, o3, o4):
        s = jnp.sum(x_ref[0, 0], axis=0)
        o1[0, 0, 0, :] = s
        o2[0, 0, 0, :] = s
        o3[0, 0, 0, :] = -s
        o4[0, 0, 0, :] = 2.0 * s

    results["four_outputs"] = run_case(
        "4out", k_4out, 4, [oshape] * 4, [spec4], [ospec] * 4, (x,))

    # the real kernel, via its public wrapper (T=130 unaligned sublanes)
    from disco_tpu.ops.cov_ops import masked_cov_pallas
    from disco_tpu.utils.transfer import to_device

    # complex arrays go through to_device (two real transfers + on-device
    # combine): the tunnel's host<->device path lacks complex dtypes, and
    # the eager jnp slice of a complex array dies the same way (this very
    # line cost round 5 a probe run)
    y_np = (rng.standard_normal((B, C, 257, T)) + 1j * rng.standard_normal((B, C, 257, T))).astype(np.complex64)
    mm_np = rng.uniform(size=(B, 257, T)).astype(np.float32)
    import time as _t

    for name, yv, mv in (
        ("full_kernel_T130", to_device(y_np), to_device(mm_np)),
        # aligned frame count (T=128): is unaligned sublane blocking the issue?
        ("full_kernel_T128", to_device(y_np[..., :128]), to_device(mm_np[..., :128])),
    ):
        t0 = _t.time()
        try:
            Rss, _ = masked_cov_pallas(yv, mv, interpret=False)
            jax.block_until_ready(Rss)
            results[name] = {"ok": True, "s": round(_t.time() - t0, 1)}
        except Exception as e:
            results[name] = {"ok": False, "error": f"{type(e).__name__}: {e}"[:160], "s": round(_t.time() - t0, 1)}
        print(json.dumps({name: results[name]}), file=sys.stderr, flush=True)

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
