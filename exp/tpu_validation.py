"""One-shot hardware validation sweep: run everything that needs the real
chip in a single claim cycle (the tunneled chip's claim/release can take
minutes, and the service occasionally goes down for hours — see
tests/conftest.py and the verify skill for the environment contract).

Covers: headline bench (RTF/MFU/stages), the CRNN corpus batched-vs-per-RIR
A/B, and the milestone configs including streaming latency.  Prints one JSON
line per section.

Usage:  python exp/tpu_validation.py [--quick]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root (for bench.py)


def section(name, fn):
    t0 = time.perf_counter()
    try:
        out = fn()
        out = {"section": name, "ok": True, **(out if isinstance(out, dict) else {"result": out})}
    except Exception as e:  # keep sweeping: one bad section must not hide the rest
        out = {"section": name, "ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    out["wall_s"] = round(time.perf_counter() - t0, 1)
    print(json.dumps(out), flush=True)
    return out


def crnn_corpus_ab(B=16, dur_s=4.0):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.driver import _batched_masks, estimate_masks
    from disco_tpu.enhance.tango import tango
    from disco_tpu.milestones import _fence, _scene
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    FS, K, C = 16000, 4, 4
    L = int(dur_s * FS)

    def make(n_ch):
        model, tx = build_crnn(n_ch=n_ch)
        st = create_train_state(model, tx, np.zeros((1, n_ch, 21, 257), "float32"))
        return (model, {"params": st.params, "batch_stats": st.batch_stats})

    models = (make(1), make(K))
    clips = [_scene(K, C, L, seed=i) for i in range(B)]
    Ys = [stft(jnp.asarray(y)) for y, s, n in clips]
    Ss = [stft(jnp.asarray(s)) for y, s, n in clips]
    Ns = [stft(jnp.asarray(n)) for y, s, n in clips]

    run1 = jax.jit(lambda Y, S, N, mz, mw: tango(Y, S, N, mz, mw, policy="local").yf)
    mz, mw = estimate_masks(Ys[0], Ss[0], Ns[0], models, "irm1", K)
    _fence(run1(Ys[0], Ss[0], Ns[0], mz, mw))
    t0 = time.perf_counter()
    for i in range(B):
        mz, mw = estimate_masks(Ys[i], Ss[i], Ns[i], models, "irm1", K)
        _fence(run1(Ys[i], Ss[i], Ns[i], mz, mw))
    t_per = time.perf_counter() - t0

    Yb, Sb, Nb = jnp.stack(Ys), jnp.stack(Ss), jnp.stack(Ns)
    runB = jax.jit(
        lambda Yb, Sb, Nb, Mz, Mw: jax.vmap(
            lambda Y, S, N, mz, mw: tango(Y, S, N, mz, mw, policy="local").yf
        )(Yb, Sb, Nb, Mz, Mw)
    )
    Mz, Mw = _batched_masks(Yb, Sb, Nb, models, "irm1", 1.0, K, "zs_hat")
    _fence(runB(Yb, Sb, Nb, Mz, Mw))
    t0 = time.perf_counter()
    Mz, Mw = _batched_masks(Yb, Sb, Nb, models, "irm1", 1.0, K, "zs_hat")
    _fence(runB(Yb, Sb, Nb, Mz, Mw))
    t_bat = time.perf_counter() - t0
    return {
        "per_rir_ms_per_clip": round(t_per / B * 1e3),
        "batched_ms_per_clip": round(t_bat / B * 1e3),
        "speedup": round(t_per / t_bat, 2),
    }


def solver_ab(B=16, dur_s=10.0, iters=3):
    """Round-3 queue #2: A/B the rank-1 GEVD solver families on-device at
    the headline batch — slope-timed RTF per solver plus SDR agreement vs
    the eigh reference output, so the offline default can be flipped (or
    kept) on measured numbers.  Also A/Bs the fused covariance kernel."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from bench import _slope_time
    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.core.metrics import si_sdr
    from disco_tpu.enhance import oracle_masks
    from disco_tpu.enhance.tango import tango
    from disco_tpu.milestones import _scene

    FS, K, C = 16000, 8, 4
    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    yb = jnp.asarray(np.stack([y] * B))
    sb = jnp.asarray(np.stack([s] * B))
    nb = jnp.asarray(np.stack([n] * B))

    def make(solver, cov_impl="xla"):
        @jax.jit
        def run(yb, sb, nb):
            def one(y, s, n):
                Y, S, N = stft(y), stft(s), stft(n)
                m = oracle_masks(S, N, "irm1")
                return tango(Y, S, N, m, m, policy="local", solver=solver,
                             cov_impl=cov_impl).yf
            return jax.vmap(one)(yb, sb, nb)
        return run

    audio_s = B * K * dur_s
    out = {}
    ref_t = None  # set ONLY by the eigh lane — agreement numbers must never
    # silently re-anchor to whichever lane happened to succeed first
    for name, solver, cov in (
        ("eigh", "eigh", "xla"),
        ("power", "power", "xla"),
        ("jacobi", "jacobi", "xla"),
        ("jacobi-pallas", "jacobi-pallas", "xla"),
        ("eigh+covfused", "eigh", "pallas"),
    ):
        try:
            run = make(solver, cov)
            yf = run(yb, sb, nb)
            dt, _ = _slope_time(run, yb, sb, nb, iters=iters)
            lane = {"rtf": round(audio_s / dt, 1), "ms_per_batch": round(dt * 1e3, 2)}
            if name == "eigh":
                ref_t = np.asarray(istft(yf[0], length=L), np.float64)
            elif ref_t is not None:
                est_t = np.asarray(istft(yf[0], length=L), np.float64)
                lane["si_sdr_vs_eigh_db"] = round(
                    float(np.mean([si_sdr(ref_t[k], est_t[k]) for k in range(K)])), 2
                )
            else:
                lane["si_sdr_vs_eigh_db"] = None  # eigh lane failed: no anchor
        except Exception as e:
            lane = {"error": f"{type(e).__name__}: {e}"[:200]}
        out[name] = lane
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true", help="smaller scales")
    args = p.parse_args(argv)

    import bench as bench_mod
    from disco_tpu import milestones

    if args.quick:
        # bench_jax returns the report dict directly (rtf, rtf_eigh,
        # dispatch_overhead_ms, mfu, stage_ms, ...)
        section("bench", lambda: bench_mod.bench_jax(batch=4, dur_s=4.0, iters=2))
        section("solver_ab", lambda: solver_ab(B=2, dur_s=2.0, iters=1))
        section("crnn_corpus_ab", lambda: crnn_corpus_ab(B=4, dur_s=2.0))
        section("milestone_separation", lambda: milestones.meetit_separation(dur_s=2.0, K=4, C=2, iters=1))
        section("streaming_latency", lambda: milestones.streaming_latency(dur_s=2.0, K=2, C=2, iters=1))
        return
    section("bench", bench_mod.bench_jax)
    section("solver_ab", solver_ab)
    section("crnn_corpus_ab", crnn_corpus_ab)
    for name, fn in (
        ("milestone_1", milestones.mvdr_single_clip),
        ("milestone_2", milestones.disco_mwf_4node),
        ("milestone_3", milestones.tango_4node),
        ("milestone_4", milestones.meetit_separation),
        ("milestone_5", milestones.batched_meetit_end_to_end),
        ("milestone_6", milestones.streaming_latency),
    ):
        section(name, fn)


if __name__ == "__main__":
    main()
