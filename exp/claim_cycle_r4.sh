#!/bin/bash
# Round-4 claim cycle A: every staged hardware measurement, sequentially,
# one JAX process at a time (the environment contract — CLAUDE.md).
# Fast compile probes first (seconds of signal on the round-4 kernel
# rewrites), then the tuning + validation sweeps, then the bench artifact.
set -u
cd /root/repo
log() { echo "=== $1 ($(date +%H:%M:%S)) ==="; }

log "probe_jacobi (scatter-free kernel compile check)"
python exp/probe_jacobi.py > exp/probe_jacobi_r4.json 2> exp/probe_jacobi_r4.err
log "probe_mosaic (covfused bisect ladder)"
python exp/probe_mosaic.py > exp/probe_mosaic_r4.json 2> exp/probe_mosaic_r4.err
log "probe_cov (covfused full-kernel parity)"
python exp/probe_cov.py > exp/probe_cov_r4.json 2> exp/probe_cov_r4.err
log "tune_hw (second-wave sweeps)"
python exp/tune_hw.py > exp/tune_hw_r4.jsonl 2> exp/tune_hw_r4.err
log "tpu_validation (bench + solver_ab + crnn_ab + milestones)"
python exp/tpu_validation.py > exp/tpu_validation_r4.jsonl 2> exp/tpu_validation_r4.err
log "bench.py (round artifact rehearsal)"
python bench.py > exp/bench_r4_manual.json 2> exp/bench_r4_manual.err
log "done"
