"""Minimal TPU-attachment probe (round 3).

One process, one claim cycle, graceful exit either way.  Never kill this
externally — a SIGKILL mid-attach is the suspected round-2 wedge trigger
(ROUND2.md).  If the attachment blocks, the process just waits; when the
chip answers it runs one fenced scalar op, prints a JSON line and exits 0.
"""
import json
import sys
import time

t0 = time.time()
try:
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    t_attach = time.time() - t0
    x = jnp.asarray([1.0, 2.0])
    t1 = time.time()
    val = float(jnp.ravel(x + x)[0])  # 1-element readback = real fence
    t_op = time.time() - t1
    print(
        json.dumps(
            {
                "ok": True,
                "devices": [str(d) for d in devs],
                "kind": devs[0].device_kind,
                "attach_s": round(t_attach, 2),
                "fenced_op_s": round(t_op, 3),
                "val": val,
            }
        ),
        flush=True,
    )
except Exception as e:
    print(
        json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500], "after_s": round(time.time() - t0, 2)}),
        flush=True,
    )
    sys.exit(1)
