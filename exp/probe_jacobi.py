"""One-shot Mosaic compile probe for the scatter-free eigh_jacobi_pallas.

Round-3 solver_ab killed the old kernel at lowering ("Unimplemented ...
scatter"); round 4 rewrote the rotation updates as broadcast one-hot
selects (ops/eigh_ops.py).  This probe answers, in seconds, whether the
rewrite actually lowers and agrees with jnp.linalg.eigh on-chip —
before the full solver_ab lane spends minutes on it.
"""
import sys; sys.path.insert(0, "/root/repo")
import json, time
import numpy as np
import jax.numpy as jnp

out = {}
rng = np.random.default_rng(0)
for C in (4, 11):
    B = 2 * 257
    X = rng.standard_normal((B, C, C)) + 1j * rng.standard_normal((B, C, C))
    A = jnp.asarray((X + np.conj(np.transpose(X, (0, 2, 1)))).astype(np.complex64))
    t0 = time.time()
    try:
        from disco_tpu.ops.eigh_ops import eigh_jacobi_pallas
        from disco_tpu.utils.backend import is_tpu

        lam, V = eigh_jacobi_pallas(A, interpret=not is_tpu())
        lam = np.asarray(lam)
        ref = np.linalg.eigvalsh(np.asarray(A))
        err = float(np.max(np.abs(lam - ref)) / np.max(np.abs(ref)))
        out[f"C{C}"] = {"ok": True, "rel_err_eigvals": round(err, 8),
                        "s": round(time.time() - t0, 1)}
    except Exception as e:
        out[f"C{C}"] = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300],
                        "s": round(time.time() - t0, 1)}
    # incremental: a hang on the next case must not lose this verdict
    print(json.dumps({f"C{C}": out[f"C{C}"]}), file=sys.stderr, flush=True)
print(json.dumps(out), flush=True)
