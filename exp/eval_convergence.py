"""Evaluation stage of the convergence experiment, decoupled from training.

The round-3 build host has one CPU core, so the two CRNN trainings run as
separate long-lived background processes (`/root/train_one.py sc|mc`
wrappers around cli/train.main, each dropping a ``{kind}_done.json`` marker
with its run name).  This script picks up those markers — or, with
``--allow-partial``, the latest checkpoint on disk even while training is
still running — and runs the held-out test-split oracle-vs-CRNN TANGO
evaluation + loss-curve summary of ``exp/train_convergence.py``, writing
the committed artifact ``exp/convergence_result.json``.
"""
from __future__ import annotations

import argparse
import json
from pathlib import Path

from train_convergence import TEST_BASE, evaluate, loss_summary  # noqa: E402


def _run_name(models_dir: Path, kind: str, allow_partial: bool) -> str:
    marker = models_dir / f"{kind}_done.json"
    if marker.exists():
        return json.loads(marker.read_text())["run_name"]
    if not allow_partial:
        raise SystemExit(f"{marker} missing — training not finished (use --allow-partial)")
    # newest *_model.msgpack whose loss file exists
    cands = sorted(models_dir.glob("*_model.msgpack"), key=lambda p: p.stat().st_mtime)
    if not cands:
        raise SystemExit(f"no checkpoints under {models_dir}")
    return cands[-1].name.replace("_model.msgpack", "")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="/root/convergence_run")
    p.add_argument("--test_rirs", type=int, default=20)
    p.add_argument("--scenario", default="living")
    p.add_argument("--noise", default="ssn")
    p.add_argument("--sc", default=None, help="single-channel run name (default: marker)")
    p.add_argument("--mc", default=None, help="multichannel run name (default: marker)")
    p.add_argument("--allow-partial", action="store_true",
                   help="fall back to the newest checkpoint when a done-marker is absent")
    p.add_argument("--out_json", default="exp/convergence_result.json")
    args = p.parse_args(argv)

    work = Path(args.workdir)
    models_dir = work / "models"
    sc = args.sc or _run_name(models_dir, "sc", args.allow_partial)
    mc = args.mc or _run_name(models_dir, "mc", args.allow_partial)
    data = work / "dataset"

    deltas = evaluate(data, work, models_dir, sc, mc, args.scenario, args.noise, args.test_rirs)
    result = {
        "config": "crnn_convergence",
        "n_train_rirs": 150,
        "n_test_rirs": args.test_rirs,
        "single_channel": {"run": sc, **loss_summary(models_dir, sc)},
        "multichannel": {"run": mc, **loss_summary(models_dir, mc)},
        "test_deltas": deltas,
        "crnn_vs_oracle_si_sdr_gap": round(
            deltas["oracle"]["delta_si_sdr"] - deltas["crnn"]["delta_si_sdr"], 3
        ),
        "partial": not (
            (models_dir / "sc_done.json").exists() and (models_dir / "mc_done.json").exists()
        ),
    }
    Path(args.out_json).write_text(json.dumps(result, indent=1))
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
