"""Converged CRNN training at corpus scale (round-2 verdict #5).

Round 2 proved the CRNN's forward math (torch-twin parity at 1e-5) and the
training loop at smoke scale (a 4-RIR, 8-epoch corpus milestone); what it
never showed is the *recipe* converging — a val-loss curve that plateaus
and the resulting oracle-vs-CRNN ΔSI-SDR gap at a realistic budget
(reference trains batch 500 x <=150 epochs with early stopping,
dnn/engine/train.py:73-85).  This experiment runs the full reference
workflow at a few-hundred-RIR scale with a true held-out split:

  1. synth speech tree (the corpus has no LibriSpeech material in-image)
  2. disco-gen + disco-mix: train RIRs 1..n_train, TEST RIRs 11001..+n_test
     (the reference's id-space split convention, driver.dset_of_rir)
  3. oracle z-export for every RIR (step-2 training inputs)
  4. train the step-1 single-channel and step-2 multichannel CRNNs to the
     early-stop plateau (patience 10, TrainConfig.early_stop_patience)
  5. disco-tango on the held-out test RIRs: oracle masks vs the trained
     checkpoints; report the ΔSI-SDR / ΔSDR / ΔSTOI gap

Stages are filesystem-idempotent (rerunning skips finished work).  The
result JSON + loss curves land in ``--workdir``; the committed artifact is
``exp/convergence_result.json``.

Run (CPU, hours):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python exp/train_convergence.py \
      --workdir exp/convergence --rirs 150 --test_rirs 20
"""
from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import numpy as np

TEST_BASE = 11000  # reference split convention: rir > 11000 -> test


def build_corpus(work: Path, n_train: int, n_test: int, scenario: str, noise: str,
                 max_order: int, seed: int):
    from disco_tpu.cli import gen_disco, get_z, mix
    from disco_tpu.milestones_corpus import synth_speech_tree

    speech = synth_speech_tree(work / "libri", n_speakers=12, dur_s=8.0, seed=seed)
    data = work / "dataset"
    jobs = [("train", 1, n_train), ("test", TEST_BASE + 1, n_test)]
    for dset, first, count in jobs:
        gen_disco.main([
            "--dset", dset, "--scenario", scenario, "--rirs", str(first), str(count),
            "--dir_out", str(data), "--librispeech", str(speech),
            "--max_order", str(max_order), "--seed", str(30 + seed),
            "--duration", "5", "8",
        ])
        mix.main([
            "--rirs", str(first), str(count), "--scenario", scenario, "--noise", noise,
            "--dir", str(data), "--snr", "0", "6",
        ])
        for rir in range(first, first + count):
            get_z.main([
                "--rir", str(rir), "--scenario", scenario, "--noise", noise,
                "--dataset", str(data), "--sav_dir", "oracle",
            ])
    return data


def train_models(data: Path, models_dir: Path, scenario: str, noise: str,
                 n_train: int, n_epochs: int, batch: int):
    """Both CRNNs to their early-stop plateau; returns (sc_name, mc_name)."""
    from disco_tpu.cli import train

    marker = models_dir / "run_names.json"
    if marker.exists():
        names = json.loads(marker.read_text())
        return names["sc"], names["mc"]
    common = [
        "--scene", scenario, "--noise", noise, "--n_files", str(n_train + 1),
        "--path_data", str(data), "--save_path", str(models_dir),
        "--n_epochs", str(n_epochs), "--batch_size", str(batch),
    ]
    t0 = time.time()
    sc_name = train.main(common + ["--single_channel"])
    print(f"[convergence] single-channel trained in {time.time() - t0:.0f}s", flush=True)
    t0 = time.time()
    mc_name = train.main(common + ["--zsigs", "zs_hat"])
    print(f"[convergence] multichannel trained in {time.time() - t0:.0f}s", flush=True)
    marker.write_text(json.dumps({"sc": sc_name, "mc": mc_name}))
    return sc_name, mc_name


def evaluate(data: Path, work: Path, models_dir: Path, sc_name: str, mc_name: str,
             scenario: str, noise: str, n_test: int):
    from disco_tpu.cli import tango
    from disco_tpu.enhance.driver import aggregate_results
    from disco_tpu.milestones_corpus import _delta_from_results

    out = {}
    for tag, mods in (
        ("oracle", None),
        ("crnn", [str(models_dir / f"{sc_name}_model.msgpack"),
                  str(models_dir / f"{mc_name}_model.msgpack")]),
    ):
        root = work / f"results_{tag}"
        for rir in range(TEST_BASE + 1, TEST_BASE + 1 + n_test):
            argv = [
                "--rir", str(rir), "--scenario", scenario, "--noise", noise,
                "--dataset", str(data), "--out_root", str(root), "--sav_dir", tag,
            ]
            if mods:
                argv += ["--mods", *mods]
            tango.main(argv)
        out[tag] = _delta_from_results(aggregate_results(root / "OIM", kind="tango", noise=noise))
    return out


def loss_summary(models_dir: Path, run_name: str) -> dict:
    curves = np.load(models_dir / f"{run_name}_losses.npz")
    tr = np.trim_zeros(curves["train_loss"], "b")
    va = np.trim_zeros(curves["val_loss"], "b")
    return {
        "epochs_run": int(len(va)),
        "best_val_epoch": int(np.argmin(va)),
        "best_val_loss": float(np.min(va)),
        "final_train_loss": float(tr[-1]),
        "val_curve": [round(float(v), 6) for v in va],
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="exp/convergence")
    p.add_argument("--rirs", type=int, default=150)
    p.add_argument("--test_rirs", type=int, default=20)
    p.add_argument("--scenario", default="living")
    p.add_argument("--noise", default="ssn")
    p.add_argument("--max_order", type=int, default=10)
    p.add_argument("--epochs", type=int, default=150)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--out_json", default="exp/convergence_result.json")
    args = p.parse_args(argv)

    work = Path(args.workdir)
    work.mkdir(parents=True, exist_ok=True)
    t0 = time.time()
    data = build_corpus(work, args.rirs, args.test_rirs, args.scenario, args.noise,
                        args.max_order, args.seed)
    print(f"[convergence] corpus ready in {time.time() - t0:.0f}s", flush=True)

    models_dir = work / "models"
    sc_name, mc_name = train_models(data, models_dir, args.scenario, args.noise,
                                    args.rirs, args.epochs, args.batch)

    deltas = evaluate(data, work, models_dir, sc_name, mc_name,
                      args.scenario, args.noise, args.test_rirs)

    result = {
        "config": "crnn_convergence",
        "n_train_rirs": args.rirs,
        "n_test_rirs": args.test_rirs,
        "batch": args.batch,
        "epoch_cap": args.epochs,
        "single_channel": loss_summary(models_dir, sc_name),
        "multichannel": loss_summary(models_dir, mc_name),
        "test_deltas": deltas,
        "crnn_vs_oracle_si_sdr_gap": round(
            deltas["oracle"]["delta_si_sdr"] - deltas["crnn"]["delta_si_sdr"], 3
        ),
        "wall_s": round(time.time() - t0, 1),
    }
    Path(args.out_json).write_text(json.dumps(result, indent=1))
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
