#!/bin/bash
# Cluster training recipe — capability parity with reference
# exp/ex1/oar_train.sh: write the per-signal file lists, rsync-stage the
# corpus onto node-local scratch, rewrite paths, launch training.  Works
# under any scheduler (OAR/SLURM/...) that gives a local scratch dir.
set -euo pipefail

scene=${1:?usage: cluster_train.sh scene noise zsigs [n_files]}
noise=${2}
zsigs=${3}
n_files=${4:-11001}

DATA_ROOT=${DATA_ROOT:-dataset/disco}
SCRATCH=${SCRATCH:-/tmp/$USER/disco_stage}
LISTS=${LISTS:-lists/${scene}_${noise}}

# 1. Build the lists of .npy inputs (deterministic across relaunches).
python -m disco_tpu.cli.lists --scene "${scene}" --noise "${noise}" \
    --zsigs ${zsigs} --n_files "${n_files}" --path_data "${DATA_ROOT}" --out "${LISTS}"

# 2. Stage every list to node-local scratch, one rsync per list in parallel
#    (the reference's --files-from trick, oar_train.sh:28-45).
mkdir -p "${SCRATCH}"
for f in "${LISTS}"/list_*.txt; do
    sed "s|^${DATA_ROOT}/||" "$f" > "${f}.rel"
    rsync -a --files-from="${f}.rel" "${DATA_ROOT}/" "${SCRATCH}/" &
done
wait

# 3. Rewrite list paths to the staged copies.
staged=${LISTS}_staged
mkdir -p "${staged}"
for f in "${LISTS}"/list_*.txt; do
    sed "s|^${DATA_ROOT}|${SCRATCH}|" "$f" > "${staged}/$(basename "$f")"
done

# 4. Train from the staged lists.
python -m disco_tpu.cli.train --scene "${scene}" --noise "${noise}" --zsigs ${zsigs} \
    --files_to_load "${staged}" --n_files "${n_files}" --path_data "${SCRATCH}"
