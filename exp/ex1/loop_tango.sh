#!/bin/bash
# Run TANGO enhancement for one (scene, noise, model_sc, model_mc, rir) tuple
# — capability parity with reference exp/ex1/loop_tango.sh (whose last line
# passes the undefined ${model} ${mod_mc}; fixed here per SURVEY.md §7).
# Loop or job-array over RIR ids for corpus-scale runs; every invocation is
# idempotent (already-processed RIRs are skipped).
set -euo pipefail

scene=${1:?usage: loop_tango.sh scene noise model_sc model_mc rir}   # meeting/living/random
noise=${2}      # it/fs/ssn
model_sc=${3}   # single-node CRNN run name, or None for oracle masks
model_mc=${4}   # multi-node CRNN run name, or None
k=${5}          # RIR id to process

path_to_models=${MODELS_DIR:-models}
vad1=${VAD1:-irm1}
vad2=${VAD2:-irm1}
sav_dir=${model_sc}_${model_mc}
zsigs=${ZSIGS:-zs_hat}

msc=None
mmc=None
[ "${model_sc}" != "None" ] && msc=${path_to_models}/${model_sc}_model.ckpt
[ "${model_mc}" != "None" ] && mmc=${path_to_models}/${model_mc}_model.ckpt

python -m disco_tpu.cli.tango -vt "${vad1}" "${vad2}" -sd "${sav_dir}" --rir "${k}" \
    -scene "${scene}" --noise "${noise}" --zsigs ${zsigs} -m "${msc}" "${mmc}"
