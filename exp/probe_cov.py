import sys; sys.path.insert(0, "/root/repo")
import json, time
import numpy as np
import jax.numpy as jnp

out = {}
rng = np.random.default_rng(0)
y = (rng.standard_normal((1, 4, 257, 130)) + 1j * rng.standard_normal((1, 4, 257, 130))).astype(np.complex64)
m = rng.uniform(size=(1, 257, 130)).astype(np.float32)

from disco_tpu.ops.cov_ops import masked_cov_pallas
from disco_tpu.beam.covariance import masked_covariances

t0 = time.time()
try:
    Rss, Rnn = masked_cov_pallas(jnp.asarray(y), jnp.asarray(m), interpret=False)
    ref_ss, ref_nn = masked_covariances(jnp.asarray(y), jnp.asarray(m))
    err = float(jnp.max(jnp.abs(jnp.real(Rss) - jnp.real(ref_ss))) + jnp.max(jnp.abs(jnp.imag(Rss) - jnp.imag(ref_ss))))
    scale = float(jnp.max(jnp.abs(jnp.real(ref_ss))))
    out["covfused"] = {"ok": True, "rel_err": err / scale, "s": round(time.time() - t0, 1)}
except Exception as e:
    out["covfused"] = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300], "s": round(time.time() - t0, 1)}
print(json.dumps(out), flush=True)
