"""One-shot covfused probe: does masked_cov_pallas lower on this backend,
and does it agree with the einsum reference on BOTH covariances?

Rnn matters as much as Rss: its (1-m)^2 weighting is the branch that
behaves differently in zero-padded bins.  ``interpret`` gates on is_tpu()
(like masked_covariances_fused) so the probe is also runnable off-chip.
"""
import sys; sys.path.insert(0, "/root/repo")
import json, time
import numpy as np
import jax.numpy as jnp

out = {}
rng = np.random.default_rng(0)
y = (rng.standard_normal((1, 4, 257, 130)) + 1j * rng.standard_normal((1, 4, 257, 130))).astype(np.complex64)
m = rng.uniform(size=(1, 257, 130)).astype(np.float32)

from disco_tpu.ops.cov_ops import masked_cov_pallas
from disco_tpu.beam.covariance import masked_covariances
from disco_tpu.utils.backend import is_tpu
from disco_tpu.utils.transfer import to_device, to_host


def _rel_err(a, b):
    a, b = to_host(a), to_host(b)
    err = float(np.max(np.abs(a.real - b.real)) + np.max(np.abs(a.imag - b.imag)))
    return err / float(np.max(np.abs(b.real)))


t0 = time.time()
try:
    interpret = not is_tpu()
    yd, md = to_device(y), to_device(m)  # complex-safe on the tunnel
    Rss, Rnn = masked_cov_pallas(yd, md, interpret=interpret)
    ref_ss, ref_nn = masked_covariances(yd, md)
    out["covfused"] = {
        "ok": True,
        "interpret": interpret,
        "rel_err_rss": _rel_err(Rss, ref_ss),
        "rel_err_rnn": _rel_err(Rnn, ref_nn),
        "s": round(time.time() - t0, 1),
    }
except Exception as e:
    out["covfused"] = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300], "s": round(time.time() - t0, 1)}
print(json.dumps(out), flush=True)
