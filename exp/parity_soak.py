"""Randomized end-to-end TANGO soak vs the float64 NumPy oracle.

The suite pins parity on fixed scenes; this sweep draws random (K, C, L,
noise level, mask type, policy) configurations and compares per-node
SI-SDR between the jitted pipeline and ``tests/reference_impls.tango_np``.

The contract is ONE-SIDED (fail only when ours lands BELOW the oracle by
more than ``tol``): binary (ibm) masks routinely produce rank-deficient
noise statistics whose GEVD eigenvector selection is legitimately
solver-sensitive — measured on random scenes, our whitened-eigh +
diagonal-loading + e1-fallback pipeline is never worse and is sometimes
BETTER than the reference formulation by up to ~1 dB, and it stays finite
on degenerate bins where the float64 scipy path emits NaN.  Graded (irm)
masks agree two-sidedly to <0.15 dB.

Usage: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python exp/parity_soak.py [--n 10]
Prints one line per configuration and a final PASS/FAIL summary.
"""
from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# Force the CPU backend regardless of the environment: the image exports
# JAX_PLATFORMS=axon, under which a bare run would claim (and, if
# interrupted, wedge) the single tunneled TPU chip for a CPU-bound soak.
# The sitecustomize may have imported jax already, so set the config too
# (the conftest.py pattern).
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # backend already initialised by the caller — respect their choice


def run(n_configs: int = 10, seed: int = 0, tol_db: float = 0.15) -> int:
    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.core.metrics import si_sdr
    from disco_tpu.enhance import oracle_masks, tango
    from tests.reference_impls import istft_np, si_sdr_np, tango_np

    rng = np.random.default_rng(seed)
    failures = 0
    for i in range(n_configs):
        K = int(rng.integers(2, 5))
        C = int(rng.integers(2, 4))
        L = int(rng.integers(12000, 40000))
        noise_scale = float(rng.uniform(0.3, 1.2))
        mask_type = rng.choice(["irm1", "irm2", "ibm1"])
        policy = rng.choice(["local", "none"])
        # round 3: the fused masked-covariance kernel joins the soak — on
        # 'local' configs it covers BOTH steps' stat stacks (interpret mode
        # on CPU), exercising random shapes the fixed tests don't
        cov_impl = rng.choice(["xla", "pallas"]) if policy == "local" else "xla"

        src = rng.standard_normal(L)
        s = np.stack([
            np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)])
            for _ in range(K)
        ])
        n = noise_scale * rng.standard_normal((K, C, L))
        y = s + n

        want = tango_np(y, s, n, mask_type=mask_type, mask_for_z=policy if policy == "local" else None)
        Y, S, N = stft(y), stft(s), stft(n)
        masks = oracle_masks(S, N, mask_type)
        res = tango(Y, S, N, masks, masks, policy=policy, mask_type=mask_type,
                    cov_impl=cov_impl)

        worst_deficit = 0.0  # how far ours falls BELOW the oracle
        best_surplus = 0.0
        oracle_nans = 0
        ours_bad = False
        for k in range(K):
            ours_sdr = float(si_sdr(s[k, 0], np.asarray(istft(res.yf[k], L), np.float64)))
            oracle_sdr = float(si_sdr_np(s[k, 0], istft_np(want["yf"][k], L)))
            if not np.isfinite(ours_sdr):
                ours_bad = True
            if not np.isfinite(oracle_sdr):
                oracle_nans += 1  # ours must stay finite where the oracle blows up
                continue
            worst_deficit = max(worst_deficit, oracle_sdr - ours_sdr)
            best_surplus = max(best_surplus, ours_sdr - oracle_sdr)
        ok = (worst_deficit < tol_db) and not ours_bad
        failures += not ok
        print(
            f"[{i:02d}] K={K} C={C} L={L} noise={noise_scale:.2f} {mask_type}/{policy}"
            f"{'/covfused' if cov_impl == 'pallas' else ''}: "
            f"deficit {worst_deficit:.4f} dB, surplus {best_surplus:.4f} dB"
            + (f", oracle NaN at {oracle_nans} node(s)" if oracle_nans else "")
            + f" {'ok' if ok else 'FAIL'}",
            flush=True,
        )
    print(
        f"{'PASS' if failures == 0 else 'FAIL'}: {n_configs - failures}/{n_configs} configs "
        f"at or above the oracle within {tol_db} dB"
    )
    return failures


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tol", type=float, default=0.15)
    args = p.parse_args()
    raise SystemExit(1 if run(args.n, args.seed, args.tol) else 0)
