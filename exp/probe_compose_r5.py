"""Round-5 composition probe: WHERE does the covfused lane die on-chip?

The round-5 ladder (exp/probe_mosaic_r5.json) proved every kernel construct
AND the full masked_cov_pallas at T=130 compile and run on real Mosaic in
~1 s — yet bench.py's full-pipeline covfused lane crashed the remote
compiler in rounds 3 and 4.  The delta is composition: 10 s clips
(T=1249: the untiled frame block was ~14 MB of VMEM at the C=11 step-2
stack), double vmap nesting (batch=16 x K=8 nodes), and the surrounding
tango program.  cov_ops is now frame-tiled (t_tile=256); this probe walks
the exact ladder from standalone production shapes to bench's literal
run_c configuration, all data generated ON DEVICE (complex dtypes cannot
cross the tunnel, and the bench shapes are GB-scale).

Incremental JSONL on stderr per case; summary JSON on stdout.
"""
import sys

sys.path.insert(0, "/root/repo")

import json
import time

import jax
import jax.numpy as jnp

results = {}


def case(name, fn):
    t0 = time.time()
    try:
        r = fn()
        r = {"ok": True, **(r or {}), "s": round(time.time() - t0, 1)}
    except Exception as e:
        r = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300], "s": round(time.time() - t0, 1)}
    results[name] = r
    print(json.dumps({name: r}), file=sys.stderr, flush=True)
    return r


def _rel_err(a, b):
    """max |a-b| / max|b| on device, scalar readback (real parts only —
    complex cannot cross the tunnel; im handled separately)."""
    num = jnp.maximum(
        jnp.max(jnp.abs(jnp.real(a) - jnp.real(b))),
        jnp.max(jnp.abs(jnp.imag(a) - jnp.imag(b))),
    )
    den = jnp.max(jnp.abs(jnp.real(b)))
    return float(num / den)


def _rand_cov_inputs(key, B, C, F, T):
    ky, km = jax.random.split(key)
    yr = jax.random.normal(ky, (B, C, F, T, 2), jnp.float32)
    y = jax.lax.complex(yr[..., 0], yr[..., 1])
    m = jax.random.uniform(km, (B, F, T), jnp.float32)
    return y, m


from disco_tpu.beam.covariance import masked_covariances
from disco_tpu.ops.cov_ops import masked_cov_pallas

key = jax.random.PRNGKey(0)


def cov_shape_case(B, C, F, T):
    def fn():
        y, m = _rand_cov_inputs(key, B, C, F, T)
        Rss, Rnn = masked_cov_pallas(y, m)
        Rss_ref, Rnn_ref = masked_covariances(y, m)
        return {
            "rel_err_rss": round(_rel_err(Rss, Rss_ref), 8),
            "rel_err_rnn": round(_rel_err(Rnn, Rnn_ref), 8),
        }

    return fn


# 1-2: standalone production shapes (step-1 stack C=4, step-2 stack C=11),
# bench clip length 10 s -> T=1249 engages the frame-tile accumulation
case("cov_C4_T1249_B32", cov_shape_case(32, 4, 257, 1249))
case("cov_C11_T1249_B16", cov_shape_case(16, 11, 257, 1249))


# 3: vmap over a leading axis (tango vmaps step1 over nodes)
def vmap_case():
    y, m = _rand_cov_inputs(key, 8, 4, 257, 130)
    got = jax.vmap(masked_cov_pallas)(y[:, None], m[:, None])
    ref = jax.vmap(masked_covariances)(y[:, None], m[:, None])
    return {"rel_err": round(_rel_err(got[0], ref[0]), 8)}


case("cov_under_vmap", vmap_case)

# 4-5: the full tango pipeline with cov_impl='pallas' — first at 2 s clips
# (short program), then bench.py's literal run_c configuration (10 s,
# batch=16, K=8, C=4), the shape that produced the round-3/4 compiler crash
from disco_tpu.core.dsp import stft
from disco_tpu.enhance import oracle_masks, tango


def tango_case(batch, K, C, dur_s, solver="power"):
    L = int(dur_s * 16000)

    def fn():
        ks = jax.random.split(key, 3)
        s = jax.random.normal(ks[0], (batch, K, C, L), jnp.float32)
        n = 0.8 * jax.random.normal(ks[1], (batch, K, C, L), jnp.float32)
        y = s + n

        def make_run(cov_impl):
            @jax.jit
            def run(y, s, n):
                def one(y1, s1, n1):
                    Y, S, N = stft(y1), stft(s1), stft(n1)
                    m = oracle_masks(S, N, "irm1")
                    return tango(Y, S, N, m, m, policy="local", solver=solver, cov_impl=cov_impl).yf

                return jax.vmap(one)(y, s, n)

            return run

        yf_p = make_run("pallas")(y, s, n)
        yf_x = make_run("xla")(y, s, n)
        return {"rel_err_vs_xla": round(_rel_err(yf_p, yf_x), 8)}

    return fn


case("tango_pallas_2s_b4_K4", tango_case(4, 4, 4, 2.0))
case("tango_pallas_10s_b16_K8_bench_shape", tango_case(16, 8, 4, 10.0))

print(json.dumps(results), flush=True)
