"""Second-wave hardware tuning sweep (round 3).

Covers the ROUND3.md "decisions staged for hardware" that the first-wave
``exp/tpu_validation.py`` sweep does NOT answer:

- ``stft_variants``     — rfft vs MXU-matmul vs pallas STFT on the bench
                          shapes (the routing bug fixed this round means the
                          matmul path has never been slope-timed on silicon).
- ``jacobi_sweeps``     — ``jacobi:N`` for N in 3..8: RTF + SI-SDR agreement
                          vs the eigh lane, so the size-adaptive sweep
                          schedule (ops/eigh_ops.default_sweeps) can be tuned
                          to measured convergence on-device.
- ``streaming_solver``  — per-frame refresh cost of the online pipeline with
                          solver eigh vs jacobi (round-3 streaming parity is
                          pinned at 0.2 dB; which is *faster* per refresh is
                          the open hardware question).
- ``combo``             — solver x cov_impl cross products solver_ab skipped
                          (jacobi+pallas-cov etc.): the candidate new default
                          is whatever this section says is fastest at
                          SDR-parity.

One process, one claim cycle, every section exception-isolated; one JSON
line per section (same contract as exp/tpu_validation.py).

Usage: python exp/tune_hw.py [--quick]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root


def section(name, fn):
    t0 = time.perf_counter()
    try:
        out = fn()
        out = {"section": name, "ok": True, **(out if isinstance(out, dict) else {"result": out})}
    except Exception as e:
        out = {"section": name, "ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    out["wall_s"] = round(time.perf_counter() - t0, 1)
    print(json.dumps(out), flush=True)
    return out


def stft_variants(batch=16, dur_s=10.0, iters=5):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from bench import _slope_time
    from disco_tpu.core.dsp import stft
    from disco_tpu.milestones import _scene

    FS, K, C = 16000, 8, 4
    L = int(dur_s * FS)
    y, _, _ = _scene(K, C, L, noise_scale=0.5)
    yb = jnp.asarray(np.stack([y] * batch))

    out = {}
    ref, ref_name = None, None
    for impl in ("rfft", "matmul", "pallas"):
        try:
            run = jax.jit(lambda x, impl=impl: stft(x, impl=impl))
            Y = run(yb)
            dt, _ = _slope_time(run, yb, iters=iters)
            lane = {"ms": round(dt * 1e3, 2)}
            Yh = np.asarray(jnp.abs(Y), np.float64)
            if ref is None:
                ref, ref_name = Yh, impl  # anchor = first lane that succeeds
            else:
                denom = float(np.mean(ref**2)) or 1.0
                lane[f"rel_err_vs_{ref_name}"] = float(np.sqrt(np.mean((Yh - ref) ** 2) / denom))
        except Exception as e:
            lane = {"error": f"{type(e).__name__}: {e}"[:200]}
        out[impl] = lane
    return out


def _tango_harness(B, dur_s, K=8, C=4):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance import oracle_masks
    from disco_tpu.enhance.tango import tango
    from disco_tpu.milestones import _scene

    FS = 16000
    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    yb = jnp.asarray(np.stack([y] * B))
    sb = jnp.asarray(np.stack([s] * B))
    nb = jnp.asarray(np.stack([n] * B))

    def make(solver, cov_impl="xla"):
        @jax.jit
        def run(yb, sb, nb):
            def one(y, s, n):
                Y, S, N = stft(y), stft(s), stft(n)
                m = oracle_masks(S, N, "irm1")
                return tango(Y, S, N, m, m, policy="local", solver=solver,
                             cov_impl=cov_impl).yf
            return jax.vmap(one)(yb, sb, nb)
        return run

    return make, (yb, sb, nb), L, K, B * K * dur_s


def _solver_lanes(lanes, B=16, dur_s=10.0, iters=3):
    """Shared lane runner: RTF per (solver, cov_impl) + SI-SDR agreement
    against the eigh/xla anchor (anchored ONLY by the eigh lane, as in
    exp/tpu_validation.solver_ab)."""
    import numpy as np

    from bench import _slope_time
    from disco_tpu.core.dsp import istft
    from disco_tpu.core.metrics import si_sdr

    make, args, L, K, audio_s = _tango_harness(B, dur_s)
    out = {}
    ref_t = None
    for name, solver, cov in lanes:
        try:
            run = make(solver, cov)
            yf = run(*args)
            dt, _ = _slope_time(run, *args, iters=iters)
            lane = {"rtf": round(audio_s / dt, 1), "ms_per_batch": round(dt * 1e3, 2)}
            est_t = np.asarray(istft(yf[0], length=L), np.float64)
            if name == "eigh":
                ref_t = est_t
            elif ref_t is not None:
                lane["si_sdr_vs_eigh_db"] = round(
                    float(np.mean([si_sdr(ref_t[k], est_t[k]) for k in range(K)])), 2
                )
            else:
                lane["si_sdr_vs_eigh_db"] = None
        except Exception as e:
            lane = {"error": f"{type(e).__name__}: {e}"[:200]}
        out[name] = lane
    return out


def jacobi_sweeps(B=16, dur_s=10.0, iters=3, ns=(3, 4, 5, 6, 8)):
    lanes = [("eigh", "eigh", "xla")]
    lanes += [(f"jacobi:{n}", f"jacobi:{n}", "xla") for n in ns]
    return _solver_lanes(lanes, B=B, dur_s=dur_s, iters=iters)


def combo(B=16, dur_s=10.0, iters=3):
    lanes = [
        ("eigh", "eigh", "xla"),
        ("jacobi+covfused", "jacobi", "pallas"),
        ("power+covfused", "power", "pallas"),
        ("jacobi-pallas+covfused", "jacobi-pallas", "pallas"),
    ]
    return _solver_lanes(lanes, B=B, dur_s=dur_s, iters=iters)


def streaming_solver(dur_s=10.0, K=4, C=4, update_every=4, iters=5):
    import numpy as np
    import jax

    from bench import _slope_time
    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance import oracle_masks
    from disco_tpu.enhance.streaming import streaming_tango
    from disco_tpu.milestones import _scene

    FS = 16000
    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    T = Y.shape[-1]
    budget_ms = 1e3 * 256 / FS

    out = {"frame_budget_ms": round(budget_ms, 3)}
    for solver in ("eigh", "jacobi"):
        try:
            run = jax.jit(
                lambda Y, mz, mw, solver=solver: streaming_tango(
                    Y, mz, mw, update_every=update_every, policy="local", solver=solver
                )["yf"]
            )
            dt, _ = _slope_time(run, Y, masks, masks, iters=iters)
            per_frame_ms = 1e3 * dt / T
            out[solver] = {
                "latency_ms_frame": round(per_frame_ms, 4),
                "rtf": round(budget_ms / per_frame_ms, 1),
            }
        except Exception as e:
            out[solver] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true", help="smaller scales")
    args = p.parse_args(argv)

    if args.quick:
        section("stft_variants", lambda: stft_variants(batch=2, dur_s=2.0, iters=1))
        section("jacobi_sweeps", lambda: jacobi_sweeps(B=2, dur_s=2.0, iters=1, ns=(4, 6)))
        section("streaming_solver", lambda: streaming_solver(dur_s=2.0, K=2, C=2, iters=1))
        section("combo", lambda: combo(B=2, dur_s=2.0, iters=1))
        return
    section("stft_variants", stft_variants)
    section("jacobi_sweeps", jacobi_sweeps)
    section("streaming_solver", streaming_solver)
    section("combo", combo)


if __name__ == "__main__":
    main()
