"""Generate per-module autodoc pages for every module in disco_tpu —
the equivalent of the reference's ``sphinx-apidoc -fTMe`` step
(reference doc/Makefile:28-30), implemented without requiring sphinx at
generation time (the build environment has no sphinx wheel; the pages are
committed and rebuilt by ``make -C doc apidoc`` wherever sphinx exists).

Run from the repo root:  python doc/gen_apidoc.py
"""
from __future__ import annotations

from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
PKG = ROOT / "disco_tpu"
OUT = ROOT / "doc" / "source" / "api"


def module_name(py: Path) -> str:
    rel = py.relative_to(ROOT).with_suffix("")
    parts = list(rel.parts)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def page(mod: str) -> str:
    underline = "=" * len(mod)
    return f"""{mod}
{underline}

.. automodule:: {mod}
   :members:
   :undoc-members:
   :show-inheritance:
"""


def main():
    OUT.mkdir(parents=True, exist_ok=True)
    for old in OUT.glob("*.rst"):
        old.unlink()
    mods = sorted(
        module_name(p)
        for p in PKG.rglob("*.py")
        if "__pycache__" not in p.parts
    )
    for mod in mods:
        (OUT / f"{mod}.rst").write_text(page(mod))
    toc = "\n".join(f"   api/{m}" for m in mods)
    (OUT.parent / "api_modules.rst").write_text(
        f"""API reference (per module)
==========================

.. toctree::
   :maxdepth: 1

{toc}
"""
    )
    print(f"wrote {len(mods)} module pages under {OUT}")


if __name__ == "__main__":
    main()
