"""Sphinx configuration (reference doc/source/conf.py parity: autodoc +
napoleon over the package)."""
import os
import sys

sys.path.insert(0, os.path.abspath("../.."))

project = "disco_tpu"
author = "disco_tpu developers"
extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
]
autodoc_mock_imports = ["jax", "flax", "optax", "matplotlib"]
html_theme = "alabaster"
exclude_patterns = []
