"""Tests for the native threaded npy loader (C++ fastloader) vs numpy."""
import numpy as np
import pytest

from disco_tpu.nn import fastload


@pytest.fixture
def npy_dir(tmp_path):
    rng = np.random.default_rng(0)
    paths, refs = [], []
    for i, T in enumerate((100, 80, 120)):
        a = (rng.standard_normal((257, T)) + 1j * rng.standard_normal((257, T))).astype("complex64")
        p = tmp_path / f"c{i}.npy"
        np.save(p, a)
        paths.append(p)
        refs.append(np.abs(a))
    f = rng.standard_normal((257, 90)).astype("float32")
    pf = tmp_path / "f.npy"
    np.save(pf, f)
    paths.append(pf)
    refs.append(np.abs(f))
    return paths, refs


def test_native_lib_builds():
    assert fastload.available(), "g++ is in the image; the native loader must build"


def test_load_abs_batch_matches_numpy(npy_dir):
    paths, refs = npy_dir
    out, frames = fastload.load_abs_batch(paths, 257, 110)
    assert out.shape == (4, 257, 110)
    for i, ref in enumerate(refs):
        t = min(ref.shape[1], 110)
        assert frames[i] == t
        np.testing.assert_allclose(out[i, :, :t], ref[:, :t], rtol=1e-6)
        assert np.all(out[i, :, t:] == 0.0)


def test_load_abs_batch_skip_cols(npy_dir):
    paths, refs = npy_dir
    out, frames = fastload.load_abs_batch(paths, 257, 110, skip_cols=30)
    for i, ref in enumerate(refs):
        t = min(ref.shape[1] - 30, 110)
        assert frames[i] == t
        np.testing.assert_allclose(out[i, :, :t], ref[:, 30:30 + t], rtol=1e-6)


def test_load_abs_batch_bad_file(tmp_path, npy_dir):
    paths, _ = npy_dir
    bad = tmp_path / "bad.npy"
    bad.write_bytes(b"not a npy file")
    with pytest.raises(RuntimeError, match="bad.npy"):
        fastload.load_abs_batch([paths[0], bad], 257, 110)


def test_load_abs_batch_missing_file(npy_dir, tmp_path):
    paths, _ = npy_dir
    with pytest.raises(RuntimeError):
        fastload.load_abs_batch([tmp_path / "nope.npy"], 257, 110)


def test_numpy_fallback_matches(npy_dir, monkeypatch):
    paths, refs = npy_dir
    native, _ = fastload.load_abs_batch(paths, 257, 110, skip_cols=10)
    monkeypatch.setattr(fastload, "get_lib", lambda: None)
    fallback, _ = fastload.load_abs_batch(paths, 257, 110, skip_cols=10)
    np.testing.assert_allclose(native, fallback, rtol=1e-6)
