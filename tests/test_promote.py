"""Promotion-subsystem tests: the generation store, the publish seam and
the controller's resume/guard logic (disco_tpu/promote).  The end-to-end
canary → gate → promote-or-rollback ladder (and its chaos drills) is gated
by ``make promote-check``; these tests pin the pieces in isolation."""
import numpy as np
import pytest

import jax

from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.promote.controller import PromotionController, rollout_unit
from disco_tpu.promote.store import (
    WEIGHT_KEYS,
    GenerationStore,
    PublishRefused,
    model_for_arch,
)

#: The flywheel tests' tiny CRNN, shared so the jit/module caches hit.
ARCH = dict(n_ch=1, win_len=4, n_freq=9, cnn_filters=(2,),
            pool_kernels=((1, 2),), conv_padding=((0, 1),),
            rnn_units=(4,), ff_units=(9,), rnn_dropouts=0.0)


def _variables(seed):
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    model, tx = build_crnn(**ARCH)
    x = np.zeros((1, ARCH["win_len"], ARCH["n_freq"]), np.float32)
    state = create_train_state(model, tx, x, seed=seed)
    return {"params": state.params, "batch_stats": state.batch_stats}


def _fake_variables(fill):
    """Weight-shaped plain-numpy payload: staging never builds the model,
    so store-mechanics tests stay jax-free and instant."""
    return {"params": {"w": np.full(3, fill, np.float32)}, "batch_stats": {}}


# ------------------------------------------------------------------ the store
def test_stage_is_idempotent_and_digest_addressed(tmp_path):
    store = GenerationStore(tmp_path / "promote")
    g1 = store.stage_variables(_fake_variables(0.0), arch=ARCH)
    g2 = store.stage_variables(_fake_variables(0.0), arch=ARCH)
    assert g1.gen_id == g2.gen_id and g1.serial == g2.serial == 1
    assert g1.gen_id.startswith("g") and len(g1.gen_id) == 13
    assert g1.digest.startswith("sha256:")
    assert store.list_ids() == [g1.gen_id]
    g3 = store.stage_variables(_fake_variables(1.0), arch=ARCH)
    assert g3.gen_id != g1.gen_id and g3.serial == 2
    assert store.list_ids() == [g1.gen_id, g3.gen_id]


def test_digest_is_key_order_canonical(tmp_path):
    """Same weights staged from dicts with different insertion order (a
    live trainer vs a restored checkpoint) must land on ONE generation."""
    store = GenerationStore(tmp_path / "promote")
    fwd = {"params": {"a": np.zeros(2, np.float32),
                      "b": np.ones(2, np.float32)}, "batch_stats": {}}
    rev = {"batch_stats": {},
           "params": {"b": np.ones(2, np.float32),
                      "a": np.zeros(2, np.float32)}}
    assert (store.stage_variables(fwd, arch=ARCH).gen_id
            == store.stage_variables(rev, arch=ARCH).gen_id)
    assert len(store.list_ids()) == 1


def test_active_pointer_and_load_roundtrip(tmp_path):
    from flax import serialization

    store = GenerationStore(tmp_path / "promote")
    assert store.active() is None
    variables = _variables(1)
    gen = store.stage_variables(variables, arch=ARCH)
    with pytest.raises(FileNotFoundError):
        store.set_active("g000000000000")  # unknown gens must not go live
    assert store.active() is None
    store.set_active(gen.gen_id)
    assert store.active() == gen.gen_id

    model, loaded = store.load(gen.gen_id)
    assert model is model_for_arch(gen.arch)  # per-arch cache shares modules
    want = serialization.to_state_dict(
        {k: variables[k] for k in WEIGHT_KEYS})
    for a, b in zip(jax.tree_util.tree_leaves(want),
                    jax.tree_util.tree_leaves(loaded), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_torn_weight_file_fails_loudly_on_load(tmp_path):
    store = GenerationStore(tmp_path / "promote")
    gen = store.stage_variables(_fake_variables(0.5), arch=ARCH)
    raw = bytearray(gen.weights_path.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    gen.weights_path.write_bytes(bytes(raw))
    assert store.get(gen.gen_id).gen_id == gen.gen_id  # meta still reads
    with pytest.raises(PublishRefused, match="torn or corrupt"):
        store.load(gen.gen_id)


def test_stage_checkpoint_refuses_junk_and_missing_keys(tmp_path):
    from flax import serialization

    store = GenerationStore(tmp_path / "promote")
    junk = tmp_path / "junk.msgpack"
    junk.write_bytes(b"\x00\x01\x02not-a-checkpoint")
    with pytest.raises(PublishRefused, match="not a readable"):
        store.stage_checkpoint(junk, arch=ARCH)
    partial = tmp_path / "partial.msgpack"
    partial.write_bytes(serialization.msgpack_serialize(
        serialization.to_state_dict(
            {"params": {"w": np.zeros(2, np.float32)}})))
    with pytest.raises(PublishRefused, match="batch_stats"):
        store.stage_checkpoint(partial, arch=ARCH)
    assert store.list_ids() == []  # refusals stage nothing


def test_stage_checkpoint_is_ledger_aware(tmp_path):
    """The publish-seam contract: a checkpoint from a run whose latest
    epoch unit is still in_flight is refused NAMING the unit — at the file
    level it is indistinguishable from a finished candidate."""
    from flax import serialization

    from disco_tpu.runs.ledger import RunLedger, unit_epoch

    store = GenerationStore(tmp_path / "promote")
    ck = tmp_path / "cand.msgpack"
    ck.write_bytes(serialization.msgpack_serialize(
        serialization.to_state_dict(_fake_variables(0.25))))

    led = RunLedger(tmp_path / "train_led.jsonl")
    led.mark_in_flight(unit_epoch(0))
    led.record(unit_epoch(0), "done", val_loss=0.5)
    gen = store.stage_checkpoint(ck, arch=ARCH, ledger=led.path)
    assert gen.serial == 1  # clean ledger: stages fine

    led.mark_in_flight(unit_epoch(1))  # mid-epoch-interrupted run
    led.close()
    with pytest.raises(PublishRefused, match="epoch:1") as ei:
        store.stage_checkpoint(ck, arch=ARCH, ledger=tmp_path / "train_led.jsonl")
    assert ei.value.unit == "epoch:1"


# ---------------------------------------------------------- the publish seam
def test_mid_epoch_crash_refuses_publish_until_clean_resume(tmp_path, rng):
    """The satellite regression: a fit() killed at the ``mid_epoch`` chaos
    seam leaves its ledger epoch in_flight, and the publish seam must
    refuse the on-disk checkpoint (which predates the interrupted epoch)
    with a clean error naming the unit — then accept it again after a
    clean resumed run."""
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state, fit, publish_checkpoint
    from disco_tpu.runs import chaos
    from disco_tpu.runs.ledger import RunLedger, unit_epoch

    x = rng.random((4, ARCH["win_len"], ARCH["n_freq"])).astype("float32")
    y = (rng.random((4, ARCH["win_len"], ARCH["n_freq"])) > 0.5).astype("float32")
    batches = lambda: iter([(x, y)])
    model, tx = build_crnn(**ARCH)
    state = create_train_state(model, tx, x[:1], seed=2)
    led_path = tmp_path / "train_led.jsonl"
    promote_dir = tmp_path / "promote"

    # epoch 0 completes (done record, improved checkpoint, published gen);
    # the second mid_epoch tick kills epoch 1 with nothing persisted
    chaos.configure("mid_epoch", after=2)
    try:
        with pytest.raises(chaos.ChaosCrash):
            fit(model, state, batches, batches, n_epochs=2,
                save_path=tmp_path / "m", run_name="t", verbose=False,
                ledger=led_path, promote_dir=promote_dir, promote_arch=ARCH)
    finally:
        chaos.disable()

    latest = RunLedger(led_path).replay()
    assert latest[unit_epoch(0)]["state"] == "done"
    assert latest[unit_epoch(1)]["state"] == "in_flight"
    store = GenerationStore(promote_dir)
    assert len(store.list_ids()) == 1  # epoch 0's publish landed

    ckpt = tmp_path / "m" / "t_model.msgpack"
    assert ckpt.is_file()
    with pytest.raises(PublishRefused, match="epoch:1") as ei:
        publish_checkpoint(promote_dir, ckpt, arch=ARCH, ledger=led_path)
    assert ei.value.unit == "epoch:1"
    assert len(store.list_ids()) == 1  # the refusal staged nothing

    # a clean resumed run redoes epoch 1 end to end; the seam accepts again
    state2 = create_train_state(model, tx, x[:1], seed=2)
    fit(model, state2, batches, batches, n_epochs=1,
        save_path=tmp_path / "m", run_name="t", verbose=False,
        ledger=led_path, resume_from=ckpt)
    assert RunLedger(led_path).replay()[unit_epoch(1)]["state"] == "done"
    gen = publish_checkpoint(promote_dir, ckpt, arch=ARCH, ledger=led_path)
    assert gen.gen_id in store.list_ids()


# ------------------------------------------------------------- the controller
def test_rollout_never_resurrects_superseded_candidate(tmp_path):
    """Regression: after promoting serial N, the old serial N-1 incumbent
    has no rollout unit — the controller must NOT treat it as a fresh
    candidate and canary live sessions backwards onto it."""
    store = GenerationStore(tmp_path / "promote")
    g1 = store.stage_variables(_fake_variables(0.0), arch=ARCH)
    g2 = store.stage_variables(_fake_variables(1.0), arch=ARCH)
    store.set_active(g2.gen_id)

    ctl = PromotionController(store, poll_s=0.01)
    try:
        ctl._maybe_begin_rollout()
        assert ctl._phase == "idle" and ctl._candidate is None
        assert rollout_unit(g1.gen_id) not in ctl._ledger.replay()

        # a genuinely newer candidate IS picked up
        g3 = store.stage_variables(_fake_variables(2.0), arch=ARCH)
        ctl._maybe_begin_rollout()
        assert ctl._phase == "canary"
        assert ctl._candidate.gen_id == g3.gen_id
        rec = ctl._ledger.replay()[rollout_unit(g3.gen_id)]
        assert rec["state"] == "in_flight"
        assert rec["attrs"]["incumbent"] == g2.gen_id
    finally:
        ctl._ledger.close()


def test_resume_settles_interrupted_rollout_from_active_pointer(tmp_path):
    """Crash-resume semantics: ACTIVE is the arbiter.  An in_flight
    rollout whose candidate is NOT active rolls back (failed, naming the
    interrupted phase); one whose ACTIVE already points at the candidate
    completes as a promotion."""
    store = GenerationStore(tmp_path / "promote")
    g1 = store.stage_variables(_fake_variables(0.0), arch=ARCH)
    g2 = store.stage_variables(_fake_variables(1.0), arch=ARCH)
    store.set_active(g1.gen_id)
    led = store.rollout_ledger()
    led.record(rollout_unit(g2.gen_id), "in_flight", phase="canary",
               candidate=g2.gen_id, incumbent=g1.gen_id)
    led.close()

    ctl = PromotionController(store, poll_s=0.01)
    try:
        ctl._resume()
        rec = ctl._ledger.replay()[rollout_unit(g2.gen_id)]
        assert rec["state"] == "failed"
        assert "crash during 'canary'" in rec["attrs"]["error"]
        assert store.active() == g1.gen_id
    finally:
        ctl._ledger.close()

    # crash AFTER the ACTIVE flip: the promotion is completed, not undone
    g3 = store.stage_variables(_fake_variables(2.0), arch=ARCH)
    store.set_active(g3.gen_id)
    led = store.rollout_ledger()
    led.record(rollout_unit(g3.gen_id), "in_flight", phase="promoting",
               candidate=g3.gen_id, incumbent=g1.gen_id)
    led.close()
    c0 = obs_registry.counter("model_promotions").value
    ctl2 = PromotionController(store, poll_s=0.01)
    try:
        ctl2._resume()
        rec = ctl2._ledger.replay()[rollout_unit(g3.gen_id)]
        assert rec["state"] == "done" and rec["attrs"]["resumed"] is True
        assert obs_registry.counter("model_promotions").value - c0 == 1
        assert obs_registry.gauge("weight_generation").value == g3.serial
        assert store.active() == g3.gen_id
    finally:
        ctl2._ledger.close()


def test_controller_rejects_bad_knobs(tmp_path):
    with pytest.raises(ValueError, match="canary_frac"):
        PromotionController(tmp_path / "p", canary_frac=1.5)
    with pytest.raises(ValueError, match="window_blocks"):
        PromotionController(tmp_path / "p", window_blocks=0)
    with pytest.raises(ValueError, match="gc_keep_last"):
        PromotionController(tmp_path / "p", gc_keep_last=-1)


# ------------------------------------------------------------ the generation GC
def test_collect_keeps_active_recent_and_pinned(tmp_path):
    store = GenerationStore(tmp_path / "promote")
    gens = [store.stage_variables(_fake_variables(float(i)), arch=ARCH)
            for i in range(5)]
    store.set_active(gens[2].gen_id)
    c0 = obs_registry.counter("generations_collected").value

    with pytest.raises(ValueError, match="keep_last"):
        store.collect(keep_last=-1)
    collected = store.collect(keep_last=1, pinned={gens[0].gen_id})
    # keeps: g2 (ACTIVE), g4 (last 1), g0 (pinned) — collects g1, g3
    assert collected == [gens[1].gen_id, gens[3].gen_id]
    assert store.list_ids() == [gens[0].gen_id, gens[2].gen_id,
                                gens[4].gen_id]
    assert obs_registry.counter("generations_collected").value - c0 == 2
    store.load(gens[2].gen_id)  # survivors still digest-verify
    with pytest.raises(FileNotFoundError):
        store.get(gens[1].gen_id)
    # idempotent: a second sweep has nothing left to take
    assert store.collect(keep_last=1, pinned={gens[0].gen_id}) == []


def test_collect_refuses_inflight_rollout_sides(tmp_path):
    """A crash mid-rollout must always find BOTH sides of the swap on
    disk: the candidate and incumbent named by an undecided (in_flight)
    rollout unit are unpinnable until the rollout is decided."""
    store = GenerationStore(tmp_path / "promote")
    g1, g2, g3, g4 = (store.stage_variables(_fake_variables(float(i)),
                                            arch=ARCH) for i in range(4))
    store.set_active(g4.gen_id)
    led = store.rollout_ledger()
    led.record(rollout_unit(g3.gen_id), "in_flight", phase="canary",
               candidate=g3.gen_id, incumbent=g1.gen_id)
    led.close()
    collected = store.collect(keep_last=0)
    # keeps: g4 (ACTIVE), g3 (in-flight candidate), g1 (its incumbent)
    assert collected == [g2.gen_id]
    assert store.list_ids() == [g1.gen_id, g3.gen_id, g4.gen_id]

    # decided rollouts release their pins
    led = store.rollout_ledger()
    led.mark_failed(rollout_unit(g3.gen_id), error="demoted",
                    phase="rolled_back")
    led.close()
    assert store.collect(keep_last=0) == [g1.gen_id, g3.gen_id]
    assert store.list_ids() == [g4.gen_id]


# ------------------------------------------------------- mid-rollout queueing
@pytest.mark.parametrize("phase", ["canary", "gating", "promoting",
                                   "rolling_back"])
def test_candidate_arriving_mid_rollout_is_queued_not_dropped(tmp_path, phase):
    """The queueing regression: a candidate staged while a rollout is in
    ANY phase must neither hijack the in-flight rollout nor be silently
    ignored — it rolls out at the next idle step (here: after the current
    rollout fails, the harder case for the serial guard)."""
    store = GenerationStore(tmp_path / "promote")
    g1 = store.stage_variables(_fake_variables(0.0), arch=ARCH)
    g2 = store.stage_variables(_fake_variables(1.0), arch=ARCH)
    store.set_active(g1.gen_id)

    ctl = PromotionController(store, poll_s=0.01)
    try:
        ctl._maybe_begin_rollout()
        assert ctl._candidate.gen_id == g2.gen_id
        with ctl._lock:
            ctl._phase = phase           # simulate rollout progress
        g3 = store.stage_variables(_fake_variables(2.0), arch=ARCH)
        # the arrival changed nothing mid-flight
        assert ctl._candidate.gen_id == g2.gen_id

        # the g2 rollout fails; g3 must still roll out afterwards
        with ctl._lock:
            ctl._fail_reason = "synthetic demotion"
        ctl._finish_rollback()
        assert ctl._phase == "idle"
        ctl._maybe_begin_rollout()
        assert ctl._candidate.gen_id == g3.gen_id
        rec = ctl._ledger.replay()[rollout_unit(g3.gen_id)]
        assert rec["state"] == "in_flight"
        assert rec["attrs"]["incumbent"] == g1.gen_id
    finally:
        ctl._ledger.close()


def test_queued_candidates_dedupe_newest_wins(tmp_path):
    """Several candidates queued behind one rollout: only the NEWEST rolls
    out; the older ones are decided durably (superseded) so a failed
    newest can never resurrect them."""
    store = GenerationStore(tmp_path / "promote")
    g1 = store.stage_variables(_fake_variables(0.0), arch=ARCH)
    store.set_active(g1.gen_id)
    g2 = store.stage_variables(_fake_variables(1.0), arch=ARCH)
    g3 = store.stage_variables(_fake_variables(2.0), arch=ARCH)
    # digest dedupe: re-staging g2's exact weights is NOT a new candidate
    assert store.stage_variables(_fake_variables(1.0),
                                 arch=ARCH).gen_id == g2.gen_id

    c0 = obs_registry.counter("candidates_superseded").value
    ctl = PromotionController(store, poll_s=0.01)
    try:
        ctl._maybe_begin_rollout()
        assert ctl._candidate.gen_id == g3.gen_id   # newest wins
        rec = ctl._ledger.replay()[rollout_unit(g2.gen_id)]
        assert rec["state"] == "failed"
        assert rec["attrs"]["superseded_by"] == g3.gen_id
        assert obs_registry.counter("candidates_superseded").value - c0 == 1

        # the newest FAILS: the superseded g2 stays decided — idle, no
        # backwards rollout
        with ctl._lock:
            ctl._fail_reason = "synthetic demotion"
        ctl._finish_rollback()
        ctl._maybe_begin_rollout()
        assert ctl._phase == "idle" and ctl._candidate is None
    finally:
        ctl._ledger.close()


def test_watch_dir_arrival_mid_rollout_emits_queued_event(tmp_path):
    from flax import serialization

    from disco_tpu import obs

    store = GenerationStore(tmp_path / "promote")
    g1 = store.stage_variables(_fake_variables(0.0), arch=ARCH)
    store.set_active(g1.gen_id)
    watch = tmp_path / "incoming"
    watch.mkdir()
    ctl = PromotionController(store, poll_s=0.01, watch_dir=watch)
    try:
        with ctl._lock:
            ctl._phase = "canary"        # a rollout is in flight
        (watch / "cand.msgpack").write_bytes(serialization.msgpack_serialize(
            serialization.to_state_dict(_fake_variables(0.5))))
        log = tmp_path / "ev.jsonl"
        with obs.recording(log):
            ctl._scan_watch_dir()
        (ev,) = [e for e in obs.read_events(log)
                 if e["attrs"].get("action") == "staged"]
        assert ev["attrs"]["queued"] is True
        assert len(store.list_ids()) == 2  # staged now, decided later
    finally:
        ctl._ledger.close()


def test_promotion_gc_sweeps_after_finish_promote(tmp_path):
    """gc_keep_last wiring: a successful promotion sweeps the store,
    keeping ACTIVE (the new generation) and the just-replaced incumbent."""
    store = GenerationStore(tmp_path / "promote")
    g1 = store.stage_variables(_fake_variables(0.0), arch=ARCH)
    store.set_active(g1.gen_id)
    g2 = store.stage_variables(_fake_variables(1.0), arch=ARCH)
    g3 = store.stage_variables(_fake_variables(2.0), arch=ARCH)

    ctl = PromotionController(store, poll_s=0.01, gc_keep_last=0)
    try:
        ctl._maybe_begin_rollout()       # g3 rolls out; g2 superseded
        assert ctl._candidate.gen_id == g3.gen_id
        ctl._finish_promote()
        assert store.active() == g3.gen_id
        # swept: g2 (superseded, undecided no more); kept: g3 (ACTIVE) and
        # g1 (the incumbent pin — sessions may still deliver from it)
        assert store.list_ids() == [g1.gen_id, g3.gen_id]
        assert ctl._phase == "idle"
    finally:
        ctl._ledger.close()


# -------------------------------------------------------------- the admission
def test_model_mask_sessions_need_a_promotion_store(tmp_path):
    from disco_tpu.serve.scheduler import AdmissionError, Scheduler
    from disco_tpu.serve.session import SessionConfig

    cfg = SessionConfig(n_nodes=4, mics_per_node=2, n_freq=9,
                        block_frames=8, update_every=4, masks="model")
    sched = Scheduler(max_sessions=2)
    with pytest.raises(AdmissionError, match="promote-dir") as ei:
        sched.open_session(cfg)
    assert ei.value.code == "bad_config"

    # promote-wired but never activated: refused naming the missing ACTIVE
    ctl = PromotionController(GenerationStore(tmp_path / "promote"),
                              poll_s=0.01)
    try:
        sched2 = Scheduler(max_sessions=2, promote=ctl)
        with pytest.raises(AdmissionError, match="ACTIVE"):
            sched2.open_session(cfg)
    finally:
        ctl._ledger.close()
