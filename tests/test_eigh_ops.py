"""Batched Jacobi hermitian eigensolver vs np.linalg.eigh ground truth."""
import numpy as np
import pytest

from disco_tpu.ops.eigh_ops import eigh_jacobi, eigh_jacobi_pallas


def _random_hermitian(rng, B, C, complex_=True, spread=1.0):
    X = rng.standard_normal((B, C, C))
    if complex_:
        X = X + 1j * rng.standard_normal((B, C, C))
    A = X @ np.conj(np.swapaxes(X, -1, -2)) * spread
    return A.astype(np.complex64 if complex_ else np.float32)


def _check_eigpairs(A, lam, V, rtol=2e-4):
    """Eigen-decomposition residual checks robust to degenerate subspaces:
    A V = V diag(lam), V unitary, lam ascending, vs float64 eigenvalues."""
    A64 = np.asarray(A, np.complex128)
    lam = np.asarray(lam, np.float64)
    V = np.asarray(V, np.complex128)
    want = np.linalg.eigvalsh(A64)
    scale = np.abs(want).max(axis=-1, keepdims=True) + 1e-12
    np.testing.assert_allclose(lam / scale, want / scale, atol=rtol)
    assert (np.diff(lam, axis=-1) >= -1e-4 * scale).all(), "not ascending"
    resid = np.linalg.norm(A64 @ V - V * lam[..., None, :], axis=(-2, -1))
    denom = np.linalg.norm(A64, axis=(-2, -1)) + 1e-12
    assert (resid / denom < 5e-4).all(), (resid / denom).max()
    eye = np.eye(V.shape[-1])
    orth = np.linalg.norm(np.conj(np.swapaxes(V, -1, -2)) @ V - eye, axis=(-2, -1))
    assert (orth < 5e-4).all(), orth.max()


@pytest.mark.parametrize("C", [2, 4, pytest.param(11, marks=pytest.mark.slow)])
def test_jacobi_matches_lapack_complex(rng, C):
    # C=4 is the step-1 size, C=11 the 8-node step-2 size (mics + K-1)
    A = _random_hermitian(rng, 64, C)
    lam, V = eigh_jacobi(A)
    _check_eigpairs(A, lam, V)


def test_jacobi_matches_lapack_real(rng):
    A = _random_hermitian(rng, 32, 3, complex_=False)
    lam, V = eigh_jacobi(A)
    assert not np.iscomplexobj(np.asarray(V))
    _check_eigpairs(A, lam, V)


def test_jacobi_extreme_scales(rng):
    """Covariance-like inputs spanning the f32 range (warm-up streaming
    covariances are ~1e-12; loud bins ~1e4)."""
    for spread in (1e-12, 1.0, 1e4):
        A = _random_hermitian(rng, 16, 5, spread=spread)
        lam, V = eigh_jacobi(A)
        _check_eigpairs(A, lam, V, rtol=5e-4)


def test_jacobi_diagonal_and_degenerate(rng):
    """Already-diagonal input and repeated eigenvalues both converge."""
    lam_true = np.array([1.0, 1.0, 2.0, 5.0], np.float32)
    A = np.diag(lam_true).astype(np.complex64)[None].repeat(4, 0)
    lam, V = eigh_jacobi(A)
    np.testing.assert_allclose(np.asarray(lam), lam_true[None].repeat(4, 0), atol=1e-6)
    _check_eigpairs(A, lam, V)


def test_jacobi_trivial_sizes(rng):
    """C=1 (no rotation pairs) returns the diagonal; a single matrix (no
    batch dims) works too."""
    A = np.array([[[3.5 + 0j]]], np.complex64)
    lam, V = eigh_jacobi(A)
    np.testing.assert_allclose(np.asarray(lam), [[3.5]], atol=1e-7)
    np.testing.assert_allclose(np.asarray(V), [[[1.0]]], atol=1e-7)
    A2 = _random_hermitian(rng, 1, 3)[0]  # (3, 3), no batch axis
    lam2, V2 = eigh_jacobi(A2)
    _check_eigpairs(A2[None], np.asarray(lam2)[None], np.asarray(V2)[None])


def test_jacobi_batched_leading_axes(rng):
    """Arbitrary leading batch axes, as used by the (node, freq) filter bank."""
    A = _random_hermitian(rng, 6, 4).reshape(2, 3, 4, 4)
    lam, V = eigh_jacobi(A)
    assert lam.shape == (2, 3, 4) and V.shape == (2, 3, 4, 4)
    _check_eigpairs(A.reshape(6, 4, 4), np.asarray(lam).reshape(6, 4),
                    np.asarray(V).reshape(6, 4, 4))


@pytest.mark.parametrize("B", [5, pytest.param(300, marks=pytest.mark.slow)])
def test_pallas_interpret_matches_xla(rng, B):
    """The pallas kernel (interpreter) is the same computation as the XLA
    formulation, including the padded-tile path (B not a tile multiple).

    rtol, not pure atol: the interpreter and the XLA compile of the same
    Jacobi schedule differ in FMA/reassociation on this jax version, so
    eigenvalues of magnitude ~30 legitimately differ by ~1e-6 RELATIVE
    (observed max 1.2e-6) while an absolute 1e-5 window is only meaningful
    near zero."""
    A = _random_hermitian(rng, B, 6)
    lam_x, V_x = eigh_jacobi(A)
    lam_p, V_p = eigh_jacobi_pallas(A, tile=128, interpret=True)
    np.testing.assert_allclose(np.asarray(lam_p), np.asarray(lam_x), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(V_p), np.asarray(V_x), rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_gevd_mwf_jacobi_impl(rng):
    """gevd_mwf(eigh_impl='jacobi') reproduces the XLA-eigh filter."""
    import jax.numpy as jnp

    from disco_tpu.beam.filters import gevd_mwf

    F, C, T = 32, 5, 200
    src = rng.standard_normal((F, T))
    gains = rng.standard_normal((C, 1, 1))
    S = gains * src[None] + 0.05 * rng.standard_normal((C, F, T))
    N = 0.6 * rng.standard_normal((C, F, T))
    Rxx = jnp.asarray(np.einsum("cft,dft->fcd", S, S) / T, jnp.complex64)
    Rnn = jnp.asarray(np.einsum("cft,dft->fcd", N, N) / T, jnp.complex64)
    w_x, t1_x = gevd_mwf(Rxx, Rnn, rank=1)
    w_j, t1_j = gevd_mwf(Rxx, Rnn, rank=1, eigh_impl="jacobi")
    assert float(np.linalg.norm(np.asarray(w_j - w_x)) / np.linalg.norm(np.asarray(w_x))) < 1e-3
    assert float(np.linalg.norm(np.asarray(t1_j - t1_x)) / np.linalg.norm(np.asarray(t1_x))) < 1e-3
    # rank-N path too
    w2_x, _ = gevd_mwf(Rxx, Rnn, rank=2)
    w2_j, _ = gevd_mwf(Rxx, Rnn, rank=2, eigh_impl="jacobi")
    assert float(np.linalg.norm(np.asarray(w2_j - w2_x)) / np.linalg.norm(np.asarray(w2_x))) < 1e-3
    with pytest.raises(ValueError, match="eigh_impl"):
        gevd_mwf(Rxx, Rnn, eigh_impl="qr")


def test_rank1_gevd_jacobi_solvers(rng):
    """'jacobi' and 'jacobi-pallas' are reachable through THE solver
    dispatch (rank1_gevd) — so the pipeline/CLI/bench can select them —
    and reproduce the eigh filter (pallas branch auto-interprets off-TPU)."""
    import jax.numpy as jnp

    from disco_tpu.beam.filters import rank1_gevd

    F, C, T = 16, 4, 100
    src = rng.standard_normal((F, T))
    gains = rng.standard_normal((C, 1, 1))
    S = gains * src[None] + 0.05 * rng.standard_normal((C, F, T))
    N = 0.6 * rng.standard_normal((C, F, T))
    Rxx = jnp.asarray(np.einsum("cft,dft->fcd", S, S) / T, jnp.complex64)
    Rnn = jnp.asarray(np.einsum("cft,dft->fcd", N, N) / T, jnp.complex64)
    w_e, t1_e = rank1_gevd(Rxx, Rnn)
    for solver in ("jacobi", "jacobi-pallas"):
        w_j, t1_j = rank1_gevd(Rxx, Rnn, solver=solver)
        err = float(np.linalg.norm(np.asarray(w_j - w_e)) / np.linalg.norm(np.asarray(w_e)))
        assert err < 1e-3, (solver, err)


def test_tango_jacobi_solver_end_to_end(rng):
    """Full two-step TANGO with solver='jacobi' matches the eigh pipeline
    at SDR level."""
    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.core.metrics import si_sdr
    from disco_tpu.enhance import oracle_masks, tango

    K, C, L = 3, 2, 16384
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)]) for _ in range(K)]
    )
    n = 0.8 * rng.standard_normal((K, C, L))
    y = s + n
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    res_e = tango(Y, S, N, masks, masks, policy="local")
    res_j = tango(Y, S, N, masks, masks, policy="local", solver="jacobi")
    for k in range(K):
        sdr_e = si_sdr(s[k, 0], np.asarray(istft(res_e.yf[k], L), np.float64))
        sdr_j = si_sdr(s[k, 0], np.asarray(istft(res_j.yf[k], L), np.float64))
        assert abs(sdr_e - sdr_j) < 0.1, (k, sdr_e, sdr_j)


@pytest.mark.slow  # ~3 min on the 2-vCPU CI host (statically unrolled sweeps)
def test_default_sweeps_adaptive_precision():
    """The size-adaptive default (None) must match np.linalg.eigh at the
    pipeline's matrix sizes — including the step-1 C=4 case where it halves
    the rotation count vs the old fixed 8 (measured: C=4 converges by
    sweep 4, C=11 by sweep 6; default_sweeps keeps one sweep of margin)."""
    from disco_tpu.ops.eigh_ops import default_sweeps, eigh_jacobi

    assert default_sweeps(4) == 5 and default_sweeps(11) == 7 and default_sweeps(16) == 8
    rng = np.random.default_rng(3)
    for C in (4, 11):
        X = rng.standard_normal((32, C, C)) + 1j * rng.standard_normal((32, C, C))
        A = (X @ np.conj(X.swapaxes(-1, -2))).astype(np.complex64)
        lam, V = eigh_jacobi(A)  # sweeps=None -> adaptive
        _check_eigpairs(A, np.asarray(lam), np.asarray(V), rtol=5e-4)


@pytest.mark.slow
def test_jacobi_sweep_spec_through_rank1_gevd():
    """'jacobi:N' solver specs reach the eigensolver: an insufficient sweep
    count visibly degrades the filter while 'jacobi:8' matches eigh."""
    from disco_tpu.beam.filters import rank1_gevd

    rng = np.random.default_rng(4)
    X = rng.standard_normal((64, 6, 24)) + 1j * rng.standard_normal((64, 6, 24))
    Rss = (X @ np.conj(X.swapaxes(-1, -2))).astype(np.complex64) / 24
    N_ = rng.standard_normal((64, 6, 24)) + 1j * rng.standard_normal((64, 6, 24))
    Rnn = (N_ @ np.conj(N_.swapaxes(-1, -2))).astype(np.complex64) / 24 + np.eye(6, dtype=np.complex64)

    w_ref, _ = rank1_gevd(Rss, Rnn, solver="eigh")
    w_8, _ = rank1_gevd(Rss, Rnn, solver="jacobi:8")
    err8 = float(np.linalg.norm(np.asarray(w_8 - w_ref)) / np.linalg.norm(np.asarray(w_ref)))
    assert err8 < 1e-3, err8
    w_1, _ = rank1_gevd(Rss, Rnn, solver="jacobi:1")
    err1 = float(np.linalg.norm(np.asarray(w_1 - w_ref)) / np.linalg.norm(np.asarray(w_ref)))
    assert err1 > err8 * 10  # one sweep is visibly unconverged

    import pytest

    with pytest.raises(ValueError, match="N >= 1"):
        rank1_gevd(Rss, Rnn, solver="jacobi:0")
