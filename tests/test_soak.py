"""Serving survival layer units: the degradation ladder controller, the
park/replay/requeue session primitives, and the disco-soak campaign
planner (the heavy multi-fault integration lives in ``make soak-check`` —
disco_tpu/runs/soak.py; these are its fast deterministic parts)."""
from __future__ import annotations

import numpy as np
import pytest

from disco_tpu.serve.ladder import RUNGS, DegradationLadder
from disco_tpu.serve.session import Session, SessionConfig, SessionStateError


def _session(**kw):
    cfg = SessionConfig(n_nodes=2, mics_per_node=1, n_freq=5, block_frames=4)
    return Session("s1", cfg, **kw)


# -- degradation ladder ------------------------------------------------------
def test_ladder_steps_up_immediately_and_down_with_hysteresis():
    lad = DegradationLadder(p95_high_ms=100.0, p95_low_ms=50.0,
                            recover_ticks=3, max_rung=3)
    trace = []
    # hot ticks step up one rung per tick, immediately
    for t in range(1, 4):
        trace.append(lad.observe(queue_wait_p95_ms=500.0, deadline_hits=0,
                                 tick=t))
    assert trace == [1, 2, 3]
    # capped at max_rung
    assert lad.observe(queue_wait_p95_ms=500.0, deadline_hits=0, tick=4) == 3
    # calm ticks only step down after recover_ticks consecutive ones
    t = 5
    downs = []
    for _ in range(9):
        downs.append(lad.observe(queue_wait_p95_ms=1.0, deadline_hits=0,
                                 tick=t))
        t += 1
    assert downs == [3, 3, 2, 2, 2, 1, 1, 1, 0]
    # every transition is stepwise and recorded
    assert [(frm, to) for (_t, frm, to, _r) in lad.transitions] == [
        (0, 1), (1, 2), (2, 3), (3, 2), (2, 1), (1, 0)]


def test_ladder_deadline_hits_step_up_and_break_calm_streaks():
    lad = DegradationLadder(p95_high_ms=100.0, p95_low_ms=50.0,
                            recover_ticks=2, max_rung=2)
    assert lad.observe(queue_wait_p95_ms=0.0, deadline_hits=1, tick=1) == 1
    # a deadline hit mid-streak resets the calm counter
    assert lad.observe(queue_wait_p95_ms=1.0, deadline_hits=0, tick=2) == 1
    assert lad.observe(queue_wait_p95_ms=1.0, deadline_hits=1, tick=3) == 2
    assert lad.observe(queue_wait_p95_ms=1.0, deadline_hits=0, tick=4) == 2
    assert lad.observe(queue_wait_p95_ms=1.0, deadline_hits=0, tick=5) == 1
    # the band between low and high neither degrades nor recovers
    assert lad.observe(queue_wait_p95_ms=75.0, deadline_hits=0, tick=6) == 1
    assert lad.observe(queue_wait_p95_ms=75.0, deadline_hits=0, tick=7) == 1


def test_ladder_is_deterministic_given_the_metric_trace():
    trace = [(500.0, 0), (800.0, 0), (1.0, 0), (1.0, 0), (1.0, 0),
             (200.0, 1), (1.0, 0), (1.0, 0), (1.0, 0), (1.0, 0)]

    def run():
        lad = DegradationLadder(p95_high_ms=100.0, p95_low_ms=50.0,
                                recover_ticks=2, max_rung=3)
        return [lad.observe(queue_wait_p95_ms=p, deadline_hits=d, tick=t)
                for t, (p, d) in enumerate(trace, 1)], lad.transitions

    rungs1, tr1 = run()
    rungs2, tr2 = run()
    assert rungs1 == rungs2 and tr1 == tr2


def test_ladder_validation_and_rung_names():
    assert RUNGS == ("full", "per_block", "no_tap", "shed")
    with pytest.raises(ValueError):
        DegradationLadder(p95_high_ms=10.0, p95_low_ms=20.0)
    with pytest.raises(ValueError):
        DegradationLadder(max_rung=4)
    with pytest.raises(ValueError):
        DegradationLadder(recover_ticks=0)


# -- session park/replay/requeue primitives ----------------------------------
def test_replay_buffer_replays_exactly_the_missing_tail():
    s = _session(replay_blocks=8)
    for seq in range(5):
        s.record_delivery(seq, np.full((2, 5, 4), seq, np.complex64))
    s.blocks_done = 5
    missing = s.replay_from(3)
    assert [q for (q, _) in missing] == [3, 4]
    assert all(np.all(yf == q) for (q, yf) in missing)
    assert s.replay_from(5) == []          # client saw everything


def test_replay_buffer_gap_refuses_instead_of_stitching_a_hole():
    s = _session(replay_blocks=2)          # deliveries 0..4, buffer keeps 3,4
    for seq in range(5):
        s.record_delivery(seq, np.zeros((1,), np.complex64))
    s.blocks_done = 5
    with pytest.raises(SessionStateError, match="replay buffer"):
        s.replay_from(1)                   # blocks 1,2 are gone forever
    assert [q for (q, _) in s.replay_from(3)] == [3, 4]


def test_requeue_front_preserves_stream_order():
    s = _session()
    for seq in range(4):
        s.push_block(seq, f"Y{seq}", "mz", "mw", 0.0)
    popped = s.pop_blocks(4)
    assert [b[0] for b in popped] == [0, 1, 2, 3]
    s.requeue_front(popped[2:])            # blocks 2,3 failed to dispatch
    s.push_block(4, "Y4", "mz", "mw", 0.0)
    assert [b[0] for b in s.pop_blocks(10)] == [2, 3, 4]
    s.requeue_front([])                    # no-op


# -- the soak campaign planner ------------------------------------------------
def test_plan_campaign_is_deterministic_and_always_multi_fault():
    from disco_tpu.runs.soak import SEEDS, plan_campaign

    for seed in SEEDS:
        a, b = plan_campaign(seed), plan_campaign(seed)
        assert a == b
        assert 2 <= len(a["sessions"]) <= 3
        assert any(s["fault"] != "none" for s in a["sessions"])
        for s in a["sessions"]:
            assert s["fault"] in ("drop", "truncate", "none")
            assert s["drop_after"] >= 1
        if a["transport_attempts"]:
            # per-block schedules only, and always one exhausting triple
            assert a["super_tick"] == 1
            idx = set(a["transport_attempts"])
            assert any(i + 1 in idx and i + 2 in idx for i in idx)
    assert plan_campaign(SEEDS[-1])["crash_leg"]


def test_soak_scene_is_whole_blocks_and_warm_matches_serve_shapes():
    from disco_tpu.runs.soak import BLOCK, _scene

    Y, m = _scene(123)
    assert Y.shape[-1] % BLOCK == 0 and Y.shape[-1] == m.shape[-1]
