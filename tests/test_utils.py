"""Tests for transfer helpers and the profiling utility."""
import numpy as np

import jax.numpy as jnp

from disco_tpu.utils import StageTimer, to_device, to_host, trace_to


def test_to_host_complex_roundtrip():
    x = (np.arange(6).reshape(2, 3) + 1j * np.ones((2, 3))).astype("complex64")
    d = to_device(x)
    assert jnp.iscomplexobj(d)
    back = to_host(d)
    np.testing.assert_allclose(back, x)


def test_to_host_real_passthrough():
    x = np.ones((4,), "float32")
    np.testing.assert_array_equal(to_host(jnp.asarray(x)), x)
    np.testing.assert_array_equal(to_host(x), x)  # numpy in, numpy out


def test_stage_timer():
    t = StageTimer()
    with t.stage("a"):
        pass
    with t.stage("a"):
        pass
    with t.stage("b", block_on=jnp.ones(())):
        pass
    rep = t.report()
    assert rep["a"]["calls"] == 2 and rep["b"]["calls"] == 1
    assert "a" in t.pretty()


def test_trace_to_noop_on_failure(tmp_path):
    # nested trace (or unavailable backend) must not raise
    with trace_to(str(tmp_path / "t1")):
        with trace_to(str(tmp_path / "t2")):
            pass
