"""Tests for transfer helpers and the profiling utility."""
import pytest
import numpy as np

import jax.numpy as jnp

from disco_tpu.utils import StageTimer, prefetch_to_device, to_device, to_host, trace_to


def test_to_host_complex_roundtrip():
    x = (np.arange(6).reshape(2, 3) + 1j * np.ones((2, 3))).astype("complex64")
    d = to_device(x)
    assert jnp.iscomplexobj(d)
    back = to_host(d)
    np.testing.assert_allclose(back, x)


def test_to_host_real_passthrough():
    x = np.ones((4,), "float32")
    np.testing.assert_array_equal(to_host(jnp.asarray(x)), x)
    np.testing.assert_array_equal(to_host(x), x)  # numpy in, numpy out


def test_stage_timer():
    t = StageTimer()
    with t.stage("a"):
        pass
    with t.stage("a"):
        pass
    with t.stage("b", block_on=jnp.ones(())):
        pass
    rep = t.report()
    assert rep["a"]["calls"] == 2 and rep["b"]["calls"] == 1
    assert "a" in t.pretty()


def test_prefetch_to_device_order_and_values():
    """Every batch arrives exactly once, in order, as device arrays."""
    batches = [(np.full((2, 3), i, np.float32), np.full((2,), -i, np.float32)) for i in range(7)]
    got = list(prefetch_to_device(iter(batches), size=3))
    assert len(got) == 7
    for i, (x, y) in enumerate(got):
        assert isinstance(x, jnp.ndarray)
        np.testing.assert_array_equal(np.asarray(x), batches[i][0])
        np.testing.assert_array_equal(np.asarray(y), batches[i][1])


def test_prefetch_to_device_empty_and_short():
    assert list(prefetch_to_device(iter([]), size=2)) == []
    one = [(np.zeros(2, np.float32),)]
    assert len(list(prefetch_to_device(iter(one), size=4))) == 1
    with pytest.raises(ValueError, match="size >= 1"):
        list(prefetch_to_device(iter(one), size=0))


def test_prefetch_to_device_propagates_source_error():
    def bad():
        yield (np.zeros(2, np.float32),)
        raise RuntimeError("loader exploded")

    it = prefetch_to_device(bad(), size=1)
    next(it)
    with pytest.raises(RuntimeError, match="loader exploded"):
        list(it)


def test_prefetch_complex_batches():
    """Complex pytree leaves go through the complex-safe transfer."""
    z = (np.arange(4) + 1j * np.arange(4)).astype(np.complex64)
    (got,), = list(prefetch_to_device(iter([(z,)]), size=1))
    np.testing.assert_array_equal(np.asarray(to_host(got)), z)


def test_stage_timer_sync_calls_block_until_ready(monkeypatch):
    """stage(block_on=...) must actually fence: the sync path calls
    jax.block_until_ready on the handed tensor (on real hardware that is
    what keeps the timing honest), and sync=False must not."""
    import jax

    blocked = []
    monkeypatch.setattr(jax, "block_until_ready", blocked.append)
    t = StageTimer(sync=True)
    x = jnp.ones((2,))
    with t.stage("fenced", block_on=x):
        pass
    assert len(blocked) == 1 and blocked[0] is x
    with t.stage("unfenced"):
        pass
    assert len(blocked) == 1  # no block_on -> no fence
    t_async = StageTimer(sync=False)
    with t_async.stage("async", block_on=x):
        pass
    assert len(blocked) == 1  # sync=False -> never fences
    # the fenced stage still accumulated its timing
    assert t.report()["fenced"]["calls"] == 1


def test_stage_timer_sync_fences_even_on_body_exception(monkeypatch):
    """The finally-path must fence before recording, or the timing of a
    raising stage silently loses the device wait."""
    import jax

    blocked = []
    monkeypatch.setattr(jax, "block_until_ready", blocked.append)
    t = StageTimer()
    with pytest.raises(RuntimeError, match="boom"):
        with t.stage("explodes", block_on=jnp.ones(())):
            raise RuntimeError("boom")
    assert len(blocked) == 1
    assert t.report()["explodes"]["calls"] == 1


def test_trace_to_success_path_starts_and_stops(monkeypatch, tmp_path):
    import jax

    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop", None))
    )
    logdir = str(tmp_path / "trace")
    with trace_to(logdir):
        assert calls == [("start", logdir)]
    assert calls == [("start", logdir), ("stop", None)]


def test_trace_to_failure_is_noop_that_still_yields(monkeypatch, capsys):
    """A profiler that cannot start must not break the pipeline: the body
    still runs, stop_trace is never called, and the note goes to stdout."""
    import jax

    def broken_start(logdir):
        raise RuntimeError("profiler busy")

    stops = []
    monkeypatch.setattr(jax.profiler, "start_trace", broken_start)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: stops.append(1))
    ran = []
    with trace_to("/nonexistent/dir"):
        ran.append(1)
    assert ran == [1]
    assert stops == []  # never started -> never stopped
    assert "trace unavailable" in capsys.readouterr().out


@pytest.mark.slow
def test_trace_to_noop_on_failure(tmp_path):
    # nested trace (or unavailable backend) must not raise
    with trace_to(str(tmp_path / "t1")):
        with trace_to(str(tmp_path / "t2")):
            pass


def test_is_tpu_false_on_cpu_and_memoized():
    """Backend routing helper: False on the CPU test backend, and the
    success-path answer is memoized (transient failures are NOT — see
    utils/backend.py)."""
    from disco_tpu.utils import backend

    assert backend.is_tpu() is False
    assert backend._cached is False  # success path memoized
    # memoized answer is returned without re-probing jax
    assert backend.is_tpu() is False
