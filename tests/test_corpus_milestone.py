"""The self-generated-corpus milestone (gen → mix → z → train → tango with
oracle AND trained CRNN masks) runs end-to-end at tiny scale — the config-3/4
numbers produced from real pipeline data (VERDICT round-1 item 5)."""
import pytest
import numpy as np

from disco_tpu.milestones_corpus import corpus_milestone, meetit_corpus_milestone


def test_meetit_corpus_milestone_tiny(tmp_path):
    """Config 4 on generated corpus material: gen_meetit → saved-artifact
    separation → every (source, node) pair separated by several dB SI-SDR
    over the ref-channel mixture baseline."""
    out = meetit_corpus_milestone(tmp_path, n_rirs=1, n_src=2, max_order=4)
    assert out["config"] == "meetit_corpus_separation"
    assert out["pairs_scored"] == 2  # source s scored at its own node s
    assert out["delta_si_sir_min"] > 3.0, out  # interference rejection
    assert out["delta_si_sdr_mean"] > 1.0, out


@pytest.mark.slow
def test_corpus_milestone_tiny(tmp_path):
    out = corpus_milestone(tmp_path, n_rirs=2, n_epochs=1, max_order=4)
    assert out["config"] == "corpus_pipeline"
    assert set(out) >= {"tango_4node_oracle", "tango_4node_crnn"}
    for entry in (out["tango_4node_oracle"], out["tango_4node_crnn"]):
        for key in ("delta_sdr_512tap", "delta_si_sdr", "delta_stoi"):
            assert np.isfinite(entry[key]), (entry, key)
    # oracle masks on pipeline data must enhance (the CRNN entry is allowed
    # to be weak at 1 epoch x 2 clips — the full run trains properly)
    assert out["tango_4node_oracle"]["delta_sdr_512tap"] > 2.0
