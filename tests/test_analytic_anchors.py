"""Closed-form analytic anchors for the self-authored numerical oracles.

Round-2 verdict (VERDICT.md "What's weak" #3): the ISM, bss_eval and STOI
implementations were validated only against builder-authored float64 oracles
— strong against regressions, weak against a shared misreading of the
third-party conventions they replace (pyroomacoustics libroom, mir_eval,
pystoi).  The tests here assert values derivable BY HAND from the published
definitions, with the expected numbers computed inline from first
principles (no reference_impls import):

* ISM: a free-field scene has exactly one image — the direct path — whose
  windowed-sinc taps and 1/(4*pi*d) amplitude are written out analytically;
  a first-order room is pinned against a hand-enumerated 7-image sum.
  (reference convolve_signals.py:84-99 delegates this to libroom)
* bss_eval: impulse references make every delayed-span projection an exact
  windowed selection, so SDR/SIR/SAR have closed forms; any <512-tap
  filtering of the reference is admissible distortion and must score ~inf.
  (reference tango.py:552-567 delegates to mir_eval)
* STOI: perfect input scores exactly 1, the score is gain-invariant on both
  arguments, monotone in SNR, and the segment-correlation core reproduces
  hand-built +-1 envelope correlations.  (reference tango.py:569-578
  delegates to pystoi)
"""
import math

import numpy as np
import pytest

from disco_tpu.core.bss import BssEval, bss_eval_one
from disco_tpu.core.metrics import _STOI_NBANDS, _STOI_SEG, _stoi_corr_sum, stoi
from disco_tpu.sim.ism import C_SOUND, FDL, shoebox_rir

FS = 16000


def _hann_sinc(u: float) -> float:
    """The libroom windowed-sinc fractional-delay tap at offset ``u`` from
    the (fractional) delay, written from the published formula: an 81-tap
    Hann-windowed sinc, window half-width (FDL//2)+1."""
    half = FDL // 2
    if abs(u) > half + 1:
        return 0.0
    w = 0.5 * (1.0 + math.cos(math.pi * u / (half + 1)))
    s = 1.0 if u == 0 else math.sin(math.pi * u) / (math.pi * u)
    return s * w


# ------------------------------------------------------------------- ISM
def test_ism_free_field_integer_delay_is_single_tap():
    """alpha=1 (fully absorbing walls) leaves ONLY the direct path, and an
    integer-sample delay collapses the windowed sinc to one tap: the RIR
    must be exactly 1/(4*pi*d) at sample round(d*fs/c) and ~0 elsewhere."""
    k = 100  # integer delay in samples
    d = k * C_SOUND / FS  # 2.143 m
    room = np.array([10.0, 10.0, 10.0])
    src = np.array([2.0, 2.0, 2.0])
    mic = np.array([[2.0 + d, 2.0, 2.0]])
    rir = np.asarray(shoebox_rir(room, src, mic, alpha=1.0, max_order=20, rir_len=512))
    amp = 1.0 / (4.0 * math.pi * d)
    assert rir.shape == (1, 512)
    assert rir[0, k] == pytest.approx(amp, rel=1e-6)
    rest = rir[0].copy()
    rest[k] = 0.0
    # sinc at the other integer offsets is ~sin(pi*n): float32 rounding of
    # pi*n leaves ~1e-5 relative residue, far below any physical image
    assert np.max(np.abs(rest)) < 1e-4 * amp


def test_ism_free_field_half_sample_delay_taps():
    """Fractional delay: every tap of the 81-tap windowed sinc at frac=0.5
    must equal amp * sinc(j - 0.5) * hann(j - 0.5), computed by hand."""
    delay = 100.5
    d = delay * C_SOUND / FS
    room = np.array([12.0, 12.0, 12.0])
    src = np.array([3.0, 3.0, 3.0])
    mic = np.array([[3.0 + d, 3.0, 3.0]])
    rir = np.asarray(shoebox_rir(room, src, mic, alpha=1.0, max_order=0, rir_len=512))
    amp = 1.0 / (4.0 * math.pi * d)
    half = FDL // 2
    expect = np.zeros(512)
    for j in range(-half, half + 1):
        expect[100 + j] = amp * _hann_sinc(j - 0.5)
    np.testing.assert_allclose(rir[0], expect, rtol=2e-5, atol=1e-9)


def test_ism_first_order_hand_enumerated_images():
    """max_order=1: the RIR must equal the hand-enumerated 7-image sum —
    direct + one mirror per wall at the textbook positions
    (2nL - x_s per axis), each with amplitude beta^1 / (4 pi d)."""
    L = np.array([4.0, 5.0, 6.0])
    src = np.array([1.0, 2.0, 3.0])
    mic = np.array([2.5, 2.0, 3.0])
    alpha = 0.75
    beta = math.sqrt(1.0 - alpha)  # 0.5
    # (image position, reflection count) — enumerated by hand
    images = [
        ((1.0, 2.0, 3.0), 0),    # direct
        ((-1.0, 2.0, 3.0), 1),   # x = 0 wall
        ((7.0, 2.0, 3.0), 1),    # x = Lx wall: 2*4 - 1
        ((1.0, -2.0, 3.0), 1),   # y = 0 wall
        ((1.0, 8.0, 3.0), 1),    # y = Ly wall: 2*5 - 2
        ((1.0, 2.0, -3.0), 1),   # z = 0 wall
        ((1.0, 2.0, 9.0), 1),    # z = Lz wall: 2*6 - 3
    ]
    rir_len = 2048
    expect = np.zeros(rir_len)
    half = FDL // 2
    for pos, n_refl in images:
        d = math.dist(pos, mic)
        a = beta**n_refl / (4.0 * math.pi * d)
        delay = d * FS / C_SOUND
        t0, frac = int(math.floor(delay)), delay - math.floor(delay)
        for j in range(-half, half + 1):
            t = t0 + j
            if 0 <= t < rir_len:
                expect[t] += a * _hann_sinc(j - frac)
    rir = np.asarray(shoebox_rir(L, src, mic[None, :], alpha=alpha, max_order=1, rir_len=rir_len))
    np.testing.assert_allclose(rir[0], expect, rtol=2e-4, atol=1e-8)


# ------------------------------------------------------------------- bss_eval
def test_bss_impulse_references_closed_form():
    """Impulse references make the block-Toeplitz Gram the identity, so the
    decomposition is an exact windowed selection with closed-form scores.

    refs: s1 = delta_0, s2 = delta_2000; flen=512 spans cover samples
    [0, 511] and [2000, 2511].  Estimate e = 3 delta_5 + 2 delta_2007 +
    4 delta_1000 therefore decomposes EXACTLY into s_target = 3 delta_5,
    e_interf = 2 delta_2007, e_artif = 4 delta_1000 (Vincent 2006 eqs. 2-5):

        SDR = 10 log10(9 / (4 + 16)),  SIR = 10 log10(9 / 4),
        SAR = 10 log10((9 + 4) / 16).
    """
    T = 3000
    refs = np.zeros((2, T))
    refs[0, 0] = 1.0
    refs[1, 2000] = 1.0
    est = np.zeros(T)
    est[5] = 3.0
    est[2007] = 2.0
    est[1000] = 4.0
    sdr, sir, sar = BssEval(refs).score(est, j=0)
    assert sdr == pytest.approx(10 * math.log10(9 / 20), abs=1e-9)
    assert sir == pytest.approx(10 * math.log10(9 / 4), abs=1e-9)
    assert sar == pytest.approx(10 * math.log10(13 / 16), abs=1e-9)


def test_bss_admissible_filtering_scores_infinite(rng):
    """Any estimate that is a <512-tap filtering of its reference is
    admissible distortion by definition (mir_eval convention the driver's
    metrics must keep): SDR/SIR/SAR all ~inf."""
    s = rng.standard_normal(4000)
    s[-200:] = 0.0  # silent tail: the filtered estimate loses nothing to
    # the length-T truncation, so the projection residual is exactly 0
    h = np.zeros(3)
    h[0], h[2] = 0.5, 0.25
    est = np.convolve(s, h)[:4000]
    sdr, sir, sar = bss_eval_one(s[None, :], est)
    assert sdr > 100.0
    assert np.isinf(sir) or sir > 100.0
    assert np.isinf(sar) or sar > 100.0


def test_bss_pure_delay_scores_infinite(rng):
    """A pure delay below the filter length is a special case of admissible
    filtering — the 'delayed estimate must not be penalized' property that
    distinguishes bss_eval from the scale-invariant family."""
    s = rng.standard_normal(4000)
    s[-200:] = 0.0  # see above: keep the delayed copy inside the window
    est = np.roll(s, 100)
    est[:100] = 0.0
    sdr, _, _ = bss_eval_one(s[None, :], est)
    assert sdr > 100.0


# ------------------------------------------------------------------- STOI
def test_stoi_perfect_signal_is_exactly_one(rng):
    """x == y: every band's clipped envelope correlation is exactly 1, so
    the mean over segments and bands is exactly 1 (to float rounding)."""
    x = rng.standard_normal(10000)  # 1 s at the internal 10 kHz rate
    assert stoi(x, x, 10000) == pytest.approx(1.0, abs=1e-12)


def test_stoi_gain_invariance(rng):
    """The per-segment normalization (alpha) and the relative silent-frame
    threshold make the score exactly invariant to scalar gain on either
    argument (Taal 2011 sec. II)."""
    x = rng.standard_normal(12000)
    y = x + 0.3 * rng.standard_normal(12000)
    base = stoi(x, y, 10000)
    assert stoi(x, 7.3 * y, 10000) == pytest.approx(base, abs=1e-12)
    assert stoi(0.02 * x, y, 10000) == pytest.approx(base, abs=1e-12)


def test_stoi_monotone_in_snr(rng):
    """More additive noise can only lower intelligibility: the score must be
    non-increasing over a decreasing-SNR sweep (same noise draw)."""
    x = rng.standard_normal(12000)
    n = rng.standard_normal(12000)
    scores = [stoi(x, x + sig * n, 10000) for sig in (0.0, 0.1, 0.3, 1.0, 3.0)]
    assert scores[0] == pytest.approx(1.0, abs=1e-12)
    for a, b in zip(scores, scores[1:]):
        assert b <= a + 1e-9
    assert scores[-1] < scores[0] - 0.2  # and the sweep actually moves


def test_stoi_segment_correlation_hand_built_envelopes():
    """The correlation core on hand-built envelopes: anti-proportional
    band envelopes (1 + a m_t vs 1 - a m_t, depth small enough that the
    -15 dB clipping never engages) correlate to exactly -1 in every band;
    proportional ones to exactly +1."""
    n_frames = 40
    t = np.arange(n_frames)
    m = np.sin(2 * np.pi * t / 10.0)
    Xb = np.tile(1.0 + 0.2 * m, (_STOI_NBANDS, 1))
    n_seg_expect = n_frames - _STOI_SEG + 1
    d, n_seg = _stoi_corr_sum(Xb, np.tile(1.0 - 0.2 * m, (_STOI_NBANDS, 1)))
    assert n_seg == n_seg_expect
    assert d == pytest.approx(-_STOI_NBANDS * n_seg_expect, abs=1e-9)
    d, _ = _stoi_corr_sum(Xb, 3.0 * Xb)
    assert d == pytest.approx(_STOI_NBANDS * n_seg_expect, abs=1e-9)
