"""Corpus throughput engine (disco_tpu.enhance.pipeline): prefetcher unit
behavior, the single-batched-readback contract, the compile-cache seam, the
corpus regression verdict in `disco-obs compare`, and — slow-marked — the
pipelined-vs-sequential parity and chaos-crash-under-prefetch integration
tests on the runs/check.py miniature-corpus harness."""
import json
import time
from pathlib import Path

import numpy as np
import pytest

from disco_tpu.enhance.pipeline import ChunkPrefetcher


# -- ChunkPrefetcher --------------------------------------------------------
def test_prefetcher_yields_in_order_with_stall():
    loads = []

    def load(i):
        loads.append(i)
        return i * 10

    pf = ChunkPrefetcher([(i,) for i in range(5)], load)
    try:
        got = [(item, stall) for item, stall in pf]
    finally:
        pf.close()
    assert [g[0] for g in got] == [0, 10, 20, 30, 40]
    assert loads == [0, 1, 2, 3, 4]
    assert all(g[1] >= 0.0 for g in got)


def test_prefetcher_overlaps_load_with_consumption():
    """While the consumer holds chunk N, the background thread loads ahead
    — by the time the first slow consume finishes, later loads happened."""
    t_load = {}

    def load(i):
        t_load[i] = time.perf_counter()
        return i

    pf = ChunkPrefetcher([(i,) for i in range(3)], load)
    try:
        it = iter(pf)
        first, _ = next(it)
        time.sleep(0.3)  # "device compute" for chunk 0
        t_consumed = time.perf_counter()
        rest = [item for item, _ in it]
    finally:
        pf.close()
    assert first == 0 and rest == [1, 2]
    # chunk 1 was loaded during chunk 0's consumption, not after it
    assert t_load[1] < t_consumed


def test_prefetcher_reraises_baseexception_at_consumer():
    """A BaseException on the loader thread (the ChaosCrash contract) must
    surface at the consuming site, after the items loaded before it."""

    class FakeCrash(BaseException):
        pass

    def load(i):
        if i == 1:
            raise FakeCrash()
        return i

    pf = ChunkPrefetcher([(0,), (1,), (2,)], load)
    try:
        it = iter(pf)
        assert next(it)[0] == 0
        with pytest.raises(FakeCrash):
            for _ in it:
                pass
    finally:
        pf.close()


def test_prefetcher_stop_requested_loads_nothing():
    loads = []
    pf = ChunkPrefetcher(
        [(i,) for i in range(4)], lambda i: loads.append(i) or i,
        stop_requested=lambda: True,
    )
    try:
        assert [item for item, _ in pf] == []
    finally:
        pf.close()
    assert loads == []


def test_prefetcher_close_unblocks_pending_loader():
    """close() must release a loader blocked on a full queue (a consumer
    that crashed mid-iteration) — no orphan thread appending ledger marks
    after its run is gone."""
    pf = ChunkPrefetcher([(i,) for i in range(20)], lambda i: i)
    item, _ = next(iter(pf))  # consume one, leave the queue full
    assert item == 0
    pf.close()
    assert not pf._thread.is_alive()
    pf.close()  # idempotent


def test_prefetcher_rejects_single_buffering():
    with pytest.raises(ValueError, match="depth"):
        ChunkPrefetcher([], lambda: None, depth=1)


# -- device_get_tree --------------------------------------------------------
def test_device_get_tree_complex_roundtrip_single_batch(rng):
    import jax.numpy as jnp

    from disco_tpu.obs.accounting import device_get_count, fence_count
    from disco_tpu.utils.transfer import device_get_tree

    c = (rng.standard_normal((3, 5)) + 1j * rng.standard_normal((3, 5))).astype("complex64")
    r = rng.standard_normal((2, 4)).astype("float32")
    tree = {"c": jnp.asarray(c), "nested": [jnp.asarray(r), None], "host": r}
    g0, f0 = device_get_count(), fence_count()
    out = device_get_tree(tree)
    # ONE batched get, one fenced RPC round — however many leaves
    assert device_get_count() - g0 == 1
    assert fence_count() - f0 == 1
    assert isinstance(out["c"], np.ndarray) and out["c"].dtype == np.complex64
    np.testing.assert_array_equal(out["c"], c)
    np.testing.assert_array_equal(out["nested"][0], r)
    assert out["nested"][1] is None
    assert out["host"] is r  # host leaves pass through untouched


def test_device_get_tree_pure_host_tree_counts_nothing(rng):
    from disco_tpu.obs.accounting import device_get_count
    from disco_tpu.utils.transfer import device_get_tree

    tree = {"a": rng.standard_normal(3), "b": None}
    g0 = device_get_count()
    out = device_get_tree(tree)
    assert device_get_count() == g0
    assert out["a"] is tree["a"]


# -- compile cache seam -----------------------------------------------------
@pytest.fixture
def _cache_state():
    """Save/restore the process-wide compile-cache resolution and the jax
    config value around each test."""
    import jax

    from disco_tpu.utils import compile_cache

    prev = jax.config.jax_compilation_cache_dir
    compile_cache._reset_for_tests()
    yield compile_cache
    compile_cache._reset_for_tests()
    jax.config.update("jax_compilation_cache_dir", prev)


def test_compile_cache_enables_at_explicit_path(_cache_state, tmp_path):
    import jax

    path = _cache_state.ensure_enabled(str(tmp_path / "xla"))
    assert path == str(tmp_path / "xla")
    assert Path(path).is_dir()
    assert jax.config.jax_compilation_cache_dir == path
    # idempotent: the first resolution wins for the whole process
    assert _cache_state.ensure_enabled(str(tmp_path / "other")) == path


def test_compile_cache_env_off(_cache_state, monkeypatch):
    monkeypatch.setenv(_cache_state.ENV_VAR, "off")
    assert _cache_state.ensure_enabled() is None


def test_compile_cache_false_disables(_cache_state):
    assert _cache_state.ensure_enabled(False) is None


def test_compile_cache_env_path_wins(_cache_state, monkeypatch, tmp_path):
    monkeypatch.setenv(_cache_state.ENV_VAR, str(tmp_path / "envcache"))
    assert _cache_state.ensure_enabled() == str(tmp_path / "envcache")


# -- disco-obs compare: corpus_clips_per_s verdict --------------------------
def _rec(rtf=6700.0, corpus=None):
    r = {"metric": "rtf_8node_mwf_enhancement", "value": rtf, "unit": "x_realtime"}
    if corpus is not None:
        r["corpus_clips_per_s"] = corpus
    return r


def test_compare_corpus_regression_flags():
    from disco_tpu.cli.obs import compare_records

    d = compare_records(_rec(corpus=10.0), _rec(corpus=8.0))  # -20% corpus
    assert d["verdict"] == "REGRESSION"
    assert "corpus" in d["detail"]
    assert any(r["key"] == "corpus_clips_per_s" for r in d["rows"])


def test_compare_corpus_ok_improved_and_absent_baseline():
    from disco_tpu.cli.obs import compare_records

    assert compare_records(_rec(corpus=10.0), _rec(corpus=9.8))["verdict"] == "OK"
    assert compare_records(_rec(corpus=10.0), _rec(corpus=12.0))["verdict"] == "IMPROVED"
    # pre-engine baselines have no corpus lane: its absence must not flag
    assert compare_records(_rec(), _rec(corpus=12.0))["verdict"] == "OK"
    # headline regression still dominates a corpus improvement
    d = compare_records(_rec(rtf=6700.0, corpus=10.0), _rec(rtf=5000.0, corpus=12.0))
    assert d["verdict"] == "REGRESSION"


def test_compare_corpus_lane_lost_is_regression(tmp_path, capsys):
    from disco_tpu.cli import obs as obs_cli

    old, new = tmp_path / "old.json", tmp_path / "new.json"
    old.write_text(json.dumps(_rec(corpus=10.0)))
    new.write_text(json.dumps(_rec()))
    with pytest.raises(SystemExit) as exc:
        obs_cli.main(["compare", str(old), str(new)])
    assert exc.value.code == 1
    assert "REGRESSION" in capsys.readouterr().out


# -- integration: parity and chaos under prefetch (miniature corpus) --------
def _mini(tmp_path):
    from disco_tpu.runs.check import _mini_corpus

    return _mini_corpus(tmp_path / "dataset")


def _enhance(corpus, out_root, **kw):
    from disco_tpu.enhance.driver import enhance_rirs_batched
    from disco_tpu.runs.check import C, K, NOISE, RIRS, SNR_RANGE

    kw.setdefault("max_batch", 2)
    kw.setdefault("score_workers", 1)
    return enhance_rirs_batched(
        str(corpus), "living", list(RIRS), NOISE, snr_range=SNR_RANGE,
        out_root=str(out_root), save_fig=False, bucket=8192,
        n_nodes=K, mics_per_node=C, **kw,
    )


def _relative_digests(ledger_path, out_root):
    """{unit: {relative artifact path: digest}} from a ledger's done records."""
    from disco_tpu.runs.ledger import RunLedger

    out = {}
    for unit, rec in RunLedger(ledger_path).replay().items():
        assert rec["state"] == "done", (unit, rec["state"])
        out[unit] = {
            str(Path(p).relative_to(out_root)): d
            for p, d in (rec.get("artifacts") or {}).items()
        }
    return out


@pytest.mark.slow
def test_pipelined_matches_sequential_bytes_and_ledger(tmp_path):
    """The engine's overlap changes scheduling, never artifacts: byte-
    identical tree, ledger replaying to the same per-unit end states with
    the same digests, and ONE batched readback per chunk (max_batch=1 →
    two chunks → two batched gets, not K×n_real per-clip reads)."""
    from disco_tpu.obs.accounting import device_get_count
    from disco_tpu.runs.check import RIRS, _trees_identical

    corpus = _mini(tmp_path)
    seq, led_seq = tmp_path / "seq", tmp_path / "led_seq.jsonl"
    pipe, led_pipe = tmp_path / "pipe", tmp_path / "led_pipe.jsonl"

    res_seq = _enhance(corpus, seq, pipeline=False, ledger=str(led_seq), max_batch=1)
    g0 = device_get_count()
    res_pipe = _enhance(corpus, pipe, pipeline=True, ledger=str(led_pipe), max_batch=1)
    assert device_get_count() - g0 == len(RIRS)  # one get per chunk
    assert set(res_seq) == set(res_pipe) == set(RIRS)

    failures = []
    _trees_identical(seq, pipe, failures, "pipelined parity")
    assert not failures, failures
    assert _relative_digests(led_seq, seq) == _relative_digests(led_pipe, pipe)

    # overlap gauges recorded
    from disco_tpu.obs.metrics import REGISTRY

    gauges = REGISTRY.snapshot()["gauges"]
    for g in ("prefetch_stall_ms", "readback_ms", "overlap_efficiency"):
        assert gauges.get(g) is not None, g


@pytest.mark.slow
@pytest.mark.parametrize("seam,after", [("mid_write", 5), ("pre_dispatch", 1),
                                        ("chunk_load", 1)])
def test_pipelined_chaos_crash_resumes_byte_identical(tmp_path, seam, after):
    """A crash under prefetch — inside an artifact write, before a dispatch
    with a chunk already prefetched, or ON the prefetch thread mid-ingest —
    resumes from the ledger to a byte-identical tree."""
    from disco_tpu.runs import chaos
    from disco_tpu.runs.check import _trees_identical

    corpus = _mini(tmp_path)
    ref = tmp_path / "ref"
    _enhance(corpus, ref, pipeline=True)

    out, led = tmp_path / "crashed", tmp_path / "led.jsonl"
    chaos.configure(seam, after=after)
    try:
        with pytest.raises(chaos.ChaosCrash):
            _enhance(corpus, out, pipeline=True, ledger=str(led))
    finally:
        chaos.disable()
    _enhance(corpus, out, pipeline=True, ledger=str(led), resume=True)
    failures = []
    _trees_identical(ref, out, failures, f"{seam} resume")
    assert not failures, failures
