"""Flywheel subsystem tests: shard format, corpus tap, shard dataset and
the sharded/bf16 training lanes (disco_tpu/flywheel, nn/training mesh+
precision paths).  The end-to-end serve→tap→shard→train loop is gated by
``make flywheel-check``; these tests pin the pieces in isolation."""
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from disco_tpu.flywheel import (
    CorpusTap,
    ShardDataset,
    ShardError,
    list_shards,
    probe_shard,
    read_shard,
    write_shard,
)
from disco_tpu.obs.metrics import REGISTRY as obs_registry

K, C, F, T = 4, 2, 9, 8


def _block(rng, seq=0, session="s"):
    Y = (rng.standard_normal((K, C, F, T))
         + 1j * rng.standard_normal((K, C, F, T))).astype(np.complex64)
    yf = (rng.standard_normal((K, F, T))
          + 1j * rng.standard_normal((K, F, T))).astype(np.complex64)
    mz = rng.uniform(0.05, 0.95, (K, F, T)).astype(np.float32)
    mw = rng.uniform(0.05, 0.95, (K, F, T)).astype(np.float32)
    return {"session": session, "seq": seq, "Y": Y, "yf": yf,
            "mask_z": mz, "mask_w": mw}


def _fill_tap_dir(tmp_path, rng, n_blocks=6, records_per_shard=3):
    tap = CorpusTap(tmp_path / "tap", records_per_shard=records_per_shard)
    for i in range(n_blocks):
        b = _block(rng, seq=i)
        assert tap.offer("s1", i, b["Y"], b["mask_z"], b["mask_w"], b["yf"])
    tap.close()
    return tmp_path / "tap"


# ---------------------------------------------------------------- shard files
def test_shard_roundtrip_preserves_complex_splits(tmp_path, rng):
    rec = _block(rng)
    p = write_shard(tmp_path / "a.shard.msgpack", [rec], meta={"k": 1})
    meta, records = read_shard(p)
    assert meta == {"k": 1} and len(records) == 1
    got = records[0]
    assert got["session"] == "s" and got["seq"] == 0
    for key in ("Y", "yf"):
        assert got[key].dtype == np.complex64
        np.testing.assert_array_equal(got[key], rec[key])
    for key in ("mask_z", "mask_w"):
        assert got[key].dtype == np.float32
        np.testing.assert_array_equal(got[key], rec[key])
    assert probe_shard(p)


def test_torn_and_tampered_shards_fail_probe(tmp_path, rng):
    p = write_shard(tmp_path / "a.shard.msgpack", [_block(rng)])
    raw = bytearray(p.read_bytes())
    # truncation: a torn write that somehow reached a final path
    torn = tmp_path / "torn.shard.msgpack"
    torn.write_bytes(bytes(raw[: len(raw) // 2]))
    assert not probe_shard(torn)
    with pytest.raises(ShardError):
        read_shard(torn)
    # tamper: flip one payload byte — the embedded digest must catch it
    flipped = bytearray(raw)
    flipped[len(flipped) // 2] ^= 0xFF
    bad = tmp_path / "bad.shard.msgpack"
    bad.write_bytes(bytes(flipped))
    assert not probe_shard(bad)
    # not-a-shard
    junk = tmp_path / "junk.shard.msgpack"
    junk.write_bytes(b"\x00\x01\x02")
    assert not probe_shard(junk)


def test_write_shard_is_atomic_under_mid_write_chaos(tmp_path, rng):
    from disco_tpu.io.atomic import TMP_SUFFIX
    from disco_tpu.runs import chaos

    victim = tmp_path / "v.shard.msgpack"
    chaos.configure("mid_write", after=1)
    try:
        with pytest.raises(chaos.ChaosCrash):
            write_shard(victim, [_block(rng)])
    finally:
        chaos.disable()
    assert not victim.exists()
    assert not list(tmp_path.rglob(f"*{TMP_SUFFIX}.*"))
    # clean retry lands
    write_shard(victim, [_block(rng)])
    assert probe_shard(victim)


# ------------------------------------------------------------------- the tap
def test_tap_overflow_drops_and_counts_without_blocking(tmp_path, rng):
    tap = CorpusTap(tmp_path / "tap", max_queue_blocks=4,
                    records_per_shard=3, start=False)
    c0 = obs_registry.counter("tap_dropped").value
    for i in range(7):
        b = _block(rng, seq=i)
        ok = tap.offer("s1", i, b["Y"], b["mask_z"], b["mask_w"], b["yf"])
        assert ok == (i < 4)  # queue bound 4: the rest drop, never block
    assert tap.dropped == 3
    assert obs_registry.counter("tap_dropped").value - c0 == 3
    stats = tap.close()  # flushes the 4 accepted blocks via a late start
    assert stats["blocks_accepted"] == 4 and stats["blocks_dropped"] == 3
    shards = list_shards(tmp_path / "tap")
    assert sum(len(read_shard(s)[1]) for s in shards) == 4
    # offers after close drop-and-count instead of raising
    b = _block(rng, seq=99)
    assert not tap.offer("s1", 99, b["Y"], b["mask_z"], b["mask_w"], b["yf"])


def test_tap_rotation_and_manifest_verify(tmp_path, rng):
    from disco_tpu.runs.ledger import RunLedger

    tap_dir = _fill_tap_dir(tmp_path, rng, n_blocks=7, records_per_shard=3)
    shards = list_shards(tap_dir)
    assert len(shards) == 3  # 3 + 3 + the close()-flushed remainder of 1
    assert [len(read_shard(s)[1]) for s in shards] == [3, 3, 1]
    done, requeued = RunLedger(tap_dir / "manifest.jsonl").verified_done(requeue=False)
    assert len(done) == 3 and not requeued


def test_tap_writer_is_jax_free_by_lint_contract():
    """The tap thread's import graph is pinned by disco-lint DL005 — this
    asserts the flywheel host-side files are actually enrolled in the
    no-jax-anywhere list (deleting them from the rule must fail a test,
    not just silently weaken the gate)."""
    from disco_tpu.analysis.rules.purity import CLIENT_FILES

    for f in ("disco_tpu/flywheel/tap.py", "disco_tpu/flywheel/shards.py",
              "disco_tpu/flywheel/dataset.py", "disco_tpu/flywheel/__init__.py"):
        assert f in CLIENT_FILES


# -------------------------------------------------------------- shard dataset
def test_dataset_deterministic_shuffle_and_epoch_variation(tmp_path, rng):
    tap_dir = _fill_tap_dir(tmp_path, rng)
    ds = ShardDataset(tap_dir, win_len=4, seed=7)
    a = list(ds.batches(4, epoch=0))
    b = list(ds.batches(4, epoch=0))
    assert len(a) > 1
    assert all(np.array_equal(xa, xb) and np.array_equal(ya, yb)
               for (xa, ya), (xb, yb) in zip(a, b))
    c = list(ds.batches(4, epoch=1))
    assert not all(np.array_equal(xa, xc) for (xa, _), (xc, _) in zip(a, c))
    # windows follow the DiscoDataset item convention: (win, F) pairs
    x0, y0 = a[0]
    assert x0.shape == (4, 4, F) and y0.shape == (4, 4, F)
    assert x0.dtype == np.float32 and y0.dtype == np.float32


def test_dataset_ledger_resume_skips_consumed_shards(tmp_path, rng):
    tap_dir = _fill_tap_dir(tmp_path, rng)
    ds = ShardDataset(tap_dir, win_len=4, seed=7)
    led = tmp_path / "led.jsonl"
    full = list(ds.batches(4, epoch=0, ledger=led))
    assert full
    # a completed epoch fully resumes to nothing
    assert list(ds.batches(4, epoch=0, ledger=led)) == []
    # another epoch is untouched by epoch-0 records
    assert len(list(ds.batches(4, epoch=1, ledger=led))) == len(
        list(ds.batches(4, epoch=1))
    )


def test_dataset_recent_window_reads_only_newest_shards(tmp_path, rng):
    """The sliding-window corpus knob: ``recent=N`` must consume exactly
    the N newest shards (by shard number) — the contract that keeps a
    continuous trainer's epoch cost bounded as the tap directory grows."""
    tap_dir = _fill_tap_dir(tmp_path, rng, n_blocks=7, records_per_shard=3)
    ds = ShardDataset(tap_dir, win_len=4, seed=7)
    shards = list_shards(tap_dir)  # [3, 3, 1] records
    led = tmp_path / "led.jsonl"
    assert list(ds.batches(4, epoch=0, ledger=led, recent=2))
    from disco_tpu.runs.ledger import RunLedger
    done, _ = RunLedger(led).verified_done(requeue=False)
    touched = {u.split(":")[1] for u in done}
    assert touched == {p.name for p in shards[-2:]}  # oldest shard untouched
    # a window wider than the directory degrades to the full corpus
    assert len(list(ds.batches(4, epoch=1, recent=99))) == len(
        list(ds.batches(4, epoch=1)))
    with pytest.raises(ValueError):
        next(ds.batches(4, epoch=0, recent=0))


def test_tap_shard_numbering_resumes_after_restart(tmp_path, rng):
    """A second CorpusTap over the same directory (crash recovery, the
    resident trainer's endurance campaign) must APPEND after the highest
    on-disk shard number — an overwrite of tap-000001 would both lose data
    and void the manifest's recorded digest for that name."""
    from disco_tpu.runs.ledger import RunLedger

    tap_dir = _fill_tap_dir(tmp_path, rng, n_blocks=3, records_per_shard=3)
    first = [p.name for p in list_shards(tap_dir)]
    tap = CorpusTap(tap_dir, records_per_shard=3)
    for i in range(3):
        b = _block(rng, seq=i, session="s2")
        assert tap.offer("s2", i, b["Y"], b["mask_z"], b["mask_w"], b["yf"])
    tap.close()
    names = [p.name for p in list_shards(tap_dir)]
    assert names[: len(first)] == first and len(names) == len(first) + 1
    assert len(set(names)) == len(names)
    # every shard — both generations — still digest-verifies in the manifest
    done, requeued = RunLedger(tap_dir / "manifest.jsonl").verified_done(
        requeue=False)
    assert len(done) == len(names) and not requeued


def test_dataset_skips_corrupt_shard_with_warning(tmp_path, rng):
    from disco_tpu import obs

    tap_dir = _fill_tap_dir(tmp_path, rng)
    intact = len(list(ShardDataset(tap_dir, win_len=4).batches(4, epoch=0)))
    good = list_shards(tap_dir)[0]
    raw = good.read_bytes()
    (tap_dir / "zz-torn.shard.msgpack").write_bytes(raw[: len(raw) // 2])
    c0 = obs_registry.peek_counter("shards_skipped")
    log = tmp_path / "ev.jsonl"
    with obs.recording(log):
        after = len(list(ShardDataset(tap_dir, win_len=4).batches(4, epoch=0)))
    assert after == intact  # the torn shard contributed nothing
    assert obs_registry.peek_counter("shards_skipped") - c0 == 1
    events = obs.read_events(log)
    assert any(e["kind"] == "warning" and "corrupt shard" in e["attrs"]["reason"]
               for e in events)


# ----------------------------------------------- scheduler post-readback seam
def test_scheduler_feeds_tap_at_the_post_readback_seam(tmp_path, rng):
    """A minimal in-process scheduler run: pushed blocks come back delivered
    AND spooled, with the tap's record bit-identical to the wire arrays."""
    from disco_tpu.serve.scheduler import Scheduler
    from disco_tpu.serve.session import SessionConfig

    Fs = 5
    cfg = SessionConfig(n_nodes=K, mics_per_node=C, n_freq=Fs,
                        block_frames=8, update_every=4)
    tap = CorpusTap(tmp_path / "tap", records_per_shard=2)
    sched = Scheduler(max_sessions=2, tap=tap)
    session = sched.open_session(cfg)
    Y = (rng.standard_normal((K, C, Fs, 8))
         + 1j * rng.standard_normal((K, C, Fs, 8))).astype(np.complex64)
    m = rng.uniform(0.05, 0.95, (K, Fs, 8)).astype(np.float32)
    sched.push_block(session, 0, Y, m, m)
    sched.push_block(session, 1, Y, m, m)
    deliveries = sched.tick()
    assert len(deliveries) == 2
    sched.request_close(session)
    sched.tick()
    tap.close()
    shards = list_shards(tmp_path / "tap")
    records = [r for s in shards for r in read_shard(s)[1]]
    assert sorted(r["seq"] for r in records) == [0, 1]
    for r in records:
        np.testing.assert_array_equal(r["Y"], Y)
        np.testing.assert_array_equal(r["mask_z"], m)
        _, seq, yf, _ = deliveries[r["seq"]]
        np.testing.assert_array_equal(r["yf"], np.asarray(yf))


# ------------------------------------------------------------- training lanes
def _tiny_model():
    from disco_tpu.nn.crnn import build_crnn

    return build_crnn(
        n_ch=1, win_len=9, n_freq=33, cnn_filters=(4, 4), conv_kernels=3,
        conv_strides=1, pool_kernels=[(1, 2)] * 2, pool_strides=None,
        conv_padding=[(0, 1)] * 2, rnn_units=(8,), ff_units=(33,),
    )


def _xy(rng, batch=8):
    x = rng.random((batch, 9, 33)).astype("float32")
    y = (rng.random((batch, 9, 33)) > 0.5).astype("float32")
    return x, y


def test_step_fn_factory_memoizes_and_canonicalizes_precision(rng):
    from disco_tpu.nn.training import make_step_fns

    model, _tx = _tiny_model()
    a = make_step_fns(model, "all", n_freq=33)
    b = make_step_fns(model, "all", n_freq=33, precision=" F32 ")
    assert a[0] is b[0] and a[1] is b[1]
    c = make_step_fns(model, "all", n_freq=33, precision="bf16")
    assert c[0] is not a[0]
    with pytest.raises(ValueError):
        make_step_fns(model, "all", n_freq=33, precision="fp8")


def test_bf16_lane_keeps_f32_masters_and_traces_one_program(rng):
    from disco_tpu.nn.training import create_train_state, make_step_fns
    from disco_tpu.obs.accounting import recompile_count

    model, tx = _tiny_model()
    x, y = _xy(rng)
    t32, _ = make_step_fns(model, "all", n_freq=33)
    tb, eb = make_step_fns(model, "all", n_freq=33, precision="bf16")
    s0 = create_train_state(model, tx, x[:1], seed=3)
    s32, l32 = t32(s0, x, y)
    n0 = recompile_count("train_step")
    sb, lb = tb(create_train_state(model, tx, x[:1], seed=3), x, y)
    sb2, _ = tb(sb, x, y)
    eb(sb2, x, y)
    # one program for the whole lane: the carried pytree keeps f32 dtypes
    assert recompile_count("train_step") - n0 <= 1
    for leaf in jax.tree_util.tree_leaves((sb.params, sb.batch_stats, sb.opt_state)):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    # the lane tracks the f32 oracle within bf16 resolution
    rel = abs(float(lb) - float(l32)) / max(abs(float(l32)), 1e-12)
    assert rel < 2e-2


def test_mesh_one_device_training_is_bit_exact(rng):
    from disco_tpu.nn.training import (
        create_train_state,
        make_step_fns,
        replicate_to_mesh,
    )
    from disco_tpu.parallel.mesh import make_mesh

    model, tx = _tiny_model()
    x, y = _xy(rng)
    t_ref, _ = make_step_fns(model, "all", n_freq=33)
    mesh = make_mesh(n_node=1, n_batch=1, devices=np.array(jax.devices()[:1]))
    t_mesh, _ = make_step_fns(model, "all", n_freq=33, mesh=mesh)

    s_ref = create_train_state(model, tx, x[:1], seed=5)
    s_mesh = replicate_to_mesh(create_train_state(model, tx, x[:1], seed=5), mesh)
    for _ in range(3):
        s_ref, l_ref = t_ref(s_ref, x, y)
        s_mesh, l_mesh = t_mesh(s_mesh, x, y)
        assert np.asarray(l_mesh).tobytes() == np.asarray(l_ref).tobytes()
    pa = np.asarray(jax.tree_util.tree_leaves(s_ref.params)[0])
    pb = np.asarray(jax.tree_util.tree_leaves(s_mesh.params)[0])
    np.testing.assert_array_equal(pa, pb)


@pytest.mark.slow
def test_mesh_eight_device_loss_parity(rng):
    from disco_tpu.flywheel.check import MESH_LOSS_RTOL
    from disco_tpu.nn.training import (
        create_train_state,
        make_step_fns,
        replicate_to_mesh,
    )
    from disco_tpu.parallel.mesh import make_mesh

    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest forces 8 virtual CPU devices"
    model, tx = _tiny_model()
    x, y = _xy(rng, batch=8)
    t_ref, _ = make_step_fns(model, "all", n_freq=33)
    mesh = make_mesh(n_node=1, n_batch=n_dev)
    t_mesh, _ = make_step_fns(model, "all", n_freq=33, mesh=mesh)
    s_ref = create_train_state(model, tx, x[:1], seed=5)
    s_mesh = replicate_to_mesh(create_train_state(model, tx, x[:1], seed=5), mesh)
    for _ in range(4):
        s_ref, l_ref = t_ref(s_ref, x, y)
        s_mesh, l_mesh = t_mesh(s_mesh, x, y)
        rel = abs(float(l_mesh) - float(l_ref)) / max(abs(float(l_ref)), 1e-12)
        assert rel <= MESH_LOSS_RTOL


@pytest.mark.slow
def test_fit_on_shards_with_prefetch_and_mesh(tmp_path, rng):
    """fit over a ShardDataset batch feed: the ChunkPrefetcher host
    prefetch records its overlap gauges, the mesh lane trains, and the
    checkpoint restores the explicit epochs_done count."""
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state, fit, load_checkpoint
    from disco_tpu.parallel.mesh import make_mesh

    tap_dir = _fill_tap_dir(tmp_path, rng, n_blocks=8, records_per_shard=4)
    ds = ShardDataset(tap_dir, win_len=4, seed=1)
    model, tx = build_crnn(n_ch=1, win_len=4, n_freq=F, cnn_filters=(2,),
                           pool_kernels=((1, 2),), conv_padding=((0, 1),),
                           rnn_units=(4,), ff_units=(F,), rnn_dropouts=0.0)
    first = next(ds.batches(2, epoch=0))
    state = create_train_state(model, tx, first[0][:1], seed=2)
    obs_registry.gauge("prefetch_stall_ms").value = None
    mesh = make_mesh(n_node=1, n_batch=len(jax.devices()))
    state, tr, va, name = fit(
        model, state, ds.batch_fn(8), ds.batch_fn(8, shuffle=False),
        n_epochs=2, save_path=tmp_path / "m", verbose=False, mesh=mesh,
    )
    assert np.count_nonzero(tr) == 2
    assert obs_registry.gauge("prefetch_stall_ms").value is not None
    assert obs_registry.gauge("overlap_efficiency").value is not None
    fresh = create_train_state(model, tx, first[0][:1], seed=2)
    _, tr_hist, va_hist = load_checkpoint(tmp_path / "m" / f"{name}_model.msgpack", fresh)
    assert 1 <= len(tr_hist) <= 2 and len(tr_hist) == len(va_hist)


@pytest.mark.slow
def test_resumed_fit_aligns_dataset_epochs_with_training_epochs(tmp_path, rng):
    """The resume protocol (batch_fn.set_start_epoch): a --weights resume
    with a reused dataset ledger must NOT replay dataset epoch 0 — whose
    shard units are already consumed — and silently train on zero batches."""
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state, fit

    tap_dir = _fill_tap_dir(tmp_path, rng, n_blocks=8, records_per_shard=4)
    ds = ShardDataset(tap_dir, win_len=4, seed=1)
    model, tx = build_crnn(n_ch=1, win_len=4, n_freq=F, cnn_filters=(2,),
                           pool_kernels=((1, 2),), conv_padding=((0, 1),),
                           rnn_units=(4,), ff_units=(F,), rnn_dropouts=0.0)
    first = next(ds.batches(2, epoch=0))
    led = tmp_path / "shards_led.jsonl"
    state = create_train_state(model, tx, first[0][:1], seed=2)
    state, tr, _va, name = fit(
        model, state, ds.batch_fn(8, ledger=led), ds.batch_fn(8, shuffle=False),
        n_epochs=2, save_path=tmp_path / "m", verbose=False,
    )
    assert np.count_nonzero(tr) == 2
    # resume for one more epoch with the SAME dataset ledger: the dataset
    # must serve epoch 2 (fresh units), not replay the consumed epoch 0
    state2 = create_train_state(model, tx, first[0][:1], seed=2)
    _, tr2, _va2, _ = fit(
        model, state2, ds.batch_fn(8, ledger=led), ds.batch_fn(8, shuffle=False),
        n_epochs=1, save_path=tmp_path / "m", verbose=False,
        resume_from=tmp_path / "m" / f"{name}_model.msgpack",
    )
    assert len(tr2) == 3 and tr2[2] > 0.0  # the resumed epoch actually trained


# ------------------------------------------------- checkpoint epoch-count fix
def test_checkpoint_stores_explicit_epoch_count_zero_loss_safe(tmp_path, rng):
    """The load_checkpoint resume bug (ISSUE 11 satellite): an epoch whose
    loss is legitimately 0.0 must not truncate the resume point."""
    from disco_tpu.nn.training import (
        create_train_state,
        load_checkpoint,
        save_checkpoint,
    )

    model, tx = _tiny_model()
    x, _ = _xy(rng, batch=2)
    state = create_train_state(model, tx, x[:1])
    # 3 completed epochs out of 5 preallocated; epoch 2's loss is EXACTLY 0.0
    train = np.array([0.5, 0.4, 0.0, 0.0, 0.0])
    val = np.array([0.6, 0.5, 0.0, 0.0, 0.0])
    save_checkpoint(tmp_path / "ck.msgpack", state, train, val, epochs_done=3)
    _, tr, va = load_checkpoint(tmp_path / "ck.msgpack", state)
    assert len(tr) == 3 and len(va) == 3  # trim_zeros would have said 2
    assert tr[2] == 0.0

    # back-compat: a pre-flywheel checkpoint (no epochs_done key) still
    # loads via the historical trim inference
    from flax import serialization

    from disco_tpu.io.atomic import write_bytes_atomic

    legacy = {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "step": state.step,
        "train_loss": train,
        "val_loss": val,
    }
    write_bytes_atomic(tmp_path / "old.msgpack", serialization.to_bytes(legacy))
    _, tr_old, _ = load_checkpoint(tmp_path / "old.msgpack", state)
    assert len(tr_old) == 2  # the old (buggy) inference, preserved for old files


# ------------------------------------------------------- lazy ChunkPrefetcher
def test_chunk_prefetcher_accepts_lazy_generators():
    """The training batch feed hands ChunkPrefetcher a GENERATOR whose
    next() does the numpy prep — it must be drained lazily on the loader
    thread, not list()-ed up front on the caller's."""
    from disco_tpu.enhance.pipeline import ChunkPrefetcher

    drained_on: list = []

    def gen():
        for i in range(4):
            drained_on.append(threading.current_thread().name)
            yield (i,)

    g = gen()
    pf = ChunkPrefetcher(g, lambda i: i * 10, depth=2)
    try:
        got = [item for item, _stall in pf]
    finally:
        pf.close()
    assert got == [0, 10, 20, 30]
    assert all(name == "disco-chunk-prefetch" for name in drained_on)


def test_trace_ids_ride_shard_records_into_train_batch_spans(tmp_path, rng):
    """disco-scope's flywheel leg: a traced delivered block's trace/span
    ids survive the shard roundtrip, and reading the shard into training
    windows records a ``train_batch`` span chaining under the tap hop —
    the client→train end of the causal chain."""
    from disco_tpu import obs
    from disco_tpu.obs import trace as obs_trace

    log = tmp_path / "fw.jsonl"
    with obs.recording(log):
        obs_trace.enable()
        try:
            tap = CorpusTap(tmp_path / "tap", records_per_shard=2)
            ctxs = {}
            for i in range(2):
                b = _block(rng, seq=i)
                ctx = obs_trace.root("client_block", seq=i, session="s1")
                ctx = obs_trace.span("deliver", ctx, session="s1", seq=i)
                ctxs[i] = ctx
                assert tap.offer("s1", i, b["Y"], b["mask_z"], b["mask_w"],
                                 b["yf"], trace=ctx)
            tap.close()
            (shard,) = list_shards(tmp_path / "tap")
            _meta, records = read_shard(shard)
            for i, rec in enumerate(records):
                assert rec["trace"]["trace"] == ctxs[i].trace
            ds = ShardDataset(tmp_path / "tap", win_len=4)
            n = sum(1 for _ in ds.batches(2, epoch=0))
            assert n >= 1
        finally:
            obs_trace.disable()
    events = obs.read_events(log)
    for i in range(2):
        path = obs_trace.verify_chain(
            events, ctxs[i].trace,
            require=("client_block", "deliver", "tap", "train_batch"))
        assert path[-1]["attrs"]["shard"] == shard.name
    # untraced offers stay untraced end to end (back-compat)
    tap2 = CorpusTap(tmp_path / "tap2", records_per_shard=1)
    b = _block(rng, seq=0)
    assert tap2.offer("s2", 0, b["Y"], b["mask_z"], b["mask_w"], b["yf"])
    tap2.close()
    (_m, (rec,)) = read_shard(list_shards(tmp_path / "tap2")[0])
    assert "trace" not in rec


def test_dropped_tap_offer_records_no_tap_span(tmp_path, rng):
    """Mint-then-commit: a block the full tap queue DROPS must not log a
    'tap' hop it never took — the chain may not claim a shard that does
    not exist."""
    from disco_tpu import obs
    from disco_tpu.obs import trace as obs_trace

    log = tmp_path / "drop.jsonl"
    with obs.recording(log):
        obs_trace.enable()
        try:
            tap = CorpusTap(tmp_path / "tap", max_queue_blocks=1,
                            records_per_shard=1, start=False)
            ctxs = []
            for i in range(3):
                b = _block(rng, seq=i)
                ctx = obs_trace.root("client_block", seq=i, session="s1")
                ctxs.append(ctx)
                ok = tap.offer("s1", i, b["Y"], b["mask_z"], b["mask_w"],
                               b["yf"], trace=ctx)
                assert ok == (i < 1)
            tap.close()
        finally:
            obs_trace.disable()
    events = obs.read_events(log)
    tap_spans = [e for e in events
                 if e["kind"] == "span" and e["stage"] == "tap"]
    assert len(tap_spans) == 1
    assert tap_spans[0]["attrs"]["trace"] == ctxs[0].trace
