"""End-to-end CLI tests: the argparse mains drive the same three-stage
filesystem pipeline as the reference's job arrays (generate → mix →
enhance / export-z), on a tiny synthetic corpus."""
import numpy as np
import pytest

from disco_tpu.cli import gen_disco, gen_meetit, get_z, lists, mix, tango
from disco_tpu.io import DatasetLayout, read_wav, write_wav

FS = 16000


@pytest.fixture(scope="module")
def speech_corpus(tmp_path_factory):
    """Flat LibriSpeech-style folder with speaker/chapter structure."""
    root = tmp_path_factory.mktemp("libri")
    rng = np.random.default_rng(0)
    files = []
    for spk in ("19", "26", "32"):
        d = root / "train-clean-100" / spk / "1"
        d.mkdir(parents=True)
        f = d / f"{spk}-1-0001.wav"
        t = np.arange(6 * FS) / FS
        env = (np.sin(2 * np.pi * 1.3 * t + float(spk)) > -0.3).astype(np.float64)
        write_wav(f, 0.3 * env * rng.standard_normal(len(t)), FS)
        files.append(f)
        # mirror into the other splits so train/test globs both find speech
        for split in ("train-clean-360", "test-clean"):
            d2 = root / split / spk / "1"
            d2.mkdir(parents=True)
            write_wav(d2 / f"{spk}-1-0001.wav", 0.3 * env * rng.standard_normal(len(t)), FS)
    return root


@pytest.fixture(scope="module")
def generated(tmp_path_factory, speech_corpus):
    """disco-gen then disco-mix over one RIR — module-scoped: CLI pipeline
    state shared by the dependent tests."""
    out = tmp_path_factory.mktemp("dataset")
    done = gen_disco.main([
        "--dset", "train", "--scenario", "random", "--rirs", "1", "1",
        "--dir_out", str(out), "--librispeech", str(speech_corpus),
        "--max_order", "6",
    ])
    assert done == [1]
    mix.main([
        "--rirs", "1", "1", "--scenario", "random", "--noise", "ssn",
        "--dir", str(out), "--snr", "0", "6",
    ])
    return out


def test_gen_and_mix_outputs(generated):
    lay = DatasetLayout(str(generated), "random", "train")
    assert (lay.base / "wav_original" / "dry" / "target" / "1_S-1.wav").exists()
    mix_wav, _ = read_wav(lay.wav_processed([0, 6], "mixture", 1, 1, noise="ssn"))
    assert len(mix_wav) > FS


def test_gen_idempotent(generated, speech_corpus):
    # second run must skip the existing RIR
    done = gen_disco.main([
        "--dset", "train", "--scenario", "random", "--rirs", "1", "1",
        "--dir_out", str(generated), "--librispeech", str(speech_corpus),
        "--max_order", "6",
    ])
    assert done == []


def test_get_z_cli(generated):
    n = get_z.main([
        "--rir", "1", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "oracle",
    ])
    assert n == 1
    lay = DatasetLayout(str(generated), "random", "train")
    z = np.load(lay.stft_z("oracle", [0, 6], "zs_hat", 1, 1, "ssn"))
    assert z.dtype == np.complex64 and z.ndim == 2
    # idempotent second run
    assert get_z.main([
        "--rir", "1", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "oracle",
    ]) == 0


def test_tango_cli(generated, tmp_path):
    results = tango.main([
        "--rir", "1", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "t1",
        "--out_root", str(tmp_path / "results"),
    ])
    assert results is not None and "sdr_cnv" in results
    assert (tmp_path / "results" / "OIM" / "results_tango_1_ssn.p").exists()


def test_tango_cli_fault_spec(generated, tmp_path):
    """--fault-spec injects the scenario end-to-end: degraded-mode output is
    still produced and finite, and the obs log carries the fault/degraded
    events (+ --fault-seed overrides the file's seed; bare --fault-seed is
    rejected)."""
    import pytest

    from disco_tpu import obs

    spec = tmp_path / "faults.yaml"
    spec.write_text("node_dropout: [1]\nnan_z: [2]\nseed: 4\n")
    log = tmp_path / "fault_run.jsonl"
    results = tango.main([
        "--rir", "1", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "tf",
        "--out_root", str(tmp_path / "results_fault"),
        "--fault-spec", str(spec), "--fault-seed", "7",
        "--obs-log", str(log),
    ])
    assert results is not None and np.isfinite(results["sdr_cnv"]).all()
    events = obs.read_events(log)
    faults = sorted(e["attrs"]["fault"] for e in events if e["kind"] == "fault")
    assert faults == ["nan_z", "node_dropout"]
    assert any(e["kind"] == "degraded" for e in events)
    with pytest.raises(SystemExit, match="--fault-seed needs --fault-spec"):
        tango.main([
            "--rir", "1", "--scenario", "random", "--noise", "ssn",
            "--dataset", str(generated), "--fault-seed", "7",
        ])


def test_lists_cli(generated, tmp_path):
    out = lists.main([
        "--scene", "random", "--noise", "ssn", "--n_files", "2",
        "--path_data", str(generated), "--out", str(tmp_path / "lists"),
    ])
    assert len(out) == 12  # 4 refs + 4 z + 4 masks
    assert (tmp_path / "lists" / "list_0.txt").exists()


def test_gen_meetit_cli(tmp_path, speech_corpus):
    out = tmp_path / "meetit"
    done = gen_meetit.main([
        "--dset", "train", "--rirs", "3", "1", "--n_src", "2",
        "--dir_out", str(out), "--librispeech", str(speech_corpus),
        "--max_order", "4", "--duration", "3", "5",
    ])
    assert done == [3]
    lay = DatasetLayout(str(out), "meetit", "train")
    assert (lay.base / "wav" / "clean" / "dry" / "3_S-1.wav").exists()
    assert (lay.base / "mask" / "3_S-2_Ch-8.npy").exists()


def test_train_cli_single_channel(generated, tmp_path):
    from disco_tpu.cli import train

    run_name = train.main([
        "--scene", "random", "--noise", "ssn", "--n_files", "2",
        "--path_data", str(generated), "--save_path", str(tmp_path / "models"),
        "--n_epochs", "1", "--batch_size", "16", "--single_channel",
    ])
    assert isinstance(run_name, str) and len(run_name) >= 4
    assert any((tmp_path / "models").iterdir())


@pytest.mark.slow
def test_full_workflow_with_trained_models(generated, tmp_path):
    """The complete reference workflow through the CLIs: z export → train a
    multichannel CRNN on the z-augmented corpus → tango with the trained
    checkpoints (the loop_tango.sh flow, reference exp/ex1)."""
    from disco_tpu.cli import train

    # z exports (idempotent if test_get_z_cli already ran)
    get_z.main([
        "--rir", "1", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "oracle",
    ])

    run_name = train.main([
        "--scene", "random", "--noise", "ssn", "--n_files", "2",
        "--path_data", str(generated), "--save_path", str(tmp_path / "models"),
        "--n_epochs", "1", "--batch_size", "16", "--zsigs", "zs_hat",
    ])
    ckpt = tmp_path / "models" / f"{run_name}_model.msgpack"
    assert ckpt.exists()

    sc_name = train.main([
        "--scene", "random", "--noise", "ssn", "--n_files", "2",
        "--path_data", str(generated), "--save_path", str(tmp_path / "models"),
        "--n_epochs", "1", "--batch_size", "16", "--single_channel",
    ])
    sc_ckpt = tmp_path / "models" / f"{sc_name}_model.msgpack"

    results = tango.main([
        "--rir", "1", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "trained",
        "--out_root", str(tmp_path / "results"),
        "--mods", str(sc_ckpt), str(ckpt),
    ])
    assert results is not None and np.all(np.isfinite(results["sdr_cnv"]))


def test_tango_cli_batched_mode(generated, tmp_path):
    results = tango.main([
        "--rirs", "1", "2", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "batched",
        "--out_root", str(tmp_path / "res_batched"),
    ])
    assert set(results) == {1}  # RIR 2 has no corpus files
    assert (tmp_path / "res_batched" / "OIM" / "results_tango_1_ssn.p").exists()


def test_get_z_cli_with_crnn_model(generated, tmp_path):
    """z export with a trained single-channel CRNN mask model (--mod_sc):
    the batched device-resident mask path feeding export_z."""
    from disco_tpu.cli import train

    sc_name = train.main([
        "--scene", "random", "--noise", "ssn", "--n_files", "2",
        "--path_data", str(generated), "--save_path", str(tmp_path / "m"),
        "--n_epochs", "1", "--batch_size", "16", "--single_channel",
    ])
    n = get_z.main([
        "--rir", "1", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "crnn_z",
        "--mod_sc", str(tmp_path / "m" / f"{sc_name}_model.msgpack"),
    ])
    assert n == 1
    lay = DatasetLayout(str(generated), "random", "train")
    z = np.load(lay.stft_z("crnn_z", [0, 6], "zs_hat", 1, 1, "ssn"))
    assert z.dtype == np.complex64 and z.ndim == 2 and np.isfinite(z).all()


def test_tango_cli_solver_precedence(tmp_path):
    """--solver resolution: explicit flag > YAML enhance.solver (--config) >
    None (defer to the driver's mode-aware default: 'power' offline /
    'eigh' streaming — round-4 default flip from the solver_ab artifact)."""
    import dataclasses

    from disco_tpu.config import DiscoConfig, EnhanceConfig, save_config

    cfg = DiscoConfig(enhance=dataclasses.replace(EnhanceConfig(), solver="power:8"))
    path = save_config(cfg, tmp_path / "cfg.yaml")

    def resolved(argv):
        return tango.resolve_solver(tango.build_parser().parse_args(argv + ["--rir", "1"]))

    assert resolved([]) is None  # driver resolves per mode (offline='power')
    assert resolved(["--config", str(path)]) == "power:8"
    assert resolved(["--config", str(path), "--solver", "jacobi"]) == "jacobi"

    # A YAML that OMITS enhance.solver must defer to the driver (None), not
    # leak the dataclass default 'power' into streaming runs (round-4
    # advisor finding: cli/tango.py resolve_solver).
    no_solver = tmp_path / "nosolver.yaml"
    no_solver.write_text("enhance:\n  mu: 1.5\n")
    assert resolved(["--config", str(no_solver)]) is None
    empty = tmp_path / "empty.yaml"
    empty.write_text("")
    assert resolved(["--config", str(empty)]) is None
    # 'enhance:' with no body parses as a null section — still "no solver".
    null_section = tmp_path / "nullsec.yaml"
    null_section.write_text("enhance:\n")
    assert resolved(["--config", str(null_section)]) is None
    # present-but-non-string solver: clean SystemExit, not an AttributeError
    import pytest

    bad_type = tmp_path / "badtype.yaml"
    bad_type.write_text("enhance:\n  solver: null\n")
    with pytest.raises(SystemExit, match="enhance.solver"):
        resolved(["--config", str(bad_type)])


def test_tango_cli_non_mapping_yaml_shapes_are_clean_errors(tmp_path):
    """Round-5 advisor finding (cli/tango.py): a YAML list/scalar top level
    crashed resolve_solver with a raw AttributeError on raw.items(), and a
    scalar `enhance:` section surfaced an uncaught ValueError from deep in
    config_from_dict.  Both must be SystemExit naming the file path."""
    import pytest

    def resolved(path):
        args = tango.build_parser().parse_args(["--rir", "1", "--config", str(path)])
        return tango.resolve_solver(args)

    top_list = tmp_path / "list.yaml"
    top_list.write_text("- enhance\n- solver\n")
    with pytest.raises(SystemExit, match=r"list\.yaml.*mapping of config sections"):
        resolved(top_list)

    top_scalar = tmp_path / "scalar.yaml"
    top_scalar.write_text("eigh\n")
    with pytest.raises(SystemExit, match=r"scalar\.yaml.*mapping of config sections"):
        resolved(top_scalar)

    scalar_section = tmp_path / "scalarsec.yaml"
    scalar_section.write_text("enhance: eigh\n")
    with pytest.raises(SystemExit, match=r"scalarsec\.yaml.*'enhance' must be a mapping"):
        resolved(scalar_section)

    list_section = tmp_path / "listsec.yaml"
    list_section.write_text("enhance:\n  - solver\n")
    with pytest.raises(SystemExit, match=r"listsec\.yaml.*'enhance' must be a mapping"):
        resolved(list_section)


def test_tango_cli_obs_log_emits_manifest_and_stage_events(generated, tmp_path):
    """--obs-log: a driver run over the fixture corpus writes a sideband
    JSONL with the run manifest first, >= 4 distinct pipeline stages, fence
    accounting from the sentinel readbacks, and a clip event — and
    `obs report` renders it (the observability-PR acceptance criterion)."""
    from disco_tpu import obs as obs_pkg
    from disco_tpu.cli import obs as obs_cli

    log = tmp_path / "events.jsonl"
    results = tango.main([
        "--rir", "1", "--scenario", "random", "--noise", "ssn",
        "--dataset", str(generated), "--sav_dir", "t_obs",
        "--out_root", str(tmp_path / "results"),
        "--obs-log", str(log),
    ])
    assert results is not None
    assert not obs_pkg.enabled()  # CLI released the recorder on exit
    events = obs_pkg.read_events(log)  # schema-validating read
    assert events[0]["kind"] == "manifest"
    assert events[0]["attrs"]["config"]["rir"] == 1
    stages = {e["stage"] for e in events if e["kind"] == "stage_end"}
    assert {"load_input", "stft", "masks", "mwf", "istft", "score_persist"} <= stages
    assert len(stages) >= 4
    clip_events = [e for e in events if e["kind"] == "clip"]
    assert len(clip_events) == 1 and clip_events[0]["attrs"]["rir"] == 1
    # sentinel readbacks (post-STFT/mask/MWF/ISTFT) each count as one fence
    counters = [e for e in events if e["kind"] == "counters"][-1]["attrs"]["counters"]
    assert counters["sentinel_checks"] >= 4
    assert counters["fences"] >= 4
    # counters are process-lifetime (other tests may have tripped sentinels
    # in this process); THIS run's per-event story must be trip-free
    assert [e for e in events if e["kind"] == "sentinel"] == []

    summary = obs_cli.main(["report", str(log)])
    assert summary["n_fences"] >= 4
    assert summary["clips"] == 1
    for name in ("stft", "masks", "mwf", "istft"):
        assert summary["stages"][name]["calls"] >= 1


def test_tango_cli_bad_yaml_solver_is_clean_error(tmp_path):
    import dataclasses

    import pytest

    from disco_tpu.config import DiscoConfig, EnhanceConfig, save_config

    cfg = DiscoConfig(enhance=dataclasses.replace(EnhanceConfig(), solver="nope"))
    path = save_config(cfg, tmp_path / "bad.yaml")
    args = tango.build_parser().parse_args(["--rir", "1", "--config", str(path)])
    with pytest.raises(SystemExit, match="enhance.solver"):
        tango.resolve_solver(args)
