"""Tests for the streaming (frame-recursive) TANGO mode — the online
covariance path of reference internal_formulas.py:84-103, wired end-to-end."""
import numpy as np
import pytest

from disco_tpu.core.dsp import istft, stft
from disco_tpu.core.metrics import si_sdr
from disco_tpu.enhance import oracle_masks
from disco_tpu.enhance.streaming import (
    streaming_step1,
    streaming_tango,
    streaming_tango_scan,
)

FS = 16000


@pytest.fixture(scope="module")
def scene():
    rng = np.random.default_rng(5)
    K, C, L = 4, 2, 4 * FS
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)]) for _ in range(K)]
    )
    n = 0.8 * rng.standard_normal((K, C, L))
    return s + n, s, n, L


def test_streaming_step1_converges_to_offline(scene):
    """On a stationary scene the smoothed covariances converge; the late
    filter output must approach the offline rank-1 GEVD z stream."""
    from disco_tpu.enhance.tango import tango_step1

    y, s, n, L = scene
    Y, S, N = stft(y[0]), stft(s[0]), stft(n[0])
    mask = np.asarray(oracle_masks(stft(s[:1]), stft(n[:1]), "irm1"))[0]

    out_s = streaming_step1(Y, mask, lambda_cor=0.98, update_every=4)
    out_o = tango_step1(Y, S, N, mask)
    # compare the tail half (after convergence), SNR-style
    zs, zo = np.asarray(out_s["z_y"]), np.asarray(out_o["z_y"])
    T = zs.shape[-1]
    tail = slice(T // 2, T)
    err = np.linalg.norm(zs[:, tail] - zo[:, tail]) / np.linalg.norm(zo[:, tail])
    assert err < 0.35, err  # recursive estimate ~ offline, not bit-equal


def test_streaming_tango_enhances(scene):
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out = streaming_tango(Y, masks, masks)
    yf = np.asarray(out["yf"])
    assert yf.shape == Y.shape[:1] + Y.shape[2:]
    for k in range(Y.shape[0]):
        enh = np.asarray(istft(yf[k], length=L))
        # skip the first second: covariances still warming up
        i = float(si_sdr(s[k, 0, FS:], y[k, 0, FS:]))
        o = float(si_sdr(s[k, 0, FS:], enh[FS:]))
        assert o > i + 3.0, (k, i, o)


def test_streaming_power_solver(scene):
    """The power solver in STREAMING mode: exponentially-smoothed warm-up
    covariances have weak eigengaps, so 12 iterations under-converge (~1 dB
    below eigh — why 'eigh' stays the streaming default); 'power:N' buys the
    gap back (documented contract: still enhances at 12, within 0.5 dB of
    eigh at 96).  Offline frame-mean covariances converge at 12 iterations
    (test_tango.test_default_solver_sdr_parity, 0.1 dB)."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out_e = streaming_tango(Y, masks, masks)
    out_p = streaming_tango(Y, masks, masks, solver="power")
    out_p96 = streaming_tango(Y, masks, masks, solver="power:96")
    for k in range(Y.shape[0]):
        sdr_in = float(si_sdr(s[k, 0, FS:], y[k, 0, FS:]))
        sdr_e = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_e["yf"])[k], length=L))[FS:]))
        sdr_p = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_p["yf"])[k], length=L))[FS:]))
        sdr_p96 = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_p96["yf"])[k], length=L))[FS:]))
        assert sdr_p > sdr_in + 2.0, (k, sdr_in, sdr_p)  # ~1 dB under eigh's +3
        assert abs(sdr_e - sdr_p96) < 0.5, (k, sdr_e, sdr_p96)


@pytest.mark.parametrize("policy", ["distant", "none"])
def test_streaming_policies_enhance(scene, policy):
    """Streaming v2 (VERDICT round-1 item 6): the 'distant' and 'none'
    mask-for-z policies run online and still enhance."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out = streaming_tango(Y, masks, masks, policy=policy)
    yf = np.asarray(out["yf"])
    for k in range(Y.shape[0]):
        enh = np.asarray(istft(yf[k], length=L))
        i = float(si_sdr(s[k, 0, FS:], y[k, 0, FS:]))
        o = float(si_sdr(s[k, 0, FS:], enh[FS:]))
        assert o > i + 1.5, (policy, k, i, o)


def test_streaming_policies_differ(scene):
    """The three policies shape the step-2 covariances differently — their
    outputs must not be identical (guards against the policy arg being
    silently ignored)."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    outs = {
        p: np.asarray(streaming_tango(Y, masks, masks, policy=p)["yf"])
        for p in ("local", "distant", "none")
    }
    assert not np.allclose(outs["local"], outs["none"])
    assert not np.allclose(outs["distant"], outs["none"])


def test_streaming_unknown_policy_raises(scene):
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    with pytest.raises(ValueError, match="offline-only"):
        streaming_tango(Y, masks, masks, policy="use_oracle_refs")


def test_streaming_latency_milestone():
    from disco_tpu.milestones import streaming_latency

    out = streaming_latency(dur_s=1.0, K=2, C=2, iters=1)
    assert out["config"] == "streaming_latency"
    for p in ("local", "distant", "none"):
        assert out["policies"][p]["per_frame_ms"] > 0
        assert np.isfinite(out["policies"][p]["rtf"])


def test_streaming_state_is_finite(scene):
    y, s, n, _ = scene
    Y = stft(y[0])
    mask = np.asarray(oracle_masks(stft(s[:1]), stft(n[:1]), "irm1"))[0]
    out = streaming_step1(Y, mask)
    for key in ("Rss", "Rnn", "w", "z_y", "zn"):
        assert np.isfinite(np.asarray(out[key])).all(), key


def test_streaming_diagnostics_single_filter(scene):
    """with_diagnostics: sf/nf come from the SAME per-block filters as yf —
    linearity check: filter(S) + filter(N) == filter(Y) when Y = S + N."""
    from disco_tpu.enhance.streaming import streaming_tango

    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out = streaming_tango(Y, masks, masks, S=S, N=N, with_diagnostics=True)
    for key in ("yf", "sf", "nf", "z_s", "z_n", "zn"):
        assert key in out
    lhs = np.asarray(out["sf"] + out["nf"])
    rhs = np.asarray(out["yf"])
    err = np.max(np.abs(lhs - rhs)) / (np.max(np.abs(rhs)) + 1e-30)
    assert err < 1e-3, err


def test_streaming_chunked_continuation_exact(scene):
    """True online use: process a stream in two chunks carrying the
    (Rss, Rnn, w) state — identical to one-shot processing when the chunk
    boundary falls on a filter-refresh block boundary."""
    y, s, n, L = scene
    Y = stft(y[0])
    mask = np.asarray(oracle_masks(stft(s[:1]), stft(n[:1]), "irm1"))[0]
    u = 4
    T = Y.shape[-1]
    T1 = (T // 2 // u) * u  # chunk boundary on a block boundary

    full = streaming_step1(Y, mask, update_every=u)
    c1 = streaming_step1(Y[..., :T1], mask[..., :T1], update_every=u)
    c2 = streaming_step1(
        Y[..., T1:], mask[..., T1:], update_every=u,
        state=(c1["Rss"], c1["Rnn"], c1["w"]),
    )
    chained = np.concatenate([np.asarray(c1["z_y"]), np.asarray(c2["z_y"])], axis=-1)
    np.testing.assert_allclose(chained, np.asarray(full["z_y"]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(c2["Rss"]), np.asarray(full["Rss"]), atol=1e-4)


def test_streaming_tango_chunked_continuation(scene):
    """Two-step online deployment across chunks: carrying the full state
    reproduces one-shot streaming_tango on refresh-aligned boundaries."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = np.asarray(oracle_masks(S, N, "irm1"))
    u = 4
    T = Y.shape[-1]
    T1 = (T // 2 // u) * u

    full = streaming_tango(Y, masks, masks, update_every=u)
    c1 = streaming_tango(Y[..., :T1], masks[..., :T1], masks[..., :T1], update_every=u)
    c2 = streaming_tango(Y[..., T1:], masks[..., T1:], masks[..., T1:],
                         update_every=u, state=c1["state"])
    chained = np.concatenate([np.asarray(c1["yf"]), np.asarray(c2["yf"])], axis=-1)
    np.testing.assert_allclose(chained, np.asarray(full["yf"]), atol=1e-4)


# -- scanned super-ticks (device-resident multi-block driver) ----------------
def _blocked_reference(Y, m, block, state, plan=None):
    """Per-block serve-style loop — the one shared oracle from the
    stream-check gate, so the per-block calling convention these parity
    tests pin cannot drift from the one ``make stream-check`` pins."""
    from disco_tpu.enhance.stream_check import per_block_reference

    return per_block_reference(Y, m, block=block, update_every=4,
                               state=state, plan=plan)


@pytest.fixture(scope="module")
def scan_scene(scene):
    y, s, n, L = scene
    Y = stft(y)
    masks = np.asarray(oracle_masks(stft(s), stft(n), "irm1"))
    return np.asarray(Y), masks


def test_streaming_scan_bit_identical_to_per_block(scan_scene):
    """The tentpole gate: N blocks through one scanned dispatch are
    bit-identical to N per-block dispatches — output AND continuation
    state."""
    import jax

    from disco_tpu.enhance.streaming import initial_stream_state, streaming_tango_scan

    Y, m = scan_scene
    K, C, F, T = Y.shape
    u, N = 4, 4
    block = 2 * u
    window = N * block
    nw = T // window

    ref, ref_state = _blocked_reference(
        Y[..., :nw * window], m[..., :nw * window], block,
        initial_stream_state(K, C, F, update_every=u),
    )
    st = initial_stream_state(K, C, F, update_every=u)
    outs = []
    for w in range(nw):
        lo, hi = w * window, (w + 1) * window
        o = streaming_tango_scan(
            Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi], update_every=u,
            state=st, z_avail=np.ones((K, window // u), np.float32),
            blocks_per_dispatch=N,
        )
        st = o["state"]
        outs.append(np.asarray(o["yf"]))
    got = np.concatenate(outs, axis=-1)
    np.testing.assert_array_equal(got, ref)
    for a, b in zip(jax.tree_util.tree_leaves(st),
                    jax.tree_util.tree_leaves(ref_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streaming_scan_holds_bit_identical(scan_scene):
    """z_avail hold semantics inside and across super-ticks: losses
    bridged identically whether the lost block falls mid-window or at a
    super-tick edge (the hold carries ride the scan carry)."""
    from disco_tpu.enhance.streaming import initial_stream_state, streaming_tango_scan

    Y, m = scan_scene
    K, C, F, T = Y.shape
    u, N = 4, 4
    block = 2 * u
    window = N * block
    nw = T // window
    per_block = block // u
    B = nw * window // u
    plan = np.ones((K, B), np.float32)
    plan[1, 3:12] = 0    # loss spanning a super-tick edge (window = 8 cols)
    plan[3, 0:2] = 0     # leading loss -> zn fallback
    plan[2, 7:8] = 0     # single lost refresh block mid-window

    ref, _ = _blocked_reference(
        Y[..., :nw * window], m[..., :nw * window], block,
        initial_stream_state(K, C, F, update_every=u), plan=plan,
    )
    st = initial_stream_state(K, C, F, update_every=u)
    outs = []
    cols = window // u
    for w in range(nw):
        lo, hi = w * window, (w + 1) * window
        o = streaming_tango_scan(
            Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi], update_every=u,
            state=st, z_avail=plan[:, w * cols:(w + 1) * cols],
            blocks_per_dispatch=N,
        )
        st = o["state"]
        outs.append(np.asarray(o["yf"]))
    np.testing.assert_array_equal(np.concatenate(outs, axis=-1), ref)


def test_streaming_scan_tail_falls_back_to_per_block(scan_scene):
    """A stream that is not a multiple of N blocks: scanned head + per-block
    tail == per-block all the way (the scheduler/bench fallback shape)."""
    from disco_tpu.enhance.streaming import (
        initial_stream_state,
        streaming_tango,
        streaming_tango_scan,
    )

    Y, m = scan_scene
    K, C, F, T = Y.shape
    u, N = 4, 4
    block = 2 * u
    window = N * block
    n_blocks = T // block
    assert n_blocks % N, "fixture must leave a partial final window"
    nw = n_blocks // N

    ref, _ = _blocked_reference(Y[..., :n_blocks * block], m[..., :n_blocks * block],
                                block, initial_stream_state(K, C, F, update_every=u))
    st = initial_stream_state(K, C, F, update_every=u)
    outs = []
    for w in range(nw):
        lo, hi = w * window, (w + 1) * window
        o = streaming_tango_scan(
            Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi], update_every=u,
            state=st, z_avail=np.ones((K, window // u), np.float32),
            blocks_per_dispatch=N,
        )
        st = o["state"]
        outs.append(np.asarray(o["yf"]))
    for i in range(nw * N, n_blocks):
        lo, hi = i * block, (i + 1) * block
        o = streaming_tango(Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi],
                            update_every=u, state=st,
                            z_avail=np.ones((K, block // u), np.float32))
        st = o["state"]
        outs.append(np.asarray(o["yf"]))
    np.testing.assert_array_equal(np.concatenate(outs, axis=-1), ref)


def test_streaming_scan_default_state_matches_default_call(scan_scene):
    """state=None in the scanned driver materializes the documented warm
    start — one scanned window equals the one-shot default streaming_tango
    over the same frames."""
    from disco_tpu.enhance.streaming import streaming_tango, streaming_tango_scan

    Y, m = scan_scene
    T = Y.shape[-1]
    u, N = 4, 4
    window = N * 2 * u
    ref = np.asarray(streaming_tango(Y[..., :window], m[..., :window],
                                     m[..., :window], update_every=u)["yf"])
    got = np.asarray(streaming_tango_scan(Y[..., :window], m[..., :window],
                                          m[..., :window], update_every=u,
                                          blocks_per_dispatch=N)["yf"])
    np.testing.assert_array_equal(got, ref)


def test_streaming_scan_validates_window(scan_scene):
    from disco_tpu.enhance.streaming import streaming_tango_scan

    Y, m = scan_scene
    u = 4
    with pytest.raises(ValueError, match="does not split"):
        streaming_tango_scan(Y[..., :3 * u], m[..., :3 * u], m[..., :3 * u],
                             update_every=u, blocks_per_dispatch=5)
    with pytest.raises(ValueError, match="multiple of update_every"):
        streaming_tango_scan(Y[..., :2 * (u + 1)], m[..., :2 * (u + 1)],
                             m[..., :2 * (u + 1)], update_every=u,
                             blocks_per_dispatch=2)
    with pytest.raises(ValueError, match=">= 1"):
        streaming_tango_scan(Y[..., :u], m[..., :u], m[..., :u],
                             update_every=u, blocks_per_dispatch=0)


@pytest.mark.slow
def test_streaming_jacobi_solver_matches_eigh(scene):
    """Jacobi is a FULL eigendecomposition, so unlike power iteration it has
    no weak-eigengap handicap on the smoothed warm-up covariances: streaming
    with 'jacobi' must track the eigh default tightly — the cheap-solver
    option for streaming that 'power' could not be (round-2 negative
    result)."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out_e = streaming_tango(Y, masks, masks)
    out_j = streaming_tango(Y, masks, masks, solver="jacobi")
    for k in range(Y.shape[0]):
        sdr_e = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_e["yf"])[k], length=L))[FS:]))
        sdr_j = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_j["yf"])[k], length=L))[FS:]))
        assert abs(sdr_e - sdr_j) < 0.2, (k, sdr_e, sdr_j)


def test_bf16_lane_scan_vs_per_block_bit_exact():
    """The bit-exactness contract holds PER LANE: under precision='bf16' the
    scanned super-tick still shares _streaming_tango_body with the per-block
    path, so chunked per-block continuation must reproduce the scan output
    bit-for-bit (same construction as the f32 gate — the lane changes the
    kernels, never the program-sharing)."""
    rng = np.random.default_rng(23)
    K_, C_, L_ = 3, 2, 12288
    y = rng.standard_normal((K_, C_, L_)).astype("float32")
    Y = stft(y)
    F, T = Y.shape[-2:]
    u, n_disp = 4, 2
    Tc = (T // (n_disp * u)) * u * n_disp
    Yw = Y[..., :Tc]
    m = rng.uniform(0.1, 0.9, (K_, F, Tc)).astype("float32")
    scan = streaming_tango_scan(Yw, m, m, update_every=u,
                                blocks_per_dispatch=n_disp, precision="bf16")
    half = Tc // n_disp
    o1 = streaming_tango(Yw[..., :half], m[..., :half], m[..., :half],
                         update_every=u, precision="bf16")
    o2 = streaming_tango(Yw[..., half:], m[..., half:], m[..., half:],
                         update_every=u, state=o1["state"], precision="bf16")
    per_block = np.concatenate([np.asarray(o1["yf"]), np.asarray(o2["yf"])], axis=-1)
    np.testing.assert_array_equal(per_block, np.asarray(scan["yf"]))


def test_streaming_f32_default_ignores_precision_spelling():
    """Canonicalization guard: passing precision='F32 ' (non-canonical
    spelling) reaches the static seam as the one canonical token — same
    program, bit-identical output, no duplicate trace (the string-typed
    mu=1 trap)."""
    from disco_tpu.obs.accounting import recompile_count

    rng = np.random.default_rng(24)
    y = rng.standard_normal((2, 2, 8192)).astype("float32")
    Y = stft(y)
    m = rng.uniform(0.1, 0.9, (2,) + Y.shape[-2:]).astype("float32")
    a = streaming_tango(Y, m, m)
    before = recompile_count("streaming_tango")
    b = streaming_tango(Y, m, m, precision=" F32 ")
    assert recompile_count("streaming_tango") == before  # no fresh program
    np.testing.assert_array_equal(np.asarray(a["yf"]), np.asarray(b["yf"]))
