"""Tests for the streaming (frame-recursive) TANGO mode — the online
covariance path of reference internal_formulas.py:84-103, wired end-to-end."""
import numpy as np
import pytest

from disco_tpu.core.dsp import istft, stft
from disco_tpu.core.metrics import si_sdr
from disco_tpu.enhance import oracle_masks
from disco_tpu.enhance.streaming import streaming_step1, streaming_tango

FS = 16000


@pytest.fixture(scope="module")
def scene():
    rng = np.random.default_rng(5)
    K, C, L = 4, 2, 4 * FS
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)]) for _ in range(K)]
    )
    n = 0.8 * rng.standard_normal((K, C, L))
    return s + n, s, n, L


def test_streaming_step1_converges_to_offline(scene):
    """On a stationary scene the smoothed covariances converge; the late
    filter output must approach the offline rank-1 GEVD z stream."""
    from disco_tpu.enhance.tango import tango_step1

    y, s, n, L = scene
    Y, S, N = stft(y[0]), stft(s[0]), stft(n[0])
    mask = np.asarray(oracle_masks(stft(s[:1]), stft(n[:1]), "irm1"))[0]

    out_s = streaming_step1(Y, mask, lambda_cor=0.98, update_every=4)
    out_o = tango_step1(Y, S, N, mask)
    # compare the tail half (after convergence), SNR-style
    zs, zo = np.asarray(out_s["z_y"]), np.asarray(out_o["z_y"])
    T = zs.shape[-1]
    tail = slice(T // 2, T)
    err = np.linalg.norm(zs[:, tail] - zo[:, tail]) / np.linalg.norm(zo[:, tail])
    assert err < 0.35, err  # recursive estimate ~ offline, not bit-equal


def test_streaming_tango_enhances(scene):
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out = streaming_tango(Y, masks, masks)
    yf = np.asarray(out["yf"])
    assert yf.shape == Y.shape[:1] + Y.shape[2:]
    for k in range(Y.shape[0]):
        enh = np.asarray(istft(yf[k], length=L))
        # skip the first second: covariances still warming up
        i = float(si_sdr(s[k, 0, FS:], y[k, 0, FS:]))
        o = float(si_sdr(s[k, 0, FS:], enh[FS:]))
        assert o > i + 3.0, (k, i, o)


def test_streaming_power_solver(scene):
    """The power solver in STREAMING mode: exponentially-smoothed warm-up
    covariances have weak eigengaps, so 12 iterations under-converge (~1 dB
    below eigh — why 'eigh' stays the streaming default); 'power:N' buys the
    gap back (documented contract: still enhances at 12, within 0.5 dB of
    eigh at 96).  Offline frame-mean covariances converge at 12 iterations
    (test_tango.test_default_solver_sdr_parity, 0.1 dB)."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out_e = streaming_tango(Y, masks, masks)
    out_p = streaming_tango(Y, masks, masks, solver="power")
    out_p96 = streaming_tango(Y, masks, masks, solver="power:96")
    for k in range(Y.shape[0]):
        sdr_in = float(si_sdr(s[k, 0, FS:], y[k, 0, FS:]))
        sdr_e = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_e["yf"])[k], length=L))[FS:]))
        sdr_p = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_p["yf"])[k], length=L))[FS:]))
        sdr_p96 = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_p96["yf"])[k], length=L))[FS:]))
        assert sdr_p > sdr_in + 2.0, (k, sdr_in, sdr_p)  # ~1 dB under eigh's +3
        assert abs(sdr_e - sdr_p96) < 0.5, (k, sdr_e, sdr_p96)


@pytest.mark.parametrize("policy", ["distant", "none"])
def test_streaming_policies_enhance(scene, policy):
    """Streaming v2 (VERDICT round-1 item 6): the 'distant' and 'none'
    mask-for-z policies run online and still enhance."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out = streaming_tango(Y, masks, masks, policy=policy)
    yf = np.asarray(out["yf"])
    for k in range(Y.shape[0]):
        enh = np.asarray(istft(yf[k], length=L))
        i = float(si_sdr(s[k, 0, FS:], y[k, 0, FS:]))
        o = float(si_sdr(s[k, 0, FS:], enh[FS:]))
        assert o > i + 1.5, (policy, k, i, o)


def test_streaming_policies_differ(scene):
    """The three policies shape the step-2 covariances differently — their
    outputs must not be identical (guards against the policy arg being
    silently ignored)."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    outs = {
        p: np.asarray(streaming_tango(Y, masks, masks, policy=p)["yf"])
        for p in ("local", "distant", "none")
    }
    assert not np.allclose(outs["local"], outs["none"])
    assert not np.allclose(outs["distant"], outs["none"])


def test_streaming_unknown_policy_raises(scene):
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    with pytest.raises(ValueError, match="offline-only"):
        streaming_tango(Y, masks, masks, policy="use_oracle_refs")


def test_streaming_latency_milestone():
    from disco_tpu.milestones import streaming_latency

    out = streaming_latency(dur_s=1.0, K=2, C=2, iters=1)
    assert out["config"] == "streaming_latency"
    for p in ("local", "distant", "none"):
        assert out["policies"][p]["per_frame_ms"] > 0
        assert np.isfinite(out["policies"][p]["rtf"])


def test_streaming_state_is_finite(scene):
    y, s, n, _ = scene
    Y = stft(y[0])
    mask = np.asarray(oracle_masks(stft(s[:1]), stft(n[:1]), "irm1"))[0]
    out = streaming_step1(Y, mask)
    for key in ("Rss", "Rnn", "w", "z_y", "zn"):
        assert np.isfinite(np.asarray(out[key])).all(), key


def test_streaming_diagnostics_single_filter(scene):
    """with_diagnostics: sf/nf come from the SAME per-block filters as yf —
    linearity check: filter(S) + filter(N) == filter(Y) when Y = S + N."""
    from disco_tpu.enhance.streaming import streaming_tango

    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out = streaming_tango(Y, masks, masks, S=S, N=N, with_diagnostics=True)
    for key in ("yf", "sf", "nf", "z_s", "z_n", "zn"):
        assert key in out
    lhs = np.asarray(out["sf"] + out["nf"])
    rhs = np.asarray(out["yf"])
    err = np.max(np.abs(lhs - rhs)) / (np.max(np.abs(rhs)) + 1e-30)
    assert err < 1e-3, err


def test_streaming_chunked_continuation_exact(scene):
    """True online use: process a stream in two chunks carrying the
    (Rss, Rnn, w) state — identical to one-shot processing when the chunk
    boundary falls on a filter-refresh block boundary."""
    y, s, n, L = scene
    Y = stft(y[0])
    mask = np.asarray(oracle_masks(stft(s[:1]), stft(n[:1]), "irm1"))[0]
    u = 4
    T = Y.shape[-1]
    T1 = (T // 2 // u) * u  # chunk boundary on a block boundary

    full = streaming_step1(Y, mask, update_every=u)
    c1 = streaming_step1(Y[..., :T1], mask[..., :T1], update_every=u)
    c2 = streaming_step1(
        Y[..., T1:], mask[..., T1:], update_every=u,
        state=(c1["Rss"], c1["Rnn"], c1["w"]),
    )
    chained = np.concatenate([np.asarray(c1["z_y"]), np.asarray(c2["z_y"])], axis=-1)
    np.testing.assert_allclose(chained, np.asarray(full["z_y"]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(c2["Rss"]), np.asarray(full["Rss"]), atol=1e-4)


def test_streaming_tango_chunked_continuation(scene):
    """Two-step online deployment across chunks: carrying the full state
    reproduces one-shot streaming_tango on refresh-aligned boundaries."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = np.asarray(oracle_masks(S, N, "irm1"))
    u = 4
    T = Y.shape[-1]
    T1 = (T // 2 // u) * u

    full = streaming_tango(Y, masks, masks, update_every=u)
    c1 = streaming_tango(Y[..., :T1], masks[..., :T1], masks[..., :T1], update_every=u)
    c2 = streaming_tango(Y[..., T1:], masks[..., T1:], masks[..., T1:],
                         update_every=u, state=c1["state"])
    chained = np.concatenate([np.asarray(c1["yf"]), np.asarray(c2["yf"])], axis=-1)
    np.testing.assert_allclose(chained, np.asarray(full["yf"]), atol=1e-4)


@pytest.mark.slow
def test_streaming_jacobi_solver_matches_eigh(scene):
    """Jacobi is a FULL eigendecomposition, so unlike power iteration it has
    no weak-eigengap handicap on the smoothed warm-up covariances: streaming
    with 'jacobi' must track the eigh default tightly — the cheap-solver
    option for streaming that 'power' could not be (round-2 negative
    result)."""
    y, s, n, L = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    out_e = streaming_tango(Y, masks, masks)
    out_j = streaming_tango(Y, masks, masks, solver="jacobi")
    for k in range(Y.shape[0]):
        sdr_e = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_e["yf"])[k], length=L))[FS:]))
        sdr_j = float(si_sdr(s[k, 0, FS:], np.asarray(istft(np.asarray(out_j["yf"])[k], length=L))[FS:]))
        assert abs(sdr_e - sdr_j) < 0.2, (k, sdr_e, sdr_j)
