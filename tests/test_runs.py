"""Crash-safe runs layer tests: atomic artifact I/O + integrity probes
(disco_tpu.io.atomic), the run ledger with verified resume
(disco_tpu.runs.ledger), graceful interruption (disco_tpu.runs.interrupt),
deterministic chaos injection (disco_tpu.runs.chaos), the preflight health
probe (utils.resilience), and the interrupt-and-resume integration of the
corpus driver and the training loop (slow-marked; `make chaos-check` runs
the full byte-identical-tree gate)."""
import os
import pickle
import signal

import numpy as np
import pytest

from disco_tpu.io import atomic
from disco_tpu.io.audio import read_wav
from disco_tpu.runs import (
    ChaosCrash,
    GracefulInterrupt,
    RunLedger,
    chaos,
    request_stop,
    stop_requested,
    unit_rir,
)


@pytest.fixture(autouse=True)
def _chaos_off():
    """Every test starts and ends with chaos disarmed and no stale stop."""
    chaos.disable()
    yield
    chaos.disable()


# -- atomic writers ---------------------------------------------------------
def test_atomic_write_success_and_crash(tmp_path):
    p = tmp_path / "x.bin"
    atomic.write_bytes_atomic(p, b"payload")
    assert p.read_bytes() == b"payload"
    assert not list(tmp_path.glob(f"*{atomic.TMP_SUFFIX}.*"))

    # a crash inside the write (any exception) leaves the OLD content and
    # no temp litter — the invariant every resume probe relies on
    with pytest.raises(RuntimeError):
        with atomic.atomic_write(p) as fh:
            fh.write(b"half-writ")
            raise RuntimeError("simulated crash")
    assert p.read_bytes() == b"payload"
    assert not list(tmp_path.glob(f"*{atomic.TMP_SUFFIX}.*"))


def test_atomic_write_mid_write_chaos_leaves_no_final_file(tmp_path):
    chaos.configure("mid_write", after=1)
    with pytest.raises(ChaosCrash):
        atomic.write_bytes_atomic(tmp_path / "never.bin", b"x")
    chaos.disable()
    assert not (tmp_path / "never.bin").exists()
    assert not list(tmp_path.glob(f"*{atomic.TMP_SUFFIX}.*"))


def test_write_wav_atomic_roundtrip(tmp_path):
    x = np.linspace(-0.5, 0.5, 321).astype(np.float32)
    p = atomic.write_wav_atomic(tmp_path / "a.wav", x, 16000)
    y, fs = read_wav(p)
    assert fs == 16000
    np.testing.assert_array_equal(x, y)


def test_save_npy_atomic_matches_np_save_suffix(tmp_path):
    # np.save("foo") writes foo.npy; the atomic twin must agree so layout
    # paths stay byte-compatible with the pre-atomic tree
    p = atomic.save_npy_atomic(tmp_path / "m", np.arange(6).reshape(2, 3))
    assert p == tmp_path / "m.npy"
    np.testing.assert_array_equal(np.load(p), np.arange(6).reshape(2, 3))


def test_savez_and_pickle_atomic(tmp_path):
    z = atomic.savez_atomic(tmp_path / "h", a=np.ones(4), b=np.zeros(2))
    with np.load(z) as d:
        np.testing.assert_array_equal(d["a"], np.ones(4))
    p = atomic.dump_pickle_atomic(tmp_path / "r.p", {"k": np.arange(3)})
    with open(p, "rb") as fh:
        np.testing.assert_array_equal(pickle.load(fh)["k"], np.arange(3))


# -- integrity probes -------------------------------------------------------
def _truncate(path, frac=0.5):
    data = path.read_bytes()
    path.write_bytes(data[: int(len(data) * frac)])
    return path


@pytest.mark.parametrize("make,probe", [
    (lambda d: atomic.write_wav_atomic(d / "a.wav", np.zeros(100, np.float32), 16000),
     atomic.probe_wav),
    (lambda d: atomic.save_npy_atomic(d / "b.npy", np.arange(100.0)),
     atomic.probe_npy),
    (lambda d: atomic.savez_atomic(d / "c.npz", x=np.arange(100.0)),
     atomic.probe_npz),
    (lambda d: atomic.dump_pickle_atomic(d / "d.p", {"x": list(range(100))}),
     atomic.probe_pickle),
])
def test_probes_pass_complete_fail_truncated(tmp_path, make, probe):
    p = make(tmp_path)
    assert probe(p)
    assert atomic.probe_artifact(p)
    _truncate(p)
    assert not probe(p)
    assert not atomic.probe_artifact(p)


def test_probe_msgpack(tmp_path):
    from flax import serialization

    p = tmp_path / "ck.msgpack"
    atomic.write_bytes_atomic(p, serialization.to_bytes({"w": np.ones((4, 4))}))
    assert atomic.probe_msgpack(p)
    _truncate(p)
    assert not atomic.probe_msgpack(p)


def test_probe_npy_object_array(tmp_path):
    # the datagen infos files are object arrays (allow_pickle) — the probe
    # must fall back to a full load and still catch truncation
    p = atomic.save_npy_atomic(
        tmp_path / "infos.npy", {"room": {"rt60": 0.3}, "mics": np.ones((3, 8))},
        allow_pickle=True,
    )
    assert atomic.probe_npy(p)
    _truncate(p)
    assert not atomic.probe_npy(p)


def test_probe_artifact_missing_and_unknown_suffix(tmp_path):
    assert not atomic.probe_artifact(tmp_path / "ghost.wav")
    unknown = tmp_path / "x.bin"
    unknown.write_bytes(b"data")
    assert atomic.probe_artifact(unknown)          # non-empty fallback
    unknown.write_bytes(b"")
    assert not atomic.probe_artifact(unknown)      # empty is never done


def test_remove_tmp_litter(tmp_path):
    sub = tmp_path / "sub"
    sub.mkdir()
    litter = sub / f"a.wav{atomic.TMP_SUFFIX}.12345"
    litter.write_bytes(b"partial")
    keep = sub / "a.wav"
    keep.write_bytes(b"done")
    not_ours = sub / "b.tmp.notapid"  # pid field not numeric: leave alone
    not_ours.write_bytes(b"?")
    removed = atomic.remove_tmp_litter(tmp_path)
    assert removed == [str(litter)]
    assert keep.exists() and not_ours.exists() and not litter.exists()
    assert atomic.remove_tmp_litter(tmp_path / "missing") == []


def test_file_digest_verify(tmp_path):
    p = tmp_path / "a.txt"
    p.write_bytes(b"abc")
    d = atomic.file_digest(p)
    assert d.startswith("sha256:") and atomic.verify_digest(p, d)
    p.write_bytes(b"abd")
    assert not atomic.verify_digest(p, d)
    assert not atomic.verify_digest(tmp_path / "missing", d)


# -- run ledger -------------------------------------------------------------
def test_ledger_lifecycle_and_verified_resume(tmp_path):
    art = atomic.save_npy_atomic(tmp_path / "out.npy", np.arange(8.0))
    led = RunLedger(tmp_path / "led.jsonl")
    u = unit_rir(3, "ssn")
    led.mark_in_flight(u, bucket=8192)
    assert led.replay()[u]["state"] == "in_flight"
    led.mark_done(u, [art])
    done, requeued = led.verified_done()
    assert done == {u} and requeued == {}

    # corrupt the artifact: the done claim must be voided and requeued
    _truncate(art)
    done, requeued = led.verified_done()
    assert done == set() and u in requeued
    assert "digest mismatch" in requeued[u]
    assert led.replay()[u]["state"] == "requeued"

    # regenerating the artifact and re-marking done re-verifies
    atomic.save_npy_atomic(tmp_path / "out.npy", np.arange(8.0))
    led.mark_done(u, [art])
    done, _ = led.verified_done()
    assert done == {u}


def test_ledger_missing_artifact_requeues(tmp_path):
    art = tmp_path / "gone.npy"
    atomic.save_npy_atomic(art, np.zeros(3))
    led = RunLedger(tmp_path / "led.jsonl")
    led.mark_done("scene:1", [art])
    art.unlink()
    done, requeued = led.verified_done()
    assert done == set() and "missing" in requeued["scene:1"]


def test_ledger_torn_final_line_is_skipped(tmp_path):
    led = RunLedger(tmp_path / "led.jsonl")
    led.mark_done("a", [])
    led.close()
    with open(tmp_path / "led.jsonl", "a") as fh:
        fh.write('{"t": 1, "unit": "b", "state": "do')  # crash mid-append
    state = RunLedger(tmp_path / "led.jsonl").replay()
    assert set(state) == {"a"}  # the torn line never poisons the history


def test_ledger_rejects_unknown_state(tmp_path):
    with pytest.raises(ValueError, match="unknown ledger state"):
        RunLedger(tmp_path / "led.jsonl").record("u", "finished")


def test_ledger_requeue_emits_warning_event_and_counter(tmp_path):
    from disco_tpu import obs
    from disco_tpu.obs.metrics import REGISTRY

    art = atomic.save_npy_atomic(tmp_path / "x.npy", np.ones(4))
    led = RunLedger(tmp_path / "led.jsonl")
    led.mark_done("u1", [art])
    _truncate(art)
    before = REGISTRY.counter("units_requeued").value
    log = tmp_path / "obs.jsonl"
    with obs.recording(log):
        led.verified_done()
    assert REGISTRY.counter("units_requeued").value == before + 1
    warns = [e for e in obs.read_events(log) if e["kind"] == "warning"]
    assert warns and warns[0]["stage"] == "resume"
    assert warns[0]["attrs"]["unit"] == "u1"


# -- chaos ------------------------------------------------------------------
def test_chaos_fires_at_nth_hit_only():
    chaos.configure("seam_x", after=3)
    chaos.tick("seam_x")
    chaos.tick("seam_other")  # different seam never counts
    chaos.tick("seam_x")
    with pytest.raises(ChaosCrash) as ei:
        chaos.tick("seam_x")
    assert ei.value.seam == "seam_x" and ei.value.hit == 3
    chaos.tick("seam_x")  # after the crash fired, the seam is spent


def test_chaos_env_configuration(monkeypatch):
    chaos._reset_for_tests()
    monkeypatch.setenv(chaos.ENV_VAR, "env_seam:2")
    chaos.tick("env_seam")
    with pytest.raises(ChaosCrash):
        chaos.tick("env_seam")
    chaos.disable()


def test_chaos_crash_passes_except_exception():
    # ChaosCrash must behave like a process death: not catchable by the
    # pipeline's own `except Exception` recovery
    chaos.configure("s", after=1)
    with pytest.raises(ChaosCrash):
        try:
            chaos.tick("s")
        except Exception:  # noqa: BLE001 - the point of the test
            pytest.fail("ChaosCrash was swallowed by `except Exception`")


# -- graceful interruption --------------------------------------------------
def test_graceful_interrupt_sigterm_sets_flag_only():
    with GracefulInterrupt() as stopped:
        assert not stopped()
        os.kill(os.getpid(), signal.SIGTERM)
        assert stopped() and stop_requested()
        os.kill(os.getpid(), signal.SIGTERM)  # repeated SIGTERM stays graceful
        assert stopped()
    assert not stop_requested()  # scope exit clears the process-wide view


def test_graceful_interrupt_second_sigint_raises():
    with pytest.raises(KeyboardInterrupt):
        with GracefulInterrupt():
            os.kill(os.getpid(), signal.SIGINT)   # first: graceful
            assert stop_requested()
            os.kill(os.getpid(), signal.SIGINT)   # second: operator insists


def test_signal_telemetry_deferred_until_poll(tmp_path):
    """A signal handler must not touch obs's non-reentrant locks (it could
    interrupt a frame holding them): the handler only flags, and the next
    stop_requested()/stopped() poll emits the `interrupted` event."""
    from disco_tpu import obs

    log = tmp_path / "o.jsonl"
    with obs.recording(log):
        with GracefulInterrupt() as stopped:
            os.kill(os.getpid(), signal.SIGTERM)
            assert not [e for e in obs.read_events(log)
                        if e["kind"] == "interrupted"]  # nothing from the handler
            assert stopped()  # the poll flushes the deferred telemetry
            evs = [e for e in obs.read_events(log) if e["kind"] == "interrupted"]
            assert len(evs) == 1 and evs[0]["attrs"]["reason"] == "SIGTERM"


def test_ledger_digest_tolerates_missing_secondary_artifacts(tmp_path):
    """digest_artifacts omits already-missing paths (the catch-up path runs
    on trees whose secondary artifacts were cleaned up) instead of raising."""
    from disco_tpu.runs import digest_artifacts

    present = atomic.save_npy_atomic(tmp_path / "kept.npy", np.ones(3))
    d = digest_artifacts([present, tmp_path / "cleaned_up.wav"])
    assert set(d) == {str(present)}


def test_request_stop_without_scope_is_false():
    assert not request_stop("nobody listening")
    assert not stop_requested()


def test_interrupt_records_event_and_counter(tmp_path):
    from disco_tpu import obs
    from disco_tpu.obs.metrics import REGISTRY

    before = REGISTRY.counter("interrupts").value
    log = tmp_path / "obs.jsonl"
    with obs.recording(log):
        with GracefulInterrupt():
            request_stop("test")
            request_stop("test-again")  # only the first transition records
    assert REGISTRY.counter("interrupts").value == before + 1
    evs = [e for e in obs.read_events(log) if e["kind"] == "interrupted"]
    assert len(evs) == 1 and evs[0]["attrs"]["reason"] == "test"


# -- preflight --------------------------------------------------------------
def test_preflight_probe_ok_on_cpu():
    from disco_tpu.utils.resilience import preflight_probe

    out = preflight_probe(deadline_s=30.0)
    assert out["ok"] and out["device_count"] >= 1 and out["dur_s"] >= 0


def test_preflight_probe_failure_is_clean(monkeypatch):
    from disco_tpu.utils import resilience

    def broken_fence(x, **kw):
        raise OSError("tunnel down")

    monkeypatch.setattr(resilience, "resilient_fence", broken_fence)
    with pytest.raises(resilience.PreflightFailed, match="never SIGKILL"):
        resilience.preflight_probe(deadline_s=0.5)


# -- driver integration -----------------------------------------------------
from tests.test_driver import NOISE, RIR, SNR_RANGE, _build_corpus  # noqa: E402


def test_corrupt_oim_pickle_is_reenhanced_not_skipped(tmp_path):
    """Satellite: the idempotency guards must validate before skipping —
    a truncated OIM pickle (crashed pre-atomic run) is re-enhanced."""
    from disco_tpu.enhance.driver import enhance_rir
    from disco_tpu.obs.metrics import REGISTRY

    corpus = _build_corpus(tmp_path / "dataset", [RIR])
    out_root = tmp_path / "results"
    assert enhance_rir(str(corpus), "living", RIR, NOISE, snr_range=SNR_RANGE,
                       out_root=str(out_root), save_fig=False) is not None
    # intact artifacts: the validated skip returns None exactly as before
    assert enhance_rir(str(corpus), "living", RIR, NOISE, snr_range=SNR_RANGE,
                       out_root=str(out_root), save_fig=False) is None

    victim = out_root / "OIM" / f"results_mwf_{RIR}_{NOISE}.p"
    _truncate(victim)
    before = REGISTRY.counter("corrupt_artifacts_detected").value
    redo = enhance_rir(str(corpus), "living", RIR, NOISE, snr_range=SNR_RANGE,
                       out_root=str(out_root), save_fig=False)
    assert redo is not None  # requeued, never trusted
    assert REGISTRY.counter("corrupt_artifacts_detected").value > before
    with open(victim, "rb") as fh:
        assert pickle.load(fh)  # regenerated complete


def test_missing_snr_sidecar_warns(tmp_path):
    """Satellite: the zeros substitution for a missing SNR sidecar is
    visible — warning event + counter, not silent."""
    from disco_tpu import obs
    from disco_tpu.enhance.driver import load_input_signals
    from disco_tpu.io.layout import DatasetLayout
    from disco_tpu.obs.metrics import REGISTRY

    corpus = _build_corpus(tmp_path / "dataset", [RIR])
    layout = DatasetLayout(str(corpus), "living", "test")
    layout.snr_log(SNR_RANGE, RIR, NOISE).unlink()
    before = REGISTRY.counter("snr_sidecar_missing").value
    log = tmp_path / "obs.jsonl"
    with obs.recording(log):
        *_, rnd_snrs = load_input_signals(layout, RIR, NOISE, SNR_RANGE)
    np.testing.assert_array_equal(rnd_snrs, np.zeros(4))
    assert REGISTRY.counter("snr_sidecar_missing").value == before + 1
    warns = [e for e in obs.read_events(log) if e["kind"] == "warning"]
    assert warns and warns[0]["stage"] == "load_input"
    assert "SNR sidecar" in warns[0]["attrs"]["reason"]


@pytest.mark.slow
def test_batched_interrupt_then_resume_identical_tree(tmp_path, monkeypatch):
    """Interrupt-and-resume integration: a graceful stop between chunks
    returns partial results with the ledger consistent; the resumed run
    completes to a tree byte-identical to an uninterrupted one."""
    from disco_tpu.enhance.driver import enhance_rirs_batched

    rirs = [RIR, RIR + 1]
    corpus = _build_corpus(tmp_path / "dataset", rirs)
    kw = dict(snr_range=SNR_RANGE, save_fig=False, max_batch=1, score_workers=1)

    ref_root = tmp_path / "ref"
    ref = enhance_rirs_batched(str(corpus), "living", rirs, NOISE,
                               out_root=str(ref_root), **kw)
    assert set(ref) == set(rirs)

    # deterministic mid-run stop: the flag raises once the first clip has
    # been fully scored.  Tied to completed work, not to a poll count — the
    # pipelined engine legitimately polls stop_requested from both the
    # dispatch loop and the prefetch thread, so a call-count fake would
    # stop the run before any chunk was processed.
    from disco_tpu.enhance import driver as driver_mod
    from disco_tpu.obs.metrics import REGISTRY

    clips0 = REGISTRY.counter("clips_enhanced").value

    def fake_stop():
        return REGISTRY.counter("clips_enhanced").value - clips0 >= 1

    monkeypatch.setattr(driver_mod.run_interrupt, "stop_requested", fake_stop)
    out_root, led = tmp_path / "out", tmp_path / "led.jsonl"
    partial = enhance_rirs_batched(str(corpus), "living", rirs, NOISE,
                                   out_root=str(out_root), ledger=str(led), **kw)
    monkeypatch.undo()
    assert len(partial) == 1  # wound down after one chunk

    done, requeued = RunLedger(led).verified_done()
    assert len(done) == 1 and not requeued  # the finished clip is verified

    resumed = enhance_rirs_batched(str(corpus), "living", rirs, NOISE,
                                   out_root=str(out_root), ledger=str(led),
                                   resume=True, **kw)
    assert set(partial) | set(resumed) == set(rirs)

    ref_tree = {p.relative_to(ref_root): p.read_bytes()
                for p in sorted(ref_root.rglob("*")) if p.is_file()}
    out_tree = {p.relative_to(out_root): p.read_bytes()
                for p in sorted(out_root.rglob("*")) if p.is_file()}
    assert set(ref_tree) == set(out_tree)
    assert all(ref_tree[k] == out_tree[k] for k in ref_tree)


@pytest.mark.slow
def test_batched_chaos_crash_then_resume(tmp_path):
    """Crash (not graceful stop) inside the run: the between_clips chaos
    crash aborts mid-corpus; --resume completes the remainder and the tree
    matches the uninterrupted run."""
    from disco_tpu.enhance.driver import enhance_rirs_batched

    rirs = [RIR, RIR + 1]
    corpus = _build_corpus(tmp_path / "dataset", rirs)
    kw = dict(snr_range=SNR_RANGE, save_fig=False, max_batch=1, score_workers=1)

    ref_root = tmp_path / "ref"
    enhance_rirs_batched(str(corpus), "living", rirs, NOISE,
                         out_root=str(ref_root), **kw)

    out_root, led = tmp_path / "out", tmp_path / "led.jsonl"
    chaos.configure("between_clips", after=1)
    with pytest.raises(ChaosCrash):
        enhance_rirs_batched(str(corpus), "living", rirs, NOISE,
                             out_root=str(out_root), ledger=str(led), **kw)
    chaos.disable()

    resumed = enhance_rirs_batched(str(corpus), "living", rirs, NOISE,
                                   out_root=str(out_root), ledger=str(led),
                                   resume=True, **kw)
    assert resumed  # at least the crashed remainder was processed
    ref_tree = {p.relative_to(ref_root): p.read_bytes()
                for p in sorted(ref_root.rglob("*")) if p.is_file()}
    out_tree = {p.relative_to(out_root): p.read_bytes()
                for p in sorted(out_root.rglob("*")) if p.is_file()}
    assert ref_tree == out_tree


@pytest.mark.slow
def test_digest_requeued_unit_bypasses_pickle_probe(tmp_path):
    """A deleted secondary artifact (WAV) does not show in the pickle-only
    _clip_done probe — but a unit the verified resume requeued must be
    REDONE, not re-certified by the catch-up path."""
    from disco_tpu.enhance.driver import enhance_rirs_batched

    corpus = _build_corpus(tmp_path / "dataset", [RIR])
    out_root, led = tmp_path / "out", tmp_path / "led.jsonl"
    kw = dict(snr_range=SNR_RANGE, save_fig=False, max_batch=1, score_workers=1)
    first = enhance_rirs_batched(str(corpus), "living", [RIR], NOISE,
                                 out_root=str(out_root), ledger=str(led), **kw)
    assert set(first) == {RIR}

    # a plain rerun with the ledger (no resume) trusts its done records:
    # nothing re-enhanced, no re-hash, no duplicate catch-up lines appended
    n_lines = len(led.read_text().splitlines())
    again = enhance_rirs_batched(str(corpus), "living", [RIR], NOISE,
                                 out_root=str(out_root), ledger=str(led), **kw)
    assert again == {} and len(led.read_text().splitlines()) == n_lines

    victim = out_root / "WAV" / str(RIR) / f"in_noi-{NOISE}_Node-2.wav"
    victim.unlink()
    resumed = enhance_rirs_batched(str(corpus), "living", [RIR], NOISE,
                                   out_root=str(out_root), ledger=str(led),
                                   resume=True, **kw)
    assert set(resumed) == {RIR}   # requeued AND actually re-enhanced
    assert atomic.probe_wav(victim)  # the deleted artifact is back
    done, requeued = RunLedger(led).verified_done()
    assert done == {unit_rir(RIR, NOISE)} and not requeued


# -- training integration ---------------------------------------------------
def _tiny_fit_setup(tmp_path):
    from disco_tpu.nn import RandomDataset, batch_iterator, create_train_state
    from tests.test_nn import _tiny_model

    model, tx = _tiny_model()
    ds = RandomDataset((21, 33), (33, 21), length=12, rng=np.random.default_rng(0))

    def batches():
        for x, y in batch_iterator(ds, 6, rng=np.random.default_rng(1)):
            yield x, np.swapaxes(y, -2, -1)

    state = create_train_state(model, tx, next(batches())[0])
    return model, state, batches


def test_load_checkpoint_corrupt_raises_clean_error(tmp_path):
    """Satellite: a truncated/corrupt checkpoint is a CheckpointError
    naming the path, not an opaque msgpack traceback."""
    from disco_tpu.nn import CheckpointError, load_checkpoint, save_checkpoint

    model, state, batches = _tiny_fit_setup(tmp_path)
    ck = tmp_path / "ck.msgpack"
    save_checkpoint(ck, state, np.zeros(2), np.zeros(2))
    _truncate(ck)
    with pytest.raises(CheckpointError, match=str(ck)):
        load_checkpoint(ck, state)
    with pytest.raises(CheckpointError, match="cannot read"):
        load_checkpoint(tmp_path / "missing.msgpack", state)


def test_cli_train_corrupt_weights_clean_exit(tmp_path, monkeypatch):
    """Satellite: `disco-train --weights <corrupt>` fails with the clean
    CheckpointError message, not a traceback."""
    from disco_tpu.cli import train as train_cli

    bad = tmp_path / "bad_model.msgpack"
    bad.write_bytes(b"\x00\x01 not msgpack")

    def fake_run(args):
        # reproduce just the resume entry the full _run would hit, without
        # needing a corpus on disk
        from disco_tpu.nn.training import load_checkpoint

        _, state, _ = _tiny_fit_setup(tmp_path)
        load_checkpoint(args.weights, state)

    monkeypatch.setattr(train_cli, "_run", fake_run)
    with pytest.raises(SystemExit) as ei:
        train_cli.main(["--weights", str(bad)])
    assert "corrupt or incompatible" in str(ei.value) and str(bad) in str(ei.value)
    assert not isinstance(ei.value.code, int)  # carries the message, not a code


@pytest.mark.slow
def test_fit_ledger_and_graceful_stop(tmp_path):
    """Training epochs land in the ledger (state-only records carrying the
    checkpoint digest as attrs — the shared losses/ckpt files are mutable,
    so they are NOT per-epoch verified artifacts); a stop requested during
    epoch 0 winds down before epoch 1 and stays resumable."""
    from disco_tpu.nn import fit

    model, state, batches = _tiny_fit_setup(tmp_path)
    led = tmp_path / "led.jsonl"
    state, tr, va, name = fit(model, state, batches, batches, n_epochs=2,
                              save_path=tmp_path, verbose=False, ledger=str(led))
    done, requeued = RunLedger(led).verified_done()
    assert done == {"epoch:0", "epoch:1"} and not requeued
    recs = RunLedger(led).replay()
    assert recs["epoch:0"]["attrs"]["improved"]
    assert recs["epoch:0"]["attrs"]["ckpt_digest"].startswith("sha256:")
    # the LAST improved epoch's digest matches the checkpoint on disk — the
    # exact file a --weights resume restarts from
    last_improved = max(
        (r for r in recs.values() if r["attrs"].get("improved")),
        key=lambda r: r["t"],
    )
    assert atomic.verify_digest(tmp_path / f"{name}_model.msgpack",
                                last_improved["attrs"]["ckpt_digest"])

    # graceful stop: epoch 0 of a fresh run completes, epoch 1 never starts
    model2, state2, batches2 = _tiny_fit_setup(tmp_path)
    calls = {"n": 0}

    def stop_after_first():
        calls["n"] += 1
        return calls["n"] > 1  # first poll (epoch 0): run; second: stop

    import disco_tpu.runs.interrupt as ri

    real = ri.stop_requested
    ri.stop_requested = stop_after_first
    try:
        _, tr2, _, name2 = fit(model2, state2, batches2, batches2, n_epochs=3,
                               save_path=tmp_path / "g", verbose=False)
    finally:
        ri.stop_requested = real
    assert np.count_nonzero(tr2) == 1  # one epoch ran, then wound down
    assert (tmp_path / "g" / f"{name2}_model.msgpack").exists()  # resumable
