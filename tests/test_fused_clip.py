"""disco-chain (disco_tpu.enhance.fused): the whole-clip and streaming
chained programs against their staged twins, the chained batch runners,
and the chained driver path.

Documented tolerances (enhance/fused.py module docstring, the
performance doc's "Chaining the clip" section):

* offline clip (``tango_clip_fused`` vs the staged stft -> masks ->
  tango -> istft dispatches): the SAME stage functions trace in the same
  order, so parity is float32 reassociation noise across the former
  dispatch boundaries — <= 1e-4 relative to the output scale (measured
  ~1e-6);
* streaming window (``streaming_clip_fused`` vs stft ->
  ``streaming_tango_scan`` -> istft on the SAME window): identical
  computation, jit-boundary noise only — <= 1e-5 absolute at unit input
  scale.  (The documented window-vs-full-clip STFT boundary difference is
  between the streaming twin and the OFFLINE path, not covered here — it
  is a design property, not a tolerance.)
* driver level (``enhance_rir(chained=True)`` vs the staged driver):
  SDR within 0.1 dB per node, bucket-matched.
"""
import pickle

import numpy as np
import pytest

from disco_tpu.enhance.fused import streaming_clip_fused, tango_clip_fused


def _staged_clip(y, s, n, solver="fused-xla", export=False):
    """The staged path mirrored stage for stage (bench.py's staged jits):
    fused STFT -> magnitude masks -> two-step tango -> ISTFT, each stage a
    separate dispatch."""
    import jax.numpy as jnp

    from disco_tpu.core.dsp import istft
    from disco_tpu.core.masks import tf_mask_mag
    from disco_tpu.enhance.tango import tango
    from disco_tpu.ops.stft_ops import stft_with_mag

    L = y.shape[-1]
    spec, mag = stft_with_mag(jnp.stack([y, s, n]), impl="xla")
    m = tf_mask_mag(mag[1][:, 0], mag[2][:, 0], "irm1")
    res = tango(spec[0], spec[1], spec[2], m, m, policy="local",
                solver=solver)
    if not export:
        return np.asarray(istft(res.yf, length=L))
    return res, np.asarray(istft(res.yf, length=L))


def _clip_signals(rng, K=2, C=2, L=4096):
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5,
                               mode="same") for _ in range(C)])
         for _ in range(K)]
    ).astype(np.float32)
    n = (0.5 * rng.standard_normal((K, C, L))).astype(np.float32)
    return s + n, s, n


# -- the offline chained program vs its staged twin ---------------------------
def test_tango_clip_fused_matches_staged_pipeline(rng):
    """ONE dispatched program == the staged stage sequence at the
    documented offline tolerance, oracle-mask path."""
    y, s, n = _clip_signals(rng)
    ref = _staged_clip(y, s, n)
    got = np.asarray(tango_clip_fused(y, s, n, solver="fused-xla",
                                      stft_impl="xla"))
    assert got.shape == ref.shape == (2, 4096)
    scale = np.abs(ref).max()
    assert np.abs(got - ref).max() <= 1e-4 * scale, (
        np.abs(got - ref).max(), scale)


def test_tango_clip_fused_client_masks_match_staged(rng):
    """The CRNN lane: explicit (K, F, T) masks as traced program inputs
    reproduce the staged path run on the same masks."""
    import jax.numpy as jnp

    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.enhance.tango import tango

    y, s, n = _clip_signals(rng)
    Y, S, N = stft(y), stft(s), stft(n)
    K, _, F, T = Y.shape
    m = rng.uniform(0.05, 0.95, (K, F, T)).astype(np.float32)
    res = tango(Y, S, N, jnp.asarray(m), jnp.asarray(m), policy="local",
                solver="fused-xla")
    ref = np.asarray(istft(res.yf, length=y.shape[-1]))
    got = np.asarray(tango_clip_fused(y, s, n, masks_z=m, solver="fused-xla",
                                      stft_impl="xla"))
    scale = np.abs(ref).max()
    assert np.abs(got - ref).max() <= 1e-4 * scale


def test_tango_clip_fused_export_payload_contract(rng):
    """export=True returns exactly the driver's scoring payload — the six
    time-domain streams (yf, z_y, sf, nf, z_s, z_n), the masks, the z
    export — each matching the staged stage outputs."""
    from disco_tpu.core.dsp import istft

    y, s, n = _clip_signals(rng)
    L = y.shape[-1]
    res, ref_yf = _staged_clip(y, s, n, export=True)
    out = tango_clip_fused(y, s, n, solver="fused-xla", stft_impl="xla",
                           export=True)
    assert set(out) == {"td", "masks_z", "mask_w", "z_y"}
    assert len(out["td"]) == 6
    scale = np.abs(ref_yf).max()
    assert np.abs(np.asarray(out["td"][0]) - ref_yf).max() <= 1e-4 * scale
    for i, stream in enumerate((res.yf, res.z_y, res.sf, res.nf, res.z_s,
                                res.z_n)):
        ref_td = np.asarray(istft(stream, length=L))
        got_td = np.asarray(out["td"][i])
        assert got_td.shape == (2, L)
        sc = max(np.abs(ref_td).max(), 1e-12)
        assert np.abs(got_td - ref_td).max() <= 1e-4 * sc, i
    np.testing.assert_allclose(np.asarray(out["masks_z"]),
                               np.asarray(res.masks_z), rtol=0, atol=1e-6)
    zsc = np.abs(np.asarray(res.z_y)).max()
    assert np.abs(np.asarray(out["z_y"])
                  - np.asarray(res.z_y)).max() <= 1e-4 * zsc


# -- the streaming chained window vs the staged scan --------------------------
def test_streaming_clip_fused_continuation_matches_staged_scan(rng):
    """Two consecutive super-tick windows through the chained program,
    state threaded, against stft -> streaming_tango_scan -> istft staged
    over the SAME windows — identical computation, jit-boundary noise
    only; and the second window really continues (differs from a cold
    start)."""
    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.enhance.streaming import streaming_tango_scan

    K, C, U, BT, F = 2, 2, 4, 8, 257
    Lw = (BT - 1) * (F - 1)
    wins = [rng.standard_normal((K, C, Lw)).astype(np.float32)
            for _ in range(2)]
    masks = [rng.uniform(0.05, 0.95, (K, F, BT)).astype(np.float32)
             for _ in range(2)]

    refs, st_ref = [], None
    for y, m in zip(wins, masks):
        out = streaming_tango_scan(stft(y), m, m, update_every=U,
                                   policy="local", state=st_ref,
                                   blocks_per_dispatch=2,
                                   solver="fused-xla")
        refs.append(np.asarray(istft(out["yf"], length=Lw)))
        st_ref = out["state"]

    got, st = [], None
    for y, m in zip(wins, masks):
        out = streaming_clip_fused(y, masks_z=m, mask_w=m, update_every=U,
                                   policy="local", state=st,
                                   blocks_per_dispatch=2,
                                   solver="fused-xla", stft_impl="xla")
        got.append(np.asarray(out["yf"]))
        st = out["state"]

    for i, (g, r) in enumerate(zip(got, refs)):
        assert g.shape == r.shape == (K, Lw)
        assert np.abs(g - r).max() <= 1e-5, (i, np.abs(g - r).max())
    cold = np.asarray(
        streaming_clip_fused(wins[1], masks_z=masks[1], mask_w=masks[1],
                             update_every=U, policy="local",
                             blocks_per_dispatch=2, solver="fused-xla",
                             stft_impl="xla")["yf"])
    assert np.abs(cold - got[1]).max() > 1e-4  # the state is load-bearing


def test_streaming_clip_fused_needs_masks_or_components(rng):
    K, C, Lw = 2, 2, 1792
    y = rng.standard_normal((K, C, Lw)).astype(np.float32)
    with pytest.raises(ValueError, match="masks_z"):
        streaming_clip_fused(y, update_every=4, blocks_per_dispatch=2)


# -- the chained batch runners and host fetch ---------------------------------
def test_make_batch_runners_chained_parity_trim_and_guards(rng):
    """The vmapped chained runner reproduces the per-clip chained program
    clip for clip; fetch_chained_host trims ragged lengths; the
    incompatible-option guards reject at construction."""
    from disco_tpu.enhance.driver import make_batch_runners
    from disco_tpu.enhance.pipeline import fetch_chained_host

    B, K, C, L = 2, 2, 2, 1024
    yb = rng.standard_normal((B, K, C, L)).astype(np.float32)
    sb = rng.standard_normal((B, K, C, L)).astype(np.float32)
    nb = rng.standard_normal((B, K, C, L)).astype(np.float32)

    run_batch, run_batch_with_masks = make_batch_runners(
        solver="fused-xla", chained=True, stft_impl="xla")
    assert run_batch_with_masks is None  # chained = oracle-mask lane only
    out_b = run_batch(yb, sb, nb)
    assert set(out_b) == {"td", "masks_z", "mask_w", "z_y"}
    assert len(out_b["td"]) == 6
    assert out_b["td"][0].shape == (B, K, L)

    host = fetch_chained_host(out_b, clip_lengths=[1024, 900], n_real=2)
    assert len(host["td"]) == 2
    assert host["td"][0][0].shape == (K, 1024)
    assert host["td"][1][0].shape == (K, 900)
    assert host["masks_z"].shape[0] == 2

    for i in range(B):
        ref = tango_clip_fused(yb[i], sb[i], nb[i], solver="fused-xla",
                               stft_impl="xla", export=True)
        ref_td = np.asarray(ref["td"][0])
        got = host["td"][i][0]
        Lr = got.shape[-1]
        scale = np.abs(ref_td).max()
        assert np.abs(got - ref_td[..., :Lr]).max() <= 1e-4 * scale, i

    for kw, frag in (
        (dict(mesh=object()), "single-device"),
        (dict(z_mask_arr=np.ones(4, np.float32)), "z-exchange"),
    ):
        with pytest.raises(ValueError, match=frag):
            make_batch_runners(solver="fused-xla", chained=True, **kw)


# -- the chained driver path --------------------------------------------------
@pytest.mark.slow
def test_enhance_rir_chained_matches_staged_and_guards(tmp_path):
    """enhance_rir(chained=True) enhances (SDR up at every node), lands
    within 0.1 dB per node of the staged driver on the same solver, and
    rejects the staged-only options."""
    from tests.test_driver import (
        EXPECTED_KEYS,
        NOISE,
        RIR,
        SNR_RANGE,
        _build_corpus,
    )

    from disco_tpu.enhance.driver import enhance_rir

    corpus = _build_corpus(tmp_path / "dataset", [RIR], lengths=[32000])
    res = enhance_rir(str(corpus), "living", RIR, NOISE,
                      snr_range=SNR_RANGE,
                      out_root=str(tmp_path / "results"), save_fig=False,
                      chained=True)
    assert res is not None
    assert EXPECTED_KEYS <= set(res), EXPECTED_KEYS - set(res)
    assert res["sdr_cnv"].shape == (4,)
    assert np.all(res["sdr_cnv"] > res["sdr_in_cnv"])

    res_s = enhance_rir(str(corpus), "living", RIR, NOISE,
                        snr_range=SNR_RANGE,
                        out_root=str(tmp_path / "results_staged"),
                        save_fig=False, solver="fused-xla")
    assert np.abs(res["sdr_cnv"] - res_s["sdr_cnv"]).max() < 0.1

    for kw in (dict(streaming=True), dict(fault_spec={"seed": 1}),
               dict(models=(1, None))):
        with pytest.raises(ValueError):
            enhance_rir(str(corpus), "living", RIR, NOISE,
                        out_root=str(tmp_path / "x"), chained=True,
                        force=True, **kw)


@pytest.mark.slow
def test_enhance_rirs_batched_chained_corpus(tmp_path):
    """The bucketed chained corpus engine on ragged lengths: per-RIR
    results with the full pickle schema, parity with the per-clip chained
    driver at the SAME bucket (padding shifts absolute SDR, so
    comparisons must be bucket-matched), artifacts on disk, and the
    non-pipelined path sharing the fetch."""
    from tests.test_driver import (
        EXPECTED_KEYS,
        NOISE,
        RIR,
        SNR_RANGE,
        _build_corpus,
    )

    from disco_tpu.enhance.driver import enhance_rir, enhance_rirs_batched

    corpus = _build_corpus(tmp_path / "dataset", [RIR, RIR + 1],
                           lengths=[32000, 30000])
    res_b = enhance_rirs_batched(
        str(corpus), "living", [RIR, RIR + 1], NOISE, snr_range=SNR_RANGE,
        out_root=str(tmp_path / "results_batched"), save_fig=False,
        chained=True, bucket=8192, max_batch=2, score_workers=1)
    assert set(res_b) == {RIR, RIR + 1}
    for r, d in res_b.items():
        assert EXPECTED_KEYS <= set(d)
        assert np.all(d["sdr_cnv"] > d["sdr_in_cnv"]), r
    pkl = (tmp_path / "results_batched" / "OIM"
           / f"results_tango_{RIR + 1}_{NOISE}.p")
    assert pkl.exists()
    with open(pkl, "rb") as f:
        assert EXPECTED_KEYS <= set(pickle.load(f))

    res_p = enhance_rir(str(corpus), "living", RIR, NOISE,
                        snr_range=SNR_RANGE,
                        out_root=str(tmp_path / "results_padded"),
                        save_fig=False, chained=True, bucket=8192)
    assert np.abs(res_b[RIR]["sdr_cnv"] - res_p["sdr_cnv"]).max() < 0.1

    res_np = enhance_rirs_batched(
        str(corpus), "living", [RIR], NOISE, snr_range=SNR_RANGE,
        out_root=str(tmp_path / "results_nopipe"), save_fig=False,
        chained=True, bucket=8192, max_batch=2, score_workers=1,
        pipeline=False)
    assert np.allclose(res_np[RIR]["sdr_cnv"], res_b[RIR]["sdr_cnv"])
