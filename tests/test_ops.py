"""Parity tests for the TPU-native STFT kernels (disco_tpu.ops) against the
rFFT reference path."""
import numpy as np
import pytest

from disco_tpu.core.dsp import _stft_rfft, stft
from disco_tpu.ops import dft_matrices, stft_matmul, stft_pallas


@pytest.fixture(scope="module")
def sig():
    rng = np.random.default_rng(3)
    return rng.standard_normal((3, 40000)).astype("float32")


def test_dft_matrices_exact():
    Dre, Dim = dft_matrices(512)
    assert Dre.shape == (512, 257) and Dim.shape == (512, 257)
    # column 0 = DC: cos=1, sin=0
    np.testing.assert_allclose(Dre[:, 0], 1.0)
    np.testing.assert_allclose(Dim[:, 0], 0.0)
    # vs direct float64 DFT
    n = np.arange(512)
    ref = np.cos(-2 * np.pi * 5 * n / 512)
    np.testing.assert_allclose(Dre[:, 5], ref, atol=1e-6)


def test_stft_matmul_matches_rfft(sig):
    a = np.asarray(_stft_rfft(sig))
    b = np.asarray(stft_matmul(sig))
    assert np.max(np.abs(a - b)) / np.max(np.abs(a)) < 1e-5


def test_stft_pallas_matches_rfft(sig):
    a = np.asarray(_stft_rfft(sig))
    c = np.asarray(stft_pallas(sig, interpret=True))
    assert np.max(np.abs(a - c)) / np.max(np.abs(a)) < 1e-5


def test_stft_pallas_ragged_tail():
    """Frame counts not divisible by the tile must round-trip (pad + trim)."""
    rng = np.random.default_rng(4)
    x = rng.standard_normal((1, 12345)).astype("float32")
    a = np.asarray(_stft_rfft(x))
    c = np.asarray(stft_pallas(x, interpret=True, tile_t=32))
    assert a.shape == c.shape
    assert np.max(np.abs(a - c)) / np.max(np.abs(a)) < 1e-5


def test_stft_dispatch_explicit(sig):
    a = np.asarray(stft(sig, impl="rfft"))
    b = np.asarray(stft(sig, impl="matmul"))
    assert np.max(np.abs(a - b)) / np.max(np.abs(a)) < 1e-5


def test_stft_matmul_requires_half_overlap():
    with pytest.raises(AssertionError, match="50%"):
        stft_matmul(np.zeros((1, 4096), "float32"), n_fft=512, hop=128)


def test_istft_matmul_matches_ola(sig):
    from disco_tpu.core.dsp import _istft_ola
    from disco_tpu.ops import istft_matmul

    S = np.asarray(_stft_rfft(sig))
    a = np.asarray(_istft_ola(S, length=sig.shape[-1]))
    b = np.asarray(istft_matmul(S, length=sig.shape[-1]))
    assert np.max(np.abs(a - b)) < 1e-4
    # perfect reconstruction of the original signal
    assert np.max(np.abs(b - sig)) < 1e-4


def test_istft_matmul_length_padding(sig):
    from disco_tpu.ops import istft_matmul

    S = np.asarray(_stft_rfft(sig[:1]))
    longer = np.asarray(istft_matmul(S, length=sig.shape[-1] + 3000))
    assert longer.shape[-1] == sig.shape[-1] + 3000
    assert np.all(longer[:, -2000:] == 0.0)


def test_istft_dispatch_explicit(sig):
    from disco_tpu.core.dsp import istft

    S = np.asarray(_stft_rfft(sig))
    a = np.asarray(istft(S, length=sig.shape[-1], impl="irfft"))
    b = np.asarray(istft(S, length=sig.shape[-1], impl="matmul"))
    assert np.max(np.abs(a - b)) < 1e-4
    with pytest.raises(ValueError, match="unknown istft impl"):
        istft(S, length=100, impl="bogus")


# ------------------------------------------------------- fused masked covs
def _cov_case(rng, lead, C=4, F=257, T=63):
    shape = lead + (C, F, T)
    y = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(np.complex64)
    m = rng.random(lead + (F, T)).astype(np.float32)
    return y, m


def test_masked_cov_pallas_matches_float64_oracle():
    """Parity against the float64 NumPy oracle (the package convention for
    numerical kernels), not just the fp32 einsum path — a shared systematic
    error in both JAX paths would slip past an einsum-vs-pallas check."""
    from tests.reference_impls import covariances_np

    from disco_tpu.beam.covariance import masked_covariances
    from disco_tpu.ops.cov_ops import masked_cov_pallas

    rng = np.random.default_rng(5)
    y, m = _cov_case(rng, lead=())
    y64, m64 = np.asarray(y, np.complex128), np.asarray(m, np.float64)
    Rss_or = covariances_np(m64[None] * y64)
    Rnn_or = covariances_np((1.0 - m64)[None] * y64)
    Rss, Rnn = masked_cov_pallas(y, m, interpret=True)
    np.testing.assert_allclose(np.asarray(Rss), Rss_or, rtol=5e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Rnn), Rnn_or, rtol=5e-4, atol=1e-6)
    # and against the production einsum path (regression coupling)
    Rss_ref, Rnn_ref = masked_covariances(y, m)
    np.testing.assert_allclose(np.asarray(Rss), np.asarray(Rss_ref), rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Rnn), np.asarray(Rnn_ref), rtol=2e-4, atol=1e-6)
    # hermitian by construction
    np.testing.assert_allclose(
        np.asarray(Rss), np.conj(np.swapaxes(np.asarray(Rss), -1, -2)), rtol=1e-6, atol=0
    )


def test_masked_cov_pallas_batched_leading_axes():
    from disco_tpu.beam.covariance import masked_covariances
    from disco_tpu.ops.cov_ops import masked_cov_pallas

    rng = np.random.default_rng(6)
    y, m = _cov_case(rng, lead=(2, 3), C=3, F=17, T=40)
    Rss_ref, Rnn_ref = masked_covariances(y, m)
    Rss, Rnn = masked_cov_pallas(y, m, interpret=True)
    assert Rss.shape == (2, 3, 17, 3, 3)
    np.testing.assert_allclose(np.asarray(Rss), np.asarray(Rss_ref), rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Rnn), np.asarray(Rnn_ref), rtol=2e-4, atol=1e-6)


def test_masked_cov_pallas_frame_tiled_accumulation():
    """T > t_tile engages the innermost-grid accumulation sweep (the VMEM
    fix for long clips: round-3/4 on-device compiles died at 10 s clips
    because the untiled frame block outgrew VMEM).  Non-multiple T also
    exercises the zero-padded tail tile."""
    from disco_tpu.beam.covariance import masked_covariances
    from disco_tpu.ops.cov_ops import masked_cov_pallas

    rng = np.random.default_rng(9)
    y, m = _cov_case(rng, lead=(), C=3, F=17, T=53)
    Rss_ref, Rnn_ref = masked_covariances(y, m)
    Rss, Rnn = masked_cov_pallas(y, m, t_tile=16, interpret=True)  # 53 -> 4 tiles
    np.testing.assert_allclose(np.asarray(Rss), np.asarray(Rss_ref), rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Rnn), np.asarray(Rnn_ref), rtol=2e-4, atol=1e-6)


def test_masked_cov_fused_dispatch():
    from disco_tpu.ops.cov_ops import masked_covariances_fused

    rng = np.random.default_rng(7)
    y, m = _cov_case(rng, lead=(), C=2, F=9, T=16)
    a = masked_covariances_fused(y, m, impl="xla")
    b = masked_covariances_fused(y, m, impl="pallas", interpret=True)
    np.testing.assert_allclose(np.asarray(a[0]), np.asarray(b[0]), rtol=2e-4, atol=1e-6)
    with pytest.raises(ValueError, match="unknown cov impl"):
        masked_covariances_fused(y, m, impl="bogus")


def test_masked_cov_pallas_under_vmap():
    """tango vmaps step1 over nodes: the kernel must batch correctly."""
    import jax

    from disco_tpu.beam.covariance import masked_covariances
    from disco_tpu.ops.cov_ops import masked_cov_pallas

    rng = np.random.default_rng(8)
    y, m = _cov_case(rng, lead=(3,), C=2, F=11, T=24)
    ref = jax.vmap(masked_covariances)(y, m)
    got = jax.vmap(lambda yy, mm: masked_cov_pallas(yy, mm, interpret=True))(y, m)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]), rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(ref[1]), rtol=2e-4, atol=1e-6)


# ----------------------------------------------- impl / precision resolution
def test_resolve_seams_identical_per_backend(monkeypatch):
    """cov_impl='auto' and stft_impl='auto' must resolve to the SAME kernel
    class on any one backend — both are backed by ops.resolve.resolve_impl."""
    import disco_tpu.utils.backend as backend

    from disco_tpu.ops.cov_ops import resolve_cov_impl
    from disco_tpu.ops.stft_ops import resolve_stft_impl

    monkeypatch.delenv("DISCO_TPU_COV_IMPL", raising=False)
    monkeypatch.delenv("DISCO_TPU_STFT_IMPL", raising=False)
    # this suite runs on CPU: auto -> xla for both
    assert resolve_cov_impl("auto") == "xla"
    assert resolve_stft_impl("auto") == "xla"
    # forced TPU (memoized backend probe): auto -> pallas for both
    monkeypatch.setattr(backend, "_cached", True)
    assert resolve_cov_impl("auto") == "pallas"
    assert resolve_stft_impl("auto") == "pallas"
    # explicit choices pass through regardless of backend
    assert resolve_cov_impl("xla") == resolve_stft_impl("xla") == "xla"


def test_resolve_env_escape_hatches(monkeypatch):
    from disco_tpu.ops.cov_ops import resolve_cov_impl
    from disco_tpu.ops.stft_ops import resolve_stft_impl

    monkeypatch.setenv("DISCO_TPU_COV_IMPL", "pallas")
    monkeypatch.setenv("DISCO_TPU_STFT_IMPL", "pallas")
    assert resolve_cov_impl("auto") == "pallas"
    assert resolve_stft_impl("auto") == "pallas"
    # an explicit impl wins over the env var
    assert resolve_cov_impl("xla") == resolve_stft_impl("xla") == "xla"
    monkeypatch.setenv("DISCO_TPU_STFT_IMPL", "bogus")
    with pytest.raises(ValueError, match="DISCO_TPU_STFT_IMPL"):
        resolve_stft_impl("auto")
    with pytest.raises(ValueError, match="unknown impl"):
        resolve_cov_impl("mosaic")


def test_resolve_precision_canonicalizes_and_rejects():
    from disco_tpu.ops.resolve import compute_dtype, resolve_precision

    assert resolve_precision("f32") == "f32"
    assert resolve_precision(" BF16 ") == "bf16"  # canonical form, one spelling
    with pytest.raises(ValueError, match="unknown precision"):
        resolve_precision("fp8")
    import jax.numpy as jnp

    assert compute_dtype("f32") == jnp.float32
    assert compute_dtype("bf16") == jnp.bfloat16


# --------------------------------------------------- fused spec+mag STFT
def test_stft_with_mag_xla_bit_identical_to_stft_abs(sig):
    """The 'xla' lane is the pre-fusion program: spec bit-identical to
    dsp.stft's backend-auto path, mag bit-identical to jnp.abs of it."""
    from disco_tpu.ops.stft_ops import stft_with_mag

    spec, mag = stft_with_mag(sig, impl="xla")
    ref = np.asarray(stft(sig))
    np.testing.assert_array_equal(np.asarray(spec), ref)
    np.testing.assert_array_equal(np.asarray(mag), np.abs(ref))


def test_stft_with_mag_pallas_parity(sig):
    from disco_tpu.ops.stft_ops import stft_with_mag

    ref = np.asarray(_stft_rfft(sig))
    spec, mag = stft_with_mag(sig, impl="pallas", interpret=True)
    scale = np.max(np.abs(ref))
    assert np.max(np.abs(np.asarray(spec) - ref)) / scale < 1e-5
    assert np.max(np.abs(np.asarray(mag) - np.abs(ref))) / scale < 1e-5


def test_stft_with_mag_bf16_lane_tolerance(sig):
    """Documented bf16-lane tolerance for the STFT stage: 1e-2 max relative
    deviation vs the f32 rFFT reference (measured ~2e-3 — bf16 operands,
    f32 accumulators), on BOTH impls."""
    from disco_tpu.ops.stft_ops import stft_with_mag

    ref = np.asarray(_stft_rfft(sig))
    scale = np.max(np.abs(ref))
    for impl in ("xla", "pallas"):
        spec, mag = stft_with_mag(sig, impl=impl, precision="bf16", interpret=True)
        assert np.max(np.abs(np.asarray(spec) - ref)) / scale < 1e-2, impl
        assert np.max(np.abs(np.asarray(mag) - np.abs(ref))) / scale < 1e-2, impl


def test_stft_fused_spec_only_matches_with_mag(sig):
    from disco_tpu.ops.stft_ops import stft_fused, stft_with_mag

    for impl in ("xla", "pallas"):
        spec = stft_fused(sig, impl=impl, interpret=True)
        spec2, _ = stft_with_mag(sig, impl=impl, interpret=True)
        np.testing.assert_array_equal(np.asarray(spec), np.asarray(spec2))


def test_stft_with_mag_unknown_impl():
    from disco_tpu.ops.stft_ops import stft_with_mag

    with pytest.raises(ValueError, match="unknown impl"):
        stft_with_mag(np.zeros((1, 4096), "float32"), impl="bogus")


# --------------------------------------------------- folded masked covs
def test_masked_cov_folded_matches_float64_oracle():
    """The folded einsum (the post-fusion 'xla' default of the tango steps)
    against the float64 oracle AND the materializing einsum it replaced."""
    from tests.reference_impls import covariances_np

    from disco_tpu.beam.covariance import masked_covariances
    from disco_tpu.ops.cov_ops import masked_covariances_folded

    rng = np.random.default_rng(15)
    y, m = _cov_case(rng, lead=())
    y64, m64 = np.asarray(y, np.complex128), np.asarray(m, np.float64)
    Rss_or = covariances_np(m64[None] * y64)
    Rnn_or = covariances_np((1.0 - m64)[None] * y64)
    Rss, Rnn = masked_covariances_folded(y, m)
    np.testing.assert_allclose(np.asarray(Rss), Rss_or, rtol=5e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Rnn), Rnn_or, rtol=5e-4, atol=1e-6)
    Rss_ref, Rnn_ref = masked_covariances(y, m)
    np.testing.assert_allclose(np.asarray(Rss), np.asarray(Rss_ref), rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Rnn), np.asarray(Rnn_ref), rtol=2e-4, atol=1e-6)


def test_masked_cov_folded_per_channel_masks():
    """(C, F, T) per-channel masks — the step-2 stacked [mics ‖ z] layout of
    the 'distant' policy — vs materializing each channel's masked stream."""
    from disco_tpu.beam.covariance import frame_mean_covariance
    from disco_tpu.ops.cov_ops import masked_covariances_folded, weighted_cov_folded

    rng = np.random.default_rng(16)
    y, _ = _cov_case(rng, lead=(), C=5, F=17, T=40)
    mc = rng.random((5, 17, 40)).astype(np.float32)
    Rss, Rnn = masked_covariances_folded(y, mc)
    Rss_ref = np.asarray(frame_mean_covariance(mc * y))
    Rnn_ref = np.asarray(frame_mean_covariance((1.0 - mc) * y))
    np.testing.assert_allclose(np.asarray(Rss), Rss_ref, rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Rnn), Rnn_ref, rtol=2e-4, atol=1e-6)
    # the single-cov fold (the 'none' policy's building block)
    R1 = weighted_cov_folded(y, mc)
    np.testing.assert_allclose(np.asarray(R1), Rss_ref, rtol=2e-4, atol=1e-6)


def test_masked_cov_pallas_per_channel_masks():
    """The extended pallas kernel under per-channel masks, interpret mode."""
    from disco_tpu.beam.covariance import frame_mean_covariance
    from disco_tpu.ops.cov_ops import masked_cov_pallas

    rng = np.random.default_rng(17)
    y, _ = _cov_case(rng, lead=(), C=4, F=17, T=53)
    mc = rng.random((4, 17, 53)).astype(np.float32)
    Rss, Rnn = masked_cov_pallas(y, mc, t_tile=16, f_tile=8, interpret=True)
    Rss_ref = np.asarray(frame_mean_covariance(mc * y))
    Rnn_ref = np.asarray(frame_mean_covariance((1.0 - mc) * y))
    np.testing.assert_allclose(np.asarray(Rss), Rss_ref, rtol=5e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Rnn), Rnn_ref, rtol=5e-4, atol=1e-6)


def test_cov_bf16_lane_tolerance():
    """Documented bf16-lane tolerance for the covariance stage: 3e-2 max
    relative deviation vs the float64 oracle (measured ~2e-3 folded /
    ~2.5e-3 pallas on this case — bf16 products, f32 accumulation)."""
    from tests.reference_impls import covariances_np

    from disco_tpu.ops.cov_ops import masked_cov_pallas, masked_covariances_folded

    rng = np.random.default_rng(18)
    y, m = _cov_case(rng, lead=())
    y64, m64 = np.asarray(y, np.complex128), np.asarray(m, np.float64)
    Rss_or = covariances_np(m64[None] * y64)
    scale = np.max(np.abs(Rss_or))
    for impl_fn in (
        lambda: masked_covariances_folded(y, m, precision="bf16")[0],
        lambda: masked_cov_pallas(y, m, interpret=True, precision="bf16")[0],
    ):
        got = np.asarray(impl_fn())
        assert np.max(np.abs(got - Rss_or)) / scale < 3e-2


def test_outer_acc_bf16_matches_f32_at_tolerance():
    """The streaming tail accumulator's bf16 form vs its f32 einsum."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.ops.cov_ops import outer_acc_bf16

    rng = np.random.default_rng(19)
    x = (rng.standard_normal((3, 9, 4)) + 1j * rng.standard_normal((3, 9, 4))
         ).astype(np.complex64)
    w = rng.random(3).astype(np.float32)
    ref = np.asarray(jnp.einsum("t,tfc,tfd->fcd", w, x, np.conj(x),
                                precision=jax.lax.Precision.HIGHEST))
    got = np.asarray(outer_acc_bf16(jnp.asarray(w), jnp.asarray(x)))
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 3e-2
    # hermitian by construction
    np.testing.assert_allclose(got, np.conj(np.swapaxes(got, -1, -2)),
                               rtol=1e-5, atol=1e-6)
