"""Parity tests for the TPU-native STFT kernels (disco_tpu.ops) against the
rFFT reference path."""
import numpy as np
import pytest

from disco_tpu.core.dsp import _stft_rfft, stft
from disco_tpu.ops import dft_matrices, stft_matmul, stft_pallas


@pytest.fixture(scope="module")
def sig():
    rng = np.random.default_rng(3)
    return rng.standard_normal((3, 40000)).astype("float32")


def test_dft_matrices_exact():
    Dre, Dim = dft_matrices(512)
    assert Dre.shape == (512, 257) and Dim.shape == (512, 257)
    # column 0 = DC: cos=1, sin=0
    np.testing.assert_allclose(Dre[:, 0], 1.0)
    np.testing.assert_allclose(Dim[:, 0], 0.0)
    # vs direct float64 DFT
    n = np.arange(512)
    ref = np.cos(-2 * np.pi * 5 * n / 512)
    np.testing.assert_allclose(Dre[:, 5], ref, atol=1e-6)


def test_stft_matmul_matches_rfft(sig):
    a = np.asarray(_stft_rfft(sig))
    b = np.asarray(stft_matmul(sig))
    assert np.max(np.abs(a - b)) / np.max(np.abs(a)) < 1e-5


def test_stft_pallas_matches_rfft(sig):
    a = np.asarray(_stft_rfft(sig))
    c = np.asarray(stft_pallas(sig, interpret=True))
    assert np.max(np.abs(a - c)) / np.max(np.abs(a)) < 1e-5


def test_stft_pallas_ragged_tail():
    """Frame counts not divisible by the tile must round-trip (pad + trim)."""
    rng = np.random.default_rng(4)
    x = rng.standard_normal((1, 12345)).astype("float32")
    a = np.asarray(_stft_rfft(x))
    c = np.asarray(stft_pallas(x, interpret=True, tile_t=32))
    assert a.shape == c.shape
    assert np.max(np.abs(a - c)) / np.max(np.abs(a)) < 1e-5


def test_stft_dispatch_explicit(sig):
    a = np.asarray(stft(sig, impl="rfft"))
    b = np.asarray(stft(sig, impl="matmul"))
    assert np.max(np.abs(a - b)) / np.max(np.abs(a)) < 1e-5


def test_stft_matmul_requires_half_overlap():
    with pytest.raises(AssertionError, match="50%"):
        stft_matmul(np.zeros((1, 4096), "float32"), n_fft=512, hop=128)


def test_istft_matmul_matches_ola(sig):
    from disco_tpu.core.dsp import _istft_ola
    from disco_tpu.ops import istft_matmul

    S = np.asarray(_stft_rfft(sig))
    a = np.asarray(_istft_ola(S, length=sig.shape[-1]))
    b = np.asarray(istft_matmul(S, length=sig.shape[-1]))
    assert np.max(np.abs(a - b)) < 1e-4
    # perfect reconstruction of the original signal
    assert np.max(np.abs(b - sig)) < 1e-4


def test_istft_matmul_length_padding(sig):
    from disco_tpu.ops import istft_matmul

    S = np.asarray(_stft_rfft(sig[:1]))
    longer = np.asarray(istft_matmul(S, length=sig.shape[-1] + 3000))
    assert longer.shape[-1] == sig.shape[-1] + 3000
    assert np.all(longer[:, -2000:] == 0.0)


def test_istft_dispatch_explicit(sig):
    from disco_tpu.core.dsp import istft

    S = np.asarray(_stft_rfft(sig))
    a = np.asarray(istft(S, length=sig.shape[-1], impl="irfft"))
    b = np.asarray(istft(S, length=sig.shape[-1], impl="matmul"))
    assert np.max(np.abs(a - b)) < 1e-4
    with pytest.raises(ValueError, match="unknown istft impl"):
        istft(S, length=100, impl="bogus")
