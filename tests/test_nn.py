"""L4 DNN stack tests: bricks shape math (cross-checked against torch),
CRNN forward, masked-MSE loss, training step convergence, SaveAndStop,
checkpoint/resume (reference dnn/ — SURVEY.md §2.5)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from disco_tpu.nn import (
    CRNN,
    RandomDataset,
    SaveAndStop,
    batch_iterator,
    build_crnn,
    cnn_output_dim,
    create_train_state,
    fit,
    get_model_name,
    load_checkpoint,
    loss_frame_bounds,
    make_step_fns,
    nanmean,
    reconstruction_loss,
    save_checkpoint,
)

CANON = dict(
    conv_kernels=3,
    conv_strides=1,
    pool_kernels=[(1, 4)] * 3,
    pool_strides=None,
    conv_padding=[(0, 1)] * 3,
)


# -- analytic shape math ----------------------------------------------------
def test_cnn_output_dim_canonical():
    # (21, 257) → (15, 4) for the canonical DISCO conv stack
    assert cnn_output_dim((21, 257), **CANON, n_layers=3) == (15, 4)


def test_cnn_output_dim_matches_torch():
    """The pure-function shape math must agree with an actual torch conv
    stack (the reference's get_output_dim ground truth)."""
    torch = pytest.importorskip("torch")
    nn_t = torch.nn

    layers = []
    chans = [1, 32, 64, 64]
    for i in range(3):
        layers += [
            nn_t.Conv2d(chans[i], chans[i + 1], 3, stride=1, padding=(0, 1)),
            nn_t.MaxPool2d((1, 4)),
        ]
    with torch.no_grad():
        out = nn_t.Sequential(*layers)(torch.zeros(1, 1, 21, 257))
    assert cnn_output_dim((21, 257), **CANON, n_layers=3) == tuple(out.shape[-2:])


@pytest.mark.parametrize(
    "hw,kern,pad,pool,expect_torch",
    [((30, 100), 5, 0, (2, 2), True), ((16, 64), (3, 5), (1, 2), (2, 4), True)],
)
def test_cnn_output_dim_matches_torch_other_configs(hw, kern, pad, pool, expect_torch):
    torch = pytest.importorskip("torch")
    conv = torch.nn.Conv2d(1, 4, kern, stride=1, padding=pad)
    pool_l = torch.nn.MaxPool2d(pool)
    with torch.no_grad():
        out = pool_l(conv(torch.zeros(1, 1, *hw)))
    got = cnn_output_dim(hw, [kern], [1], [pool], [None], conv_padding=[pad], n_layers=1)
    assert got == tuple(out.shape[-2:])


def test_loss_frame_bounds():
    # reference dnn/utils.py:189-209 semantics
    assert loss_frame_bounds(21, "all") == (0, 21)
    assert loss_frame_bounds(21, "mid") == (10, 11)
    assert loss_frame_bounds(21, "last") == (20, 21)
    assert loss_frame_bounds(21, 5) == (5, 6)


def test_crnn_loss_frames_all():
    model = CRNN(input_shape=(1, 21, 257))
    (ff_in, lf_in), (ff_out, lf_out) = model.loss_frames("all")
    assert (ff_in, lf_in) == (3, 18)  # (21-15)//2 .. (21+15)//2
    assert (ff_out, lf_out) == (0, 15)


# -- CRNN forward -----------------------------------------------------------
@pytest.mark.parametrize("n_ch", [1, 4])
def test_crnn_forward_shapes(n_ch):
    model, _ = build_crnn(n_ch=n_ch)
    x = jnp.ones((2, n_ch, 21, 257)) if n_ch > 1 else jnp.ones((2, 21, 257))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (2, 15, 257)  # 15 conv-cropped frames, 257-bin mask
    assert float(out.min()) >= 0.0 and float(out.max()) <= 1.0  # sigmoid


# -- loss -------------------------------------------------------------------
def test_nanmean_ignores_nans():
    v = jnp.array([1.0, jnp.nan, 3.0])
    assert float(nanmean(v)) == pytest.approx(2.0)


def test_reconstruction_loss_is_input_weighted_mse(rng):
    y_true = jnp.asarray(rng.random((4, 5)))
    y_pred = jnp.asarray(rng.random((4, 5)))
    x_in = jnp.asarray(rng.random((4, 5)))
    expected = np.mean(((np.asarray(y_pred) - np.asarray(y_true)) * np.asarray(x_in)) ** 2)
    assert float(reconstruction_loss(y_true, y_pred, x_in)) == pytest.approx(expected, rel=1e-6)


# -- training ---------------------------------------------------------------
def _tiny_model():
    return build_crnn(
        n_ch=1,
        n_freq=33,
        cnn_filters=(4, 4),
        conv_kernels=3,
        conv_strides=1,
        pool_kernels=[(1, 2)] * 2,
        pool_strides=None,
        conv_padding=[(0, 1)] * 2,
        rnn_units=(8,),
        ff_units=(33,),
    )


def test_train_step_reduces_loss(rng):
    model, tx = _tiny_model()
    x = rng.random((8, 21, 33)).astype("float32")
    y = (rng.random((8, 21, 33)) > 0.5).astype("float32")
    state = create_train_state(model, tx, x[:1])
    train_step, eval_step = make_step_fns(model, "all", n_freq=33)
    first = float(eval_step(state, jnp.asarray(x), jnp.asarray(y)))
    for _ in range(30):
        state, loss = train_step(state, jnp.asarray(x), jnp.asarray(y))
    assert float(loss) < first


def test_save_and_stop_gate():
    gate = SaveAndStop(patience=2, mode="min")
    assert gate.save_model_query(1.0)
    assert not gate.save_model_query(1.5)
    assert not gate.save_model_query(1.4)
    assert not gate.early_stop_query()
    assert not gate.save_model_query(1.3)
    assert gate.early_stop_query()
    with pytest.raises(ValueError):
        SaveAndStop(mode="other")


def test_checkpoint_roundtrip_and_resume(tmp_path, rng):
    model, tx = _tiny_model()
    x = rng.random((4, 21, 33)).astype("float32")
    state = create_train_state(model, tx, x[:1])
    train_step, _ = make_step_fns(model, "all", n_freq=33)
    y = rng.random((4, 21, 33)).astype("float32")
    state, _ = train_step(state, jnp.asarray(x), jnp.asarray(y))

    losses = np.array([0.5, 0.4, 0.0, 0.0])  # zero-padded history
    save_checkpoint(tmp_path / "ck.msgpack", state, losses, losses)
    fresh = create_train_state(model, tx, x[:1], seed=7)
    restored, tr, va = load_checkpoint(tmp_path / "ck.msgpack", fresh)
    assert list(tr) == [0.5, 0.4]  # trailing zeros trimmed (trim_zeros)
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(restored.params)[0]),
        np.asarray(jax.tree.leaves(state.params)[0]),
    )


def test_fit_smoke_with_random_dataset(tmp_path):
    """End-to-end epoch loop on the corpus-free fake dataset
    (reference RandomDataset, datasets.py:13-36)."""
    model, tx = _tiny_model()
    ds = RandomDataset((21, 33), (33, 21), length=12, rng=np.random.default_rng(0))

    def batches():
        # labels arrive (F, T) like saved masks; transpose to (T, F)
        for x, y in batch_iterator(ds, 6, rng=np.random.default_rng(1)):
            yield x, np.swapaxes(y, -2, -1)

    state = create_train_state(model, tx, next(batches())[0])
    state, tr, va, name = fit(
        model, state, batches, batches, n_epochs=2, save_path=tmp_path, verbose=False
    )
    assert (tmp_path / f"{name}_losses.npz").exists()
    assert (tmp_path / f"{name}_model.msgpack").exists()
    assert len(tr) == 2 and tr[0] > 0

    # resume: loss history splices
    state2 = create_train_state(model, tx, next(batches())[0])
    _, tr2, _, name2 = fit(
        model, state2, batches, batches, n_epochs=1,
        save_path=tmp_path, resume_from=tmp_path / f"{name}_model.msgpack", verbose=False,
    )
    assert name2.endswith("_retrain")
    assert len(tr2) >= 3


def test_get_model_name():
    assert len(get_model_name()) == 4
    assert get_model_name("models/ab3X_model.msgpack") == "ab3X_retrain"


# ------------------------------------------------------- 2-D RNN architecture
def test_rnn_mask_forward_shapes():
    from disco_tpu.nn.crnn import build_rnn

    model, tx = build_rnn(n_ch=1, win_len=21, n_freq=33)
    x = np.random.default_rng(0).random((2, 21, 33)).astype("float32")
    state = create_train_state(model, tx, x[:1])
    out = model.apply({"params": state.params, "batch_stats": state.batch_stats}, jnp.asarray(x))
    assert out.shape == (2, 21, 33)  # no conv cropping: frame-per-frame


def test_rnn_mask_freq_stacks_channels():
    from disco_tpu.nn.crnn import build_rnn

    model, tx = build_rnn(n_ch=4, win_len=21, n_freq=33)
    x = np.random.default_rng(0).random((2, 4, 21, 33)).astype("float32")
    state = create_train_state(model, tx, x[:1])
    out = model.apply({"params": state.params, "batch_stats": state.batch_stats}, jnp.asarray(x))
    assert out.shape == (2, 21, 33)


def test_rnn_mask_trains():
    from disco_tpu.nn.crnn import build_rnn

    rng = np.random.default_rng(1)
    model, tx = build_rnn(n_ch=1, win_len=11, n_freq=17, rnn_units=(16,), ff_units=(17,))
    x = rng.random((8, 11, 17)).astype("float32")
    y = (rng.random((8, 11, 17)) > 0.5).astype("float32")
    state = create_train_state(model, tx, x[:1])
    train_step, eval_step = make_step_fns(model, "all", n_freq=17)
    first = float(eval_step(state, jnp.asarray(x), jnp.asarray(y)))
    for _ in range(30):
        state, loss = train_step(state, jnp.asarray(x), jnp.asarray(y))
    assert float(loss) < first
