"""disco-lint (disco_tpu.analysis): per-rule true-positive + near-miss
fixtures, the suppression machinery, the reporters/CLI, and the repo-wide
self-run gate (the test twin of ``make lint-check``).

The fixture snippets are linted IN MEMORY under synthetic repo-relative
paths (rules scope by path), so each rule is pinned against at least one
violation it must catch and one nearby shape it must NOT flag."""
from __future__ import annotations

import json
import textwrap

import pytest

from disco_tpu import analysis
from disco_tpu.analysis import registries, report
from disco_tpu.analysis.registry import SUPPRESSION_RULE_ID


def lint(src, rel, rules=None, suppress=True):
    return analysis.lint_source(
        textwrap.dedent(src), rel, rules=rules, use_suppressions=suppress
    )


def rule_ids(res):
    return [f.rule for f in res.findings]


# -- registry ----------------------------------------------------------------
def test_rule_catalog_shape():
    rules = analysis.get_rules()
    assert len(rules) == 16
    assert sorted(rules) == [f"DL{i:03d}" for i in range(1, 17)]
    for rid, rule in rules.items():
        assert rule.id == rid and rule.name and rule.summary


# -- DL001 fence-discipline --------------------------------------------------
def test_dl001_flags_bare_block_until_ready():
    res = lint("import jax\njax.block_until_ready(x)\n",
               "disco_tpu/enhance/foo.py", rules={"DL001"})
    assert rule_ids(res) == ["DL001"]
    # bare from-import form too
    res = lint("from jax import block_until_ready\nblock_until_ready(x)\n",
               "disco_tpu/serve/foo.py", rules={"DL001"})
    assert rule_ids(res) == ["DL001"]


def test_dl001_allows_obs_and_milestones():
    for rel in ("disco_tpu/obs/foo.py", "disco_tpu/milestones.py"):
        res = lint("import jax\njax.block_until_ready(x)\n", rel, rules={"DL001"})
        assert rule_ids(res) == []


# -- DL002 host-readback-in-loop ---------------------------------------------
def test_dl002_flags_readback_in_loop():
    src = """
    from disco_tpu.utils import to_host
    def f(xs):
        return [to_host(x) for x in xs]
    def g(xs):
        out = []
        for x in xs:
            out.append(np.asarray(x))
        return out
    """
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL002"})
    assert rule_ids(res) == ["DL002", "DL002"]


def test_dl002_near_misses():
    src = """
    from disco_tpu.utils import to_host, device_get_tree
    def f(xs):
        host = device_get_tree(xs)     # sanctioned batched path, in no loop
        one = to_host(xs[0])           # outside any loop
        for x in host:
            use(x)
        return [device_get_tree_not_really for _ in host]
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL002"})) == []
    # the rule only scopes enhance/serve/nn — core is exempt
    loop = "def f(xs):\n    return [to_host(x) for x in xs]\n"
    assert rule_ids(lint(loop, "disco_tpu/core/foo.py", rules={"DL002"})) == []


def test_dl002_while_and_iter_expression_semantics():
    # the for-iterable runs once (not flagged); a while test re-runs (flagged)
    once = "def f(xs):\n    for x in to_host(xs):\n        use(x)\n"
    assert rule_ids(lint(once, "disco_tpu/nn/foo.py", rules={"DL002"})) == []
    per = "def f(xs):\n    while to_host(xs).any():\n        step()\n"
    assert rule_ids(lint(per, "disco_tpu/nn/foo.py", rules={"DL002"})) == ["DL002"]
    # a comprehension's FIRST generator iterable also runs exactly once —
    # one batched readback feeding a comprehension is the sanctioned shape
    comp = "def f(x):\n    return [g(v) for v in to_host(x)]\n"
    assert rule_ids(lint(comp, "disco_tpu/nn/foo.py", rules={"DL002"})) == []
    # ... but per-iteration positions (the element, inner generators) count
    inner = "def f(xs):\n    return [v for x in xs for v in to_host(x)]\n"
    assert rule_ids(lint(inner, "disco_tpu/nn/foo.py", rules={"DL002"})) == ["DL002"]


# -- DL003 raw-tunnel-transfer -----------------------------------------------
def test_dl003_flags_raw_device_get_put():
    src = "import jax\na = jax.device_get(x)\nb = jax.device_put(y)\n"
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL003"})
    assert rule_ids(res) == ["DL003", "DL003"]
    src = "from jax import device_get\na = device_get(x)\n"
    assert rule_ids(lint(src, "disco_tpu/serve/foo.py", rules={"DL003"})) == ["DL003"]


def test_dl003_near_misses():
    # device_get_tree is the sanctioned wrapper; a local device_get helper
    # NOT imported from jax is someone else's function
    src = """
    from disco_tpu.utils import device_get_tree
    from mylib import device_get
    a = device_get_tree(x)
    b = device_get(x)
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL003"})) == []
    # utils/transfer.py is the one allowed home of the raw primitive
    raw = "import jax\na = jax.device_get(x)\n"
    assert rule_ids(lint(raw, "disco_tpu/utils/transfer.py", rules={"DL003"})) == []


# -- DL004 atomic-write ------------------------------------------------------
def test_dl004_flags_raw_writes():
    src = """
    import numpy as np, pickle, soundfile as sf
    def persist(path, arr, obj, sig):
        np.save(path, arr)
        with open(path, "w") as fh:
            fh.write("x")
        with pickle_path.open(mode="wb") as fh:
            pickle.dump(obj, fh)
        sf.write(path, sig, 16000)
        path.write_bytes(b"x")
    """
    res = lint(src, "disco_tpu/datagen/foo.py", rules={"DL004"})
    # np.save, open("w"), Path.open(mode="wb"), pickle.dump, sf.write, write_bytes
    assert rule_ids(res) == ["DL004"] * 6


def test_dl004_module_qualified_open_variants():
    # gzip/io/codecs-style X.open carries the BUILTIN signature: the mode
    # sits at position 1, not 0 (which is where Path.open keeps it)
    src = "import gzip, io\ngzip.open(p, 'wb')\nio.open(p, 'w')\n"
    res = lint(src, "disco_tpu/runs/foo.py", rules={"DL004"})
    assert rule_ids(res) == ["DL004", "DL004"]
    ok = "import gzip\ngzip.open(p)\ngzip.open(p, 'rb')\n"
    assert rule_ids(lint(ok, "disco_tpu/runs/foo.py", rules={"DL004"})) == []


def test_dl004_near_misses():
    src = """
    import numpy as np
    from disco_tpu.io.atomic import save_npy_atomic, atomic_write
    def ok(path, arr):
        save_npy_atomic(path, arr)          # the sanctioned writer
        with open(path) as fh:              # read mode
            fh.read()
        with open(path, "a") as fh:         # append: the ledger protocol
            fh.write("line")
        with open(path, mode) as fh:        # non-literal mode: skipped
            fh.write("x")
        np.save_other(path, arr)            # not a numpy writer
    """
    assert rule_ids(lint(src, "disco_tpu/runs/foo.py", rules={"DL004"})) == []
    # outside the run-critical packages the rule does not apply
    raw = "import numpy as np\nnp.save(p, a)\n"
    assert rule_ids(lint(raw, "disco_tpu/core/foo.py", rules={"DL004"})) == []


# -- DL005 import-purity -----------------------------------------------------
def test_dl005_client_bans_jax_anywhere():
    src = "def f():\n    import jax\n    return jax\n"
    res = lint(src, "disco_tpu/serve/client.py", rules={"DL005"})
    assert rule_ids(res) == ["DL005"]
    res = lint("import torch\n", "disco_tpu/serve/protocol.py", rules={"DL005"})
    assert rule_ids(res) == ["DL005"]


def test_dl005_cli_bans_module_level_only():
    top = "import jax\n"
    assert rule_ids(lint(top, "disco_tpu/cli/foo.py", rules={"DL005"})) == ["DL005"]
    lazy = "def main():\n    import jax\n    return jax\n"
    assert rule_ids(lint(lazy, "disco_tpu/cli/foo.py", rules={"DL005"})) == []
    # outside client/cli scope, jax is the whole point of the package
    assert rule_ids(lint(top, "disco_tpu/serve/server.py", rules={"DL005"})) == []
    # near-miss: jaxtyping is not jax
    assert rule_ids(lint("import jaxtyping\n", "disco_tpu/cli/foo.py",
                         rules={"DL005"})) == []


# -- DL006 reference-citation ------------------------------------------------
def test_dl006_flags_missing_docstring_and_citation():
    src = '''
    """Module docstring with no citation."""
    def undocumented():
        return 1
    def uncited():
        """Does things."""
        return 2
    '''
    res = lint(src, "disco_tpu/core/foo.py", rules={"DL006"})
    assert rule_ids(res) == ["DL006", "DL006"]


def test_dl006_near_misses():
    src = '''
    """Module docstring with no citation."""
    def cited():
        """Twin of the reference loop (tango.py:528-639)."""
    def declared():
        """No reference counterpart: invented here."""
    def _private():
        pass
    '''
    assert rule_ids(lint(src, "disco_tpu/core/foo.py", rules={"DL006"})) == []
    # a module-level citation covers members that only describe themselves
    src = '''
    """Helpers for the reference main (tango.py:1-100)."""
    def helper():
        """Small helper."""
    '''
    assert rule_ids(lint(src, "disco_tpu/core/foo.py", rules={"DL006"})) == []
    # "preference" must not read as "reference"
    src = '''
    """Module docstring with no citation."""
    def f():
        """Sorts by user preference."""
    '''
    assert rule_ids(lint(src, "disco_tpu/core/foo.py", rules={"DL006"})) == ["DL006"]


# -- DL007 traced-float-literal ----------------------------------------------
def test_dl007_flags_int_literals():
    src = "streaming_tango(Y, m, m, mu=1)\ntango(Y, lambda_cor=0)\n"
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL007"})
    assert rule_ids(res) == ["DL007", "DL007"]


def test_dl007_near_misses():
    src = "f(mu=1.0)\nf(lambda_cor=0.99)\nf(mu=mu)\nf(nu=1)\nf(1)\n"
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL007"})) == []


# -- DL008 never-sigkill -----------------------------------------------------
def test_dl008_flags_kill_apis():
    src = """
    import os, signal
    os.kill(pid, signal.SIGTERM)
    proc.kill()
    proc.terminate()
    sig = signal.SIGKILL
    """
    res = lint(src, "disco_tpu/runs/foo.py", rules={"DL008"})
    assert rule_ids(res) == ["DL008"] * 4


def test_dl008_near_misses():
    src = """
    def kill(session):      # a local function named kill is not os.kill
        drop(session)
    kill(s)
    state = proc.terminated  # attribute access, not the call
    msg = "never SIGKILL"    # strings/docstrings are not references
    """
    assert rule_ids(lint(src, "disco_tpu/runs/foo.py", rules={"DL008"})) == []


# -- DL009 obs-event-kind ----------------------------------------------------
def test_dl009_flags_unregistered_kind():
    src = "from disco_tpu.obs import events as obs_events\nobs_events.record('clipz', rir=1)\n"
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL009"})
    assert rule_ids(res) == ["DL009"]


def test_dl009_near_misses():
    src = """
    from disco_tpu.obs import events as obs_events
    obs_events.record("clip", rir=1)      # registered kind
    obs_events.record(kind_var, rir=1)    # non-literal: skipped
    ledger.record(unit, "done")           # a DIFFERENT record() API
    plan.record(mode="offline")
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL009"})) == []


# -- DL010 chaos-seam --------------------------------------------------------
def test_dl010_flags_unregistered_seam():
    src = "from disco_tpu.runs import chaos\nchaos.tick('mid_wrote')\n"
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL010"})
    assert rule_ids(res) == ["DL010"]


def test_dl010_near_misses():
    src = """
    from disco_tpu.runs import chaos
    chaos.tick("mid_write")          # registered seam
    clock.tick(5)                    # non-string first arg: skipped
    accounting.fence_tick()          # different function
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL010"})) == []


# -- DL011 scan-unroll -------------------------------------------------------
def test_dl011_flags_scan_without_unroll_in_gated_modules():
    src = """
    import jax
    def f(xs):
        return jax.lax.scan(body, init, xs)
    """
    for rel in ("disco_tpu/enhance/streaming.py", "disco_tpu/serve/scheduler.py"):
        res = lint(src, rel, rules={"DL011"})
        assert rule_ids(res) == ["DL011"], rel
    # bare from-import form too
    src2 = "from jax.lax import scan\nscan(body, init, xs)\n"
    assert rule_ids(lint(src2, "disco_tpu/enhance/streaming.py",
                         rules={"DL011"})) == ["DL011"]


def test_dl011_near_misses():
    # explicit unroll (either choice) is the point of the rule
    src = """
    import jax
    jax.lax.scan(body, init, xs, unroll=4)
    jax.lax.scan(body, init, xs, unroll=1)
    sched.scan(job)                      # a different .scan API
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/streaming.py",
                         rules={"DL011"})) == []
    # non-gated modules may scan however they like (their outputs are not
    # bit-exactness-gated against a per-block reference)
    src2 = "import jax\njax.lax.scan(body, init, xs)\n"
    assert rule_ids(lint(src2, "disco_tpu/enhance/tango.py",
                         rules={"DL011"})) == []


def test_registries_extracted_from_source():
    root = analysis.repo_root()
    kinds = registries.event_kinds(root)
    assert {"manifest", "clip", "fault", "session", "span", "flight"} <= kinds
    seams = registries.chaos_seams(root)
    assert {"mid_write", "serve_tick", "between_blocks"} <= seams
    stages = registries.span_stages(root)
    assert {"client_block", "enqueue", "dispatch", "readback", "deliver",
            "tap", "train_batch"} <= stages
    sections = registries.status_sections(root)
    assert {"sessions", "counters", "gauges", "latency", "inflight"} <= sections


# -- DL014 span-stage / status-section ----------------------------------------
def test_dl014_flags_unregistered_span_stage():
    src = ("from disco_tpu.obs import trace as obs_trace\n"
           "obs_trace.span('despatch', ctx)\n")
    assert rule_ids(lint(src, "disco_tpu/serve/foo.py",
                         rules={"DL014"})) == ["DL014"]
    # the root() form (stage kwarg) is checked too
    src = ("from disco_tpu.obs import trace as obs_trace\n"
           "obs_trace.root(stage='client_blok')\n")
    assert rule_ids(lint(src, "disco_tpu/serve/foo.py",
                         rules={"DL014"})) == ["DL014"]
    # ... and the mint-then-commit form (record_span — the tap's shape)
    src = ("from disco_tpu.obs import trace as obs_trace\n"
           "obs_trace.record_span('tapp', ctx, parent=p)\n")
    assert rule_ids(lint(src, "disco_tpu/flywheel/foo.py",
                         rules={"DL014"})) == ["DL014"]


def test_dl014_flags_unregistered_status_section():
    src = ("from disco_tpu.serve.status import status_section\n"
           "status_section(payload, 'counterz')\n")
    assert rule_ids(lint(src, "disco_tpu/cli/foo.py",
                         rules={"DL014"})) == ["DL014"]


def test_dl014_near_misses():
    src = """
    from disco_tpu.obs import trace as obs_trace
    from disco_tpu.serve.status import status_section
    obs_trace.span("dispatch", ctx)          # registered hop
    obs_trace.root("client_block", seq=1)    # registered root
    obs_trace.span(stage_var, ctx)           # non-literal: skipped
    status_section(payload, "counters")      # registered section
    status_section(payload, name_var)        # non-literal: skipped
    tree.span(3)                             # a DIFFERENT span() API
    math.root(x)                             # a DIFFERENT root()
    """
    assert rule_ids(lint(src, "disco_tpu/serve/foo.py", rules={"DL014"})) == []


# -- suppressions ------------------------------------------------------------
_VIOLATION = "import jax\njax.block_until_ready(x)  # disco-lint: disable=DL001 -- pinned fixture\n"


def test_suppression_same_line_and_next_line():
    res = lint(_VIOLATION, "disco_tpu/enhance/foo.py", rules={"DL001"})
    assert rule_ids(res) == []
    assert [(f.rule, just) for f, just in res.suppressed] == [("DL001", "pinned fixture")]
    above = ("import jax\n"
             "# disco-lint: disable=DL001 -- fixture, comment-above form\n"
             "jax.block_until_ready(x)\n")
    res = lint(above, "disco_tpu/enhance/foo.py", rules={"DL001"})
    assert rule_ids(res) == [] and len(res.suppressed) == 1


def test_file_disable_suppresses_whole_file():
    src = ("# disco-lint: file-disable=DL001 -- fixture-wide waiver\n"
           "import jax\n"
           "jax.block_until_ready(x)\n"
           "jax.block_until_ready(y)\n")
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL001"})
    assert rule_ids(res) == [] and len(res.suppressed) == 2


def test_suppression_without_justification_is_a_finding():
    src = "import jax\njax.block_until_ready(x)  # disco-lint: disable=DL001\n"
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL001"})
    # the waiver is void (DL001 still fires) AND the bad comment is reported
    assert sorted(rule_ids(res)) == [SUPPRESSION_RULE_ID, "DL001"]


def test_unknown_rule_id_and_unsuppressable_dl000():
    src = "x = 1  # disco-lint: disable=DL999 -- no such rule\n"
    res = lint(src, "disco_tpu/enhance/foo.py")
    assert rule_ids(res) == [SUPPRESSION_RULE_ID]
    src = "x = 1  # disco-lint: disable=DL000 -- nice try\n"
    res = lint(src, "disco_tpu/enhance/foo.py")
    assert SUPPRESSION_RULE_ID in rule_ids(res)


def test_unused_suppression_is_a_finding():
    src = "x = 1  # disco-lint: disable=DL001 -- waives nothing\n"
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL001"})
    assert rule_ids(res) == [SUPPRESSION_RULE_ID]
    assert "unused suppression" in res.findings[0].message


def test_no_suppressions_mode_reports_everything():
    res = lint(_VIOLATION, "disco_tpu/enhance/foo.py", rules={"DL001"},
               suppress=False)
    assert rule_ids(res) == ["DL001"] and res.suppressed == []


# -- reporters / CLI ---------------------------------------------------------
def test_json_reporter_schema():
    res = lint(_VIOLATION + "import jax.numpy\njax.device_get(q)\n",
               "disco_tpu/enhance/foo.py", rules={"DL001", "DL003"})
    doc = json.loads(report.format_json(res))
    assert set(doc) == {"clean", "counts", "findings", "suppressed"}
    assert doc["clean"] is (not doc["findings"])
    assert doc["counts"]["by_rule"].get("DL003") == 1
    assert doc["suppressed"][0]["justification"] == "pinned fixture"
    f = doc["findings"][0]
    assert {"path", "line", "col", "rule", "name", "message"} <= set(f)


def test_text_reporter_line_format():
    res = lint("import jax\njax.device_get(x)\n", "disco_tpu/enhance/foo.py",
               rules={"DL003"})
    text = report.format_text(res)
    assert "disco_tpu/enhance/foo.py:2:0: DL003 [raw-tunnel-transfer]" in text
    assert "1 finding(s)" in text


def test_cli_end_to_end(tmp_path, capsys):
    from disco_tpu.analysis import cli

    bad = tmp_path / "bad.py"
    bad.write_text("f(mu=1)\n")
    assert cli.main([str(bad), "--format", "json", "--rules", "DL007"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["counts"]["by_rule"] == {"DL007": 1}
    assert cli.main([str(bad), "--rules", "DL001"]) == 0
    assert cli.main(["--list-rules"]) == 0
    assert "DL010" in capsys.readouterr().out
    assert cli.main([str(bad), "--rules", "DLXXX"]) == 2
    assert cli.main([str(tmp_path / "missing.py")]) == 2


def test_rules_filter_does_not_flag_other_rules_suppressions():
    """A focused --rules run must not report the shipped waivers of
    NON-selected rules as unused DL000 (the repo stays clean under any
    filter)."""
    res = analysis.lint_paths(rules={"DL005"})
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    # the unused check still works when the suppressed rule IS selected
    src = "x = 1  # disco-lint: disable=DL001 -- waives nothing\n"
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL001"})
    assert rule_ids(res) == [SUPPRESSION_RULE_ID]
    # ... and stays quiet when it is not
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL005"})
    assert rule_ids(res) == []


def test_outside_root_targets_are_reported(tmp_path, capsys):
    from disco_tpu.analysis import cli

    f = tmp_path / "loose.py"
    f.write_text("x = 1\n")
    res = analysis.lint_paths([str(f)], rules={"DL001"})
    assert res.outside == ["loose.py"]
    assert cli.main([str(f), "--rules", "DL001"]) == 0
    assert "outside the repo root" in capsys.readouterr().err


# -- DL015 bare-thread-primitive ---------------------------------------------
def test_dl015_flags_unregistered_thread_timer_and_lock():
    src = """
    import threading
    _rogue_lock = threading.Lock()
    def nope(): pass
    t = threading.Thread(target=nope)
    threading.Timer(2.0, nope)
    """
    res = lint(src, "disco_tpu/foo.py", rules={"DL015"})
    assert rule_ids(res) == ["DL015"] * 3
    assert "_rogue_lock" in res.findings[0].message       # unregistered id
    assert "race-role entry point" in res.findings[1].message
    # an anonymous (unassigned) lock can never be registered
    res = lint("import threading\nlocks = [threading.Lock()]\n",
               "disco_tpu/foo.py", rules={"DL015"})
    assert rule_ids(res) == ["DL015"]
    assert "anonymous" in res.findings[0].message or \
        "not a module-level name" in res.findings[0].message


def test_dl015_near_misses():
    # a registered role entry-point leaf as target is clean anywhere...
    src = """
    import threading
    class Tap:
        def _run(self): pass
        def start(self):
            threading.Thread(target=self._run).start()
    """
    assert rule_ids(lint(src, "disco_tpu/foo.py", rules={"DL015"})) == []
    # ...a registered lock attribute on its registered module:Class too
    src = """
    import threading
    class CorpusTap:
        def __init__(self):
            self._lock = threading.Lock()
    """
    assert rule_ids(lint(src, "disco_tpu/flywheel/tap.py",
                         rules={"DL015"})) == []
    # somebody else's Lock is not threading's
    src = "from mylib import Lock\nx = Lock()\n"
    assert rule_ids(lint(src, "disco_tpu/foo.py", rules={"DL015"})) == []
    # a file that never imports threading is skipped wholesale
    src = "def Thread(target): pass\nThread(target=1)\n"
    assert rule_ids(lint(src, "disco_tpu/foo.py", rules={"DL015"})) == []


def test_dl015_timer_with_registered_leaf_is_clean():
    src = """
    import threading
    class DispatchDeadlineLike:
        def _fire(self): pass
        def arm(self):
            self._timer = threading.Timer(1.0, self._fire)
    """
    assert rule_ids(lint(src, "disco_tpu/foo.py", rules={"DL015"})) == []


# -- DL016 fused-solver-selection ---------------------------------------------
def test_dl016_flags_direct_fused_op_calls():
    src = """
    from disco_tpu.ops.mwf_ops import rank1_gevd_fused
    def solve(Rss, Rnn):
        return rank1_gevd_fused(Rss, Rnn, impl="pallas")
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py",
                         rules={"DL016"})) == ["DL016"]
    # the resolver and the raw kernels count too, attribute form included
    src2 = """
    from disco_tpu.ops import mwf_ops
    impl = mwf_ops.resolve_mwf_impl("auto")
    w, t1 = mwf_ops.fused_mwf_pallas(Rss, Rnn)
    """
    assert rule_ids(lint(src2, "disco_tpu/serve/scheduler.py",
                         rules={"DL016"})) == ["DL016", "DL016"]


def test_dl016_flags_fused_literal_comparisons():
    src = """
    def pick(solver):
        if solver == "fused":
            return 1
        if solver in ("fused-pallas", "eigh"):
            return 2
        return 0
    """
    assert rule_ids(lint(src, "disco_tpu/cli/foo.py",
                         rules={"DL016"})) == ["DL016", "DL016"]
    # the ':N' suffixed spellings are the same family
    src2 = 'ok = spec != "fused:8"\n'
    assert rule_ids(lint(src2, "disco_tpu/enhance/foo.py",
                         rules={"DL016"})) == ["DL016"]


def test_dl016_near_misses():
    # passing a fused spec AS DATA through the dispatch table is the
    # sanctioned path; other string comparisons are untouched; ops/ and
    # the dispatch table itself are exempt
    src = """
    from disco_tpu.beam.filters import rank1_gevd
    def run(Rss, Rnn):
        w, _ = rank1_gevd(Rss, Rnn, solver="fused")
        mode = "offline"
        if mode == "streaming":
            pass
        return w
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL016"})) == []
    src2 = """
    from disco_tpu.ops.mwf_ops import rank1_gevd_fused
    def dispatch(base):
        if base == "fused":
            return rank1_gevd_fused
    """
    assert rule_ids(lint(src2, "disco_tpu/ops/mwf_ops.py", rules={"DL016"})) == []
    assert rule_ids(lint(src2, "disco_tpu/beam/filters.py", rules={"DL016"})) == []


def test_dl016_flags_startswith_family_probes():
    # the step-1 fusion round's scope extension: prefix probes are the
    # same ad-hoc family check as literal comparisons
    src = """
    def pick(solver):
        if solver.startswith("fused"):
            return 1
        return 0
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py",
                         rules={"DL016"})) == ["DL016"]
    # the ':N'-suffixed and dashed spellings are the same family
    src2 = 'chained = spec.startswith("fused-pallas")\n'
    assert rule_ids(lint(src2, "disco_tpu/serve/foo.py",
                         rules={"DL016"})) == ["DL016"]


def test_dl016_startswith_and_predicate_near_misses():
    # is_fused_spec IS the sanctioned family predicate (a call, not a
    # comparison); startswith against non-family strings stays untouched;
    # the grammar module itself is exempt
    src = """
    from disco_tpu.solver_spec import is_fused_spec
    def pick(solver):
        if is_fused_spec(solver):
            return 1
        if name.startswith("fused_mwf"):
            return 2
        if path.startswith("ops/"):
            return 3
        return 0
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL016"})) == []
    src2 = """
    def is_fused_spec(v):
        return parse_solver_spec(v)[0] in FUSED_IMPLS
    ok = base == "fused"
    """
    assert rule_ids(lint(src2, "disco_tpu/solver_spec.py", rules={"DL016"})) == []


# -- the repo itself ---------------------------------------------------------
def test_repo_lints_clean():
    """The self-run gate: zero unsuppressed findings over the default
    targets, and every suppression carries a non-empty justification."""
    res = analysis.lint_paths()
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    assert res.n_files > 100  # the walk really covered the tree
    for f, just in res.suppressed:
        assert just.strip(), f"unjustified suppression for {f.render()}"


def test_shipped_suppressions_are_load_bearing():
    """Ignoring the suppression comments must re-surface real findings in
    the files that carry them — i.e. removing any rule's suppression set
    makes the gate fail (acceptance criterion)."""
    res = analysis.lint_paths(use_suppressions=False)
    got = {(f.rule, f.path) for f in res.findings}
    expected = {
        ("DL001", "__graft_entry__.py"),          # driver-contract fences
        ("DL003", "__graft_entry__.py"),          # CPU-mesh device_put
        ("DL002", "disco_tpu/enhance/stream_check.py"),  # per-block oracle
        ("DL002", "disco_tpu/enhance/driver.py"), # host time_domain unpack
        ("DL002", "disco_tpu/serve/scheduler.py"),# wire-decoded host arrays
        ("DL004", "disco_tpu/runs/check.py"),     # deliberate bit rot
    }
    missing = expected - got
    assert not missing, f"suppressed sites vanished (or rules stopped firing): {missing}"


@pytest.mark.parametrize(
    "src,rel,rule",
    [
        # reverting the zexport atomic-write fix would re-flag np.save
        ("import numpy as np\nfor k in range(4):\n    np.save(p, arr[k])\n",
         "disco_tpu/enhance/zexport.py", "DL004"),
        # reverting the driver's batched readback would re-flag the loop
        ("from disco_tpu.utils import resilient_to_host\n"
         "for k in range(4):\n    z = resilient_to_host(res.z_y[k])\n",
         "disco_tpu/enhance/driver.py", "DL002"),
    ],
)
def test_satellite_fix_reverts_fail_the_gate(src, rel, rule):
    res = lint(src, rel, rules={rule})
    assert rule in rule_ids(res)


# -- DL012 fused-magnitude-precision -----------------------------------------
def test_dl012_flags_abs_of_stft():
    src = """
    import jax.numpy as jnp
    from disco_tpu.core.dsp import stft
    def features(y):
        return jnp.abs(stft(y))
    """
    res = lint(src, "disco_tpu/enhance/foo.py", rules={"DL012"})
    assert rule_ids(res) == ["DL012"]
    # np.abs over the matmul/pallas entry points counts too
    src2 = """
    import numpy as np
    from disco_tpu.ops.stft_ops import stft_matmul
    mag = np.abs(stft_matmul(y))
    """
    assert rule_ids(lint(src2, "disco_tpu/nn/feats.py",
                         rules={"DL012"})) == ["DL012"]


def test_dl012_flags_bf16_cast_literals():
    src = """
    import jax.numpy as jnp
    def f(x):
        return x.astype("bfloat16")
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py",
                         rules={"DL012"})) == ["DL012"]
    src2 = """
    import jax.numpy as jnp
    def f(x):
        y = x.astype(jnp.bfloat16)
        z = jnp.zeros((3,), dtype=jnp.bfloat16)
        return y, z
    """
    assert rule_ids(lint(src2, "disco_tpu/serve/foo.py",
                         rules={"DL012"})) == ["DL012", "DL012"]


def test_dl012_near_misses():
    # abs of a VARIABLE holding a spec (not a nested stft call), f32 casts,
    # and the precision= seam itself are all fine
    src = """
    import jax.numpy as jnp
    from disco_tpu.core.dsp import stft
    def f(y):
        spec = stft(y)
        mag = jnp.abs(spec)
        g = mag.astype("float32")
        return tango(spec, precision="bf16")   # requesting the lane is the point
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL012"})) == []
    # inside ops/ both shapes ARE the implementation — exempt
    src2 = """
    import jax.numpy as jnp
    from disco_tpu.core.dsp import stft
    def stft_with_mag(y):
        return jnp.abs(stft(y)), y.astype(jnp.bfloat16)
    """
    assert rule_ids(lint(src2, "disco_tpu/ops/stft_ops.py", rules={"DL012"})) == []


# -- DL013 adhoc-transport-retry ----------------------------------------------
def test_dl013_flags_retry_loops_swallowing_transport_errors():
    # the classic while-retry that swallows and goes again
    src = """
    def fetch(x):
        while True:
            try:
                return readback(x)
            except OSError:
                continue
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py",
                         rules={"DL013"})) == ["DL013"]
    # attempt-counting for-range with a transport tuple and a sleep
    src = """
    def fetch(x):
        for attempt in range(5):
            try:
                return readback(x)
            except (ConnectionError, TimeoutError):
                time.sleep(0.1)
    """
    assert rule_ids(lint(src, "disco_tpu/serve/foo.py",
                         rules={"DL013"})) == ["DL013"]
    # socket.error spelling counts too
    src = """
    def fetch(x):
        while not done:
            try:
                step(x)
            except socket.error:
                pass
    """
    assert rule_ids(lint(src, "disco_tpu/io/foo.py",
                         rules={"DL013"})) == ["DL013"]


def test_dl013_near_misses():
    # a fail-fast handler (re-raise) is not a retry
    src = """
    def fetch(x):
        while True:
            try:
                return readback(x)
            except OSError as e:
                raise RuntimeError("dead tunnel") from e
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL013"})) == []
    # a break leaves the loop: bounded, not a silent retry
    src = """
    def fetch(x):
        while True:
            try:
                return readback(x)
            except OSError:
                break
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL013"})) == []
    # skipping a failed ITEM of a for-each is different work next
    # iteration, not a re-attempt of the same crossing
    src = """
    def load_all(paths):
        out = []
        for p in paths:
            try:
                out.append(read(p))
            except OSError:
                continue
        return out
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL013"})) == []
    # non-transport exceptions are out of scope
    src = """
    def parse(xs):
        while True:
            try:
                return decode(xs)
            except ValueError:
                xs = fix(xs)
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL013"})) == []
    # one attempt inside a try (loop INSIDE the try) is not a retry loop
    src = """
    def drain(q):
        try:
            while q:
                send(q.pop())
        except OSError:
            pass
    """
    assert rule_ids(lint(src, "disco_tpu/enhance/foo.py", rules={"DL013"})) == []


def test_dl013_allowed_files_are_exempt():
    src = """
    def connect(addr):
        while True:
            try:
                return dial(addr)
            except OSError:
                time.sleep(0.05)
    """
    # the one sanctioned implementation...
    assert rule_ids(lint(src, "disco_tpu/utils/resilience.py",
                         rules={"DL013"})) == []
    # ...and the numpy-only client files, which the DL005 purity contract
    # bars from importing utils.resilience at all
    assert rule_ids(lint(src, "disco_tpu/serve/client.py",
                         rules={"DL013"})) == []
