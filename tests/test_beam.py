"""Parity tests for the beamforming core against the scipy/NumPy oracle."""
import numpy as np
import pytest

import jax.numpy as jnp

from disco_tpu.beam import (
    frame_mean_covariance,
    masked_covariances,
    smoothed_covariance,
    get_filter_type,
    intern_filter,
)
from tests.reference_impls import covariances_np, intern_filter_np


def random_spd(rng, C, scale=1.0):
    """Random hermitian positive-definite matrix."""
    X = rng.normal(size=(C, 2 * C)) + 1j * rng.normal(size=(C, 2 * C))
    return scale * (X @ X.conj().T) / (2 * C) + 0.1 * np.eye(C)


# ----------------------------------------------------------------- covariance
def test_frame_mean_covariance_parity(rng):
    a = (rng.normal(size=(3, 5, 40)) + 1j * rng.normal(size=(3, 5, 40))).astype(np.complex64)
    got = np.asarray(frame_mean_covariance(jnp.asarray(a)))
    want = covariances_np(a.astype(np.complex128))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_masked_covariances(rng):
    y = (rng.normal(size=(4, 5, 30)) + 1j * rng.normal(size=(4, 5, 30))).astype(np.complex64)
    m = rng.uniform(size=(5, 30)).astype(np.float32)
    Rss, Rnn = masked_covariances(jnp.asarray(y), jnp.asarray(m))
    want_s = covariances_np((m[None] * y).astype(np.complex128))
    want_n = covariances_np(((1 - m[None]) * y).astype(np.complex128))
    np.testing.assert_allclose(np.asarray(Rss), want_s, atol=1e-4)
    np.testing.assert_allclose(np.asarray(Rnn), want_n, atol=1e-4)


def test_smoothed_covariance(rng):
    C = 4
    R = np.zeros((C, C), np.complex64)
    x = (rng.normal(size=C) + 1j * rng.normal(size=C)).astype(np.complex64)
    got = np.asarray(smoothed_covariance(jnp.asarray(R), jnp.asarray(x), 0.95))
    want = 0.95 * R + 0.05 * np.outer(x, np.conj(x))
    np.testing.assert_allclose(got, want, atol=1e-5)
    # masked variant
    got_m = np.asarray(
        smoothed_covariance(jnp.asarray(R), jnp.asarray(x), 0.95, mask=jnp.asarray(0.5))
    )
    np.testing.assert_allclose(got_m, 0.5 * want, atol=1e-5)


# -------------------------------------------------------------------- filters
@pytest.mark.parametrize(
    "name,expected", [("gevd", ("gevd", "full")), ("rank2-gevd", ("gevd", 2)),
                      ("rank12-gevd", ("gevd", 12)),
                      ("r1-mwf", ("r1-mwf", None)), ("mwf", ("mwf", None))]
)
def test_get_filter_type(name, expected):
    assert get_filter_type(name) == expected


def test_get_filter_type_rejects_malformed():
    with pytest.raises(ValueError):
        get_filter_type("rankX-gevd")


@pytest.mark.parametrize("C", [2, 4, 7])
@pytest.mark.parametrize("ftype", ["gevd", "r1-mwf", "mwf"])
def test_filter_parity(rng, C, ftype):
    Rxx = random_spd(rng, C, scale=2.0)
    Rnn = random_spd(rng, C)
    W, t1 = intern_filter(
        jnp.asarray(Rxx, jnp.complex64), jnp.asarray(Rnn, jnp.complex64), 1.0, ftype, 1
    )
    W_ref, t1_ref = intern_filter_np(Rxx, Rnn, 1.0, ftype, 1)
    np.testing.assert_allclose(np.asarray(W), W_ref, atol=5e-3)
    if ftype == "gevd":
        np.testing.assert_allclose(np.asarray(t1), t1_ref, atol=5e-3)


@pytest.mark.parametrize("rank", [1, 2, "full"])
def test_gevd_rank_parity(rng, rank):
    C = 5
    Rxx = random_spd(rng, C, scale=3.0)
    Rnn = random_spd(rng, C)
    W, _ = intern_filter(
        jnp.asarray(Rxx, jnp.complex64), jnp.asarray(Rnn, jnp.complex64), 1.0, "gevd", rank
    )
    W_ref, _ = intern_filter_np(Rxx, Rnn, 1.0, "gevd", rank)
    np.testing.assert_allclose(np.asarray(W), W_ref, atol=5e-3)


def test_gevd_batched(rng):
    """The filter must vectorize over (node, freq) leading axes."""
    K, F, C = 3, 8, 4
    Rxx = np.stack([[random_spd(rng, C, 2.0) for _ in range(F)] for _ in range(K)])
    Rnn = np.stack([[random_spd(rng, C) for _ in range(F)] for _ in range(K)])
    W, t1 = intern_filter(
        jnp.asarray(Rxx, jnp.complex64), jnp.asarray(Rnn, jnp.complex64), 1.0, "gevd", 1
    )
    assert W.shape == (K, F, C) and t1.shape == (K, F, C)
    for k in range(K):
        for f in range(F):
            W_ref, _ = intern_filter_np(Rxx[k, f], Rnn[k, f], 1.0, "gevd", 1)
            np.testing.assert_allclose(np.asarray(W[k, f]), W_ref, atol=5e-3)


def test_gevd_mask_derived_covariances(rng):
    """End-to-end: mask-weighted covariances from a synthetic mixture give a
    filter matching the float64 oracle (the tango step-1 inner computation)."""
    C, F, T = 4, 6, 50
    s = rng.normal(size=(C, F, T)) + 1j * rng.normal(size=(C, F, T))
    n = 0.5 * (rng.normal(size=(C, F, T)) + 1j * rng.normal(size=(C, F, T)))
    y = s + n
    m = np.clip(np.abs(s[0]) / (np.abs(s[0]) + np.abs(n[0])), 0, 1)
    Rss, Rnn = masked_covariances(jnp.asarray(y, jnp.complex64), jnp.asarray(m, jnp.float32))
    W, _ = intern_filter(Rss, Rnn, 1.0, "gevd", 1)
    Rss_ref = covariances_np(m[None] * y)
    Rnn_ref = covariances_np((1 - m[None]) * y)
    for f in range(F):
        W_ref, _ = intern_filter_np(Rss_ref[f], Rnn_ref[f], 1.0, "gevd", 1)
        np.testing.assert_allclose(np.asarray(W[f]), W_ref, atol=2e-2)


def test_gevd_degenerate_bins_stay_finite():
    """Hardware regression (round 2): on TPU the default bf16 matmul
    precision could leave frame-mean noise covariances numerically
    indefinite, so Cholesky emitted NaN bins and step-2 outputs were
    poisoned.  Two defenses are pinned here: covariance einsums run at
    HIGHEST precision, and gevd_mwf falls back to the e1 selector on any
    non-finite bin instead of propagating NaN."""
    import jax.numpy as jnp

    from disco_tpu.beam.filters import gevd_mwf

    rng = np.random.default_rng(0)
    C = 5
    X = rng.standard_normal((257, C, 30)) + 1j * rng.standard_normal((257, C, 30))
    Rxx = np.einsum("fct,fdt->fcd", X, X.conj()) / 30
    # indefinite noise covariance: a healthy Gram minus too much diagonal
    Rnn = Rxx.copy()
    Rnn[:50] -= 2.0 * np.eye(C)[None]
    w, t1 = gevd_mwf(jnp.asarray(Rxx, jnp.complex64), jnp.asarray(Rnn, jnp.complex64), rank=1)
    assert bool(jnp.isfinite(w.real).all() & jnp.isfinite(w.imag).all())
    assert bool(jnp.isfinite(t1.real).all())


def test_gevd_power_matches_eigh_rank1():
    """The power-iteration rank-1 solver reproduces the eigh-based filter
    wherever the speech field has a dominant direction (here: rank-1 speech
    + white noise — agreement at f32 roundoff).  On hardware the full
    pipeline is HBM-bound, so this is an accuracy contract, not a speed
    claim."""
    import jax.numpy as jnp

    from disco_tpu.beam.filters import gevd_mwf, gevd_mwf_power, intern_filter

    rng = np.random.default_rng(1)
    F, C, T = 64, 5, 200
    src = rng.standard_normal((F, T))
    gains = rng.standard_normal((C, 1, 1))
    S = gains * src[None] + 0.02 * rng.standard_normal((C, F, T))
    N = 0.5 * rng.standard_normal((C, F, T))
    Rxx = jnp.asarray(np.einsum("cft,dft->fcd", S, S) / T, jnp.complex64)
    Rnn = jnp.asarray(np.einsum("cft,dft->fcd", N, N) / T, jnp.complex64)
    w_e, t1_e = gevd_mwf(Rxx, Rnn, rank=1)
    w_p, t1_p = gevd_mwf_power(Rxx, Rnn)
    assert float(jnp.linalg.norm(w_p - w_e) / jnp.linalg.norm(w_e)) < 1e-4
    assert float(jnp.linalg.norm(t1_p - t1_e) / jnp.linalg.norm(t1_e)) < 1e-4
    # dispatcher surface
    w_d, _ = intern_filter(Rxx, Rnn, ftype="gevd-power", rank=1)
    np.testing.assert_allclose(np.asarray(w_d), np.asarray(w_p), atol=1e-7)
    with pytest.raises(ValueError, match="rank-1 only"):
        intern_filter(Rxx, Rnn, ftype="gevd-power", rank=2)


def test_rank1_gevd_sanitize_flag():
    """Degenerate bins (NaN covariances) yield the e1 selector when
    sanitize=True (default) and surface as non-finite when sanitize=False —
    the contract the streaming ffill fallback depends on (it must see the
    failure to keep the previous block's filter)."""
    import jax.numpy as jnp

    from disco_tpu.beam.filters import rank1_gevd

    rng = np.random.default_rng(2)
    F, C, T = 8, 3, 50
    X = rng.standard_normal((C, F, T))
    Rxx = np.einsum("cft,dft->fcd", X, X) / T
    Rnn = np.eye(C)[None] * np.ones((F, 1, 1))
    Rnn = np.array(Rnn)
    Rnn[2] = np.nan  # poison one bin
    Rxx_j, Rnn_j = jnp.asarray(Rxx, jnp.complex64), jnp.asarray(Rnn, jnp.complex64)
    for solver in ("eigh", "power", "power:24"):
        w_s, t1_s = rank1_gevd(Rxx_j, Rnn_j, solver=solver)
        assert bool(jnp.isfinite(w_s.real).all()), solver
        np.testing.assert_allclose(np.asarray(w_s)[2], np.eye(C, 1)[:, 0], atol=0, err_msg=solver)
        w_r, _ = rank1_gevd(Rxx_j, Rnn_j, solver=solver, sanitize=False)
        assert not bool(jnp.isfinite(w_r.real)[2].all()), solver
        assert bool(jnp.isfinite(w_r.real)[:2].all()), solver
    with pytest.raises(ValueError, match="unknown GEVD solver"):
        rank1_gevd(Rxx_j, Rnn_j, solver="qr")


def test_get_filter_type_gevd_power():
    from disco_tpu.beam.filters import get_filter_type

    assert get_filter_type("gevd-power") == ("gevd-power", 1)
    assert get_filter_type("rank3-gevd") == ("gevd", 3)
    with pytest.raises(ValueError):
        get_filter_type("rankX-gevd")
