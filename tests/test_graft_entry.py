"""Pin the driver-contract entry points: entry() compiles; dryrun_multichip
runs the three sharded programs on the 8-virtual-device CPU mesh."""
import jax
import pytest


def test_entry_compiles():
    from __graft_entry__ import entry

    fn, args = entry()
    jax.jit(fn).lower(*args).compile()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device test mesh")
@pytest.mark.slow
def test_dryrun_multichip():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)
