"""Tests for disco_tpu.sim.signals on a tiny synthetic wav corpus."""
import numpy as np
import pytest

from disco_tpu.io import write_wav
from disco_tpu.sim import InterferentSpeakersSetup, SpeechAndNoiseSetup, normalize_to_var

FS = 16000


@pytest.fixture
def corpus(tmp_path):
    """LibriSpeech-shaped corpus: {speaker}/{chapter}/{utt}.wav + noises."""
    rng = np.random.default_rng(0)
    speech_files = []
    for spk in ("101", "102", "103"):
        d = tmp_path / "speech" / spk / "1"
        d.mkdir(parents=True)
        f = d / f"{spk}-1-0001.wav"
        # 6 s of modulated noise (speech-like energy bursts)
        t = np.arange(6 * FS) / FS
        env = (np.sin(2 * np.pi * 1.3 * t) > 0).astype(np.float64)
        write_wav(f, 0.3 * env * rng.standard_normal(len(t)), FS)
        speech_files.append(str(f))
    noise_dir = tmp_path / "noise"
    noise_dir.mkdir()
    noise_files = []
    for i in range(2):
        f = noise_dir / f"n{i}.wav"
        write_wav(f, 0.2 * rng.standard_normal(8 * FS), FS)
        noise_files.append(str(f))
    return speech_files, noise_files


def _setup(corpus, rng=None):
    speech, noise = corpus
    return SpeechAndNoiseSetup(
        target_list=speech,
        talkers_list=speech,
        noises_dict={"fs": noise},
        duration_range=(5, 10),
        var_tar=10 ** (-23 / 10),
        snr_dry_range=[[0, 0]],
        snr_cnv_range=(-10, 15),
        min_delta_snr=0,
        rng=rng or np.random.default_rng(1),
    )


def test_normalize_to_var(corpus):
    rng = np.random.default_rng(0)
    x = np.concatenate([np.zeros(FS), rng.standard_normal(2 * FS)])
    var_tar = 0.005
    y, vad = normalize_to_var(x, var_tar)
    assert np.var(y[vad == 1]) == pytest.approx(var_tar, rel=0.1)


def test_get_target_segment(corpus):
    setup = _setup(corpus)
    sig, vad, fs = setup.get_target_segment(corpus[0][0])
    assert fs == FS
    # 1 s lead silence
    np.testing.assert_array_equal(sig[:FS], 0)
    np.testing.assert_array_equal(vad[:FS], 0)
    assert len(sig) == len(vad)
    # active-sample variance == var_tar
    assert np.var(sig[vad == 1]) == pytest.approx(setup.var_tar, rel=0.15)
    assert setup.target_duration == pytest.approx(7.0, abs=0.1)


def test_short_target_rejected(corpus, tmp_path):
    f = tmp_path / "short.wav"
    write_wav(f, np.random.default_rng(0).standard_normal(FS), FS)  # 1 s < 5 s min
    setup = _setup(corpus)
    sig, vad, fs = setup.get_target_segment(str(f))
    assert sig is None and vad is None


def test_noise_segment_category(corpus):
    setup = _setup(corpus)
    n, f, start, vad, fs = setup.get_noise_segment("fs", 4.0)
    assert len(n) == 4 * FS
    assert f in corpus[1]
    assert abs(np.mean(n)) < 1e-9
    assert vad is None


def test_noise_segment_ssn(corpus):
    setup = _setup(corpus)
    n, f, start, vad, fs = setup.get_noise_segment("SSN", 5.0)
    assert len(n) == 5 * FS and f is None


def test_noise_too_long_raises(corpus):
    setup = _setup(corpus)
    with pytest.raises(ValueError):
        setup.get_noise_segment("fs", 100.0)
    with pytest.raises(ValueError):
        setup.get_noise_segment("bogus", 1.0)


def test_random_dry_snr_in_range(corpus):
    setup = _setup(corpus)
    setup.snr_dry_range = np.array([[0, 6], [3, 9]])
    setup.source_snr = np.zeros(2)
    snrs = setup.get_random_dry_snr()
    assert 0 <= snrs[0] <= 6 and 3 <= snrs[1] <= 9


def test_interferent_speakers_no_repeat(corpus):
    speech, _ = corpus
    setup = InterferentSpeakersSetup(
        speakers_list=speech,
        duration_range=(5, 10),
        var_tar=10 ** (-23 / 10),
        snr_dry_range=[[0, 0]],
        snr_cnv_range=(-10, 15),
        min_delta_snr=0,
        rng=np.random.default_rng(0),
    )
    y1, v1 = setup.get_signal(5.0)
    y2, v2 = setup.get_signal(5.0)
    y3, v3 = setup.get_signal(5.0)
    assert len(set(setup.speakers_ids)) == 3
    with pytest.raises(ValueError):
        setup.get_signal(5.0)  # only 3 speakers exist
    setup.reset()
    y4, _ = setup.get_signal(5.0)
    assert len(setup.speakers_ids) == 1
