"""Unit tests for bench.py's k-queued slope timing — the measurement math
every hardware RTF claim rests on (README 'Timing methodology').

The tunnel model: each fenced measurement costs ``overhead + k * t_exec``
(one fixed RPC round-trip per fence, k queued on-device executions).  The
slope estimator must recover ``t_exec`` exactly under that model and fall
back conservatively when jitter makes the slope non-positive."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def test_slope_recovers_on_device_time(monkeypatch):
    import bench
    from disco_tpu import milestones

    calls = {}

    def fake_time_queued(fn, *args, k=1, iters=5):
        calls[k] = calls.get(k, 0) + 1
        return 0.080 + k * 0.012  # 80 ms tunnel + 12 ms/exec

    # bench re-exports the timing seam from disco_tpu.milestones (round 4);
    # _slope_time resolves _time_queued in milestones' globals, so that is
    # the module to patch.
    monkeypatch.setattr(milestones, "_time_queued", fake_time_queued)
    slope, t1 = bench._slope_time(lambda: None, k=6, iters=3)
    assert abs(slope - 0.012) < 1e-12  # true on-device time, tunnel removed
    assert abs(t1 - 0.092) < 1e-12  # single-dispatch keeps the tunnel
    assert set(calls) == {1, 6}


def test_slope_nonpositive_falls_back_to_upper_bound(monkeypatch):
    """RPC jitter can make t_k <= t_1; the estimator must then report the
    conservative amortized upper bound t_k / k, never a tiny/negative
    'fast' number."""
    import bench
    from disco_tpu import milestones

    def fake_time_queued(fn, *args, k=1, iters=5):
        return 0.100 if k == 1 else 0.090  # jitter: k=6 cheaper than k=1

    monkeypatch.setattr(milestones, "_time_queued", fake_time_queued)
    slope, _ = bench._slope_time(lambda: None, k=6, iters=3)
    assert abs(slope - 0.090 / 6) < 1e-12


def test_time_queued_uses_median(monkeypatch):
    import time as _time

    import bench
    from disco_tpu import milestones

    seq = iter([0.0, 0.5, 1.0, 1.1, 2.0, 2.9, 4.0, 4.2, 6.0, 6.25])
    monkeypatch.setattr(_time, "perf_counter", lambda: next(seq))
    monkeypatch.setattr(milestones, "_fence", lambda x: 0.0)
    monkeypatch.setattr(milestones, "_leaf", lambda x: x)
    # warm-up consumes nothing from the clock (fence mocked), 5 iters ->
    # deltas 0.5, 0.1, 0.9, 0.2, 0.25 -> sorted median = 0.25
    dt = bench._time_queued(lambda: 0, k=1, iters=5)
    assert abs(dt - 0.25) < 1e-12


def test_bench_failure_record_names_backend(monkeypatch, capsys):
    """Even a crashed run's one JSON line carries the active jax backend
    (when init got far enough to know it) — the field `disco-obs compare`
    uses to refuse cross-backend verdicts (the BENCH_r06 hazard)."""
    import json

    import pytest

    import bench

    def boom(**kw):
        raise RuntimeError("synthetic backend failure")

    # the probe reports only an ALREADY-initialized backend (asking an
    # uninitialized jax would be a fresh chip claim on the tunnel — it
    # must yield None there, never block): initialize CPU first so the
    # reporting path is the one under test
    import jax

    assert jax.default_backend() == "cpu"
    monkeypatch.setattr(bench, "bench_jax", boom)
    monkeypatch.setenv("BENCH_WATCHDOG_S", "0")   # no watchdog thread
    with pytest.raises(SystemExit) as exc:
        bench.main([])
    assert exc.value.code == 2
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["value"] is None
    assert "synthetic backend failure" in record["error"]
    assert "backend" in record            # None only if jax never initialized
    assert record["backend"] == "cpu"     # conftest forces the CPU backend
