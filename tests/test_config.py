"""Tests for the typed config tree (SURVEY.md §5.6 consolidation)."""
import dataclasses

import pytest

from disco_tpu.config import (
    ArrayConfig,
    DiscoConfig,
    StftConfig,
    config_from_dict,
    load_config,
    save_config,
)


def test_defaults_match_reference_constants():
    cfg = DiscoConfig()
    assert cfg.stft.n_fft == 512 and cfg.stft.hop == 256 and cfg.stft.n_freq == 257
    assert cfg.array.mics_per_node == (4, 4, 4, 4) and cfg.array.n_channels == 16
    assert cfg.enhance.win_len == 21 and cfg.enhance.snr_range == ((0, 6),)
    assert cfg.train.batch_size == 500 and cfg.train.lr == 1e-3
    assert cfg.corpus.splits == (10000, 1000, 1000)
    assert cfg.room.max_order == 20


def test_yaml_roundtrip(tmp_path):
    cfg = DiscoConfig(
        root="/data/disco",
        stft=StftConfig(n_fft=1024, hop=512),
        array=ArrayConfig(mics_per_node=(2, 2)),
    )
    p = save_config(cfg, tmp_path / "cfg.yaml")
    back = load_config(p)
    assert back == cfg  # frozen dataclasses compare structurally


def test_partial_dict_applies_defaults():
    cfg = config_from_dict({"stft": {"n_fft": 256}})
    assert cfg.stft.n_fft == 256
    assert cfg.stft.hop == 256  # default preserved


def test_enhance_solver_field_roundtrips(tmp_path):
    """The round-2 solver spec survives dict construction and YAML I/O."""
    cfg = config_from_dict({"enhance": {"solver": "power:24"}})
    assert cfg.enhance.solver == "power:24"
    assert cfg.enhance.filter_type == "gevd"  # defaults preserved
    back = load_config(save_config(cfg, tmp_path / "s.yaml"))
    assert back.enhance.solver == "power:24"
    assert cfg.array.n_nodes == 4


def test_enhance_solver_default_is_power():
    """Round-4 default flip: the offline solver default is 'power',
    traceable to the round-3 on-device A/B (exp/tpu_validation_r3.jsonl
    solver_ab: 6722x vs eigh 4833x at 49 dB output agreement)."""
    from disco_tpu.config import EnhanceConfig

    assert EnhanceConfig().solver == "power"


def test_unknown_key_rejected():
    with pytest.raises(ValueError, match="unknown keys"):
        config_from_dict({"stft": {"nfft": 256}})
    with pytest.raises(ValueError, match="unknown config section"):
        config_from_dict({"sftf": {}})


def test_frozen():
    cfg = DiscoConfig()
    with pytest.raises(dataclasses.FrozenInstanceError):
        cfg.root = "x"


def test_mesh_from_config():
    from disco_tpu.config import DiscoConfig, MeshConfig
    from disco_tpu.parallel.mesh import mesh_from_config

    m = mesh_from_config(DiscoConfig(mesh=MeshConfig(n_node=4)))
    assert m.shape["node"] == 4
    m2 = mesh_from_config(MeshConfig(n_node=2, n_frame=4))
    assert dict(m2.shape) == {"node": 2, "frame": 4}
    m3 = mesh_from_config(MeshConfig(n_node=2, n_frame=2, n_batch=2))
    assert dict(m3.shape) == {"batch": 2, "node": 2, "frame": 2}


def test_mesh_from_config_none_node_uses_all_devices():
    """n_node=None means 'all remaining devices' on every path, not 1."""
    import jax

    from disco_tpu.config import MeshConfig
    from disco_tpu.parallel.mesh import mesh_from_config

    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest should force an 8-device CPU mesh"
    m = mesh_from_config(MeshConfig(n_frame=2))
    assert dict(m.shape) == {"node": 4, "frame": 2}
    m2 = mesh_from_config(MeshConfig(n_batch=2))
    assert m2.shape["node"] == 4
    m3 = mesh_from_config(MeshConfig())
    assert m3.shape["node"] == 8
