"""Generate the committed golden ISM fixture (golden_rir_order20.npz).

Run from the repo root:  python tests/data/gen_golden_rir.py

pyroomacoustics cannot be installed in the build environment (zero egress),
so the fixture is produced by the independent float64 NumPy oracle
``tests.reference_impls.shoebox_rir_np_order20`` — a loop/chunk float64
implementation of libroom's documented conventions, structurally unrelated
to the float32 JAX kernel it pins (`disco_tpu.sim.ism.shoebox_rir`).  The
scene mirrors the DISCO setup: a living-room-sized shoebox, RT60 0.5 s via
Eyring absorption, one target + one noise source, two 2-mic nodes.
"""
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from disco_tpu.sim.geometry import eyring_absorption
from tests.reference_impls import shoebox_rir_np_order20

ROOM = np.array([5.0, 4.0, 3.0])
SOURCES = np.array([[1.0, 1.0, 1.5], [4.2, 3.1, 1.2]])  # target, noise
MICS = np.array([
    [3.50, 2.50, 1.50], [3.55, 2.50, 1.50],   # node 1
    [1.80, 3.20, 1.40], [1.85, 3.20, 1.40],   # node 2
])
RT60 = 0.5
MAX_ORDER = 20
RIR_LEN = 12288
FS = 16000


def main():
    alpha = float(eyring_absorption(RT60, *ROOM))
    rirs = np.stack([
        shoebox_rir_np_order20(ROOM, src, MICS, alpha, max_order=MAX_ORDER,
                               rir_len=RIR_LEN, fs=FS)
        for src in SOURCES
    ])  # (S, M, L) float64
    out = Path(__file__).parent / "golden_rir_order20.npz"
    np.savez_compressed(
        out, room_dim=ROOM, sources=SOURCES, mics=MICS, alpha=alpha,
        rt60=RT60, max_order=MAX_ORDER, rir_len=RIR_LEN, fs=FS, rirs=rirs,
    )
    print(f"wrote {out} ({out.stat().st_size/1e6:.2f} MB), alpha={alpha:.4f}")


if __name__ == "__main__":
    main()
