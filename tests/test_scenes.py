"""Tests for disco_tpu.scenes — the batched scenario factory: batched-ISM
parity against the float64 NumPy oracle, the one-bucket policy, SNR gain
math, dynamic-scene crossfade continuity, SceneStream determinism and
ledger resume, the training-feed seam, and the geometry samplers'
rejection-sampling properties (seeded determinism + bounded retry).

``make scene-check`` (disco_tpu/scenes/check.py) drills the heavier
end-to-end invariants (dispatch accounting, chaos crash-and-resume trees);
these tests pin the component-level contracts the gate builds on.
"""
import numpy as np
import pytest

from disco_tpu.scenes import (
    BATCH_QUANTUM,
    SceneBatch,
    SceneStream,
    boundary_jumps,
    draw_scene_batch,
    dynamic_scene_mixture,
    noise_gain_for_snr,
    piecewise_trajectory,
    scene_batch_bucket,
    segment_weights,
    simulate_scene_batch,
    synthetic_dry_pair,
    unit_scene_batch,
)
from tests.reference_impls import shoebox_rirs_batched_np

FS = 16000


# ------------------------------------------------------------ batched oracle
def _tiny_batch(rng, n_scenes=2, n_mics=2, L=2048):
    """A hand-built SceneBatch (no geometry sampler): B scenes x 2 sources
    x n_mics mics in small rooms, synthetic dry pairs."""
    dims, srcs, mics, alphas, betas, drys, gains, snrs = [], [], [], [], [], [], [], []
    for _ in range(n_scenes):
        dim = rng.uniform([3.5, 3.0, 2.5], [5.0, 4.0, 3.0])
        dims.append(dim.astype(np.float32))
        srcs.append(rng.uniform(0.8, 2.2, size=(2, 3)).astype(np.float32))
        mics.append(rng.uniform(1.0, 2.4, size=(n_mics, 3)).astype(np.float32))
        alphas.append(np.float32(rng.uniform(0.2, 0.5)))
        betas.append(np.float32(rng.uniform(0.3, 0.5)))
        target, noise = synthetic_dry_pair(rng, L)
        drys.append(np.stack([target, noise]))
        snr = float(rng.uniform(-5, 10))
        gains.append(np.float32(noise_gain_for_snr(target, noise, snr)))
        snrs.append(np.float32(snr))
    return SceneBatch(
        room_dims=np.stack(dims), sources=np.stack(srcs), mics=np.stack(mics),
        alphas=np.asarray(alphas, np.float32), betas=np.asarray(betas, np.float32),
        dry=np.stack(drys), noise_gains=np.asarray(gains, np.float32),
        snr_db=np.asarray(snrs, np.float32),
    )


def test_batched_rirs_match_f64_oracle():
    """The batched lane against the independent float64 loop oracle — same
    tolerance regime as the per-scene parity test (test_sim.py)."""
    from disco_tpu.sim.ism import shoebox_rirs_batched

    rng = np.random.default_rng(11)
    batch = _tiny_batch(rng, n_scenes=2, n_mics=2)
    got = np.asarray(shoebox_rirs_batched(
        batch.room_dims, batch.sources, batch.mics, batch.alphas,
        max_order=2, rir_len=1024, fs=FS))
    want = shoebox_rirs_batched_np(
        batch.room_dims, batch.sources, batch.mics, batch.alphas,
        max_order=2, rir_len=1024, fs=FS)
    scale = np.abs(want).max()
    assert np.abs(got - want).max() / scale < 1e-4


def test_batched_rirs_match_per_scene_path():
    """vmap parity: scene b of the batched kernel == the per-scene
    shoebox_rirs launch on scene b's geometry."""
    from disco_tpu.sim.ism import shoebox_rirs, shoebox_rirs_batched

    rng = np.random.default_rng(12)
    batch = _tiny_batch(rng, n_scenes=3, n_mics=2)
    got = np.asarray(shoebox_rirs_batched(
        batch.room_dims, batch.sources, batch.mics, batch.alphas,
        max_order=3, rir_len=1024, fs=FS))
    for b in range(batch.n_scenes):
        one = np.asarray(shoebox_rirs(
            batch.room_dims[b], batch.sources[b], batch.mics[b],
            float(batch.alphas[b]), max_order=3, rir_len=1024, fs=FS))
        np.testing.assert_allclose(got[b], one, atol=1e-6)


def test_simulate_scene_batch_shapes_and_mask_range():
    rng = np.random.default_rng(13)
    batch = _tiny_batch(rng, n_scenes=2, n_mics=2, L=2048)
    out = simulate_scene_batch(batch, max_order=2, fs=FS)
    B, M, L = 2, 2, 2048
    assert out["noisy"].shape == (B, M, L)
    assert out["clean"].shape == (B, M, L)
    assert out["rirs"].shape[:3] == (B, 2, M)
    assert out["mag_noisy"].shape == out["mask"].shape
    assert np.all(np.isfinite(out["noisy"]))
    assert np.all((out["mask"] >= 0.0) & (out["mask"] <= 1.0))


# ------------------------------------------------------------- bucket policy
def test_scene_batch_bucket_dominates_every_scene():
    """The batch bucket is the max of the canonical per-scene rir_bucket
    policy at the batch quantum — every scene's tail fits, and the length
    is quantum-aligned."""
    from disco_tpu.sim.ism import rir_bucket

    rng = np.random.default_rng(14)
    batch = _tiny_batch(rng, n_scenes=4)
    order, rir_len = scene_batch_bucket(batch, max_order=8, fs=FS)
    assert order == 8
    assert rir_len % BATCH_QUANTUM == 0
    per_scene = [rir_bucket(float(batch.betas[b]), batch.room_dims[b],
                            max_order=8, fs=FS, quantum=BATCH_QUANTUM)[1]
                 for b in range(batch.n_scenes)]
    assert rir_len == max(per_scene)


@pytest.mark.parametrize("snr_db", [-10.0, 0.0, 7.5])
def test_noise_gain_hits_snr(snr_db):
    rng = np.random.default_rng(15)
    target = rng.standard_normal(4096) * 0.3
    noise = rng.standard_normal(4096) * 2.0
    g = noise_gain_for_snr(target, noise, snr_db)
    got = 10 * np.log10(np.mean(target**2) / np.mean((g * noise) ** 2))
    assert got == pytest.approx(snr_db, abs=1e-3)


def test_synthetic_dry_pair_deterministic_and_normalized():
    a = synthetic_dry_pair(np.random.default_rng(3), 4096)
    b = synthetic_dry_pair(np.random.default_rng(3), 4096)
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
    assert np.std(a[0]) == pytest.approx(1.0, rel=1e-3)
    assert np.std(a[1]) == pytest.approx(1.0, rel=1e-3)


# ------------------------------------------------------------ dynamic scenes
def test_piecewise_trajectory_endpoints_and_monotone():
    path = piecewise_trajectory([0.0, 0.0, 1.0], [2.0, 4.0, 1.0], 4)
    assert path.shape == (4, 3)
    # segment-center sampling: first/last waypoints sit half a segment in
    np.testing.assert_allclose(path[0], [0.25, 0.5, 1.0], atol=1e-6)
    np.testing.assert_allclose(path[-1], [1.75, 3.5, 1.0], atol=1e-6)
    assert np.all(np.diff(path[:, 0]) > 0)
    with pytest.raises(ValueError):
        piecewise_trajectory([0, 0, 0], [1, 1, 1], 0)


def test_segment_weights_partition_of_unity():
    w = segment_weights(4096, 5, crossfade=256)
    assert w.shape == (5, 4096)
    np.testing.assert_allclose(w.sum(0), 1.0, atol=1e-6)
    assert np.all(w >= 0.0)


def test_segment_weights_hard_switch_is_binary():
    w = segment_weights(1000, 4, crossfade=0)
    assert set(np.unique(w)) <= {np.float32(0.0), np.float32(1.0)}
    np.testing.assert_allclose(w.sum(0), 1.0, atol=0)


def test_dynamic_crossfade_smoother_than_hard_switch():
    """The scene-check continuity contract at test scale: on a sine dry
    signal, the crossfaded mixture's boundary jumps are well under the
    hard-switched blend's click."""
    t = np.arange(4096) / FS
    dry = np.sin(2 * np.pi * 440.0 * t).astype(np.float32)
    room = np.array([4.0, 3.0, 2.5], np.float32)
    path = piecewise_trajectory([1.0, 1.0, 1.2], [3.0, 2.0, 1.2], 3)
    mics = np.array([[2.0, 1.5, 1.5], [2.1, 1.5, 1.5]], np.float32)
    kw = dict(alpha=0.3, dry=dry, max_order=2, rir_len=1024, fs=FS)
    soft = dynamic_scene_mixture(room, path, mics, crossfade=512, **kw)
    hard = dynamic_scene_mixture(room, path, mics, crossfade=0, **kw)
    j_soft = boundary_jumps(soft["mixture"], 3).max()
    j_hard = boundary_jumps(hard["mixture"], 3).max()
    assert j_soft < 0.5 * j_hard


# --------------------------------------------------------------- SceneStream
def _tiny_stream(seed=7, batches_per_epoch=2):
    return SceneStream(
        seed=seed, scenes_per_batch=2, batches_per_epoch=batches_per_epoch,
        duration_s=0.25, max_order=2, win_len=4, snr_range=(0.0, 5.0),
        setup_overrides={"n_sensors_per_node": (2, 2)},
    )


def test_scene_stream_deterministic_across_instances():
    a = [x for x, _y in _tiny_stream(seed=7).batches(4, epoch=0)]
    b = [x for x, _y in _tiny_stream(seed=7).batches(4, epoch=0)]
    c = [x for x, _y in _tiny_stream(seed=8).batches(4, epoch=0)]
    assert len(a) == len(b) > 0
    for xa, xb in zip(a, b):
        np.testing.assert_array_equal(xa, xb)
    assert not all(np.array_equal(xa, xc) for xa, xc in zip(a, c))


def test_scene_stream_window_convention():
    stream = _tiny_stream()
    geo = stream.peek_geometry()
    x, y = next(stream.batches(3, epoch=0))
    assert x.shape == (3, stream.win_len, geo["n_freq"])
    assert y.shape == x.shape
    assert np.all((y >= 0.0) & (y <= 1.0))


def test_scene_stream_ledger_resume_skips_consumed_batches(tmp_path):
    """A fully consumed epoch's scene-batch units replay to ZERO batches
    through the same ledger — the verified_done resume contract."""
    led = tmp_path / "led.jsonl"
    stream = _tiny_stream()
    n_first = sum(1 for _ in stream.batches(4, epoch=0, ledger=led))
    assert n_first > 0
    n_replay = sum(1 for _ in stream.batches(4, epoch=0, ledger=led))
    assert n_replay == 0
    # a FRESH epoch through the same ledger still serves in full
    assert sum(1 for _ in stream.batches(4, epoch=1, ledger=led)) == n_first


def test_scene_stream_batch_fn_start_epoch():
    stream = _tiny_stream(batches_per_epoch=1)
    make = stream.batch_fn(4)
    make.set_start_epoch(2)
    resumed = [x for x, _y in make()]
    direct = [x for x, _y in stream.batches(4, epoch=2)]
    assert len(resumed) == len(direct)
    for xa, xb in zip(resumed, direct):
        np.testing.assert_array_equal(xa, xb)


def test_unit_scene_batch_ids():
    assert unit_scene_batch(3, 7) == "scene_batch:3:7"


@pytest.mark.slow
def test_scene_stream_feeds_fit(tmp_path):
    """The training-feed seam: fit() trains off SceneStream.batch_fn exactly
    as it does off ShardDataset.batch_fn (the resident trainer's dataset=
    seam rides the same surface)."""
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state, fit

    stream = _tiny_stream(batches_per_epoch=1)
    F = stream.peek_geometry()["n_freq"]
    model, tx = build_crnn(n_ch=1, win_len=4, n_freq=F, cnn_filters=(2,),
                           pool_kernels=((1, 2),), conv_padding=((0, 1),),
                           rnn_units=(4,), ff_units=(F,), rnn_dropouts=0.0)
    first = next(stream.batches(2, epoch=0))
    state = create_train_state(model, tx, first[0][:1], seed=2)
    _state, tr, va, _name = fit(
        model, state, stream.batch_fn(4), stream.batch_fn(4, shuffle=False),
        n_epochs=1, save_path=tmp_path / "m", verbose=False,
    )
    assert len(tr) == 1 and np.isfinite(tr[0]) and tr[0] > 0.0
    assert len(va) == 1 and np.isfinite(va[0])


# ----------------------------------------------- geometry sampling properties
def test_draw_scene_batch_rectangular_and_seeded():
    rng_a = np.random.default_rng(21)
    rng_b = np.random.default_rng(21)
    kw = dict(duration_s=0.25, setup_overrides={"n_sensors_per_node": (2, 2)})
    a = draw_scene_batch(rng_a, 3, **kw)
    b = draw_scene_batch(rng_b, 3, **kw)
    assert a.room_dims.shape == (3, 3)
    assert a.sources.shape == (3, 2, 3)
    assert a.mics.shape == (3, 4, 3)
    for field in ("room_dims", "sources", "mics", "alphas", "dry",
                  "noise_gains", "snr_db"):
        np.testing.assert_array_equal(getattr(a, field), getattr(b, field))


@pytest.mark.parametrize("seed", [5, 6])
def test_geometry_sampler_seeded_determinism(seed):
    """Two samplers driven by equal-seeded generators produce identical
    configurations — the property the per-scene (seed, rir_id, stream)
    reseeding discipline in datagen/disco.py rests on."""
    from disco_tpu.sim import make_setup

    cfg_a = make_setup("random", rng=np.random.default_rng(seed)).create_room_setup()
    cfg_b = make_setup("random", rng=np.random.default_rng(seed)).create_room_setup()
    np.testing.assert_array_equal(cfg_a.room_dim, cfg_b.room_dim)
    np.testing.assert_array_equal(cfg_a.source_positions, cfg_b.source_positions)
    np.testing.assert_array_equal(cfg_a.mic_positions, cfg_b.mic_positions)
    assert cfg_a.alpha == cfg_b.alpha and cfg_a.beta == cfg_b.beta


def test_geometry_rejection_sampling_bounded_retry():
    """Unsatisfiable constraints fail loudly within the trial budget — a
    RuntimeError, never an infinite rejection loop."""
    from disco_tpu.sim import make_setup

    sampler = make_setup(
        "random", rng=np.random.default_rng(9),
        # two nodes forced >= 50 m apart inside a <= 8 m room: impossible
        d_nn=50.0, n_sensors_per_node=(2, 2),
    )
    with pytest.raises(RuntimeError, match="no valid room configuration"):
        sampler.create_room_setup(max_config_trials=5)


def test_geometry_rejection_sampling_respects_constraints():
    """Sampled configurations honor the declared min-distance constraints
    (wall clearance, node spacing, source-node spacing)."""
    from disco_tpu.sim import make_setup

    sampler = make_setup("random", rng=np.random.default_rng(10))
    for _ in range(5):
        cfg = sampler.create_room_setup()
        dims = cfg.room_dim
        nodes = sampler.nodes_centers
        # pairwise node spacing in the xy plane
        for i in range(len(nodes)):
            for j in range(i + 1, len(nodes)):
                assert np.hypot(*(nodes[i][:2] - nodes[j][:2])) >= sampler.d_nn - 1e-9
        # wall clearance for nodes and sources
        for n in nodes:
            assert np.all(n[:2] >= sampler.d_nw - 1e-9)
            assert np.all(n[:2] <= dims[:2] - sampler.d_nw + 1e-9)
        for s in cfg.source_positions:
            assert np.all(s[:2] >= sampler.d_sw - 1e-9)
            assert np.all(s[:2] <= dims[:2] - sampler.d_sw + 1e-9)
            for n in nodes:
                assert np.hypot(*(s[:2] - n[:2])) >= sampler.d_sn - 1e-9
