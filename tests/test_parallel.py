"""The mesh contract: node-sharded shard_map TANGO == single-device vmap
TANGO, on the virtual 8-device CPU mesh (SURVEY.md §7 step 3)."""
import jax
import numpy as np
import pytest

from disco_tpu.core.dsp import stft
from disco_tpu.enhance import oracle_masks, tango
from disco_tpu.parallel import make_mesh, node_sharding, tango_sharded

from tests.test_tango import _scene


@pytest.fixture(scope="module")
def scene8():
    # 8 nodes x 2 mics so every virtual device owns exactly one node.
    return _scene(np.random.default_rng(3), K=8, C=2, L=8192)


def test_mesh_shape():
    mesh = make_mesh(n_node=8)
    assert dict(mesh.shape) == {"batch": 1, "node": 8}
    mesh2 = make_mesh(n_node=4, n_batch=2)
    assert dict(mesh2.shape) == {"batch": 2, "node": 4}


@pytest.mark.parametrize("policy", ["local", "none", "distant", "use_oracle_zs"])
def test_sharded_matches_vmap(scene8, policy):
    y, s, n = scene8
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")

    want = tango(Y, S, N, masks, masks, policy=policy)

    mesh = make_mesh(n_node=8)
    sh = node_sharding(mesh)
    Ys, Ss, Ns = (jax.device_put(a, sh) for a in (Y, S, N))
    ms = jax.device_put(masks, sh)
    got = tango_sharded(Ys, Ss, Ns, ms, ms, mesh, policy=policy)

    for key in ("yf", "sf", "nf", "z_y", "z_s", "z_n", "zn"):
        a = np.asarray(getattr(got, key))
        b = np.asarray(getattr(want, key))
        err = np.linalg.norm(a - b) / np.linalg.norm(b)
        assert err < 1e-5, (key, err)


def test_sharded_power_solver_matches_vmap(scene8):
    """solver='power' under shard_map equals the single-device vmap path with
    the same solver — the z-exchange and the solver compose."""
    y, s, n = scene8
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    want = tango(Y, S, N, masks, masks, policy="local", solver="power")

    mesh = make_mesh(n_node=8)
    sh = node_sharding(mesh)
    got = tango_sharded(
        jax.device_put(Y, sh), jax.device_put(S, sh), jax.device_put(N, sh),
        jax.device_put(masks, sh), jax.device_put(masks, sh), mesh,
        policy="local", solver="power",
    )
    err = np.linalg.norm(np.asarray(got.yf) - np.asarray(want.yf)) / np.linalg.norm(
        np.asarray(want.yf)
    )
    assert err < 1e-5, err


def test_sharded_two_nodes_per_device(scene8):
    """K=8 nodes on 4 devices: two nodes per shard still produces identical
    results (the n_local > 1 path)."""
    y, s, n = scene8
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    want = tango(Y, S, N, masks, masks, policy="local")

    mesh = make_mesh(n_node=4)
    sh = node_sharding(mesh)
    got = tango_sharded(
        jax.device_put(Y, sh), jax.device_put(S, sh), jax.device_put(N, sh),
        jax.device_put(masks, sh), jax.device_put(masks, sh), mesh, policy="local",
    )
    err = np.linalg.norm(np.asarray(got.yf) - np.asarray(want.yf)) / np.linalg.norm(
        np.asarray(want.yf)
    )
    assert err < 1e-5, err


def test_batch_sharded_matches_vmap():
    """(batch=2, node=4) GSPMD-partitioned corpus TANGO == plain vmap(tango):
    the sharding-annotation formulation (XLA-placed collectives) and the
    explicit shard_map formulation bracket the same math."""
    from disco_tpu.parallel import tango_batch_sharded

    B, K, C, L = 4, 4, 2, 8192
    scenes = [_scene(np.random.default_rng(100 + b), K=K, C=C, L=L) for b in range(B)]
    Yb = stft(np.stack([s[0] for s in scenes]))
    Sb = stft(np.stack([s[1] for s in scenes]))
    Nb = stft(np.stack([s[2] for s in scenes]))
    Mb = jax.vmap(lambda S, N: oracle_masks(S, N, "irm1"))(Sb, Nb)

    want = jax.vmap(lambda Y, S, N, m: tango(Y, S, N, m, m, policy="local"))(Yb, Sb, Nb, Mb)

    mesh = make_mesh(n_node=4, n_batch=2)
    got = tango_batch_sharded(Yb, Sb, Nb, Mb, Mb, mesh, policy="local")
    for key in ("yf", "z_y", "zn"):
        a = np.asarray(getattr(got, key))
        b = np.asarray(getattr(want, key))
        err = np.linalg.norm(a - b) / np.linalg.norm(b)
        assert err < 1e-5, (key, err)


# ------------------------------------------------- sequence (frame) parallel
def test_frame_sharded_matches_single_device():
    """(node=4, frame=2) mesh: frame-axis sequence parallelism must be
    numerically identical to the single-device vmap path (covariances psum
    over frame shards)."""
    from disco_tpu.parallel import make_mesh_2d, tango_frame_sharded

    rng = np.random.default_rng(11)
    K, C, L = 4, 2, 8192
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)]) for _ in range(K)]
    )
    n = 0.7 * rng.standard_normal((K, C, L))
    y = s + n
    Y, S, N = stft(y), stft(s), stft(n)
    T = Y.shape[-1]
    if T % 2:  # frame axis must split evenly over 2 shards
        Y, S, N = Y[..., :-1], S[..., :-1], N[..., :-1]
    masks = oracle_masks(S, N, "irm1")

    ref = tango(Y, S, N, masks, masks, policy="local")
    mesh = make_mesh_2d(n_node=4, n_frame=2)
    sharded = tango_frame_sharded(Y, S, N, masks, masks, mesh, policy="local")
    for key in ("yf", "z_y", "zn"):
        a, b = np.asarray(getattr(ref, key)), np.asarray(getattr(sharded, key))
        err = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert err < 1e-4, (key, err)


@pytest.mark.slow
def test_frame_sharded_all_policies():
    from disco_tpu.parallel import make_mesh_2d, tango_frame_sharded

    rng = np.random.default_rng(12)
    K, C, L = 2, 2, 4096
    y = rng.standard_normal((K, C, L))
    s = 0.7 * rng.standard_normal((K, C, L))
    n = y - s
    Y, S, N = stft(y), stft(s), stft(n)
    if Y.shape[-1] % 4:
        cut = Y.shape[-1] - Y.shape[-1] % 4
        Y, S, N = Y[..., :cut], S[..., :cut], N[..., :cut]
    masks = oracle_masks(S, N, "irm1")
    mesh = make_mesh_2d(n_node=2, n_frame=4)
    for policy in ("local", "none", "distant", "compressed", "use_oracle_refs", "use_oracle_zs"):
        ref = tango(Y, S, N, masks, masks, policy=policy)
        out = tango_frame_sharded(Y, S, N, masks, masks, mesh, policy=policy)
        err = np.max(np.abs(np.asarray(ref.yf) - np.asarray(out.yf)))
        scale = np.max(np.abs(np.asarray(ref.yf))) + 1e-30
        assert err / scale < 1e-4, (policy, err / scale)


def test_hybrid_mesh_and_distributed_init():
    from disco_tpu.parallel import distributed_init, hybrid_mesh

    assert distributed_init() is False  # single-process: clean no-op
    mesh = hybrid_mesh(n_node=2, n_frame=2)
    assert mesh.shape["node"] == 2 and mesh.shape["frame"] == 2
    assert mesh.shape["batch"] == 2  # 8 devices / (2*2)
    mesh1 = hybrid_mesh(n_batch_dcn=1, n_node=4, n_frame=2)
    assert dict(mesh1.shape) == {"batch": 1, "node": 4, "frame": 2}


def test_ring_exchange_matches_all_gather():
    """The ppermute-ring z-exchange must be bit-identical to the all_gather
    one (same math, different collective schedule)."""
    from disco_tpu.parallel import make_mesh, node_sharding

    rng = np.random.default_rng(21)
    K, C, L = 8, 2, 4096
    y = rng.standard_normal((K, C, L)).astype("float32")
    s = 0.7 * rng.standard_normal((K, C, L)).astype("float32")
    n = y - s
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    mesh = make_mesh(n_node=8)
    a = tango_sharded(Y, S, N, masks, masks, mesh, policy="local")
    b = tango_sharded(Y, S, N, masks, masks, mesh, policy="local", z_exchange="ring")
    for key in ("yf", "z_y", "zn"):
        np.testing.assert_allclose(
            np.asarray(getattr(a, key)), np.asarray(getattr(b, key)), rtol=1e-5, atol=1e-6
        )


def test_ring_all_gather_order():
    """ring_all_gather reproduces all_gather's node ordering for a
    multi-row shard."""
    import jax
    from jax.sharding import PartitionSpec as P

    from disco_tpu.parallel import make_mesh, ring_all_gather, shard_map_compat

    mesh = make_mesh(n_node=4)
    x = np.arange(8, dtype=np.float32).reshape(8, 1)  # 2 rows per device

    def f(xs):
        return ring_all_gather(xs, "node"), jax.lax.all_gather(xs, "node", axis=0, tiled=True)

    ring, ref = shard_map_compat(
        f, mesh=mesh, in_specs=P("node"), out_specs=P("node")
    )(x)
    np.testing.assert_array_equal(np.asarray(ring), np.asarray(ref))


@pytest.mark.slow
def test_sharded_cov_impl_pallas_matches_vmap(scene8):
    """cov_impl='pallas' (fused masked-covariance kernel) under shard_map
    equals the single-device vmap path — the kernel composes with the
    node-sharded z-exchange."""
    y, s, n = scene8
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    want = tango(Y, S, N, masks, masks, policy="local", cov_impl="pallas")

    mesh = make_mesh(n_node=8)
    sh = node_sharding(mesh)
    got = tango_sharded(
        jax.device_put(Y, sh), jax.device_put(S, sh), jax.device_put(N, sh),
        jax.device_put(masks, sh), jax.device_put(masks, sh), mesh,
        policy="local", cov_impl="pallas",
    )
    err = np.linalg.norm(np.asarray(got.yf) - np.asarray(want.yf)) / np.linalg.norm(
        np.asarray(want.yf)
    )
    assert err < 1e-5, err
