"""The mesh contract: node-sharded shard_map TANGO == single-device vmap
TANGO, on the virtual 8-device CPU mesh (SURVEY.md §7 step 3)."""
import jax
import numpy as np
import pytest

from disco_tpu.core.dsp import stft
from disco_tpu.enhance import oracle_masks, tango
from disco_tpu.parallel import make_mesh, node_sharding, tango_sharded

from tests.test_tango import _scene


@pytest.fixture(scope="module")
def scene8():
    # 8 nodes x 2 mics so every virtual device owns exactly one node.
    return _scene(np.random.default_rng(3), K=8, C=2, L=8192)


def test_mesh_shape():
    mesh = make_mesh(n_node=8)
    assert dict(mesh.shape) == {"batch": 1, "node": 8}
    mesh2 = make_mesh(n_node=4, n_batch=2)
    assert dict(mesh2.shape) == {"batch": 2, "node": 4}


@pytest.mark.parametrize("policy", ["local", "none", "distant", "use_oracle_zs"])
def test_sharded_matches_vmap(scene8, policy):
    y, s, n = scene8
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")

    want = tango(Y, S, N, masks, masks, policy=policy)

    mesh = make_mesh(n_node=8)
    sh = node_sharding(mesh)
    Ys, Ss, Ns = (jax.device_put(a, sh) for a in (Y, S, N))
    ms = jax.device_put(masks, sh)
    got = tango_sharded(Ys, Ss, Ns, ms, ms, mesh, policy=policy)

    for key in ("yf", "sf", "nf", "z_y", "z_s", "z_n", "zn"):
        a = np.asarray(getattr(got, key))
        b = np.asarray(getattr(want, key))
        err = np.linalg.norm(a - b) / np.linalg.norm(b)
        assert err < 1e-5, (key, err)


def test_sharded_two_nodes_per_device(scene8):
    """K=8 nodes on 4 devices: two nodes per shard still produces identical
    results (the n_local > 1 path)."""
    y, s, n = scene8
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    want = tango(Y, S, N, masks, masks, policy="local")

    mesh = make_mesh(n_node=4)
    sh = node_sharding(mesh)
    got = tango_sharded(
        jax.device_put(Y, sh), jax.device_put(S, sh), jax.device_put(N, sh),
        jax.device_put(masks, sh), jax.device_put(masks, sh), mesh, policy="local",
    )
    err = np.linalg.norm(np.asarray(got.yf) - np.asarray(want.yf)) / np.linalg.norm(
        np.asarray(want.yf)
    )
    assert err < 1e-5, err
