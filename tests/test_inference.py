"""L5 inference-glue tests: frame padding, normalization (incl. native
PCEN), window prep, mask reshaping, z-channel selection, CRNN mask path,
and z-export file contract (reference speech_enhancement/utils.py,
tango.py:158-249, get_z_signals.py)."""
import numpy as np
import pytest

import jax

from disco_tpu.core.dsp import n_stft_frames, stft
from disco_tpu.enhance import (
    compute_z_signals,
    crnn_mask,
    export_z,
    get_frames_to_pad,
    get_z_for_mask,
    normalization,
    oracle_masks,
    pcen,
    prepare_data,
    reshape_mask,
    vad_mask,
)
from disco_tpu.io.audio import write_wav
from disco_tpu.io.layout import DatasetLayout
from disco_tpu.nn import build_crnn, create_train_state


# -- frame padding ----------------------------------------------------------
def test_get_frames_to_pad():
    # reference utils.py:13-33 with win 21 / out 15
    assert get_frames_to_pad(21, "mid") == (10, 10)
    assert get_frames_to_pad(21, "last", out_len=15) == (17, 3)
    assert get_frames_to_pad(21, "all") == (0, 0)
    with pytest.raises(ValueError):
        get_frames_to_pad(21, "bogus")


# -- normalization ----------------------------------------------------------
def test_normalization_modes(rng):
    x = (rng.random((257, 50)) + 0.01).astype("float32")
    assert np.allclose(normalization(x, None), np.clip(x, 1e-6, 1e3))
    un = normalization(x, "scale_to_unit_norm", axis=1)
    np.testing.assert_allclose(np.linalg.norm(un, axis=1), 1.0, rtol=1e-5)
    q = normalization(x, "scale_to_1", axis=1)
    assert np.quantile(q, 0.99, axis=1) == pytest.approx(1.0, rel=1e-5)
    cs = normalization(x, "center_and_scale", axis=1)
    np.testing.assert_allclose(np.mean(cs, axis=1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.std(cs, axis=1), 1.0, rtol=1e-4)


def test_normalization_accepts_complex(rng):
    x = (rng.random((10, 20)) + 1j * rng.random((10, 20))).astype("complex64")
    out = normalization(x, "scale_to_unit_norm", axis=1)
    assert np.isrealobj(out)


def test_pcen_properties(rng):
    """PCEN of a constant signal ≈ (1 + bias)^power − bias^power; AGC makes
    output level nearly independent of input gain."""
    S = np.full((5, 200), 100.0)
    out = pcen(S, eps=1e-6)
    expect = (100.0 / (1e-6 + 100.0) ** 0.98 + 2.0) ** 0.5 - 2.0**0.5
    np.testing.assert_allclose(out[:, 50:], expect, rtol=1e-2)
    x = rng.random((5, 300)) + 0.5
    a, b = pcen(x), pcen(100.0 * x)
    assert np.abs(np.median(a[:, 50:]) - np.median(b[:, 50:])) < 0.3


# -- prepare_data -----------------------------------------------------------
def test_prepare_data_3d_shapes(rng):
    F, T = 33, 60
    y = rng.random((F, T)).astype("float32")
    z = [rng.random((F, T)).astype("float32") for _ in range(3)]
    out = prepare_data(y, True, z_data=z, win_len=21, win_hop=1, frame_to_pred="last", frames_lost=6)
    assert out.shape == (T, 4, 21, F)  # one window per original frame
    # window i ends at padded frame i+20; unpadded content is y[:, :i+4]
    np.testing.assert_allclose(out[0, 0, :17, :], 0.0)
    np.testing.assert_allclose(out[0, 0, 17:, :], y[:, :4].T, rtol=1e-6)


def test_prepare_data_2d_stacks_freq(rng):
    F, T = 33, 40
    y = rng.random((F, T)).astype("float32")
    z = [rng.random((F, T)).astype("float32")]
    out = prepare_data(y, False, z_data=z, win_len=21, win_hop=1, frame_to_pred="last", frames_lost=6)
    assert out.shape == (T, 21, 2 * F)


def test_prepare_data_matches_reference_loop(rng):
    """Vectorized windowing must equal the reference's per-window loop
    (utils.py:107-131)."""
    F, T, win_len, frames_lost = 9, 30, 21, 6
    y = rng.random((F, T)).astype("float32")
    pad = get_frames_to_pad(win_len, "last", out_len=win_len - frames_lost)
    y_pad = np.pad(y, ((0, 0), pad))
    n_samples = int(1 + np.floor((T + sum(pad) - win_len) / 1))
    expected = np.zeros((n_samples, 1, win_len, F), "float32")
    for i in range(n_samples):
        expected[i, 0] = y_pad[:, i : i + win_len].T
    got = prepare_data(y, True, win_len=win_len, frame_to_pred="last", frames_lost=frames_lost)
    np.testing.assert_allclose(got, expected, rtol=1e-6)


# -- reshape_mask -----------------------------------------------------------
def test_reshape_mask(rng):
    stack = rng.random((40, 15, 257)).astype("float32")
    last = reshape_mask(stack, "last")
    assert last.shape == (257, 40)
    np.testing.assert_allclose(last, stack[:, -1, :].T)
    mid = reshape_mask(stack, "mid")
    np.testing.assert_allclose(mid, stack[:, 7, :].T)
    with pytest.raises(NotImplementedError):
        reshape_mask(stack, "all")


# -- z selection ------------------------------------------------------------
def test_get_z_for_mask_single_kind(rng):
    z_s = rng.random((4, 5, 6))
    z_n = rng.random((4, 5, 6))
    out = get_z_for_mask(z_s, z_n, k=1, z_sigs="zs_hat")
    np.testing.assert_allclose(out, z_s[[0, 2, 3]])
    out_n = get_z_for_mask(z_s, z_n, k=3, z_sigs="zn_hat")
    np.testing.assert_allclose(out_n, z_n[[0, 1, 2]])


def test_get_z_for_mask_interleaved(rng):
    z_s = rng.random((4, 5, 6))
    z_n = rng.random((4, 5, 6))
    out = get_z_for_mask(z_s, z_n, k=0, z_sigs=["zs_hat", "zn_hat"])
    assert out.shape == (6, 5, 6)
    # local pair (zs_0, zn_0) dropped; order zs_1, zn_1, zs_2, zn_2, ...
    np.testing.assert_allclose(out[0], z_s[1])
    np.testing.assert_allclose(out[1], z_n[1])
    np.testing.assert_allclose(out[4], z_s[3])


# -- CRNN mask path ---------------------------------------------------------
def _small_crnn(n_ch):
    return build_crnn(
        n_ch=n_ch, n_freq=33,
        cnn_filters=(4, 4), conv_kernels=3, conv_strides=1,
        pool_kernels=[(1, 2)] * 2, pool_strides=None, conv_padding=[(0, 1)] * 2,
        rnn_units=(8,), ff_units=(33,),
    )


@pytest.mark.parametrize("with_z", [False, True])
def test_crnn_mask_shapes(rng, with_z):
    F, T = 33, 30
    Y = (rng.random((F, T)) + 1j * rng.random((F, T))).astype("complex64")
    model, tx = _small_crnn(4 if with_z else 1)
    n_ch = 4 if with_z else 1
    state = create_train_state(model, tx, np.zeros((1, n_ch, 21, F), "float32"))
    z = [Y * 0.5] * 3 if with_z else None
    m = crnn_mask(Y, model, {"params": state.params, "batch_stats": state.batch_stats}, z=z)
    assert m.shape == (F, T)
    assert (m >= 0).all() and (m <= 1).all()


def test_vad_mask(rng):
    fs = 16000
    t = np.arange(fs) / fs
    x = np.concatenate([0.001 * rng.standard_normal(fs), np.sin(2 * np.pi * 440 * t)]).astype("float32")
    m = vad_mask(x, n_freq=5, n_frames=n_stft_frames(len(x)))
    assert set(np.unique(m)).issubset({0.0, 1.0})
    assert m[:, -20:].mean() > 0.9  # active speech at the end
    assert (m == m[0:1]).all()  # constant across freq


# -- z export ---------------------------------------------------------------
def _write_processed(root, rir, noise="ssn", snr=(0, 6), K=4, C=4, L=8192, seed=0):
    rng = np.random.default_rng(seed)
    lay = DatasetLayout(str(root), "random", "train")
    sigs = {}
    # target saved WITHOUT noise tag, mixture/noise with it (postgen.save_data)
    for source, tag in (("mixture", noise), ("target", None), ("noise", noise)):
        sig = rng.standard_normal((K, C, L)).astype("float32") * 0.1
        sigs[source] = sig
        for node in range(K):
            for c in range(C):
                ch = 1 + node * C + c
                write_wav(lay.ensure_dir(lay.wav_processed(snr, source, rir, ch, noise=tag)), sig[node, c], 16000)
    return lay, sigs


def test_compute_z_signals_matches_step1(rng):
    K, C, L = 2, 3, 4096
    s = rng.standard_normal((K, C, L)).astype("float32")
    n = 0.3 * rng.standard_normal((K, C, L)).astype("float32")
    y = s + n
    out = compute_z_signals(y, s, n, mask_type="irm1")
    F, T = 257, n_stft_frames(L)
    assert out["z_y"].shape == (K, F, T)
    # zn = y_ref − z
    Y = stft(y)
    np.testing.assert_allclose(
        np.asarray(out["zn"]), np.asarray(Y[:, 0] - out["z_y"]), rtol=1e-4, atol=1e-5
    )


def test_export_z_files_and_idempotency(tmp_path):
    lay, _ = _write_processed(tmp_path, rir=1)
    assert export_z(str(tmp_path), "random", 1, "ssn") is True
    for k in range(1, 5):
        for zsig in ("zs_hat", "zn_hat"):
            raw = lay.stft_z("oracle", (0, 6), zsig, 1, k, "ssn", normed=False)
            nrm = lay.stft_z("oracle", (0, 6), zsig, 1, k, "ssn", normed=True)
            assert raw.exists() and nrm.exists()
            assert np.iscomplexobj(np.load(raw))
            assert not np.iscomplexobj(np.load(nrm))
    # second call is a no-op (idempotency guard)
    assert export_z(str(tmp_path), "random", 1, "ssn") is False


def test_crnn_mask_with_rnn_architecture():
    """The inference path also serves the 2-D RNN family (freq-stacked
    windows, three_d_tensor=False — the reference's 2-D branch of
    prepare_data, utils.py:100-120)."""
    import numpy as np

    from disco_tpu.enhance.inference import crnn_mask
    from disco_tpu.nn.crnn import build_rnn
    from disco_tpu.nn.training import create_train_state

    rng = np.random.default_rng(4)
    model, tx = build_rnn(n_ch=1, win_len=21, n_freq=257, rnn_units=(32,))
    state = create_train_state(model, tx, np.zeros((1, 21, 257), "float32"))
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    Y = (rng.standard_normal((257, 80)) + 1j * rng.standard_normal((257, 80))).astype("complex64")
    mask = crnn_mask(Y, model, variables, three_d_tensor=False)
    assert mask.shape == (257, 80)
    assert np.all(mask >= 0) and np.all(mask <= 1)


def test_batched_masks_fall_back_for_noncanonical_conv():
    """A CRNN with time padding cannot hoist its convs to the full stream;
    the batched path must fall back to per-window forwards and still match
    crnn_mask exactly."""
    import numpy as np

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.inference import _conv_stream_safe, crnn_mask, crnn_masks_batched
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    model, tx = build_crnn(n_ch=1, conv_padding=((1, 1), (1, 1), (1, 1)))
    assert not _conv_stream_safe(model)
    state = create_train_state(model, tx, np.zeros((1, 1, 21, 257), "float32"))
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    rng = np.random.default_rng(0)
    Y = np.asarray(stft(rng.standard_normal((2, 6000)).astype("float32")))
    batched = crnn_masks_batched(Y, model, variables)
    for k in range(2):
        np.testing.assert_allclose(np.asarray(batched[k]), crnn_mask(Y[k], model, variables), atol=1e-6)


def test_batched_masks_reject_all_frames():
    import numpy as np
    import pytest

    from disco_tpu.enhance.inference import crnn_masks_batched
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    model, tx = build_crnn(n_ch=1)
    state = create_train_state(model, tx, np.zeros((1, 1, 21, 257), "float32"))
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    with pytest.raises(NotImplementedError):
        crnn_masks_batched(np.zeros((1, 257, 50), "complex64"), model, variables,
                           frame_to_pred="all")


def test_batched_masks_rnn_architecture():
    """RNNMask (2-D archi) through the device-resident batched path: the
    4-D windows are freq-stacked inside the module; must equal the
    per-stream crnn_mask path."""
    import numpy as np

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.inference import crnn_mask, crnn_masks_batched
    from disco_tpu.nn.crnn import build_rnn
    from disco_tpu.nn.training import create_train_state

    model, tx = build_rnn(n_ch=1)
    state = create_train_state(model, tx, np.zeros((1, 21, 257), "float32"))
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    rng = np.random.default_rng(3)
    Y = np.asarray(stft(rng.standard_normal((2, 5000)).astype("float32")))
    batched = crnn_masks_batched(Y, model, variables)
    for k in range(2):
        single = crnn_mask(Y[k], model, variables, three_d_tensor=True)
        np.testing.assert_allclose(np.asarray(batched[k]), single, atol=1e-6)
