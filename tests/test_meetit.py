"""Tests for the MEETIT generator (gen_meetit parity)."""
import numpy as np
import pytest

from disco_tpu.datagen import (
    check_sir_validity,
    get_masks,
    get_value_range,
    simulate_meetit_room,
)
from disco_tpu.datagen.meetit import save_meetit_scene, sir_at_node
from disco_tpu.io import DatasetLayout, write_wav
from disco_tpu.sim import InterferentSpeakersSetup, make_setup

FS = 16000


def test_get_value_range():
    np.testing.assert_allclose(get_value_range(0, 100, 0, 20, 5), [0, 4])
    np.testing.assert_allclose(get_value_range(99, 100, 0, 20, 5), [16, 20])


def test_sir_at_node_known_ratio(rng):
    s = rng.standard_normal((4, 16000))
    n = 0.1 * rng.standard_normal((4, 16000))
    assert sir_at_node(s, n) == pytest.approx(20.0, abs=0.5)


def test_check_sir_validity():
    # Inter-node spread > 2 dB -> reject.
    assert not check_sir_validity([10.0, 5.0], [], bin_level=5)
    # Out of [2, 14] range -> reject.
    assert not check_sir_validity([1.0, 1.0], [], bin_level=5)
    assert not check_sir_validity([15.0, 15.0], [], bin_level=5)
    # Valid and empty history -> accept.
    assert check_sir_validity([5.0, 5.0], [], bin_level=2)
    # Class already full -> reject.
    past = [[5.1, 5.0], [5.2, 5.0]]
    assert not check_sir_validity([5.0, 5.0], past, bin_level=2)
    # Another class still open -> accept.
    assert check_sir_validity([12.0, 12.0], past, bin_level=2)


@pytest.fixture
def speakers(tmp_path):
    rng = np.random.default_rng(0)
    files = []
    for spk in ("201", "202", "203", "204", "205"):
        d = tmp_path / "speech" / spk / "1"
        d.mkdir(parents=True)
        f = d / f"{spk}-1-0001.wav"
        t = np.arange(7 * FS) / FS
        env = (np.sin(2 * np.pi * (1.0 + 0.1 * int(spk[-1])) * t) > -0.3).astype(np.float64)
        write_wav(f, 0.3 * env * rng.standard_normal(len(t)), FS)
        files.append(str(f))
    return files


def test_simulate_meetit_room_end_to_end(tmp_path, speakers):
    rng = np.random.default_rng(1)
    setup = make_setup("meetit", rng=rng, n_sensors_per_node=(2, 2, 2, 2))
    sig = InterferentSpeakersSetup(
        speakers_list=speakers,
        duration_range=(5, 6),
        var_tar=10 ** (-23 / 10),
        snr_dry_range=[[0, 0]],
        snr_cnv_range=(-10, 15),
        min_delta_snr=0,
        rng=rng,
    )
    mics_per_node = (2, 2, 2, 2)
    scene = None
    for _ in range(20):
        cfg = setup.create_room_setup()
        # Wide accept gate for the tiny test: vmin/vmax via monkey bin level.
        out = simulate_meetit_room(
            cfg, sig, "train", mics_per_node, past_sirs=[], n_rirs_per_proc=1000,
            max_order=4, rng=rng, sir_vmin=-10.0, sir_vmax=10.0,
        )
        if out != "redraw_room_setup":
            scene = out
            break
    assert scene is not None, "no valid meetit room in 20 draws"
    n_src = len(cfg.source_positions)
    assert scene.images.shape[0] == n_src and scene.images.shape[1] == 8
    assert scene.sirs.shape == (4,)

    # Masks: per source, per channel, in [0, 1], summing to ~1 across sources
    # where there is energy.
    mix, masks = get_masks(scene.images, mics_per_node)
    assert mix.shape[0] == 8 and masks.shape[0] == n_src
    assert masks.min() >= 0 and masks.max() <= 1

    lay = DatasetLayout(str(tmp_path / "out"), "meetit", "train")
    save_meetit_scene(scene, {"sirs": scene.sirs}, 3, lay)
    assert (lay.base / "wav" / "clean" / "dry" / "3_S-1.wav").exists()
    assert (lay.base / "wav" / "clean" / "cnv" / f"3_S-{n_src}_Ch-8.wav").exists()
    assert (lay.base / "log" / "infos" / "3.npy").exists()


def test_meetit_corpus_feeds_separation(tmp_path, speakers):
    """Saved MEETIT artifacts (mix STFTs + per-source IRMs) drive
    separate_with_masks directly — the corpus -> separation bridge of the
    ICASSP 2021 use case."""
    from disco_tpu.datagen.meetit import generate_meetit_rirs, load_meetit_sample
    from disco_tpu.enhance import separate_with_masks

    sig = InterferentSpeakersSetup(
        speakers_list=speakers,
        duration_range=(2, 3),
        var_tar=10 ** (-23 / 10),
        snr_dry_range=[[0, 0]],
        snr_cnv_range=(-60, 60),
        min_delta_snr=-1,
        rng=np.random.default_rng(3),
    )
    lay = DatasetLayout(str(tmp_path), "meetit", "train")
    done = generate_meetit_rirs(2, "train", 7, 1, sig, lay, rng=np.random.default_rng(1), max_order=4)
    assert done == [7]

    Y, masks = load_meetit_sample(lay, 7, [4, 4])
    assert Y.shape[0] == 2 and masks.shape[0] == 2
    est = np.asarray(separate_with_masks(Y, masks))
    assert est.shape == (2, 2) + Y.shape[2:]
    assert np.isfinite(est).all()
