"""Tests for the corpus-acquisition subsystem (reference pre_generation/),
driven entirely by a fake Freesound client — zero network."""
import csv
import logging
import os

import pytest

from disco_tpu.datagen.download import (
    DownloadConfig,
    FreesoundInquirer,
    clean_info,
    download_freesound,
    extract_category_ids,
    get_missing,
    limit_exec,
    serial_exec,
    set_up_log,
    update_csv,
)


class FakeSound:
    def __init__(self, sid, name="snd"):
        self.id = sid
        self.name = name
        self.retrieved = []

    def retrieve(self, output_dir, name=None):
        path = os.path.join(output_dir, name)
        with open(path, "wb") as fh:
            fh.write(b"RIFFfake")
        self.retrieved.append(path)


class FakePage:
    def __init__(self, sounds, has_next):
        self.sounds = sounds
        self._next = "url" if has_next else None

    def as_dict(self):
        return {"next": self._next}

    def __iter__(self):
        return iter(self.sounds)


class FakeClient:
    """freesound.FreesoundClient-shaped test double; serves 2 pages then
    stops (the reference's pagination-until-no-next loop)."""

    def __init__(self, per_page=3):
        self.calls = []
        self.per_page = per_page

    def text_search(self, **kwargs):
        self.calls.append(kwargs)
        page = kwargs.get("page", 1)
        base = 100 * page
        return FakePage([FakeSound(str(base + i)) for i in range(self.per_page)], has_next=page < 2)


def test_config_promotes_string_queries():
    cfg = DownloadConfig(queries={"fan": "fan vent", "baby": ["baby cry", "infant"]})
    assert cfg.queries["fan"] == ["fan vent"]
    assert cfg.queries["baby"] == ["baby cry", "infant"]


def test_config_requires_source():
    with pytest.raises(ValueError):
        DownloadConfig()


def test_config_from_yaml(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text("queries:\n  fan: fan vent\nfields_to_save: ['id']\nmin_duration: 3\n")
    cfg = DownloadConfig.from_yaml(p)
    assert cfg.min_duration == 3 and cfg.fields_to_save == ("id",)


def test_queries_pagination():
    client = FakeClient()
    inq = FreesoundInquirer(client)
    pages = list(inq.queries_to_files(["vacuum"], ["id"], min_duration=3))
    # ALL pages yielded, including the final one (the reference drops it —
    # not reproduced, SURVEY.md §7)
    assert len(pages) == 2
    assert client.calls[0]["filter"] == "duration:[3 TO *]"
    assert client.calls[0]["page_size"] == 150


def test_ids_batched_200():
    client = FakeClient()
    inq = FreesoundInquirer(client)
    ids = [str(i) for i in range(450)]
    pages = list(inq.ids_to_files(ids, ["id"]))
    assert len(pages) == 6  # 3 id batches (200+200+50) x 2 pages each
    assert "id:(0 OR 1" in client.calls[0]["filter"]
    assert client.calls[0]["page_size"] == 150  # batches are paginated


def test_extract_category_ids(tmp_path):
    p = tmp_path / "ids.csv"
    p.write_text(",fan,baby\n0,11,21\n1,12,22\n2,13,\n")
    out = extract_category_ids(p)
    assert out == {"fan": ["11", "12"], "baby": ["21", "22"]}  # dropna row 2


def test_update_csv_dedup_and_sort(tmp_path):
    p = tmp_path / "info.csv"
    update_csv({"id": ["3", "1"], "name": ["c", "a"]}, p, sort_label="id", sep="\t")
    update_csv({"id": ["2", "1"], "name": ["b", "a"]}, p, sort_label="id", sep="\t")
    with open(p) as fh:
        rows = list(csv.reader(fh, delimiter="\t"))
    assert rows[0] == ["id", "name"]
    assert [r[0] for r in rows[1:]] == ["1", "2", "3"]  # deduped + sorted


def test_limit_exec_sleeps_after_quota():
    sleeps = []
    t = [0.0]

    def clock():
        t[0] += 0.1
        return t[0]

    @limit_exec(max_per_minute=3, sleep=sleeps.append, clock=clock)
    def f():
        return 1

    for _ in range(7):
        f()
    # two full quotas of 3 -> two sleeps of just under 60 s
    assert len(sleeps) == 2 and all(55 < s < 60 for s in sleeps)


def test_download_freesound_end_to_end(tmp_path):
    cfg = DownloadConfig(queries={"fan": "fan vent"}, fields_to_save=["id"], min_duration=3)
    client = FakeClient()
    n = download_freesound(cfg, FreesoundInquirer(client), str(tmp_path), num_jobs=1)
    assert n == 6  # both pages downloaded
    wavs = sorted(os.listdir(tmp_path / "fan"))
    assert "100.wav" in wavs and "200.wav" in wavs and "fan.csv" in wavs


def test_download_freesound_by_ids(tmp_path):
    ids_csv = tmp_path / "ids.csv"
    ids_csv.write_text(",fan\n0,11\n1,12\n")
    cfg = DownloadConfig(id_file=str(ids_csv), fields_to_save=["id"])
    n = download_freesound(cfg, FreesoundInquirer(FakeClient()), str(tmp_path / "out"))
    assert n == 6
    assert (tmp_path / "out" / "fan" / "fan.csv").exists()


def test_csv_disk_reconciliation(tmp_path):
    d = tmp_path / "fan"
    d.mkdir()
    (d / "11.wav").write_bytes(b"x")
    (d / "12.wav").write_bytes(b"x")
    (d / "99.wav").write_bytes(b"x")  # on disk, not in csv
    p = d / "fan.csv"
    p.write_text("id\tname\n11\ta\n12\tb\n13\tc\n", )  # 13 in csv, not on disk
    assert get_missing(p) == ["99.wav"]
    dropped = clean_info(p)
    assert dropped == 1
    with open(p) as fh:
        rows = [r.split("\t")[0] for r in fh.read().splitlines()[1:]]
    assert rows == ["11", "12"]


def test_set_up_log_file(tmp_path):
    log = set_up_log(str(tmp_path / "x" / "run.log"), level=1)
    log.info("hello")
    logging.shutdown()
    assert "hello" in (tmp_path / "x" / "run.log").read_text()


def test_serial_exec():
    assert serial_exec(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]


def test_download_cli_list_urls(capsys):
    from disco_tpu.cli.download import main

    assert main(["--list-urls"]) == 0
    out = capsys.readouterr().out
    assert "openslr.org" in out and "zenodo.org" in out


def test_download_cli_clean(tmp_path, capsys):
    from disco_tpu.cli.download import main

    d = tmp_path / "fan"
    d.mkdir()
    (d / "11.wav").write_bytes(b"x")
    (d / "fan.csv").write_text("id\tname\n11\ta\n13\tc\n")
    assert main(["--clean", str(tmp_path)]) == 0  # exit code, not count
    assert "dropped 1 stale csv rows" in capsys.readouterr().out


def test_download_dispatcher_rate_limits(tmp_path):
    """Rate limiting is enforced at the dispatcher: one sleep per full batch
    of max_per_minute downloads, regardless of worker count."""
    sleeps = []
    cfg = DownloadConfig(queries={"fan": "fan vent"}, fields_to_save=["id"])
    client = FakeClient(per_page=5)
    download_freesound(
        cfg, FreesoundInquirer(client), str(tmp_path),
        max_per_minute=2, sleep=sleeps.append, clock=lambda: 0.0,
    )
    # 5 sounds per page -> batches of 2: sleeps between batches (2 per page)
    assert len(sleeps) == 4 and all(s == 60 for s in sleeps)
