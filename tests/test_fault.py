"""Fault subsystem tests: spec parsing, seeded injection determinism,
degraded-mode correctness against the K-1-subset float64 oracle, the
streaming last-good-z hold, the resilience retry wrapper, and the tunnel
transfer guard."""
import dataclasses
import json

import numpy as np
import pytest

from disco_tpu.fault import FaultPlan, FaultSpec, load_fault_spec, plan_faults

K, C, L = 3, 2, 16384


def _scene(rng, K=K, C=C, L=L):
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same")
                   for _ in range(C)]) for _ in range(K)]
    )
    n = 0.8 * rng.standard_normal((K, C, L))
    return s + n, s, n


@pytest.fixture(scope="module")
def scene():
    return _scene(np.random.default_rng(7))


@pytest.fixture(scope="module")
def stfts(scene):
    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.tango import oracle_masks

    y, s, n = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    return Y, S, N, masks


# -- spec -------------------------------------------------------------------
def test_spec_defaults_and_validation():
    spec = FaultSpec()
    assert not spec.any_fault()
    spec = FaultSpec(node_dropout=[1], nan_z=(2,), link_loss_prob=0.5)
    assert spec.any_fault() and spec.node_dropout == (1,) and spec.nan_z == (2,)
    spec.validate_for(4)
    with pytest.raises(ValueError, match="names node"):
        spec.validate_for(2)
    with pytest.raises(ValueError, match="probability"):
        FaultSpec(link_loss_prob=1.5)
    with pytest.raises(ValueError, match="node ids"):
        FaultSpec(node_dropout=[-1])
    with pytest.raises(ValueError, match="unknown field"):
        FaultSpec.from_dict({"node_droput": [1]})
    # bool is an int subclass: 'node_dropout: true' must not become node 1
    with pytest.raises(ValueError, match="node ids"):
        FaultSpec(node_dropout=True)
    with pytest.raises(ValueError, match="node ids"):
        FaultSpec(nan_z=[True, 2])


def test_spec_file_roundtrip(tmp_path):
    spec = FaultSpec(seed=3, node_dropout=(1,), link_loss_prob=0.25, nan_z=(0,))
    js = tmp_path / "spec.json"
    js.write_text(json.dumps(spec.to_dict()))
    assert load_fault_spec(js) == spec
    yml = tmp_path / "spec.yaml"
    yml.write_text("seed: 3\nnode_dropout: [1]\nlink_loss_prob: 0.25\nnan_z: [0]\n")
    assert load_fault_spec(yml) == spec
    assert load_fault_spec(spec) is spec
    assert load_fault_spec(spec.to_dict()) == spec
    bad = tmp_path / "bad.yaml"
    bad.write_text("- just\n- a list\n")
    with pytest.raises(ValueError, match="mapping"):
        load_fault_spec(bad)
    # malformed YAML and bad field types surface as ValueError (the CLI
    # renders those as clean errors naming the file, never a traceback)
    broken = tmp_path / "broken.yaml"
    broken.write_text("node_dropout: [1,\n")
    with pytest.raises(ValueError, match="not valid YAML"):
        load_fault_spec(broken)
    with pytest.raises(ValueError, match="'seed'"):
        FaultSpec(seed=None)


# -- injector ----------------------------------------------------------------
def test_plan_deterministic_same_seed():
    spec = FaultSpec(seed=5, dropout_prob=0.3, link_loss_prob=0.2,
                     stale_prob=0.1, nan_prob=0.2)
    a = plan_faults(spec, n_nodes=6, n_blocks=20)
    b = plan_faults(spec, n_nodes=6, n_blocks=20)
    np.testing.assert_array_equal(a.avail, b.avail)
    np.testing.assert_array_equal(a.z_nan, b.z_nan)
    assert a.faults == b.faults
    c = plan_faults(dataclasses.replace(spec, seed=6), n_nodes=6, n_blocks=20)
    assert not (np.array_equal(a.avail, c.avail) and np.array_equal(a.z_nan, c.z_nan)
                and a.faults == c.faults)


def test_plan_explicit_faults_and_views():
    plan = plan_faults(FaultSpec(node_dropout=(1,), nan_z=(2,)), n_nodes=4, n_blocks=3)
    assert isinstance(plan, FaultPlan)
    np.testing.assert_array_equal(plan.avail_offline, [1, 0, 1, 1])
    np.testing.assert_array_equal(plan.z_nan, [False, False, True, False])
    # streaming view folds the NaN node into unavailability
    np.testing.assert_array_equal(plan.avail_streaming[2], [0, 0, 0])
    kinds = sorted(f["fault"] for f in plan.faults)
    assert kinds == ["nan_z", "node_dropout"]
    # a dropped node is never additionally NaN-corrupted
    plan2 = plan_faults(FaultSpec(node_dropout=(1,), nan_z=(1,)), n_nodes=4)
    assert not plan2.z_nan.any()


def test_plan_link_loss_restricted_nodes():
    spec = FaultSpec(seed=1, link_loss_prob=0.8, link_loss_nodes=(0,))
    plan = plan_faults(spec, n_nodes=3, n_blocks=50)
    assert (plan.avail[1:] == 1.0).all()  # only node 0 may lose blocks
    assert (plan.avail[0] == 0.0).any()


def test_plan_records_fault_events_and_counters(tmp_path):
    from disco_tpu import obs

    plan = plan_faults(FaultSpec(node_dropout=(0,), nan_z=(1,)), n_nodes=3)
    log = tmp_path / "faults.jsonl"
    with obs.recording(log):
        plan.record(mode="offline")
    events = obs.read_events(log)
    kinds = sorted(e["attrs"]["fault"] for e in events if e["kind"] == "fault")
    assert kinds == ["nan_z", "node_dropout"]
    assert all(e["attrs"]["mode"] == "offline" for e in events if e["kind"] == "fault")


# -- degraded-mode correctness ----------------------------------------------
@pytest.fixture(scope="module")
def subset_oracle(scene):
    """Float64 NumPy oracle run on the K-1 subset (node 1 removed)."""
    from tests.reference_impls import tango_np

    y, s, n = scene
    keep = np.array([0, 2])
    return tango_np(y[keep], s[keep], n[keep], mask_type="irm1", mask_for_z="local"), keep


def test_dropout_matches_subset_oracle(stfts, subset_oracle):
    """With node 1 masked out, each surviving node's output matches the
    float64 oracle on the K-1 subset within the existing parity tolerances
    (the acceptance bar of ISSUE 2)."""
    from disco_tpu.enhance.tango import tango

    Y, S, N, masks = stfts
    want, keep = subset_oracle
    res = tango(Y, S, N, masks, masks, policy="local", solver="eigh",
                z_mask=np.array([1.0, 0.0, 1.0], np.float32))
    for i, k in enumerate(keep):
        got = np.asarray(res.yf[k])
        ref = want["yf"][i]
        err = np.linalg.norm(got - ref) / np.linalg.norm(ref)
        assert err < 1e-1, (k, err)  # test_tango.test_step2_output_parity tol
        pw = np.linalg.norm(ref, axis=-1)
        hi = pw > np.percentile(pw, 50)
        err_hi = np.linalg.norm((got - ref)[hi]) / np.linalg.norm(ref[hi])
        assert err_hi < 5e-2, (k, err_hi)


def test_dropout_sdr_matches_subset_oracle(scene, stfts, subset_oracle):
    from disco_tpu.core.dsp import istft
    from disco_tpu.core.metrics import si_sdr
    from disco_tpu.enhance.tango import tango

    from tests.reference_impls import istft_np, si_sdr_np

    y, s, n = scene
    Y, S, N, masks = stfts
    want, keep = subset_oracle
    res = tango(Y, S, N, masks, masks, policy="local", solver="eigh",
                z_mask=np.array([1.0, 0.0, 1.0], np.float32))
    for i, k in enumerate(keep):
        ours = si_sdr(s[k, 0], np.asarray(istft(res.yf[k], L), np.float64))
        oracle = si_sdr_np(s[k, 0], istft_np(want["yf"][i], L))
        assert abs(ours - oracle) < 0.1, (k, ours, oracle)


def test_dropout_matches_subset_pipeline_tight(stfts):
    """Masked full-K run vs our own pipeline on the physical K-1 subset:
    same precision on both sides, so agreement is at f32 roundoff — the
    channel masking + covariance regularization is exactly the subset MWF,
    for the eigh anchor AND the 'power' pipeline default."""
    from disco_tpu.enhance.tango import tango

    Y, S, N, masks = stfts
    keep = np.array([0, 2])
    Yk, Sk, Nk, mk = (np.asarray(a)[keep] for a in (Y, S, N, masks))
    for solver in ("eigh", "power"):
        res_m = tango(Y, S, N, masks, masks, policy="local", solver=solver,
                      z_mask=np.array([1.0, 0.0, 1.0], np.float32))
        res_s = tango(Yk, Sk, Nk, mk, mk, policy="local", solver=solver)
        for i, k in enumerate(keep):
            a, b = np.asarray(res_m.yf[k]), np.asarray(res_s.yf[i])
            err = np.linalg.norm(a - b) / np.linalg.norm(b)
            assert err < 1e-4, (solver, k, err)


def test_nan_z_guard_detects_and_excludes(stfts):
    """NaN-corrupted z (injected at the exchange seam) is detected by the
    finiteness guard and excluded: every node's output is finite and equals
    the explicit-mask run."""
    from disco_tpu.enhance.tango import tango

    Y, S, N, masks = stfts
    res_nan = tango(Y, S, N, masks, masks, policy="local", solver="eigh",
                    z_nan=np.array([False, True, False]))
    yf = np.asarray(res_nan.yf)
    assert np.isfinite(yf).all()
    res_m = tango(Y, S, N, masks, masks, policy="local", solver="eigh",
                  z_mask=np.array([1.0, 0.0, 1.0], np.float32))
    np.testing.assert_allclose(yf[[0, 2]], np.asarray(res_m.yf)[[0, 2]],
                               rtol=1e-4, atol=1e-6)


def test_all_links_down_degrades_to_local_mwf(stfts):
    """K-1 = 0 available streams: each node falls back to beamforming on
    its own mics — finite output everywhere."""
    from disco_tpu.enhance.tango import tango

    Y, S, N, masks = stfts
    res = tango(Y, S, N, masks, masks, policy="local", solver="eigh",
                z_mask=np.zeros(K, np.float32))
    yf = np.asarray(res.yf)
    assert np.isfinite(yf).all()


def test_receiver_specific_link_mask(stfts):
    """(K, K) asymmetric availability: only node 0's inbound link from node
    1 is down; node 2 still consumes z_1, so their outputs differ from a
    global dropout but node 0's matches its subset run."""
    from disco_tpu.enhance.tango import tango

    Y, S, N, masks = stfts
    zm = np.ones((K, K), np.float32)
    zm[0, 1] = 0.0
    res = tango(Y, S, N, masks, masks, policy="local", solver="eigh", z_mask=zm)
    res_drop = tango(Y, S, N, masks, masks, policy="local", solver="eigh",
                     z_mask=np.array([1.0, 0.0, 1.0], np.float32))
    res_clean = tango(Y, S, N, masks, masks, policy="local", solver="eigh")
    # node 0 sees the dropout; node 2 does not
    np.testing.assert_allclose(np.asarray(res.yf[0]), np.asarray(res_drop.yf[0]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(res.yf[2]), np.asarray(res_clean.yf[2]),
                               rtol=1e-4, atol=1e-6)


def test_fault_injection_end_to_end_deterministic(stfts, tmp_path):
    """Same spec + seed -> identical events and identical outputs (the
    determinism half of the ISSUE 2 acceptance criteria)."""
    from disco_tpu import obs
    from disco_tpu.enhance.tango import tango

    Y, S, N, masks = stfts
    spec = FaultSpec(seed=9, dropout_prob=0.4, nan_prob=0.4)

    def run(tag):
        plan = plan_faults(spec, n_nodes=K, n_blocks=1)
        log = tmp_path / f"{tag}.jsonl"
        with obs.recording(log):
            plan.record(mode="offline")
        res = tango(Y, S, N, masks, masks, policy="local", solver="eigh",
                    z_mask=plan.avail_offline,
                    z_nan=plan.z_nan if plan.z_nan.any() else None)
        events = [{k: v for k, v in e.items() if k != "t"}
                  for e in obs.read_events(log)]
        return events, np.asarray(res.yf)

    ev1, yf1 = run("a")
    ev2, yf2 = run("b")
    assert ev1 == ev2
    np.testing.assert_array_equal(yf1, yf2)
    assert np.isfinite(yf1).all()


def test_nonlocal_policy_degraded_finite(stfts):
    """The stat-shaping policies also run degraded (stats and application
    channels are masked consistently)."""
    from disco_tpu.enhance.tango import tango

    Y, S, N, masks = stfts
    for policy in ("none", "distant", "compressed"):
        res = tango(Y, S, N, masks, masks, policy=policy, solver="eigh",
                    z_mask=np.array([1.0, 0.0, 1.0], np.float32),
                    z_nan=np.array([False, False, True]))
        # node 1 dropped AND node 2 corrupted: only local mics + nothing left
        assert np.isfinite(np.asarray(res.yf)).all(), policy


# -- sharded paths -----------------------------------------------------------
@pytest.fixture(scope="module")
def scene4():
    """4-node scene: divisible over 2- and 4-device mesh axes."""
    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.tango import oracle_masks

    y, s, n = _scene(np.random.default_rng(5), K=4)
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    return Y, S, N, masks


def test_sharded_fault_mask_matches_single_device(scene4):
    """The (K,) availability mask rides the z-exchange all_gather: the
    node-sharded pipeline with a dropout + a NaN'd z matches the
    single-device tango(z_mask=...) bit-for-bit (same math, different
    placement — the mask and guard verdicts must agree on every device)."""
    from disco_tpu.enhance.tango import tango
    from disco_tpu.parallel import make_mesh, tango_sharded

    Y, S, N, masks = scene4
    zm = np.array([1.0, 0.0, 1.0, 1.0], np.float32)
    want = tango(Y, S, N, masks, masks, policy="local", solver="eigh", z_mask=zm)
    mesh = make_mesh(n_node=4, n_batch=1)
    got = tango_sharded(Y, S, N, masks, masks, mesh, policy="local",
                        solver="eigh", z_mask=zm)
    np.testing.assert_array_equal(np.asarray(got.yf), np.asarray(want.yf))
    assert np.isfinite(np.asarray(got.yf)).all()


def test_frame_sharded_fault_mask_matches_single_device(scene4):
    """Sequence-parallel mode: the finiteness-guard verdict is
    pmin-combined across frame shards, so exclusion is consistent on every
    shard and the result matches the single-device run."""
    from disco_tpu.enhance.tango import tango
    from disco_tpu.parallel import make_mesh_2d, tango_frame_sharded

    Y, S, N, masks = scene4
    T = np.asarray(Y).shape[-1] // 2 * 2  # trim to a frame-shardable length
    Yt, St, Nt, mt = (np.asarray(a)[..., :T] for a in (Y, S, N, masks))
    zm = np.array([1.0, 0.0, 1.0, 1.0], np.float32)
    want = tango(Yt, St, Nt, mt, mt, policy="local", solver="eigh", z_mask=zm)
    mesh = make_mesh_2d(n_node=4, n_frame=2)
    got = tango_frame_sharded(Yt, St, Nt, mt, mt, mesh, policy="local",
                              solver="eigh", z_mask=zm)
    err = (np.linalg.norm(np.asarray(got.yf) - np.asarray(want.yf))
           / np.linalg.norm(np.asarray(want.yf)))
    assert err < 1e-5, err  # psum'd covariances: f32 roundoff, not bitwise


def test_batch_sharded_fault_masks_match_single_device(scene4):
    """tango_batch_sharded with per-clip (B, K) masks + NaN flags (the
    enhance_rirs_batched mesh path): each clip matches its single-device
    degraded run, and a NaN'd clip stays finite."""
    from disco_tpu.enhance.tango import tango
    from disco_tpu.parallel import make_mesh, tango_batch_sharded

    Y, S, N, masks = scene4
    Ya, Sa, Na, ma = (np.asarray(a) for a in (Y, S, N, masks))
    Yb, Sb, Nb = np.stack([Ya, Ya * 0.5]), np.stack([Sa, Sa * 0.5]), np.stack([Na, Na * 0.5])
    mb = np.stack([ma, ma])
    zmb = np.stack([[1.0, 0.0, 1.0, 1.0], [1.0, 1.0, 1.0, 1.0]]).astype(np.float32)
    znb = np.zeros((2, 4), bool)
    znb[1, 2] = True
    mesh = make_mesh(n_node=4, n_batch=2)
    got = tango_batch_sharded(Yb, Sb, Nb, mb, mb, mesh, policy="local",
                              solver="eigh", z_mask_b=zmb, z_nan_b=znb)
    yf = np.asarray(got.yf)
    assert np.isfinite(yf).all()
    want0 = tango(Ya, Sa, Na, ma, ma, policy="local", solver="eigh",
                  z_mask=zmb[0])
    want1 = tango(Ya * 0.5, Sa * 0.5, Na * 0.5, ma, ma, policy="local",
                  solver="eigh", z_nan=znb[1])
    np.testing.assert_array_equal(yf[0], np.asarray(want0.yf))
    np.testing.assert_array_equal(yf[1], np.asarray(want1.yf))


# -- streaming hold ----------------------------------------------------------
def test_hold_last_good_matches_numpy_ffill(rng):
    from disco_tpu.enhance.streaming import hold_last_good

    Kh, F, T, u = 3, 5, 26, 4
    B = -(-T // u)
    z = (rng.standard_normal((Kh, F, T)) + 1j * rng.standard_normal((Kh, F, T))).astype(np.complex64)
    fb = (rng.standard_normal((Kh, F, T)) + 1j * rng.standard_normal((Kh, F, T))).astype(np.complex64)
    avail = (rng.random((Kh, B)) > 0.4).astype(np.float32)
    held = np.asarray(hold_last_good(z, avail, u, fallback=fb))

    pad = (-T) % u
    zp = np.pad(z, ((0, 0), (0, 0), (0, pad)))
    fp = np.pad(fb, ((0, 0), (0, 0), (0, pad)))
    zb = zp.reshape(Kh, F, B, u)
    fbb = fp.reshape(Kh, F, B, u)
    out = np.empty_like(zb)
    for k in range(Kh):
        last = None  # last emitted block once ANY delivery has happened
        for b in range(B):
            if avail[k, b] > 0:
                out[k, :, b] = zb[k, :, b]
                last = out[k, :, b]
            elif last is not None:
                out[k, :, b] = last
            else:
                # before the first delivery: each lost block uses its own
                # (time-aligned) fallback block
                out[k, :, b] = fbb[k, :, b]
    want = out.reshape(Kh, F, B * u)[..., :T]
    np.testing.assert_allclose(held, want, atol=0)


def test_hold_never_leaks_nan(rng):
    """A lost block full of NaN must never reach the output (where-select,
    not multiplication)."""
    from disco_tpu.enhance.streaming import hold_last_good

    z = rng.standard_normal((1, 4, 8)).astype(np.complex64)
    z[0, :, 4:] = np.nan
    avail = np.array([[1.0, 0.0]], np.float32)  # u=4: block 1 lost
    held = np.asarray(hold_last_good(z, avail, 4))
    assert np.isfinite(held).all()
    np.testing.assert_allclose(held[0, :, 4:], z[0, :, :4], atol=0)


def test_streaming_all_available_identical_and_degraded_finite(stfts):
    from disco_tpu.enhance.streaming import DEFAULT_UPDATE_EVERY, streaming_tango

    Y, _, _, masks = stfts
    T = np.asarray(Y).shape[-1]
    B = -(-T // DEFAULT_UPDATE_EVERY)
    base = streaming_tango(Y, masks, masks)
    ones = streaming_tango(Y, masks, masks, z_avail=np.ones((K, B), np.float32))
    np.testing.assert_array_equal(np.asarray(base["yf"]), np.asarray(ones["yf"]))

    avail = np.ones((K, B), np.float32)
    avail[1, B // 3: 2 * B // 3] = 0.0  # transient mid-stream link loss
    deg = streaming_tango(Y, masks, masks, z_avail=avail)
    assert np.isfinite(np.asarray(deg["yf"])).all()
    assert not np.allclose(np.asarray(deg["yf"]), np.asarray(base["yf"]))
    # (K,) shorthand broadcasts over blocks
    deg2 = streaming_tango(Y, masks, masks, z_avail=np.array([1, 0, 1], np.float32))
    assert np.isfinite(np.asarray(deg2["yf"])).all()


def test_streaming_chunked_fault_continuation_exact(stfts):
    """A loss straddling a chunk boundary is bridged with the PREVIOUS
    chunk's last good block: the hold carry rides the continuation state,
    so chunked == unchunked (refresh-block-aligned split, same contract as
    the covariance-state continuation)."""
    import jax

    from disco_tpu.enhance.streaming import DEFAULT_UPDATE_EVERY, streaming_tango

    Y, _, _, masks = stfts
    u = DEFAULT_UPDATE_EVERY
    T = np.asarray(Y).shape[-1]
    B = -(-T // u)
    B1 = B // 2
    T1 = B1 * u  # block-aligned chunk split
    avail = np.ones((K, B), np.float32)
    # node 2's z lost from the last block of chunk 1 THROUGH chunk 2's start
    avail[2, B1 - 1: B1 + 3] = 0.0

    full = streaming_tango(Y, masks, masks, z_avail=avail)
    c1 = streaming_tango(Y[..., :T1], masks[..., :T1], masks[..., :T1],
                         z_avail=avail[:, :B1])
    c2 = streaming_tango(Y[..., T1:], masks[..., T1:], masks[..., T1:],
                         z_avail=avail[:, B1:], state=c1["state"])
    got = np.concatenate([np.asarray(c1["yf"]), np.asarray(c2["yf"])], axis=-1)
    np.testing.assert_allclose(got, np.asarray(full["yf"]), rtol=2e-4, atol=1e-5)
    # the carry is part of the state pytree
    assert "hold" in c1["state"]
    jax.tree_util.tree_leaves(c1["state"]["hold"])  # well-formed pytree


# -- resilience --------------------------------------------------------------
def test_call_with_retries_recovers_and_records(tmp_path):
    from disco_tpu import obs
    from disco_tpu.utils.resilience import call_with_retries

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError(f"tunnel hiccup {calls['n']}")
        return 42

    slept = []
    log = tmp_path / "retry.jsonl"
    with obs.recording(log):
        out = call_with_retries(flaky, retries=3, base_delay_s=0.01,
                                label="fetch", sleep=slept.append)
    assert out == 42 and calls["n"] == 3
    assert slept == [0.01, 0.02]  # deterministic exponential backoff
    events = obs.read_events(log)
    kinds = [e["kind"] for e in events]
    assert kinds.count("fault") == 2 and kinds.count("recovery") == 1
    assert all(e["stage"] == "fetch" for e in events)


def test_call_with_retries_gives_up_and_raises():
    from disco_tpu.obs.metrics import REGISTRY
    from disco_tpu.utils.resilience import call_with_retries

    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise TimeoutError("dead link")

    before = REGISTRY.counter("retry_giveups").value
    with pytest.raises(TimeoutError, match="dead link"):
        call_with_retries(always_fails, retries=2, base_delay_s=0.0, sleep=lambda _: None)
    assert calls["n"] == 3  # initial + 2 retries, never more
    assert REGISTRY.counter("retry_giveups").value == before + 1


def test_call_with_retries_deadline():
    from disco_tpu.utils.resilience import DeadlineExceeded, call_with_retries

    def always_fails():
        raise OSError("down")

    with pytest.raises(DeadlineExceeded, match="deadline"):
        call_with_retries(always_fails, retries=100, base_delay_s=10.0,
                          deadline_s=0.001, sleep=lambda _: None)


def test_retrying_decorator_and_resilient_transfer():
    from disco_tpu.utils.resilience import resilient_to_device, resilient_to_host, retrying

    attempts = {"n": 0}

    @retrying(retries=1, base_delay_s=0.0, sleep=lambda _: None)
    def once_flaky(x):
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise RuntimeError("first call fails")
        return x * 2

    assert once_flaky(21) == 42
    z = np.arange(6, dtype=np.complex64).reshape(2, 3) * (1 + 1j)
    dev = resilient_to_device(z)
    np.testing.assert_allclose(resilient_to_host(dev), z)

    # the wrapped function's kwargs never collide with the retry options
    @retrying(retries=1, base_delay_s=0.0, sleep=lambda _: None, label="kw")
    def takes_retry_named_kwargs(x, retries=0, label="inner"):
        return (x, retries, label)

    assert takes_retry_named_kwargs(1, retries=9, label="mine") == (1, 9, "mine")


def test_transport_errors_narrow_the_wired_seams():
    """The always-on seams retry only transport-layer failures: a
    deterministic TypeError raises immediately (no sleep, no retry)."""
    from disco_tpu.utils.resilience import TRANSPORT_ERRORS, call_with_retries

    calls = {"n": 0}

    def buggy():
        calls["n"] += 1
        raise TypeError("bad dtype")

    def no_sleep(_):
        raise AssertionError("backoff must not run for a deterministic bug")

    with pytest.raises(TypeError, match="bad dtype"):
        call_with_retries(buggy, retries=3, retry_on=TRANSPORT_ERRORS, sleep=no_sleep)
    assert calls["n"] == 1
    assert ConnectionError in TRANSPORT_ERRORS and TimeoutError in TRANSPORT_ERRORS


def test_call_with_retries_zero_retries_single_attempt():
    """retries=0 means exactly ONE attempt: success passes through, failure
    raises immediately with no backoff sleep and a giveup tick."""
    from disco_tpu.obs.metrics import REGISTRY
    from disco_tpu.utils.resilience import call_with_retries

    assert call_with_retries(lambda: 7, retries=0) == 7

    calls = {"n": 0}

    def fails():
        calls["n"] += 1
        raise ConnectionError("one shot")

    def no_sleep(_):
        raise AssertionError("retries=0 must never back off")

    before = REGISTRY.counter("retry_giveups").value
    with pytest.raises(ConnectionError, match="one shot"):
        call_with_retries(fails, retries=0, sleep=no_sleep)
    assert calls["n"] == 1
    assert REGISTRY.counter("retry_giveups").value == before + 1


def test_call_with_retries_negative_retries_rejected():
    from disco_tpu.utils.resilience import call_with_retries

    with pytest.raises(ValueError, match="retries must be >= 0"):
        call_with_retries(lambda: 1, retries=-1)


def test_deadline_expires_mid_backoff():
    """The budget runs out BETWEEN attempts: earlier backoffs complete, the
    sleep that would cross the deadline is never taken, and the raised
    DeadlineExceeded chains the last underlying error."""
    from disco_tpu.obs.metrics import REGISTRY
    from disco_tpu.utils.resilience import DeadlineExceeded, call_with_retries

    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise OSError(f"down {calls['n']}")

    slept = []
    before = REGISTRY.counter("retry_giveups").value
    # delays would be 0.05, 0.10, 0.20; with ~0 elapsed wall time the 0.20
    # sleep is the first to cross deadline_s=0.12 — two backoffs happen,
    # the third is refused
    with pytest.raises(DeadlineExceeded, match="3 failed attempt") as ei:
        call_with_retries(always_fails, retries=100, base_delay_s=0.05,
                          backoff=2.0, max_delay_s=10.0, deadline_s=0.12,
                          sleep=slept.append)
    assert slept == [0.05, 0.10]
    assert calls["n"] == 3  # the refused sleep also ends the attempts
    assert isinstance(ei.value.__cause__, OSError)
    assert REGISTRY.counter("retry_giveups").value == before + 1


# -- tunnel transfer guard ---------------------------------------------------
def test_guard_tunnel_complex(monkeypatch):
    from disco_tpu.utils import transfer

    z = np.ones(4, np.complex64)
    transfer.guard_tunnel_complex(z)  # CPU backend: no-op

    monkeypatch.setattr(transfer, "_tunneled_attachment", lambda: True)
    with pytest.raises(transfer.TunnelTransferError, match="to_host / to_device"):
        transfer.guard_tunnel_complex(z, where="raw np.asarray")
    transfer.guard_tunnel_complex(np.ones(4, np.float32))  # real is fine
    # the sanctioned helpers still work on complex under the tunnel flag
    dev = transfer.to_device(z)
    np.testing.assert_allclose(transfer.to_host(dev), z)


def test_to_device_passthrough_for_device_arrays():
    """A device-resident array must NOT round-trip the host (for complex
    that raw round-trip is exactly what the tunnel cannot do)."""
    import jax.numpy as jnp

    from disco_tpu.utils.transfer import to_device

    x = jnp.asarray(np.ones(3, np.float32))
    assert to_device(x) is x
    z = to_device(np.ones(3, np.complex64))
    assert to_device(z) is z


# -- degraded scoring --------------------------------------------------------
def test_node_metrics_nan_stream_scores_as_nan(rng):
    """A corrupted (NaN) stream scores as NaN metrics with EXACTLY the same
    key set as a healthy node (so per-RIR pickles still stack), instead of
    crashing in the BSS projector's cho_solve."""
    from disco_tpu.core.bss import BssEval
    from disco_tpu.enhance.driver import _NODE_METRIC_KEYS, _node_metrics_pair

    fs, L = 16000, 32000
    s = rng.standard_normal(L)
    n = 0.5 * rng.standard_normal(L)
    y = s + n
    est = y * 0.8
    sl = slice(fs, L)
    proj_dry = BssEval(np.stack((s[sl], n[sl])), 256)
    bad = est.copy()
    bad[20000:] = np.nan
    tango_d, mwf_d = _node_metrics_pair(
        y, s, n, est, bad, s, n, est, n * 0.1, bad, bad, fs, sl, proj_dry,
        bss_filt_len=256,
    )
    assert set(tango_d) == set(_NODE_METRIC_KEYS)
    assert set(mwf_d) == set(_NODE_METRIC_KEYS)
    assert np.isfinite(tango_d["sdr_cnv"])
    assert all(np.isnan(v) for v in mwf_d.values())


# -- obs report rendering ----------------------------------------------------
def test_obs_report_renders_fault_events(tmp_path):
    """`disco-obs report` surfaces injected faults, retries/recoveries and
    the degraded-mode entry (the ISSUE 2 telemetry contract)."""
    from disco_tpu import obs
    from disco_tpu.cli.obs import render_report, summarize
    from disco_tpu.utils.resilience import call_with_retries

    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        plan = plan_faults(FaultSpec(node_dropout=(1,), nan_z=(2,)), n_nodes=4)
        plan.record(mode="offline")
        obs.record("degraded", stage="mwf", mode="offline",
                   n_streams_excluded=1, nodes=[1], nan_nodes=[2])
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] == 1:
                raise OSError("hiccup")
            return 0

        call_with_retries(flaky, retries=1, base_delay_s=0.0, label="fence",
                          sleep=lambda _: None)
    summary = summarize(obs.read_events(log))
    assert len(summary["faults"]) == 3  # dropout + nan_z + transient_error
    assert len(summary["recoveries"]) == 1 and len(summary["degraded"]) == 1
    text = render_report(summary)
    assert "node_dropout×1" in text and "nan_z×1" in text
    assert "transient_error@fence×1" in text
    assert "recoveries: fence×1" in text
    assert "DEGRADED mode at stage 'mwf'" in text


# -- the fault-check gate ----------------------------------------------------
def test_fault_check_smoke_passes(capsys):
    from disco_tpu.fault.check import main

    assert main() == 0
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rec["fault_check"] == "ok" and rec["n_fault_events"] == 2


def test_call_with_retries_seeded_jitter_pins_the_draw_sequence():
    """The jittered schedule is deterministic given the seed: exactly the
    ``random.Random(seed).random()`` stream, one draw per sleep, scaling
    each delay by ``1 - jitter * u`` — never above the un-jittered delay
    (deadline accounting stays conservative)."""
    import random

    from disco_tpu.utils.resilience import call_with_retries

    def run(seed, jitter):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise ConnectionError("hiccup")
            return "ok"

        slept = []
        assert call_with_retries(flaky, retries=5, base_delay_s=0.1,
                                 backoff=2.0, max_delay_s=10.0,
                                 jitter=jitter, jitter_seed=seed,
                                 sleep=slept.append) == "ok"
        return slept

    rng = random.Random(7)
    expect = [d * (1.0 - 0.5 * rng.random()) for d in (0.1, 0.2, 0.4)]
    assert run(7, 0.5) == expect                 # the pinned draw sequence
    assert run(7, 0.5) == expect                 # same seed, same schedule
    assert run(8, 0.5) != expect                 # different seed, different
    base = run(9, 0.0)
    assert base == [0.1, 0.2, 0.4]               # jitter=0: the old exact path
    for got, cap in zip(run(11, 1.0), (0.1, 0.2, 0.4)):
        assert 0.0 <= got <= cap                 # never above the deterministic delay


def test_call_with_retries_rejects_bad_jitter():
    from disco_tpu.utils.resilience import call_with_retries

    with pytest.raises(ValueError, match="jitter"):
        call_with_retries(lambda: 1, jitter=1.5)
    with pytest.raises(ValueError, match="jitter"):
        call_with_retries(lambda: 1, jitter=-0.1)


def test_dispatch_deadline_marks_suspect_never_kills(tmp_path):
    """The DispatchDeadline watchdog: on expiry it flips the flag, ticks
    the counter and records the fault event — the guarded block always
    runs to completion (never interrupted, never killed)."""
    import time

    from disco_tpu import obs
    from disco_tpu.obs.metrics import REGISTRY
    from disco_tpu.utils.resilience import DispatchDeadline

    before = REGISTRY.counter("dispatch_deadline_hits").value
    log = tmp_path / "deadline.jsonl"
    ran = []
    with obs.recording(log):
        with DispatchDeadline(0.02, label="serve_tick") as dd:
            time.sleep(0.08)     # blow the deadline; the work still finishes
            ran.append("finished")
    assert ran == ["finished"] and dd.expired
    assert dd.elapsed_s() >= 0.02
    assert REGISTRY.counter("dispatch_deadline_hits").value == before + 1
    events = obs.read_events(log)
    (ev,) = [e for e in events if e["attrs"].get("fault") == "dispatch_deadline"]
    assert ev["stage"] == "serve_tick"

    # the happy path: cancelled cleanly, no flag, no counter
    with DispatchDeadline(5.0) as dd2:
        pass
    assert not dd2.expired
    assert REGISTRY.counter("dispatch_deadline_hits").value == before + 1
    with pytest.raises(ValueError, match="deadline_s"):
        DispatchDeadline(0.0)
