"""Tests for the distributed source-separation API (MEETIT/ICASSP setup)."""
import numpy as np
import pytest

from disco_tpu.core.dsp import istft, stft
from disco_tpu.core.metrics import si_sdr
from disco_tpu.enhance import separate_sources, separate_with_masks
from disco_tpu.enhance.tango import oracle_masks

FS = 16000


@pytest.fixture(scope="module")
def meet_scene():
    rng = np.random.default_rng(9)
    K, C, L, n_src = 4, 2, 3 * FS, 2
    srcs = [rng.standard_normal(L) for _ in range(n_src)]
    imgs = np.stack(
        [
            np.stack(
                [np.stack([np.convolve(s, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)]) for _ in range(K)]
            )
            for s in srcs
        ]
    ).astype(np.float32)
    return imgs, imgs.sum(0), L


def test_separate_sources_improves_both(meet_scene):
    imgs, y, L = meet_scene
    Y = stft(y)
    S_imgs = stft(imgs)
    est = np.asarray(istft(separate_sources(Y, S_imgs), length=L))
    n_src, K = imgs.shape[:2]
    deltas = []
    for s in range(n_src):
        for k in range(K):
            ref = imgs[s, k, 0]
            deltas.append(si_sdr(ref, est[s, k]) - si_sdr(ref, y[k, 0]))
    # every (source, node) pair improves strongly with producer-side masks
    assert min(deltas) > 5.0, deltas
    assert np.mean(deltas) > 8.0, deltas


def test_separate_with_masks_matches_oracle_path(meet_scene):
    imgs, y, L = meet_scene
    Y = stft(y)
    S_imgs = stft(imgs)
    masks = np.stack(
        [np.asarray(oracle_masks(S_imgs[s], Y - S_imgs[s], "irm1")) for s in range(imgs.shape[0])]
    )
    est_masked = np.asarray(separate_with_masks(Y, masks))
    est_oracle = np.asarray(separate_sources(Y, S_imgs))
    err = np.max(np.abs(est_masked - est_oracle)) / np.max(np.abs(est_oracle))
    assert err < 1e-4  # identical masked-covariance statistics
