"""Tests for disco_tpu.serve — the online enhancement service.

The load-bearing claim is *serve/offline parity*: every block a session
streams through the continuous-batching scheduler must come back
bit-identical to the offline ``streaming_tango`` run of the same clip
(``make serve-check`` gates the full concurrent-clients version; these
tests pin the pieces at unit size).
"""
from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pytest

from disco_tpu.core.dsp import stft
from disco_tpu.enhance.streaming import initial_stream_state, streaming_tango
from disco_tpu.serve import protocol
from disco_tpu.serve.scheduler import AdmissionError, QueueFull, Scheduler
from disco_tpu.serve.session import (
    Session,
    SessionConfig,
    SessionStateError,
    load_session_state,
    probe_session_state,
    save_session_state,
)

K, C, U = 4, 2, 4
BLOCK = 2 * U  # frames per serve block


@pytest.fixture(scope="module")
def stream():
    """A small (K, C, F, T) STFT stream + masks + its offline reference."""
    rng = np.random.default_rng(3)
    y = rng.standard_normal((K, C, 6000)).astype(np.float32)
    Y = np.asarray(stft(y))
    F, T = Y.shape[-2:]
    m = rng.uniform(0.05, 0.95, size=(K, F, T)).astype(np.float32)
    ref = np.asarray(streaming_tango(Y, m, m, update_every=U, policy="local")["yf"])
    return Y, m, ref


def _config(F, **kw):
    return SessionConfig(n_nodes=K, mics_per_node=C, n_freq=F,
                         block_frames=BLOCK, update_every=U, **kw)


def _run_scheduler(sched, session, Y, m):
    """Feed a whole stream through one scheduler session block by block."""
    T = Y.shape[-1]
    outs = {}
    n_blocks = -(-T // BLOCK)
    for i in range(n_blocks):
        lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
        sched.push_block(session, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
        for _s, seq, yf, _lat in sched.tick():
            outs[seq] = yf
    return np.concatenate([outs[i] for i in range(n_blocks)], axis=-1)


# -- protocol ----------------------------------------------------------------
def test_protocol_array_roundtrip():
    rng = np.random.default_rng(0)
    for arr in (
        rng.standard_normal((3, 5)).astype(np.float32),
        (rng.standard_normal((2, 4)) + 1j * rng.standard_normal((2, 4))).astype(np.complex64),
        np.zeros((4,), bool),
        np.arange(6, dtype=np.int64).reshape(2, 3),
    ):
        back = protocol.decode_array(protocol.encode_array(arr))
        assert back.dtype == arr.dtype and back.shape == arr.shape
        np.testing.assert_array_equal(back, arr)


def test_protocol_frame_roundtrip():
    frame = {"type": "block", "seq": 3,
             "Y": (np.ones((2, 2)) + 1j * np.ones((2, 2))).astype(np.complex64),
             "nested": {"mask": np.zeros((2, 3), np.float32)}}
    data = protocol.pack_frame(frame)
    back = protocol.unpack_payload(data[protocol.frame_header_size():])
    assert back["type"] == "block" and back["seq"] == 3
    np.testing.assert_array_equal(back["Y"], frame["Y"])
    np.testing.assert_array_equal(back["nested"]["mask"], frame["nested"]["mask"])


def test_protocol_rejects_bad_payloads():
    bad = protocol.encode_array(np.ones((3, 3), np.float32))
    bad["shape"] = [3, 4]  # declared shape no longer matches payload
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_array(bad)
    with pytest.raises(protocol.ProtocolError):
        protocol.unpack_payload(b"\xc3")  # bare msgpack `true`: not a map
    with pytest.raises(protocol.ProtocolError, match="payload"):
        # non-bytes data field: TypeError inside np.frombuffer must still
        # surface as a clean ProtocolError, not a numpy internal error
        protocol.decode_array({"__nd__": 1, "dtype": "<f4", "shape": [1], "data": 5})


def test_protocol_truncated_frame_is_an_error():
    a, b = socket.socketpair()
    try:
        data = protocol.pack_frame({"type": "close", "session": "x"})
        a.sendall(data[: len(data) - 3])
        a.close()
        with pytest.raises(protocol.ProtocolError, match="mid-frame"):
            protocol.recv_frame(b)
    finally:
        b.close()


def test_client_modules_never_import_jax():
    """The environment contract allows ONE chip-claiming process — serve
    clients must be importable without jax.  Pinned structurally via the
    disco-lint import-purity rule (DL005), so the client purity contract
    has exactly ONE implementation (the bespoke AST walk that used to live
    here moved into disco_tpu.analysis.rules.purity)."""
    from disco_tpu import analysis
    from disco_tpu.analysis.rules.purity import CLIENT_FILES

    root = analysis.repo_root()
    res = analysis.lint_paths([str(root / f) for f in CLIENT_FILES],
                              rules={"DL005"})
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    # ... and the rule has teeth: a lazy in-function jax import in a client
    # module (which module-level-only checks would miss) IS caught
    bad = analysis.lint_source("def f():\n    import jax.numpy\n",
                               rel=CLIENT_FILES[0], rules={"DL005"})
    assert [f.rule for f in bad.findings] == ["DL005"]


# -- session config / state --------------------------------------------------
def test_session_config_validation():
    with pytest.raises(ValueError, match="multiple of update_every"):
        SessionConfig(n_nodes=4, mics_per_node=2, n_freq=9, block_frames=6, update_every=4)
    with pytest.raises(ValueError, match=">= 2"):
        SessionConfig(n_nodes=1, mics_per_node=2, n_freq=9, block_frames=8)
    with pytest.raises(ValueError, match="offline-only"):
        SessionConfig(n_nodes=4, mics_per_node=2, n_freq=9, block_frames=8,
                      policy="use_oracle_refs")
    with pytest.raises(ValueError, match="ref_mic"):
        SessionConfig(n_nodes=4, mics_per_node=2, n_freq=9, block_frames=8, ref_mic=2)
    with pytest.raises(ValueError, match="unknown field"):
        SessionConfig.from_dict({"n_nodes": 4, "mics_per_node": 2, "n_freq": 9,
                                 "block_frames": 8, "bogus": 1})


def test_initial_stream_state_matches_default_warm_start(stream):
    """streaming_tango(state=initial_stream_state, z_avail=ones) must be
    bit-identical to the default call — the serve path's block-0 premise."""
    Y, m, ref = stream
    F, T = Y.shape[-2:]
    st = initial_stream_state(K, C, F, update_every=U)
    avail = np.ones((K, -(-T // U)), np.float32)
    out = streaming_tango(Y, m, m, update_every=U, policy="local",
                          state=st, z_avail=avail)
    np.testing.assert_array_equal(np.asarray(out["yf"]), ref)


def test_session_state_roundtrip(tmp_path, stream):
    Y, m, _ = stream
    F = Y.shape[-2]
    cfg = _config(F)
    s = Session("abc", cfg, state=initial_stream_state(K, C, F, update_every=U),
                blocks_done=2, z_avail=np.ones(K, np.float32))
    path = save_session_state(tmp_path / "abc.state.msgpack", s)
    assert probe_session_state(path)
    back = load_session_state(path)
    assert back.id == "abc" and back.blocks_done == 2 and back.config == cfg
    import jax

    leaves0 = jax.tree_util.tree_leaves(s.state)
    leaves1 = jax.tree_util.tree_leaves(back.state)
    assert len(leaves0) == len(leaves1)
    for a, b in zip(leaves0, leaves1):
        assert np.asarray(a).dtype == np.asarray(b).dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_session_state_corruption_detected(tmp_path, stream):
    Y, _, _ = stream
    F = Y.shape[-2]
    s = Session("x", _config(F), state=initial_stream_state(K, C, F, update_every=U))
    path = save_session_state(tmp_path / "x.state.msgpack", s)
    raw = bytearray(path.read_bytes())
    raw[len(raw) // 2] ^= 0xFF  # bit rot inside the state payload
    path.write_bytes(bytes(raw))
    assert not probe_session_state(path)
    with pytest.raises(SessionStateError):
        load_session_state(path)
    # truncation (the crash-mid-write shape the atomic writer prevents at
    # the final path, but a copy could still suffer)
    path2 = tmp_path / "y.state.msgpack"
    path2.write_bytes(path.read_bytes()[: len(raw) // 3])
    assert not probe_session_state(path2)


# -- scheduler ---------------------------------------------------------------
def test_scheduler_parity_two_interleaved_sessions(stream):
    """Two sessions ticked together: each bit-identical to its offline
    one-shot run, one batched readback per tick-with-work."""
    from disco_tpu.obs.accounting import device_get_count

    Y, m, ref = stream
    F, T = Y.shape[-2:]
    rng = np.random.default_rng(9)
    Y2 = np.asarray(stft(rng.standard_normal((K, C, 6000)).astype(np.float32)))
    m2 = rng.uniform(0.05, 0.95, size=(K, F, T)).astype(np.float32)
    ref2 = np.asarray(
        streaming_tango(Y2, m2, m2, update_every=U, policy="local", mu=1.2)["yf"]
    )

    sched = Scheduler(max_sessions=4, max_queue_blocks=8)
    s1 = sched.open_session(_config(F))
    s2 = sched.open_session(_config(F, mu=1.2))
    outs = {s1.id: {}, s2.id: {}}
    gets0 = device_get_count()
    n_blocks = -(-T // BLOCK)
    for i in range(n_blocks):
        lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
        sched.push_block(s1, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
        sched.push_block(s2, i, Y2[..., lo:hi], m2[..., lo:hi], m2[..., lo:hi])
        for sess, seq, yf, lat in sched.tick():
            outs[sess.id][seq] = yf
            assert lat >= 0.0
    got1 = np.concatenate([outs[s1.id][i] for i in range(n_blocks)], axis=-1)
    got2 = np.concatenate([outs[s2.id][i] for i in range(n_blocks)], axis=-1)
    np.testing.assert_array_equal(got1, ref)
    np.testing.assert_array_equal(got2, ref2)
    assert device_get_count() - gets0 == sched.ticks_with_work == n_blocks


def test_scheduler_parity_with_fault_mask(stream):
    """A per-session (K,) z_mask degrades exactly like the offline
    z_avail run — the fault path flows through the service unchanged."""
    Y, m, _ = stream
    F = Y.shape[-2]
    mask = np.array([1, 0, 1, 1], np.float32)
    ref = np.asarray(streaming_tango(Y, m, m, update_every=U, policy="local",
                                     z_avail=mask)["yf"])
    sched = Scheduler(max_sessions=2)
    s = sched.open_session(_config(F), z_mask=mask)
    got = _run_scheduler(sched, s, Y, m)
    np.testing.assert_array_equal(got, ref)


def test_scheduler_resume_equivalence(tmp_path, stream):
    """Checkpoint mid-stream, reload into a fresh scheduler, continue:
    the stitched outputs equal the uninterrupted offline run bit-for-bit."""
    Y, m, ref = stream
    F, T = Y.shape[-2:]
    n_blocks = -(-T // BLOCK)
    half = n_blocks // 2

    sched = Scheduler(max_sessions=2)
    s = sched.open_session(_config(F), session_id="resume-me")
    outs = {}
    for i in range(half):
        lo, hi = i * BLOCK, (i + 1) * BLOCK
        sched.push_block(s, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
        for _s, seq, yf, _lat in sched.tick():
            outs[seq] = yf
    paths = sched.checkpoint_sessions(tmp_path)
    assert set(paths) == {"resume-me"}

    sched2 = Scheduler(max_sessions=2)
    s2 = sched2.open_session(_config(F), resume_from=paths["resume-me"])
    assert s2.blocks_done == half and s2.id == "resume-me"
    for i in range(half, n_blocks):
        lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
        sched2.push_block(s2, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
        for _s, seq, yf, _lat in sched2.tick():
            outs[seq] = yf
    got = np.concatenate([outs[i] for i in range(n_blocks)], axis=-1)
    np.testing.assert_array_equal(got, ref)


def test_scheduler_resume_config_mismatch_rejected(tmp_path, stream):
    Y, m, _ = stream
    F = Y.shape[-2]
    sched = Scheduler(max_sessions=2)
    s = sched.open_session(_config(F), session_id="a")
    paths = sched.checkpoint_sessions(tmp_path)
    sched2 = Scheduler(max_sessions=2)
    with pytest.raises(AdmissionError, match="different"):
        sched2.open_session(_config(F, mu=2.0), resume_from=paths["a"])


def test_scheduler_admission_and_queue_bounds(stream):
    from disco_tpu.obs.metrics import REGISTRY

    Y, m, _ = stream
    F = Y.shape[-2]
    sched = Scheduler(max_sessions=1, max_queue_blocks=2)
    s = sched.open_session(_config(F))
    rejects0 = REGISTRY.counter("admission_reject").value
    with pytest.raises(AdmissionError, match="max_sessions"):
        sched.open_session(_config(F))
    assert REGISTRY.counter("admission_reject").value == rejects0 + 1

    blk = (Y[..., :BLOCK], m[..., :BLOCK], m[..., :BLOCK])
    sched.push_block(s, 0, *blk)
    sched.push_block(s, 1, *blk)
    with pytest.raises(QueueFull, match="max_queue_blocks"):
        sched.push_block(s, 2, *blk)
    with pytest.raises(QueueFull, match="out-of-order"):
        sched.push_block(s, 5, *blk)
    with pytest.raises(QueueFull, match="shape"):
        sched.push_block(s, 2, Y[..., :BLOCK], m[..., : BLOCK - 1], m[..., :BLOCK])
    # draining: no new sessions
    sched.start_drain()
    with pytest.raises(AdmissionError, match="draining"):
        sched.open_session(_config(F))


def test_scheduler_eviction_counter(stream):
    from disco_tpu.obs.metrics import REGISTRY

    Y, m, _ = stream
    F = Y.shape[-2]
    sched = Scheduler(max_sessions=2)
    s = sched.open_session(_config(F))
    ev0 = REGISTRY.counter("session_evicted").value
    sched.evict(s, "slow client")
    assert REGISTRY.counter("session_evicted").value == ev0 + 1
    assert sched.get(s.id) is None
    with pytest.raises(QueueFull, match="evicted"):
        sched.push_block(s, 0, Y[..., :BLOCK], m[..., :BLOCK], m[..., :BLOCK])


def test_scheduler_supertick_parity_fewer_readbacks(stream):
    """Super-ticks: N queued blocks ride ONE scanned dispatch + readback —
    per-session results byte-identical to per-block ticks, with fewer
    batched readbacks than delivered blocks."""
    from disco_tpu.obs.accounting import device_get_count
    from disco_tpu.obs.metrics import REGISTRY

    Y, m, ref = stream
    F, T = Y.shape[-2:]
    n_blocks = -(-T // BLOCK)
    N = 2

    sched = Scheduler(max_sessions=2, max_queue_blocks=2 * N,
                      blocks_per_super_tick=N)
    assert sched.overlap_readback  # defaults on with super-ticks
    s = sched.open_session(_config(F))
    outs = {}
    gets0 = device_get_count()
    super0 = REGISTRY.counter("serve_super_ticks").value
    i = 0
    while i < n_blocks:
        for _ in range(N):
            if i < n_blocks:
                lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
                sched.push_block(s, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
                i += 1
        for _s, seq, yf, lat in sched.tick():
            outs[seq] = yf
            assert lat >= 0.0
    for _ in range(3):  # flush the double-buffered readback
        for _s, seq, yf, _lat in sched.tick():
            outs[seq] = yf
    gets = device_get_count() - gets0
    assert len(outs) == n_blocks
    got = np.concatenate([outs[i] for i in range(n_blocks)], axis=-1)
    np.testing.assert_array_equal(got, ref)
    # ceil(14 full / 2) scans + the ragged tail per-block: strictly fewer
    # readbacks than delivered blocks, one per tick-with-work
    assert gets < n_blocks
    assert gets == sched.ticks_with_work
    assert REGISTRY.counter("serve_super_ticks").value > super0
    # queue accounting drained: nothing queued, nothing in flight
    assert sched.pending_blocks() == 0 and s.inflight == 0


def test_scheduler_supertick_resume_equivalence(tmp_path, stream):
    """Checkpoint/resume across super-ticks stays bit-exact: checkpoints
    land on delivered-block boundaries (the drain gate waits for the
    in-flight buffer)."""
    Y, m, ref = stream
    F, T = Y.shape[-2:]
    n_blocks = -(-T // BLOCK)
    N = 2
    half = (n_blocks // 2) // N * N  # a super-tick boundary

    sched = Scheduler(max_sessions=2, max_queue_blocks=2 * N,
                      blocks_per_super_tick=N)
    s = sched.open_session(_config(F), session_id="st-resume")
    outs = {}
    i = 0
    while i < half:
        for _ in range(N):
            sched.push_block(s, i, Y[..., i * BLOCK:(i + 1) * BLOCK],
                             m[..., i * BLOCK:(i + 1) * BLOCK],
                             m[..., i * BLOCK:(i + 1) * BLOCK])
            i += 1
        for _s, seq, yf, _lat in sched.tick():
            outs[seq] = yf
    while sched.pending_blocks():
        for _s, seq, yf, _lat in sched.tick():
            outs[seq] = yf
    assert len(outs) == half
    paths = sched.checkpoint_sessions(tmp_path)

    sched2 = Scheduler(max_sessions=2, blocks_per_super_tick=N)
    s2 = sched2.open_session(_config(F), resume_from=paths["st-resume"])
    assert s2.blocks_done == half
    for i in range(half, n_blocks):
        lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
        sched2.push_block(s2, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
        for _s, seq, yf, _lat in sched2.tick():
            outs[seq] = yf
    while sched2.pending_blocks():
        for _s, seq, yf, _lat in sched2.tick():
            outs[seq] = yf
    got = np.concatenate([outs[i] for i in range(n_blocks)], axis=-1)
    np.testing.assert_array_equal(got, ref)


def test_scheduler_supertick_deep_queue_groups_per_tick(stream):
    """A queue deeper than N forms SEVERAL scanned groups in one tick (one
    fence per N blocks even when everything is queued up front), instead of
    capping the pop at N — and stays bit-identical to the per-block path."""
    from disco_tpu.obs.accounting import device_get_count

    Y, m, ref = stream
    F, T = Y.shape[-2:]
    n_blocks = -(-T // BLOCK)
    N = 2

    sched = Scheduler(max_sessions=2, max_queue_blocks=n_blocks,
                      blocks_per_super_tick=N, overlap_readback=False)
    s = sched.open_session(_config(F))
    for i in range(n_blocks):
        lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
        sched.push_block(s, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
    gets0 = device_get_count()
    outs = {}
    while sched.pending_blocks():
        for _s, seq, yf, _lat in sched.tick():
            outs[seq] = yf
    gets = device_get_count() - gets0
    assert len(outs) == n_blocks
    got = np.concatenate([outs[i] for i in range(n_blocks)], axis=-1)
    np.testing.assert_array_equal(got, ref)
    # the whole queue fits one tick's budget: ONE readback covering all
    # ceil(full/N) scan groups + the ragged tail, not one tick per group
    assert gets == sched.ticks_with_work == 1


def test_scheduler_supertick_misaligned_budget_stays_scanned(stream):
    """max_blocks_per_tick not a multiple of N: a deep queue must keep
    riding scan groups (the sub-N budget remainder stays queued for the
    next tick) instead of shedding per-block dispatches every tick."""
    Y, m, ref = stream
    F, T = Y.shape[-2:]
    n_blocks = -(-T // BLOCK)
    N = 4
    full = n_blocks - 1 if T % BLOCK else n_blocks

    sched = Scheduler(max_sessions=2, max_queue_blocks=n_blocks,
                      blocks_per_super_tick=N, max_blocks_per_tick=N + 2,
                      overlap_readback=False)
    s = sched.open_session(_config(F))
    for i in range(n_blocks):
        lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
        sched.push_block(s, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
    outs = {}
    while sched.pending_blocks():
        for _s, seq, yf, _lat in sched.tick():
            outs[seq] = yf
    assert len(outs) == n_blocks
    got = np.concatenate([outs[i] for i in range(n_blocks)], axis=-1)
    np.testing.assert_array_equal(got, ref)
    # one tick per scan group + one for the (sub-N tail + ragged) remainder
    assert sched.ticks_with_work == full // N + 1


def test_scheduler_supertick_exceeding_tick_budget_rejected():
    """blocks_per_super_tick > max_blocks_per_tick could never form a
    group — fail at startup instead of silently serving per-block."""
    with pytest.raises(ValueError, match="blocks_per_super_tick"):
        Scheduler(max_blocks_per_tick=4, blocks_per_super_tick=8)


def test_scheduler_supertick_close_waits_for_inflight(stream):
    """A close request with blocks still in the double-buffer must not
    finish the session before those blocks are delivered."""
    Y, m, _ = stream
    F = Y.shape[-2]
    N = 2
    sched = Scheduler(max_sessions=2, max_queue_blocks=2 * N,
                      blocks_per_super_tick=N)
    s = sched.open_session(_config(F))
    for i in range(N):
        sched.push_block(s, i, Y[..., i * BLOCK:(i + 1) * BLOCK],
                         m[..., i * BLOCK:(i + 1) * BLOCK],
                         m[..., i * BLOCK:(i + 1) * BLOCK])
    sched.request_close(s)
    first = sched.tick()   # dispatches the super-tick; delivery deferred
    assert first == [] and s.inflight == N and s.status == "open"
    second = sched.tick()  # flushes the buffer, then finishes the session
    assert len(second) == N
    assert s.inflight == 0 and sched.get(s.id) is None


# -- server / client end-to-end ----------------------------------------------
def _serve_scene(seed, L=6000):
    rng = np.random.default_rng(seed)
    Y = np.asarray(stft(rng.standard_normal((K, C, L)).astype(np.float32)))
    F, T = Y.shape[-2:]
    m = rng.uniform(0.05, 0.95, size=(K, F, T)).astype(np.float32)
    return Y, m


def test_server_single_client_parity(stream):
    from disco_tpu.serve import EnhanceServer, ServeClient

    Y, m, ref = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        cl.open(_config(F))
        yf = cl.enhance_clip(Y, m, m)
        info = cl.close()
        cl.shutdown()
        assert info["blocks_done"] == -(-Y.shape[-1] // BLOCK)
        np.testing.assert_array_equal(yf, ref)
    finally:
        srv.stop()


def test_server_rejects_over_capacity(stream):
    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError

    Y, m, _ = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=1)
    addr = srv.start()
    try:
        c1 = ServeClient(addr)
        c1.open(_config(F))
        c2 = ServeClient(addr)
        with pytest.raises(ServeError, match="max_sessions"):
            c2.open(_config(F))
        c2.shutdown()
        c1.close()
        c1.shutdown()
    finally:
        srv.stop()


def test_server_evicts_slow_client(stream):
    """A client that streams blocks without draining its socket is evicted
    with a clean error frame once the output backlog bound is hit.

    The jam must be real, not lucky.  Two independent races made the old
    form of this test flaky-to-hanging: (1) the client started reading
    right after its sends, and block compute is the bottleneck here, so
    the "slow client" mostly did not exist — every frame was consumed as
    it was posted and the backlog never formed; (2) even an unread frame
    only registers as backlog once the writer blocks in drain(), and
    default TCP autotuning gives the kernel megabytes of slack, so the
    pipe never jammed.  Deterministic form: the client does NOT read at
    all until the server has actually evicted the session (observed
    in-process — eviction frees the registry slot), tiny socket buffers
    on both ends plus a zero transport high-water mark jam the writer on
    the FIRST unread ~66 KiB frame, and one block per tick spreads the
    posts so a later tick's post observes the jammed queue (back-to-back
    posts within one tick all read qsize before the loop thread executes
    any put).  Only then does the client drain the socket and assert the
    clean ``evicted`` error frame; the socket timeout turns any residual
    no-eviction outcome into a failure instead of a hang."""
    from disco_tpu.serve import EnhanceServer
    from disco_tpu.serve.session import EVICTED

    Y, m, _ = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=2, max_backlog=1, max_queue_blocks=16,
                        max_blocks_per_tick=1, sock_sndbuf=4096,
                        write_buffer_high=0)
    addr = srv.start()
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    sock.connect(addr)
    sock.settimeout(120.0)
    try:
        protocol.send_frame(sock, {"type": "open", "config": _config(F).to_dict()})
        opened = protocol.recv_frame(sock)
        assert opened["type"] == "open_ok"
        blk = {"Y": Y[..., :BLOCK].astype(np.complex64),
               "mask_z": m[..., :BLOCK], "mask_w": m[..., :BLOCK]}
        for seq in range(6):  # sent up front; NOT read back until evicted
            protocol.send_frame(sock, {"type": "block", "seq": seq, **blk})
        for _ in range(1200):  # bounded: ~2 min >> 3 one-block ticks
            if not srv.scheduler.sessions():
                break           # slot freed: the eviction has happened
            time.sleep(0.1)
        else:
            raise AssertionError("session never evicted despite jammed pipe")
        frames = []
        while True:
            f = protocol.recv_frame(sock)
            if f is None:
                break
            frames.append(f)
            if f["type"] == "error":
                break
        errors = [f for f in frames if f["type"] == "error"]
        assert errors and errors[0]["code"] == "evicted"
        session = srv.scheduler  # registry slot freed
        assert all(s.status != EVICTED for s in session.sessions())
    finally:
        sock.close()
        srv.stop()


def test_server_survives_non_numeric_block(stream):
    """A shape-correct block with a non-numeric dtype (the wire codec
    round-trips ANY declared dtype) must die as a clean ``bad_block`` on
    the I/O thread — not crash the dispatch thread and take every other
    live session down with it."""
    from disco_tpu.serve import EnhanceServer, ServeClient

    Y, m, ref = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=4)
    addr = srv.start()
    try:
        good = ServeClient(addr)
        good.open(_config(F))
        sock = socket.create_connection(addr)
        try:
            protocol.send_frame(sock, {"type": "open", "config": _config(F).to_dict()})
            assert protocol.recv_frame(sock)["type"] == "open_ok"
            evil = np.full(Y[..., :BLOCK].shape, "x", dtype="<U1")
            protocol.send_frame(sock, {"type": "block", "seq": 0, "Y": evil,
                                       "mask_z": m[..., :BLOCK],
                                       "mask_w": m[..., :BLOCK]})
            err = protocol.recv_frame(sock)
            assert err is not None and err["type"] == "error"
            assert err["code"] == "bad_block"
        finally:
            sock.close()
        # the innocent concurrent session is still served, bit-exact
        yf = good.enhance_clip(Y, m, m)
        np.testing.assert_array_equal(yf, ref)
        good.close()
        good.shutdown()
        assert srv.crashed is None
    finally:
        srv.stop()


def test_enhance_clip_resumed_fully_done_returns_empty(stream):
    """Resuming a session whose checkpoint already covers the whole clip
    returns an empty (K, F, 0) result instead of crashing on an empty
    concatenate."""
    from disco_tpu.serve import EnhanceServer, ServeClient

    Y, m, _ = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        cl.open(_config(F))
        cl.blocks_done = -(-Y.shape[-1] // BLOCK)  # as a fully-done resume reports
        out = cl.enhance_clip(Y, m, m)
        assert out.shape == (K, F, 0) and out.dtype == np.complex64
        cl.close()
        cl.shutdown()
    finally:
        srv.stop()


# -- disco-serve CLI ---------------------------------------------------------
def test_serve_cli_parser_defaults_and_fault_seam():
    from disco_tpu.cli import serve as serve_cli

    args = serve_cli.build_parser().parse_args([])
    assert args.port == 7433 and args.max_sessions == 16
    assert args.preflight == 0.0 and args.obs_log is None and args.unix is None
    # the shared fault seam: --fault-seed without --fault-spec is a clean
    # CLI error (cli.common.resolve_fault_spec), not a crash mid-serve
    with pytest.raises(SystemExit, match="--fault-seed needs --fault-spec"):
        serve_cli.main(["--fault-seed", "3"])


@pytest.mark.slow
def test_serve_cli_end_to_end_unix_socket_drain(tmp_path, stream):
    """disco-serve over a unix socket with the shared production seams:
    serve blocks bit-exactly, then a graceful stop (the in-process SIGINT
    equivalent) drains, checkpoints into --state-dir, and the --obs-log
    carries the serve lifecycle + latency telemetry."""
    import time

    from disco_tpu import obs
    from disco_tpu.cli import serve as serve_cli
    from disco_tpu.runs.interrupt import request_stop
    from disco_tpu.serve import ServeClient

    Y, m, ref = stream
    F = Y.shape[-2]
    sock = tmp_path / "serve.sock"
    log = tmp_path / "serve.jsonl"
    th = threading.Thread(
        target=serve_cli.main,
        args=([
            "--unix", str(sock), "--state-dir", str(tmp_path / "state"),
            "--obs-log", str(log), "--max-sessions", "2",
        ],),
        daemon=True,
    )
    th.start()
    deadline = time.time() + 30
    while not sock.exists() and time.time() < deadline:
        time.sleep(0.02)
    assert sock.exists(), "disco-serve never bound its unix socket"

    cl = ServeClient(str(sock))
    cl.open(_config(F), session_id="cli-sess")
    outs = {}
    for i in range(2):
        cl.send_block(Y[..., i * BLOCK:(i + 1) * BLOCK],
                      m[..., i * BLOCK:(i + 1) * BLOCK],
                      m[..., i * BLOCK:(i + 1) * BLOCK])
        outs[i] = cl.recv_enhanced(i)
    assert request_stop("test drain")  # the CLI's GracefulInterrupt scope
    info = cl.wait_closed(timeout_s=60)
    th.join(60)
    assert not th.is_alive()
    cl.shutdown()

    assert info["blocks_done"] == 2 and info.get("resumable")
    got = np.concatenate([outs[0], outs[1]], axis=-1)
    np.testing.assert_array_equal(got, ref[..., : 2 * BLOCK])
    from disco_tpu.serve.session import probe_session_state

    assert probe_session_state(info["state_path"])

    events = obs.read_events(log)  # schema-validating read
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "manifest" and "counters" in kinds
    (start,) = [e for e in events if e["kind"] == "run_start"]
    assert start["attrs"]["tool"] == "disco-serve"
    assert start["attrs"]["state_dir"] == str(tmp_path / "state")
    actions = [e["attrs"]["action"] for e in events if e["kind"] == "session"]
    assert "open" in actions and "drain" in actions
    (counters,) = [e for e in events if e["kind"] == "counters"]
    lat = counters["attrs"]["histograms"]["serve_block_latency_ms"]
    # >= : the latency histogram is process-global, earlier tests feed it too
    assert lat["count"] >= 2 and lat["p95"] is not None


@pytest.mark.slow
def test_server_concurrent_sessions_parity_and_drain(tmp_path):
    """Four concurrent threads stream different clips with different
    params; all outputs bit-match offline.  Then a drain mid-stream
    checkpoints a live session and the resumed continuation still
    bit-matches."""
    from disco_tpu.serve import EnhanceServer, ServeClient

    scenes = []
    for i, kw in enumerate(({}, {"mu": 1.2}, {"lambda_cor": 0.97}, {})):
        Y, m = _serve_scene(20 + i)
        okw = {k: v for k, v in kw.items()}
        ref = np.asarray(streaming_tango(Y, m, m, update_every=U,
                                         policy="local", **okw)["yf"])
        scenes.append((Y, m, kw, ref))
    F = scenes[0][0].shape[-2]

    srv = EnhanceServer(max_sessions=8, state_dir=tmp_path)
    addr = srv.start()
    results = [None] * len(scenes)

    def worker(i):
        Y, m, kw, _ = scenes[i]
        cl = ServeClient(addr)
        cl.open(_config(F, **kw))
        results[i] = cl.enhance_clip(Y, m, m)
        cl.close()
        cl.shutdown()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(scenes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for i, (_, _, _, ref) in enumerate(scenes):
        np.testing.assert_array_equal(results[i], ref)

    # drain with a live half-fed session
    Y, m = _serve_scene(99)
    ref = np.asarray(streaming_tango(Y, m, m, update_every=U, policy="local")["yf"])
    n_blocks = -(-Y.shape[-1] // BLOCK)
    half = n_blocks // 2
    cl = ServeClient(addr)
    cl.open(_config(F), session_id="drainee")
    outs = {}
    for i in range(half):
        cl.send_block(Y[..., i * BLOCK:(i + 1) * BLOCK],
                      m[..., i * BLOCK:(i + 1) * BLOCK],
                      m[..., i * BLOCK:(i + 1) * BLOCK])
        outs[i] = cl.recv_enhanced(i)
    stopper = threading.Thread(target=srv.stop)
    stopper.start()
    info = cl.wait_closed()
    stopper.join(timeout=60)
    cl.shutdown()
    assert info["blocks_done"] == half and info.get("resumable")

    srv2 = EnhanceServer(max_sessions=8, state_dir=tmp_path)
    addr2 = srv2.start()
    try:
        cl2 = ServeClient(addr2)
        cl2.open(_config(F), resume="drainee")
        assert cl2.blocks_done == half
        rest = cl2.enhance_clip(Y, m, m)
        cl2.close()
        cl2.shutdown()
    finally:
        srv2.stop()
    full = np.concatenate(
        [np.concatenate([outs[i] for i in range(half)], axis=-1), rest], axis=-1
    )
    np.testing.assert_array_equal(full, ref)


# -- serving survival layer (disco-soak PR) ----------------------------------
def test_transient_transport_error_does_not_evict(stream):
    """THE regression of the survival layer: a transient XlaRuntimeError
    during dispatch must retry in place — the old scheduler evicted the
    innocent session on ANY exception (serve/scheduler.py per-session
    isolation), turning every tunnel hiccup into a dropped stream."""
    from jax.errors import JaxRuntimeError

    from disco_tpu.serve import EnhanceServer, ServeClient
    from disco_tpu.serve.scheduler import set_dispatch_fault_injector

    Y, m, ref = stream
    F = Y.shape[-2]
    calls = [0]

    def flaky(_sid, _seqs):
        calls[0] += 1
        if calls[0] == 2:
            raise JaxRuntimeError("tunnel RPC dropped (injected)")

    set_dispatch_fault_injector(flaky)
    try:
        srv = EnhanceServer(max_sessions=2)
        srv.scheduler.dispatch_retry_base_s = 0.001
        addr = srv.start()
        cl = ServeClient(addr)
        cl.open(_config(F))
        yf = cl.enhance_clip(Y, m, m)
        cl.close()
        cl.shutdown()
        srv.stop()
    finally:
        set_dispatch_fault_injector(None)
    assert calls[0] > 2, "the injected fault never fired (seam moved?)"
    np.testing.assert_array_equal(yf, ref)  # retried, not evicted


def test_exhausted_transport_budget_quarantines_then_recovers(stream):
    """A transport burst past the retry budget must quarantine (blocks
    re-queued in order, carry untouched) and the released session must
    finish bit-exact — never evict, never corrupt."""
    from disco_tpu.serve import EnhanceServer, ServeClient
    from disco_tpu.serve.scheduler import set_dispatch_fault_injector
    from disco_tpu.serve.session import QUARANTINED  # noqa: F401  (state exists)

    Y, m, ref = stream
    F = Y.shape[-2]
    n = [0]

    def burst(_sid, _seqs):
        n[0] += 1
        if n[0] <= 4:   # > retries+1 of the first dispatch: exhausts
            raise TimeoutError("injected transport burst")

    set_dispatch_fault_injector(burst)
    try:
        srv = EnhanceServer(max_sessions=2, quarantine_ticks=3)
        srv.scheduler.dispatch_retry_base_s = 0.001
        addr = srv.start()
        cl = ServeClient(addr, timeout_s=60)
        cl.open(_config(F))
        yf = cl.enhance_clip(Y, m, m)
        cl.close()
        cl.shutdown()
        srv.stop()
    finally:
        set_dispatch_fault_injector(None)
    np.testing.assert_array_equal(yf, ref)


def test_reconnect_after_drop_stitches_bit_exact(stream):
    """Kill the socket mid-stream; the client reattaches with its resume
    token and the stitched stream equals offline streaming_tango byte for
    byte (missed deliveries replayed, eaten input blocks resent)."""
    import socket as socket_mod

    from disco_tpu.serve import EnhanceServer, ServeClient

    Y, m, ref = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr, retry_seed=3)
        cl.open(_config(F))
        killed = [False]

        def on_block(seq, _yf):
            if seq == 1 and not killed[0]:
                killed[0] = True
                cl._sock.shutdown(socket_mod.SHUT_RDWR)

        yf = cl.enhance_clip(Y, m, m, on_block=on_block)
        cl.close()
        cl.shutdown()
    finally:
        srv.stop()
    assert killed[0] and cl.reattaches >= 1
    np.testing.assert_array_equal(yf, ref)


def test_mid_frame_truncation_parks_not_corrupts(stream):
    """A partial frame followed by EOF must PARK the session (the torn
    block never reaches push_block) and the reattached stream must still
    be bit-exact — the wire fault corrupts nothing."""
    import socket as socket_mod

    from disco_tpu.serve import EnhanceServer, ServeClient, protocol as proto

    Y, m, ref = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        cl.open(_config(F))
        fired = [False]

        def on_block(seq, _yf):
            if seq == 1 and not fired[0]:
                fired[0] = True
                half = proto.pack_frame({"type": "close"})
                cl._sock.sendall(half[: len(half) // 2])
                cl._sock.shutdown(socket_mod.SHUT_WR)

        yf = cl.enhance_clip(Y, m, m, on_block=on_block)
        info = cl.close()
        cl.shutdown()
    finally:
        srv.stop()
    assert fired[0] and cl.reattaches >= 1
    assert info["blocks_done"] == -(-Y.shape[-1] // BLOCK)
    np.testing.assert_array_equal(yf, ref)


def test_shed_park_with_eaten_block_resends_not_deadlocks(stream):
    """A shed-to-park notice that lands while the client is blocked in
    ``recv_enhanced`` — with the awaited input block eaten by the park —
    must surface the documented ``backpressure`` resend signal after the
    transparent reattach, not keep waiting for an output the server will
    never produce (the server is idle, waiting for the resend: a mutual
    stall observed live behind a ladder shed on a cold-compile spike)."""
    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError

    Y, m, ref = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        sid = cl.open(_config(F))
        cl.send_block(Y[..., :BLOCK], m[..., :BLOCK], m[..., :BLOCK], seq=0)
        cl.recv_enhanced(0, timeout_s=30)
        # shed the session exactly as the ladder does: park with the
        # connection up; the dispatch loop posts the ``parked`` notice
        session = srv.scheduler.get(sid)
        assert srv.scheduler.park(session, "shed: overload (test)",
                                  notice=True)
        deadline = time.monotonic() + 10.0
        while cl._frames.qsize() == 0:        # notice reached the client
            assert time.monotonic() < deadline, "park notice never posted"
            time.sleep(0.01)
        # this block is eaten — the parked session rejects it — and the
        # client is blocked on its output when the notice is processed
        cl.send_block(Y[..., BLOCK:2 * BLOCK], m[..., BLOCK:2 * BLOCK],
                      m[..., BLOCK:2 * BLOCK], seq=1)
        with pytest.raises(ServeError, match="resend") as ei:
            cl.recv_enhanced(1, timeout_s=10)
        assert ei.value.code == "backpressure"
        assert cl.reattaches == 1 and cl.resend_from == 1
        # the documented recovery: resend from the rollback point, then
        # the stream continues bit-exact
        cl.send_block(Y[..., BLOCK:2 * BLOCK], m[..., BLOCK:2 * BLOCK],
                      m[..., BLOCK:2 * BLOCK], seq=1)
        yf = cl.recv_enhanced(1, timeout_s=30)
        np.testing.assert_array_equal(yf, ref[..., BLOCK:2 * BLOCK])
        cl.close()
        cl.shutdown()
    finally:
        srv.stop()


def test_client_connect_retries_survive_server_restart_window():
    """First OSError on connect used to be fatal; the bounded seeded
    backoff must ride out a late-binding server (and still fail cleanly
    when nothing ever listens)."""
    import socket as socket_mod

    from disco_tpu.serve import EnhanceServer, ServeClient

    # reserve a port, release it, bind the server there AFTER the client
    # starts dialing — the first connect attempts get connection-refused
    probe = socket_mod.socket()
    probe.bind(("127.0.0.1", 0))
    host, port = probe.getsockname()[:2]
    probe.close()
    srv = EnhanceServer(host=host, port=port)
    binder = threading.Timer(0.15, srv.start)
    binder.start()
    try:
        cl = ServeClient((host, port), connect_retries=8,
                         connect_base_delay_s=0.05, retry_seed=1)
        cl.shutdown()
    finally:
        binder.join()
        srv.stop()
    # no listener at all: bounded retries then a clean OSError
    with pytest.raises(OSError):
        ServeClient((host, port), connect_retries=1,
                    connect_base_delay_s=0.01)


def test_park_ttl_expires_and_frees_the_slot(stream):
    """A parked session whose client never returns must not hold its
    admission slot forever: the TTL reclaims it (park_expired counter,
    EVICTED status) and a new session can open."""
    import time as time_mod

    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError

    Y, m, _ = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=1, park_ttl_s=0.2)
    addr = srv.start()
    try:
        cl = ServeClient(addr, reattach_retries=0)
        cl.open(_config(F), session_id="ghost")
        cl.send_block(Y[..., :BLOCK], m[..., :BLOCK], m[..., :BLOCK])
        cl.recv_enhanced(0, timeout_s=60)
        cl.shutdown()              # drops the connection: session parks
        # while parked, the slot is held: an open must be rejected
        deadline = time_mod.monotonic() + 5.0
        cl3 = None
        while time_mod.monotonic() < deadline:
            cl2 = ServeClient(addr, reattach_retries=0)
            try:
                cl2.open(_config(F), session_id="taker")
                cl3 = cl2
                break
            except ServeError as e:
                assert e.code == "capacity"   # parked ghost holds the slot
                cl2.shutdown()
                time_mod.sleep(0.05)
        assert cl3 is not None, "park TTL never freed the slot"
        cl3.close()
        cl3.shutdown()
    finally:
        srv.stop()
    from disco_tpu.obs.metrics import REGISTRY

    assert REGISTRY.counter("park_expired").value >= 1


def test_exhausted_mid_pop_requeues_only_undispatched_blocks():
    """THE multi-block-pop regression: when a transport budget exhausts on
    the 4th block of a 4-block pop, only the failed block may be re-queued
    — re-queueing the already-dispatched ones would deliver them twice
    through a double-advanced carry (duplicated, WRONG frames)."""
    from disco_tpu.serve.scheduler import Scheduler, set_dispatch_fault_injector

    Y, m = _serve_scene(77, L=16000)
    ref = np.asarray(
        streaming_tango(Y, m, m, update_every=U, policy="local")["yf"])
    F = Y.shape[-2]
    T = Y.shape[-1]
    n_blocks = -(-T // BLOCK)
    assert n_blocks >= 4
    sched = Scheduler(max_sessions=1, max_queue_blocks=8,
                      quarantine_ticks=1, dispatch_retries=1)
    sched.dispatch_retry_base_s = 0.001
    s = sched.open_session(_config(F))

    def fail_block_3(_sid, seqs):
        if 3 in seqs:
            raise TimeoutError("injected: block 3's tunnel is down")

    set_dispatch_fault_injector(fail_block_3)
    try:
        # queue 4 blocks BEFORE the first tick: one pop covers all four
        for i in range(4):
            lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
            sched.push_block(s, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
        outs = {}
        for _s, seq, yf, _lat in sched.tick():
            assert seq not in outs, f"block {seq} delivered twice"
            outs[seq] = yf
        # blocks 0-2 dispatched once; 3 re-queued; session quarantined
        assert sorted(outs) == [0, 1, 2]
        assert s.status == "quarantined"
        assert [b[0] for b in s._pending] == [3]
    finally:
        set_dispatch_fault_injector(None)
    # tunnel heals: a quarantined session backpressures input (QueueFull)
    # until the cool-off releases it, then the stream finishes bit-exact
    with pytest.raises(QueueFull, match="quarantined"):
        sched.push_block(s, 4, Y[..., 4 * BLOCK:5 * BLOCK],
                         m[..., 4 * BLOCK:5 * BLOCK], m[..., 4 * BLOCK:5 * BLOCK])
    for _ in range(20):
        for _s, seq, yf, _lat in sched.tick():
            assert seq not in outs, f"block {seq} delivered twice"
            outs[seq] = yf
        if s.status == "open":
            break
    assert s.status == "open"
    for i in range(4, n_blocks):
        lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
        sched.push_block(s, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
    for _ in range(200):
        for _s, seq, yf, _lat in sched.tick():
            assert seq not in outs, f"block {seq} delivered twice"
            outs[seq] = yf
        if len(outs) == n_blocks:
            break
    assert sorted(outs) == list(range(n_blocks))
    got = np.concatenate([outs[i] for i in range(n_blocks)], axis=-1)
    np.testing.assert_array_equal(got, ref)


# -- disco-scope: causal tracing, status frame, pre-span back-compat ----------
@pytest.fixture
def _tracing():
    """Tracing + a fresh obs log for the scope tests; everything off after."""
    from disco_tpu import obs
    from disco_tpu.obs import trace as obs_trace

    obs_trace.enable()
    yield obs_trace
    obs_trace.disable()
    obs.disable()


def test_pre_span_client_served_unchanged(stream, _tracing, tmp_path):
    """THE back-compat pin: a client that never sends a trace header (the
    pre-span wire shape) is served bit-for-bit unchanged — even with
    tracing enabled server-side — and leaves ZERO span events."""
    from disco_tpu import obs
    from disco_tpu.serve import EnhanceServer, ServeClient

    Y, m, ref = stream
    F = Y.shape[-2]
    log = tmp_path / "serve.jsonl"
    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        with obs.recording(log):
            cl = ServeClient(addr, trace=False)
            cl.open(_config(F), session_id="prespan")
            yf = cl.enhance_clip(Y, m, m)
            cl.close()
            cl.shutdown()
    finally:
        srv.stop()
    np.testing.assert_array_equal(yf, ref)
    from disco_tpu import obs as obs_pkg

    events = obs_pkg.read_events(log)
    spans = [e for e in events if e["kind"] == "span"]
    assert spans == [], f"pre-span client produced {len(spans)} span events"
    # the session itself was served and closed normally
    actions = [e["attrs"]["action"] for e in events if e["kind"] == "session"]
    assert "open" in actions and "close" in actions


def test_traced_client_chains_every_delivered_block(stream, _tracing, tmp_path):
    """With tracing on end to end, every delivered block reconstructs the
    serve chain client_block → enqueue → dispatch → readback → deliver,
    and the output stays bit-exact (tracing observes, never perturbs)."""
    from disco_tpu import obs
    from disco_tpu.obs import trace as obs_trace
    from disco_tpu.serve import EnhanceServer, ServeClient

    Y, m, ref = stream
    F, T = Y.shape[-2:]
    n_blocks = -(-T // BLOCK)
    log = tmp_path / "serve.jsonl"
    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        with obs.recording(log):
            cl = ServeClient(addr)   # trace=None: follows the enabled tracer
            cl.open(_config(F), session_id="traced")
            yf = cl.enhance_clip(Y, m, m)
            cl.close()
            cl.shutdown()
    finally:
        srv.stop()
    np.testing.assert_array_equal(yf, ref)
    events = obs.read_events(log)
    delivered = {e["attrs"]["seq"]: e["attrs"]["trace"]
                 for e in events if e["kind"] == "span"
                 and e["stage"] == "deliver"
                 and e["attrs"].get("session") == "traced"}
    assert sorted(delivered) == list(range(n_blocks))
    for seq, tid in delivered.items():
        path = obs_trace.verify_chain(
            events, tid,
            require=("client_block", "enqueue", "dispatch", "readback",
                     "deliver"))
        # per-hop attribution rides the chain
        stages = {e["stage"]: e["attrs"] for e in path}
        assert stages["dispatch"]["wait_ms"] is not None
        assert stages["readback"]["readback_ms"] >= 0.0
        assert stages["deliver"]["latency_ms"] >= 0.0
        assert stages["client_block"]["seq"] == seq


def test_status_frame_agrees_with_registry(stream):
    """The read-only status frame: works without an open session, its
    counters section equals the registry snapshot exactly, and the SLO
    evaluator judges it."""
    from disco_tpu.obs.metrics import REGISTRY
    from disco_tpu.serve import EnhanceServer, ServeClient, evaluate_slo
    from disco_tpu.serve.status import fetch_status, status_section

    Y, m, _ref = stream
    F = Y.shape[-2]
    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        cl.open(_config(F), session_id="statustest")
        cl.send_block(Y[..., :BLOCK], m[..., :BLOCK], m[..., :BLOCK])
        cl.recv_enhanced(0, timeout_s=60)
        status = cl.status(timeout_s=30)
        assert status_section(status, "counters") == \
            REGISTRY.snapshot()["counters"]
        sessions = {s["id"]: s for s in status_section(status, "sessions")}
        assert sessions["statustest"]["status"] == "open"
        assert sessions["statustest"]["blocks_done"] == 1
        lat = status_section(status, "latency")["serve_block_latency_ms"]
        assert lat["count"] >= 1
        # a sessionless probe sees the same surface (disco-obs top path)
        bare = fetch_status(addr)
        assert status_section(bare, "scheduler")["tick_no"] >= 1
        # permissive targets: the registry is process-global, and earlier
        # tests legitimately evicted sessions — shape is what is pinned
        verdict = evaluate_slo(status, {"serve_p95_ms": 1e9,
                                        "queue_wait_p95_ms": 1e9,
                                        "max_drop_rate": 1.0,
                                        "max_evict_rate": 1.0})
        assert verdict["verdict"] == "OK" and len(verdict["checks"]) == 4
        # ... and a tight target flips the verdict deterministically
        tight = evaluate_slo(status, {"serve_p95_ms": 1e-9})
        assert tight["verdict"] == "VIOLATED"
        # unknown sections fail loudly at the accessor
        with pytest.raises(KeyError, match="unknown status section"):
            status_section(status, "countrz")
        cl.close()
        cl.shutdown()
    finally:
        srv.stop()


def test_status_section_registry_matches_payload_schema(stream):
    """Every registered STATUS_SECTIONS name is present in a real payload
    and vice versa (the DL014 registry and the builder cannot drift)."""
    from disco_tpu.serve import STATUS_SECTIONS, Scheduler, status_payload

    Y, m, _ref = stream
    F = Y.shape[-2]
    sched = Scheduler(max_sessions=2)
    sched.open_session(_config(F))
    payload = status_payload(sched)
    assert set(payload) == set(STATUS_SECTIONS)


def test_evicted_session_clears_tracer_inflight(stream, _tracing):
    """Terminal states drop the tracer's in-flight entries: an eviction
    with pending traced blocks must not leave ghost spans growing the
    bounded table forever (the `disco-obs top` live view would rot)."""
    from disco_tpu.obs import trace as obs_trace
    from disco_tpu.serve.scheduler import Scheduler

    Y, m, _ref = stream
    F = Y.shape[-2]
    sched = Scheduler(max_sessions=2)
    s = sched.open_session(_config(F), session_id="ghost")
    for i in range(2):
        lo, hi = i * BLOCK, (i + 1) * BLOCK
        ctx = obs_trace.root("client_block", seq=i, session=s.id)
        sched.push_block(s, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi],
                         trace=ctx.to_wire())
    assert obs_trace.tracer().inflight_snapshot()["count"] == 2
    sched.evict(s, "test: slow client")
    assert obs_trace.tracer().inflight_snapshot()["count"] == 0
    assert s.trace_ctx == {}


# -- chained (time-domain) sessions ------------------------------------------
def _chained_config(**kw):
    # Lw = (block_frames - 1) * hop = 7 * 256 = 1792 samples per window
    return SessionConfig(n_nodes=K, mics_per_node=C, n_freq=257,
                         block_frames=8, update_every=U, domain="time",
                         solver="fused-xla", **kw)


@pytest.mark.slow
def test_chained_session_bit_parity_with_offline_twin():
    """Two whole time-domain windows through a domain='time' session come
    back BIT-identical to the offline ``streaming_clip_fused`` run with
    the same continuation state — serve and offline dispatch the same
    jitted program by construction (scheduler._serve_chained_step), so
    this parity is an identity, not a tolerance."""
    from disco_tpu.enhance.fused import streaming_clip_fused
    from disco_tpu.serve import EnhanceServer, ServeClient

    cfg = _chained_config()
    Lw = cfg.block_samples
    rng = np.random.default_rng(5)
    wins = [rng.standard_normal((K, C, Lw)).astype(np.float32)
            for _ in range(2)]
    masks = [rng.uniform(0.05, 0.95, (K, 257, 8)).astype(np.float32)
             for _ in range(2)]

    refs, state = [], None
    for y, m in zip(wins, masks):
        out = streaming_clip_fused(y, masks_z=m, mask_w=m, update_every=U,
                                   policy="local", state=state,
                                   solver="fused-xla")
        refs.append(np.asarray(out["yf"]))
        state = out["state"]

    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        cl.open(cfg)
        got = []
        for i, (y, m) in enumerate(zip(wins, masks)):
            cl.send_block(y, m, m)
            got.append(cl.recv_enhanced(i, timeout_s=300))
        cl.close()
        cl.shutdown()
    finally:
        srv.stop()
    for i, (g, r) in enumerate(zip(got, refs)):
        assert g.shape == r.shape == (K, Lw), i
        np.testing.assert_array_equal(g, r)


def test_chained_sessions_admission_gate():
    """--no-chained-sessions turns the time-domain lane off at the door:
    admission fails with a clean error naming the flag, before any
    program is compiled for the session."""
    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError

    srv = EnhanceServer(max_sessions=2, allow_chained=False)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        with pytest.raises(ServeError, match="chained"):
            cl.open(_chained_config())
        cl.shutdown()
    finally:
        srv.stop()


def test_chained_misaligned_window_rejected():
    """A window whose frame count is not refresh-aligned is rejected at
    validation (a clean per-session error), never dispatched."""
    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError

    srv = EnhanceServer(max_sessions=2)
    addr = srv.start()
    try:
        cl = ServeClient(addr)
        cl.open(_chained_config())
        bad = np.zeros((K, C, 1792 - 256), np.float32)  # 7 frames, U = 4
        mbad = np.zeros((K, 257, 7), np.float32)
        cl.send_block(bad, mbad, mbad)
        with pytest.raises(ServeError):
            cl.recv_enhanced(0, timeout_s=60)
        cl.shutdown()
    finally:
        srv.stop()
