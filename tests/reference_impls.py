"""NumPy/SciPy oracle implementations of the reference semantics, used as
ground truth in parity tests.

These deliberately re-state the *formulas* of the reference (librosa's centered
STFT, the ideal-mask definitions of sigproc_utils.py:58-86, the SDW-MWF /
GEVD-MWF filters of se_utils/internal_formulas.py:31-103, and the two-step
TANGO pipeline of speech_enhancement/tango.py:252-457) in plain float64 NumPy,
independent of the JAX implementations under test.
"""
from __future__ import annotations

import numpy as np
import scipy.linalg
import scipy.signal

F64_EPS = np.finfo(np.float64).eps
ETA = 1e6


# ---------------------------------------------------------------- STFT oracle
def hann_periodic_np(n):
    return 0.5 - 0.5 * np.cos(2.0 * np.pi * np.arange(n) / n)


def stft_np(x, n_fft=512, hop=256):
    """Centered STFT with reflect padding and periodic Hann — the librosa
    conventions the reference relies on (tango.py:335)."""
    pad = n_fft // 2
    xp = np.pad(np.asarray(x, np.float64), pad, mode="reflect")
    n_frames = 1 + (len(xp) - n_fft) // hop
    win = hann_periodic_np(n_fft)
    out = np.empty((n_fft // 2 + 1, n_frames), np.complex128)
    for t in range(n_frames):
        out[:, t] = np.fft.rfft(xp[t * hop : t * hop + n_fft] * win)
    return out


def istft_np(spec, length, n_fft=512, hop=256):
    """Windowed overlap-add inverse with squared-window normalization
    (librosa istft conventions, tango.py:528-539)."""
    n_freq, n_frames = spec.shape
    win = hann_periodic_np(n_fft)
    total = (n_frames - 1) * hop + n_fft
    y = np.zeros(total)
    wss = np.zeros(total)
    for t in range(n_frames):
        frame = np.fft.irfft(spec[:, t], n=n_fft)
        y[t * hop : t * hop + n_fft] += frame * win
        wss[t * hop : t * hop + n_fft] += win**2
    nz = wss > np.finfo(np.float64).tiny
    y[nz] /= wss[nz]
    pad = n_fft // 2
    y = y[pad : pad + length]
    if len(y) < length:
        y = np.pad(y, (0, length - len(y)))
    return y


# ---------------------------------------------------------------- mask oracle
def tf_mask_np(s, n, mask_type="irm1", bin_thr=0.0):
    power = int(mask_type[-1])
    if mask_type.startswith("irm"):
        xi = (np.abs(s) / np.maximum(np.abs(n), F64_EPS)) ** power
        return xi / (1 + xi)
    if mask_type.startswith("ibm"):
        xi = (np.abs(s) / np.maximum(np.abs(n), F64_EPS)) ** power
        return (xi >= 10 ** (bin_thr / 10)).astype(np.float64)
    if mask_type.startswith("iam"):
        return (np.abs(s) / np.abs(s + n)) ** power
    raise ValueError(mask_type)


def vad_oracle_np(x, win_len=512, win_hop=256, thr=0.001, rat=2):
    """Windowed power-threshold VAD (sigproc_utils.py:12-55)."""
    x = np.asarray(x, np.float64)
    x2 = np.abs((x - x.mean()) ** 2)
    thr_ = thr * np.quantile(x2, 0.99)
    vad = np.zeros(len(x2))
    n_win = int(np.ceil((len(x2) - win_len) / win_hop + 1))
    for n in range(n_win):
        lo = n * win_hop
        hi = min(lo + win_len, len(x2))
        seg = x2[lo:hi]
        if np.sum(seg > thr_) >= int(len(seg) / rat):
            vad[lo:hi] = 1
    return vad


# -------------------------------------------------------------- filter oracle
def intern_filter_np(Rxx, Rnn, mu=1.0, ftype="gevd", rank=1):
    """SDW-MWF / GEVD-MWF filters (internal_formulas.py:31-81), float64."""
    C = Rxx.shape[0]
    t1 = np.zeros(C, dtype=Rxx.dtype)
    t1[0] = 1.0
    if ftype == "r1-mwf":
        D, X = np.linalg.eig(Rxx)
        D = np.real(D)
        imax = D.argmax()
        Rxx1 = np.outer(np.abs(D[imax]) * X[:, imax], np.conj(X[:, imax]))
        P = np.linalg.lstsq(Rnn, Rxx1, rcond=None)[0]
        return P[:, 0] / (mu + np.trace(P)), t1
    if ftype == "gevd":
        D, Q = scipy.linalg.eig(Rxx, Rnn)
        D = np.clip(D.real, F64_EPS, ETA)
        order = np.argsort(D)[::-1]
        D = D[order]
        Q = Q[:, order]
        if rank != "full":
            D = np.where(np.arange(C) < rank, D, 0.0)
        Qinv = np.linalg.inv(Q)
        W = (Q @ np.diag(D / (D + mu)) @ Qinv)[:, 0]
        t1 = Q[:, 0] * Qinv[0, 0]
        return W, t1
    if ftype == "mwf":
        P = np.linalg.lstsq(Rnn + Rxx, Rxx, rcond=None)[0]
        return P[:, 0], t1
    raise ValueError(ftype)


def covariances_np(a, b=None):
    """Frame-mean of rank-1 outer products: (C, F, T) -> (F, C, C)
    (tango.py:357-364)."""
    b = a if b is None else b
    C, F, T = a.shape
    R = np.zeros((F, C, C), np.complex128)
    for f in range(F):
        for t in range(T):
            R[f] += np.outer(a[:, f, t], np.conj(b[:, f, t]))
    return R / T


# ---------------------------------------------------------------- TANGO oracle
def tango_np(y, s, n, mask_type="irm1", mask_for_z="local"):
    """Two-step distributed rank-1 GEVD-MWF (tango.py:252-457) with oracle
    masks, equal channel counts per node.  y/s/n: (K, C, L) float64.

    Returns dict of (K, F, T) stacks: yf, sf, nf, z_y, z_s, z_n, zn, plus the
    per-node masks.
    """
    K, C, L = y.shape
    Y = np.stack([[stft_np(y[k, c]) for c in range(C)] for k in range(K)])
    S = np.stack([[stft_np(s[k, c]) for c in range(C)] for k in range(K)])
    N = np.stack([[stft_np(n[k, c]) for c in range(C)] for k in range(K)])
    F, T = Y.shape[-2:]

    # Step 1: local rank-1 GEVD at each node -> compressed signal z.
    masks_z = np.stack([tf_mask_np(S[k, 0], N[k, 0], mask_type) for k in range(K)])
    z_y = np.zeros((K, F, T), np.complex128)
    z_s = np.zeros((K, F, T), np.complex128)
    z_n = np.zeros((K, F, T), np.complex128)
    for k in range(K):
        sh = masks_z[k][None] * Y[k]
        nh = (1 - masks_z[k][None]) * Y[k]
        Rss = covariances_np(sh)
        Rnn = covariances_np(nh)
        for f in range(F):
            w, _ = intern_filter_np(Rss[f], Rnn[f], mu=1.0, ftype="gevd", rank=1)
            z_y[k, f] = np.conj(w) @ Y[k, :, f, :]
            z_s[k, f] = np.conj(w) @ S[k, :, f, :]
            z_n[k, f] = np.conj(w) @ N[k, :, f, :]
    zn = Y[:, 0] - z_y

    # Step 2: global rank-1 GEVD on [local mics ‖ z_{j != k}].
    yf = np.zeros((K, F, T), np.complex128)
    sf = np.zeros((K, F, T), np.complex128)
    nf = np.zeros((K, F, T), np.complex128)
    mask_w = masks_z  # oracle masks: step-2 mask equals step-1 mask at ref mic
    for k in range(K):
        others = [j for j in range(K) if j != k]
        stack_y = np.concatenate([Y[k], z_y[others]], axis=0)
        stack_s = np.concatenate([S[k], z_s[others]], axis=0)
        stack_n = np.concatenate([N[k], z_n[others]], axis=0)
        m = mask_w[k][None]
        if mask_for_z == "local":
            zs_stat = np.concatenate([m * Y[k], m * z_y[others]], axis=0)
            zn_stat = np.concatenate([(1 - m) * Y[k], (1 - m) * z_y[others]], axis=0)
        elif mask_for_z is None:
            zs_stat = np.concatenate([m * Y[k], z_y[others]], axis=0)
            zn_stat = np.concatenate([(1 - m) * Y[k], zn[others]], axis=0)
        else:
            raise NotImplementedError(mask_for_z)
        Rss = covariances_np(zs_stat)
        Rnn = covariances_np(zn_stat)
        for f in range(F):
            w, _ = intern_filter_np(Rss[f], Rnn[f], mu=1.0, ftype="gevd", rank=1)
            yf[k, f] = np.conj(w) @ stack_y[:, f, :]
            sf[k, f] = np.conj(w) @ stack_s[:, f, :]
            nf[k, f] = np.conj(w) @ stack_n[:, f, :]

    return {
        "yf": yf, "sf": sf, "nf": nf,
        "z_y": z_y, "z_s": z_s, "z_n": z_n, "zn": zn,
        "masks_z": masks_z, "mask_w": mask_w,
    }


def si_sdr_np(reference, estimation):
    """Scale-invariant SDR (metrics.py:342-392 semantics), float64."""
    reference = np.asarray(reference, np.float64)
    estimation = np.asarray(estimation, np.float64)
    alpha = np.sum(reference * estimation, -1, keepdims=True) / np.sum(
        reference**2, -1, keepdims=True
    )
    proj = alpha * reference
    noise = estimation - proj
    return 10 * np.log10(np.sum(proj**2, -1) / np.sum(noise**2, -1))


# ------------------------------------------------------------------ ISM oracle
def shoebox_rir_np(room_dim, source, mic, alpha, max_order=3, rir_len=4096, fs=16000, c=343.0, fdl=81):
    """Loop-based Allen & Berkley shoebox ISM with windowed-sinc fractional
    delays — the independent float64 oracle for disco_tpu.sim.ism (same
    conventions as pyroomacoustics' libroom: sum-order truncation, uniform
    sqrt(1-alpha) wall reflection, 1/(4 pi d) spreading)."""
    room_dim = np.asarray(room_dim, np.float64)
    source = np.asarray(source, np.float64)
    mic = np.asarray(mic, np.float64)
    beta = np.sqrt(max(1.0 - alpha, 0.0))
    half = fdl // 2
    rir = np.zeros(rir_len)
    N = max_order
    for n in range(-N, N + 1):
        for l in range(-N, N + 1):
            for m in range(-N, N + 1):
                for u in (0, 1):
                    for v in (0, 1):
                        for w in (0, 1):
                            n_refl = (abs(n - u) + abs(n) + abs(l - v) + abs(l)
                                      + abs(m - w) + abs(m))
                            if n_refl > N:
                                continue
                            img = np.array([
                                (1 - 2 * u) * source[0] + 2 * n * room_dim[0],
                                (1 - 2 * v) * source[1] + 2 * l * room_dim[1],
                                (1 - 2 * w) * source[2] + 2 * m * room_dim[2],
                            ])
                            d = max(np.linalg.norm(img - mic), 1e-3)
                            amp = beta**n_refl / (4 * np.pi * d)
                            delay = d * fs / c
                            t0 = int(np.floor(delay))
                            frac = delay - t0
                            for tap in range(-half, half + 1):
                                t = t0 + tap
                                if 0 <= t < rir_len:
                                    arg = tap - frac
                                    win = 0.5 * (1 + np.cos(np.pi * arg / (half + 1)))
                                    rir[t] += amp * np.sinc(arg) * win
    return rir


def shoebox_rirs_batched_np(room_dims, sources, mics, alphas, max_order=3,
                            rir_len=4096, fs=16000, c=343.0, fdl=81):
    """Float64 oracle of the BATCHED ISM lane
    (disco_tpu.sim.ism.shoebox_rirs_batched): B scenes x S sources x M mics
    of independent :func:`shoebox_rir_np` calls, stacked to
    ``(B, S, M, rir_len)``.  Deliberately the dumbest possible composition —
    the batched kernel's vmap-over-scenes structure never enters, so a
    broadcasting bug along any batch axis shows up as a parity failure."""
    room_dims = np.asarray(room_dims, np.float64)
    sources = np.asarray(sources, np.float64)
    mics = np.asarray(mics, np.float64)
    B, S = sources.shape[:2]
    M = mics.shape[1]
    out = np.zeros((B, S, M, rir_len))
    for b in range(B):
        for s in range(S):
            for m in range(M):
                out[b, s, m] = shoebox_rir_np(
                    room_dims[b], sources[b, s], mics[b, m], float(alphas[b]),
                    max_order=max_order, rir_len=rir_len, fs=fs, c=c, fdl=fdl)
    return out


def shoebox_rir_np_order20(room_dim, source, mics, alpha, max_order=20,
                           rir_len=8192, fs=16000, c=343.0, fdl=81,
                           chunk=20000):
    """Order-20, multi-mic float64 ISM oracle.

    Same physics as :func:`shoebox_rir_np` but feasible at high orders: the
    (n, l, m, u, v, w) lattice is enumerated once on host and the per-image
    work is vectorized in float64 chunks with an ``np.add.at`` scatter — a
    genuinely different computation path from the JAX kernel (which builds a
    dense (mics, images, taps) tensor and scatter-adds on device, in
    float32).  Used to pin `disco_tpu.sim.ism.shoebox_rir` at reference
    fidelity (VERDICT round 1, next-round item 1) and to generate the
    committed golden fixture (tests/data/golden_rir_order20.npz) in lieu of
    a pyroomacoustics-generated one — pyroomacoustics is not installable in
    this environment (zero egress), so the float64 oracle plays the role of
    libroom ground truth; conventions follow libroom's documented ones
    (sum-order truncation, sqrt(1-alpha) reflection, 1/(4 pi d) spreading,
    81-tap Hann windowed-sinc fractional delay).
    """
    room_dim = np.asarray(room_dim, np.float64)
    source = np.asarray(source, np.float64)
    mics = np.atleast_2d(np.asarray(mics, np.float64))
    M = mics.shape[0]
    beta = np.sqrt(max(1.0 - alpha, 0.0))
    half = fdl // 2

    # lattice enumeration (host, float64)
    N = max_order
    rng_ = np.arange(-N, N + 1)
    cells = np.stack(np.meshgrid(rng_, rng_, rng_, indexing="ij"), -1).reshape(-1, 3)
    pars = np.stack(np.meshgrid([0, 1], [0, 1], [0, 1], indexing="ij"), -1).reshape(-1, 3)
    lat = np.repeat(cells, len(pars), axis=0)
    par = np.tile(pars, (len(cells), 1))
    n_refl = np.abs(lat - par).sum(-1) + np.abs(lat).sum(-1)
    keep = n_refl <= N
    lat, par, n_refl = lat[keep], par[keep], n_refl[keep]

    taps = np.arange(-half, half + 1, dtype=np.float64)
    out = np.zeros((M, rir_len + 1))
    for lo in range(0, len(lat), chunk):
        l_c, p_c, r_c = lat[lo:lo + chunk], par[lo:lo + chunk], n_refl[lo:lo + chunk]
        img = (1.0 - 2.0 * p_c) * source[None, :] + 2.0 * l_c * room_dim[None, :]
        d = np.maximum(np.linalg.norm(img[None, :, :] - mics[:, None, :], axis=-1), 1e-3)
        amp = beta ** r_c[None, :] / (4.0 * np.pi * d)          # (M, I)
        delay = d * (fs / c)
        t0 = np.floor(delay).astype(np.int64)
        frac = delay - t0
        arg = taps[None, None, :] - frac[..., None]              # (M, I, T)
        win = 0.5 * (1.0 + np.cos(np.pi * arg / (half + 1)))
        win[np.abs(arg) > half + 1] = 0.0
        vals = amp[..., None] * np.sinc(arg) * win
        idx = t0[..., None] + taps.astype(np.int64)[None, None, :]
        oob = (idx < 0) | (idx >= rir_len)
        idx = np.where(oob, rir_len, idx)
        vals = np.where(oob, 0.0, vals)
        for mi in range(M):
            np.add.at(out[mi], idx[mi].reshape(-1), vals[mi].reshape(-1))
    return out[:, :rir_len]


def rt60_schroeder(rir, fs=16000, lo_db=-5.0, hi_db=-35.0):
    """RT60 estimate by linear fit of the Schroeder energy-decay curve
    between ``lo_db`` and ``hi_db`` (the T30 method, extrapolated to 60 dB)."""
    e = np.cumsum(np.asarray(rir, np.float64)[::-1] ** 2)[::-1]
    edc = 10 * np.log10(np.maximum(e / e[0], 1e-30))
    sel = (edc <= lo_db) & (edc >= hi_db)
    t = np.flatnonzero(sel)
    if len(t) < 10:
        return np.nan
    slope, _ = np.polyfit(t / fs, edc[sel], 1)
    return -60.0 / slope
