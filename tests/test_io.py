"""Tests for the native WAV codec (io/audio.py) — soundfile-compatible
semantics incl. the 24-bit PCM support scipy.io.wavfile lacks (VERDICT
round-1 missing #4)."""
import struct

import numpy as np
import pytest

from disco_tpu.io.audio import SUBTYPES, read_wav, write_wav


@pytest.fixture
def sig():
    rng = np.random.RandomState(0)
    return (0.8 * rng.randn(1000)).clip(-1, 0.999).astype(np.float32)


@pytest.mark.parametrize("subtype,atol", [
    ("FLOAT", 0.0),
    ("DOUBLE", 1e-7),          # float32 signal in a float64 container
    ("PCM_16", 2.0**-15),
    ("PCM_24", 2.0**-23),
    ("PCM_32", 2.0**-23),      # quantization below float32 resolution
])
def test_round_trip(tmp_path, sig, subtype, atol):
    p = tmp_path / f"{subtype}.wav"
    write_wav(p, sig, 16000, subtype=subtype)
    back, fs = read_wav(p)
    assert fs == 16000
    assert back.dtype == np.float32
    np.testing.assert_allclose(back, sig, atol=atol)


def test_round_trip_multichannel(tmp_path):
    rng = np.random.RandomState(1)
    x = (0.5 * rng.randn(500, 3)).clip(-1, 0.999).astype(np.float32)
    p = tmp_path / "mc.wav"
    write_wav(p, x, 8000, subtype="PCM_24")
    back, fs = read_wav(p)
    assert back.shape == (500, 3) and fs == 8000
    np.testing.assert_allclose(back, x, atol=2.0**-23)


def test_pcm24_interleaving_is_little_endian(tmp_path):
    """One full-scale-ish sample: check the exact 3-byte layout."""
    p = tmp_path / "one.wav"
    write_wav(p, np.array([0.5], np.float64), 16000, subtype="PCM_24")
    raw = p.read_bytes()
    data_at = raw.index(b"data") + 8
    assert raw[data_at : data_at + 3] == bytes([0x00, 0x00, 0x40])  # 0x400000 LE


def test_negative_pcm24_sign_extension(tmp_path):
    p = tmp_path / "neg.wav"
    x = np.array([-0.5, -1.0, 0.25], np.float64)
    write_wav(p, x, 16000, subtype="PCM_24")
    back, _ = read_wav(p, dtype=np.float64)
    np.testing.assert_allclose(back, x, atol=2.0**-22)


def test_scipy_interop_reading_our_files(tmp_path, sig):
    """Files we write in scipy-supported formats load identically there."""
    import scipy.io.wavfile

    for subtype, scale in (("PCM_16", 2.0**15), ("FLOAT", 1.0)):
        p = tmp_path / f"interop_{subtype}.wav"
        write_wav(p, sig, 16000, subtype=subtype)
        fs, data = scipy.io.wavfile.read(str(p))
        assert fs == 16000
        np.testing.assert_allclose(data / scale, sig, atol=2.0 / scale if scale > 1 else 0)


def test_reading_scipy_written_files(tmp_path, sig):
    import scipy.io.wavfile

    p16 = tmp_path / "s16.wav"
    scipy.io.wavfile.write(str(p16), 16000, (sig * 2**15).astype(np.int16))
    back, fs = read_wav(p16)
    np.testing.assert_allclose(back, sig, atol=2.0**-14)

    pf = tmp_path / "sf.wav"
    scipy.io.wavfile.write(str(pf), 16000, sig)
    back, _ = read_wav(pf)
    np.testing.assert_allclose(back, sig, atol=0)


def test_extensible_header(tmp_path, sig):
    """WAVE_FORMAT_EXTENSIBLE (0xFFFE) wrapping PCM is resolved through the
    sub-format GUID."""
    pcm = (sig * 2**15).astype("<i2").tobytes()
    # GUID = {00000001-0000-0010-8000-00aa00389b71}: PCM sub-format
    guid = struct.pack("<H", 1) + b"\x00\x00" + bytes.fromhex("0000100080000000aa00389b71")
    # base fmt (16) + cbSize=22 + validBits + channelMask + 16-byte GUID
    fmt = (struct.pack("<HHIIHH", 0xFFFE, 1, 16000, 32000, 2, 16)
           + struct.pack("<HHI", 22, 16, 0b1) + guid[:16])
    body = struct.pack("<4sI", b"fmt ", len(fmt)) + fmt + struct.pack("<4sI", b"data", len(pcm)) + pcm
    p = tmp_path / "ext.wav"
    p.write_bytes(struct.pack("<4sI4s", b"RIFF", 4 + len(body), b"WAVE") + body)
    back, fs = read_wav(p)
    assert fs == 16000
    np.testing.assert_allclose(back, sig, atol=2.0**-14)


def test_odd_data_chunk_padding(tmp_path):
    """Odd-byte data chunks (e.g. mono 24-bit with odd sample count) are
    word-aligned on write and read back fine."""
    x = np.array([0.1, -0.2, 0.3], np.float64)  # 9 data bytes
    p = tmp_path / "odd.wav"
    write_wav(p, x, 16000, subtype="PCM_24")
    assert p.stat().st_size % 2 == 0
    back, _ = read_wav(p, dtype=np.float64)
    np.testing.assert_allclose(back, x, atol=2.0**-22)


def test_full_scale_pcm_does_not_wrap(tmp_path):
    """+1.0 must clip to the positive rail, not wrap to full-scale negative."""
    for subtype, rail in (("PCM_16", (2**15 - 1) / 2**15),
                          ("PCM_24", (2**23 - 1) / 2**23),
                          ("PCM_32", (2**31 - 1) / 2**31)):
        p = tmp_path / f"rail_{subtype}.wav"
        write_wav(p, np.array([1.0, -1.0]), 16000, subtype=subtype)
        back, _ = read_wav(p, dtype=np.float64)
        assert back[0] == pytest.approx(rail, abs=1e-9), subtype
        assert back[1] == -1.0, subtype


def test_bad_file_raises(tmp_path):
    p = tmp_path / "bad.wav"
    p.write_bytes(b"not a wav file at all")
    with pytest.raises(ValueError, match="RIFF"):
        read_wav(p)


def test_unknown_subtype_raises(tmp_path):
    with pytest.raises(ValueError, match="subtype"):
        write_wav(tmp_path / "x.wav", np.zeros(4), 16000, subtype="PCM_8")
    assert set(SUBTYPES) == {"PCM_16", "PCM_24", "PCM_32", "FLOAT", "DOUBLE"}
