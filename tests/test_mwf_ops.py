"""Fused rank-1 GEVD-MWF solve (ops/mwf_ops.py) vs the float64 oracle.

Documented tolerances (measured on the CI host, see the perf doc's
solve-fusion section):

* exact lane (f32, both impls): rel-l2 vs the float64 oracle filter
  <= 1e-3 on well-conditioned pencils (measured ~5e-7 — the same level as
  the separate-stage f32 eigh path) and <= 5e-2 on near-degenerate
  warm-up-scale pencils;
* xla-vs-pallas (same algorithm, two compilations): <= 1e-5 rel-l2
  (measured ~2e-7);
* bf16 lane (pencil planes quantized at the HBM->VMEM boundary, f32
  in-VMEM iterations): <= 2e-2 rel-l2 vs the oracle (measured ~2e-3),
  SDR within 0.1 dB of the f32 lane end-to-end (test_tango-style gate).
"""
import numpy as np
import pytest

from disco_tpu.beam.filters import parse_solver_spec, rank1_gevd, solver_lane_info
from disco_tpu.ops.mwf_ops import (
    fused_mwf_pallas,
    fused_mwf_xla,
    rank1_gevd_fused,
    resolve_mwf_impl,
)
from tests.reference_impls import intern_filter_np


def _pencils(rng, C, F=16, T=80, scale=1.0, cond="good"):
    """Random hermitian PSD (F, C, C) pencils in float64 (+ complex64
    copies): a rank-1-dominant speech field over diffuse noise — the
    filter bank's covariance shape.  ``cond='warmup'`` builds
    near-degenerate warm-up-like statistics: very few frames (rank
    deficient before loading), ~1e-12 trace scale."""
    if cond == "warmup":
        T = max(C // 2, 2)
        scale = 1e-12
    src = rng.standard_normal((F, T))
    gains = rng.standard_normal((C, 1, 1)) + 1j * rng.standard_normal((C, 1, 1))
    S = gains * src[None] + 0.05 * (
        rng.standard_normal((C, F, T)) + 1j * rng.standard_normal((C, F, T))
    )
    N = 0.6 * (rng.standard_normal((C, F, T)) + 1j * rng.standard_normal((C, F, T)))
    Rss64 = np.einsum("cft,dft->fcd", S, np.conj(S)) / T * scale
    Rnn64 = np.einsum("cft,dft->fcd", N, np.conj(N)) / T * scale
    if cond == "good":
        Rnn64 = Rnn64 + 0.1 * scale * np.eye(C)
    return Rss64, Rnn64


def _oracle_w(Rss64, Rnn64, mu=1.0):
    F = Rss64.shape[0]
    return np.stack([
        intern_filter_np(Rss64[f], Rnn64[f], mu=mu, ftype="gevd", rank=1)[0]
        for f in range(F)
    ])


def _rel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return float(np.linalg.norm(a - b) / np.linalg.norm(b))


@pytest.mark.parametrize("C", [4, 6])
def test_fused_lanes_match_float64_oracle(rng, C):
    """Both fused lanes (XLA twin, pallas kernel in interpret mode) against
    the float64 GEVD oracle at the documented exact-lane tolerance, and
    against each other at roundoff."""
    Rss64, Rnn64 = _pencils(rng, C)
    Rss = Rss64.astype(np.complex64)
    Rnn = Rnn64.astype(np.complex64)
    W64 = _oracle_w(Rss64, Rnn64)
    w_x, _ = fused_mwf_xla(Rss, Rnn)
    w_p, _ = fused_mwf_pallas(Rss, Rnn, tile=128, interpret=True)
    assert _rel(w_x, W64) < 1e-3, _rel(w_x, W64)
    assert _rel(w_p, W64) < 1e-3, _rel(w_p, W64)
    assert _rel(w_p, w_x) < 1e-5, _rel(w_p, w_x)


@pytest.mark.slow
@pytest.mark.parametrize("C", list(range(4, 12)))
def test_fused_oracle_parity_full_size_range(rng, C):
    """The full pipeline size range C in {4..11} (step-1 mics up to the
    8-node step-2 stack width), both lanes, oracle-pinned."""
    Rss64, Rnn64 = _pencils(rng, C)
    Rss = Rss64.astype(np.complex64)
    Rnn = Rnn64.astype(np.complex64)
    W64 = _oracle_w(Rss64, Rnn64)
    w_x, _ = fused_mwf_xla(Rss, Rnn)
    w_p, _ = fused_mwf_pallas(Rss, Rnn, tile=128, interpret=True)
    assert _rel(w_x, W64) < 1e-3, (C, _rel(w_x, W64))
    assert _rel(w_p, W64) < 1e-3, (C, _rel(w_p, W64))


def test_fused_near_degenerate_warmup_pencils(rng):
    """Warm-up-scale statistics (~1e-12 trace, fewer frames than channels —
    rank-deficient before the loading): on these pencils EVERY f32 solver
    family departs from the float64 oracle (the clamped generalized
    eigenvalues sit at the conditioning limit), so the contract is that
    the fused chain tracks the SHIPPED f32 eigh path bin for bin — same
    degenerate-bin behavior as the solver it replaces — and the sanitized
    output is always finite."""
    from disco_tpu.beam.filters import gevd_mwf

    Rss64, Rnn64 = _pencils(rng, 5, cond="warmup")
    Rss = Rss64.astype(np.complex64)
    Rnn = Rnn64.astype(np.complex64)
    w_e = np.asarray(gevd_mwf(Rss, Rnn, rank=1, sanitize=False)[0])
    fin_e = np.isfinite(w_e).all(axis=-1)
    for w, name in (
        (fused_mwf_xla(Rss, Rnn)[0], "xla"),
        (fused_mwf_pallas(Rss, Rnn, tile=128, interpret=True)[0], "pallas"),
    ):
        w = np.asarray(w)
        # on the bins the eigh path solves, the fused chain agrees
        ok = fin_e & np.isfinite(w).all(axis=-1)
        assert ok.sum() >= fin_e.sum() * 0.9, (name, ok.sum(), fin_e.sum())
        if ok.any():
            assert _rel(w[ok], w_e[ok]) < 5e-2, (name, _rel(w[ok], w_e[ok]))
        # the sanitize guard keeps the pipeline finite regardless
        w_s = np.asarray(rank1_gevd(Rss, Rnn, solver=f"fused-{name}")[0])
        assert np.isfinite(w_s).all(), name


def test_fused_nan_sanitize_path(rng):
    """A corrupted pencil (NaN entries) must surface exactly like
    gevd_mwf's degenerate-bin policy: the e1 pass-through selector under
    sanitize=True, raw non-finite values under sanitize=False (the
    streaming ffill hold's signal)."""
    Rss64, Rnn64 = _pencils(rng, 4, F=8)
    Rss = Rss64.astype(np.complex64)
    Rnn = Rnn64.astype(np.complex64)
    Rnn_bad = np.array(Rnn)
    Rnn_bad[3] = np.nan
    for impl in ("xla", "pallas"):
        kw = {"interpret": True} if impl == "pallas" else {}
        w, t1 = rank1_gevd_fused(Rss, Rnn_bad, impl=impl, sanitize=True, **kw)
        w, t1 = np.asarray(w), np.asarray(t1)
        e1 = np.zeros(4, np.complex64)
        e1[0] = 1.0
        np.testing.assert_array_equal(w[3], e1)
        np.testing.assert_array_equal(t1[3], e1)
        assert np.isfinite(w).all() and np.isfinite(t1).all()
        w_raw, _ = rank1_gevd_fused(Rss, Rnn_bad, impl=impl, sanitize=False, **kw)
        assert not np.isfinite(np.asarray(w_raw)[3]).all()
        # intact bins are untouched by the guard
        w_ok, _ = rank1_gevd_fused(Rss, Rnn, impl=impl, sanitize=True, **kw)
        np.testing.assert_allclose(w[:3], np.asarray(w_ok)[:3], rtol=0, atol=0)


def test_fused_bf16_lane_documented_tolerance(rng):
    """The bf16 solve lane (module docstring): measured deviation within
    the documented <= 2e-2 rel-l2 vs the float64 oracle, and the default
    f32 lane is bit-identical whether or not the bf16 program exists."""
    Rss64, Rnn64 = _pencils(rng, 6)
    Rss = Rss64.astype(np.complex64)
    Rnn = Rnn64.astype(np.complex64)
    W64 = _oracle_w(Rss64, Rnn64)
    w_f32 = np.asarray(rank1_gevd(Rss, Rnn, solver="fused-xla")[0])
    for impl in ("xla", "pallas"):
        kw = {"interpret": True} if impl == "pallas" else {}
        w_b, _ = rank1_gevd_fused(Rss, Rnn, impl=impl, precision="bf16", **kw)
        err = _rel(w_b, W64)
        assert 1e-5 < err < 2e-2, (impl, err)  # really quantized, still in tolerance
    # default lane bit-identical to the explicit f32 spelling
    w_again = np.asarray(rank1_gevd(Rss, Rnn, solver="fused-xla", precision="f32")[0])
    np.testing.assert_array_equal(w_f32, w_again)


def test_fused_specs_through_rank1_gevd_dispatch(rng):
    """'fused', 'fused-xla', 'fused-pallas' and ':N' sweep suffixes are
    reachable through THE solver dispatch and reproduce the eigh filter;
    the grammar rejects malformed fused specs."""
    Rss64, Rnn64 = _pencils(rng, 4)
    Rss = Rss64.astype(np.complex64)
    Rnn = Rnn64.astype(np.complex64)
    w_e, t1_e = rank1_gevd(Rss, Rnn, solver="eigh")
    for spec in ("fused", "fused-xla", "fused-pallas", "fused:8", "fused-pallas:8"):
        w, t1 = rank1_gevd(Rss, Rnn, solver=spec)
        assert _rel(w, w_e) < 1e-3, (spec, _rel(w, w_e))
        assert _rel(t1, t1_e) < 1e-3, (spec, _rel(t1, t1_e))
    assert parse_solver_spec("fused:3") == ("fused", 3)
    with pytest.raises(ValueError, match="N >= 1"):
        rank1_gevd(Rss, Rnn, solver="fused:0")
    with pytest.raises(ValueError, match="unknown GEVD solver"):
        parse_solver_spec("fused-mosaic")
    # an insufficient sweep count visibly degrades vs the converged default
    w_1, _ = rank1_gevd(Rss, Rnn, solver="fused:1")
    assert _rel(w_1, w_e) > 10 * _rel(rank1_gevd(Rss, Rnn, solver="fused")[0], w_e)


# -- the step-1 batch-in-lanes fused lane (disco-chain) -----------------------
def _step1_field(rng, K, C, F=16, T=64):
    """Per-node speech-over-noise STFT fields (float64 + complex64 copies)
    and speech-presence masks — the step-1 local MWF's input shape."""
    Y64 = np.empty((K, C, F, T), np.complex128)
    masks = np.empty((K, F, T), np.float32)
    for k in range(K):
        src = rng.standard_normal((F, T)) + 1j * rng.standard_normal((F, T))
        gains = rng.standard_normal((C, 1, 1)) + 1j * rng.standard_normal(
            (C, 1, 1))
        S = gains * src
        N = 0.6 * (rng.standard_normal((C, F, T))
                   + 1j * rng.standard_normal((C, F, T)))
        Y64[k] = S + N
        ps, pn = np.abs(S[0]) ** 2, np.abs(N[0]) ** 2
        masks[k] = (ps / (ps + pn)).astype(np.float32)
    return Y64, Y64.astype(np.complex64), masks


def _step1_oracle_z(Y64, masks):
    """Float64 step-1 z per node: masked covariances -> GEVD filter ->
    compression (the step-1 half of reference_impls.tango_np)."""
    from tests.reference_impls import covariances_np

    K, C, F, T = Y64.shape
    z = np.zeros((K, F, T), np.complex128)
    for k in range(K):
        Rss = covariances_np(masks[k][None] * Y64[k])
        Rnn = covariances_np((1 - masks[k][None]) * Y64[k])
        for f in range(F):
            w, _ = intern_filter_np(Rss[f], Rnn[f], mu=1.0, ftype="gevd",
                                    rank=1)
            z[k, f] = np.conj(w) @ Y64[k, :, f, :]
    return z


@pytest.mark.parametrize("C", [2, 4, 6])
def test_step1_fused_matches_float64_oracle(rng, C):
    """compute_z_signals(solver='fused*') — ALL K x F step-1 pencils as
    ONE batch-in-lanes solve — against the float64 per-pencil GEVD oracle
    at the documented exact-lane tolerance, on both impl lanes, across
    the step-1 mic range; the separate-stage eigh path sits at the same
    level (the fused lane replaces it 1:1)."""
    from disco_tpu.enhance import compute_z_signals

    K = 3
    Y64, Y, masks = _step1_field(rng, K, C)
    z64 = _step1_oracle_z(Y64, masks)
    z_e = np.asarray(compute_z_signals(None, None, None, Y=Y, S=Y, N=Y,
                                       masks_z=masks, solver="eigh")["z_y"])
    assert _rel(z_e, z64) < 1e-3, (C, _rel(z_e, z64))
    for spec in ("fused-xla", "fused-pallas"):
        z_f = np.asarray(compute_z_signals(None, None, None, Y=Y, S=Y, N=Y,
                                           masks_z=masks,
                                           solver=spec)["z_y"])
        assert _rel(z_f, z64) < 1e-3, (spec, C, _rel(z_f, z64))
        assert _rel(z_f, z_e) < 1e-3, (spec, C, _rel(z_f, z_e))


def test_step1_fused_bf16_documented_tolerance(rng):
    """The bf16 solve lane through the step-1 fusion: really quantized,
    still inside the documented <= 2e-2 rel tolerance vs the oracle."""
    from disco_tpu.enhance import compute_z_signals

    Y64, Y, masks = _step1_field(rng, 3, 4)
    z64 = _step1_oracle_z(Y64, masks)
    z_b = np.asarray(compute_z_signals(None, None, None, Y=Y, S=Y, N=Y,
                                       masks_z=masks, solver="fused-xla",
                                       precision="bf16")["z_y"])
    err = _rel(z_b, z64)
    assert 1e-6 < err < 2e-2, err


def test_step1_fused_warmup_scale_stays_finite(rng):
    """Warm-up-scale step-1 statistics (tiny trace, fewer frames than
    mics): the fused lane's sanitize guard keeps every z bin finite —
    same degenerate-bin policy as the eigh path it replaces."""
    from disco_tpu.enhance import compute_z_signals

    K, C, F, T = 2, 4, 8, 2
    Y = (1e-6 * (rng.standard_normal((K, C, F, T))
                 + 1j * rng.standard_normal((K, C, F, T)))
         ).astype(np.complex64)
    masks = rng.uniform(0.05, 0.95, (K, F, T)).astype(np.float32)
    for spec in ("fused-xla", "eigh"):
        z = np.asarray(compute_z_signals(None, None, None, Y=Y, S=Y, N=Y,
                                         masks_z=masks, solver=spec)["z_y"])
        assert np.isfinite(z).all(), spec


def test_step1_fused_time_domain_entry_and_zn_invariant(rng):
    """The (K, C, L) time entry point with a fused spec: matches the eigh
    step-1 at tolerance and preserves the zn = y_ref - z export contract
    (test_inference's invariant, fused edition); a malformed spec fails
    through THE shared grammar."""
    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance import compute_z_signals

    K, C, L = 2, 3, 4096
    s = rng.standard_normal((K, C, L)).astype(np.float32)
    n = (0.3 * rng.standard_normal((K, C, L))).astype(np.float32)
    y = s + n
    out_f = compute_z_signals(y, s, n, mask_type="irm1", solver="fused")
    out_e = compute_z_signals(y, s, n, mask_type="irm1", solver="eigh")
    assert _rel(out_f["z_y"], out_e["z_y"]) < 1e-3
    Y = stft(y)
    np.testing.assert_allclose(
        np.asarray(out_f["zn"]),
        np.asarray(Y[:, 0] - out_f["z_y"]), rtol=1e-4, atol=1e-5)
    with pytest.raises(ValueError, match="unknown GEVD solver"):
        compute_z_signals(y, s, n, solver="fused-mosaic")


def test_resolve_mwf_impl_policy(monkeypatch):
    """The shared ops.resolve policy: 'auto' = xla off-TPU, the
    DISCO_TPU_MWF_IMPL env escape hatch overrides, explicit choices pass
    through, junk rejected — same semantics as the cov/stft seams."""
    monkeypatch.delenv("DISCO_TPU_MWF_IMPL", raising=False)
    assert resolve_mwf_impl("auto") == "xla"  # CPU test env
    assert resolve_mwf_impl("pallas") == "pallas"
    assert resolve_mwf_impl("xla") == "xla"
    monkeypatch.setenv("DISCO_TPU_MWF_IMPL", "pallas")
    assert resolve_mwf_impl("auto") == "pallas"
    assert resolve_mwf_impl("xla") == "xla"  # explicit beats env
    monkeypatch.setenv("DISCO_TPU_MWF_IMPL", "mosaic")
    with pytest.raises(ValueError, match="DISCO_TPU_MWF_IMPL"):
        resolve_mwf_impl("auto")
    with pytest.raises(ValueError, match="unknown impl"):
        resolve_mwf_impl("fused")


def test_solver_lane_info_provenance(monkeypatch):
    """The bench-record provenance helper resolves each family to its
    concrete kernel (post-ops.resolve for the fused family)."""
    monkeypatch.delenv("DISCO_TPU_MWF_IMPL", raising=False)
    assert solver_lane_info("power") == {
        "spec": "power", "base": "power", "n": None, "impl": "xla"}
    assert solver_lane_info("jacobi-pallas:6")["impl"] == "pallas"
    info = solver_lane_info("fused")
    assert info["base"] == "fused" and info["impl"] == "xla"  # CPU resolution
    monkeypatch.setenv("DISCO_TPU_MWF_IMPL", "pallas")
    assert solver_lane_info("fused")["impl"] == "pallas"
    assert solver_lane_info("fused-xla")["impl"] == "xla"  # pinned lane wins


def test_serve_session_config_validates_solver():
    """SessionConfig runs wire-decoded solver specs through THE shared
    grammar at admission (a bad spec fails with a clean error instead of
    at first dispatch inside the tick loop)."""
    from disco_tpu.serve.session import SessionConfig

    kw = dict(n_nodes=2, mics_per_node=2, n_freq=5, block_frames=8)
    assert SessionConfig(**kw, solver="fused").solver == "fused"
    assert SessionConfig(**kw, solver="fused-pallas:6").solver == "fused-pallas:6"
    with pytest.raises(ValueError, match="session config solver"):
        SessionConfig(**kw, solver="fused-mosaic")
    with pytest.raises(ValueError, match="session config solver"):
        SessionConfig(**kw, solver="eigh:4")


@pytest.mark.parametrize("solver", ["fused", "fused-pallas"])
def test_streaming_refresh_with_fused_solver(rng, solver):
    """The streaming refresh path reaches the fused solve (sanitize=False
    + ffill hold semantics preserved): finite output on BOTH lanes —
    'fused-pallas' runs the kernel under _stream_filter's jax.vmap (the
    exact shape an on-TPU serve session with the fused solver dispatches),
    in interpret mode off-TPU, so the vmap batching of the pallas_call is
    covered before the first real-chip session hits it."""
    import jax.numpy as jnp

    from disco_tpu.enhance.streaming import streaming_tango

    K, C, F, T = 2, 2, 5, 16
    Y = jnp.asarray(
        (rng.standard_normal((K, C, F, T))
         + 1j * rng.standard_normal((K, C, F, T))).astype(np.complex64))
    m = jnp.asarray(rng.uniform(0.1, 0.9, (K, F, T)).astype(np.float32))
    out = streaming_tango(Y, m, m, update_every=4, solver=solver)
    yf = np.asarray(out["yf"])
    assert yf.shape == (K, F, T)
    assert np.isfinite(yf).all()
    assert np.abs(yf).max() > 0


def test_session_config_solver_validation_stays_jax_free():
    """SessionConfig is constructed in the numpy-only serve CLIENT process:
    its solver validation (disco_tpu.solver_spec) must not drag jax into a
    fresh interpreter — the DL005 purity / single-chip-claim contract
    (pulling jax into a client host would claim the tunneled chip)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "from disco_tpu.serve.session import SessionConfig\n"
         "SessionConfig(n_nodes=2, mics_per_node=2, n_freq=5,\n"
         "              block_frames=8, solver='fused:6')\n"
         "assert 'jax' not in sys.modules, 'jax leaked into the client'\n"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


def test_tango_fused_solver_end_to_end(rng):
    """Full two-step TANGO with solver='fused' matches the eigh pipeline
    at SDR level (the test_eigh_ops jacobi gate, fused edition)."""
    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.core.metrics import si_sdr
    from disco_tpu.enhance import oracle_masks, tango

    K, C, L = 3, 2, 16384
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same")
                   for _ in range(C)]) for _ in range(K)]
    )
    n = 0.8 * rng.standard_normal((K, C, L))
    y = s + n
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    res_e = tango(Y, S, N, masks, masks, policy="local", solver="eigh")
    res_f = tango(Y, S, N, masks, masks, policy="local", solver="fused")
    for k in range(K):
        sdr_e = si_sdr(s[k, 0], np.asarray(istft(res_e.yf[k], L), np.float64))
        sdr_f = si_sdr(s[k, 0], np.asarray(istft(res_f.yf[k], L), np.float64))
        assert abs(sdr_e - sdr_f) < 0.1, (k, sdr_e, sdr_f)


@pytest.mark.slow
def test_tango_fused_bf16_sdr_gate(rng):
    """The bf16 solve lane end-to-end (tango, solver='fused',
    precision='bf16'): SDR within 0.1 dB of the fused f32 lane — the
    PR-9 documented-tolerance pattern extended into the solve."""
    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.core.metrics import si_sdr
    from disco_tpu.enhance import oracle_masks, tango

    K, C, L = 2, 2, 16384
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same")
                   for _ in range(C)]) for _ in range(K)]
    )
    n = 0.8 * rng.standard_normal((K, C, L))
    y = s + n
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    res_f = tango(Y, S, N, masks, masks, policy="local", solver="fused")
    res_b = tango(Y, S, N, masks, masks, policy="local", solver="fused",
                  precision="bf16")
    for k in range(K):
        sdr_f = si_sdr(s[k, 0], np.asarray(istft(res_f.yf[k], L), np.float64))
        sdr_b = si_sdr(s[k, 0], np.asarray(istft(res_b.yf[k], L), np.float64))
        assert abs(sdr_f - sdr_b) < 0.1, (k, sdr_f, sdr_b)
