"""Property-based invariants (hypothesis) for the pure DSP/math kernels —
the reference's parametrized-pure-function test style (SURVEY.md §4) pushed
to randomized inputs.  Jitted functions keep FIXED shapes across examples
(values are drawn, shapes are not) so each property compiles once."""
import numpy as np
import pytest

# hypothesis is not part of the image's baked-in dependency set (and nothing
# may be pip-installed, CLAUDE.md); skip cleanly instead of erroring at
# collection so the tier-1 gate sees a tracked skip, not a collection error.
hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed in this environment"
)
from hypothesis import given, settings, strategies as st

from disco_tpu.core.dsp import N_FFT, istft, n_stft_frames, stft
from disco_tpu.core.masks import tf_mask
from disco_tpu.core.mathx import cart2pol, db2lin, lin2db, pol2cart
from disco_tpu.core.sigproc import increase_to_snr

_SET = settings(max_examples=25, deadline=None)

floats = st.floats(min_value=-1e3, max_value=1e3, allow_nan=False, width=64)
pos_floats = st.floats(min_value=1e-6, max_value=1e6, allow_nan=False, width=64)


@given(st.lists(pos_floats, min_size=1, max_size=16))
@_SET
def test_db_roundtrip(vals):
    x = np.asarray(vals)
    np.testing.assert_allclose(db2lin(lin2db(x)), x, rtol=1e-5)  # f32 kernels


@given(st.lists(floats, min_size=2, max_size=2), st.lists(floats, min_size=2, max_size=2))
@_SET
def test_polar_roundtrip(a, b):
    x, y = np.asarray(a), np.asarray(b)
    rho, phi = cart2pol(x, y)
    x2, y2 = pol2cart(rho, phi)
    np.testing.assert_allclose(x2, x, atol=1e-3)  # f32 trig at |v| up to 1e3
    np.testing.assert_allclose(y2, y, atol=1e-3)


@given(st.integers(min_value=0, max_value=2**31 - 1))
@_SET
def test_stft_istft_roundtrip(seed):
    """Perfect reconstruction (COLA) to f32 tolerance at a fixed length."""
    rng = np.random.default_rng(seed)
    L = 4096
    x = rng.standard_normal(L).astype(np.float32)
    y = np.asarray(istft(stft(x), length=L))
    # boundary frames are touched by the reflect-pad; interior is exact
    np.testing.assert_allclose(y[N_FFT:-N_FFT], x[N_FFT:-N_FFT], atol=2e-6)
    assert np.asarray(stft(x)).shape == (N_FFT // 2 + 1, n_stft_frames(L))


@given(st.integers(min_value=0, max_value=2**31 - 1),
       st.sampled_from(["irm1", "irm2", "ibm1", "iam1", "iam2"]))
@_SET
def test_mask_ranges(seed, kind):
    rng = np.random.default_rng(seed)
    S = (rng.standard_normal((8, 10)) + 1j * rng.standard_normal((8, 10))).astype(np.complex64)
    N = (rng.standard_normal((8, 10)) + 1j * rng.standard_normal((8, 10))).astype(np.complex64)
    m = np.asarray(tf_mask(S, N, kind))
    assert np.isfinite(m).all()
    assert (m >= 0).all()
    if kind.startswith(("irm", "ibm")):
        assert (m <= 1.0 + 1e-6).all()


@given(st.integers(min_value=0, max_value=2**31 - 1),
       st.floats(min_value=-10, max_value=20, allow_nan=False))
@_SET
def test_increase_to_snr_hits_target(seed, snr_db):
    rng = np.random.default_rng(seed)
    s = rng.standard_normal(8000)
    n = rng.standard_normal(8000)
    n2 = increase_to_snr(s, n, snr_db)
    got = 10 * np.log10(np.var(s) / np.var(n2))
    assert abs(got - snr_db) < 0.2, (got, snr_db)


@given(st.integers(min_value=0, max_value=2**31 - 1),
       st.floats(min_value=-5.0, max_value=5.0, allow_nan=False))
@_SET
def test_jacobi_shift_invariance(seed, shift):
    """eigh_jacobi(A + c I) has eigenvalues shifted by exactly c and the
    same invariant subspaces (residual check against the shifted matrix)."""
    from disco_tpu.ops.eigh_ops import eigh_jacobi

    rng = np.random.default_rng(seed)
    X = rng.standard_normal((4, 5, 5)) + 1j * rng.standard_normal((4, 5, 5))
    A = (X @ np.conj(np.swapaxes(X, -1, -2)) / 5).astype(np.complex64)
    lam0, _ = eigh_jacobi(A)
    As = (A + shift * np.eye(5)).astype(np.complex64)
    lam1, V1 = eigh_jacobi(As)
    np.testing.assert_allclose(np.asarray(lam1), np.asarray(lam0) + shift, atol=5e-3)
    V1 = np.asarray(V1, np.complex128)
    resid = np.linalg.norm(As.astype(np.complex128) @ V1 - V1 * np.asarray(lam1, np.float64)[..., None, :])
    assert resid / (np.linalg.norm(As) + 1e-9) < 1e-3


@given(st.integers(min_value=0, max_value=2**31 - 1))
@_SET
def test_welford_matches_numpy(seed):
    from disco_tpu.core.mathx import WelfordsOnlineAlgorithm

    rng = np.random.default_rng(seed)
    widths = (7, 31, 2, 19)  # fixed: one jit compile per shape across all examples
    chunks = [rng.standard_normal((3, w)) for w in widths]  # (features, frames)
    w = WelfordsOnlineAlgorithm(3)
    for c in chunks:
        w.quick_update(c)
    allx = np.concatenate(chunks, axis=1)
    np.testing.assert_allclose(np.asarray(w.mean), allx.mean(1), atol=1e-4)  # f32 state
    np.testing.assert_allclose(np.asarray(w.std), allx.std(1), atol=1e-4)
