"""All BASELINE.json milestone configs run end-to-end (tiny scales)."""
import numpy as np
import pytest

from disco_tpu import milestones


@pytest.fixture(scope="module")
def results():
    return milestones.run_all(tiny=True)


def test_all_configs_run(results):
    names = [r["config"] for r in results]
    assert names == [
        "mvdr_single_clip",
        "disco_mwf_4node",
        "tango_4node",
        "meetit_separation",
        "batched_meetit_end_to_end",
        "streaming_latency",
    ]
    for r in results[:5]:
        assert r["rtf"] > 0


def test_mvdr_improves(results):
    r = results[0]
    assert r["si_sdr_out"] > r["si_sdr_in"] + 3


def test_mwf_and_tango_improve(results):
    for r in (results[1], results[2]):
        assert all(d > 1 for d in r["delta_si_sdr"]), r  # 1 s tiny clips: coarse stats


def test_separation_improves(results):
    assert all(d > 0 for d in results[3]["delta_si_sdr"]), results[3]  # tiny 1 s clips


def test_batched_end_to_end_finite(results):
    r = results[4]
    assert np.isfinite(r["mean_si_sdr_out"])
    assert r["rooms"] == 2
