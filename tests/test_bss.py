"""Tests for the native filtered-projection bss_eval (core/bss.py).

The FFT/block-Toeplitz implementation is pinned against an INDEPENDENT
brute-force oracle that materializes the delayed-reference design matrix
explicitly and projects with ``np.linalg.lstsq`` — a completely different
computation path for the same math (Vincent et al. 2006).  mir_eval itself
is not available in this environment; the brute-force oracle plays the role
of its golden values.
"""
import numpy as np
import pytest

from disco_tpu.core.bss import bss_eval_sources, _Projector
from disco_tpu.core.metrics import si_bss


def _brute_force_projection(refs, est, flen, srcs):
    """Oracle: explicit (T+flen-1, len(srcs)*flen) design matrix of delayed
    references, lstsq projection of the zero-padded estimate onto it."""
    nsrc, T = refs.shape
    n_out = T + flen - 1
    cols = []
    for i in srcs:
        padded = np.concatenate([refs[i], np.zeros(flen - 1)])
        for tau in range(flen):
            cols.append(np.roll(padded, tau) * (np.arange(n_out) >= tau))
    A = np.stack(cols, axis=1)
    e = np.concatenate([est, np.zeros(flen - 1)])
    coef, *_ = np.linalg.lstsq(A, e, rcond=None)
    return A @ coef


def _brute_force_bss(refs, est, j, flen):
    T = refs.shape[1]
    s_target = _brute_force_projection(refs, est, flen, [j])
    p_all = _brute_force_projection(refs, est, flen, list(range(refs.shape[0])))
    e_interf = p_all - s_target
    e_artif = np.concatenate([est, np.zeros(flen - 1)]) - p_all
    sdr = 10 * np.log10(np.sum(s_target**2) / np.sum((e_interf + e_artif) ** 2))
    sir = 10 * np.log10(np.sum(s_target**2) / np.sum(e_interf**2))
    sar = 10 * np.log10(np.sum((s_target + e_interf) ** 2) / np.sum(e_artif**2))
    return sdr, sir, sar


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(7)


def test_projection_matches_brute_force(rng):
    refs = rng.randn(2, 300)
    est = 0.7 * refs[0] + 0.3 * refs[1] + 0.05 * rng.randn(300)
    flen = 12
    proj = _Projector(refs, flen)
    for srcs in ([0], [1], [0, 1]):
        fast = proj.project(est, list(srcs))
        slow = _brute_force_projection(refs, est, flen, srcs)
        np.testing.assert_allclose(fast, slow, atol=1e-8)


def test_metrics_match_brute_force(rng):
    refs = rng.randn(2, 400)
    h = rng.randn(5) * np.array([1.0, 0.5, 0.25, 0.12, 0.06])
    est0 = np.convolve(refs[0], h)[:400] + 0.1 * refs[1] + 0.01 * rng.randn(400)
    est1 = refs[1] + 0.2 * refs[0] + 0.02 * rng.randn(400)
    flen = 16
    sdr, sir, sar, perm = bss_eval_sources(refs, np.stack([est0, est1]),
                                           compute_permutation=False, filt_len=flen)
    for i, est in enumerate([est0, est1]):
        exp = _brute_force_bss(refs, est, i, flen)
        np.testing.assert_allclose((sdr[i], sir[i], sar[i]), exp, atol=1e-6)
    assert list(perm) == [0, 1]


def test_filtered_reference_scores_high(rng):
    """A purely FIR-filtered reference (taps < filt_len) is admissible
    distortion: SDR limited only by numerical precision.  The references
    carry trailing zeros so the filtered estimate is exactly representable
    in the delayed span (no truncated convolution tail)."""
    s = rng.randn(2, 4000)
    s[:, -64:] = 0.0
    h = rng.randn(64) * np.exp(-np.arange(64) / 8.0)
    est = np.stack([np.convolve(s[0], h)[:4000], np.convolve(s[1], h)[:4000]])
    sdr, sir, sar, _ = bss_eval_sources(s, est, compute_permutation=False, filt_len=128)
    assert np.all(sdr > 50) and np.all(sir > 50)


def test_scale_invariance(rng):
    refs = rng.randn(2, 500)
    est = np.stack([refs[0] + 0.3 * refs[1] + 0.1 * rng.randn(500),
                    refs[1] + 0.1 * rng.randn(500)])
    a = bss_eval_sources(refs, est, compute_permutation=False, filt_len=8)
    b = bss_eval_sources(refs, 3.7 * est, compute_permutation=False, filt_len=8)
    for x, y in zip(a[:3], b[:3]):
        np.testing.assert_allclose(x, y, atol=1e-9)


def test_permutation_recovery(rng):
    refs = rng.randn(2, 600)
    ests = np.stack([refs[1] + 0.05 * rng.randn(600), refs[0] + 0.05 * rng.randn(600)])
    _, sir, _, perm = bss_eval_sources(refs, ests, compute_permutation=True, filt_len=8)
    assert list(perm) == [1, 0]
    assert np.all(sir > 10)


def test_si_vs_filtered_calibration(rng):
    """CALIBRATION (VERDICT round-1 missing #1): on a filtered-target mixture
    the 512-tap family credits the filtering as target while SI-SDR counts it
    as distortion — the filtered SDR must dominate, and the delta on this
    construction is large (>10 dB).  This quantifies why the two families'
    numbers must not be compared against each other across papers."""
    T = 8000
    s = rng.randn(2, T)
    s[:, -40:] = 0.0
    h = np.zeros(40)
    h[0], h[3], h[11], h[29] = 1.0, -0.9, 0.7, -0.5   # harsh but admissible channel
    est = np.convolve(s[0], h)[:T] + 0.1 * s[1]
    sdr_f, _, _, _ = bss_eval_sources(s, np.stack([est, s[1]]),
                                      compute_permutation=False, filt_len=512)
    sdr_si, _, _ = si_bss(est, s.T, 0)
    assert sdr_f[0] > sdr_si + 10
    assert sdr_si < 5  # the echo is real distortion for the SI family


def test_all_zero_estimates_do_not_crash():
    """Silent estimates make every permutation's SIR NaN; the identity
    permutation must come back (not a crash) with NaN scores."""
    rng = np.random.RandomState(5)
    refs = rng.randn(2, 500)
    sdr, sir, sar, perm = bss_eval_sources(refs, np.zeros_like(refs),
                                           compute_permutation=True, filt_len=8)
    assert list(perm) == [0, 1]
    assert np.all(np.isnan(sdr) | np.isinf(sdr))


def test_single_source():
    rng = np.random.RandomState(3)
    s = rng.randn(1, 1000)
    est = s[0] + 0.1 * rng.randn(1000)
    sdr, sir, sar, perm = bss_eval_sources(s, est[None], compute_permutation=False, filt_len=32)
    assert np.isinf(sir[0])  # no interferers
    np.testing.assert_allclose(sdr[0], sar[0], atol=1e-9)
    assert 15 < sdr[0] < 30
