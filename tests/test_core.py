"""Parity tests for disco_tpu.core against the NumPy oracles."""
import numpy as np
import pytest

import jax.numpy as jnp

from disco_tpu.core import (
    db2lin,
    lin2db,
    cart2pol,
    pol2cart,
    floor_to_multiple,
    round_to_base,
    my_mse,
    next_pow_2,
    WelfordsOnlineAlgorithm,
    stft,
    istft,
    n_stft_frames,
    tf_mask,
    vad_oracle_batch,
)
from tests.reference_impls import stft_np, istft_np, tf_mask_np, vad_oracle_np


# ----------------------------------------------------------------- math utils
@pytest.mark.parametrize("num,div,expected", [(102, 10, 100), (65, 8, 64), (64, 8, 64)])
def test_floor_to_multiple(num, div, expected):
    assert floor_to_multiple(num, div) == expected


@pytest.mark.parametrize("x,base,expected", [(109.56, 5, 110), (108.56, 4, 108), (56, 10, 60)])
def test_round_to_base(x, base, expected):
    assert float(round_to_base(x, base)) == expected


@pytest.mark.parametrize("db,lin,exp", [(10.0, 10.0, 1), (20.0, 10.0, 2), (0.0, 1.0, 1)])
def test_db2lin_lin2db(db, lin, exp):
    assert np.isclose(float(db2lin(db, exp)), lin)
    if exp == 1:
        assert np.isclose(float(lin2db(lin)), db)


def test_polar_roundtrip(rng):
    x, y = rng.normal(size=50), rng.normal(size=50)
    r, th = cart2pol(x, y)
    x2, y2 = pol2cart(r, th)
    np.testing.assert_allclose(np.asarray(x2), x, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y2), y, atol=1e-5)


def test_my_mse(rng):
    a, b = rng.normal(size=(4, 7)), rng.normal(size=(4, 7))
    assert np.isclose(float(my_mse(a, b)), np.mean((a - b) ** 2), atol=1e-6)


@pytest.mark.parametrize("x,expected", [(3, 4), (4, 4), (5, 8), (250.3, 256)])
def test_next_pow_2(x, expected):
    assert next_pow_2(x) == expected


@pytest.mark.parametrize("chunk", [100, 400])
def test_welford_streaming_stats(rng, chunk):
    dim = 6
    data = rng.normal(loc=2.0, scale=3.0, size=(dim, 1200)).astype(np.float32)
    w = WelfordsOnlineAlgorithm(dim)
    for start in range(0, data.shape[1], chunk):
        w.quick_update(data[:, start : start + chunk])
    np.testing.assert_allclose(np.asarray(w.mean), data.mean(axis=1), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(w.std), data.std(axis=1), rtol=1e-3)
    assert w.count == data.shape[1]


def test_welford_dim_mismatch():
    w = WelfordsOnlineAlgorithm(4)
    with pytest.raises(AssertionError, match="4 features"):
        w.quick_update(np.zeros((3, 10)))


# ----------------------------------------------------------------------- STFT
@pytest.mark.parametrize("length", [16000, 16001, 80000])
def test_stft_matches_librosa_convention(rng, length):
    x = rng.normal(size=length).astype(np.float32)
    got = np.asarray(stft(x))
    want = stft_np(x)
    assert got.shape == want.shape
    assert got.shape[-1] == n_stft_frames(length)
    np.testing.assert_allclose(got, want.astype(np.complex64), atol=2e-3)


def test_stft_batched(rng):
    x = rng.normal(size=(2, 3, 8000)).astype(np.float32)
    got = np.asarray(stft(x))
    for i in range(2):
        for j in range(3):
            np.testing.assert_allclose(
                got[i, j], stft_np(x[i, j]).astype(np.complex64), atol=2e-3
            )


@pytest.mark.parametrize("length", [16000, 16123])
def test_istft_roundtrip(rng, length):
    x = rng.normal(size=length).astype(np.float32)
    y = np.asarray(istft(stft(x), length=length))
    # centered STFT round-trip is exact away from the very edges
    np.testing.assert_allclose(y[256:-256], x[256:-256], atol=1e-3)


def test_istft_matches_oracle(rng):
    x = rng.normal(size=16000).astype(np.float32)
    spec = stft_np(x)
    got = np.asarray(istft(jnp.asarray(spec.astype(np.complex64)), length=16000))
    want = istft_np(spec, 16000)
    np.testing.assert_allclose(got, want, atol=1e-3)


# ---------------------------------------------------------------------- masks
@pytest.mark.parametrize("mask_type", ["irm1", "irm2", "ibm1", "iam1", "iam2"])
def test_tf_mask_parity(rng, mask_type):
    s = (rng.normal(size=(257, 60)) + 1j * rng.normal(size=(257, 60))).astype(np.complex64)
    n = (rng.normal(size=(257, 60)) + 1j * rng.normal(size=(257, 60))).astype(np.complex64)
    got = np.asarray(tf_mask(s, n, mask_type=mask_type))
    want = tf_mask_np(s.astype(np.complex128), n.astype(np.complex128), mask_type)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_vad_oracle_parity(rng):
    # speech-like: silence, burst, silence
    x = np.concatenate(
        [0.001 * rng.normal(size=4000), rng.normal(size=8000), 0.001 * rng.normal(size=4000)]
    ).astype(np.float32)
    got = np.asarray(vad_oracle_batch(x))
    want = vad_oracle_np(x)
    # Allow disagreement on a tiny fraction of samples from f32 threshold ties
    assert np.mean(got != want) < 0.01
    assert got[6000] == 1.0 and got[100] == 0.0
