"""disco-race (disco_tpu.analysis.race): call-graph resolution incl. the
declared dynamic-dispatch fallbacks, per-check true-positive + near-miss
fixtures, the shared suppression machinery under the ``disco-race``
marker, manifest determinism (the committed golden must rebuild
bit-identically), the CLI exit codes + JSON schema (disco-lint key
shape), the repo-wide self-run gate, and the three revert fixtures the
ISSUE pins (handler-in-lock, jax-from-tap-thread, unregistered spawn).

Miniature programs are analyzed fully in memory (``analyze(files=...)``)
with their own role/lock registries, so every check is pinned against at
least one violation it must catch and one nearby shape it must NOT flag.
The revert fixtures re-analyze the REAL repo with one file's source
mutated back to a buggy shape (``overrides=``) — proving the gate is
load-bearing against exactly the regressions it was built for.
"""
from __future__ import annotations

import json
import signal as signal_mod
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from disco_tpu.analysis.race import analyze, manifest as manifest_mod
from disco_tpu.analysis.race import runner as race_runner
from disco_tpu.analysis.race.checks import CHECKS, HYGIENE_RULE
from disco_tpu.analysis.race.roles import ROLES, Role

ROOT = Path(__file__).resolve().parents[1]


def role(name, *entry_points, jax_ok=False, flag_only=False):
    return Role(name=name, entry_points=tuple(entry_points),
                jax_ok=jax_ok, flag_only=flag_only, summary="test role")


def mini(files, roles=(), locks=None, dynamic=None, attrs=None,
         suppress=True):
    """Analyze an in-memory miniature program with its own registries."""
    return analyze(
        files=[(rel, textwrap.dedent(src)) for rel, src in files.items()],
        roles={r.name: r for r in roles},
        locks=dict(locks or {}),
        dynamic_calls=dict(dynamic or {}),
        attr_types=dict(attrs or {}),
        use_suppressions=suppress,
        golden=False,
    )


def check_ids(res):
    return [f.rule for f in res.findings]


# -- catalog -----------------------------------------------------------------
def test_check_catalog_shape():
    assert sorted(CHECKS) == [f"DR{i:03d}" for i in range(1, 9)]
    for cid, (name, summary) in CHECKS.items():
        assert name and summary
    assert HYGIENE_RULE == ("DR000", "race-suppression")


# -- DR001 unregistered-thread ------------------------------------------------
def test_dr001_flags_unregistered_spawns_and_passes_registered():
    files = {"pkg/a.py": """
        import threading
        def run(): pass
        def rogue(): pass
        def main_():
            threading.Thread(target=run).start()
            threading.Thread(target=rogue).start()
    """}
    res = mini(files, roles=[role("worker", "pkg.a:run")])
    assert check_ids(res) == ["DR001"]
    assert "rogue" in res.findings[0].message


def test_dr001_timer_signal_and_executor_forms():
    files = {"pkg/a.py": """
        import signal
        import threading
        from concurrent.futures import ThreadPoolExecutor
        def fire(): pass
        def handler(signum, frame): pass
        def main_(cb):
            threading.Timer(2.0, fire).start()
            signal.signal(signal.SIGTERM, handler)
            with ThreadPoolExecutor() as ex:
                ex.submit(fire)
            signal.signal(signal.SIGTERM, cb)   # unresolvable target
    """}
    res = mini(files, roles=[role("watchdog", "pkg.a:fire"),
                             role("sig", "pkg.a:handler", flag_only=True)])
    assert check_ids(res) == ["DR001"]
    assert "'cb' does not resolve" in res.findings[0].message


def test_dr001_stale_registry_entry_is_a_finding():
    files = {"pkg/a.py": "def run(): pass\n"}
    res = mini(files, roles=[role("worker", "pkg.a:gone")])
    assert check_ids(res) == ["DR001"]
    assert "not found in the program model" in res.findings[0].message


# -- DR002 jax-outside-dispatch ----------------------------------------------
_JAXY = {"pkg/a.py": """
    import jax.numpy as jnp
    import numpy as np
    def worker():
        helper()
        return np.zeros(3)        # numpy is fine anywhere
    def helper():
        return jnp.zeros(3)
"""}


def test_dr002_flags_jax_reachable_from_hostonly_role():
    res = mini(_JAXY, roles=[role("loader", "pkg.a:worker")])
    assert check_ids(res) == ["DR002"]
    assert "jnp.zeros" in res.findings[0].message
    assert "pkg.a:worker -> pkg.a:helper" in res.findings[0].message


def test_dr002_jax_ok_role_and_unreached_code_pass():
    res = mini(_JAXY, roles=[role("driver", "pkg.a:worker", jax_ok=True)])
    assert check_ids(res) == []
    # helper unreached by any role: unconstrained
    res = mini(_JAXY, roles=[])
    assert check_ids(res) == []


def test_dr002_sees_defs_nested_in_with_for_while_blocks():
    """Functions declared inside with/for/while bodies (the check-harness
    closure idiom) must enter the model — code reached through them must
    not silently escape the reachability checks."""
    files = {"pkg/a.py": """
        import jax.numpy as jnp
        def worker():
            for _ in range(1):
                def helper():
                    return jnp.zeros(3)
                helper()
    """}
    res = mini(files, roles=[role("loader", "pkg.a:worker")])
    assert check_ids(res) == ["DR002"]


def test_dr002_through_declared_dynamic_dispatch_fallback():
    files = {"pkg/a.py": """
        import jax
        class P:
            def __init__(self, cb):
                self._cb = cb
            def loop(self):
                self._cb()
        def jaxy():
            return jax.device_get(1)
    """}
    # without the declared fallback the indirect call is invisible...
    res = mini(files, roles=[role("loader", "pkg.a:P.loop")])
    assert check_ids(res) == []
    # ...the DYNAMIC_CALLS declaration closes the edge
    res = mini(files, roles=[role("loader", "pkg.a:P.loop")],
               dynamic={"pkg.a:P.loop::self._cb": ("pkg.a:jaxy",)})
    assert check_ids(res) == ["DR002"]


# -- DR003 signal-handler-unsafe ---------------------------------------------
def test_dr003_flags_lock_and_blocking_in_handler_reach():
    files = {"pkg/a.py": """
        import threading
        import time
        _lock = threading.Lock()
        def handler(signum, frame):
            deeper()
        def deeper():
            with _lock:
                pass
            time.sleep(0.1)
    """}
    res = mini(files, roles=[role("sig", "pkg.a:handler", flag_only=True)],
               locks={"pkg.a::_lock": "test"})
    assert check_ids(res) == ["DR003", "DR003"]
    assert "lock acquisition" in res.findings[0].message
    assert "time.sleep" in res.findings[1].message


def test_dr003_flag_set_only_handler_is_clean():
    files = {"pkg/a.py": """
        class G:
            def handler(self, signum, frame):
                self.stopped = True
                self.reason = "sig"
    """}
    res = mini(files, roles=[role("sig", "pkg.a:G.handler", flag_only=True)])
    assert check_ids(res) == []


# -- DR004 blocking-under-lock ------------------------------------------------
def test_dr004_direct_and_transitive_blocking_under_lock():
    files = {"pkg/a.py": """
        import queue
        import threading
        import time
        _lock = threading.Lock()
        q = queue.Queue()
        def direct():
            with _lock:
                q.get()
        def indirect():
            with _lock:
                helper()
        def helper():
            time.sleep(1.0)
    """}
    res = mini(files, locks={"pkg.a::_lock": "test"})
    assert check_ids(res) == ["DR004", "DR004"]
    assert ".get() without timeout" in res.findings[0].message
    assert "may block" in res.findings[1].message


def test_dr004_bounded_calls_and_unlocked_blocking_pass():
    files = {"pkg/a.py": """
        import queue
        import threading
        _lock = threading.Lock()
        q = queue.Queue()
        def bounded(t):
            with _lock:
                q.get(timeout=0.05)
                q.put_nowait(1)
                t.join(5.0)
        def unlocked():
            q.get()
        def not_a_queue(d):
            with _lock:
                return d.get("key")   # dict.get takes args: not blocking
    """}
    res = mini(files, locks={"pkg.a::_lock": "test"})
    assert check_ids(res) == []


# -- DR005 unregistered-lock --------------------------------------------------
def test_dr005_unregistered_and_anonymous_and_dead_entries():
    files = {"pkg/a.py": """
        import threading
        _lock = threading.Lock()
        _rogue = threading.Lock()
        def f(x):
            with x.some_lock:
                pass
    """}
    res = mini(files, locks={"pkg.a::_lock": "test",
                             "pkg.a::_gone": "no creation site"})
    msgs = [f.message for f in res.findings]
    assert check_ids(res) == ["DR005", "DR005", "DR005"]
    assert any("pkg.a::_rogue" in m for m in msgs)            # unregistered
    assert any("some_lock" in m for m in msgs)                # unresolvable
    assert any("pkg.a::_gone" in m for m in msgs)             # dead entry


def test_dr005_registered_instance_lock_is_clean():
    files = {"pkg/a.py": """
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
    """}
    res = mini(files, locks={"pkg.a:C::_lock": "test"})
    assert check_ids(res) == []


# -- DR006 lock-order-cycle ---------------------------------------------------
def test_dr006_cycle_and_self_reacquire():
    files = {"pkg/a.py": """
        import threading
        _A = threading.Lock()
        _B = threading.Lock()
        def f():
            with _A:
                with _B:
                    pass
        def g():
            with _B:
                helper()
        def helper():
            with _A:
                pass
    """}
    res = mini(files, locks={"pkg.a::_A": "a", "pkg.a::_B": "b"})
    assert check_ids(res) == ["DR006"]
    assert "cycle" in res.findings[0].message
    # self re-acquisition through a call is an instant deadlock
    files = {"pkg/a.py": """
        import threading
        _A = threading.Lock()
        def f():
            with _A:
                helper()
        def helper():
            with _A:
                pass
    """}
    res = mini(files, locks={"pkg.a::_A": "a"})
    assert check_ids(res) == ["DR006"]
    assert "re-acquisition" in res.findings[0].message


def test_dr006_consistent_order_is_clean():
    files = {"pkg/a.py": """
        import threading
        _A = threading.Lock()
        _B = threading.Lock()
        def f():
            with _A:
                with _B:
                    pass
        def g():
            with _A:
                with _B:
                    pass
    """}
    res = mini(files, locks={"pkg.a::_A": "a", "pkg.a::_B": "b"})
    assert check_ids(res) == []


# -- DR007 unlocked-shared-write ----------------------------------------------
_SHARED = """
    import threading
    class C:
        def __init__(self):
            self.x = 0                      # construction: excluded
            self._lock = threading.Lock()
        def a(self):
            {a_body}
        def b(self):
            {b_body}
"""


def _shared_files(a_body, b_body):
    return {"pkg/a.py": _SHARED.format(a_body=a_body, b_body=b_body)}


def test_dr007_two_roles_without_common_lock():
    res = mini(_shared_files("self.x = 1", "self.x = 2"),
               roles=[role("r1", "pkg.a:C.a"), role("r2", "pkg.a:C.b")],
               locks={"pkg.a:C::_lock": "test"})
    assert check_ids(res) == ["DR007"]
    assert "'pkg.a:C.x'" in res.findings[0].message
    assert "r1" in res.findings[0].message and "r2" in res.findings[0].message


def test_dr007_common_lock_single_role_and_init_pass():
    guarded = """
            with self._lock:
                self.x = 1"""
    res = mini(_shared_files(guarded, guarded.replace("= 1", "= 2")),
               roles=[role("r1", "pkg.a:C.a"), role("r2", "pkg.a:C.b")],
               locks={"pkg.a:C::_lock": "test"})
    assert check_ids(res) == []
    # one role writing from two methods: no cross-role hazard
    res = mini(_shared_files("self.x = 1", "self.x = 2"),
               roles=[role("r1", "pkg.a:C.a", "pkg.a:C.b")],
               locks={"pkg.a:C::_lock": "test"})
    assert check_ids(res) == []


# -- suppressions (shared machinery, disco-race marker) -----------------------
def test_race_suppression_semantics():
    src = """
        import threading
        _rogue = threading.Lock()  # disco-race: disable=DR005 -- test fixture lock
    """
    res = mini({"pkg/a.py": src}, locks={})
    assert check_ids(res) == []
    assert len(res.suppressed) == 1
    finding, just = res.suppressed[0]
    assert finding.rule == "DR005" and just == "test fixture lock"
    # the disco-LINT marker must not waive a disco-RACE finding
    src = """
        import threading
        _rogue = threading.Lock()  # disco-lint: disable=DL001 -- wrong tool
    """
    res = mini({"pkg/a.py": src}, locks={})
    assert check_ids(res) == ["DR005"]


def test_race_suppression_hygiene_dr000():
    # missing justification and unused waivers are DR000 findings
    src = """
        import threading
        _rogue = threading.Lock()  # disco-race: disable=DR005
        x = 1  # disco-race: disable=DR004 -- waives nothing
    """
    res = mini({"pkg/a.py": src}, locks={})
    rules = check_ids(res)
    assert rules.count("DR000") == 2      # no justification + unused
    assert "DR005" in rules               # malformed comment waives nothing


# -- manifest -----------------------------------------------------------------
def test_manifest_diff_reports_topology_drift():
    files = {"pkg/a.py": """
        import threading
        _lock = threading.Lock()
        def run():
            with _lock:
                pass
    """}
    res = mini(files, roles=[role("worker", "pkg.a:run")],
               locks={"pkg.a::_lock": "test"})
    m = res.manifest
    assert m["roles"]["worker"]["locks_held"] == ["pkg.a::_lock"]
    drifted = json.loads(json.dumps(m))
    drifted["roles"]["worker"]["locks_held"] = []
    msgs = manifest_mod.diff(drifted, m)
    assert msgs and "locks_held" in msgs[0]
    assert manifest_mod.diff(m, json.loads(json.dumps(m))) == []


def test_committed_manifest_rebuilds_bit_identically_twice():
    """Acceptance criterion: the committed golden is a pure function of
    the source — two fresh rebuilds and the committed file all agree byte
    for byte."""
    committed = (ROOT / manifest_mod.GOLDEN_REL).read_text()
    one = manifest_mod.dumps(analyze(golden=False).manifest)
    two = manifest_mod.dumps(analyze(golden=False).manifest)
    assert one == two
    assert one == committed, (
        "concurrency manifest drift vs the committed golden — review the "
        "topology change and run `disco-race --update`"
    )


# -- the repo itself ----------------------------------------------------------
def test_repo_analyzes_clean():
    res = analyze()
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    assert res.n_files > 100
    for f, just in res.suppressed:
        assert just.strip(), f"unjustified suppression for {f.render()}"


def test_shipped_race_suppressions_are_load_bearing():
    """--no-suppressions must re-surface the real findings behind every
    shipped waiver: deleting a waiver (or reverting the PR-13 fixes) fails
    the gate."""
    res = analyze(use_suppressions=False)
    got = {(f.rule, f.path) for f in res.findings}
    expected = {
        ("DR001", "disco_tpu/runs/interrupt.py"),     # signal-restore site
        ("DR007", "disco_tpu/runs/interrupt.py"),     # handler flag stores
        ("DR007", "disco_tpu/serve/server.py"),       # crash stash handoff
        ("DR007", "disco_tpu/utils/resilience.py"),   # watchdog expired flag
    }
    missing = expected - got
    assert not missing, f"suppressed sites vanished: {missing}"


# -- revert fixtures (the gate is load-bearing) -------------------------------
def _override(rel, old, new):
    src = (ROOT / rel).read_text()
    assert old in src, f"revert fixture anchor gone from {rel}: {old!r}"
    return {rel: src.replace(old, new)}


def test_revert_handler_in_lock_shape_fails_dr003():
    """Re-introducing the PR 3 bug class — the signal handler routing
    through _trip, whose telemetry flush takes obs's non-reentrant locks —
    must fail."""
    rel = "disco_tpu/runs/interrupt.py"
    src = (ROOT / rel).read_text()
    anchor = ("        self.stopped = True\n"
              "        self.reason = self.reason or name\n")
    assert anchor in src
    res = analyze(overrides={rel: src.replace(anchor,
                                              "        self._trip(name)\n")})
    assert any(f.rule == "DR003" for f in res.findings), \
        "\n".join(f.render() for f in res.findings)


def test_revert_jax_in_tap_thread_fails_dr002():
    """A jax call reachable from the tap-writer thread must fail (the
    loader/tap host-only contract)."""
    res = analyze(overrides=_override(
        "disco_tpu/flywheel/tap.py",
        "self._buf.append(item)",
        "import jax\n                self._buf.append(jax.device_get(item))",
    ))
    hits = [f for f in res.findings if f.rule == "DR002"]
    assert hits and any("tap_writer" in f.message for f in hits)


def test_revert_unregistered_spawn_fails_dr001():
    rel = "disco_tpu/flywheel/tap.py"
    src = (ROOT / rel).read_text() + textwrap.dedent("""
        def _rogue_worker():
            pass

        def _start_rogue():
            threading.Thread(target=_rogue_worker).start()
    """)
    res = analyze(overrides={rel: src})
    hits = [f for f in res.findings if f.rule == "DR001"]
    assert hits and any("_rogue_worker" in f.message for f in hits)


# -- CLI ----------------------------------------------------------------------
def test_cli_clean_run_json_schema(capsys):
    from disco_tpu.analysis.race import cli

    assert cli.main(["--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc) == {"clean", "counts", "findings", "suppressed"}
    assert doc["clean"] is True
    assert doc["counts"]["files"] > 100
    assert {"findings", "suppressed", "files", "by_rule"} <= set(doc["counts"])
    for s in doc["suppressed"]:
        assert {"path", "line", "col", "rule", "name", "message",
                "justification"} <= set(s)


def test_cli_list_checks_and_failure_exit(capsys, monkeypatch):
    from disco_tpu.analysis.race import cli

    assert cli.main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    assert "DR000" in out and "DR008" in out
    # a dirty result exits 1 (the gate contract)
    from disco_tpu.analysis.findings import Finding

    dirty = race_runner.RaceResult(
        findings=[Finding(path="x.py", line=1, col=0, rule="DR001",
                          name="unregistered-thread", message="boom")],
        suppressed=[], n_files=1, manifest={},
    )
    monkeypatch.setattr(race_runner, "analyze", lambda **kw: dirty)
    assert cli.main([]) == 1
    assert "DR001" in capsys.readouterr().out


def test_race_gate_runs_without_jax_import():
    """The hermetic pin (like disco-lint's): a full disco-race run in a
    fresh interpreter must never import jax — the gate can run while
    another process holds the chip."""
    code = (
        "import sys\n"
        "from disco_tpu.analysis.race import analyze\n"
        "res = analyze()\n"
        "assert 'jax' not in sys.modules, 'race analyzer imported jax'\n"
        "sys.exit(0 if not res.findings else 1)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr


# -- the pinned GracefulInterrupt runtime contract ----------------------------
def test_graceful_interrupt_handler_is_flag_only_at_runtime():
    """The PR 3 regression pin, runtime side (DR003 pins it statically):
    the handler itself must emit NOTHING — no counter tick, no event —
    only set flags; the next stop_requested() poll emits exactly once,
    and a second poll must not double-emit (the flush transition is
    lock-guarded against racing pollers)."""
    from disco_tpu.obs.metrics import REGISTRY
    from disco_tpu.runs import interrupt as ri

    g = ri.GracefulInterrupt(signals=())   # scope without real handlers
    counter = REGISTRY.counter("interrupts")
    with g:
        before = counter.value
        g._handler(signal_mod.SIGTERM, None)
        assert g.stopped
        assert counter.value == before, "handler emitted telemetry"
        assert ri.stop_requested()         # the poll flushes...
        assert counter.value == before + 1
        assert ri.stop_requested()         # ...exactly once
        assert counter.value == before + 1
