"""The native threaded WAV batch reader (disco_tpu/native/fastwav.cpp):
sample-exact parity with the pure-Python decoder across PCM subtypes, the
corpus batch contract (equal length / rate / mono), and graceful fallback."""
import numpy as np
import pytest

from disco_tpu.io import fastwav
from disco_tpu.io.audio import SUBTYPES, read_wav, write_wav

FS = 16000


@pytest.fixture
def wav_dir(tmp_path, rng):
    paths = []
    x = (0.8 * np.sin(2 * np.pi * 440 * np.arange(2048) / FS)).astype(np.float64)
    for i, subtype in enumerate(("PCM_16", "PCM_24", "PCM_32", "FLOAT", "DOUBLE")):
        p = tmp_path / f"sig_{i}_{subtype}.wav"
        write_wav(p, x * (0.5 + 0.1 * i), FS, subtype=subtype)
        paths.append(p)
    return paths


def test_native_library_builds():
    assert fastwav.available(), "g++ is in the image; the native wav reader must build"


def test_batch_matches_python_decoder(wav_dir):
    batch, fs = fastwav.read_wavs_batch(wav_dir)
    assert fs == FS and batch.shape == (len(wav_dir), 2048) and batch.dtype == np.float32
    for i, p in enumerate(wav_dir):
        want, _ = read_wav(p)
        np.testing.assert_array_equal(batch[i], np.asarray(want, np.float32), err_msg=str(p))


def test_python_fallback_identical(wav_dir, monkeypatch):
    native, fs_n = fastwav.read_wavs_batch(wav_dir)
    monkeypatch.setattr(fastwav, "get_lib", lambda: None)
    fallback, fs_f = fastwav.read_wavs_batch(wav_dir)
    assert fs_n == fs_f
    np.testing.assert_array_equal(native, fallback)


def test_missing_file_raises(wav_dir, tmp_path):
    with pytest.raises(RuntimeError, match="failed reading"):
        fastwav.read_wavs_batch(wav_dir + [tmp_path / "nope.wav"])


def test_ragged_batch_raises(wav_dir, tmp_path):
    short = tmp_path / "short.wav"
    write_wav(short, np.zeros(999), FS, subtype="PCM_16")
    with pytest.raises(RuntimeError, match="ragged"):
        fastwav.read_wavs_batch(wav_dir + [short])


def test_stereo_rejected(wav_dir, tmp_path):
    stereo = tmp_path / "stereo.wav"
    write_wav(stereo, np.zeros((2048, 2)), FS, subtype="PCM_16")
    with pytest.raises(RuntimeError):
        fastwav.read_wavs_batch([stereo] + wav_dir)


def test_corrupt_chunk_size_is_an_error_not_a_crash(tmp_path):
    """A data-chunk size field corrupted to ~4GB must surface as the
    RuntimeError contract, not a std::bad_alloc escaping a worker thread
    (which would abort the whole process)."""
    import struct

    good = tmp_path / "good.wav"
    write_wav(good, np.zeros(1024), FS, subtype="PCM_16")
    raw = bytearray(good.read_bytes())
    idx = raw.find(b"data")
    raw[idx + 4 : idx + 8] = struct.pack("<I", 0xFFFFFFF0)
    bad = tmp_path / "bad.wav"
    bad.write_bytes(bytes(raw))
    with pytest.raises(RuntimeError, match="bad.wav"):
        fastwav.read_wavs_batch([good, bad])


def test_fuzzed_garbage_never_crashes(tmp_path, rng):
    """Random bytes — truncated headers, bogus chunk ids, mid-chunk EOFs —
    must surface as the RuntimeError contract, never a native crash."""
    good = tmp_path / "anchor.wav"
    write_wav(good, np.zeros(256), FS, subtype="PCM_16")
    template = bytearray(good.read_bytes())
    for i in range(40):
        raw = bytearray(template)
        kind = i % 4
        if kind == 0:  # pure noise
            raw = bytearray(rng.integers(0, 256, rng.integers(1, 200), dtype=np.uint8).tobytes())
        elif kind == 1:  # truncate anywhere
            raw = raw[: int(rng.integers(1, len(raw)))]
        elif kind == 2:  # flip random bytes in the header region
            for _ in range(4):
                raw[int(rng.integers(0, min(64, len(raw))))] = int(rng.integers(0, 256))
        else:  # random chunk-size fields
            raw[4:8] = rng.integers(0, 256, 4, dtype=np.uint8).tobytes()
        bad = tmp_path / f"fuzz_{i}.wav"
        bad.write_bytes(bytes(raw))
        try:
            batch, _ = fastwav.read_wavs_batch([good, bad])
            # a mutation may leave a decodable file — fine, but finite
            assert np.isfinite(batch).all()
        except RuntimeError:
            pass  # the documented failure contract


def test_empty_batch_raises():
    with pytest.raises(ValueError, match="empty"):
        fastwav.read_wavs_batch([])


def test_corpus_ingest_uses_batch_reader(tmp_path, rng):
    """load_node_signals decodes through the batch reader and returns the
    same (K, C, L) stacks as per-file reads."""
    from disco_tpu.enhance.zexport import load_node_signals
    from disco_tpu.io.layout import DatasetLayout

    K, C, L = 2, 2, 1024
    layout = DatasetLayout(tmp_path, "living", "train")
    want = {}
    for source, tag in (("mixture", "fs"), ("target", None), ("noise", "fs")):
        for ch in range(1, K * C + 1):
            x = rng.standard_normal(L) * 0.1
            p = layout.wav_processed((0, 6), source, 7, ch, noise=tag)
            p.parent.mkdir(parents=True, exist_ok=True)
            write_wav(p, x, FS, subtype="PCM_16")
            want[(source, ch)] = np.asarray(read_wav(p)[0], np.float32)
    y, s, n = load_node_signals(layout, 7, "fs", (0, 6), n_nodes=K, mics_per_node=C)
    for arr, source in ((y, "mixture"), (s, "target"), (n, "noise")):
        assert arr.shape == (K, C, L)
        for node in range(K):
            for c in range(C):
                np.testing.assert_array_equal(arr[node, c], want[(source, 1 + node * C + c)])
