"""Cross-framework parity: the flax CRNN against a torch twin.

The reference's L4 stack is torch (dnn/models/crnn.py, nn_structures.py);
ours is flax.  This test builds the same architecture in torch (conv →
BatchNorm(eval) → maxpool → GRU → Dense+sigmoid), copies the FLAX weights
into it, and asserts the two frameworks produce the same mask to f32
precision — pinning our conv padding, pooling, batch-norm and GRU gate
conventions to torch's (the reference's) semantics, not just to shape
checks.

torch (CPU wheel) is in the image; the test skips if it ever is not.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from disco_tpu.nn.crnn import CRNN

# small but structurally faithful config: 2 conv layers with freq-only
# pooling, freq padding (0,1), GRU, sigmoid FF — the reference shape
N_CH, WIN, F = 1, 21, 33
CNN = (4, 8)
RNN_UNITS = 16


def _build_flax():
    import jax

    model = CRNN(
        input_shape=(N_CH, WIN, F),
        cnn_filters=CNN,
        conv_kernels=3,
        conv_strides=1,
        pool_kernels=((1, 4), (1, 4)),
        conv_padding=((0, 1), (0, 1)),
        rnn_units=(RNN_UNITS,),
        rnn_cell="gru",
        ff_units=(F,),
        ff_activation="sigmoid",
    )
    x0 = np.zeros((1, N_CH, WIN, F), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x0)
    return model, variables


def _copy_gru_weights(cell_params, torch_gru, hidden: int, suffix: str = ""):
    """flax GRUCell params -> torch GRU layer-0 weights: rows ordered
    [r, z, n]; flax has no hidden-side r/z biases (zeroed in torch).
    ``suffix="_reverse"`` targets the reverse direction of a bidirectional
    torch GRU."""
    with torch.no_grad():
        Wi = np.concatenate([np.asarray(cell_params[g]["kernel"]).T for g in ("ir", "iz", "in")], 0)
        Wh = np.concatenate([np.asarray(cell_params[g]["kernel"]).T for g in ("hr", "hz", "hn")], 0)
        bi = np.concatenate([np.asarray(cell_params[g]["bias"]) for g in ("ir", "iz", "in")])
        bh = np.zeros(3 * hidden, np.float32)
        bh[2 * hidden :] = np.asarray(cell_params["hn"]["bias"])
        getattr(torch_gru, f"weight_ih_l0{suffix}").copy_(torch.from_numpy(Wi.copy()))
        getattr(torch_gru, f"weight_hh_l0{suffix}").copy_(torch.from_numpy(Wh.copy()))
        getattr(torch_gru, f"bias_ih_l0{suffix}").copy_(torch.from_numpy(bi))
        getattr(torch_gru, f"bias_hh_l0{suffix}").copy_(torch.from_numpy(bh))


class _TorchTwin(torch.nn.Module):
    """The same architecture in torch, with OUR feature-merge order
    (time kept, (freq, channel) flattened with channel fastest) so weights
    transfer one-to-one."""

    def __init__(self):
        super().__init__()
        chans = (N_CH,) + CNN
        self.convs = torch.nn.ModuleList(
            [torch.nn.Conv2d(chans[i], chans[i + 1], 3, padding=(0, 1)) for i in range(len(CNN))]
        )
        self.bns = torch.nn.ModuleList([torch.nn.BatchNorm2d(c) for c in CNN])
        self.pool = torch.nn.MaxPool2d((1, 4))
        f_out = F
        for _ in CNN:
            f_out = (f_out + 2 - 2)  # conv k3 pad1: freq preserved
            f_out = f_out // 4
        self.gru = torch.nn.GRU(f_out * CNN[-1], RNN_UNITS, batch_first=True)
        self.ff = torch.nn.Linear(RNN_UNITS, F)

    def forward(self, x):  # x: (B, C, T, F)
        for conv, bn in zip(self.convs, self.bns):
            x = self.pool(bn(conv(x)))
        b, c, t, f = x.shape
        x = x.permute(0, 2, 3, 1).reshape(b, t, f * c)  # (B, T, F*C), c fastest
        x, _ = self.gru(x)
        return torch.sigmoid(self.ff(x))


def _copy_flax_to_torch(variables, twin):
    p = variables["params"]
    bs = variables["batch_stats"]
    cnn_p = p["CNN2d_0"]
    cnn_s = bs["CNN2d_0"]
    with torch.no_grad():
        for i in range(len(CNN)):
            k = np.asarray(cnn_p[f"Conv_{i}"]["kernel"])  # (kh, kw, cin, cout)
            twin.convs[i].weight.copy_(torch.from_numpy(np.transpose(k, (3, 2, 0, 1)).copy()))
            twin.convs[i].bias.copy_(torch.from_numpy(np.asarray(cnn_p[f"Conv_{i}"]["bias"])))
            bn_p, bn_s = cnn_p[f"BatchNorm_{i}"], cnn_s[f"BatchNorm_{i}"]
            twin.bns[i].weight.copy_(torch.from_numpy(np.asarray(bn_p["scale"])))
            twin.bns[i].bias.copy_(torch.from_numpy(np.asarray(bn_p["bias"])))
            twin.bns[i].running_mean.copy_(torch.from_numpy(np.asarray(bn_s["mean"])))
            twin.bns[i].running_var.copy_(torch.from_numpy(np.asarray(bn_s["var"])))

        # flax GRUCell: r = σ(x·Wir + bir + h·Whr); z likewise; n = tanh(x·Win
        # + bin + r*(h·Whn + bhn)) — mapping in _copy_gru_weights.
        _copy_gru_weights(p["RNN_0"]["GRUCell_0"], twin.gru, RNN_UNITS)

        ff = p["FF_0"]["Dense_0"]
        twin.ff.weight.copy_(torch.from_numpy(np.asarray(ff["kernel"]).T.copy()))
        twin.ff.bias.copy_(torch.from_numpy(np.asarray(ff["bias"])))


def test_crnn_matches_torch_twin():
    import jax

    model, variables = _build_flax()
    # non-trivial batch stats so the eval-mode normalization actually moves
    rng = np.random.default_rng(3)
    bs = jax.tree_util.tree_map(
        lambda v: np.abs(rng.standard_normal(v.shape)).astype(np.float32) + 0.5,
        variables["batch_stats"],
    )
    variables = {"params": variables["params"], "batch_stats": bs}

    twin = _TorchTwin().eval()
    _copy_flax_to_torch(variables, twin)

    x = rng.standard_normal((2, N_CH, WIN, F)).astype(np.float32)
    ours = np.asarray(model.apply(variables, x, train=False))
    with torch.no_grad():
        theirs = twin(torch.from_numpy(x)).numpy()
    assert ours.shape == theirs.shape
    np.testing.assert_allclose(ours, theirs, atol=2e-5)


def test_rnn_mask_family_matches_torch_twin():
    """The 2-D RNN architecture ('rnn' archi path, no convs): stacked GRUs
    + sigmoid FF against the torch equivalent at identical weights."""
    import jax

    from disco_tpu.nn.crnn import RNNMask

    WIN2, FEAT, H1, H2, OUT = 11, 20, 12, 8, 20
    model = RNNMask(
        input_shape=(WIN2, FEAT), rnn_units=(H1, H2), rnn_cell="gru",
        ff_units=(OUT,), ff_activation="sigmoid",
    )
    rng = np.random.default_rng(5)
    x = rng.standard_normal((3, WIN2, FEAT)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(2), x)
    ours = np.asarray(model.apply(variables, x, train=False))

    g1 = torch.nn.GRU(FEAT, H1, batch_first=True)
    g2 = torch.nn.GRU(H1, H2, batch_first=True)
    ff = torch.nn.Linear(H2, OUT)
    with torch.no_grad():
        _copy_gru_weights(variables["params"]["RNN_0"]["GRUCell_0"], g1, H1)
        _copy_gru_weights(variables["params"]["RNN_0"]["GRUCell_1"], g2, H2)
        ffp = variables["params"]["FF_0"]["Dense_0"]
        ff.weight.copy_(torch.from_numpy(np.asarray(ffp["kernel"]).T.copy()))
        ff.bias.copy_(torch.from_numpy(np.asarray(ffp["bias"])))
        h, _ = g1(torch.from_numpy(x))
        h, _ = g2(h)
        theirs = torch.sigmoid(ff(h)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_bidirectional_rnn_matches_torch():
    """The rnn_bi path: our [forward ‖ backward] concat equals torch's
    bidirectional GRU output layout at identical weights."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.nn.bricks import RNN

    I, H, T = 6, 5, 30
    brick = RNN(features=(H,), cell_type="gru", bidirectional=True)
    rng = np.random.default_rng(8)
    x = rng.standard_normal((2, T, I)).astype(np.float32)
    variables = brick.init(jax.random.PRNGKey(4), jnp.asarray(x))
    ours = np.asarray(brick.apply(variables, jnp.asarray(x)))

    tg = torch.nn.GRU(I, H, batch_first=True, bidirectional=True)
    p = variables["params"]
    _copy_gru_weights(p["GRUCell_0"], tg, H)
    _copy_gru_weights(p["GRUCell_1"], tg, H, suffix="_reverse")
    with torch.no_grad():
        theirs = tg(torch.from_numpy(x))[0].numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_gru_gate_convention_matches_torch():
    """Isolated single-layer GRU parity over a long sequence: the gate
    formulas (reset applied to the projected hidden state, matching torch)
    drift-free across 100 steps."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    I, H, T = 5, 7, 100
    cell = nn.GRUCell(features=H)
    rnn = nn.RNN(cell)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, T, I)).astype(np.float32)
    variables = rnn.init(jax.random.PRNGKey(1), jnp.asarray(x))
    ours = np.asarray(rnn.apply(variables, jnp.asarray(x)))

    tg = torch.nn.GRU(I, H, batch_first=True)
    _copy_gru_weights(variables["params"]["cell"], tg, H)
    with torch.no_grad():
        theirs = tg(torch.from_numpy(x))[0].numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5)
