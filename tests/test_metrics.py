"""Parity and property tests for disco_tpu.core.metrics / core.sigproc / io.

The float64 NumPy formulas of the reference (metrics.py, sigproc_utils.py) are
the oracle; the si_sdr doctest values of reference metrics.py:355-372 are
reproduced verbatim.
"""
import doctest

import numpy as np
import pytest

import disco_tpu.core.metrics as M
from disco_tpu.core.sigproc import (
    band_importance,
    frame_vad,
    increase_to_snr,
    noise_from_signal,
    sliding_window,
    third_octave_band,
    third_octave_filterbank,
)
from disco_tpu.io.layout import DatasetLayout, case_of_rir, snr_dirname


# ------------------------------------------------------------------- si_sdr
def test_si_sdr_reference_doctest_values():
    """The exact doctest values of reference metrics.py:355-372."""
    np.random.seed(0)
    ref = np.random.randn(100)
    assert np.isinf(M.si_sdr(ref, ref))
    assert np.isinf(M.si_sdr(ref, ref * 2))
    assert M.si_sdr(ref, np.flip(ref)) == pytest.approx(-25.127672346460717)
    assert M.si_sdr(ref, ref + np.flip(ref)) == pytest.approx(0.481070445785553)
    assert M.si_sdr(ref, ref + 0.5) == pytest.approx(6.3704606032577304)
    assert M.si_sdr(ref, ref * 2 + 1) == pytest.approx(6.3704606032577304)
    np.testing.assert_allclose(
        M.si_sdr([ref, ref], [ref * 2 + 1, ref * 1 + 0.5]),
        [6.3704606, 6.3704606],
        rtol=1e-6,
    )


def test_si_sdr_jax_matches_numpy(rng):
    ref = rng.standard_normal((3, 4000))
    est = ref + 0.1 * rng.standard_normal((3, 4000))
    got = np.asarray(M.si_sdr_jax(ref.astype(np.float32), est.astype(np.float32)))
    np.testing.assert_allclose(got, M.si_sdr(ref, est), rtol=1e-3)


def test_module_doctests():
    failures, _ = doctest.testmod(M)
    assert failures == 0


# ------------------------------------------------------- broadband snr / sd
def test_snr_known_value(rng):
    s = rng.standard_normal(8000)
    n = 0.1 * rng.standard_normal(8000)
    assert M.snr(s, n) == pytest.approx(20.0, abs=0.5)
    assert M.snr(s, n, db=False) == pytest.approx(100.0, rel=0.15)


def test_snr_ignores_zero_padding(rng):
    s = rng.standard_normal(8000)
    n = 0.1 * rng.standard_normal(8000)
    sp = np.concatenate([s, np.zeros(4000)])
    np_ = np.concatenate([n, np.zeros(4000)])
    assert M.snr(sp, np_) == pytest.approx(M.snr(s, n))


def test_delta_snr_and_sd(rng):
    s = rng.standard_normal(8000)
    n = rng.standard_normal(8000)
    assert M.delta_snr(s, 0.5 * n, s, n) == pytest.approx(20 * np.log10(2), abs=1e-6)
    assert M.sd(0.5 * s, s) == pytest.approx(20 * np.log10(2), abs=1e-6)


# ---------------------------------------------------------------- fw_snr/sd
def test_fw_snr_recovers_broadband_snr_of_white_noise(rng):
    """For white target and white noise, every band has the same SNR, so the
    importance-weighted mean must equal the broadband SNR."""
    s = rng.standard_normal(32000)
    n = 0.1 * rng.standard_normal(32000)
    _, mean, F = M.fw_snr(s, n, fs=16000)
    assert mean == pytest.approx(20.0, abs=1.0)
    assert F[-1] * 2 ** (1 / 6) < 8000


def test_fw_snr_clipping(rng):
    s = rng.standard_normal(16000)
    _, mean_hi, _ = M.fw_snr(s, 1e-6 * rng.standard_normal(16000), fs=16000)
    _, mean_lo, _ = M.fw_snr(s, 1e6 * rng.standard_normal(16000), fs=16000)
    assert mean_hi == pytest.approx(25.0, abs=1e-9)
    assert mean_lo == pytest.approx(-15.0, abs=1e-9)


def test_fw_sd_identity_is_zero(rng):
    s = rng.standard_normal(16000)
    _, mean, _ = M.fw_sd(s, s, fs=16000)
    assert mean == pytest.approx(0.0, abs=1e-9)


def test_band_importance_narrowband():
    I, F = band_importance(8000)
    assert F[0] == 200 and F[-1] * 2 ** (1 / 6) < 4000
    # At fs=16 kHz the 8000 Hz band's upper edge exceeds Nyquist, so the
    # reference's selection keeps 17 of the 18 wideband bands.
    I16, F16 = band_importance(16000)
    assert len(F16) == 17 and I16.shape == (17,)


# ----------------------------------------------------------------- seg_snr
def test_seg_snr_constant_snr(rng):
    s = rng.standard_normal(16000)
    n = 0.1 * rng.standard_normal(16000)
    assert M.seg_snr(s, n, 512, 256) == pytest.approx(20.0, abs=1.0)


def test_seg_snr_vad_gates_silence(rng):
    s = np.concatenate([rng.standard_normal(8000), np.zeros(8000)])
    n = 0.1 * rng.standard_normal(16000)
    vad = np.concatenate([np.ones(8000), np.zeros(8000)])
    gated = M.seg_snr(s, n, 512, 256, vad=vad)
    assert gated == pytest.approx(20.0, abs=1.5)


# ---------------------------------------------------------- reverb_ratios
def test_reverb_ratios_known_split(rng):
    fs = 16000
    rir = np.zeros(4000)
    rir[10] = 1.0  # direct path
    tail = 0.01 * rng.standard_normal(4000 - (10 + 320))
    rir[10 + 320 :] = tail  # reverberant tail after 20 ms
    drr, srr = M.reverb_ratios(rng.standard_normal(8000), rir, reverb_start=20, fs=fs)
    expected_drr = 10 * np.log10(1.0 / np.sum(tail**2))
    assert drr == pytest.approx(expected_drr, abs=1e-9)
    assert srr == pytest.approx(expected_drr, abs=2.0)


# ----------------------------------------------------------------- si_bss
def test_si_bss_clean_estimate_high_sdr(rng):
    t = rng.standard_normal((8000, 2))
    est = t[:, 0] + 0.01 * rng.standard_normal(8000)
    sisdr, sisir, sisar = M.si_bss(est, t, 0)
    assert sisdr > 35
    assert sisir > sisdr  # interference share of a white residual is small
    assert M.si_bss(2.0 * est, t, 0)[0] == pytest.approx(sisdr, abs=1e-6)


def test_si_bss_interference(rng):
    t = rng.standard_normal((8000, 2))
    est = t[:, 0] + 0.1 * t[:, 1]
    sisdr, sisir, sisar = M.si_bss(est, t, 0)
    assert sisir == pytest.approx(20.0, abs=0.5)
    assert sisar > 50  # no artifacts: residual lies in span(targets)


def test_ci_wp(rng):
    x = rng.standard_normal((400, 3))
    np.testing.assert_allclose(
        M.ci_wp(x), 1.96 * np.nanstd(x, axis=0) / np.sqrt(400), rtol=1e-12
    )


# ----------------------------------------------------------------- sigproc
def test_sliding_window_and_frame_vad():
    x = np.arange(10.0)
    w = sliding_window(x, 4, 2)
    assert w.shape == (4, 4)
    np.testing.assert_array_equal(w[0], [0, 1, 2, 3])
    np.testing.assert_array_equal(w[-1], [6, 7, 8, 9])
    vad = np.concatenate([np.ones(6), np.zeros(6)])
    fv = frame_vad(vad, 4, 4)
    np.testing.assert_array_equal(fv, [1, 1, 0])


def test_increase_to_snr(rng):
    x = rng.standard_normal(16000)
    n = 3.7 * rng.standard_normal(16000)
    n_ = increase_to_snr(x, n, 5.0)
    assert M.snr(x, n_) == pytest.approx(5.0, abs=1e-6)


def test_noise_from_signal_preserves_spectrum(rng):
    x = rng.standard_normal(4096)
    out = noise_from_signal(x, rng=rng)
    assert out.shape == x.shape
    # irfft discards the imaginary parts of the DC and Nyquist bins, so the
    # magnitude match holds on the interior bins only.
    X = np.abs(np.fft.rfft(x))
    N = np.abs(np.fft.rfft(out))
    np.testing.assert_allclose(N[1:-1], X[1:-1], rtol=1e-6, atol=1e-9)


def test_third_octave_band_ratios():
    fc, fl, fu = third_octave_band(1000, i_band=0)
    assert fc == 1000 and fl == pytest.approx(1000 * 2 ** (-1 / 6)) and fu == pytest.approx(1000 * 2 ** (1 / 6))
    fc, fl, fu = third_octave_band(1000, n_band=18)
    assert len(fc) == 18


def test_third_octave_filterbank_band_selectivity(rng):
    import scipy.signal

    fs = 16000
    F = np.array([500.0, 2000.0])
    b, a = third_octave_filterbank(F, fs, order=4)
    assert b.shape == (2, 9) and a.shape == (2, 9)
    t = np.arange(fs) / fs
    tone_in = np.sin(2 * np.pi * 500 * t)
    tone_out = np.sin(2 * np.pi * 2000 * t)
    in_band = scipy.signal.lfilter(b[0], a[0], tone_in)
    out_band = scipy.signal.lfilter(b[0], a[0], tone_out)
    assert np.var(in_band[2000:]) > 100 * np.var(out_band[2000:])


# ---------------------------------------------------------------------- io
def test_wav_roundtrip(tmp_path, rng):
    from disco_tpu.io import read_wav, write_wav

    x = (0.5 * rng.standard_normal(1600)).astype(np.float32)
    p = tmp_path / "a.wav"
    write_wav(p, x, 16000)
    y, fs = read_wav(p)
    assert fs == 16000
    np.testing.assert_allclose(y, x, atol=1e-7)


def test_wav_reads_int16_as_float(tmp_path):
    import scipy.io.wavfile

    from disco_tpu.io import read_wav

    p = tmp_path / "i.wav"
    scipy.io.wavfile.write(str(p), 16000, np.array([0, 16384, -32768], np.int16))
    y, fs = read_wav(p)
    np.testing.assert_allclose(y, [0.0, 0.5, -1.0])


def test_layout_paths_match_reference_conventions(tmp_path):
    lay = DatasetLayout(str(tmp_path), "living", "train")
    assert str(lay.wav_original("cnv", "target", 12, 1, 3)).endswith(
        "living/train/wav_original/cnv/target/12_S-1_Ch-3.wav"
    )
    assert str(lay.wav_original("cnv", "noise", 12, 2, 3, noise="ssn")).endswith(
        "living/train/wav_original/cnv/noise/12_S-2_ssn_Ch-3.wav"
    )
    assert str(lay.wav_processed([0, 6], "mixture", 12, 3, noise="ssn")).endswith(
        "living/train/wav_processed/0-6/mixture/12_ssn_Ch-3.wav"
    )
    assert str(lay.stft_processed([0, 6], "mixture", 12, 3, noise="ssn", normed=True)).endswith(
        "living/train/stft_processed/normed/abs/0-6/mixture/12_ssn_Ch-3.npy"
    )
    assert str(lay.mask_processed([0, 6], 12, 3, "ssn")).endswith(
        "living/train/mask_processed/0-6/12_ssn_Ch-3.npy"
    )
    assert str(lay.stft_z("zf", [0, 6], "zs_hat", 12, 2, "ssn")).endswith(
        "living/train/stft_z/zf/raw/0-6/zs_hat/12_ssn_Node-2.npy"
    )
    assert str(lay.snr_log([0, 6], 12, "ssn")).endswith(
        "living/train/log/snrs/dry/0-6/12_ssn.npy"
    )
    assert snr_dirname([0, 6]) == "0-6"


def test_case_of_rir_split():
    assert case_of_rir(1) == "train"
    assert case_of_rir(10000) == "train"
    assert case_of_rir(10001) == "val"
    assert case_of_rir(11000) == "val"
    assert case_of_rir(11001) == "test"
    assert case_of_rir(12000) == "test"
    with pytest.raises(AssertionError):
        case_of_rir(12001)


# ----------------------------------------------------------------- STOI
def test_stoi_identity_is_one():
    from disco_tpu.core.metrics import stoi

    rng = np.random.default_rng(0)
    fs = 16000
    t = np.arange(3 * fs) / fs
    # speech-like: broadband noise with slow envelope modulation
    s = rng.standard_normal(len(t)) * (1 + 0.8 * np.sin(2 * np.pi * 4 * t))
    assert stoi(s, s, fs) == pytest.approx(1.0, abs=1e-6)


def test_stoi_monotonic_in_snr():
    from disco_tpu.core.metrics import stoi

    rng = np.random.default_rng(1)
    fs = 16000
    t = np.arange(3 * fs) / fs
    s = rng.standard_normal(len(t)) * (1 + 0.8 * np.sin(2 * np.pi * 4 * t))
    n = rng.standard_normal(len(s))
    vals = []
    for snr_db in (20, 5, -10):
        y = s + n * np.sqrt(np.var(s) / np.var(n)) * 10 ** (-snr_db / 20)
        vals.append(stoi(s, y, fs))
    assert vals[0] > vals[1] > vals[2]
    assert 0.0 <= vals[2] < vals[0] <= 1.0


def test_stoi_extended_mode():
    from disco_tpu.core.metrics import stoi

    rng = np.random.default_rng(2)
    fs = 10000  # no resampling path
    s = rng.standard_normal(3 * fs)
    y = s + 0.3 * rng.standard_normal(len(s))
    d = stoi(s, y, fs, extended=True)
    assert 0.0 < d <= 1.0


# -------------------------------------------------- STOI golden pinning
# pystoi is not installable in this environment (zero egress), so the native
# STOI cannot be pinned against its outputs directly (VERDICT round-1
# missing #2).  Instead: (a) hard-coded regression fixtures freeze today's
# numerics against future drift, (b) the published algorithm's invariances
# (scale invariance in the degraded signal, both modes) are asserted, and
# (c) the values sit in the plausible band pystoi produces for these SNRs
# (STOI ~0.65-0.70 at 0 dB white noise, ~0.95 at 10 dB — Taal et al. 2011
# fig. 5), which a conventions bug (framing, band edges) would leave.


def _stoi_fixture_signals():
    rng = np.random.RandomState(42)
    fs = 16000
    t = np.arange(3 * fs) / fs
    s = (np.sin(2 * np.pi * 1.5 * t) > -0.2) * rng.randn(len(t))
    noise = np.random.RandomState(7).randn(len(t))
    return s, noise, fs


@pytest.mark.parametrize("snr_db,want,want_ext", [
    (0.0, 0.6755659017, 0.5933293367),
    (5.0, 0.8666618007, 0.8212280097),
    (10.0, 0.9543521884, 0.9344268255),
])
def test_stoi_golden_regression(snr_db, want, want_ext):
    from disco_tpu.core.metrics import stoi

    s, noise, fs = _stoi_fixture_signals()
    noise = noise * np.sqrt(np.var(s) / np.var(noise)) * 10 ** (-snr_db / 20)
    y = s + noise
    assert float(stoi(s, y, fs)) == pytest.approx(want, abs=1e-8)
    assert float(stoi(s, y, fs, extended=True)) == pytest.approx(want_ext, abs=1e-8)
    # plausibility band vs the published STOI-vs-SNR behavior
    assert {0.0: 0.55, 5.0: 0.78, 10.0: 0.9}[snr_db] < want < 1.0


def test_stoi_scale_invariant_in_degraded():
    from disco_tpu.core.metrics import stoi

    s, noise, fs = _stoi_fixture_signals()
    y = s + 0.3 * noise
    for mode in (False, True):
        a = stoi(s, y, fs, extended=mode)
        b = stoi(s, 2.0 * y, fs, extended=mode)
        assert a == pytest.approx(b, abs=1e-9), mode
