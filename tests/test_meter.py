"""disco-meter (disco_tpu.analysis.meter): the analytic cost model, the
explicit-unknowns contract, the committed manifests and their budgets,
the registry sync with the trace catalog, and the roofline join.

Runs under the conftest CPU config (8 virtual devices) — which, like the
trace goldens, is itself under test: the committed cost manifests must be
reproduced bit-identically here, proving the model counts properties of
the traced program, not of the device topology."""
from __future__ import annotations

import copy
import json
from pathlib import Path

import pytest

from disco_tpu.analysis.meter import budgets, check, costmodel, stages
from disco_tpu.analysis.trace.programs import PROGRAMS
from disco_tpu.obs import roofline

ROOT = Path(__file__).resolve().parents[1]


# -- cost model: known-flops sanity ------------------------------------------
def test_dot_general_flops_and_traffic_are_exact():
    import jax
    import jax.numpy as jnp

    M, K, N = 8, 16, 4
    a = jax.ShapeDtypeStruct((M, K), jnp.float32)
    b = jax.ShapeDtypeStruct((K, N), jnp.float32)
    rep = costmodel.cost_of_fn(jnp.dot, (a, b), program="matmul")
    assert rep["flops"] == 2 * M * N * K
    assert rep["flops_by_class"] == {"dot_general": 2 * M * N * K}
    # materialization model: each operand read + the result written once
    assert rep["traffic_bytes"] == 4 * (M * K + K * N + M * N)
    assert rep["hbm_bytes_in"] == 4 * (M * K + K * N)
    assert rep["hbm_bytes_out"] == 4 * M * N
    assert rep["unmodeled"]["traffic_fraction"] == 0.0
    assert rep["version"] == costmodel.VERSION


def test_complex_mul_and_fft_conventions():
    import jax
    import jax.numpy as jnp

    z = jax.ShapeDtypeStruct((32,), jnp.complex64)
    rep = costmodel.cost_of_fn(lambda a: a * a, (z,), program="cmul")
    assert rep["flops"] == 32 * 6           # complex mul = 6 real flops
    n = 64
    x = jax.ShapeDtypeStruct((n,), jnp.float32)
    rep = costmodel.cost_of_fn(jnp.fft.rfft, (x,), program="fft")
    assert rep["flops_by_class"]["fft"] == int(5 * n * 6)   # 5·N·log2(N)


def test_scan_costs_body_times_length_plus_carry_roundtrip():
    import jax
    import jax.numpy as jnp

    L = 10
    c0 = jax.ShapeDtypeStruct((4,), jnp.float32)
    xs = jax.ShapeDtypeStruct((L, 4), jnp.float32)

    def f(c, xs):
        return jax.lax.scan(lambda c, x: (jnp.sin(c) + x, c), c, xs)

    rep = costmodel.cost_of_fn(f, (c0, xs), program="scan")
    one = costmodel.cost_of_fn(
        lambda c, x: jnp.sin(c) + x, (c0, c0), program="body")
    # body flops scale with the trip count
    assert rep["flops"] == L * one["flops"]
    # the carry round-trips HBM every iteration: 2·|carry|·L on top of the
    # boundary, so the scan's traffic dominates L× the body boundary
    assert rep["traffic_bytes"] >= 2 * 16 * L


def test_while_loop_counted_once_and_surfaced():
    import jax
    import jax.numpy as jnp

    def f(x):
        return jax.lax.while_loop(lambda c: c[0] < 10.0,
                                  lambda c: (c[0] + 1.0, jnp.cos(c[1])),
                                  (x, x))

    rep = costmodel.cost_of_fn(
        f, (jax.ShapeDtypeStruct((), jnp.float32),), program="wh")
    assert rep["while_loops"] == 1


# -- fused islands: boundary-only traffic, interior flops kept ---------------
def test_fused_island_zeroes_interior_traffic_but_keeps_flops():
    import jax
    import jax.numpy as jnp

    x = jax.ShapeDtypeStruct((64, 64), jnp.float32)

    def interior(a):
        return jnp.sin(a) @ jnp.cos(a) + jnp.tanh(a)

    def fused_mwf_xla(a):                   # pjit named by __name__
        return interior(a)

    jitted = jax.jit(fused_mwf_xla)

    def with_island(a):
        return jitted(a) * 2.0

    def without_island(a):
        return interior(a) * 2.0

    ri = costmodel.cost_of_fn(with_island, (x,), program="island")
    rf = costmodel.cost_of_fn(without_island, (x,), program="flat")
    assert ri["fused_islands"] == ["fused_mwf_xla"]
    assert rf["fused_islands"] == []
    # the interior's real work counts either way…
    assert ri["flops"] == rf["flops"]
    # …but the island's intermediates never touch HBM: boundary bytes only
    assert ri["traffic_bytes"] < rf["traffic_bytes"]
    # a pjit NOT in the declared fused set is no island
    other = jax.jit(interior)
    rn = costmodel.cost_of_fn(
        lambda a: other(a) * 2.0, (x,), program="nope",
        fused_units=("something_else",))
    assert rn["fused_islands"] == []
    assert rn["traffic_bytes"] == rf["traffic_bytes"]


# -- explicit unknowns: a primitive the model does not know ------------------
def _bind_synthetic_primitive():
    """A jaxpr whose only equation is a primitive the model has no entry
    for (the explicit-unknowns fixture)."""
    import jax
    import jax.numpy as jnp
    from jax import core as jcore

    prim = jcore.Primitive("frobnicate_v99")
    prim.def_abstract_eval(lambda x: x)
    return jax.make_jaxpr(lambda a: prim.bind(a))(
        jax.ShapeDtypeStruct((128,), jnp.float32))


def test_unknown_primitive_lands_in_unmodeled_bucket():
    assert costmodel.classify("frobnicate_v99") == "unmodeled"
    rep = costmodel.cost_of_jaxpr(_bind_synthetic_primitive(),
                                  program="synthetic")
    assert rep["unmodeled"]["primitives"] == {"frobnicate_v99": 1}
    assert rep["unmodeled"]["traffic_bytes"] == 2 * 128 * 4
    # the unknown is ALL this program's traffic: fraction 1.0
    assert rep["unmodeled"]["traffic_fraction"] == 1.0
    assert rep["traffic_by_class"]["unmodeled"] == rep["traffic_bytes"]


def test_unmodeled_fraction_past_ceiling_trips_the_budget():
    rep = costmodel.cost_of_jaxpr(_bind_synthetic_primitive(),
                                  program="synthetic")
    msgs = budgets.check_unmodeled(rep)
    assert len(msgs) == 1
    assert "frobnicate_v99" in msgs[0] and "ceiling" in msgs[0]
    # an override reviewed in budgets.py grants headroom
    assert budgets.unmodeled_ceiling("synthetic") == \
        budgets.UNMODELED_FRACTION_MAX
    rep_ok = dict(rep, unmodeled=dict(rep["unmodeled"], traffic_fraction=0.0))
    assert budgets.check_unmodeled(rep_ok) == []


def test_update_refuses_manifest_breaching_its_own_budget(
        monkeypatch, tmp_path):
    """`disco-meter --update` must not be able to smuggle an unmodeled hot
    loop into the committed goldens."""
    from disco_tpu.analysis.trace.programs import ProgramSpec

    def build():
        import jax
        import jax.numpy as jnp
        from jax import core as jcore

        prim = jcore.Primitive("frobnicate_v99")
        prim.def_abstract_eval(lambda x: x)
        return (lambda a: prim.bind(a),
                (jax.ShapeDtypeStruct((128,), jnp.float32),), {})

    spec = ProgramSpec("synthetic_unknown", "fixture", build)
    monkeypatch.setattr(
        "disco_tpu.analysis.trace.programs.PROGRAMS",
        {"synthetic_unknown": spec})
    monkeypatch.setattr(check, "GOLDEN_DIR", tmp_path / "cost")
    result = check.run_checks(update=True, programs={"synthetic_unknown"})
    assert not result.clean
    checks = {f["check"] for f in result.findings}
    assert "budget" in checks and "golden" in checks
    assert not (tmp_path / "cost" / "synthetic_unknown.json").exists()
    assert result.updated == []


# -- committed manifests: bit-identical rebuild under this device config -----
def test_committed_manifests_rebuild_bit_identically():
    """The full gate — every catalog program re-traced and re-costed here
    (8 virtual CPU devices) must match the committed manifests exactly,
    hold every budget, and pass registry sync in both directions."""
    result = check.run_checks()
    assert result.findings == []
    assert result.n_programs == len(PROGRAMS)
    # and the manifest bytes on disk are the canonical dumps() form
    for name in PROGRAMS:
        path = check.golden_path(name)
        text = path.read_text()
        assert costmodel.dumps(json.loads(text)) == text, name


def test_committed_fused_manifest_beats_eigh_on_hbm_traffic():
    """The design thesis as data: the fused manifests model strictly
    fewer HBM bytes than their separate-stage eigh twins — step 2
    (PR 15) and step 1 (the disco-chain round) alike."""
    goldens = {}
    for step in ("step1", "step2"):
        fused = check.load_golden(f"tango_{step}_fused")
        eigh = check.load_golden(f"tango_{step}_eigh")
        assert fused is not None and eigh is not None, step
        assert fused["traffic_bytes"] < eigh["traffic_bytes"], step
        # fusing keeps the flops (same math) while cutting the traffic, so
        # the arithmetic intensity strictly improves
        assert fused["arithmetic_intensity"] > eigh["arithmetic_intensity"]
        assert "fused_mwf_xla" in fused["fused_islands"], step
        assert eigh["fused_islands"] == [], step
        goldens[f"tango_{step}_fused"] = fused
        goldens[f"tango_{step}_eigh"] = eigh
    assert budgets.check_cross(goldens) == []


def test_cross_budget_reports_missing_program_and_violation():
    fused = check.load_golden("tango_step2_fused")
    msgs = budgets.check_cross({"tango_step2_fused": fused})
    # one message per declared inequality that cannot be evaluated:
    # step-2 is missing its eigh twin, step-1 is missing both programs
    assert len(msgs) == len(budgets.CROSS_BUDGETS)
    assert all("missing" in m for m in msgs)
    full = {
        "tango_step2_fused": dict(fused, traffic_bytes=10**12),
        "tango_step2_eigh": check.load_golden("tango_step2_eigh"),
        "tango_step1_fused": check.load_golden("tango_step1_fused"),
        "tango_step1_eigh": check.load_golden("tango_step1_eigh"),
    }
    msgs = budgets.check_cross(full)
    assert len(msgs) == 1 and "violated" in msgs[0]
    assert "pencils" in msgs[0]     # the thesis text travels with the red
    # the step-1 inequality trips the same way
    full["tango_step2_fused"] = fused
    full["tango_step1_fused"] = dict(
        full["tango_step1_fused"], traffic_bytes=10**12)
    msgs = budgets.check_cross(full)
    assert len(msgs) == 1 and "violated" in msgs[0]
    assert "batch-in-lanes" in msgs[0]


# -- drift: an inflated-traffic manifest fails with a readable diff ----------
def test_inflated_traffic_fails_with_per_class_diff():
    golden = check.load_golden("tango_step2_fused")
    drifted = copy.deepcopy(golden)
    drifted["traffic_bytes"] += 4096
    drifted["traffic_by_class"]["data_movement"] = (
        drifted["traffic_by_class"].get("data_movement", 0) + 4096)
    drifted["fused_islands"] = []
    lines = costmodel.diff_reports(golden, drifted)
    assert any("traffic_bytes" in ln and "+" in ln for ln in lines)
    assert any("traffic_by_class[data_movement]" in ln for ln in lines)
    assert any("lost island re-exposes" in ln for ln in lines)


def test_version_bump_short_circuits_to_regenerate_hint():
    golden = check.load_golden("tango_step2_fused")
    lines = costmodel.diff_reports(dict(golden, version=0), golden)
    assert len(lines) == 1 and "regenerate" in lines[0]


def test_unfusing_the_solver_trips_the_gate():
    """Revert-style fixture: cost the fused program with the island
    declaration gone (exactly what reverting the solve-fusion round would
    do) — the re-exposed interior traffic must show up as a readable
    manifest diff AND break the cross-budget."""
    fn, args, kwargs = PROGRAMS["tango_step2_fused"].build()
    current = costmodel.cost_of_fn(fn, args, kwargs=kwargs, fused_units=(),
                                   program="tango_step2_fused")
    golden = check.load_golden("tango_step2_fused")
    assert current["traffic_bytes"] > golden["traffic_bytes"]
    lines = costmodel.diff_reports(golden, current)
    assert any("traffic_bytes" in ln for ln in lines)
    assert any("fused islands" in ln for ln in lines)
    msgs = budgets.check_cross({
        "tango_step2_fused": current,
        "tango_step2_eigh": check.load_golden("tango_step2_eigh"),
    })
    assert msgs and "violated" in msgs[0]


# -- registry sync -----------------------------------------------------------
def test_registry_sync_flags_missing_and_stale_manifests(
        monkeypatch, tmp_path):
    from disco_tpu.analysis.trace.programs import ProgramSpec

    def build():
        import jax
        import jax.numpy as jnp

        return (lambda a: a * 2.0,
                (jax.ShapeDtypeStruct((8,), jnp.float32),), {})

    specs = {"tiny_a": ProgramSpec("tiny_a", "fixture", build),
             "tiny_b": ProgramSpec("tiny_b", "fixture", build)}
    monkeypatch.setattr(
        "disco_tpu.analysis.trace.programs.PROGRAMS", specs)
    gdir = tmp_path / "cost"
    gdir.mkdir()
    monkeypatch.setattr(check, "GOLDEN_DIR", gdir)
    # commit tiny_a's manifest plus a STALE one; leave tiny_b uncommitted
    (gdir / "tiny_a.json").write_text(
        costmodel.dumps(check.build_report(specs["tiny_a"])))
    (gdir / "deleted_program.json").write_text("{}")
    result = check.run_checks()
    reg = {f["program"]: f["message"] for f in result.findings
           if f["check"] == "registry"}
    assert "tiny_b" in reg and "no cost manifest" in reg["tiny_b"]
    assert "deleted_program" in reg and "stale" in reg["deleted_program"]
    # cross-budget unevaluable on this synthetic catalog: also a finding
    assert any(f["check"] == "cross" for f in result.findings)


def test_unknown_program_raises_and_cli_exits_2(capsys):
    from disco_tpu.analysis.meter import cli

    with pytest.raises(KeyError):
        check.run_checks(programs={"no_such_program"})
    assert cli.main(["--programs", "no_such_program"]) == 2
    assert "no_such_program" in capsys.readouterr().err
    assert cli.main(["--list-programs"]) == 0
    out = capsys.readouterr().out
    for name in PROGRAMS:
        assert name in out


def test_single_program_pass_skips_catalog_wide_checks(capsys):
    from disco_tpu.analysis.meter import cli

    rc = cli.main(["--programs", "tango_step2_fused", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["clean"]
    assert payload["counts"]["programs"] == 1
    assert list(payload["reports"]) == ["tango_step2_fused"]


# -- workload-sized stage costs (the roofline's cost side) -------------------
TINY = stages.Workload(batch=1, dur_s=0.2, n_nodes=2, mics_per_node=2)


def test_offline_stage_costs_cover_bench_stage_keys():
    costs = stages.offline_stage_costs(TINY)
    assert set(costs) == set(stages.STAGE_KEYS)
    for key, c in costs.items():
        assert c["flops"] >= 0 and c["traffic_bytes"] >= 0, key
    assert costs["full_pipeline"]["flops"] > 0
    # step-2 is full minus step-1, like the bench timing
    assert (costs["step2_exchange_mwf"]["flops"]
            < costs["full_pipeline"]["flops"])


def test_streaming_scan_cost_matches_bench_shrink_and_bounds():
    out = stages.streaming_scan_cost(dur_s=2.0, blocks_per_dispatch=4)
    assert out is not None
    assert out["window_frames"] == 4 * out["block_frames"]
    assert out["flops"] > 0
    # a clip too short for even one update block: no lane, not a crash
    assert stages.streaming_scan_cost(dur_s=0.05) is None


def test_serve_block_cost_is_per_block():
    out = stages.serve_block_cost()
    assert out["block_frames"] == 16
    assert out["flops"] > 0 and out["traffic_bytes"] > 0


def test_fused_pipeline_cost_models_less_traffic_than_eigh_pipeline():
    fused = stages.fused_pipeline_cost(TINY)
    plain = stages.offline_stage_costs(TINY, solver="eigh")["full_pipeline"]
    assert fused["flops"] > 0
    assert fused["traffic_bytes"] < plain["traffic_bytes"]


# -- roofline join -----------------------------------------------------------
def test_roofline_renders_from_committed_bench_r05_without_tpu():
    """The exact artifact the issue names: `disco-obs roofline
    BENCH_r05.json` must produce a verdict per measured stage on a host
    with no TPU, assuming the headline workload (r05 predates the
    `workload` field)."""
    from disco_tpu.cli.obs import load_bench_record

    record = load_bench_record(ROOT / "BENCH_r05.json")
    result = roofline.stage_verdicts(record)
    assert result["workload_assumed"] is True
    assert result["cost_model_version"] == costmodel.VERSION
    got = {r["stage"] for r in result["rows"]}
    assert got == set(record["stage_ms"]) & set(stages.STAGE_KEYS)
    for row in result["rows"]:
        assert row["verdict"] in (
            "compute-bound", "bandwidth-bound", "dispatch-bound")
        assert row["gflops_per_s"] >= 0 and row["gb_per_s"] >= 0
    text = roofline.render(result)
    assert "verdict" in text and "assumed" in text
    for row in result["rows"]:
        assert row["stage"] in text


def test_roofline_verdict_boundaries():
    record = {
        "stage_ms": {"full_pipeline": 50.0},
        "workload": {"batch": 1, "dur_s": 0.2, "n_nodes": 2,
                     "mics_per_node": 2},
    }
    res = roofline.stage_verdicts(record)
    assert res["workload_assumed"] is False
    (row,) = res["rows"]
    assert row["verdict"] in ("compute-bound", "bandwidth-bound",
                              "dispatch-bound")
    # blow the measured time up 10000x: neither roof explains it
    slow = dict(record, stage_ms={"full_pipeline": 50.0 * 1e4})
    (srow,) = roofline.stage_verdicts(slow)["rows"]
    assert srow["verdict"] == "dispatch-bound"
    assert srow["fraction_of_peak"] < roofline.DISPATCH_FRAC
    # crank the declared peaks down far enough and the same measurement
    # reads as AT the roof on its binding dimension
    tiny_peaks = roofline.stage_verdicts(
        record, peak_tflops=1e-9, peak_gbps=1e-9)
    (trow,) = tiny_peaks["rows"]
    assert trow["verdict"] == ("compute-bound"
                               if trow["frac_compute"] >= trow["frac_bandwidth"]
                               else "bandwidth-bound")
    assert trow["fraction_of_peak"] > 1.0


def test_workload_of_record_roundtrip():
    w, assumed = roofline.workload_of_record({})
    assert assumed is True and w == stages.HEADLINE
    w, assumed = roofline.workload_of_record(
        {"workload": {"batch": 2, "dur_s": 0.5}})
    assert assumed is False
    assert w.batch == 2 and w.dur_s == 0.5
    assert w.n_nodes == stages.HEADLINE.n_nodes
