"""End-to-end dataset generation test: simulate → save → mix (PostGenerator)
→ consume with TANGO — the reference's three-stage filesystem pipeline
(SURVEY.md §1 inter-layer contract) on a tiny synthetic corpus."""
import numpy as np
import pytest

from disco_tpu.datagen import PostGenerator, generate_disco_rirs
from disco_tpu.io import DatasetLayout, read_wav, write_wav
from disco_tpu.sim import SpeechAndNoiseSetup

FS = 16000


@pytest.fixture
def corpus(tmp_path):
    rng = np.random.default_rng(0)
    speech = []
    for spk in ("7", "8"):
        d = tmp_path / "LibriSpeech" / spk / "1"
        d.mkdir(parents=True)
        f = d / f"{spk}-1-0001.wav"
        t = np.arange(6 * FS) / FS
        env = (np.sin(2 * np.pi * 1.1 * t + float(spk)) > -0.2).astype(np.float64)
        write_wav(f, 0.3 * env * rng.standard_normal(len(t)), FS)
        speech.append(str(f))
    noise_dir = tmp_path / "noises"
    noise_dir.mkdir()
    nf = noise_dir / "n0.wav"
    write_wav(nf, 0.2 * rng.standard_normal(8 * FS), FS)
    return speech, [str(nf)]


@pytest.fixture
def signal_setup(corpus):
    speech, noise = corpus
    return SpeechAndNoiseSetup(
        target_list=speech,
        talkers_list=speech,
        noises_dict={"fs": noise},
        duration_range=(5, 10),
        var_tar=10 ** (-23 / 10),
        snr_dry_range=[[0, 0]],
        snr_cnv_range=(-60, 60),  # wide gate: tiny corpus must not redraw forever
        min_delta_snr=-1,
        rng=np.random.default_rng(3),
    )


def test_generate_then_mix_then_enhance(tmp_path, signal_setup):
    root = str(tmp_path / "dataset")
    layout = DatasetLayout(root, "random", "train")
    # max_order=6 keeps the CPU test fast; the kernel is order-agnostic.
    done = generate_disco_rirs(
        "random", "train", 1, 1, signal_setup, layout,
        rng=np.random.default_rng(5), max_order=6,
    )
    assert done == [1]

    # --- generated layout ---------------------------------------------------
    assert (layout.base / "wav_original" / "dry" / "target" / "1_S-1.wav").exists()
    assert (layout.base / "wav_original" / "dry" / "noise" / "1_S-2_ssn.wav").exists()
    for ch in (1, 16):
        assert (layout.base / "wav_original" / "cnv" / "target" / f"1_S-1_Ch-{ch}.wav").exists()
        assert (layout.base / "wav_original" / "cnv" / "noise" / f"1_S-2_ssn_Ch-{ch}.wav").exists()
        assert (layout.base / "wav_original" / "cnv" / "noise" / f"1_S-2_fs_Ch-{ch}.wav").exists()
    assert layout.infos(1).exists()
    infos = np.load(layout.infos(1), allow_pickle=True).item()
    assert infos["rirs"].shape[0] == 2 and infos["rirs"].shape[1] == 16
    # reference infos contract (convolve_signals.py:438-446): plot_conf-ready
    assert {"length", "width", "height", "alpha"} <= set(infos["room"])
    assert infos["mics"].shape[0] == 3  # (3, n_mics) positions
    assert infos["sources"].ndim == 2
    from disco_tpu.enhance import plot_conf

    fig = plot_conf(infos, return_fig=True)
    assert fig is not None

    # Train clips padded to 11 s (duration_range[-1] + 1).
    x, fs = read_wav(layout.base / "wav_original" / "cnv" / "target" / "1_S-1_Ch-1.wav")
    assert len(x) == 11 * FS

    # Idempotency: re-run generates nothing.
    assert generate_disco_rirs(
        "random", "train", 1, 1, signal_setup, layout, rng=np.random.default_rng(5), max_order=6
    ) == []

    # --- mixing pass (rename noise images to the ssn tag the mixer expects) --
    pg = PostGenerator(1, 1, "random", "ssn", [0, 6], root, rng=np.random.default_rng(7))
    assert pg.post_process() == [1]
    assert pg.post_process() == []  # idempotent

    mix, _ = read_wav(layout.wav_processed([0, 6], "mixture", 1, 1, noise="ssn"))
    tar, _ = read_wav(layout.wav_processed([0, 6], "target", 1, 1))
    noi, _ = read_wav(layout.wav_processed([0, 6], "noise", 1, 1, noise="ssn"))
    np.testing.assert_allclose(mix, tar + noi, atol=1e-6)
    mask = np.load(layout.mask_processed([0, 6], 1, 1, "ssn"))
    assert mask.shape[0] == 257 and 0 <= mask.min() and mask.max() <= 1
    spec = np.load(layout.stft_processed([0, 6], "mixture", 1, 1, noise="ssn"))
    assert spec.shape[0] == 257 and np.iscomplexobj(spec)

    # --- consume with TANGO: the corpus feeds the enhancement pipeline ------
    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance import oracle_masks, tango

    def load_stack(kind, noise):
        chans = []
        for ch in range(1, 17):
            x, _ = read_wav(layout.wav_processed([0, 6], kind, 1, ch, noise=noise))
            chans.append(x)
        return np.array(chans).reshape(4, 4, -1)

    y = load_stack("mixture", "ssn")
    s = load_stack("target", None)
    n = load_stack("noise", "ssn")
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    res = tango(Y, S, N, masks, masks, policy="local")
    assert np.isfinite(np.asarray(res.yf)).all()


def test_snr_at_mics_shapes(rng):
    from disco_tpu.datagen import snr_at_mics

    s = rng.standard_normal((8, 16000))
    n = 0.1 * rng.standard_normal((8, 16000))
    snrs, node_snrs, dmin = snr_at_mics(s, n, [4, 4])
    assert snrs.shape == (8,) and node_snrs.shape == (2,)
    assert np.all(snrs > 10)  # ~20 dB white-on-white
    assert dmin >= 0
