"""Resident-trainer tests: the co-resident training slice that runs on the
serve dispatch thread (disco_tpu/flywheel/resident).  The full serve +
trainer + promotion-controller endurance campaign (multi-generation, with
crashes at every seam) is gated by ``make endure-check``; these tests pin
the trainer's three contracts in isolation: ladder-aware throttling,
ledger-exact crash resume (zero re-consumed shard units, no torn
checkpoint) and the idempotent publish bracket."""
import json

import numpy as np
import pytest

from disco_tpu import obs
from disco_tpu.flywheel import ResidentTrainer, write_shard
from disco_tpu.flywheel.resident import CKPT_NAME, LEDGER_NAME, unit_publish
from disco_tpu.io.atomic import file_digest
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.promote.store import GenerationStore
from disco_tpu.runs import chaos
from disco_tpu.runs.ledger import RunLedger, unit_epoch

K, C, F, T = 4, 2, 9, 8

#: test_promote.py's tiny CRNN, shared so the jit/module caches hit.
ARCH = dict(n_ch=1, win_len=4, n_freq=9, cnn_filters=(2,),
            pool_kernels=((1, 2),), conv_padding=((0, 1),),
            rnn_units=(4,), ff_units=(9,), rnn_dropouts=0.0)


def _block(rng, seq=0, session="s"):
    Y = (rng.standard_normal((K, C, F, T))
         + 1j * rng.standard_normal((K, C, F, T))).astype(np.complex64)
    yf = (rng.standard_normal((K, F, T))
          + 1j * rng.standard_normal((K, F, T))).astype(np.complex64)
    mz = rng.uniform(0.05, 0.95, (K, F, T)).astype(np.float32)
    mw = rng.uniform(0.05, 0.95, (K, F, T)).astype(np.float32)
    return {"session": session, "seq": seq, "Y": Y, "yf": yf,
            "mask_z": mz, "mask_w": mw}


def _fill_shards(tmp_path, rng, n_shards=2, records=3):
    tap = tmp_path / "tap"
    tap.mkdir(exist_ok=True)
    for i in range(n_shards):
        recs = [_block(rng, seq=i * records + j) for j in range(records)]
        write_shard(tap / f"s{i:03d}.shard.msgpack", recs)
    return tap


def _run_until(trainer, pred, max_ticks=300):
    """Tick the trainer until ``pred(trainer)`` holds (the harness stand-in
    for the scheduler's per-tick call)."""
    for tick in range(max_ticks):
        trainer.step(tick_no=tick)
        if pred(trainer):
            return
    raise AssertionError(f"predicate never held in {max_ticks} ticks: "
                         f"{trainer.stats()}")


def _done_counts(led_path, prefix):
    """{unit: #done-records} over the RAW ledger file (not the replay) —
    the zero-re-consumed-units contract counts appends, not latest state."""
    counts = {}
    for line in led_path.read_text().splitlines():
        rec = json.loads(line)
        if rec["unit"].startswith(prefix) and rec["state"] == "done":
            counts[rec["unit"]] = counts.get(rec["unit"], 0) + 1
    return counts


# ------------------------------------------------------------- ladder throttle
def test_ladder_throttle_runs_zero_steps_that_tick(tmp_path, rng):
    """The ladder-aware contract: rung >= throttle_rung ⇒ ZERO train steps
    that tick (counted + evented on the transitions), below ⇒ trains."""
    tap = _fill_shards(tmp_path, rng)
    tr = ResidentTrainer(tap, tmp_path / "train", arch=ARCH, batch_size=4,
                         steps_per_tick=2, throttle_rung=2)
    c0 = obs_registry.counter("train_throttled_ticks").value
    log = tmp_path / "ev.jsonl"
    try:
        with obs.recording(log):
            assert tr.step(tick_no=0, rung=2) == 0   # at the threshold
            assert tr.step(tick_no=1, rung=3) == 0   # above it
            assert tr.stats()["throttled"] is True
            assert tr.stats()["steps_total"] == 0
            assert tr.step(tick_no=2, rung=1) == 2   # back below: trains
        assert tr.stats()["throttled"] is False
        assert tr.stats()["steps_total"] == 2
        assert obs_registry.counter("train_throttled_ticks").value - c0 == 2
        throttle = [e for e in obs.read_events(log)
                    if e["kind"] == "train_throttled"]
        assert [e["attrs"]["action"] for e in throttle] == ["paused", "resumed"]
        assert throttle[0]["attrs"]["rung"] == 2
    finally:
        tr.close()


def test_trainer_idles_without_consuming_anything(tmp_path, rng):
    """No shards: step() is a cheap no-op that never opens an epoch unit
    (an idle server must not grow the ledger)."""
    (tmp_path / "tap").mkdir()
    tr = ResidentTrainer(tmp_path / "tap", tmp_path / "train", arch=ARCH)
    try:
        assert tr.step(tick_no=0) == 0
        assert tr.step(tick_no=1) == 0
        latest = RunLedger(tmp_path / "train" / LEDGER_NAME).replay()
        assert not any(u.startswith("epoch:") for u in latest)
        assert not tr.ckpt_path.exists()
    finally:
        tr.close()


def test_trainer_rejects_bad_knobs(tmp_path):
    with pytest.raises(ValueError, match="steps_per_tick"):
        ResidentTrainer(tmp_path, tmp_path, steps_per_tick=0)
    with pytest.raises(ValueError, match="publish_every"):
        ResidentTrainer(tmp_path, tmp_path, publish_every=0)
    with pytest.raises(ValueError, match="publish"):
        ResidentTrainer(tmp_path, tmp_path, publish="sometimes")
    with pytest.raises(ValueError, match="throttle_rung"):
        ResidentTrainer(tmp_path, tmp_path, throttle_rung=-1)


# ------------------------------------------------------------- crash + resume
def test_mid_epoch_crash_resumes_without_reconsuming_shards(tmp_path, rng):
    """ChaosCrash at ``mid_epoch`` (train pass done, nothing persisted):
    the restart re-enters the interrupted epoch, every already-done shard
    unit verifies and is skipped (zero re-consumed units), the epoch
    closes with zero batches, and training continues into the next epoch
    on the same shards under fresh units."""
    tap = _fill_shards(tmp_path, rng)
    train = tmp_path / "train"
    kw = dict(arch=ARCH, promote_dir=tmp_path / "promote", batch_size=4,
              steps_per_tick=4, publish="always", max_epochs=2)

    tr = ResidentTrainer(tap, train, **kw)
    chaos.configure("mid_epoch", after=1)
    try:
        with pytest.raises(chaos.ChaosCrash):
            for tick in range(300):
                tr.step(tick_no=tick)
    finally:
        chaos.disable()
        tr.close()

    led_path = train / LEDGER_NAME
    latest = RunLedger(led_path).replay()
    assert latest[unit_epoch(0)]["state"] == "in_flight"
    shard0 = _done_counts(led_path, "shard:")
    assert shard0 and all(u.endswith(":epoch:0") for u in shard0)
    assert not tr.ckpt_path.exists()  # crash preceded the checkpoint
    assert len(GenerationStore(tmp_path / "promote").list_ids()) == 0

    tr2 = ResidentTrainer(tap, train, **kw)
    try:
        _run_until(tr2, lambda t: t.stats()["epochs_done"] >= 2)
    finally:
        tr2.close()

    latest = RunLedger(led_path).replay()
    rec0 = latest[unit_epoch(0)]
    assert rec0["state"] == "done"
    # the resumed epoch found every shard unit already done: ZERO batches
    assert rec0["attrs"]["steps"] == 0
    # raw-ledger proof: each shard unit was consumed exactly once — the
    # epoch-0 units by the crashed pass only, never re-done by the resume
    for unit, n in _done_counts(led_path, "shard:").items():
        assert n == 1, f"shard unit {unit} consumed {n} times"
    # epoch 1 then trained for real on fresh units and checkpointed
    rec1 = latest[unit_epoch(1)]
    assert rec1["state"] == "done" and rec1["attrs"]["steps"] > 0
    assert file_digest(tr2.ckpt_path) == rec1["attrs"]["ckpt_digest"]
    # the zero-batch epoch 0 never published; epoch 1 did
    assert latest.get(unit_publish(0)) is None
    assert latest[unit_publish(1)]["state"] == "done"
    assert len(GenerationStore(tmp_path / "promote").list_ids()) == 1


def test_pre_publish_crash_restages_idempotently(tmp_path, rng):
    """ChaosCrash at ``pre_publish`` (checkpoint + epoch record durable,
    generation NOT staged): the restart finds the in_flight publish unit,
    re-stages the same checkpoint (same digest ⇒ same generation) before
    training on, and consumes no shard unit twice."""
    tap = _fill_shards(tmp_path, rng)
    train = tmp_path / "train"
    promote = tmp_path / "promote"
    kw = dict(arch=ARCH, promote_dir=promote, batch_size=4,
              steps_per_tick=4, publish="always", max_epochs=1)

    tr = ResidentTrainer(tap, train, **kw)
    chaos.configure("pre_publish", after=1)
    try:
        with pytest.raises(chaos.ChaosCrash):
            for tick in range(300):
                tr.step(tick_no=tick)
    finally:
        chaos.disable()
        tr.close()

    led_path = train / LEDGER_NAME
    latest = RunLedger(led_path).replay()
    rec0 = latest[unit_epoch(0)]
    assert rec0["state"] == "done" and rec0["attrs"]["steps"] > 0
    assert latest[unit_publish(0)]["state"] == "in_flight"
    assert GenerationStore(promote).list_ids() == []  # nothing staged
    # the checkpoint is intact (atomic save), exactly as the ledger digests it
    assert file_digest(tr.ckpt_path) == rec0["attrs"]["ckpt_digest"]

    tr2 = ResidentTrainer(tap, train, **kw)
    try:
        # one tick finishes the interrupted publish before any training
        tr2.step(tick_no=0)
    finally:
        tr2.close()

    latest = RunLedger(led_path).replay()
    pub = latest[unit_publish(0)]
    assert pub["state"] == "done" and pub["attrs"]["resumed"] is True
    store = GenerationStore(promote)
    assert [pub["attrs"]["gen"]] == store.list_ids()
    store.load(pub["attrs"]["gen"])  # digest-verifies: no torn generation
    assert tr2.stats()["generations_published"] == 1
    for unit, n in _done_counts(led_path, "shard:").items():
        assert n == 1, f"shard unit {unit} consumed {n} times"
    # max_epochs=1 already done on the first run: the resume trained nothing
    assert tr2.stats()["steps_total"] == 0
