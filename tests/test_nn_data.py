"""Data-pipeline tests: input-list construction against the file layout,
DiscoDataset windowing/jitter/stacking semantics, RAM-partial equivalence
(reference dnn/data/datasets.py, dnn/utils.py:74-140)."""
import numpy as np
import pytest

from disco_tpu.io.layout import DatasetLayout
from disco_tpu.nn.data import (
    FS,
    TRAIN_DUR,
    DiscoDataset,
    DiscoPartialDataset,
    batch_iterator,
    get_input_lists,
    load_input_lists,
    write_input_lists,
)

N_FREQ = 257
SNR = [0, 6]


def _make_corpus(root, rirs=(1, 2), n_nodes=4, z_sigs=("zs_hat",), seed=0):
    """Synthetic corpus matching the generated-file layout: full-length
    train STFTs (11 s → 684 centered frames) with recognizable content."""
    rng = np.random.default_rng(seed)
    lay = DatasetLayout(str(root), "random", "train")
    n_frames = (TRAIN_DUR * FS - 512) // 256 + 3
    for rir in rirs:
        for node in range(n_nodes):
            ch = 1 + n_nodes * node
            stft = (rng.random((N_FREQ, n_frames)) + 0.1).astype("complex64")
            mask = rng.random((N_FREQ, n_frames)).astype("float32")
            p = lay.stft_processed(SNR, "mixture", rir, ch, noise="ssn", normed=True)
            np.save(lay.ensure_dir(p), stft)
            np.save(lay.ensure_dir(lay.mask_processed(SNR, rir, ch, "ssn")), mask)
            for zsig in z_sigs:
                z = (rng.random((N_FREQ, n_frames)) + 0.1).astype("complex64")
                np.save(lay.ensure_dir(lay.stft_z("oracle", SNR, zsig, rir, node + 1, "ssn", normed=True)), z)
    return lay


def test_get_input_lists_layout(tmp_path):
    _make_corpus(tmp_path, rirs=(1, 2))
    lists = get_input_lists(str(tmp_path), [1, 2], scenes="random", z_sigs=["zs_hat"])
    # [4 refs | 4 z | 4 masks] rows, one entry per rir
    assert len(lists) == 12 and all(len(row) == 2 for row in lists)
    assert "stft_processed" in lists[0][0] and "Ch-1.npy" in lists[0][0]
    assert "stft_z" in lists[4][0] and "Node-1" in lists[4][0]
    assert "mask_processed" in lists[-1][0] and "Ch-13" in lists[-1][0]
    for row in lists:
        for p in row:
            assert np.load(p) is not None  # every path exists


def test_write_and_load_input_lists(tmp_path):
    _make_corpus(tmp_path, rirs=(1,))
    lists = get_input_lists(str(tmp_path), [1], scenes="random", z_sigs=["zs_hat"])
    write_input_lists(lists, tmp_path / "lists")
    assert load_input_lists(tmp_path / "lists") == [list(map(str, row)) for row in lists]


def test_disco_dataset_windows(tmp_path):
    _make_corpus(tmp_path, rirs=(1, 2))
    lists = get_input_lists(str(tmp_path), [1, 2], scenes="random", z_sigs=["zs_hat"])
    ds = DiscoDataset(lists, stack_axis=2, rng=np.random.default_rng(3))
    # 684 total frames − 63 (first second) = 621 usable → (621−21)//8+1 windows
    n_usable = (TRAIN_DUR * FS - 512) // 256 + 3 - int(np.ceil(FS / 256))
    assert ds.win_per_seg[0] == (n_usable - 21) // 8 + 1
    assert len(ds) == 2 * ds.win_per_seg[0]

    x, y = ds[0]
    # local ref + 3 z channels, (C, T, F) after the swap; label (T, F)
    assert x.shape == (4, 21, N_FREQ)
    assert y.shape == (21, N_FREQ)
    assert x.dtype == np.float32 and (x >= 0).all()  # magnitudes


def test_disco_dataset_single_channel(tmp_path):
    _make_corpus(tmp_path, rirs=(1,), z_sigs=())
    lists = get_input_lists(str(tmp_path), [1], scenes="random", z_sigs=None)
    ds = DiscoDataset(lists, stack_axis=0, rng=np.random.default_rng(0))
    x, y = ds[5]
    assert x.shape == (21, N_FREQ) and y.shape == (21, N_FREQ)


def test_disco_dataset_freq_stacked(tmp_path):
    _make_corpus(tmp_path, rirs=(1,))
    lists = get_input_lists(str(tmp_path), [1], scenes="random", z_sigs=["zs_hat"])
    ds = DiscoDataset(lists, stack_axis=1, rng=np.random.default_rng(0))
    x, y = ds[0]
    assert x.shape == (21, 4 * N_FREQ)  # ref ‖ 3 z's on the freq axis
    assert y.shape == (21, N_FREQ)


def test_partial_dataset_matches_full(tmp_path):
    """DiscoPartialDataset (lazy ref/mask loads) must produce the same item
    as DiscoDataset given identical random draws (datasets.py:165-221)."""
    _make_corpus(tmp_path, rirs=(1,))
    lists = get_input_lists(str(tmp_path), [1], scenes="random", z_sigs=["zs_hat"])
    full = DiscoDataset(lists, stack_axis=2, rng=np.random.default_rng(11))
    part = DiscoPartialDataset(lists, stack_axis=2, rng=np.random.default_rng(11))
    xf, yf = full[7]
    xp, yp = part[7]
    np.testing.assert_allclose(xp, xf, rtol=1e-6)
    np.testing.assert_allclose(yp, yf, rtol=1e-6)


def test_jitter_stays_in_bounds(tmp_path):
    _make_corpus(tmp_path, rirs=(1,))
    lists = get_input_lists(str(tmp_path), [1], scenes="random", z_sigs=["zs_hat"])
    ds = DiscoDataset(lists, stack_axis=2, rng=np.random.default_rng(0))
    last = len(ds) - 1
    for _ in range(5):  # random jitter at the last window must clamp
        k, m = ds.get_item_indices(last)
        assert m + ds.win_len <= ds.n_frames[k]


def test_batch_iterator_shapes(tmp_path):
    _make_corpus(tmp_path, rirs=(1,))
    lists = get_input_lists(str(tmp_path), [1], scenes="random", z_sigs=["zs_hat"])
    ds = DiscoDataset(lists, stack_axis=2, rng=np.random.default_rng(0))
    x, y = next(batch_iterator(ds, 8, rng=np.random.default_rng(1)))
    assert x.shape == (8, 4, 21, N_FREQ) and y.shape == (8, 21, N_FREQ)
