"""End-to-end test of the per-RIR enhancement driver (reference tango.py
main:460-641 parity: results layout, pickle keys, idempotency)."""
import pickle

import numpy as np
import pytest

from disco_tpu.enhance.driver import aggregate_results, enhance_rir
from disco_tpu.io import DatasetLayout, read_wav, write_wav

FS = 16000
K, C = 4, 4
RIR = 11001  # test-split id
NOISE = "ssn"
SNR_RANGE = (0, 6)


def _build_corpus(root, rirs, lengths=None):
    """A synthetic processed corpus for the given RIR ids (2 s clips unless
    per-RIR ``lengths`` is given): a coherent target across mics + diffuse
    noise, plus dry refs and the SNR log per RIR."""
    rng = np.random.default_rng(7)
    layout = DatasetLayout(str(root), "living", "test")
    lengths = dict(zip(rirs, lengths)) if lengths is not None else {}
    for rir in rirs:
        L = lengths.get(rir, 2 * FS)
        src = 0.2 * rng.standard_normal(L)  # broadband speech-like source
        for node in range(K):
            for c in range(C):
                ch = 1 + node * C + c
                s = np.convolve(src, rng.standard_normal(8) * 0.5, mode="same")
                n = 0.1 * rng.standard_normal(L)
                write_wav(layout.ensure_dir(layout.wav_processed(SNR_RANGE, "target", rir, ch)), s, FS)
                write_wav(layout.ensure_dir(layout.wav_processed(SNR_RANGE, "noise", rir, ch, noise=NOISE)), n, FS)
                write_wav(layout.ensure_dir(layout.wav_processed(SNR_RANGE, "mixture", rir, ch, noise=NOISE)), s + n, FS)
        write_wav(layout.ensure_dir(layout.dry_source("target", rir, 1)), src, FS)
        write_wav(layout.ensure_dir(layout.dry_source("noise", rir, 2, noise=NOISE)), 0.1 * rng.standard_normal(L), FS)
        snr_log = layout.snr_log(SNR_RANGE, rir, NOISE)
        layout.ensure_dir(snr_log)
        np.save(snr_log, np.full(K, 3.0))
    return root


@pytest.fixture
def processed_corpus(tmp_path):
    return _build_corpus(tmp_path / "dataset", [RIR])


EXPECTED_KEYS = {
    # reference pickle schema (tango.py:617-635); sdr/sir/sar carry the
    # mir_eval-compatible 512-tap filtered-projection family
    "snr_in_raw", "sdr_cnv", "sir_cnv", "sar_cnv", "sdr_dry", "sir_dry", "sar_dry",
    "sdr_in_cnv", "sir_in_cnv", "sdr_in_dry", "sir_in_dry", "sar_in_dry",
    "delta_stoi_cnv", "delta_stoi_dry", "snr_out", "snr_in_cnv", "snr_in_dry",
    "fw_sd_cnv", "fw_sd_dry",
    # scale-invariant (Le Roux) family, written alongside
    "si_sdr_cnv", "si_sir_cnv", "si_sar_cnv", "si_sdr_dry", "si_sir_dry", "si_sar_dry",
    "si_sdr_in_cnv", "si_sir_in_cnv", "si_sdr_in_dry", "si_sir_in_dry", "si_sar_in_dry",
}


def test_enhance_rir_end_to_end(processed_corpus, tmp_path):
    out_root = tmp_path / "results"
    results = enhance_rir(
        str(processed_corpus), "living", RIR, NOISE,
        snr_range=SNR_RANGE, out_root=str(out_root), save_fig=False,
    )
    assert results is not None
    assert EXPECTED_KEYS <= set(results)
    for key in ("sdr_cnv", "snr_out"):
        assert results[key].shape == (K,)

    # the filter must actually enhance: output SDR above input SDR
    assert np.all(results["sdr_cnv"] > results["sdr_in_cnv"])

    # results tree contract (reference main:475-492,596-639)
    assert (out_root / "OIM" / f"results_tango_{RIR}_{NOISE}.p").exists()
    assert (out_root / "OIM" / f"results_mwf_{RIR}_{NOISE}.p").exists()
    assert (out_root / "WAV" / str(RIR) / f"out_mix-{NOISE}_Node-1.wav").exists()
    assert (out_root / "WAV" / str(RIR) / f"mid_z-{NOISE}_Node-4.wav").exists()
    assert (out_root / "MASK" / str(RIR) / f"step1_{NOISE}_Node-1.npy").exists()
    assert (out_root / "STFT" / "z" / "raw" / "0-6" / f"{RIR}_{NOISE}_Node-1.npy").exists()

    # idempotency guard (main:477-479)
    assert enhance_rir(
        str(processed_corpus), "living", RIR, NOISE,
        snr_range=SNR_RANGE, out_root=str(out_root), save_fig=False,
    ) is None

    # mwf pickle has the same schema
    with open(out_root / "OIM" / f"results_mwf_{RIR}_{NOISE}.p", "rb") as fh:
        resz = pickle.load(fh)
    assert EXPECTED_KEYS <= set(resz)

    agg = aggregate_results(out_root / "OIM", kind="tango")
    assert agg["sdr_cnv"].shape == (K,)
    agg_none = aggregate_results(out_root / "OIM", kind="tango", noise="other")
    assert agg_none == {}


def test_estimate_masks_crnn_path():
    """estimate_masks with real (module, variables) pairs for both steps —
    the staged flow: step-1 masks feed z computation feeding the step-2
    multichannel CRNN (reference main:497-503)."""
    import numpy as np

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.driver import estimate_masks
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    rng = np.random.default_rng(2)
    K, C, L = 4, 2, 16000
    y = rng.standard_normal((K, C, L)).astype("float32")
    s = 0.6 * rng.standard_normal((K, C, L)).astype("float32")
    n = y - s
    Y, S, N = stft(y), stft(s), stft(n)

    def make(n_ch):
        model, tx = build_crnn(n_ch=n_ch)
        x0 = np.zeros((1, n_ch, 21, 257), "float32")
        state = create_train_state(model, tx, x0)
        return (model, {"params": state.params, "batch_stats": state.batch_stats})

    models = (make(1), make(K))  # step 2 consumes [y_ref ‖ z_{j≠k}] = K channels
    masks_z, mask_w = estimate_masks(Y, S, N, models, "irm1", K)
    for m in (np.asarray(masks_z), np.asarray(mask_w)):
        assert m.shape == (K, Y.shape[2], Y.shape[3])
        assert np.all(m >= 0) and np.all(m <= 1)  # sigmoid output range


def test_crnn_masks_batched_matches_per_node_loop():
    """One concatenated forward == K sequential crnn_mask calls."""
    import numpy as np

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.inference import crnn_mask, crnn_masks_batched
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    rng = np.random.default_rng(4)
    K, L = 3, 8000
    Y = np.asarray(stft(rng.standard_normal((K, L)).astype("float32")))
    model, tx = build_crnn(n_ch=1)
    state = create_train_state(model, tx, np.zeros((1, 1, 21, 257), "float32"))
    variables = {"params": state.params, "batch_stats": state.batch_stats}

    batched = crnn_masks_batched(Y, model, variables)
    for k in range(K):
        single = crnn_mask(Y[k], model, variables)
        np.testing.assert_allclose(batched[k], single, atol=1e-6)


@pytest.mark.slow
def test_enhance_rirs_batched_crnn_matches_per_rir(processed_corpus, tmp_path):
    """The corpus driver's models path (VERDICT round-1 item 3): batched
    CRNN-mask enhancement reproduces the per-RIR CRNN path's metrics."""
    import numpy as np

    from disco_tpu.enhance.driver import enhance_rirs_batched
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    def make(n_ch):
        model, tx = build_crnn(n_ch=n_ch)
        x0 = np.zeros((1, n_ch, 21, 257), "float32")
        state = create_train_state(model, tx, x0)
        return (model, {"params": state.params, "batch_stats": state.batch_stats})

    models = (make(1), make(K))
    r_one = enhance_rir(
        str(processed_corpus), "living", RIR, NOISE, snr_range=SNR_RANGE,
        out_root=str(tmp_path / "per_rir"), save_fig=False, models=models,
        bucket=8192,
    )
    r_batch = enhance_rirs_batched(
        str(processed_corpus), "living", [RIR], NOISE, snr_range=SNR_RANGE,
        out_root=str(tmp_path / "batched"), save_fig=False, models=models,
        bucket=8192, max_batch=2,
    )
    assert set(r_batch) == {RIR}
    for key in ("sdr_cnv", "snr_out", "sdr_in_cnv"):
        np.testing.assert_allclose(r_batch[RIR][key], r_one[key], atol=0.2)


def test_enhance_rir_streaming_mode(processed_corpus, tmp_path):
    out_root = tmp_path / "results_streaming"
    results = enhance_rir(
        str(processed_corpus), "living", RIR, NOISE,
        snr_range=SNR_RANGE, out_root=str(out_root), save_fig=False,
        streaming=True,
    )
    assert results is not None
    # the online filter with warm-up is weaker than offline, but must improve
    assert np.mean(results["sdr_cnv"]) > np.mean(results["sdr_in_cnv"])


def test_bucketing_near_invariance(processed_corpus, tmp_path):
    """Length bucketing changes only the clip-end boundary frames; metrics
    must agree within the documented ~2 dB bound and outputs must exist at
    the true (unpadded) length."""
    r_buck = enhance_rir(
        str(processed_corpus), "living", RIR, NOISE, snr_range=SNR_RANGE,
        out_root=str(tmp_path / "rb"), save_fig=False, bucket=8192,
    )
    r_none = enhance_rir(
        str(processed_corpus), "living", RIR, NOISE, snr_range=SNR_RANGE,
        out_root=str(tmp_path / "rn"), save_fig=False, bucket=0,
    )
    for key in ("sdr_cnv", "snr_out"):
        np.testing.assert_allclose(r_buck[key], r_none[key], atol=2.0)
    from disco_tpu.io import read_wav

    wav, _ = read_wav(tmp_path / "rb" / "WAV" / str(RIR) / f"out_mix-{NOISE}_Node-1.wav")
    assert len(wav) == 2 * FS  # trimmed to the true clip length
    # saved masks/z are trimmed to the TRUE frame count (identical shapes
    # with and without bucketing)
    mb = np.load(tmp_path / "rb" / "MASK" / str(RIR) / f"step1_{NOISE}_Node-1.npy")
    mn = np.load(tmp_path / "rn" / "MASK" / str(RIR) / f"step1_{NOISE}_Node-1.npy")
    assert mb.shape == mn.shape


def test_enhance_rirs_batched(processed_corpus, tmp_path):
    """Batched corpus driver: same results contract as the per-RIR path,
    one vmapped launch per length bucket."""
    from disco_tpu.enhance.driver import enhance_rirs_batched

    out_root = tmp_path / "batched"
    results = enhance_rirs_batched(
        str(processed_corpus), "living", [RIR, RIR + 1], NOISE,
        snr_range=SNR_RANGE, out_root=str(out_root), save_fig=False,
    )
    # RIR+1 has no corpus files -> skipped; RIR processed once
    assert set(results) == {RIR}
    assert EXPECTED_KEYS <= set(results[RIR])
    assert np.all(results[RIR]["sdr_cnv"] > results[RIR]["sdr_in_cnv"])
    assert (out_root / "OIM" / f"results_tango_{RIR}_{NOISE}.p").exists()
    # idempotent second call
    assert enhance_rirs_batched(
        str(processed_corpus), "living", [RIR], NOISE,
        snr_range=SNR_RANGE, out_root=str(out_root), save_fig=False,
    ) == {}


def test_enhance_rirs_batched_ragged_lengths(tmp_path):
    """A ragged corpus (clip lengths landing in two different buckets) is
    grouped into one compiled program per bucket, padded clips are trimmed
    back to their true lengths, and every RIR is scored and persisted."""
    from disco_tpu.enhance.driver import enhance_rirs_batched

    rirs = [RIR, RIR + 1, RIR + 2]
    # bucket_length(.., 8192): 32000->32768 alone; 33000 and 40000 BOTH ->
    # 40960, so one compiled batch holds two clips of different true lengths
    # and each must be trimmed to its own L
    lengths = [2 * FS, 33000, 40000]
    corpus = _build_corpus(tmp_path / "ragged", rirs, lengths=lengths)
    out_root = tmp_path / "res"
    results = enhance_rirs_batched(
        str(corpus), "living", rirs, NOISE, snr_range=SNR_RANGE,
        out_root=str(out_root), save_fig=False, bucket=8192, max_batch=2,
    )
    assert set(results) == set(rirs)
    for rir, L in zip(rirs, lengths):
        # enhanced WAV trimmed to the true clip length (padding removed)
        wav = read_wav(out_root / "WAV" / str(rir) / f"out_mix-{NOISE}_Node-1.wav")[0]
        assert len(wav) == L, (rir, len(wav), L)
        assert np.all(results[rir]["sdr_cnv"] > results[rir]["sdr_in_cnv"])


@pytest.mark.slow
def test_enhance_rirs_batched_score_workers_identical(tmp_path):
    """Threaded scoring (score_workers>1) produces bit-identical metrics to
    inline scoring — the overlap changes scheduling, never math.  Three RIRs
    with max_batch=1 force three chunks, so multiple futures and the
    bounded cross-chunk drain ordering (pipeline.MAX_PENDING_CHUNKS) are
    actually exercised (results must stay keyed to their RIR across chunk
    boundaries)."""
    from disco_tpu.enhance.driver import enhance_rirs_batched

    rirs = [RIR, RIR + 1, RIR + 2]
    corpus = _build_corpus(tmp_path / "dataset3", rirs)
    kw = dict(snr_range=SNR_RANGE, save_fig=False, max_batch=1)
    r_inline = enhance_rirs_batched(
        str(corpus), "living", rirs, NOISE,
        out_root=str(tmp_path / "inline"), score_workers=1, **kw,
    )
    r_pool = enhance_rirs_batched(
        str(corpus), "living", rirs, NOISE,
        out_root=str(tmp_path / "pool"), score_workers=4, **kw,
    )
    assert set(r_inline) == set(r_pool) == set(rirs)
    for rir in rirs:
        for key in r_inline[rir]:
            np.testing.assert_array_equal(
                np.asarray(r_inline[rir][key]), np.asarray(r_pool[rir][key]),
                err_msg=f"{rir}/{key}",
            )


def test_enhance_rir_power_solver_on_corpus(processed_corpus, tmp_path):
    """--solver power on real pipeline data: enhancement metrics land within
    0.5 dB of the eigh path across all nodes (offline covariances have
    strong eigengaps — the tight-parity regime)."""
    r_e = enhance_rir(
        str(processed_corpus), "living", RIR, NOISE, snr_range=SNR_RANGE,
        out_root=str(tmp_path / "eigh"), save_fig=False,
    )
    r_p = enhance_rir(
        str(processed_corpus), "living", RIR, NOISE, snr_range=SNR_RANGE,
        out_root=str(tmp_path / "power"), save_fig=False, solver="power",
    )
    for key in ("sdr_cnv", "si_sdr_cnv", "snr_out"):
        np.testing.assert_allclose(
            np.asarray(r_p[key]), np.asarray(r_e[key]), atol=0.5, err_msg=key
        )
    assert np.all(np.asarray(r_p["sdr_cnv"]) > np.asarray(r_p["sdr_in_cnv"]))


def test_enhance_rirs_batched_on_mesh_identical(tmp_path):
    """Corpus enhancement on a (batch=2, node=4) GSPMD mesh produces the
    same metrics as the single-device vmap path — the multi-chip corpus
    story end-to-end (ingest → sharded enhancement → scoring)."""
    from disco_tpu.enhance.driver import enhance_rirs_batched
    from disco_tpu.parallel import make_mesh

    rirs = [RIR, RIR + 1]
    corpus = _build_corpus(tmp_path / "dsm", rirs)
    kw = dict(snr_range=SNR_RANGE, save_fig=False, max_batch=2)
    r_plain = enhance_rirs_batched(
        str(corpus), "living", rirs, NOISE, out_root=str(tmp_path / "plain"), **kw,
    )
    mesh = make_mesh(n_node=4, n_batch=2)
    r_mesh = enhance_rirs_batched(
        str(corpus), "living", rirs, NOISE, out_root=str(tmp_path / "mesh"),
        mesh=mesh, **kw,
    )
    assert set(r_plain) == set(r_mesh) == set(rirs)
    for rir in rirs:
        for key in ("sdr_cnv", "si_sdr_cnv", "snr_out"):
            np.testing.assert_allclose(
                np.asarray(r_mesh[rir][key]), np.asarray(r_plain[rir][key]),
                rtol=2e-4, atol=1e-3, err_msg=f"{rir}/{key}",
            )


def test_aggregate_cli(processed_corpus, tmp_path, capsys):
    """disco-aggregate: mean ± CI table and JSON over the OIM pickles."""
    import json

    from disco_tpu.cli import aggregate

    out_root = tmp_path / "agg_results"
    enhance_rir(
        str(processed_corpus), "living", RIR, NOISE,
        snr_range=SNR_RANGE, out_root=str(out_root), save_fig=False,
    )
    summary = aggregate.main([str(out_root / "OIM"), "--json"])
    printed = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(printed) == summary
    assert summary["sdr_cnv"]["n"] == K
    assert np.isfinite(summary["sdr_cnv"]["mean"])
    # table mode + key subset
    sub = aggregate.main([str(out_root / "OIM"), "--keys", "sdr_cnv", "snr_out"])
    assert set(sub) == {"sdr_cnv", "snr_out"}
    # empty dir
    assert aggregate.main([str(tmp_path / "nothing")]) == {}


def test_streaming_rejects_pallas_cov(processed_corpus, tmp_path):
    """--streaming uses the smoothed-covariance estimator; the fused offline
    kernel must be rejected, not silently ignored."""
    with pytest.raises(ValueError, match="cov_impl"):
        enhance_rir(
            str(processed_corpus), "living", RIR, NOISE, save_dir="s_cov",
            streaming=True, cov_impl="pallas", out_root=str(tmp_path / "res_s_cov"),
        )
