"""End-to-end parity tests for the TANGO two-step pipeline against the
float64 NumPy oracle (tests/reference_impls.tango_np, restating reference
tango.py:252-457)."""
import numpy as np
import pytest

from disco_tpu.core.dsp import istft, stft
from disco_tpu.core.metrics import si_sdr
from disco_tpu.enhance import oracle_masks, others_index, tango

from tests.reference_impls import istft_np, si_sdr_np, stft_np, tango_np

K, C, L = 3, 2, 16384  # small but non-trivial: 3 nodes x 2 mics x 1 s
FS = 16000


def _scene(rng, K=K, C=C, L=L):
    """Synthesized multichannel scene: a shared 'speech' source with random
    per-mic FIR channels + diffuse noise, so covariances are genuinely rank-
    deficient-ish and the GEVD has work to do."""
    src = rng.standard_normal(L)
    s = np.stack(
        [
            np.stack(
                [np.convolve(src, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)]
            )
            for _ in range(K)
        ]
    )
    n = 0.8 * rng.standard_normal((K, C, L))
    y = s + n
    return y, s, n


@pytest.fixture(scope="module")
def scene():
    return _scene(np.random.default_rng(7))


@pytest.fixture(scope="module")
def oracle(scene):
    y, s, n = scene
    return tango_np(y, s, n, mask_type="irm1", mask_for_z="local")


@pytest.fixture(scope="module")
def ours(scene):
    # solver='eigh' explicitly: this fixture is the reference-bit-matching
    # anchor for the tight-tolerance parity tests (the reference semantics
    # of internal_formulas.py:56-73).  The pipeline DEFAULT is 'power'
    # since round 4; its agreement with this anchor is pinned at the SDR
    # level by test_default_solver_sdr_parity below.
    y, s, n = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks_z = oracle_masks(S, N, "irm1")
    return tango(Y, S, N, masks_z, masks_z, policy="local", solver="eigh"), (Y, S, N)


def test_others_index():
    np.testing.assert_array_equal(others_index(3), [[1, 2], [0, 2], [0, 1]])


def test_step1_z_parity(oracle, ours):
    """Compressed streams match the float64 oracle closely in relative l2."""
    res, _ = ours
    for key in ("z_y", "z_s", "z_n", "zn"):
        got = np.asarray(res.__getattribute__(key))
        want = oracle[key]
        err = np.linalg.norm(got - want) / np.linalg.norm(want)
        assert err < 5e-3, (key, err)


def test_step2_output_parity(oracle, ours):
    # Two chained f32 eigendecompositions vs the f64 oracle: in the
    # ill-conditioned near-DC bins the GEVD direction is sensitive to
    # precision, so raw-STFT agreement is checked at 5% on the energetic
    # half of the bins and 10% overall; the meaningful anchor is SDR-level
    # parity (test_sdr_parity_with_oracle, 0.1 dB).
    # nf is the residual the filter suppresses by ~20 dB, so tiny absolute
    # deviations inflate its relative error — it gets the looser bound.
    res, _ = ours
    for key, tol, tol_hi in (("yf", 1e-1, 5e-2), ("sf", 1e-1, 5e-2), ("nf", 2e-1, 2e-1)):
        got = np.asarray(getattr(res, key))
        want = oracle[key]
        err = np.linalg.norm(got - want) / np.linalg.norm(want)
        assert err < tol, (key, err)
        pw = np.linalg.norm(want, axis=-1)
        hi = pw > np.percentile(pw, 50)
        err_hi = np.linalg.norm((got - want)[None, hi]) / np.linalg.norm(want[None, hi])
        assert err_hi < tol_hi, (key, err_hi)


def test_enhancement_improves_snr(scene, ours):
    """The acceptance bar: output SNR (filtered-speech vs filtered-noise
    power) beats the ref-mic input SNR by several dB at every node."""
    y, s, n = scene
    res, _ = ours
    for k in range(K):
        snr_in = 10 * np.log10(np.var(s[k, 0]) / np.var(n[k, 0]))
        sf = np.asarray(istft(res.sf[k], L), np.float64)
        nf = np.asarray(istft(res.nf[k], L), np.float64)
        snr_out = 10 * np.log10(np.var(sf) / np.var(nf))
        assert snr_out > snr_in + 3.0, (k, snr_in, snr_out)


def test_sdr_parity_with_oracle(scene, oracle, ours):
    y, s, n = scene
    res, _ = ours
    for k in range(K):
        ref = s[k, 0]
        ours_sdr = si_sdr(ref, np.asarray(istft(res.yf[k], L), np.float64))
        oracle_sdr = si_sdr_np(ref, istft_np(oracle["yf"][k], L))
        assert abs(ours_sdr - oracle_sdr) < 0.1, (k, ours_sdr, oracle_sdr)


def test_policy_none_matches_oracle(scene):
    y, s, n = scene
    want = tango_np(y, s, n, mask_type="irm1", mask_for_z=None)
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    # bit-parity anchor vs the f64 oracle -> the eigh lane (see `ours`)
    res = tango(Y, S, N, masks, masks, policy="none", solver="eigh")
    err = np.linalg.norm(np.asarray(res.yf) - want["yf"]) / np.linalg.norm(want["yf"])
    assert err < 5e-3, err


@pytest.mark.parametrize("policy", ["distant", "compressed", "use_oracle_refs", "use_oracle_zs"])
def test_other_policies_run_and_enhance(scene, policy):
    """The remaining policy branches execute and still enhance (no oracle
    restated for each — the branch semantics are covered by code review +
    the 'local'/'none' parity anchors)."""
    y, s, n = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    res = tango(Y, S, N, masks, masks, policy=policy)
    enh = np.asarray(istft(res.yf[0], L), np.float64)
    assert si_sdr(s[0, 0], enh) > si_sdr(s[0, 0], y[0, 0])


def test_default_solver_sdr_parity(scene, ours):
    """The full two-step pipeline on its DEFAULT solver ('power' since the
    round-4 flip from the solver_ab artifact) lands within 0.1 dB SI-SDR
    of the eigh anchor at every node — the acceptance bar that lets the
    cheap solver stand in for the batched eigendecomposition."""
    y, s, n = scene
    res_e, (Y, S, N) = ours
    masks = oracle_masks(S, N, "irm1")
    res_p = tango(Y, S, N, masks, masks, policy="local")  # default solver
    for k in range(K):
        sdr_e = si_sdr(s[k, 0], np.asarray(istft(res_e.yf[k], L), np.float64))
        sdr_p = si_sdr(s[k, 0], np.asarray(istft(res_p.yf[k], L), np.float64))
        assert abs(sdr_e - sdr_p) < 0.1, (k, sdr_e, sdr_p)


def test_oracle_step1_stats_branch(scene):
    y, s, n = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    res = tango(Y, S, N, masks, masks, policy="local", oracle_step1_stats=True)
    assert np.isfinite(np.asarray(res.yf)).all()


def test_batched_tango_vmaps_over_rooms(scene):
    """Rooms are an array axis: vmap(tango) on a stacked batch equals per-room
    calls."""
    import jax

    y, s, n = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    Yb = np.stack([Y, Y * 0.5])
    Sb = np.stack([S, S * 0.5])
    Nb = np.stack([N, N * 0.5])
    mb = np.stack([masks, masks])
    batched = jax.vmap(lambda a, b, c, d: tango(a, b, c, d, d, policy="local"))(
        Yb, Sb, Nb, mb
    )
    single = tango(Y, S, N, masks, masks, policy="local")
    np.testing.assert_allclose(
        np.asarray(batched.yf[0]), np.asarray(single.yf), rtol=2e-4, atol=1e-5
    )


def test_cov_impl_pallas_matches_xla(scene, ours):
    """cov_impl='pallas' (the fused masked-covariance kernel, interpret mode
    off-TPU) must reproduce the default einsum path through the FULL
    two-step pipeline — same filters, same outputs.  Solver held fixed at
    'eigh' on BOTH sides: this test isolates the covariance implementation,
    and the `ours` fixture is the eigh-pinned anchor (the pipeline default
    moved to 'power' in round 4; pallas-vs-xla agrees at ~6e-7 rel-l2 for
    either solver when matched)."""
    y, s, n = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks_z = oracle_masks(S, N, "irm1")
    res_ref, _ = ours
    res = tango(
        Y, S, N, masks_z, masks_z, policy="local", cov_impl="pallas", solver="eigh"
    )
    np.testing.assert_allclose(
        np.asarray(res.yf), np.asarray(res_ref.yf), rtol=5e-3, atol=5e-5
    )
    # non-local policy: step 2 keeps the einsum stat path, step 1 fuses
    res_d = tango(
        Y, S, N, masks_z, masks_z, policy="distant", cov_impl="pallas", solver="eigh"
    )
    res_d_ref = tango(Y, S, N, masks_z, masks_z, policy="distant", solver="eigh")
    np.testing.assert_allclose(
        np.asarray(res_d.yf), np.asarray(res_d_ref.yf), rtol=5e-3, atol=5e-5
    )


def test_bf16_lane_oracle_parity_and_default_untouched(scene, oracle, ours):
    """The opt-in bf16 compute lane, gated by the float64 oracle with
    documented per-stage tolerances: step-1 compressed streams within 1e-2
    relative l2 of the oracle (measured ~1e-3 on this scene; the f32 gate is
    5e-3), end-to-end yf within the SAME 1e-1 bound as the f32 lane, and
    SDR within 0.1 dB of the f32 lane.  Requesting the lane must not
    perturb the default: a fresh f32 call stays bit-identical to the
    module-scope fixture."""
    y, s, n = scene
    res_f, (Y, S, N) = ours
    masks = oracle_masks(S, N, "irm1")
    res_b = tango(Y, S, N, masks, masks, policy="local", solver="eigh",
                  precision="bf16")
    for key, tol in (("z_y", 1e-2), ("zn", 1e-2), ("yf", 1e-1)):
        got = np.asarray(getattr(res_b, key))
        want = oracle[key]
        err = np.linalg.norm(got - want) / np.linalg.norm(want)
        assert err < tol, (key, err)
    from disco_tpu.core.metrics import si_sdr as _si_sdr

    for k in range(K):
        sdr_f = _si_sdr(s[k, 0], np.asarray(istft(res_f.yf[k], L), np.float64))
        sdr_b = _si_sdr(s[k, 0], np.asarray(istft(res_b.yf[k], L), np.float64))
        assert abs(float(sdr_f) - float(sdr_b)) < 0.1, (k, sdr_f, sdr_b)
    # the default lane is untouched by the bf16 program existing
    res_f2 = tango(Y, S, N, masks, masks, policy="local", solver="eigh")
    np.testing.assert_array_equal(np.asarray(res_f2.yf), np.asarray(res_f.yf))


def test_bf16_lane_other_policies_run(scene):
    """The folded per-channel paths ('distant') and the two-stack fold
    ('none') execute under the bf16 lane and stay finite."""
    y, s, n = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    for policy in ("distant", "none"):
        res = tango(Y, S, N, masks, masks, policy=policy, precision="bf16")
        assert np.isfinite(np.asarray(res.yf)).all(), policy


def test_precision_rejects_non_canonical_tokens(scene):
    """tango is jitted DIRECTLY, so a spelling variant normalized inside the
    body would already have keyed a duplicate program (the string-typed mu=1
    retrace trap) — non-canonical tokens must raise at trace time instead of
    silently retracing (ops.resolve.check_canonical_precision)."""
    y, s, n = scene
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")
    for bad in ("fp8", "F32", " bf16 "):
        with pytest.raises(ValueError, match="not canonical"):
            tango(Y, S, N, masks, masks, precision=bad)
