"""Test configuration: force the CPU backend with 8 virtual devices so the
multi-chip sharding paths (Mesh / shard_map / pjit) are exercised without TPU
hardware, per the build environment contract."""
import os

# Hard override: the image may export JAX_PLATFORMS=axon (single real TPU chip
# behind a tunnel); tests must run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
