"""Test configuration: force the CPU backend with 8 virtual devices so the
multi-chip sharding paths (Mesh / shard_map / pjit) are exercised without TPU
hardware, per the build environment contract.

The image's sitecustomize imports jax at interpreter start (to register the
axon TPU plugin), so setting JAX_PLATFORMS via os.environ here is too late —
jax has already read the env at import. Use jax.config.update instead, which
works as long as no backend has been initialised yet.
"""
import os

# Hermetic tests: the drivers enable the persistent XLA compile cache by
# default (disco_tpu.utils.compile_cache) — keep the suite from writing
# shared state under ~/.cache, and from coupling test runs through a warm
# cache, unless a test opts in explicitly.
os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
