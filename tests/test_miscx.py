"""Tests for disco_tpu.core.miscx (reference misc_utils.py parity)."""
import numpy as np
import pytest

from disco_tpu.core.miscx import (
    bar_data,
    channel_range_of_node,
    concatenate_dicts,
    find_unmatched_dim,
    get_node_from_channel,
    get_random_string,
    integerize,
    repeat_matrix,
    trim_2d_array,
    truncated_eye,
    yaml2dict,
)


@pytest.mark.parametrize(
    "ch,geo,node",
    [(0, [4, 4, 4, 4], 0), (3, [4, 4, 4, 4], 0), (4, [4, 4, 4, 4], 1), (15, [4, 4, 4, 4], 3), (2, [1, 2, 3], 1)],
)
def test_get_node_from_channel(ch, geo, node):
    assert get_node_from_channel(ch, geo) == node


def test_channel_range_roundtrip():
    geo = [4, 2, 4, 6]
    for node in range(len(geo)):
        start, stop = channel_range_of_node(node, geo)
        assert stop - start == geo[node]
        for ch in range(start, stop):
            assert get_node_from_channel(ch, geo) == node


def test_find_unmatched_dim():
    a, b = np.zeros((3, 5, 2)), np.zeros((3, 7, 2))
    (dims,) = find_unmatched_dim(a, b)
    assert list(dims) == [1]


def test_concatenate_dicts_mismatched_axis():
    d1 = {"x": np.ones((2, 3)), "y": np.zeros((4,))}
    d2 = {"x": np.ones((2, 5)), "y": np.zeros((4,))}
    out = concatenate_dicts([d1, d2])
    assert out["x"].shape == (2, 8)
    assert out["y"].shape == (8,)


def test_repeat_matrix_fortran_order():
    a = np.arange(6).reshape(2, 3)
    b = repeat_matrix(a, 4)
    assert b.shape == (2, 3, 4)
    for r in range(4):
        np.testing.assert_array_equal(b[:, :, r], a)


@pytest.mark.parametrize("N,j,k", [(5, 3, 0), (4, 2, 1), (6, 6, 0)])
def test_truncated_eye(N, j, k):
    m = truncated_eye(N, j, k)
    assert m.shape == (N + abs(k), N + abs(k)) if k else (N, N)
    assert m.sum() == j
    assert np.all(np.diag(m, k=k)[:j] == 1)


def test_trim_2d_array():
    m = np.zeros((3, 7))
    m[:, 2:5] = 1.0
    np.testing.assert_array_equal(trim_2d_array(m, axis=0, trim="fb"), m[:, 2:5])
    np.testing.assert_array_equal(trim_2d_array(m, axis=0, trim="f"), m[:, 2:])
    np.testing.assert_array_equal(trim_2d_array(m, axis=0, trim="b"), m[:, :5])
    mt = m.T
    np.testing.assert_array_equal(trim_2d_array(mt, axis=1, trim="fb"), mt[2:5, :])


def test_bar_data():
    x_edges = np.array([1.0, 2.0, 3.0])
    x = np.array([0.5, 1.5, 1.7, 2.5])
    y = np.array([10.0, 20.0, 30.0, 40.0])
    means, cis = bar_data(x_edges, x, y)
    assert means[0] == 10.0
    assert means[1] == 25.0
    assert means[2] == 40.0


def test_get_random_string():
    s = get_random_string(12)
    assert len(s) == 12 and s.isalnum()


def test_integerize_conventions():
    np.testing.assert_array_equal(integerize("4 4 4 4"), np.array([4, 4, 4, 4]))
    assert integerize("None") is None
    assert integerize("a b") == ["a", "b"]
    assert integerize("plain") == "plain"
    assert integerize({"n": "1 2"})["n"].tolist() == [1, 2]


def test_yaml2dict(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text("geo: 4 4 4 4\nname: run\nnothing: None\n")
    d = yaml2dict(p)
    assert d["geo"].tolist() == [4, 4, 4, 4]
    assert d["name"] == "run"
    assert d["nothing"] is None
