"""Tests for disco_tpu.obs — events/schema, metrics, fence/recompile
accounting, numerics sentinels, the obs CLI (report/compare), and bench.py's
one-JSON-line stdout contract with --obs-log enabled.

The JSONL schema tests double as the CI gate: `make obs-check` runs them
(`-k schema`), so any event-schema drift fails the build."""
import json
import os
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

from disco_tpu import obs
from disco_tpu.cli import obs as obs_cli

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # for bench.py


@pytest.fixture(autouse=True)
def _clean_recorder():
    """Every test starts and ends with recording, tracing and the flight
    recorder off (all three are process-global)."""
    obs.disable()
    obs.trace.disable()
    obs.flight.disable()
    yield
    obs.disable()
    obs.trace.disable()
    obs.flight.disable()


# -- events / recorder ------------------------------------------------------
def test_recorder_disabled_is_noop(tmp_path):
    assert not obs.enabled()
    assert obs.record("note", msg="dropped") is None
    with obs.stage("never"):
        pass  # no recorder, no file, no error


def test_record_roundtrip_and_manifest(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        ev = obs.write_manifest(config={"solver": "power"}, tool="test")
        assert ev is not None
        obs.record("note", stage="s", msg="hello", value=3)
    events = obs.read_events(log)
    assert [e["kind"] for e in events] == ["manifest", "note"]
    man = events[0]["attrs"]
    # manifest carries provenance: git SHA, backend, devices, versions
    assert man["config"] == {"solver": "power"}
    assert man["platform"] == "cpu" and man["device_count"] == 8
    assert man["versions"]["jax"] and man["versions"]["numpy"]
    assert len(man["git_sha"]) == 40
    assert events[1]["attrs"] == {"msg": "hello", "value": 3}


def test_stage_records_duration_and_fences(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        with obs.stage("work", rir=7):
            obs.fence_tick(3)
            time.sleep(0.01)
    (ev,) = obs.read_events(log)
    assert ev["kind"] == "stage_end" and ev["stage"] == "work"
    assert ev["attrs"]["fences"] == 3 and ev["attrs"]["rir"] == 7
    assert ev["attrs"]["dur_s"] >= 0.01


def test_recorder_append_only_and_threadsafe(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        threads = [
            threading.Thread(target=lambda i=i: obs.record("note", i=i))
            for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    events = obs.read_events(log)
    assert sorted(e["attrs"]["i"] for e in events) == list(range(16))


def test_unserializable_attr_degrades_to_repr(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.record("note", obj=object())  # must not raise
    (ev,) = obs.read_events(log)
    assert "object" in ev["attrs"]["obj"]


# -- schema (run by `make obs-check` via -k schema) -------------------------
def test_event_schema_validation():
    good = {"t": 1.0, "kind": "note", "stage": None, "attrs": {}}
    obs.validate_event(good)
    with pytest.raises(ValueError, match="unknown event kind"):
        obs.validate_event({**good, "kind": "nope"})
    with pytest.raises(ValueError, match="missing key"):
        obs.validate_event({"kind": "note"})
    with pytest.raises(ValueError, match="'t' must be a number"):
        obs.validate_event({**good, "t": "late"})
    with pytest.raises(ValueError, match="'stage' must be a string"):
        obs.validate_event({**good, "stage": 3})
    with pytest.raises(ValueError, match="'attrs' must be an object"):
        obs.validate_event({**good, "attrs": []})


def test_emitted_log_conforms_to_schema(tmp_path):
    """Every event the instrumented pipeline emits must validate: exercise
    each producer once and re-read with validation on."""
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.write_manifest(config={"a": 1})
        with obs.stage("stft", rir=1):
            pass
        f = obs.counted_jit(lambda x: x + 1, label="unit")
        f(jnp.ones(3))
        obs.check_finite("bad", jnp.asarray([np.nan]), stage="mwf")
        obs.record("clip", rir=1, noise="ssn")
        obs.record("epoch", stage="train", epoch=0, train_loss=0.5, val_loss=0.6)
        obs.record("watchdog", stage="bench", timeout_s=1.0)
        obs.record("bench_result", stage="bench", value=1.0)
        # the fault-tolerance producers (disco_tpu.fault / utils.resilience)
        from disco_tpu.fault import FaultSpec, plan_faults
        from disco_tpu.utils.resilience import call_with_retries

        plan_faults(FaultSpec(node_dropout=(0,)), n_nodes=2).record(mode="offline")
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] == 1:
                raise OSError("transient")
            return 1

        call_with_retries(flaky, retries=1, base_delay_s=0.0, sleep=lambda _: None)
        obs.record("degraded", stage="mwf", mode="offline", nodes=[0])
        # the crash-safe runs producers (disco_tpu.runs)
        obs.record("run_start", stage="enhance", tool="test",
                   preflight={"ok": True, "dur_s": 0.01})
        obs.record("run_resume", stage="enhance", n_done=1, n_requeued=0)
        from disco_tpu.runs import GracefulInterrupt, request_stop

        with GracefulInterrupt():
            request_stop("schema-test")  # emits "interrupted"
        obs.record("warning", stage="load_input", reason="schema-test")
        # the causal-tracing + flight-recorder producers (obs.trace/flight)
        from disco_tpu.obs import flight as obs_flight
        from disco_tpu.obs import trace as obs_trace

        obs_trace.enable()
        try:
            ctx = obs_trace.root("client_block", seq=0, session="s1")
            obs_trace.span("enqueue", ctx, session="s1", seq=0)
        finally:
            obs_trace.disable()
        obs_flight.enable(dump_dir=tmp_path / "flight")
        try:
            obs_flight.dump(trigger="manual", reason="schema-test")
        finally:
            obs_flight.disable()
        obs.record("counters", **obs.REGISTRY.snapshot())
    events = obs.read_events(log, validate=True)  # raises on any drift
    assert {e["kind"] for e in events} == {
        "manifest", "stage_end", "jit_trace", "sentinel", "clip", "epoch",
        "watchdog", "bench_result", "fault", "recovery", "degraded",
        "run_start", "run_resume", "interrupted", "warning", "span",
        "flight", "counters",
    }


def test_read_events_rejects_schema_drift(tmp_path):
    log = tmp_path / "bad.jsonl"
    log.write_text('{"t": 1.0, "kind": "martian", "stage": null, "attrs": {}}\n')
    with pytest.raises(ValueError, match="martian"):
        obs.read_events(log)
    log.write_text("not json\n")
    with pytest.raises(ValueError, match="not valid JSON"):
        obs.read_events(log)


# -- rotation (the size-bounded JSONL satellite) ----------------------------
def test_recorder_rotation_spans_segments(tmp_path):
    """A size-capped log rotates atomically (events.jsonl → events.N.jsonl)
    and read_events transparently spans the segments in order."""
    from disco_tpu.obs.events import rotated_segments

    log = tmp_path / "events.jsonl"
    with obs.recording(log, max_bytes=220):
        for i in range(30):
            obs.record("note", i=i)
    segs = rotated_segments(log)
    assert len(segs) >= 2, "no rotation at a 220-byte cap over 30 events"
    assert segs[0].name == "events.1.jsonl"
    # nothing lost, order preserved across every seam
    events = obs.read_events(log)
    assert [e["attrs"]["i"] for e in events] == list(range(30))
    # the bound holds per segment (one in-flight line of slack)
    for seg in segs:
        assert seg.stat().st_size <= 220 + 120


def test_recorder_rotation_schema_validates_and_appends_fresh(tmp_path):
    """Re-enabling onto a rotated path keeps counting segments upward
    instead of clobbering the history."""
    from disco_tpu.obs.events import rotated_segments

    log = tmp_path / "events.jsonl"
    with obs.recording(log, max_bytes=150):
        for i in range(6):
            obs.record("note", i=i)
    n0 = len(rotated_segments(log))
    assert n0 >= 1
    with obs.recording(log, max_bytes=150):
        for i in range(6, 12):
            obs.record("note", i=i)
    assert len(rotated_segments(log)) > n0
    assert [e["attrs"]["i"] for e in obs.read_events(log)] == list(range(12))


def test_read_events_tolerates_torn_rotation_seam(tmp_path):
    """A crash mid-append leaves a torn final line; after rotation that
    tear sits at a segment seam and must be skipped — while a torn line in
    the LIVE file (or mid-segment) still raises."""
    good0 = '{"t": 1.0, "kind": "note", "stage": null, "attrs": {"i": 0}}'
    good1 = '{"t": 3.0, "kind": "note", "stage": null, "attrs": {"i": 1}}'
    torn = '{"t": 2.0, "kind": "no'
    log = tmp_path / "events.jsonl"
    (tmp_path / "events.1.jsonl").write_text(good0 + "\n" + torn)
    log.write_text(good1 + "\n")
    events = obs.read_events(log)
    assert [e["attrs"]["i"] for e in events] == [0, 1]
    # mid-segment corruption is NOT a seam tear: still an error
    (tmp_path / "events.1.jsonl").write_text(torn + "\n" + good0 + "\n")
    with pytest.raises(ValueError, match="not valid JSON"):
        obs.read_events(log)
    # and the live file keeps the strict contract
    log.write_text(torn + "\n")
    (tmp_path / "events.1.jsonl").unlink()
    with pytest.raises(ValueError, match="not valid JSON"):
        obs.read_events(log)


# -- causal tracing (obs.trace) ---------------------------------------------
def test_trace_disabled_is_strict_noop():
    from disco_tpu.obs import trace as obs_trace

    assert not obs_trace.enabled()
    assert obs_trace.root("client_block") is None
    ctx = obs_trace.SpanCtx(trace="t" * 16, span="s" * 16)
    assert obs_trace.span("enqueue", ctx) is ctx  # unchanged, unrecorded
    assert obs_trace.span("enqueue", None) is None


def test_trace_from_wire_rejects_malformed_headers():
    """A malformed trace header must degrade to untraced, never raise —
    the pre-span back-compat contract at the protocol seam."""
    from disco_tpu.obs import trace as obs_trace

    assert obs_trace.from_wire(None) is None
    assert obs_trace.from_wire("nope") is None
    assert obs_trace.from_wire({"trace": 3, "span": "s"}) is None
    assert obs_trace.from_wire({"trace": "", "span": "s"}) is None
    assert obs_trace.from_wire({"trace": "x" * 99, "span": "s"}) is None
    ctx = obs_trace.from_wire({"trace": "abc", "span": "def"})
    assert ctx.trace == "abc" and ctx.span == "def"


def test_trace_chain_reconstruction_and_verification(tmp_path):
    from disco_tpu.obs import trace as obs_trace

    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs_trace.enable()
        try:
            ctx = obs_trace.root("client_block", seq=0, session="sA")
            ctx = obs_trace.span("enqueue", ctx, session="sA", seq=0)
            # a failed attempt forks off the chain; the retry re-chains
            # from the same parent and the walk keeps only the survivors
            obs_trace.span("dispatch", ctx, failed=True, error="boom")
            ctx = obs_trace.span("dispatch", ctx, tick=3, wait_ms=1.5)
            ctx = obs_trace.span("readback", ctx, tick=3, readback_ms=2.0)
            ctx = obs_trace.span("deliver", ctx, session="sA", seq=0,
                                 latency_ms=4.0)
        finally:
            obs_trace.disable()
    events = obs.read_events(log)
    (tid,) = obs_trace.trace_ids(events)
    path = obs_trace.verify_chain(
        events, tid,
        require=("client_block", "enqueue", "dispatch", "readback", "deliver"))
    assert [e["stage"] for e in path] == [
        "client_block", "enqueue", "dispatch", "readback", "deliver"]
    assert not path[2]["attrs"].get("failed")  # the fork is off the path
    # waterfall renders every hop + the attribution fields
    art = obs_trace.render_waterfall(events, tid)
    for token in ("client_block", "queue-wait=1.50ms", "readback=2.00ms",
                  "latency=4.00ms", "session=sA"):
        assert token in art, art
    # a chain missing its terminal hop fails loudly
    with pytest.raises(ValueError, match="no 'tap' span"):
        obs_trace.verify_chain(events, tid, require=("enqueue", "tap"))
    with pytest.raises(ValueError, match="no span events"):
        obs_trace.chain(events, "not-a-trace")


def test_trace_cross_process_chain_stops_at_enqueue(tmp_path):
    """A server-side log whose enqueue hop names a client-process root
    (never recorded here) still reconstructs — the chain legitimately
    starts at enqueue; a dangling parent anywhere else still raises."""
    from disco_tpu.obs import trace as obs_trace

    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs_trace.enable()
        try:
            remote = obs_trace.SpanCtx(trace=obs_trace.new_id(),
                                       span=obs_trace.new_id())
            ctx = obs_trace.span("enqueue", remote, session="sB", seq=0)
            ctx = obs_trace.span("dispatch", ctx, tick=1)
        finally:
            obs_trace.disable()
    events = obs.read_events(log)
    path = obs_trace.chain(events, remote.trace)
    assert [e["stage"] for e in path] == ["enqueue", "dispatch"]
    # drop the enqueue span: dispatch's dangling parent must now raise
    broken = [e for e in events if e["stage"] != "enqueue"]
    with pytest.raises(ValueError, match="broken chain"):
        obs_trace.chain(broken, remote.trace)


def test_obs_cli_trace_lists_and_renders(tmp_path, capsys):
    from disco_tpu.obs import trace as obs_trace

    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs_trace.enable()
        try:
            ctx = obs_trace.root("client_block", seq=0, session="sC")
            ctx = obs_trace.span("enqueue", ctx, session="sC", seq=0)
            ctx = obs_trace.span("dispatch", ctx, tick=1, wait_ms=0.5)
        finally:
            obs_trace.disable()
    ids = obs_cli.main(["trace", str(log)])
    out = capsys.readouterr().out
    assert len(ids) == 1 and ids[0] in out and "session=sC" in out
    obs_cli.main(["trace", str(log), ids[0]])
    out = capsys.readouterr().out
    assert "client_block" in out and "waterfall" in out


# -- flight recorder (obs.flight) -------------------------------------------
def test_flight_ring_bounded_and_collects_without_recorder(tmp_path):
    """The ring collects events with the JSONL sink OFF (that is the
    point: post-mortems without foresight), bounded per subsystem."""
    import json as json_mod

    from disco_tpu.obs import flight as obs_flight

    assert not obs.enabled()
    obs_flight.enable(dump_dir=tmp_path, capacity=8)
    try:
        for i in range(50):
            obs.record("note", stage="subsys", i=i)
        snap = obs_flight.flight().snapshot()
        assert len(snap["subsys"]) == 8
        assert [e["attrs"]["i"] for e in snap["subsys"]] == list(range(42, 50))
        a = obs_flight.dump(tmp_path / "a.json", trigger="manual", reason="t")
        b = obs_flight.dump(tmp_path / "b.json", trigger="manual", reason="t")
        # byte-stable: same ring state, identical bytes
        assert a.read_bytes() == b.read_bytes()
        payload = json_mod.loads(a.read_text())
        assert payload["trigger"] == "manual"
        assert [e["attrs"]["i"] for e in payload["subsystems"]["subsys"]] \
            == list(range(42, 50))
    finally:
        obs_flight.disable()
    # disarmed: strict no-op again
    assert obs_flight.auto_dump("quarantine") is None
    assert obs.record("note", i=0) is None


def test_flight_auto_dump_names_trigger_and_records_event(tmp_path):
    from disco_tpu.obs import flight as obs_flight

    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs_flight.enable(dump_dir=tmp_path / "dumps")
        try:
            obs.record("warning", stage="serve", reason="context")
            p1 = obs_flight.auto_dump("quarantine", reason="s1 strike 1")
            p2 = obs_flight.auto_dump("watchdog", reason="tick 9")
        finally:
            obs_flight.disable()
    assert p1.name == "flight-0001-quarantine.json"
    assert p2.name == "flight-0002-watchdog.json"
    flights = [e for e in obs.read_events(log) if e["kind"] == "flight"]
    assert [e["attrs"]["trigger"] for e in flights] == ["quarantine", "watchdog"]
    assert obs.REGISTRY.peek_counter("flight_dumps") >= 2


def test_flight_dump_without_dir_is_none_and_sentinel_trips_dump(tmp_path):
    """auto_dump without a dump dir is a no-op; a sentinel trip triggers a
    dump when armed with one (the sentinel → flight wiring)."""
    from disco_tpu.obs import flight as obs_flight

    obs_flight.enable()   # ring only, no dir
    try:
        assert obs_flight.auto_dump("sentinel") is None
    finally:
        obs_flight.disable()
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs_flight.enable(dump_dir=tmp_path / "d")
        try:
            # a whole diverged pytree trips ONCE: one dump per check, one
            # reason naming every bad leaf — not one ring write per leaf
            obs.check_finite("state", (jnp.asarray([np.nan]),
                                       jnp.ones(3),
                                       jnp.asarray([np.inf])), stage="mwf")
        finally:
            obs_flight.disable()
    dumps = list((tmp_path / "d").glob("flight-*-sentinel.json"))
    assert len(dumps) == 1
    assert "state[0], state[2]" in json.loads(dumps[0].read_text())["reason"]


def test_check_finite_runs_in_flight_only_mode(tmp_path):
    """The post-mortem-without-foresight mode: --flight-dir with NO
    --obs-log must still run the sentinels and dump on a trip (check_finite
    gates on events.active(), not the JSONL-only enabled())."""
    from disco_tpu.obs import flight as obs_flight

    assert not obs.enabled()
    obs_flight.enable(dump_dir=tmp_path / "d")
    try:
        assert obs.check_finite("bad", jnp.asarray([np.nan]),
                                stage="mwf") is False
    finally:
        obs_flight.disable()
    dumps = list((tmp_path / "d").glob("flight-*-sentinel.json"))
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    # the sentinel event itself is in the dumped ring (mwf subsystem)
    kinds = [e["kind"] for e in payload["subsystems"].get("mwf", [])]
    assert "sentinel" in kinds


# -- sentinels under the bf16 lane (PR 9) -----------------------------------
def test_check_finite_bf16_carries_precision_and_f32_stats(tmp_path):
    """The bf16 compute lane's sentinel story: the event names the active
    precision, and the tensor stats use f32 accumulators — a bf16 mean
    over 4096 ones would stick near 256/4096 (8-bit mantissa), f32 gives
    exactly 1.0."""
    log = tmp_path / "run.jsonl"
    bad = np.concatenate([[np.nan], np.ones(4095, np.float32)])
    with obs.recording(log):
        x = jnp.asarray(bad, dtype=jnp.bfloat16)
        assert obs.check_finite("step2_yf", x, stage="mwf",
                                precision="bf16") is False
        # clean bf16 tensor: no trip, still no error from the cast path
        assert obs.check_finite("clean", jnp.ones(16, jnp.bfloat16),
                                precision="bf16") is True
    (ev,) = [e for e in obs.read_events(log) if e["kind"] == "sentinel"]
    assert ev["attrs"]["precision"] == "bf16"
    assert ev["attrs"]["dtype"] == "bfloat16"
    assert ev["attrs"]["n_nan"] == 1
    assert ev["attrs"]["finite_mean"] == 1.0
    assert ev["attrs"]["finite_absmax"] == 1.0


def test_check_finite_f32_has_no_precision_attr(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.check_finite("y", jnp.asarray([np.inf]), stage="mwf")
    (ev,) = [e for e in obs.read_events(log) if e["kind"] == "sentinel"]
    assert "precision" not in ev["attrs"]


# -- metrics registry -------------------------------------------------------
def test_registry_counters_gauges_histograms():
    reg = obs.REGISTRY
    base = reg.counter("t_counter").value
    reg.counter("t_counter").inc()
    reg.counter("t_counter").inc(4)
    assert reg.counter("t_counter").value == base + 5
    reg.gauge("t_gauge").set(2.5)
    reg.histogram("t_hist").observe(1.0)
    reg.histogram("t_hist").observe(3.0)
    snap = reg.snapshot()
    assert snap["counters"]["t_counter"] == base + 5
    assert snap["gauges"]["t_gauge"] == 2.5
    h = snap["histograms"]["t_hist"]
    assert h["count"] == 2 and h["min"] == 1.0 and h["max"] == 3.0 and h["mean"] == 2.0
    pretty = reg.pretty()
    assert "t_counter" in pretty and "t_gauge" in pretty and "t_hist" in pretty


def test_histogram_percentiles_on_known_samples():
    """p50/p95/p99 pin against numpy's linear-interpolation definition —
    the numbers `disco-obs report` renders for serve request latency."""
    from disco_tpu.obs.metrics import Histogram

    h = Histogram("t")
    values = list(range(1, 101))
    for v in values:
        h.observe(float(v))
    s = h.summary()
    for q, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        assert s[key] == pytest.approx(float(np.percentile(values, q)))
        assert h.percentile(q) == pytest.approx(float(np.percentile(values, q)))
    assert h.percentile(0) == 1.0 and h.percentile(100) == 100.0
    # one sample: every percentile IS that sample; empty: None, not a crash
    h1 = Histogram("one")
    h1.observe(7.0)
    assert h1.summary()["p50"] == 7.0 == h1.summary()["p99"]
    empty = Histogram("none").summary()
    assert empty["p50"] is None and empty["p95"] is None


def test_histogram_reservoir_bounded_and_reset():
    """A long-lived serving process must not grow histogram memory without
    bound: retained samples cap at RESERVOIR_SIZE, the estimate stays sane,
    and reset() zeroes in place."""
    from disco_tpu.obs.metrics import RESERVOIR_SIZE, Histogram

    h = Histogram("t")
    n = 3 * RESERVOIR_SIZE
    for i in range(n):
        h.observe(float(i % 100))
    assert h.count == n and h.total == sum(float(i % 100) for i in range(n))
    assert len(h._samples) == RESERVOIR_SIZE
    assert 30.0 <= h.percentile(50.0) <= 70.0  # uniform-subsample estimate
    h.reset()
    assert h.count == 0 and h.percentile(50.0) is None
    assert h.summary()["p95"] is None


def test_registry_reset_keeps_module_bindings_live():
    """reset() zeroes in place: the fence counter bound at accounting import
    time must keep counting after a reset."""
    from disco_tpu.obs import accounting

    obs.fence_tick()
    obs.REGISTRY.reset()
    assert obs.fence_count() == 0
    obs.fence_tick()
    assert obs.fence_count() == 1 == accounting._FENCES.value


# -- accounting -------------------------------------------------------------
def test_fence_accounting_via_milestones_fence():
    from disco_tpu.milestones import _fence

    n0 = obs.fence_count()
    _fence(jnp.ones(3))
    _fence(jnp.asarray([1j + 1.0]))  # complex goes through jnp.real
    assert obs.fence_count() == n0 + 2
    assert obs.rpc_overhead_s(2) == pytest.approx(0.16)  # 2 x ~80 ms


def test_counted_jit_counts_retraces(tmp_path):
    log = tmp_path / "run.jsonl"
    calls = []

    @obs.counted_jit(label="fn_under_test")
    def f(x):
        calls.append(1)
        return x * 2

    n0 = obs.recompile_count()
    with obs.recording(log):
        np.testing.assert_allclose(f(jnp.ones(3)), 2 * np.ones(3))
        f(jnp.ones(3))          # cache hit: no event
        f(jnp.ones((2, 2)))     # new shape: retrace
    assert obs.recompile_count() == n0 + 2
    assert len(calls) == 2  # traced twice, dispatched three times
    events = [e for e in obs.read_events(log) if e["kind"] == "jit_trace"]
    assert len(events) == 2
    assert all(e["stage"] == "fn_under_test" for e in events)


def test_counted_jit_supports_static_argnames_and_lower():
    @obs.counted_jit(label="s", static_argnames=("k",))
    def g(x, k=2):
        return x * k

    np.testing.assert_allclose(g(jnp.ones(2), k=3), 3 * np.ones(2))
    assert g.lower(jnp.ones(2), k=3).compile() is not None


# -- sentinels --------------------------------------------------------------
def test_check_finite_disabled_is_noop_and_true():
    assert obs.check_finite("x", jnp.asarray([np.nan])) is True  # opt-in


def test_check_finite_records_offending_stage_and_stats(tmp_path):
    log = tmp_path / "run.jsonl"
    bad = np.ones((4, 8), np.float32)
    bad[1, 3] = np.nan
    bad[2, 5] = np.inf
    with obs.recording(log):
        assert obs.check_finite("clean", jnp.ones((3, 3))) is True
        assert obs.check_finite("post_mwf", jnp.asarray(bad), stage="mwf") is False
        # complex input: non-finite in either component trips
        zbad = np.ones(4, np.complex64)
        zbad[0] = np.nan + 1j
        assert obs.check_finite("z", jnp.asarray(zbad), stage="stft") is False
    events = [e for e in obs.read_events(log) if e["kind"] == "sentinel"]
    assert len(events) == 2
    ev = events[0]
    assert ev["stage"] == "mwf" and ev["attrs"]["name"] == "post_mwf"
    assert ev["attrs"]["n_nonfinite"] == 2
    assert ev["attrs"]["n_nan"] == 1 and ev["attrs"]["n_inf"] == 1
    assert ev["attrs"]["shape"] == [4, 8]
    assert ev["attrs"]["finite_absmax"] == 1.0
    assert events[1]["stage"] == "stft"


def test_check_finite_pytree_names_leaves(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        ok = obs.check_finite(
            "masks", (jnp.ones(3), jnp.asarray([np.inf])), stage="masks"
        )
    assert ok is False
    (ev,) = [e for e in obs.read_events(log) if e["kind"] == "sentinel"]
    assert ev["attrs"]["name"] == "masks[1]"


# -- deprecation shim -------------------------------------------------------
def test_utils_profiling_shim_warns_and_reexports():
    import importlib

    import disco_tpu.utils.profiling as prof

    with pytest.warns(DeprecationWarning, match="disco_tpu.obs"):
        importlib.reload(prof)
    from disco_tpu.obs.metrics import StageTimer

    assert prof.StageTimer is StageTimer


# -- obs CLI: report --------------------------------------------------------
def _synthetic_log(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.write_manifest(config={"rir": 1}, tool="test")
        for name, dur in (("stft", 0.01), ("masks", 0.002), ("mwf", 0.05),
                          ("istft", 0.004)):
            obs.record("stage_end", stage=name, dur_s=dur, fences=1)
        obs.record("stage_end", stage="mwf", dur_s=0.03, fences=2)
        obs.record("jit_trace", stage="run_batch", n_new_programs=1)
        obs.record("sentinel", stage="mwf", name="yf", n_nonfinite=3,
                   shape=[2, 2], n_nan=3, n_inf=0)
        obs.record("clip", rir=1, noise="ssn")
        obs.record("counters", **obs.REGISTRY.snapshot())
    return log


def test_obs_report_renders_stage_table_and_fences(tmp_path, capsys):
    log = _synthetic_log(tmp_path)
    summary = obs_cli.main(["report", str(log)])
    out = capsys.readouterr().out
    # stage totals: two mwf events aggregate
    assert summary["stages"]["mwf"] == pytest.approx(
        {"calls": 2, "total_s": 0.08, "fences": 3, "mean_s": 0.04}
    )
    assert summary["n_fences"] >= 6
    assert summary["est_rpc_s"] == pytest.approx(summary["n_fences"] * 0.08)
    assert summary["clips"] == 1
    # the per-label recompile table may carry OTHER labels too (the
    # counters snapshot is the live process registry — earlier counted_jit
    # tests legitimately appear), so pin the run_batch row, not the table
    for token in ("stft", "masks", "mwf", "istft", "fences:", "SENTINEL",
                  "recompiled programs"):
        assert token in out, token
    (row,) = [ln for ln in out.splitlines() if ln.startswith("run_batch ")]
    assert row.split()[-1] == "1"


def test_obs_report_serve_section(tmp_path, capsys):
    """Session lifecycle events + the serve counters/gauges/histogram from
    the final snapshot render as a serve section with latency percentiles."""
    log = tmp_path / "serve.jsonl"
    with obs.recording(log):
        obs.record("session", stage="serve", action="open", session="s1")
        obs.record("session", stage="serve", action="open", session="s2")
        obs.record("session", stage="serve", action="evict", session="s2",
                   reason="slow client")
        obs.record("session", stage="serve", action="close", session="s1", blocks=8)
        obs.record("session", stage="serve", action="drain", n_checkpointed=0)
        obs.record(
            "counters",
            counters={"serve_ticks": 5, "serve_blocks": 40,
                      "admission_reject": 1, "session_evicted": 1},
            gauges={"sessions_active": 0.0, "queue_depth": 0.0,
                    "batch_occupancy": 0.25},
            histograms={"serve_block_latency_ms": {
                "count": 40, "total": 800.0, "mean": 20.0, "min": 5.0,
                "max": 80.0, "p50": 18.0, "p95": 60.0, "p99": 75.0}},
        )
    summary = obs_cli.main(["report", str(log)])
    out = capsys.readouterr().out
    sv = summary["serve"]
    assert sv["sessions"] == {"open": 2, "evict": 1, "close": 1, "drain": 1}
    assert sv["admission_reject"] == 1 and sv["session_evicted"] == 1
    assert sv["serve_blocks"] == 40 and sv["serve_ticks"] == 5
    assert sv["latency_ms"]["p95"] == 60.0
    for token in ("serve sessions:", "open×2", "admission rejects=1",
                  "evictions=1", "p50=18", "p95=60", "p99=75",
                  "serve_block_latency_ms"):
        assert token in out, token


def test_obs_report_without_serve_events_has_no_serve_section(tmp_path):
    log = tmp_path / "plain.jsonl"
    with obs.recording(log):
        obs.record("stage_end", stage="stft", dur_s=0.01, fences=1)
    assert obs_cli.summarize(obs.read_events(log))["serve"] is None


# -- obs CLI: compare -------------------------------------------------------
def _bench_record(rtf):
    return {
        "metric": "rtf_8node_mwf_enhancement", "value": rtf,
        "unit": "x_realtime", "value_single_dispatch": rtf * 0.7,
        "stage_ms": {"full_pipeline": 1280e3 / rtf},
    }


def test_obs_compare_flags_ten_percent_regression(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_bench_record(6700.0)))
    new.write_text(json.dumps(_bench_record(6030.0)))  # -10%
    with pytest.raises(SystemExit) as exc:
        obs_cli.main(["compare", str(old), str(new)])
    assert exc.value.code == 1
    assert "VERDICT: REGRESSION" in capsys.readouterr().out


def test_obs_compare_ok_within_noise_and_improved(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_bench_record(6700.0)))
    new.write_text(json.dumps(_bench_record(6710.0)))
    diff = obs_cli.main(["compare", str(old), str(new)])
    assert diff["verdict"] == "OK"
    old2 = tmp_path / "old2.json"
    old2.write_text(json.dumps(_bench_record(5000.0)))
    diff = obs_cli.main(["compare", str(old2), str(new)])
    assert diff["verdict"] == "IMPROVED"
    assert "VERDICT" in capsys.readouterr().out


def test_obs_compare_refuses_cross_backend_records(tmp_path, capsys):
    """The BENCH_r06 hazard closed: a CPU-fallback candidate must never be
    judged against an on-TPU baseline — compare REFUSES (exit 2, distinct
    from the regression exit 1) instead of reporting a bogus verdict."""
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    rec = _bench_record(6700.0)
    rec["backend"] = "axon"
    old.write_text(json.dumps(rec))
    rec = _bench_record(6700.0)
    rec["backend"] = "cpu"
    new.write_text(json.dumps(rec))
    with pytest.raises(SystemExit) as exc:
        obs_cli.main(["compare", str(old), str(new)])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "refusing to judge" in err and "axon" in err and "cpu" in err


def test_obs_compare_refuses_cpu_fallback_against_committed_r05(tmp_path, capsys):
    """The COMMITTED on-TPU r05 baseline (its parsed record carries
    backend='axon' — provenance the run's own stderr tail logged) must
    refuse a CPU-fallback candidate with exit 2: the exact BENCH_r06
    hazard of a session without the 'axon' backend producing a
    CPU-degraded record that would otherwise read as a catastrophic
    regression against the on-TPU trajectory."""
    root = Path(__file__).resolve().parents[1]
    r05 = json.loads((root / "BENCH_r05.json").read_text())
    assert r05["parsed"]["backend"] == "axon"   # the annotation under test
    cand = tmp_path / "r06_cpu_fallback.json"
    rec = _bench_record(3.2)                    # CPU-speed "regression"
    rec["backend"] = "cpu"
    cand.write_text(json.dumps(rec))
    with pytest.raises(SystemExit) as exc:
        obs_cli.main(["compare", str(root / "BENCH_r05.json"), str(cand)])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "refusing to judge" in err and "axon" in err and "cpu" in err


def test_obs_compare_backend_judged_when_matching_or_legacy(tmp_path):
    """Same backend on both sides is judged normally, and records from
    before the field existed (BENCH_r01–r05) carry no claim: comparisons
    against them stay judged — obs-check's committed-trajectory invocation
    must not start failing."""
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    rec = _bench_record(6700.0)
    rec["backend"] = "axon"
    old.write_text(json.dumps(rec))
    new.write_text(json.dumps(rec))
    assert obs_cli.main(["compare", str(old), str(new)])["verdict"] == "OK"
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps(_bench_record(6700.0)))   # no backend field
    assert obs_cli.main(["compare", str(legacy), str(new)])["verdict"] == "OK"
    assert obs_cli.main(["compare", str(new), str(legacy)])["verdict"] == "OK"


def test_obs_compare_reads_bench_r_wrappers_and_null_candidate(tmp_path):
    """The committed BENCH_r04→r05 trajectory must read as OK (this is the
    exact invocation `make obs-check` gates CI with), and a null candidate
    value must be a REGRESSION, not a crash."""
    root = Path(__file__).resolve().parents[1]
    diff = obs_cli.main(
        ["compare", str(root / "BENCH_r04.json"), str(root / "BENCH_r05.json")]
    )
    assert diff["verdict"] == "OK"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"metric": "rtf", "value": None}))
    with pytest.raises(SystemExit):
        obs_cli.main(["compare", str(root / "BENCH_r04.json"), str(bad)])


def test_obs_compare_serve_lane_judged_only_with_baseline(tmp_path):
    """serve_blocks_per_s: same rule as the corpus lane — judged only when
    the baseline carries it (pre-serve records must not flag), a candidate
    that lost the measured lane is a REGRESSION, and an improved lane can
    lift an otherwise-OK verdict."""
    def rec(path, rtf, serve=None, p95=None):
        d = _bench_record(rtf)
        if serve is not None:
            d["serve_blocks_per_s"] = serve
            d["serve_p95_ms"] = p95
        p = tmp_path / path
        p.write_text(json.dumps(d))
        return str(p)

    old = rec("old.json", 6700.0, serve=100.0, p95=40.0)
    with pytest.raises(SystemExit):  # -20% serve throughput
        obs_cli.main(["compare", old, rec("slow.json", 6700.0, serve=80.0, p95=55.0)])
    with pytest.raises(SystemExit):  # lane lost entirely
        obs_cli.main(["compare", old, rec("lost.json", 6700.0)])
    diff = obs_cli.main(["compare", old, rec("fast.json", 6700.0, serve=120.0, p95=30.0)])
    assert diff["verdict"] == "IMPROVED"
    rows = {r["key"]: r for r in diff["rows"]}
    assert rows["serve_blocks_per_s"]["rel"] == pytest.approx(0.2)
    assert rows["serve_p95_ms"]["higher_is_better"] is False
    # baseline WITHOUT the lane: candidate's serve numbers ride along
    # unjudged
    pre = rec("pre.json", 6700.0)
    diff = obs_cli.main(["compare", pre, rec("cand.json", 6700.0, serve=50.0, p95=90.0)])
    assert diff["verdict"] == "OK"


def test_obs_compare_streaming_scan_lane_judged_like_serve(tmp_path):
    """streaming_rtf_scan: the amortized super-tick lane is judged exactly
    like the corpus/serve lanes — only when the baseline carries it, and a
    candidate that lost the measured lane is a REGRESSION."""
    def rec(path, rtf, scan=None):
        d = _bench_record(rtf)
        if scan is not None:
            d["streaming_rtf_scan"] = scan
        p = tmp_path / path
        p.write_text(json.dumps(d))
        return str(p)

    old = rec("old.json", 6700.0, scan=100.0)
    with pytest.raises(SystemExit):  # -20% amortized streaming throughput
        obs_cli.main(["compare", old, rec("slow.json", 6700.0, scan=80.0)])
    with pytest.raises(SystemExit):  # lane lost entirely
        obs_cli.main(["compare", old, rec("lost.json", 6700.0)])
    diff = obs_cli.main(["compare", old, rec("fast.json", 6700.0, scan=130.0)])
    assert diff["verdict"] == "IMPROVED"
    # pre-scan baseline: candidate's lane rides along unjudged
    diff = obs_cli.main(["compare", rec("pre.json", 6700.0),
                         rec("cand.json", 6700.0, scan=50.0)])
    assert diff["verdict"] == "OK"


def test_obs_compare_span_overhead_floor_gates_noise(tmp_path):
    """span_overhead_ns: judged lower-is-better like a latency lane, but
    with an absolute floor — nanosecond noise around the ≈0 disabled cost
    never flags, a real (>1 µs) blow-up does, and a lost measured lane is
    still a REGRESSION."""
    def rec(path, span=None):
        d = _bench_record(6700.0)
        if span is not None:
            d["span_overhead_ns"] = span
        p = tmp_path / path
        p.write_text(json.dumps(d))
        return str(p)

    old = rec("old.json", span=100.0)
    # 8x worse but still under the 1 µs floor: noise, not a regression
    assert obs_cli.main(
        ["compare", old, rec("noise.json", span=800.0)])["verdict"] == "OK"
    with pytest.raises(SystemExit):  # a real overhead appeared
        obs_cli.main(["compare", old, rec("slow.json", span=5000.0)])
    with pytest.raises(SystemExit):  # measured lane lost entirely
        obs_cli.main(["compare", old, rec("lost.json")])
    # pre-span baseline: candidate's lane rides along unjudged
    assert obs_cli.main(
        ["compare", rec("pre.json"), rec("cand.json", span=5000.0)]
    )["verdict"] == "OK"


def test_obs_compare_reads_event_log_bench_result(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.record("bench_result", stage="bench", **_bench_record(6000.0))
    old = tmp_path / "old.json"
    old.write_text(json.dumps(_bench_record(6000.0)))
    diff = obs_cli.main(["compare", str(old), str(log)])
    assert diff["verdict"] == "OK"


# -- bench.py contract ------------------------------------------------------
def _canned_bench_corpus(**_):
    return 0.5, {"n_clips": 4, "clip_dur_s": 2.0, "prefetch_stall_ms": 12.0,
                 "readback_ms": 80.0, "overlap_efficiency": 0.97,
                 "batched_readbacks": 2}


def _canned_bench_serve(**_):
    return 120.0, 35.0, {"n_sessions": 4, "blocks_per_session": 8,
                         "block_frames": 16, "clip_dur_s": 4.0, "ticks": 10,
                         "p50_ms": 20.0, "p99_ms": 50.0,
                         "mean_blocks_per_tick": 3.2}


def _canned_bench_jax(**_):
    return {
        "rtf": 6700.0, "rtf_single_dispatch": 4900.0, "rtf_eigh": 4800.0,
        "rtf_jacobi": 3900.0, "jacobi_error": None,
        "rtf_covfused": 6800.0, "covfused_error": None,
        "dispatch_overhead_ms": 70.0, "flops_per_clip": 3.5e10, "mfu": 0.03,
        "stage_ms": {"full_pipeline": 190.0},
    }


def test_bench_single_json_line_stdout_with_obs_log(tmp_path, monkeypatch, capsys):
    """Tier-1 contract: with --obs-log the full event stream goes to the
    file and stdout stays EXACTLY one parseable JSON line."""
    import bench

    monkeypatch.setattr(bench, "bench_jax", _canned_bench_jax)
    monkeypatch.setattr(bench, "bench_streaming", lambda **_: (0.85, 16.0, 18.9))
    monkeypatch.setattr(bench, "bench_streaming_scan",
                        lambda **_: (95.0, 2.7, 0.125,
                                     {"blocks_per_dispatch": 8}))
    monkeypatch.setattr(bench, "bench_corpus", _canned_bench_corpus)
    monkeypatch.setattr(bench, "bench_serve", _canned_bench_serve)
    monkeypatch.setattr(bench, "bench_numpy", lambda **_: 3.0)
    log = tmp_path / "bench_events.jsonl"
    bench.main(["--obs-log", str(log)])
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1, out_lines
    record = json.loads(out_lines[0])
    assert record["metric"] == "rtf_8node_mwf_enhancement"
    assert record["value"] == 6700.0
    events = obs.read_events(log)  # schema-validating read
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "manifest"
    assert "bench_result" in kinds and "counters" in kinds
    stages = {e["stage"] for e in events if e["kind"] == "stage_end"}
    assert {"bench_jax", "bench_streaming", "bench_serve", "bench_numpy"} <= stages
    # the sideband mirrors the stdout record
    (br,) = [e for e in events if e["kind"] == "bench_result"]
    assert br["attrs"]["value"] == record["value"]
    # recorder released: bench.main disabled it on exit
    assert not obs.enabled()


def test_bench_stdout_unchanged_without_obs_log(monkeypatch, capsys):
    import bench

    monkeypatch.setattr(bench, "bench_jax", _canned_bench_jax)
    monkeypatch.setattr(bench, "bench_streaming", lambda **_: (0.85, 16.0, 18.9))
    monkeypatch.setattr(bench, "bench_streaming_scan",
                        lambda **_: (95.0, 2.7, 0.125,
                                     {"blocks_per_dispatch": 8}))
    monkeypatch.setattr(bench, "bench_corpus", _canned_bench_corpus)
    monkeypatch.setattr(bench, "bench_serve", _canned_bench_serve)
    monkeypatch.setattr(bench, "bench_numpy", lambda **_: 3.0)
    bench.main([])
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1
    record = json.loads(out_lines[0])
    assert record["vs_baseline"] == pytest.approx(6700.0 / 3.0, rel=0.01)
    # the corpus-mode metric of the pipelined engine rides the same line
    assert record["corpus_clips_per_s"] == 0.5
    assert record["corpus_pipeline"]["prefetch_stall_ms"] == 12.0
    # ... and so do the online-serving lane's numbers
    assert record["serve_blocks_per_s"] == 120.0
    assert record["serve_p95_ms"] == 35.0
    assert record["serve_sessions"]["n_sessions"] == 4


def test_bench_error_path_records_event_and_one_line(tmp_path, monkeypatch, capsys):
    import bench

    def boom(**_):
        raise RuntimeError("UNAVAILABLE: tunnel down")

    monkeypatch.setattr(bench, "bench_jax", boom)
    log = tmp_path / "err.jsonl"
    with pytest.raises(SystemExit) as exc:
        bench.main(["--obs-log", str(log)])
    assert exc.value.code == 2
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1
    assert "UNAVAILABLE" in json.loads(out_lines[0])["error"]
    events = obs.read_events(log)
    (br,) = [e for e in events if e["kind"] == "bench_result"]
    assert "UNAVAILABLE" in br["attrs"]["error"]
    assert not obs.enabled()


def test_bench_watchdog_emits_event_and_diagnostic_line(tmp_path, monkeypatch, capsys):
    """The watchdog diagnostic goes through the event schema (satellite):
    when it fires, a `watchdog` event lands in the log before the process
    exits, alongside the parseable stdout line."""
    import bench

    exited = threading.Event()
    monkeypatch.setattr(bench.os, "_exit", lambda code: exited.set())
    log = tmp_path / "wd.jsonl"
    obs.enable(log)
    done = bench._start_watchdog(0.05)
    assert exited.wait(5.0), "watchdog did not fire"
    done.set()
    time.sleep(0.05)  # let the thread finish its print
    out = capsys.readouterr().out
    (line,) = [l for l in out.splitlines() if l.strip()]
    assert json.loads(line)["value"] is None
    events = obs.read_events(log)
    (wd,) = [e for e in events if e["kind"] == "watchdog"]
    assert wd["stage"] == "bench"
    assert wd["attrs"]["timeout_s"] == pytest.approx(0.05)
    assert "counters" in wd["attrs"]  # final registry snapshot rides along


# -- training telemetry -----------------------------------------------------
def test_fit_records_epoch_events(tmp_path):
    from disco_tpu.nn.crnn import build_rnn
    from disco_tpu.nn.training import create_train_state, fit

    model, tx = build_rnn(n_ch=1, win_len=11, n_freq=17, rnn_units=(16,), ff_units=(17,))
    x = np.random.default_rng(0).standard_normal((4, 11, 17)).astype(np.float32)
    y = np.abs(np.random.default_rng(1).standard_normal((4, 11, 17))).astype(np.float32)
    state = create_train_state(model, tx, x[:1])

    def batches():
        yield x, y

    log = tmp_path / "train.jsonl"
    with obs.recording(log):
        fit(model, state, batches, batches, n_epochs=3,
            save_path=str(tmp_path / "m"), verbose=False)
    events = obs.read_events(log)
    epochs = [e for e in events if e["kind"] == "epoch"]
    assert [e["attrs"]["epoch"] for e in epochs] == [0, 1, 2]
    a = epochs[0]["attrs"]
    assert a["steps"] == 1 and np.isfinite(a["train_loss"]) and np.isfinite(a["val_loss"])
    # epoch 0 traces train+eval (and epoch 1 may retrace train_step once:
    # the init state's weak types canonicalize after the first
    # apply_gradients); by epoch 2 the programs must be cache-stable —
    # exactly the per-epoch recompile drift this event exists to expose.
    assert a["recompiles"] >= 2
    assert epochs[2]["attrs"]["recompiles"] == 0
    assert obs.REGISTRY.gauge("val_loss").value == pytest.approx(
        epochs[2]["attrs"]["val_loss"]
    )


def test_obs_compare_mfu_and_stage_lanes(tmp_path):
    """The hot-path-fusion lanes: mfu (higher better) and the two dominant
    stage_ms entries (lower better) are judged like the corpus/serve lanes —
    baseline-gated, lost-measured-lane = REGRESSION, inverted sign for the
    stage times."""
    def rec(path, rtf, mfu=None, stft=None, step2=None):
        d = _bench_record(rtf)
        if mfu is not None:
            d["mfu"] = mfu
        if stft is not None:
            d["stage_ms"]["stft_x3"] = stft
            d["stage_ms"]["step2_exchange_mwf"] = step2
        p = tmp_path / path
        p.write_text(json.dumps(d))
        return p

    base = rec("base.json", 6700.0, mfu=0.03, stft=57.6, step2=115.9)
    # a 2x stage-time REDUCTION with mfu up = IMPROVED (not a regression —
    # lower stage_ms is better)
    good = rec("good.json", 6710.0, mfu=0.11, stft=25.0, step2=50.0)
    assert obs_cli.main(["compare", str(base), str(good)])["verdict"] == "IMPROVED"
    # stage time BLOWING UP regresses even with the headline flat
    slow = rec("slow.json", 6710.0, mfu=0.03, stft=80.0, step2=115.9)
    with pytest.raises(SystemExit):
        obs_cli.main(["compare", str(base), str(slow)])
    # losing a measured mfu lane = REGRESSION
    lost = rec("lost.json", 6710.0, stft=57.6, step2=115.9)
    with pytest.raises(SystemExit):
        obs_cli.main(["compare", str(base), str(lost)])
    # a baseline without the lanes never judges them (pre-fusion records)
    old_base = rec("old_base.json", 6700.0)
    assert obs_cli.main(
        ["compare", str(old_base), str(lost)]
    )["verdict"] == "OK"


def test_obs_compare_per_stage_mfu_and_gbps_tables(tmp_path):
    """The meter round's per-stage efficiency tables: every
    ``mfu_by_stage.*`` / ``hbm_gbps_by_stage.*`` row the BASELINE carries
    is judged higher-is-better, a candidate that lost a measured stage
    lane is a REGRESSION, and pre-meter baselines (r01–r05) gate
    nothing."""
    def rec(path, rtf, mfu=None, gbps=None):
        d = _bench_record(rtf)
        if mfu is not None:
            d["mfu_by_stage"] = mfu
            d["hbm_gbps_by_stage"] = gbps
        p = tmp_path / path
        p.write_text(json.dumps(d))
        return str(p)

    base = rec("base.json", 6700.0,
               mfu={"stft_x3": 0.13, "full_pipeline": 0.03},
               gbps={"stft_x3": 106.0, "full_pipeline": 90.0})
    # one stage's efficiency collapsing flags even with the headline flat
    slow = rec("slow.json", 6700.0,
               mfu={"stft_x3": 0.05, "full_pipeline": 0.03},
               gbps={"stft_x3": 106.0, "full_pipeline": 90.0})
    with pytest.raises(SystemExit):
        obs_cli.main(["compare", base, slow])
    # a stage dropping OUT of the table is a REGRESSION, not a skip
    lost = rec("lost.json", 6700.0,
               mfu={"full_pipeline": 0.03}, gbps={"full_pipeline": 90.0})
    with pytest.raises(SystemExit):
        obs_cli.main(["compare", base, lost])
    # both tables up: IMPROVED, with the rows visible in the diff
    good = rec("good.json", 6700.0,
               mfu={"stft_x3": 0.20, "full_pipeline": 0.05},
               gbps={"stft_x3": 140.0, "full_pipeline": 120.0})
    diff = obs_cli.main(["compare", base, good])
    assert diff["verdict"] == "IMPROVED"
    rows = {r["key"]: r for r in diff["rows"]}
    assert rows["mfu_by_stage.stft_x3"]["higher_is_better"] is True
    assert rows["hbm_gbps_by_stage.full_pipeline"]["higher_is_better"] is True
    # a pre-meter baseline judges nothing: the candidate's tables ride along
    pre = rec("pre.json", 6700.0)
    assert obs_cli.main(["compare", pre, lost])["verdict"] == "OK"


def test_bench_record_carries_fused_kernel_fields(monkeypatch, capsys):
    """The ONE-JSON-line record documents the active fused kernels: the
    stft_impl/precision fields plus the bf16 error-reporting lane ride the
    line exactly like cov_impl does."""
    import bench

    canned = dict(_canned_bench_jax())
    canned.update({
        "cov_impl": "xla", "stft_impl": "xla", "precision": "f32",
        "rtf_bf16": 7200.0, "bf16_max_rel_err": 0.0021, "bf16_error": None,
    })
    monkeypatch.setattr(bench, "bench_jax", lambda **_: canned)
    monkeypatch.setattr(bench, "bench_streaming", lambda **_: (0.85, 16.0, 18.9))
    monkeypatch.setattr(bench, "bench_streaming_scan",
                        lambda **_: (95.0, 2.7, 0.125,
                                     {"blocks_per_dispatch": 8}))
    monkeypatch.setattr(bench, "bench_corpus", _canned_bench_corpus)
    monkeypatch.setattr(bench, "bench_serve", _canned_bench_serve)
    monkeypatch.setattr(bench, "bench_numpy", lambda **_: 3.0)
    bench.main([])
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1
    record = json.loads(out_lines[0])
    assert record["stft_impl"] == "xla"
    assert record["precision"] == "f32"
    assert record["rtf_bf16"] == 7200.0
    assert record["bf16_max_rel_err"] == 0.0021
    assert record["bf16_error"] is None


def test_bench_record_carries_fused_solve_lane_and_provenance(monkeypatch, capsys):
    """The solve-fusion round's record contract: rtf_fused_solver rides the
    line, and solver_lanes names each solve lane's resolved spec AND
    concrete impl (post-ops.resolve) so records distinguish jacobi XLA
    from pallas from the fused kernel without re-running."""
    import bench

    canned = dict(_canned_bench_jax())
    canned.update({
        "rtf_fused": 9100.0, "fused_error": None,
        "solver_lanes": {
            "rtf": {"spec": "power", "base": "power", "n": None, "impl": "xla"},
            "rtf_fused_solver": {"spec": "fused", "base": "fused", "n": None,
                                 "impl": "pallas"},
        },
    })
    monkeypatch.setattr(bench, "bench_jax", lambda **_: canned)
    monkeypatch.setattr(bench, "bench_streaming", lambda **_: (0.85, 16.0, 18.9))
    monkeypatch.setattr(bench, "bench_streaming_scan",
                        lambda **_: (95.0, 2.7, 0.125,
                                     {"blocks_per_dispatch": 8}))
    monkeypatch.setattr(bench, "bench_corpus", _canned_bench_corpus)
    monkeypatch.setattr(bench, "bench_serve", _canned_bench_serve)
    monkeypatch.setattr(bench, "bench_numpy", lambda **_: 3.0)
    bench.main([])
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1
    record = json.loads(out_lines[0])
    assert record["rtf_fused_solver"] == 9100.0
    assert record["fused_error"] is None
    assert record["solver_lanes"]["rtf_fused_solver"]["impl"] == "pallas"
    assert record["solver_lanes"]["rtf"]["spec"] == "power"
    # a failed lane still distinguishes "crashed" from "not measured"
    canned2 = dict(_canned_bench_jax())
    canned2.update({"rtf_fused": None, "fused_error": "XlaRuntimeError: boom"})
    monkeypatch.setattr(bench, "bench_jax", lambda **_: canned2)
    bench.main([])
    record2 = json.loads(
        [l for l in capsys.readouterr().out.splitlines() if l.strip()][0])
    assert record2["rtf_fused_solver"] is None
    assert "XlaRuntimeError" in record2["fused_error"]
