"""Tests for disco_tpu.obs — events/schema, metrics, fence/recompile
accounting, numerics sentinels, the obs CLI (report/compare), and bench.py's
one-JSON-line stdout contract with --obs-log enabled.

The JSONL schema tests double as the CI gate: `make obs-check` runs them
(`-k schema`), so any event-schema drift fails the build."""
import json
import os
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

from disco_tpu import obs
from disco_tpu.cli import obs as obs_cli

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # for bench.py


@pytest.fixture(autouse=True)
def _clean_recorder():
    """Every test starts and ends with recording off (the recorder is
    process-global)."""
    obs.disable()
    yield
    obs.disable()


# -- events / recorder ------------------------------------------------------
def test_recorder_disabled_is_noop(tmp_path):
    assert not obs.enabled()
    assert obs.record("note", msg="dropped") is None
    with obs.stage("never"):
        pass  # no recorder, no file, no error


def test_record_roundtrip_and_manifest(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        ev = obs.write_manifest(config={"solver": "power"}, tool="test")
        assert ev is not None
        obs.record("note", stage="s", msg="hello", value=3)
    events = obs.read_events(log)
    assert [e["kind"] for e in events] == ["manifest", "note"]
    man = events[0]["attrs"]
    # manifest carries provenance: git SHA, backend, devices, versions
    assert man["config"] == {"solver": "power"}
    assert man["platform"] == "cpu" and man["device_count"] == 8
    assert man["versions"]["jax"] and man["versions"]["numpy"]
    assert len(man["git_sha"]) == 40
    assert events[1]["attrs"] == {"msg": "hello", "value": 3}


def test_stage_records_duration_and_fences(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        with obs.stage("work", rir=7):
            obs.fence_tick(3)
            time.sleep(0.01)
    (ev,) = obs.read_events(log)
    assert ev["kind"] == "stage_end" and ev["stage"] == "work"
    assert ev["attrs"]["fences"] == 3 and ev["attrs"]["rir"] == 7
    assert ev["attrs"]["dur_s"] >= 0.01


def test_recorder_append_only_and_threadsafe(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        threads = [
            threading.Thread(target=lambda i=i: obs.record("note", i=i))
            for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    events = obs.read_events(log)
    assert sorted(e["attrs"]["i"] for e in events) == list(range(16))


def test_unserializable_attr_degrades_to_repr(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.record("note", obj=object())  # must not raise
    (ev,) = obs.read_events(log)
    assert "object" in ev["attrs"]["obj"]


# -- schema (run by `make obs-check` via -k schema) -------------------------
def test_event_schema_validation():
    good = {"t": 1.0, "kind": "note", "stage": None, "attrs": {}}
    obs.validate_event(good)
    with pytest.raises(ValueError, match="unknown event kind"):
        obs.validate_event({**good, "kind": "nope"})
    with pytest.raises(ValueError, match="missing key"):
        obs.validate_event({"kind": "note"})
    with pytest.raises(ValueError, match="'t' must be a number"):
        obs.validate_event({**good, "t": "late"})
    with pytest.raises(ValueError, match="'stage' must be a string"):
        obs.validate_event({**good, "stage": 3})
    with pytest.raises(ValueError, match="'attrs' must be an object"):
        obs.validate_event({**good, "attrs": []})


def test_emitted_log_conforms_to_schema(tmp_path):
    """Every event the instrumented pipeline emits must validate: exercise
    each producer once and re-read with validation on."""
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.write_manifest(config={"a": 1})
        with obs.stage("stft", rir=1):
            pass
        f = obs.counted_jit(lambda x: x + 1, label="unit")
        f(jnp.ones(3))
        obs.check_finite("bad", jnp.asarray([np.nan]), stage="mwf")
        obs.record("clip", rir=1, noise="ssn")
        obs.record("epoch", stage="train", epoch=0, train_loss=0.5, val_loss=0.6)
        obs.record("watchdog", stage="bench", timeout_s=1.0)
        obs.record("bench_result", stage="bench", value=1.0)
        # the fault-tolerance producers (disco_tpu.fault / utils.resilience)
        from disco_tpu.fault import FaultSpec, plan_faults
        from disco_tpu.utils.resilience import call_with_retries

        plan_faults(FaultSpec(node_dropout=(0,)), n_nodes=2).record(mode="offline")
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] == 1:
                raise OSError("transient")
            return 1

        call_with_retries(flaky, retries=1, base_delay_s=0.0, sleep=lambda _: None)
        obs.record("degraded", stage="mwf", mode="offline", nodes=[0])
        # the crash-safe runs producers (disco_tpu.runs)
        obs.record("run_start", stage="enhance", tool="test",
                   preflight={"ok": True, "dur_s": 0.01})
        obs.record("run_resume", stage="enhance", n_done=1, n_requeued=0)
        from disco_tpu.runs import GracefulInterrupt, request_stop

        with GracefulInterrupt():
            request_stop("schema-test")  # emits "interrupted"
        obs.record("warning", stage="load_input", reason="schema-test")
        obs.record("counters", **obs.REGISTRY.snapshot())
    events = obs.read_events(log, validate=True)  # raises on any drift
    assert {e["kind"] for e in events} == {
        "manifest", "stage_end", "jit_trace", "sentinel", "clip", "epoch",
        "watchdog", "bench_result", "fault", "recovery", "degraded",
        "run_start", "run_resume", "interrupted", "warning", "counters",
    }


def test_read_events_rejects_schema_drift(tmp_path):
    log = tmp_path / "bad.jsonl"
    log.write_text('{"t": 1.0, "kind": "martian", "stage": null, "attrs": {}}\n')
    with pytest.raises(ValueError, match="martian"):
        obs.read_events(log)
    log.write_text("not json\n")
    with pytest.raises(ValueError, match="not valid JSON"):
        obs.read_events(log)


# -- metrics registry -------------------------------------------------------
def test_registry_counters_gauges_histograms():
    reg = obs.REGISTRY
    base = reg.counter("t_counter").value
    reg.counter("t_counter").inc()
    reg.counter("t_counter").inc(4)
    assert reg.counter("t_counter").value == base + 5
    reg.gauge("t_gauge").set(2.5)
    reg.histogram("t_hist").observe(1.0)
    reg.histogram("t_hist").observe(3.0)
    snap = reg.snapshot()
    assert snap["counters"]["t_counter"] == base + 5
    assert snap["gauges"]["t_gauge"] == 2.5
    h = snap["histograms"]["t_hist"]
    assert h["count"] == 2 and h["min"] == 1.0 and h["max"] == 3.0 and h["mean"] == 2.0
    pretty = reg.pretty()
    assert "t_counter" in pretty and "t_gauge" in pretty and "t_hist" in pretty


def test_histogram_percentiles_on_known_samples():
    """p50/p95/p99 pin against numpy's linear-interpolation definition —
    the numbers `disco-obs report` renders for serve request latency."""
    from disco_tpu.obs.metrics import Histogram

    h = Histogram("t")
    values = list(range(1, 101))
    for v in values:
        h.observe(float(v))
    s = h.summary()
    for q, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        assert s[key] == pytest.approx(float(np.percentile(values, q)))
        assert h.percentile(q) == pytest.approx(float(np.percentile(values, q)))
    assert h.percentile(0) == 1.0 and h.percentile(100) == 100.0
    # one sample: every percentile IS that sample; empty: None, not a crash
    h1 = Histogram("one")
    h1.observe(7.0)
    assert h1.summary()["p50"] == 7.0 == h1.summary()["p99"]
    empty = Histogram("none").summary()
    assert empty["p50"] is None and empty["p95"] is None


def test_histogram_reservoir_bounded_and_reset():
    """A long-lived serving process must not grow histogram memory without
    bound: retained samples cap at RESERVOIR_SIZE, the estimate stays sane,
    and reset() zeroes in place."""
    from disco_tpu.obs.metrics import RESERVOIR_SIZE, Histogram

    h = Histogram("t")
    n = 3 * RESERVOIR_SIZE
    for i in range(n):
        h.observe(float(i % 100))
    assert h.count == n and h.total == sum(float(i % 100) for i in range(n))
    assert len(h._samples) == RESERVOIR_SIZE
    assert 30.0 <= h.percentile(50.0) <= 70.0  # uniform-subsample estimate
    h.reset()
    assert h.count == 0 and h.percentile(50.0) is None
    assert h.summary()["p95"] is None


def test_registry_reset_keeps_module_bindings_live():
    """reset() zeroes in place: the fence counter bound at accounting import
    time must keep counting after a reset."""
    from disco_tpu.obs import accounting

    obs.fence_tick()
    obs.REGISTRY.reset()
    assert obs.fence_count() == 0
    obs.fence_tick()
    assert obs.fence_count() == 1 == accounting._FENCES.value


# -- accounting -------------------------------------------------------------
def test_fence_accounting_via_milestones_fence():
    from disco_tpu.milestones import _fence

    n0 = obs.fence_count()
    _fence(jnp.ones(3))
    _fence(jnp.asarray([1j + 1.0]))  # complex goes through jnp.real
    assert obs.fence_count() == n0 + 2
    assert obs.rpc_overhead_s(2) == pytest.approx(0.16)  # 2 x ~80 ms


def test_counted_jit_counts_retraces(tmp_path):
    log = tmp_path / "run.jsonl"
    calls = []

    @obs.counted_jit(label="fn_under_test")
    def f(x):
        calls.append(1)
        return x * 2

    n0 = obs.recompile_count()
    with obs.recording(log):
        np.testing.assert_allclose(f(jnp.ones(3)), 2 * np.ones(3))
        f(jnp.ones(3))          # cache hit: no event
        f(jnp.ones((2, 2)))     # new shape: retrace
    assert obs.recompile_count() == n0 + 2
    assert len(calls) == 2  # traced twice, dispatched three times
    events = [e for e in obs.read_events(log) if e["kind"] == "jit_trace"]
    assert len(events) == 2
    assert all(e["stage"] == "fn_under_test" for e in events)


def test_counted_jit_supports_static_argnames_and_lower():
    @obs.counted_jit(label="s", static_argnames=("k",))
    def g(x, k=2):
        return x * k

    np.testing.assert_allclose(g(jnp.ones(2), k=3), 3 * np.ones(2))
    assert g.lower(jnp.ones(2), k=3).compile() is not None


# -- sentinels --------------------------------------------------------------
def test_check_finite_disabled_is_noop_and_true():
    assert obs.check_finite("x", jnp.asarray([np.nan])) is True  # opt-in


def test_check_finite_records_offending_stage_and_stats(tmp_path):
    log = tmp_path / "run.jsonl"
    bad = np.ones((4, 8), np.float32)
    bad[1, 3] = np.nan
    bad[2, 5] = np.inf
    with obs.recording(log):
        assert obs.check_finite("clean", jnp.ones((3, 3))) is True
        assert obs.check_finite("post_mwf", jnp.asarray(bad), stage="mwf") is False
        # complex input: non-finite in either component trips
        zbad = np.ones(4, np.complex64)
        zbad[0] = np.nan + 1j
        assert obs.check_finite("z", jnp.asarray(zbad), stage="stft") is False
    events = [e for e in obs.read_events(log) if e["kind"] == "sentinel"]
    assert len(events) == 2
    ev = events[0]
    assert ev["stage"] == "mwf" and ev["attrs"]["name"] == "post_mwf"
    assert ev["attrs"]["n_nonfinite"] == 2
    assert ev["attrs"]["n_nan"] == 1 and ev["attrs"]["n_inf"] == 1
    assert ev["attrs"]["shape"] == [4, 8]
    assert ev["attrs"]["finite_absmax"] == 1.0
    assert events[1]["stage"] == "stft"


def test_check_finite_pytree_names_leaves(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        ok = obs.check_finite(
            "masks", (jnp.ones(3), jnp.asarray([np.inf])), stage="masks"
        )
    assert ok is False
    (ev,) = [e for e in obs.read_events(log) if e["kind"] == "sentinel"]
    assert ev["attrs"]["name"] == "masks[1]"


# -- deprecation shim -------------------------------------------------------
def test_utils_profiling_shim_warns_and_reexports():
    import importlib

    import disco_tpu.utils.profiling as prof

    with pytest.warns(DeprecationWarning, match="disco_tpu.obs"):
        importlib.reload(prof)
    from disco_tpu.obs.metrics import StageTimer

    assert prof.StageTimer is StageTimer


# -- obs CLI: report --------------------------------------------------------
def _synthetic_log(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.write_manifest(config={"rir": 1}, tool="test")
        for name, dur in (("stft", 0.01), ("masks", 0.002), ("mwf", 0.05),
                          ("istft", 0.004)):
            obs.record("stage_end", stage=name, dur_s=dur, fences=1)
        obs.record("stage_end", stage="mwf", dur_s=0.03, fences=2)
        obs.record("jit_trace", stage="run_batch", n_new_programs=1)
        obs.record("sentinel", stage="mwf", name="yf", n_nonfinite=3,
                   shape=[2, 2], n_nan=3, n_inf=0)
        obs.record("clip", rir=1, noise="ssn")
        obs.record("counters", **obs.REGISTRY.snapshot())
    return log


def test_obs_report_renders_stage_table_and_fences(tmp_path, capsys):
    log = _synthetic_log(tmp_path)
    summary = obs_cli.main(["report", str(log)])
    out = capsys.readouterr().out
    # stage totals: two mwf events aggregate
    assert summary["stages"]["mwf"] == pytest.approx(
        {"calls": 2, "total_s": 0.08, "fences": 3, "mean_s": 0.04}
    )
    assert summary["n_fences"] >= 6
    assert summary["est_rpc_s"] == pytest.approx(summary["n_fences"] * 0.08)
    assert summary["clips"] == 1
    # the per-label recompile table may carry OTHER labels too (the
    # counters snapshot is the live process registry — earlier counted_jit
    # tests legitimately appear), so pin the run_batch row, not the table
    for token in ("stft", "masks", "mwf", "istft", "fences:", "SENTINEL",
                  "recompiled programs"):
        assert token in out, token
    (row,) = [ln for ln in out.splitlines() if ln.startswith("run_batch ")]
    assert row.split()[-1] == "1"


def test_obs_report_serve_section(tmp_path, capsys):
    """Session lifecycle events + the serve counters/gauges/histogram from
    the final snapshot render as a serve section with latency percentiles."""
    log = tmp_path / "serve.jsonl"
    with obs.recording(log):
        obs.record("session", stage="serve", action="open", session="s1")
        obs.record("session", stage="serve", action="open", session="s2")
        obs.record("session", stage="serve", action="evict", session="s2",
                   reason="slow client")
        obs.record("session", stage="serve", action="close", session="s1", blocks=8)
        obs.record("session", stage="serve", action="drain", n_checkpointed=0)
        obs.record(
            "counters",
            counters={"serve_ticks": 5, "serve_blocks": 40,
                      "admission_reject": 1, "session_evicted": 1},
            gauges={"sessions_active": 0.0, "queue_depth": 0.0,
                    "batch_occupancy": 0.25},
            histograms={"serve_block_latency_ms": {
                "count": 40, "total": 800.0, "mean": 20.0, "min": 5.0,
                "max": 80.0, "p50": 18.0, "p95": 60.0, "p99": 75.0}},
        )
    summary = obs_cli.main(["report", str(log)])
    out = capsys.readouterr().out
    sv = summary["serve"]
    assert sv["sessions"] == {"open": 2, "evict": 1, "close": 1, "drain": 1}
    assert sv["admission_reject"] == 1 and sv["session_evicted"] == 1
    assert sv["serve_blocks"] == 40 and sv["serve_ticks"] == 5
    assert sv["latency_ms"]["p95"] == 60.0
    for token in ("serve sessions:", "open×2", "admission rejects=1",
                  "evictions=1", "p50=18", "p95=60", "p99=75",
                  "serve_block_latency_ms"):
        assert token in out, token


def test_obs_report_without_serve_events_has_no_serve_section(tmp_path):
    log = tmp_path / "plain.jsonl"
    with obs.recording(log):
        obs.record("stage_end", stage="stft", dur_s=0.01, fences=1)
    assert obs_cli.summarize(obs.read_events(log))["serve"] is None


# -- obs CLI: compare -------------------------------------------------------
def _bench_record(rtf):
    return {
        "metric": "rtf_8node_mwf_enhancement", "value": rtf,
        "unit": "x_realtime", "value_single_dispatch": rtf * 0.7,
        "stage_ms": {"full_pipeline": 1280e3 / rtf},
    }


def test_obs_compare_flags_ten_percent_regression(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_bench_record(6700.0)))
    new.write_text(json.dumps(_bench_record(6030.0)))  # -10%
    with pytest.raises(SystemExit) as exc:
        obs_cli.main(["compare", str(old), str(new)])
    assert exc.value.code == 1
    assert "VERDICT: REGRESSION" in capsys.readouterr().out


def test_obs_compare_ok_within_noise_and_improved(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_bench_record(6700.0)))
    new.write_text(json.dumps(_bench_record(6710.0)))
    diff = obs_cli.main(["compare", str(old), str(new)])
    assert diff["verdict"] == "OK"
    old2 = tmp_path / "old2.json"
    old2.write_text(json.dumps(_bench_record(5000.0)))
    diff = obs_cli.main(["compare", str(old2), str(new)])
    assert diff["verdict"] == "IMPROVED"
    assert "VERDICT" in capsys.readouterr().out


def test_obs_compare_reads_bench_r_wrappers_and_null_candidate(tmp_path):
    """The committed BENCH_r04→r05 trajectory must read as OK (this is the
    exact invocation `make obs-check` gates CI with), and a null candidate
    value must be a REGRESSION, not a crash."""
    root = Path(__file__).resolve().parents[1]
    diff = obs_cli.main(
        ["compare", str(root / "BENCH_r04.json"), str(root / "BENCH_r05.json")]
    )
    assert diff["verdict"] == "OK"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"metric": "rtf", "value": None}))
    with pytest.raises(SystemExit):
        obs_cli.main(["compare", str(root / "BENCH_r04.json"), str(bad)])


def test_obs_compare_serve_lane_judged_only_with_baseline(tmp_path):
    """serve_blocks_per_s: same rule as the corpus lane — judged only when
    the baseline carries it (pre-serve records must not flag), a candidate
    that lost the measured lane is a REGRESSION, and an improved lane can
    lift an otherwise-OK verdict."""
    def rec(path, rtf, serve=None, p95=None):
        d = _bench_record(rtf)
        if serve is not None:
            d["serve_blocks_per_s"] = serve
            d["serve_p95_ms"] = p95
        p = tmp_path / path
        p.write_text(json.dumps(d))
        return str(p)

    old = rec("old.json", 6700.0, serve=100.0, p95=40.0)
    with pytest.raises(SystemExit):  # -20% serve throughput
        obs_cli.main(["compare", old, rec("slow.json", 6700.0, serve=80.0, p95=55.0)])
    with pytest.raises(SystemExit):  # lane lost entirely
        obs_cli.main(["compare", old, rec("lost.json", 6700.0)])
    diff = obs_cli.main(["compare", old, rec("fast.json", 6700.0, serve=120.0, p95=30.0)])
    assert diff["verdict"] == "IMPROVED"
    rows = {r["key"]: r for r in diff["rows"]}
    assert rows["serve_blocks_per_s"]["rel"] == pytest.approx(0.2)
    assert rows["serve_p95_ms"]["higher_is_better"] is False
    # baseline WITHOUT the lane: candidate's serve numbers ride along
    # unjudged
    pre = rec("pre.json", 6700.0)
    diff = obs_cli.main(["compare", pre, rec("cand.json", 6700.0, serve=50.0, p95=90.0)])
    assert diff["verdict"] == "OK"


def test_obs_compare_streaming_scan_lane_judged_like_serve(tmp_path):
    """streaming_rtf_scan: the amortized super-tick lane is judged exactly
    like the corpus/serve lanes — only when the baseline carries it, and a
    candidate that lost the measured lane is a REGRESSION."""
    def rec(path, rtf, scan=None):
        d = _bench_record(rtf)
        if scan is not None:
            d["streaming_rtf_scan"] = scan
        p = tmp_path / path
        p.write_text(json.dumps(d))
        return str(p)

    old = rec("old.json", 6700.0, scan=100.0)
    with pytest.raises(SystemExit):  # -20% amortized streaming throughput
        obs_cli.main(["compare", old, rec("slow.json", 6700.0, scan=80.0)])
    with pytest.raises(SystemExit):  # lane lost entirely
        obs_cli.main(["compare", old, rec("lost.json", 6700.0)])
    diff = obs_cli.main(["compare", old, rec("fast.json", 6700.0, scan=130.0)])
    assert diff["verdict"] == "IMPROVED"
    # pre-scan baseline: candidate's lane rides along unjudged
    diff = obs_cli.main(["compare", rec("pre.json", 6700.0),
                         rec("cand.json", 6700.0, scan=50.0)])
    assert diff["verdict"] == "OK"


def test_obs_compare_reads_event_log_bench_result(tmp_path):
    log = tmp_path / "run.jsonl"
    with obs.recording(log):
        obs.record("bench_result", stage="bench", **_bench_record(6000.0))
    old = tmp_path / "old.json"
    old.write_text(json.dumps(_bench_record(6000.0)))
    diff = obs_cli.main(["compare", str(old), str(log)])
    assert diff["verdict"] == "OK"


# -- bench.py contract ------------------------------------------------------
def _canned_bench_corpus(**_):
    return 0.5, {"n_clips": 4, "clip_dur_s": 2.0, "prefetch_stall_ms": 12.0,
                 "readback_ms": 80.0, "overlap_efficiency": 0.97,
                 "batched_readbacks": 2}


def _canned_bench_serve(**_):
    return 120.0, 35.0, {"n_sessions": 4, "blocks_per_session": 8,
                         "block_frames": 16, "clip_dur_s": 4.0, "ticks": 10,
                         "p50_ms": 20.0, "p99_ms": 50.0,
                         "mean_blocks_per_tick": 3.2}


def _canned_bench_jax(**_):
    return {
        "rtf": 6700.0, "rtf_single_dispatch": 4900.0, "rtf_eigh": 4800.0,
        "rtf_jacobi": 3900.0, "jacobi_error": None,
        "rtf_covfused": 6800.0, "covfused_error": None,
        "dispatch_overhead_ms": 70.0, "flops_per_clip": 3.5e10, "mfu": 0.03,
        "stage_ms": {"full_pipeline": 190.0},
    }


def test_bench_single_json_line_stdout_with_obs_log(tmp_path, monkeypatch, capsys):
    """Tier-1 contract: with --obs-log the full event stream goes to the
    file and stdout stays EXACTLY one parseable JSON line."""
    import bench

    monkeypatch.setattr(bench, "bench_jax", _canned_bench_jax)
    monkeypatch.setattr(bench, "bench_streaming", lambda **_: (0.85, 16.0, 18.9))
    monkeypatch.setattr(bench, "bench_streaming_scan",
                        lambda **_: (95.0, 2.7, 0.125,
                                     {"blocks_per_dispatch": 8}))
    monkeypatch.setattr(bench, "bench_corpus", _canned_bench_corpus)
    monkeypatch.setattr(bench, "bench_serve", _canned_bench_serve)
    monkeypatch.setattr(bench, "bench_numpy", lambda **_: 3.0)
    log = tmp_path / "bench_events.jsonl"
    bench.main(["--obs-log", str(log)])
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1, out_lines
    record = json.loads(out_lines[0])
    assert record["metric"] == "rtf_8node_mwf_enhancement"
    assert record["value"] == 6700.0
    events = obs.read_events(log)  # schema-validating read
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "manifest"
    assert "bench_result" in kinds and "counters" in kinds
    stages = {e["stage"] for e in events if e["kind"] == "stage_end"}
    assert {"bench_jax", "bench_streaming", "bench_serve", "bench_numpy"} <= stages
    # the sideband mirrors the stdout record
    (br,) = [e for e in events if e["kind"] == "bench_result"]
    assert br["attrs"]["value"] == record["value"]
    # recorder released: bench.main disabled it on exit
    assert not obs.enabled()


def test_bench_stdout_unchanged_without_obs_log(monkeypatch, capsys):
    import bench

    monkeypatch.setattr(bench, "bench_jax", _canned_bench_jax)
    monkeypatch.setattr(bench, "bench_streaming", lambda **_: (0.85, 16.0, 18.9))
    monkeypatch.setattr(bench, "bench_streaming_scan",
                        lambda **_: (95.0, 2.7, 0.125,
                                     {"blocks_per_dispatch": 8}))
    monkeypatch.setattr(bench, "bench_corpus", _canned_bench_corpus)
    monkeypatch.setattr(bench, "bench_serve", _canned_bench_serve)
    monkeypatch.setattr(bench, "bench_numpy", lambda **_: 3.0)
    bench.main([])
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1
    record = json.loads(out_lines[0])
    assert record["vs_baseline"] == pytest.approx(6700.0 / 3.0, rel=0.01)
    # the corpus-mode metric of the pipelined engine rides the same line
    assert record["corpus_clips_per_s"] == 0.5
    assert record["corpus_pipeline"]["prefetch_stall_ms"] == 12.0
    # ... and so do the online-serving lane's numbers
    assert record["serve_blocks_per_s"] == 120.0
    assert record["serve_p95_ms"] == 35.0
    assert record["serve_sessions"]["n_sessions"] == 4


def test_bench_error_path_records_event_and_one_line(tmp_path, monkeypatch, capsys):
    import bench

    def boom(**_):
        raise RuntimeError("UNAVAILABLE: tunnel down")

    monkeypatch.setattr(bench, "bench_jax", boom)
    log = tmp_path / "err.jsonl"
    with pytest.raises(SystemExit) as exc:
        bench.main(["--obs-log", str(log)])
    assert exc.value.code == 2
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1
    assert "UNAVAILABLE" in json.loads(out_lines[0])["error"]
    events = obs.read_events(log)
    (br,) = [e for e in events if e["kind"] == "bench_result"]
    assert "UNAVAILABLE" in br["attrs"]["error"]
    assert not obs.enabled()


def test_bench_watchdog_emits_event_and_diagnostic_line(tmp_path, monkeypatch, capsys):
    """The watchdog diagnostic goes through the event schema (satellite):
    when it fires, a `watchdog` event lands in the log before the process
    exits, alongside the parseable stdout line."""
    import bench

    exited = threading.Event()
    monkeypatch.setattr(bench.os, "_exit", lambda code: exited.set())
    log = tmp_path / "wd.jsonl"
    obs.enable(log)
    done = bench._start_watchdog(0.05)
    assert exited.wait(5.0), "watchdog did not fire"
    done.set()
    time.sleep(0.05)  # let the thread finish its print
    out = capsys.readouterr().out
    (line,) = [l for l in out.splitlines() if l.strip()]
    assert json.loads(line)["value"] is None
    events = obs.read_events(log)
    (wd,) = [e for e in events if e["kind"] == "watchdog"]
    assert wd["stage"] == "bench"
    assert wd["attrs"]["timeout_s"] == pytest.approx(0.05)
    assert "counters" in wd["attrs"]  # final registry snapshot rides along


# -- training telemetry -----------------------------------------------------
def test_fit_records_epoch_events(tmp_path):
    from disco_tpu.nn.crnn import build_rnn
    from disco_tpu.nn.training import create_train_state, fit

    model, tx = build_rnn(n_ch=1, win_len=11, n_freq=17, rnn_units=(16,), ff_units=(17,))
    x = np.random.default_rng(0).standard_normal((4, 11, 17)).astype(np.float32)
    y = np.abs(np.random.default_rng(1).standard_normal((4, 11, 17))).astype(np.float32)
    state = create_train_state(model, tx, x[:1])

    def batches():
        yield x, y

    log = tmp_path / "train.jsonl"
    with obs.recording(log):
        fit(model, state, batches, batches, n_epochs=3,
            save_path=str(tmp_path / "m"), verbose=False)
    events = obs.read_events(log)
    epochs = [e for e in events if e["kind"] == "epoch"]
    assert [e["attrs"]["epoch"] for e in epochs] == [0, 1, 2]
    a = epochs[0]["attrs"]
    assert a["steps"] == 1 and np.isfinite(a["train_loss"]) and np.isfinite(a["val_loss"])
    # epoch 0 traces train+eval (and epoch 1 may retrace train_step once:
    # the init state's weak types canonicalize after the first
    # apply_gradients); by epoch 2 the programs must be cache-stable —
    # exactly the per-epoch recompile drift this event exists to expose.
    assert a["recompiles"] >= 2
    assert epochs[2]["attrs"]["recompiles"] == 0
    assert obs.REGISTRY.gauge("val_loss").value == pytest.approx(
        epochs[2]["attrs"]["val_loss"]
    )


def test_obs_compare_mfu_and_stage_lanes(tmp_path):
    """The hot-path-fusion lanes: mfu (higher better) and the two dominant
    stage_ms entries (lower better) are judged like the corpus/serve lanes —
    baseline-gated, lost-measured-lane = REGRESSION, inverted sign for the
    stage times."""
    def rec(path, rtf, mfu=None, stft=None, step2=None):
        d = _bench_record(rtf)
        if mfu is not None:
            d["mfu"] = mfu
        if stft is not None:
            d["stage_ms"]["stft_x3"] = stft
            d["stage_ms"]["step2_exchange_mwf"] = step2
        p = tmp_path / path
        p.write_text(json.dumps(d))
        return p

    base = rec("base.json", 6700.0, mfu=0.03, stft=57.6, step2=115.9)
    # a 2x stage-time REDUCTION with mfu up = IMPROVED (not a regression —
    # lower stage_ms is better)
    good = rec("good.json", 6710.0, mfu=0.11, stft=25.0, step2=50.0)
    assert obs_cli.main(["compare", str(base), str(good)])["verdict"] == "IMPROVED"
    # stage time BLOWING UP regresses even with the headline flat
    slow = rec("slow.json", 6710.0, mfu=0.03, stft=80.0, step2=115.9)
    with pytest.raises(SystemExit):
        obs_cli.main(["compare", str(base), str(slow)])
    # losing a measured mfu lane = REGRESSION
    lost = rec("lost.json", 6710.0, stft=57.6, step2=115.9)
    with pytest.raises(SystemExit):
        obs_cli.main(["compare", str(base), str(lost)])
    # a baseline without the lanes never judges them (pre-fusion records)
    old_base = rec("old_base.json", 6700.0)
    assert obs_cli.main(
        ["compare", str(old_base), str(lost)]
    )["verdict"] == "OK"


def test_bench_record_carries_fused_kernel_fields(monkeypatch, capsys):
    """The ONE-JSON-line record documents the active fused kernels: the
    stft_impl/precision fields plus the bf16 error-reporting lane ride the
    line exactly like cov_impl does."""
    import bench

    canned = dict(_canned_bench_jax())
    canned.update({
        "cov_impl": "xla", "stft_impl": "xla", "precision": "f32",
        "rtf_bf16": 7200.0, "bf16_max_rel_err": 0.0021, "bf16_error": None,
    })
    monkeypatch.setattr(bench, "bench_jax", lambda **_: canned)
    monkeypatch.setattr(bench, "bench_streaming", lambda **_: (0.85, 16.0, 18.9))
    monkeypatch.setattr(bench, "bench_streaming_scan",
                        lambda **_: (95.0, 2.7, 0.125,
                                     {"blocks_per_dispatch": 8}))
    monkeypatch.setattr(bench, "bench_corpus", _canned_bench_corpus)
    monkeypatch.setattr(bench, "bench_serve", _canned_bench_serve)
    monkeypatch.setattr(bench, "bench_numpy", lambda **_: 3.0)
    bench.main([])
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(out_lines) == 1
    record = json.loads(out_lines[0])
    assert record["stft_impl"] == "xla"
    assert record["precision"] == "f32"
    assert record["rtf_bf16"] == 7200.0
    assert record["bf16_max_rel_err"] == 0.0021
    assert record["bf16_error"] is None
