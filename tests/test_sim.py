"""Tests for disco_tpu.sim: image lattice, ISM RIR physics + oracle parity,
FFT convolution, and the scenario-sampling constraints."""
import numpy as np
import pytest

from disco_tpu.sim import (
    LivingRoomSetup,
    MeetingRoomSetup,
    MeetitSetup,
    RoomDefaults,
    circular_array_2d,
    eyring_absorption,
    fft_convolve,
    image_lattice,
    make_setup,
    rir_length_for,
    shoebox_rir,
    shoebox_rirs,
)
from tests.reference_impls import shoebox_rir_np

FS = 16000
C = 343.0


# ------------------------------------------------------------------- lattice
def test_image_lattice_counts():
    lat, par = image_lattice(0)
    assert len(lat) == 1  # direct path only
    lat1, _ = image_lattice(1)
    assert len(lat1) == 7  # direct + 6 first-order walls
    lat2, _ = image_lattice(2)
    # order 2: octahedral numbers — 1, 7, 25, ...
    assert len(lat2) == 25


def test_image_lattice_orders_bounded():
    lat, par = image_lattice(3)
    n_refl = np.abs(lat - par).sum(-1) + np.abs(lat).sum(-1)
    assert n_refl.max() == 3
    assert n_refl.min() == 0


# ----------------------------------------------------------------------- rir
def test_direct_path_physics():
    """Anechoic room (alpha=1): single peak at d/c with 1/(4 pi d) amplitude."""
    room = np.array([6.0, 4.0, 3.0])
    src = np.array([2.0, 2.0, 1.5])
    mic = np.array([4.0, 2.0, 1.5])  # d = 2 m
    rir = np.asarray(shoebox_rir(room, src, mic[None], 1.0, max_order=0, rir_len=2048))
    d = 2.0
    peak = int(round(d * FS / C))
    assert abs(int(np.argmax(rir[0])) - peak) <= 1
    # The windowed sinc spreads a fractional-delay impulse over taps; its DC
    # gain (tap sum) carries the 1/(4 pi d) spreading amplitude.
    assert np.sum(rir[0]) == pytest.approx(1 / (4 * np.pi * d), rel=0.02)


def test_amplitude_decays_with_distance():
    room = np.array([10.0, 6.0, 3.0])
    src = np.array([1.0, 3.0, 1.5])
    mics = np.array([[2.0, 3.0, 1.5], [5.0, 3.0, 1.5]])  # 1 m and 4 m
    rir = np.asarray(shoebox_rir(room, src, mics, 1.0, max_order=0, rir_len=2048))
    assert np.sum(rir[0]) == pytest.approx(4 * np.sum(rir[1]), rel=0.05)


def test_oracle_parity_small_room():
    room = np.array([4.0, 3.0, 2.5])
    src = np.array([1.0, 1.2, 1.1])
    mic = np.array([2.5, 2.0, 1.3])
    alpha = eyring_absorption(0.4, *room)
    got = np.asarray(shoebox_rir(room, src, mic[None], alpha, max_order=3, rir_len=2048))[0]
    want = shoebox_rir_np(room, src, mic, alpha, max_order=3, rir_len=2048)
    err = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert err < 1e-4, err


def test_reverberant_energy_decay():
    """Schroeder decay of a reverberant RIR: energy must drop by tens of dB
    over the RT60 horizon."""
    room = np.array([5.0, 4.0, 3.0])
    src = np.array([1.0, 1.0, 1.5])
    mic = np.array([3.5, 2.5, 1.5])
    rt60 = 0.4
    alpha = eyring_absorption(rt60, *room)
    L = rir_length_for(rt60)
    rir = np.asarray(shoebox_rir(room, src, mic[None], alpha, max_order=20, rir_len=L))[0]
    e = np.cumsum(rir[::-1] ** 2)[::-1]
    edc = 10 * np.log10(np.maximum(e / e[0], 1e-12))
    i0 = int(np.argmax(np.abs(rir)))
    # At ~rt60 after the direct path the decay curve should be well below -30 dB.
    i1 = min(int(i0 + rt60 * FS), L - 1)
    assert edc[i1] < -30, edc[i1]


def test_shoebox_rirs_batched_sources():
    room = np.array([4.0, 3.0, 2.5])
    srcs = np.array([[1.0, 1.0, 1.0], [3.0, 2.0, 1.5]])
    mics = np.array([[2.0, 1.5, 1.2], [2.2, 1.5, 1.2], [2.4, 1.5, 1.2]])
    out = np.asarray(shoebox_rirs(room, srcs, mics, 0.3, max_order=2, rir_len=1024))
    assert out.shape == (2, 3, 1024)
    single = np.asarray(shoebox_rir(room, srcs[1], mics, 0.3, max_order=2, rir_len=1024))
    np.testing.assert_allclose(out[1], single, atol=1e-6)


# ------------------------------------------------------------------ convolve
def test_fft_convolve_matches_np(rng):
    x = rng.standard_normal((2, 3, 1000)).astype(np.float32)
    h = rng.standard_normal((2, 3, 200)).astype(np.float32)
    got = np.asarray(fft_convolve(x, h, out_len=1000))
    for i in range(2):
        for j in range(3):
            want = np.convolve(x[i, j], h[i, j])[:1000]
            np.testing.assert_allclose(got[i, j], want, atol=2e-3)


# ------------------------------------------------------------------ geometry
def test_eyring_absorption_formula():
    a = eyring_absorption(0.5, 6.0, 4.0, 3.0)
    vol, sur = 72.0, 2 * (24 + 18 + 12)
    want = 1 - np.exp((1.7e-5 * 0.5 - 0.1611) * vol / (0.5 * sur))
    assert a == pytest.approx(want)
    assert 0 < a < 1


def test_circular_array():
    arr = circular_array_2d([1.0, 2.0], 4, 0.0, 0.05)
    assert arr.shape == (2, 4)
    np.testing.assert_allclose(np.linalg.norm(arr - [[1.0], [2.0]], axis=0), 0.05, atol=1e-12)


@pytest.mark.parametrize("scenario", ["random", "living", "meeting", "meetit"])
def test_scenarios_sample_valid_configs(scenario):
    rng = np.random.default_rng(11)
    setup = make_setup(scenario, rng=rng)
    d = RoomDefaults()
    for _ in range(5):
        cfg = setup.create_room_setup()
        # Room in range
        assert d.l_range[0] <= cfg.length <= d.l_range[1]
        assert d.beta_range[0] <= cfg.beta <= d.beta_range[1]
        assert 0 < cfg.alpha < 1
        # All mics strictly inside the room
        assert np.all(cfg.mic_positions[0] > 0) and np.all(cfg.mic_positions[0] < cfg.length)
        assert np.all(cfg.mic_positions[1] > 0) and np.all(cfg.mic_positions[1] < cfg.width)
        # Sub-arrays: every mic at d_mn from its node center
        at = 0
        for k, m in enumerate(d.n_sensors_per_node):
            sub = cfg.mic_positions[:2, at : at + m]
            r = np.linalg.norm(sub - cfg.nodes_centers[k][:2, None], axis=0)
            np.testing.assert_allclose(r, d.d_mn, atol=1e-9)
            at += m
        # Sources inside the room, away from walls
        assert np.all(cfg.source_positions[:, 0] > 0) and np.all(
            cfg.source_positions[:, 0] < cfg.length
        )


def test_random_scenario_min_distances():
    rng = np.random.default_rng(5)
    setup = make_setup("random", rng=rng)
    d = RoomDefaults()
    for _ in range(5):
        cfg = setup.create_room_setup()
        cc = cfg.nodes_centers[:, :2]
        for i in range(len(cc)):
            for j in range(i + 1, len(cc)):
                assert np.linalg.norm(cc[i] - cc[j]) >= d.d_nn - 1e-9
        for s in cfg.source_positions[:, :2]:
            for c in cc:
                assert np.linalg.norm(s - c) >= d.d_sn - 1e-9


def test_living_room_nodes_near_walls():
    rng = np.random.default_rng(2)
    setup = make_setup("living", rng=rng)
    cfg = setup.create_room_setup()
    d = RoomDefaults()
    d_nw_max = d.d_mw - d.d_mn  # LivingRoom: d_mw is the MAX wall distance
    near_wall = 0
    for c in cfg.nodes_centers[:3]:
        dist_wall = min(c[0], cfg.length - c[0], c[1], cfg.width - c[1])
        if dist_wall <= d_nw_max + 1e-9:
            near_wall += 1
    assert near_wall == 3


def test_meetit_sources_face_nodes():
    rng = np.random.default_rng(8)
    setup = make_setup("meetit", rng=rng)
    cfg = setup.create_room_setup()
    # Each source shares its angular position with its node: the (source -
    # table center) and (node - table center) directions are parallel.
    tc = np.asarray(setup.table_center[:2])
    for k in range(len(cfg.nodes_centers)):
        v_node = cfg.nodes_centers[k][:2] - tc
        v_src = cfg.source_positions[k][:2] - tc
        cos = np.dot(v_node, v_src) / (np.linalg.norm(v_node) * np.linalg.norm(v_src))
        assert cos > 0.9, (k, cos)


def test_meeting_nodes_on_table():
    rng = np.random.default_rng(4)
    setup = make_setup("meeting", rng=rng)
    cfg = setup.create_room_setup()
    tc = np.asarray(setup.table_center[:2])
    for c in cfg.nodes_centers:
        assert np.linalg.norm(c[:2] - tc) <= setup.table_radius + 1e-9
        assert c[2] == pytest.approx(setup.table_center[2])
