"""Tests for disco_tpu.sim: image lattice, ISM RIR physics + oracle parity,
FFT convolution, and the scenario-sampling constraints."""
from pathlib import Path

import numpy as np
import pytest

from disco_tpu.sim import (
    LivingRoomSetup,
    MeetingRoomSetup,
    MeetitSetup,
    RoomDefaults,
    circular_array_2d,
    eyring_absorption,
    fft_convolve,
    image_lattice,
    make_setup,
    rir_length_for,
    shoebox_rir,
    shoebox_rirs,
)
from tests.reference_impls import shoebox_rir_np

FS = 16000
C = 343.0


# ------------------------------------------------------------------- lattice
def test_image_lattice_counts():
    lat, par = image_lattice(0)
    assert len(lat) == 1  # direct path only
    lat1, _ = image_lattice(1)
    assert len(lat1) == 7  # direct + 6 first-order walls
    lat2, _ = image_lattice(2)
    # order 2: octahedral numbers — 1, 7, 25, ...
    assert len(lat2) == 25


def test_image_lattice_orders_bounded():
    lat, par = image_lattice(3)
    n_refl = np.abs(lat - par).sum(-1) + np.abs(lat).sum(-1)
    assert n_refl.max() == 3
    assert n_refl.min() == 0


# ----------------------------------------------------------------------- rir
def test_direct_path_physics():
    """Anechoic room (alpha=1): single peak at d/c with 1/(4 pi d) amplitude."""
    room = np.array([6.0, 4.0, 3.0])
    src = np.array([2.0, 2.0, 1.5])
    mic = np.array([4.0, 2.0, 1.5])  # d = 2 m
    rir = np.asarray(shoebox_rir(room, src, mic[None], 1.0, max_order=0, rir_len=2048))
    d = 2.0
    peak = int(round(d * FS / C))
    assert abs(int(np.argmax(rir[0])) - peak) <= 1
    # The windowed sinc spreads a fractional-delay impulse over taps; its DC
    # gain (tap sum) carries the 1/(4 pi d) spreading amplitude.
    assert np.sum(rir[0]) == pytest.approx(1 / (4 * np.pi * d), rel=0.02)


def test_amplitude_decays_with_distance():
    room = np.array([10.0, 6.0, 3.0])
    src = np.array([1.0, 3.0, 1.5])
    mics = np.array([[2.0, 3.0, 1.5], [5.0, 3.0, 1.5]])  # 1 m and 4 m
    rir = np.asarray(shoebox_rir(room, src, mics, 1.0, max_order=0, rir_len=2048))
    assert np.sum(rir[0]) == pytest.approx(4 * np.sum(rir[1]), rel=0.05)


def test_oracle_parity_small_room():
    room = np.array([4.0, 3.0, 2.5])
    src = np.array([1.0, 1.2, 1.1])
    mic = np.array([2.5, 2.0, 1.3])
    alpha = eyring_absorption(0.4, *room)
    got = np.asarray(shoebox_rir(room, src, mic[None], alpha, max_order=3, rir_len=2048))[0]
    want = shoebox_rir_np(room, src, mic, alpha, max_order=3, rir_len=2048)
    err = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert err < 1e-4, err


def test_reverberant_energy_decay():
    """Schroeder decay of a reverberant RIR: energy must drop by tens of dB
    over the RT60 horizon."""
    room = np.array([5.0, 4.0, 3.0])
    src = np.array([1.0, 1.0, 1.5])
    mic = np.array([3.5, 2.5, 1.5])
    rt60 = 0.4
    alpha = eyring_absorption(rt60, *room)
    L = rir_length_for(rt60)
    rir = np.asarray(shoebox_rir(room, src, mic[None], alpha, max_order=20, rir_len=L))[0]
    e = np.cumsum(rir[::-1] ** 2)[::-1]
    edc = 10 * np.log10(np.maximum(e / e[0], 1e-12))
    i0 = int(np.argmax(np.abs(rir)))
    # At ~rt60 after the direct path the decay curve should be well below -30 dB.
    i1 = min(int(i0 + rt60 * FS), L - 1)
    assert edc[i1] < -30, edc[i1]


def test_shoebox_rirs_batched_sources():
    room = np.array([4.0, 3.0, 2.5])
    srcs = np.array([[1.0, 1.0, 1.0], [3.0, 2.0, 1.5]])
    mics = np.array([[2.0, 1.5, 1.2], [2.2, 1.5, 1.2], [2.4, 1.5, 1.2]])
    out = np.asarray(shoebox_rirs(room, srcs, mics, 0.3, max_order=2, rir_len=1024))
    assert out.shape == (2, 3, 1024)
    single = np.asarray(shoebox_rir(room, srcs[1], mics, 0.3, max_order=2, rir_len=1024))
    np.testing.assert_allclose(out[1], single, atol=1e-6)


# ------------------------------------------------------------------ convolve
def test_fft_convolve_matches_np(rng):
    x = rng.standard_normal((2, 3, 1000)).astype(np.float32)
    h = rng.standard_normal((2, 3, 200)).astype(np.float32)
    got = np.asarray(fft_convolve(x, h, out_len=1000))
    for i in range(2):
        for j in range(3):
            want = np.convolve(x[i, j], h[i, j])[:1000]
            np.testing.assert_allclose(got[i, j], want, atol=2e-3)


# ------------------------------------------------------------------ geometry
def test_eyring_absorption_formula():
    a = eyring_absorption(0.5, 6.0, 4.0, 3.0)
    vol, sur = 72.0, 2 * (24 + 18 + 12)
    want = 1 - np.exp((1.7e-5 * 0.5 - 0.1611) * vol / (0.5 * sur))
    assert a == pytest.approx(want)
    assert 0 < a < 1


def test_circular_array():
    arr = circular_array_2d([1.0, 2.0], 4, 0.0, 0.05)
    assert arr.shape == (2, 4)
    np.testing.assert_allclose(np.linalg.norm(arr - [[1.0], [2.0]], axis=0), 0.05, atol=1e-12)


def test_room_setup_plot():
    """RoomSetup.plot renders the top-view observability figure (reference
    plot_room, room_setups.py:238-253) without touching the pyplot state."""
    rng = np.random.default_rng(3)
    cfg = make_setup("random", rng=rng).create_room_setup()
    fig = cfg.plot()
    assert fig is not None
    ax = fig.axes[0]
    assert len(ax.lines) >= 2  # mics + sources scatter
    labels = [t.get_text() for t in ax.texts]
    assert f"Node {len(cfg.nodes_centers)}" in labels
    assert "Source 1" in labels


@pytest.mark.parametrize("scenario", ["random", "living", "meeting", "meetit"])
def test_scenarios_sample_valid_configs(scenario):
    rng = np.random.default_rng(11)
    setup = make_setup(scenario, rng=rng)
    d = RoomDefaults()
    for _ in range(5):
        cfg = setup.create_room_setup()
        # Room in range
        assert d.l_range[0] <= cfg.length <= d.l_range[1]
        assert d.beta_range[0] <= cfg.beta <= d.beta_range[1]
        assert 0 < cfg.alpha < 1
        # All mics strictly inside the room
        assert np.all(cfg.mic_positions[0] > 0) and np.all(cfg.mic_positions[0] < cfg.length)
        assert np.all(cfg.mic_positions[1] > 0) and np.all(cfg.mic_positions[1] < cfg.width)
        # Sub-arrays: every mic at d_mn from its node center
        at = 0
        for k, m in enumerate(d.n_sensors_per_node):
            sub = cfg.mic_positions[:2, at : at + m]
            r = np.linalg.norm(sub - cfg.nodes_centers[k][:2, None], axis=0)
            np.testing.assert_allclose(r, d.d_mn, atol=1e-9)
            at += m
        # Sources inside the room, away from walls
        assert np.all(cfg.source_positions[:, 0] > 0) and np.all(
            cfg.source_positions[:, 0] < cfg.length
        )


def test_random_scenario_min_distances():
    rng = np.random.default_rng(5)
    setup = make_setup("random", rng=rng)
    d = RoomDefaults()
    for _ in range(5):
        cfg = setup.create_room_setup()
        cc = cfg.nodes_centers[:, :2]
        for i in range(len(cc)):
            for j in range(i + 1, len(cc)):
                assert np.linalg.norm(cc[i] - cc[j]) >= d.d_nn - 1e-9
        for s in cfg.source_positions[:, :2]:
            for c in cc:
                assert np.linalg.norm(s - c) >= d.d_sn - 1e-9


def test_living_room_nodes_near_walls():
    rng = np.random.default_rng(2)
    setup = make_setup("living", rng=rng)
    cfg = setup.create_room_setup()
    d = RoomDefaults()
    d_nw_max = d.d_mw - d.d_mn  # LivingRoom: d_mw is the MAX wall distance
    near_wall = 0
    for c in cfg.nodes_centers[:3]:
        dist_wall = min(c[0], cfg.length - c[0], c[1], cfg.width - c[1])
        if dist_wall <= d_nw_max + 1e-9:
            near_wall += 1
    assert near_wall == 3


def test_meetit_sources_face_nodes():
    rng = np.random.default_rng(8)
    setup = make_setup("meetit", rng=rng)
    cfg = setup.create_room_setup()
    # Each source shares its angular position with its node: the (source -
    # table center) and (node - table center) directions are parallel.
    tc = np.asarray(setup.table_center[:2])
    for k in range(len(cfg.nodes_centers)):
        v_node = cfg.nodes_centers[k][:2] - tc
        v_src = cfg.source_positions[k][:2] - tc
        cos = np.dot(v_node, v_src) / (np.linalg.norm(v_node) * np.linalg.norm(v_src))
        assert cos > 0.9, (k, cos)


def test_meeting_nodes_on_table():
    rng = np.random.default_rng(4)
    setup = make_setup("meeting", rng=rng)
    cfg = setup.create_room_setup()
    tc = np.asarray(setup.table_center[:2])
    for c in cfg.nodes_centers:
        assert np.linalg.norm(c[:2] - tc) <= setup.table_radius + 1e-9
        assert c[2] == pytest.approx(setup.table_center[2])


# ------------------------------------------- order-20 fidelity pinning
# (VERDICT round 1, next-round item 1)

GOLDEN = Path(__file__).parent / "data" / "golden_rir_order20.npz"


def test_golden_fixture_parity_order20():
    """Tap-level parity of the float32 JAX kernel against the committed
    order-20 multi-mic float64 fixture (generated once by
    tests/data/gen_golden_rir.py from the independent NumPy oracle —
    pyroomacoustics is not installable here, so the float64 oracle plays
    the role of libroom ground truth)."""
    g = np.load(GOLDEN)
    got = np.asarray(
        shoebox_rirs(
            g["room_dim"].astype(np.float32), g["sources"].astype(np.float32),
            g["mics"].astype(np.float32), float(g["alpha"]),
            max_order=int(g["max_order"]), rir_len=int(g["rir_len"]),
        )
    ).astype(np.float64)
    want = g["rirs"]
    assert got.shape == want.shape == (2, 4, int(g["rir_len"]))
    rel = np.linalg.norm(got - want, axis=-1) / np.linalg.norm(want, axis=-1)
    # float32 kernel vs float64 oracle: measured ~8e-5; 5e-4 budgeted
    assert rel.max() < 5e-4, rel


def test_oracle_fast_matches_loop_oracle():
    """The chunk-vectorized order-20 oracle reproduces the original
    loop-based oracle exactly where both are feasible (order 3)."""
    from tests.reference_impls import shoebox_rir_np, shoebox_rir_np_order20

    room = np.array([4.0, 3.0, 2.5])
    src = np.array([1.0, 1.2, 1.1])
    mic = np.array([2.5, 2.0, 1.3])
    a = eyring_absorption(0.4, *room)
    slow = shoebox_rir_np(room, src, mic, a, max_order=3, rir_len=2048)
    fast = shoebox_rir_np_order20(room, src, mic[None], a, max_order=3, rir_len=2048)[0]
    np.testing.assert_allclose(fast, slow, atol=1e-12)


def test_rt60_statistics_vs_eyring():
    """Statistical check over random rooms: the Schroeder-decay RT60 of
    order-20 kernel RIRs tracks the Eyring design target.  Order truncation
    caps the late tail (as libroom's finite order does), so the check runs
    in the regime order 20 covers (small rooms, RT60 <= 0.35 s) and asserts
    a calibrated band (measured mean ratio ~0.83) rather than exactness."""
    from tests.reference_impls import rt60_schroeder

    rng = np.random.default_rng(3)
    ratios = []
    for _ in range(6):
        dim = rng.uniform([3.5, 3.0, 2.4], [5.0, 4.5, 2.8])
        rt = rng.uniform(0.22, 0.35)
        a = float(eyring_absorption(rt, *dim))
        src = dim * rng.uniform(0.25, 0.75, 3)
        mic = dim * rng.uniform(0.25, 0.75, 3)
        L = rir_length_for(rt * 2.0)
        r = np.asarray(shoebox_rir(dim, src, mic[None], a, max_order=20, rir_len=L))[0]
        est = rt60_schroeder(r)
        assert np.isfinite(est)
        ratios.append(est / rt)
    ratios = np.array(ratios)
    assert 0.65 < ratios.mean() < 1.2, ratios
    assert np.all((ratios > 0.45) & (ratios < 1.5)), ratios


def test_rt60_monotone_in_target():
    """Same room, higher Eyring RT60 target -> longer measured decay.
    Compared on the early decay (T15 fit, -5..-20 dB) at targets the
    order-20 lattice fully covers — beyond ~0.3 s in a room this size the
    truncated tail makes the Schroeder estimate saturate (a property shared
    with any finite-order ISM, including libroom's)."""
    from tests.reference_impls import rt60_schroeder

    dim = np.array([4.5, 3.8, 2.6])
    src = np.array([1.2, 1.0, 1.3])
    mic = np.array([3.2, 2.6, 1.5])
    ests = []
    for rt in (0.15, 0.3):
        a = float(eyring_absorption(rt, *dim))
        L = rir_length_for(0.8)
        r = np.asarray(shoebox_rir(dim, src, mic[None], a, max_order=20, rir_len=L))[0]
        ests.append(rt60_schroeder(r, lo_db=-5.0, hi_db=-20.0))
    assert ests[1] > 1.3 * ests[0], ests


def test_config5_sdr_invariant_to_rir_source():
    """End-to-end SDR parity (VERDICT item 1 'done' bar): the config-5
    pipeline (simulate + convolve + two-step TANGO) produces the same
    SI-SDR whether the RIRs come from the float32 kernel or the float64
    golden fixture — i.e. kernel fidelity is sufficient at the level the
    framework is judged on."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.core.metrics import si_sdr
    from disco_tpu.enhance import oracle_masks, tango

    g = np.load(GOLDEN)
    L = 16000
    K, Cc = 2, 2
    rng = np.random.default_rng(0)
    dry = rng.standard_normal((2, L)).astype(np.float32)

    kernel_rirs = np.asarray(
        shoebox_rirs(
            g["room_dim"].astype(np.float32), g["sources"].astype(np.float32),
            g["mics"].astype(np.float32), float(g["alpha"]),
            max_order=int(g["max_order"]), rir_len=int(g["rir_len"]),
        )
    )
    golden_rirs = g["rirs"].astype(np.float32)

    @jax.jit
    def enhance_with(rirs):
        imgs = fft_convolve(jnp.asarray(dry)[:, None, :], jnp.asarray(rirs), out_len=L)
        s = imgs[0].reshape(K, Cc, L)
        n = imgs[1].reshape(K, Cc, L)
        y = s + n
        Y, S, N = stft(y), stft(s), stft(n)
        m = oracle_masks(S, N, "irm1")
        res = tango(Y, S, N, m, m, policy="local")
        return istft(res.yf, length=L), s

    out_k, s_k = map(np.asarray, enhance_with(kernel_rirs))
    out_g, s_g = map(np.asarray, enhance_with(golden_rirs))
    for k in range(K):
        sdr_k = float(si_sdr(s_k[k, 0], out_k[k]))
        sdr_g = float(si_sdr(s_g[k, 0], out_g[k]))
        assert abs(sdr_k - sdr_g) < 0.1, (k, sdr_k, sdr_g)
