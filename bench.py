"""Headline benchmark: real-time factor of 8-node MWF (TANGO) speech
enhancement @16 kHz (BASELINE.md north star).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``value`` is audio-seconds enhanced per wall-second (x realtime) for the
jitted batched TPU pipeline; ``vs_baseline`` is the speedup over the float64
NumPy reference implementation (the loop-per-(node,freq) formulas of
reference tango.py:252-457) measured on this same host and extrapolated from
a short clip.
"""
import json
import time

import numpy as np

from disco_tpu.milestones import _fence, _scene

FS = 16000
K, C = 8, 4  # 8-node, 4 mics per node (north-star config)


def bench_jax(batch=16, dur_s=10.0, iters=5):
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance import oracle_masks, tango

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    yb = jnp.asarray(np.stack([y] * batch))
    sb = jnp.asarray(np.stack([s] * batch))
    nb = jnp.asarray(np.stack([n] * batch))

    @jax.jit
    def run(yb, sb, nb):
        def one(y, s, n):
            Y, S, N = stft(y), stft(s), stft(n)
            m = oracle_masks(S, N, "irm1")
            return tango(Y, S, N, m, m, policy="local").yf

        # Return the full enhanced spectra: jit outputs must be materialized,
        # so the timed program is exactly the production program.
        return jax.vmap(one)(yb, sb, nb)

    fence = _fence  # shared tunnel-safe host-readback execution fence

    fence(run(yb, sb, nb))  # compile + warm up
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fence(run(yb, sb, nb))
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[len(times) // 2]  # median
    audio_s = batch * K * dur_s  # per-node enhanced outputs
    return audio_s / dt


def bench_numpy(dur_s=1.0):
    from tests.reference_impls import tango_np

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    t0 = time.perf_counter()
    tango_np(np.asarray(y, np.float64), np.asarray(s, np.float64), np.asarray(n, np.float64))
    dt = time.perf_counter() - t0
    return K * dur_s / dt


def main():
    rtf = bench_jax()
    try:
        rtf_np = bench_numpy()
    except Exception:
        rtf_np = None
    vs = (rtf / rtf_np) if rtf_np else None
    print(
        json.dumps(
            {
                "metric": "rtf_8node_mwf_enhancement",
                "value": round(rtf, 2),
                "unit": "x_realtime",
                "vs_baseline": round(vs, 2) if vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
