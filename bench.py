"""Headline benchmark: real-time factor of 8-node MWF (TANGO) speech
enhancement @16 kHz (BASELINE.md north star).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``value`` is audio-seconds enhanced per wall-second (x realtime) for the
jitted batched TPU pipeline; ``vs_baseline`` is the speedup over the float64
NumPy reference implementation (the loop-per-(node,freq) formulas of
reference tango.py:252-457) measured on this same host and extrapolated from
a short clip.
"""
import json
import time

import numpy as np

FS = 16000
K, C = 8, 4  # 8-node, 4 mics per node (north-star config)


def _scene(K, C, L, seed=0):
    rng = np.random.default_rng(seed)
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8), mode="same") for _ in range(C)]) for _ in range(K)]
    ).astype(np.float32)
    n = 0.5 * rng.standard_normal((K, C, L)).astype(np.float32)
    return s + n, s, n


def bench_jax(batch=4, dur_s=10.0, iters=5):
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance import oracle_masks, tango

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L)
    yb = jnp.asarray(np.stack([y] * batch))
    sb = jnp.asarray(np.stack([s] * batch))
    nb = jnp.asarray(np.stack([n] * batch))

    @jax.jit
    def run(yb, sb, nb):
        def one(y, s, n):
            Y, S, N = stft(y), stft(s), stft(n)
            m = oracle_masks(S, N, "irm1")
            return tango(Y, S, N, m, m, policy="local").yf

        # Return the full enhanced spectra: jit outputs must be materialized,
        # so the timed program is exactly the production program.
        return jax.vmap(one)(yb, sb, nb)

    def fence(out):
        # Transfer one output-dependent element to host.  On tunneled/async
        # device attachments block_until_ready() was measured returning in
        # ~20us for a >100ms program; a host readback of the result is the
        # only reliable execution fence there.  (jnp.real: the tunnel cannot
        # transfer complex dtypes.)
        return float(jnp.real(out[0, 0, 0, 0]))

    fence(run(yb, sb, nb))  # compile + warm up
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fence(run(yb, sb, nb))
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[len(times) // 2]  # median
    audio_s = batch * K * dur_s  # per-node enhanced outputs
    return audio_s / dt


def bench_numpy(dur_s=1.0):
    from tests.reference_impls import tango_np

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L)
    t0 = time.perf_counter()
    tango_np(np.asarray(y, np.float64), np.asarray(s, np.float64), np.asarray(n, np.float64))
    dt = time.perf_counter() - t0
    return K * dur_s / dt


def main():
    rtf = bench_jax()
    try:
        rtf_np = bench_numpy()
    except Exception:
        rtf_np = None
    vs = (rtf / rtf_np) if rtf_np else None
    print(
        json.dumps(
            {
                "metric": "rtf_8node_mwf_enhancement",
                "value": round(rtf, 2),
                "unit": "x_realtime",
                "vs_baseline": round(vs, 2) if vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
