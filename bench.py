"""Headline benchmark: real-time factor of 8-node MWF (TANGO) speech
enhancement @16 kHz (BASELINE.md north star), with a FLOP model, MFU and a
per-stage wall-time breakdown (VERDICT round-1 item 4).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"flops_per_clip", "stage_ms", ...}.  ``value`` is audio-seconds enhanced per
wall-second (x realtime) for the jitted batched TPU pipeline; ``vs_baseline``
is the speedup over the float64 NumPy reference implementation (the
loop-per-(node,freq) formulas of reference tango.py:252-457) measured on this
same host at 2 s clip length (long enough to amortize NumPy setup).

Timing methodology (round-2 fix): this machine reaches its TPU through a
tunneled device attachment with a measured ~80 ms fixed RPC round-trip per
fenced dispatch — a scalar add costs the same ~80 ms as a full STFT batch, so
single-dispatch timings mostly measure the tunnel, not the chip, and
``block_until_ready`` returns in ~20 us without waiting (the fence is a
1-element host readback instead).  Each measurement therefore queues k
programs asynchronously, fences once, and takes the SLOPE
``(t_k - t_1) / (k - 1)`` — the true on-device execution time; the intercept
is reported as ``dispatch_overhead_ms``.  ``value`` uses the slope (the
number that holds on a directly-attached v5e); ``value_single_dispatch``
keeps the tunnel-included figure for continuity with BENCH_r01.

FLOPs come from XLA's own cost model (``compiled.cost_analysis()['flops']``)
over the exact compiled program, not a hand count; MFU divides by the
device's peak dense-f32 throughput (override with BENCH_PEAK_TFLOPS).  The
pipeline is FFT- and small-hermitian-eig-dominated (257-point spectra,
C<=11 matrices), so it sits on the memory/latency side of the roofline, not
the MXU side — a LOW MFU with a HIGH RTF is the expected signature, and the
stage breakdown shows where the time actually goes.

The headline ``value`` runs the pipeline DEFAULT solver — 'power'
(dominant-eigenpair power iteration) since round 4, flipped from 'eigh' on
the round-3 on-device A/B (solver_ab, exp/tpu_validation_r3.jsonl: power
6722x vs eigh 4833x at 49 dB output agreement; SDR parity pinned at 0.1 dB
in tests/test_tango.py).  ``rtf_eigh_solver`` keeps the
reference-bit-matching eigh lane in every record.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

from disco_tpu.milestones import (  # noqa: F401  (_slope_time re-exported
    _fence,  # for exp/tune_hw.py and the validation sweeps)
    _leaf,
    _scene,
    _slope_time,
    _time_queued,
)
from disco_tpu.obs import events as obs_events
from disco_tpu.obs.metrics import REGISTRY as obs_registry

FS = 16000
K, C = 8, 4  # 8-node, 4 mics per node (north-star config)

# peak dense fp32 TFLOP/s by device kind (MXU peak; bf16 is ~2x these)
_PEAK_TFLOPS = {
    "TPU v4": 137.5,
    "TPU v5e": 98.0,
    "TPU v5 lite": 98.0,
    "TPU v5p": 229.5,
    "TPU v6e": 459.0,
    "cpu": 0.5,
}


def _peak_flops():
    import jax

    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = jax.devices()[0].device_kind
    for name, tf in _PEAK_TFLOPS.items():
        if name.lower() in kind.lower():
            return tf * 1e12
    return _PEAK_TFLOPS["cpu"] * 1e12




def bench_jax(batch=16, dur_s=10.0, iters=5):
    """Returns dict with rtf (slope, default=power solver), rtf_single_dispatch, rtf_eigh,
    dispatch overhead, flops_per_clip, mfu, stage_ms."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.core.masks import tf_mask_mag
    from disco_tpu.enhance import compute_z_signals, oracle_masks, tango
    from disco_tpu.ops.stft_ops import stft_with_mag

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    yb = jnp.asarray(np.stack([y] * batch))
    sb = jnp.asarray(np.stack([s] * batch))
    nb = jnp.asarray(np.stack([n] * batch))

    def make_run(solver, cov_impl="auto", precision="f32", stft_impl="auto"):
        @jax.jit
        def run(yb, sb, nb):
            def one(y, s, n):
                # the fused hot path: ONE spec+magnitude STFT over the
                # stacked y/s/n streams, irm masks straight from the
                # emitted magnitudes, mask-folded covariances inside tango
                spec, mag = stft_with_mag(jnp.stack([y, s, n]),
                                          impl=stft_impl, precision=precision)
                Y, S, N = spec[0], spec[1], spec[2]
                m = tf_mask_mag(mag[1][:, 0], mag[2][:, 0], "irm1")
                return tango(Y, S, N, m, m, policy="local", solver=solver,
                             cov_impl=cov_impl, precision=precision).yf

            # Return the full enhanced spectra: jit outputs must be
            # materialized, so the timed program is exactly the production
            # program.
            return jax.vmap(one)(yb, sb, nb)

        return run

    # headline lane = the production default (tango's solver default:
    # 'power' since round 4, traceable to the round-3 solver_ab artifact)
    run = make_run("power")
    dt, dt1 = _slope_time(run, yb, sb, nb, iters=iters)
    audio_s = batch * K * dur_s  # per-node enhanced outputs
    rtf = audio_s / dt
    rtf_single = audio_s / dt1

    run_e = make_run("eigh")
    dt_e, _ = _slope_time(run_e, yb, sb, nb, iters=iters)
    rtf_eigh = audio_s / dt_e

    # full-eigendecomposition alternative (ops/eigh_ops.py); measured so the
    # hardware record carries all solver families.  A failure is recorded as
    # an error string, not silently null — the record must distinguish
    # "solver broken on this backend" from "not measured".
    jacobi_error = None
    try:
        run_j = make_run("jacobi")
        dt_j, _ = _slope_time(run_j, yb, sb, nb, iters=iters)
        rtf_jacobi = audio_s / dt_j
    except Exception as e:
        rtf_jacobi = None
        jacobi_error = f"{type(e).__name__}: {e}"[:200]

    # fused solve lane (ops/mwf_ops.py, the step2_exchange_mwf attack): the
    # whole cov->whiten->Jacobi->filter solve chain as one VMEM-resident
    # program ('fused' resolves per backend through ops.resolve, like the
    # cov/stft 'auto' knobs — the ACTIVE impl is recorded in solver_lanes).
    fused_error = None
    try:
        run_f = make_run("fused")
        dt_f, _ = _slope_time(run_f, yb, sb, nb, iters=iters)
        rtf_fused = audio_s / dt_f
    except Exception as e:
        rtf_fused = None
        fused_error = f"{type(e).__name__}: {e}"[:200]

    # chained-clip lane (enhance/fused.py, the disco-chain attack): the
    # ENTIRE per-clip chain — STFT, masks, both MWF steps, ISTFT — as one
    # program, so the lane's slope is the on-device cost of the whole clip
    # with zero inter-stage dispatches (the staged stage_ms rows below each
    # pay their own fenced dispatch on the tunnel).
    chained_error = None
    rtf_chained = dt_ch = None
    try:
        from disco_tpu.enhance.fused import tango_clip_fused

        jchained = jax.jit(jax.vmap(
            lambda y, s, n: tango_clip_fused.__wrapped__(y, s, n,
                                                         solver="fused")
        ))
        dt_ch, _ = _slope_time(jchained, yb, sb, nb, iters=iters)
        rtf_chained = audio_s / dt_ch
    except Exception as e:
        chained_error = f"{type(e).__name__}: {e}"[:200]

    # fused masked-covariance kernel (ops/cov_ops.py, round-2 verdict #3):
    # same default solver, covariance stage reads Y once instead of
    # materializing the masked copies.
    covfused_error = None
    try:
        run_c = make_run("power", cov_impl="pallas")
        dt_c, _ = _slope_time(run_c, yb, sb, nb, iters=iters)
        rtf_covfused = audio_s / dt_c
    except Exception as e:
        rtf_covfused = None
        covfused_error = f"{type(e).__name__}: {e}"[:200]

    # bf16 compute lane (ops.resolve): bf16 multiply inner loops with f32
    # accumulators in the fused STFT/covariance kernels.  A SEPARATE
    # error-reporting lane — the default lane's numerics are untouched, and
    # the record carries the measured deviation so the speedup is never
    # quoted without its cost.  The error is computed ON DEVICE (one real
    # scalar readback — complex outputs cannot cross the tunnel).
    bf16_error = None
    rtf_bf16 = bf16_max_rel_err = None
    try:
        run_b = make_run("power", precision="bf16")
        rel = jax.jit(
            lambda a, b: jnp.max(jnp.abs(a - b)) / jnp.max(jnp.abs(b))
        )
        bf16_max_rel_err = float(rel(run_b(yb, sb, nb), run(yb, sb, nb)))
        dt_b, _ = _slope_time(run_b, yb, sb, nb, iters=iters)
        rtf_bf16 = audio_s / dt_b
    except Exception as e:
        bf16_error = f"{type(e).__name__}: {e}"[:200]

    # ---- FLOP model: XLA's cost analysis of the exact compiled program
    flops_total = None
    try:
        cost = jax.jit(run).lower(yb, sb, nb).compile().cost_analysis()
        if cost:
            flops_total = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass
    mfu = (flops_total / dt) / _peak_flops() if flops_total else None
    flops_per_clip = flops_total / batch if flops_total else None

    # the active kernels behind the headline's 'auto' defaults (cov: fused
    # pallas on TPU since round 6; stft: the fused spec+mag kernel of this
    # round) and the default-lane precision
    from disco_tpu.ops.cov_ops import resolve_cov_impl
    from disco_tpu.ops.stft_ops import resolve_stft_impl

    cov_impl_active = resolve_cov_impl("auto")
    stft_impl_active = resolve_stft_impl("auto")

    # resolved provenance of every solve lane (post-ops.resolve): records
    # must distinguish 'jacobi' XLA from pallas from the fused kernel
    # without re-running the bench on the same attachment
    from disco_tpu.beam.filters import solver_lane_info

    solver_lanes = {
        "rtf": solver_lane_info("power"),
        "rtf_eigh_solver": solver_lane_info("eigh"),
        "rtf_jacobi_solver": solver_lane_info("jacobi"),
        "rtf_fused_solver": solver_lane_info("fused"),
        # the two disco-chain lanes both ride the fused solve spec: records
        # must say which concrete impl the 'fused' auto spec resolved to
        # when the chained/step-1 numbers were taken
        "rtf_fused_step1": solver_lane_info("fused"),
        "rtf_chained_clip": solver_lane_info("fused"),
    }

    # ---- per-stage breakdown, each stage's ON-DEVICE time via the slope
    # (stages slightly over-add vs the full pipeline, which fuses tighter).
    # stft_x3 is the fused analysis stage: ONE spec+magnitude program over
    # the stacked y/s/n streams (the key predates the fusion — same stage,
    # an order less HBM traffic), measured on the same method as before.
    jstft = jax.jit(
        lambda a, b, c: stft_with_mag(jnp.stack([a, b, c]))
    )
    spec_b, mag_b = jstft(yb, sb, nb)
    Yb, Sb, Nb = spec_b[0], spec_b[1], spec_b[2]
    jmask = jax.jit(jax.vmap(lambda ms, mn: tf_mask_mag(ms[:, 0], mn[:, 0], "irm1")))
    Mb = jmask(mag_b[1], mag_b[2])
    jstep1 = jax.jit(
        jax.vmap(lambda Y, S, N, m: compute_z_signals(None, None, None, Y=Y, S=S, N=N, masks_z=m)["z_y"])
    )
    jfull = jax.jit(
        jax.vmap(lambda Y, S, N, m: tango(Y, S, N, m, m, policy="local").yf)
    )
    yf = jfull(Yb, Sb, Nb, Mb)
    jistft = jax.jit(lambda Z: istft(Z, length=L))

    t_stft = _slope_time(jstft, yb, sb, nb, iters=iters)[0]  # fused y+s+n (+mag)
    t_mask = _slope_time(jmask, mag_b[1], mag_b[2], iters=iters)[0]
    t_step1 = _slope_time(jstep1, Yb, Sb, Nb, Mb, iters=iters)[0]
    t_full = _slope_time(jfull, Yb, Sb, Nb, Mb, iters=iters)[0]
    t_istft = _slope_time(jistft, yf, iters=iters)[0]

    # step-1 fused-solve lane (the step-1 half of the disco-chain attack):
    # the SAME step-1 program with all K×F pencils through the
    # batch-in-lanes fused solve (compute_z_signals(solver='fused')) —
    # directly comparable to stage_ms.step1_local_mwf, which times the
    # default per-node vmapped 'power' path.
    fused_step1_error = None
    rtf_fused_step1 = t_step1_fused = None
    try:
        jstep1_f = jax.jit(jax.vmap(
            lambda Y, S, N, m: compute_z_signals(
                None, None, None, Y=Y, S=S, N=N, masks_z=m, solver="fused"
            )["z_y"]
        ))
        t_step1_fused = _slope_time(jstep1_f, Yb, Sb, Nb, Mb, iters=iters)[0]
        rtf_fused_step1 = audio_s / t_step1_fused
    except Exception as e:
        fused_step1_error = f"{type(e).__name__}: {e}"[:200]

    stage_ms = {
        "stft_x3": round(t_stft * 1e3, 2),
        "masks": round(t_mask * 1e3, 2),
        "step1_local_mwf": round(t_step1 * 1e3, 2),
        "step2_exchange_mwf": round(max(t_full - t_step1, 0.0) * 1e3, 2),
        "istft": round(t_istft * 1e3, 2),
        "full_pipeline": round(dt * 1e3, 2),
    }
    if t_step1_fused is not None:
        stage_ms["step1_fused_mwf"] = round(t_step1_fused * 1e3, 2)
    if dt_ch is not None:
        stage_ms["chained_clip"] = round(dt_ch * 1e3, 2)
    return {
        "rtf": rtf,
        "cov_impl": cov_impl_active,
        "stft_impl": stft_impl_active,
        "precision": "f32",
        "rtf_bf16": rtf_bf16,
        "bf16_max_rel_err": bf16_max_rel_err,
        "bf16_error": bf16_error,
        "rtf_single_dispatch": rtf_single,
        "rtf_eigh": rtf_eigh,
        "rtf_jacobi": rtf_jacobi,
        "jacobi_error": jacobi_error,
        "rtf_fused": rtf_fused,
        "fused_error": fused_error,
        "rtf_chained": rtf_chained,
        "chained_error": chained_error,
        "rtf_fused_step1": rtf_fused_step1,
        "fused_step1_error": fused_step1_error,
        "solver_lanes": solver_lanes,
        "rtf_covfused": rtf_covfused,
        "covfused_error": covfused_error,
        "dispatch_overhead_ms": round(max(dt1 - dt, 0.0) * 1e3, 2),
        "flops_per_clip": flops_per_clip,
        "mfu": mfu,
        "stage_ms": stage_ms,
    }


def bench_streaming(dur_s=10.0, K=4, C=4, update_every=4, iters=5):
    """Per-frame on-device latency of the online (streaming) TANGO pipeline
    — the 'config 6' ≈1 ms/frame claim, now emitted into the artifact
    (round-2 verdict #6).  Slope-timed like every other lane; returns
    (latency_ms_frame, frame_budget_ms, rtf)."""
    import jax

    from disco_tpu.core.dsp import stft
    from disco_tpu.core.masks import tf_mask
    from disco_tpu.enhance.streaming import streaming_tango
    from disco_tpu.milestones import _scene

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    Y, S, N = stft(y), stft(s), stft(n)
    masks = jax.vmap(lambda Sk, Nk: tf_mask(Sk[0], Nk[0], "irm1"))(S, N)
    T = Y.shape[-1]

    @jax.jit
    def run(Y, mz, mw):
        return streaming_tango(Y, mz, mw, update_every=update_every, policy="local")["yf"]

    dt, _ = _slope_time(run, Y, masks, masks, iters=iters)
    per_frame_ms = 1e3 * dt / T
    budget_ms = 1e3 * 256 / FS  # hop / fs: the real-time deadline per frame
    return per_frame_ms, budget_ms, budget_ms / per_frame_ms


def bench_streaming_scan(dur_s=10.0, K=4, C=4, update_every=4,
                         blocks_per_dispatch=8, iters=5):
    """Amortized streaming-deployment lane: the per-block serving loop pays
    one fenced ~80 ms tunnel RPC per delivered block, the scanned super-tick
    (``streaming_tango_scan``) pays it once per ``blocks_per_dispatch``
    blocks.  Both sub-lanes here are therefore timed *tunnel-included*
    (single fenced dispatch — ``_slope_time``'s t1), because the RPC is
    exactly the cost being amortized; the k-queued slope is reported in the
    stats for the on-device view.

    Returns (rtf_scan, rtf_block, dispatches_per_block, stats):
    ``rtf_scan``/``rtf_block`` = realtime factor of the scanned / per-block
    block-recursive deployment (audio seconds per wall second, one fenced
    dispatch per super-tick / per block); ``dispatches_per_block`` = fenced
    RPC rounds per processed block measured from the obs fence accounting
    (→ 1/N for the scanned path, plus the shared warm-up fences).
    """
    import jax

    from disco_tpu.core.dsp import stft
    from disco_tpu.core.masks import tf_mask
    from disco_tpu.enhance.streaming import (
        initial_stream_state,
        streaming_tango,
        streaming_tango_scan,
    )
    from disco_tpu.milestones import _scene
    from disco_tpu.obs.accounting import fence_count

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    Y, S, N = stft(y), stft(s), stft(n)
    masks = jax.vmap(lambda Sk, Nk: tf_mask(Sk[0], Nk[0], "irm1"))(S, N)
    F, T = Y.shape[-2:]
    u = update_every
    block = 4 * u                      # serve-style block_frames
    if T < blocks_per_dispatch * block:
        # smoke-sized clips (BENCH_DUR_S < ~2 s at N=8): shrink the block so
        # the N-wide window still fits — the lane measures RPC amortization,
        # which only needs N refresh-aligned blocks, not a fixed block size
        block = (T // (blocks_per_dispatch * u)) * u
    window = blocks_per_dispatch * block
    if block < u:
        raise RuntimeError(
            f"clip too short for the scan lane: {T} frames cannot hold "
            f"{blocks_per_dispatch} refresh-aligned blocks"
        )
    state = initial_stream_state(K, C, F, update_every=u)
    avail_b = np.ones((K, block // u), np.float32)
    avail_w = np.ones((K, window // u), np.float32)
    calls = {"scan": 0}

    def run_block(Yb, mb, st):
        return streaming_tango(Yb, mb, mb, update_every=u, policy="local",
                               state=st, z_avail=avail_b)["yf"]

    def run_scan(Yw, mw, st):
        calls["scan"] += 1
        return streaming_tango_scan(
            Yw, mw, mw, update_every=u, policy="local", state=st,
            z_avail=avail_w, blocks_per_dispatch=blocks_per_dispatch,
        )["yf"]

    budget_ms = 1e3 * 256 / FS
    dt_b, dt1_b = _slope_time(run_block, Y[..., :block], masks[..., :block],
                              state, iters=iters)
    f0 = fence_count()
    dt_s, dt1_s = _slope_time(run_scan, Y[..., :window], masks[..., :window],
                              state, iters=iters)
    fences_scan = fence_count() - f0
    rtf_block = budget_ms / (1e3 * dt1_b / block)
    rtf_scan = budget_ms / (1e3 * dt1_s / window)
    dispatches_per_block = (
        fences_scan / (calls["scan"] * blocks_per_dispatch) if calls["scan"] else None
    )
    stats = {
        "block_frames": block,
        "window_frames": window,
        "blocks_per_dispatch": blocks_per_dispatch,
        "rtf_scan_slope": round(budget_ms / (1e3 * dt_s / window), 1),
        "rtf_block_slope": round(budget_ms / (1e3 * dt_b / block), 1),
        "dispatch_ms_scan": round(max(dt1_s - dt_s, 0.0) * 1e3, 2),
        "dispatch_ms_block": round(max(dt1_b - dt_b, 0.0) * 1e3, 2),
    }
    return rtf_scan, rtf_block, dispatches_per_block, stats


def bench_corpus(n_clips=4):
    """End-to-end corpus throughput of the pipelined execution engine
    (``disco_tpu.enhance.pipeline``): clips enhanced per wall-second over a
    self-generated miniature corpus, load → dispatch → batched readback →
    scoring included — the number the overlapped prefetch/dispatch/readback
    engine exists to move, where ``rtf`` only measures the on-device
    kernel.  Reuses the chaos-check miniature-corpus harness
    (``disco_tpu.runs.check``: 4 nodes x 2 mics, 2 s clips).

    Returns (corpus_clips_per_s, pipeline_stats) where pipeline_stats
    carries the engine's overlap gauges (prefetch_stall_ms, readback_ms,
    overlap_efficiency) and the batched-readback count for the run.
    """
    import tempfile
    from pathlib import Path

    from disco_tpu.enhance.driver import enhance_rirs_batched
    from disco_tpu.obs.accounting import device_get_count
    from disco_tpu.runs.check import C as MINI_C
    from disco_tpu.runs.check import K as MINI_K
    from disco_tpu.runs.check import NOISE, SNR_RANGE, _mini_corpus

    rirs = list(range(11001, 11001 + n_clips))
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        corpus = _mini_corpus(tmp / "dataset", rirs=rirs)
        gets0 = device_get_count()
        t0 = time.perf_counter()
        res = enhance_rirs_batched(
            str(corpus), "living", rirs, NOISE, snr_range=SNR_RANGE,
            out_root=str(tmp / "out"), save_fig=False, bucket=8192,
            max_batch=2, n_nodes=MINI_K, mics_per_node=MINI_C, score_workers=2,
        )
        dt = time.perf_counter() - t0
    if len(res) != n_clips:
        raise RuntimeError(f"corpus lane enhanced {len(res)}/{n_clips} clips")
    gauges = obs_registry.snapshot()["gauges"]
    stats = {
        "n_clips": n_clips,
        "clip_dur_s": 2.0,
        "prefetch_stall_ms": gauges.get("prefetch_stall_ms"),
        "readback_ms": gauges.get("readback_ms"),
        "overlap_efficiency": gauges.get("overlap_efficiency"),
        "batched_readbacks": device_get_count() - gets0,
    }
    return n_clips / dt, stats


def bench_serve(n_sessions=4, dur_s=4.0):
    """Online-serving lane: loopback server (``disco_tpu.serve``), N
    concurrent synthetic streaming sessions, continuous batching on the one
    device.  The numbers the lane exists to move: ``serve_blocks_per_s``
    (aggregate enhanced-block throughput across sessions, wall-clock) and
    ``serve_p95_ms`` (per-block request latency p95 — enqueue at the
    scheduler to host-side delivery, from the ``serve_block_latency_ms``
    histogram's reservoir).  A compile warm-up session runs first and the
    histogram is reset, so p95 measures serving, not XLA compiles.

    Returns (serve_blocks_per_s, serve_p95_ms, stats).
    """
    import threading

    from disco_tpu.core.dsp import stft
    from disco_tpu.serve import EnhanceServer, ServeClient, SessionConfig

    Ks, Cs, u = 4, 2, 4
    block = 4 * u
    rng = np.random.default_rng(7)
    Y = np.asarray(stft(rng.standard_normal((Ks, Cs, int(dur_s * FS))).astype(np.float32)))
    F, T = Y.shape[-2:]
    m = rng.uniform(0.05, 0.95, size=(Ks, F, T)).astype(np.float32)
    cfg = SessionConfig(n_nodes=Ks, mics_per_node=Cs, n_freq=F,
                        block_frames=block, update_every=u)
    n_blocks = -(-T // block)

    srv = EnhanceServer(max_sessions=max(8, n_sessions))
    addr = srv.start()
    errors: list[str] = []

    def worker(i):
        try:
            cl = ServeClient(addr)
            cl.open(cfg, session_id=f"bench{i}")
            cl.enhance_clip(Y, m, m)
            cl.close()
            cl.shutdown()
        except Exception as e:
            errors.append(f"serve session {i}: {type(e).__name__}: {e}")

    try:
        worker("warmup")  # compiles the bucket's programs once
        if errors:
            raise RuntimeError("; ".join(errors))
        lat_hist = obs_registry.histogram("serve_block_latency_ms")
        lat_hist.reset()
        # the total's two components (queue-wait vs dispatch-to-delivery):
        # what --blocks-per-super-tick tuning trades against each other
        wait_hist = obs_registry.histogram("serve_queue_wait_ms")
        wait_hist.reset()
        disp_hist = obs_registry.histogram("serve_dispatch_ms")
        disp_hist.reset()
        ticks0 = srv.scheduler.ticks_with_work
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_sessions)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        ticks = srv.scheduler.ticks_with_work - ticks0
        # survival probe (off the measured window): one park -> reattach ->
        # resume cycle, wall ms from the socket kill to the next delivered
        # block of the bit-exact stitched stream — the latency a client
        # actually pays for a dropped connection under the survival layer
        reattach_ms = None
        try:
            import socket as socket_mod

            cl = ServeClient(addr, retry_seed=11)
            cl.open(cfg, session_id="bench-reattach")
            marks: dict = {}

            def on_block(seq, _yf):
                if seq == 1 and "t0" not in marks:
                    marks["t0"] = time.perf_counter()
                    cl._sock.shutdown(socket_mod.SHUT_RDWR)
                elif "t0" in marks and "t1" not in marks:
                    marks["t1"] = time.perf_counter()

            cl.enhance_clip(Y, m, m, on_block=on_block)
            cl.close()
            cl.shutdown()
            if "t1" in marks:
                reattach_ms = round((marks["t1"] - marks["t0"]) * 1e3, 3)
        except Exception:
            pass   # the probe must never fail the lane
    finally:
        srv.stop()
    if errors:
        raise RuntimeError("; ".join(errors))
    total_blocks = n_sessions * n_blocks
    p95_ms = lat_hist.percentile(95.0)
    stats = {
        "n_sessions": n_sessions,
        "blocks_per_session": n_blocks,
        "block_frames": block,
        "clip_dur_s": dur_s,
        "ticks": ticks,
        "p50_ms": lat_hist.percentile(50.0),
        "p99_ms": lat_hist.percentile(99.0),
        "queue_wait_p95_ms": wait_hist.percentile(95.0),
        "dispatch_p95_ms": disp_hist.percentile(95.0),
        "mean_blocks_per_tick": total_blocks / ticks if ticks else None,
        "reattach_ms": reattach_ms,
    }
    return total_blocks / dt, p95_ms, stats


def bench_train(n_steps=8, batch=8):
    """Flywheel training lane: ``train_steps_per_s`` — jitted CRNN
    train-step throughput (``nn.training.make_step_fns``) on synthetic
    windowed batches.  The steps form a sequential state chain, so queuing
    them async and fencing ONCE on the last loss drains the whole chain —
    the same single-fence discipline as every other lane (a per-step fence
    would measure the ~80 ms tunnel RPC n_steps times).  A reduced-width
    CRNN (conv 8/16/16, GRU 64 — pinned in the stats) keeps the trend lane
    cheap on CPU smoke runs; the canonical model rides ``disco-train``.

    Returns (train_steps_per_s, stats).
    """
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state, make_step_fns

    win, n_freq = 21, 257
    model, tx = build_crnn(
        n_ch=1, win_len=win, n_freq=n_freq,
        cnn_filters=(8, 16, 16), rnn_units=(64,), ff_units=(n_freq,),
    )
    rng = np.random.default_rng(5)
    x = rng.standard_normal((batch, win, n_freq)).astype(np.float32)
    y = rng.uniform(0.1, 0.9, (batch, win, n_freq)).astype(np.float32)
    train_step, _ = make_step_fns(model, "all")
    state = create_train_state(model, tx, x[:1], seed=5)
    state, loss = train_step(state, x, y)  # compile + warm
    _fence(loss)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = train_step(state, x, y)
    _fence(loss)
    dt = time.perf_counter() - t0
    stats = {
        "n_steps": n_steps,
        "batch": batch,
        "win_len": win,
        "n_freq": n_freq,
        "model": "crnn(8,16,16)/gru64",
        "step_ms": round(dt / n_steps * 1e3, 3),
    }
    return n_steps / dt, stats


def bench_tap(n_blocks=64):
    """Flywheel tap lane: ``tap_blocks_per_s`` — host-side spool
    throughput of the corpus tap (offer → background shard rotation →
    atomic write + manifest record), measured to a temp dir with
    serve-shaped synthetic blocks.  Pure host work (msgpack + sha256 +
    fsync) — the number that says whether the tap can keep up with the
    serve scheduler's delivery rate without dropping.

    Returns (tap_blocks_per_s, stats).
    """
    import tempfile
    from pathlib import Path

    from disco_tpu.flywheel import CorpusTap

    Ks, Cs, F, Tb = 4, 2, 257, 16
    rng = np.random.default_rng(9)
    Y = (rng.standard_normal((Ks, Cs, F, Tb))
         + 1j * rng.standard_normal((Ks, Cs, F, Tb))).astype(np.complex64)
    yf = (rng.standard_normal((Ks, F, Tb))
          + 1j * rng.standard_normal((Ks, F, Tb))).astype(np.complex64)
    m = rng.uniform(0.05, 0.95, (Ks, F, Tb)).astype(np.float32)
    with tempfile.TemporaryDirectory() as tmp:
        tap = CorpusTap(Path(tmp) / "tap", max_queue_blocks=max(n_blocks, 8),
                        records_per_shard=16)
        t0 = time.perf_counter()
        for i in range(n_blocks):
            tap.offer("bench", i, Y, m, m, yf)
        stats_tap = tap.close()
        dt = time.perf_counter() - t0
    if stats_tap["blocks_dropped"]:
        raise RuntimeError(
            f"tap lane dropped {stats_tap['blocks_dropped']} blocks with an "
            "n_blocks-deep queue — the spool path is broken"
        )
    stats = {
        "n_blocks": n_blocks,
        "block_mb": round(
            (Y.nbytes + yf.nbytes + 2 * m.nbytes) / 1e6, 3
        ),
        "shards_written": stats_tap["shards_written"],
    }
    return n_blocks / dt, stats


def bench_scenes(n_batches=2, n_scenes=8, dur_s=1.0, max_order=8):
    """Scenario-factory lane: ``scenes_per_s`` — batched on-device scene
    simulation throughput through ``disco_tpu.scenes``: every timed batch
    is ONE compiled program (B-scene ISM RIR lattice → dry→wet FFT
    convolve → SNR mixing → reference-mic STFT magnitudes + IRM mask) and
    ONE batched readback, so on the tunneled attachment the lane pays one
    ~80 ms RPC per B scenes instead of per scene.  Each distinct bucket's
    compile is warmed outside the timed window (the retrace budget is
    ``make scene-check``'s business, not a throughput number); the
    readback accounting is asserted so a regression that splits the
    factory into per-scene dispatches fails the lane rather than shipping
    a quietly-worse number.

    Returns (scenes_per_s, stats).
    """
    from disco_tpu.obs.accounting import device_get_count, recompile_count
    from disco_tpu.scenes import draw_scene_batch, simulate_scene_batch

    rng = np.random.default_rng(23)
    batches = [draw_scene_batch(rng, n_scenes, duration_s=dur_s)
               for _ in range(n_batches)]
    for b in batches:  # warm every bucket: compile outside the timed window
        simulate_scene_batch(b, max_order=max_order)
    g0 = device_get_count()
    r0 = recompile_count("scene_batch")
    t0 = time.perf_counter()
    for b in batches:
        simulate_scene_batch(b, max_order=max_order)
    dt = time.perf_counter() - t0
    gets = device_get_count() - g0
    if gets != n_batches:
        raise RuntimeError(
            f"scenes lane issued {gets} batched readbacks for {n_batches} "
            "scene batches — the one-dispatch-per-batch contract is broken"
        )
    stats = {
        "n_batches": n_batches,
        "scenes_per_batch": n_scenes,
        "scene_dur_s": dur_s,
        "max_order": max_order,
        "readbacks": gets,
        "retraces_timed": recompile_count("scene_batch") - r0,
    }
    return n_batches * n_scenes / dt, stats


def bench_promote(dur_s=2.0):
    """Live-flywheel lane: one loopback server with the corpus tap, the
    co-resident trainer and the promotion controller all armed — served
    blocks spool into shards, the trainer interleaves train-step slices on
    the dispatch thread between ticks and republishes generations into the
    store, and the controller canaries + promotes each one (canary swap at
    a block boundary → SLO-gated canary window → fleet adoption + atomic
    ``ACTIVE`` flip).

    ``flywheel_generations`` counts the complete tap→train→publish→canary→
    promote generations the loop closed — the lane's liveness bit: 0 means
    the flywheel never turned.  ``tap_to_promotion_ms`` is the p50 of the
    controller's own staged_t→flip observations over those generations.
    The SDR leg is off (no external scorer in a bench) and the wall-clock
    SLO legs are relaxed to ceilings a slow host cannot trip — host speed
    must never decide whether the flywheel turns — while the rate legs
    (drop/evict) keep production targets.

    Returns (tap_to_promotion_ms, flywheel_generations, stats).
    """
    import tempfile
    from pathlib import Path

    from disco_tpu.core.dsp import stft
    from disco_tpu.flywheel.resident import ResidentTrainer
    from disco_tpu.flywheel.tap import CorpusTap
    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state
    from disco_tpu.promote.controller import PromotionController
    from disco_tpu.promote.store import GenerationStore
    from disco_tpu.serve import EnhanceServer, ServeClient, SessionConfig

    Ks, Cs, u = 4, 2, 4
    block = 2 * u
    gens_target = 2
    rng = np.random.default_rng(13)
    Y = np.asarray(
        stft(rng.standard_normal((Ks, Cs, int(dur_s * FS))).astype(np.float32)))
    F, T = Y.shape[-2:]
    n_blocks = T // block
    # reduced-width CRNN (same spirit as the train lane): the lane measures
    # flywheel machinery, not mask quality
    model, tx = build_crnn(
        n_ch=1, win_len=block // 2, n_freq=F, cnn_filters=(4,),
        pool_kernels=((1, 4),), conv_padding=((0, 1),), rnn_units=(16,),
        ff_units=(F,), rnn_dropouts=0.0)
    arch = dict(n_ch=1, win_len=block // 2, n_freq=F, cnn_filters=(4,),
                pool_kernels=((1, 4),), conv_padding=((0, 1),),
                rnn_units=(16,), ff_units=(F,), rnn_dropouts=0.0)
    x0 = np.zeros((1, 1, block // 2, F), np.float32)
    state = create_train_state(model, tx, x0, seed=13)
    vars_a = {"params": state.params, "batch_stats": state.batch_stats}
    cfg = SessionConfig(n_nodes=Ks, mics_per_node=Cs, n_freq=F,
                        block_frames=block, update_every=u, masks="model")
    with tempfile.TemporaryDirectory() as tmp:
        store = GenerationStore(Path(tmp) / "gens")
        inc = store.stage_variables(vars_a, arch=arch, source="bench")
        store.set_active(inc.gen_id)
        tap = CorpusTap(Path(tmp) / "tap", records_per_shard=2)
        tr = ResidentTrainer(Path(tmp) / "tap", Path(tmp) / "train",
                             promote_dir=store.root, arch=arch,
                             batch_size=4, steps_per_tick=4,
                             publish="always", publish_every=1,
                             recent_shards=6)
        ctl = PromotionController(store, canary_frac=1.0, sdr_gate_db=None,
                                  slo_gate=True,
                                  slo_targets={"serve_p95_ms": 60000.0,
                                               "queue_wait_p95_ms": 60000.0},
                                  window_blocks=2,
                                  gate_timeout_s=30.0, poll_s=0.005)
        srv = EnhanceServer(max_sessions=2, tap=tap, promote=ctl, resident=tr)
        promotions0 = obs_registry.peek_counter("model_promotions")
        try:
            addr = srv.start()
            cl = ServeClient(addr)
            cl.open(cfg, session_id="bench-promote")

            def pump(i):
                lo = (i % n_blocks) * block   # synthetic content, looped
                cl.send_block(Y[..., lo:lo + block])
                cl.recv_enhanced(i, timeout_s=120)

            for i in range(2):                # compile warm-up (incumbent)
                pump(i)
            # compile-time blocks out of the latency reservoirs: the SLO
            # leg of the promotion gate judges serving, not XLA compiles
            # (same exclusion bench_serve applies to its p95)
            obs_registry.histogram("serve_block_latency_ms").reset()
            obs_registry.histogram("serve_queue_wait_ms").reset()
            t0 = time.perf_counter()
            rounds = 2
            while rounds < 400:
                done = (obs_registry.peek_counter("model_promotions")
                        - promotions0)
                if done >= gens_target:
                    break
                pump(rounds)
                rounds += 1
            wall_ms = (time.perf_counter() - t0) * 1e3
            cl.close()
            cl.shutdown()
        finally:
            srv.stop()
            tap.close()
        generations = (obs_registry.peek_counter("model_promotions")
                       - promotions0)
        if generations < gens_target:
            raise RuntimeError(
                f"flywheel lane closed only {generations} generation(s) in "
                f"{rounds} paced blocks — the live loop never turned "
                f"(trainer: {tr.stats()})")
        trs = tr.stats()
        shards = tap.stats()["shards_written"]
    # the committed latency is the controller's own staged_t→ACTIVE-flip
    # observation, p50 over this run's promoted generations; wall_ms (time
    # to close gens_target generations as seen from the bench loop) rides
    # in stats as the cross-check
    tap_ms = obs_registry.histogram("tap_to_promotion_ms").percentile(50.0)
    if tap_ms is None:
        tap_ms = wall_ms
    stats = {
        "rounds": rounds,
        "block_frames": block,
        "canary_window_blocks": 2,
        "wall_ms": round(wall_ms, 3),
        "epochs_done": trs["epochs_done"],
        "train_steps": trs["steps_total"],
        "generations_published": trs["generations_published"],
        "shards_written": shards,
        "model": "crnn(4)/gru16",
    }
    return tap_ms, generations, stats


def bench_span_overhead(n_disabled=200_000, n_enabled=2000):
    """Causal-tracing seam cost: ``span_overhead_ns`` — the per-call delta
    between the tracing-ENABLED hot path (span bookkeeping + flight-ring
    append, the ``disco-serve --trace`` configuration) and the DISABLED
    production seam, which must be a measured no-op (one attribute check;
    the strict-no-op contract of ``obs.trace`` that ``make perf-check``
    asserts at ≈0).  Pure host work, no jax.

    Returns (span_overhead_ns, stats) where stats carries the two raw
    lanes (``disabled_ns`` is the number the no-op contract is judged on).
    """
    from disco_tpu.obs import flight as obs_flight
    from disco_tpu.obs import trace as obs_trace

    ctx = obs_trace.SpanCtx(trace=obs_trace.new_id(), span=obs_trace.new_id())
    t0 = time.perf_counter()
    for _ in range(n_disabled):
        obs_trace.span("dispatch", ctx)
    disabled_ns = (time.perf_counter() - t0) / n_disabled * 1e9
    obs_flight.enable(capacity=64)   # the ring sink; JSONL rides --obs-log
    obs_trace.enable()
    try:
        t0 = time.perf_counter()
        for _ in range(n_enabled):
            obs_trace.span("dispatch", ctx, tick=0)
        enabled_ns = (time.perf_counter() - t0) / n_enabled * 1e9
    finally:
        obs_trace.disable()
        obs_flight.disable()
    stats = {
        "disabled_ns": round(disabled_ns, 1),
        "enabled_ns": round(enabled_ns, 1),
        "n_disabled": n_disabled,
        "n_enabled": n_enabled,
    }
    return enabled_ns - disabled_ns, stats


def bench_numpy(dur_s=2.0):
    from tests.reference_impls import tango_np

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    t0 = time.perf_counter()
    tango_np(np.asarray(y, np.float64), np.asarray(s, np.float64), np.asarray(n, np.float64))
    dt = time.perf_counter() - t0
    return K * dur_s / dt


def _start_watchdog(timeout_s: float):
    """Emit a diagnostic JSON line and exit if the bench makes no progress.

    The tunneled chip attachment claims the device at first jax use and
    BLOCKS INDEFINITELY while another (possibly dead) holder keeps the
    claim — observed wedged for hours after a killed process.  Without
    this, a wedged chip turns the bench record into silence; with it, the
    record says what happened.  Disable with BENCH_WATCHDOG_S=0.

    With --obs-log active the same diagnostic also lands in the event
    stream as a ``watchdog`` event (flushed before ``os._exit``), so the
    sideband log tells the story even when stdout is lost.
    """
    import threading

    done = threading.Event()

    def fire():
        if not done.wait(timeout_s):
            obs_events.record(
                "watchdog", stage="bench",
                timeout_s=timeout_s,
                suspected_cause="wedged tunneled device attachment "
                                "(chip claim held by a dead process) or an "
                                "undersized BENCH_WATCHDOG_S for this backend",
                **obs_registry.snapshot(),
            )
            print(
                json.dumps(
                    {
                        "metric": "rtf_8node_mwf_enhancement",
                        "value": None,
                        "unit": "x_realtime",
                        "error": f"bench did not complete within BENCH_WATCHDOG_S={timeout_s:.0f}s. "
                                 "On the tunneled TPU the usual cause is a wedged device "
                                 "attachment (chip claim held by a dead process blocks the "
                                 "first jax use indefinitely — see README/verify notes); a "
                                 "legitimately slow run (CPU backend, raised BENCH_* knobs) "
                                 "needs a larger BENCH_WATCHDOG_S.",
                    }
                ),
                flush=True,
            )
            os._exit(3)

    threading.Thread(target=fire, daemon=True).start()
    return done


def build_parser():
    p = argparse.ArgumentParser(
        description="Headline RTF benchmark (prints ONE JSON line to stdout)"
    )
    p.add_argument(
        "--obs-log",
        default=os.environ.get("BENCH_OBS_LOG") or None,
        help="append the full telemetry event stream (manifest, per-lane "
             "stage events, watchdog diagnostics, the final record) to this "
             "JSONL file; stdout stays exactly one JSON line either way",
    )
    return p


def _meter_join(r, batch, dur_s, rtf_scan, scan_stats, serve_bps):
    """The in-record roofline join: measured stage/lane times × the
    analytic disco-meter stage costs at THIS run's workload
    (``disco_tpu.analysis.meter.stages`` — abstract tracing, milliseconds
    of host work, no extra device dispatch).  Returns the record fields:
    ``mfu_by_stage`` / ``hbm_gbps_by_stage`` (per timed offline stage),
    ``lane_mfu`` / ``lane_flops`` (streaming-scan window, serve block and
    fused-solver lanes — the RTF-only lanes finally get attributable
    flops), ``workload`` and ``cost_model_version``.  These are the
    MODEL's conservative algorithmic flops, deliberately a different
    convention from the XLA ``cost_analysis`` flops behind the headline
    ``mfu``/``flops_per_clip`` — ``cost_model_version`` marks which
    convention a consumer is joining against."""
    from disco_tpu.analysis.meter import costmodel, stages

    peak = _peak_flops()
    w = stages.Workload(batch=batch, dur_s=dur_s, fs=FS,
                        n_nodes=K, mics_per_node=C)
    sc = stages.offline_stage_costs(w)
    mfu_by_stage, gbps_by_stage = {}, {}
    for sk, ms in (r.get("stage_ms") or {}).items():
        cost = sc.get(sk)
        if not cost or not ms:
            continue
        secs = ms / 1e3
        mfu_by_stage[sk] = round(cost["flops"] / secs / peak, 6)
        gbps_by_stage[sk] = round(cost["traffic_bytes"] / secs / 1e9, 3)
    lane_mfu, lane_flops = {}, {}
    if rtf_scan and scan_stats:
        scost = stages.streaming_scan_cost(
            dur_s=dur_s, fs=FS,
            blocks_per_dispatch=scan_stats["blocks_per_dispatch"])
        if scost and scost["window_frames"] == scan_stats["window_frames"]:
            # rtf_scan is tunnel-included per-window realtime factor:
            # wall seconds per window = frames x hop / fs / rtf
            wall_s = scost["window_frames"] * 256 / FS / rtf_scan
            lane_flops["streaming_scan_window"] = scost["flops"]
            lane_mfu["streaming_scan"] = round(
                scost["flops"] / wall_s / peak, 6)
    if serve_bps:
        bcost = stages.serve_block_cost(
            dur_s=float(os.environ.get("BENCH_SERVE_DUR_S", 4.0)), fs=FS)
        lane_flops["serve_block"] = bcost["flops"]
        lane_mfu["serve"] = round(bcost["flops"] * serve_bps / peak, 6)
    if r.get("rtf_fused"):
        fcost = stages.fused_pipeline_cost(w)
        audio_s = batch * K * dur_s
        dt_fused = audio_s / r["rtf_fused"]
        lane_flops["fused_pipeline"] = fcost["flops"]
        lane_mfu["fused_solver"] = round(
            fcost["flops"] / dt_fused / peak, 6)
    return {
        "mfu_by_stage": mfu_by_stage,
        "hbm_gbps_by_stage": gbps_by_stage,
        "lane_mfu": lane_mfu,
        "lane_flops": lane_flops,
        "workload": {"batch": batch, "dur_s": dur_s, "fs": FS,
                     "n_nodes": K, "mics_per_node": C},
        "cost_model_version": costmodel.VERSION,
        "meter_error": None,
    }


def main(argv=None):
    args = build_parser().parse_args(argv)
    # knobs: BENCH_BATCH / BENCH_DUR_S / BENCH_ITERS override the workload
    # size (defaults are the headline config; smaller values for CPU smoke
    # tests).
    batch = int(os.environ.get("BENCH_BATCH", 16))
    dur_s = float(os.environ.get("BENCH_DUR_S", 10.0))
    iters = int(os.environ.get("BENCH_ITERS", 5))
    timeout_s = float(os.environ.get("BENCH_WATCHDOG_S", 1800))
    if args.obs_log:
        obs_events.enable(args.obs_log)
        obs_events.write_manifest(
            config={"batch": batch, "dur_s": dur_s, "iters": iters,
                    "watchdog_s": timeout_s},
            tool="bench.py",
        )
    done = _start_watchdog(timeout_s) if timeout_s > 0 else None
    try:
        with obs_events.stage("bench_jax", batch=batch, clip_dur_s=dur_s, iters=iters):
            r = bench_jax(batch=batch, dur_s=dur_s, iters=iters)
    except Exception as e:
        # A failed backend init (e.g. the tunneled chip service answering
        # UNAVAILABLE, as in BENCH_r02) must still leave a PARSEABLE record:
        # one JSON line naming the cause, then a nonzero exit.  A raw stack
        # trace is an artifact only a human can read.
        # Name the backend when init got far enough to know it.  Probe
        # BEFORE disarming the watchdog, and only when a backend is
        # ALREADY initialized (xla_bridge._backends non-empty — merely
        # having `jax` imported is not enough): default_backend() on an
        # uninitialized jax would be the FIRST device use, and on the
        # tunnel that claims the chip and can block indefinitely while
        # the failure record must still print.
        backend = None
        try:
            if "jax" in sys.modules:
                from jax._src import xla_bridge as _xb

                if getattr(_xb, "_backends", None):
                    backend = sys.modules["jax"].default_backend()
        except Exception:
            backend = None
        if done is not None:
            done.set()
        record = {
            "metric": "rtf_8node_mwf_enhancement",
            "backend": backend,
            "value": None,
            "unit": "x_realtime",
            "error": f"{type(e).__name__}: {e}"[:500],
        }
        obs_events.record("bench_result", stage="bench", **record)
        obs_events.disable()
        print(json.dumps(record), flush=True)
        raise SystemExit(2)
    streaming_error = None
    try:
        with obs_events.stage("bench_streaming", clip_dur_s=dur_s, iters=iters):
            lat_ms, budget_ms, stream_rtf = bench_streaming(dur_s=dur_s, iters=iters)
    except Exception as e:
        # like the jacobi lane: the artifact must distinguish "lane crashed"
        # from "not measured"
        lat_ms = budget_ms = stream_rtf = None
        streaming_error = f"{type(e).__name__}: {e}"[:200]
    # amortized streaming lane: scanned super-ticks vs per-block dispatch
    # (BENCH_BLOCKS_PER_DISPATCH, 0 disables the lane)
    rtf_scan = rtf_block = dpb = scan_stats = scan_error = None
    n_dispatch = int(os.environ.get("BENCH_BLOCKS_PER_DISPATCH", 8))
    if n_dispatch > 0:
        try:
            with obs_events.stage("bench_streaming_scan",
                                  blocks_per_dispatch=n_dispatch, iters=iters):
                rtf_scan, rtf_block, dpb, scan_stats = bench_streaming_scan(
                    dur_s=dur_s, blocks_per_dispatch=n_dispatch, iters=iters
                )
        except Exception as e:
            scan_error = f"{type(e).__name__}: {e}"[:200]
    # corpus lane: end-to-end clips/s through the pipelined engine
    # (BENCH_CORPUS_CLIPS clips; 0 disables the lane)
    corpus_cps = corpus_stats = corpus_error = None
    n_corpus = int(os.environ.get("BENCH_CORPUS_CLIPS", 4))
    if n_corpus > 0:
        try:
            with obs_events.stage("bench_corpus", n_clips=n_corpus):
                corpus_cps, corpus_stats = bench_corpus(n_clips=n_corpus)
        except Exception as e:
            corpus_error = f"{type(e).__name__}: {e}"[:200]
    # serve lane: online service throughput/latency over loopback
    # (BENCH_SERVE_SESSIONS concurrent sessions; 0 disables the lane)
    serve_bps = serve_p95 = serve_stats = serve_error = None
    n_serve = int(os.environ.get("BENCH_SERVE_SESSIONS", 4))
    if n_serve > 0:
        try:
            with obs_events.stage("bench_serve", n_sessions=n_serve):
                serve_bps, serve_p95, serve_stats = bench_serve(
                    n_sessions=n_serve,
                    dur_s=float(os.environ.get("BENCH_SERVE_DUR_S", 4.0)),
                )
        except Exception as e:
            serve_error = f"{type(e).__name__}: {e}"[:200]
    # flywheel training lane: train_steps_per_s of the jitted CRNN step
    # (BENCH_TRAIN_STEPS steps; 0 disables the lane)
    train_sps = train_stats = train_error = None
    n_train = int(os.environ.get("BENCH_TRAIN_STEPS", 8))
    if n_train > 0:
        try:
            with obs_events.stage("bench_train", n_steps=n_train):
                train_sps, train_stats = bench_train(
                    n_steps=n_train,
                    batch=int(os.environ.get("BENCH_TRAIN_BATCH", 8)),
                )
        except Exception as e:
            train_error = f"{type(e).__name__}: {e}"[:200]
    # flywheel tap lane: host-side corpus-tap spool throughput
    # (BENCH_TAP_BLOCKS blocks; 0 disables the lane)
    tap_bps = tap_stats = tap_error = None
    n_tap = int(os.environ.get("BENCH_TAP_BLOCKS", 64))
    if n_tap > 0:
        try:
            with obs_events.stage("bench_tap", n_blocks=n_tap):
                tap_bps, tap_stats = bench_tap(n_blocks=n_tap)
        except Exception as e:
            tap_error = f"{type(e).__name__}: {e}"[:200]
    # scenario-factory lane: batched scene-simulation throughput
    # (BENCH_SCENE_BATCHES batches of BENCH_SCENE_B scenes; 0 disables)
    scenes_sps = scene_stats = scene_error = None
    n_scene_batches = int(os.environ.get("BENCH_SCENE_BATCHES", 2))
    if n_scene_batches > 0:
        try:
            with obs_events.stage("bench_scenes", n_batches=n_scene_batches):
                scenes_sps, scene_stats = bench_scenes(
                    n_batches=n_scene_batches,
                    n_scenes=int(os.environ.get("BENCH_SCENE_B", 8)),
                    dur_s=float(os.environ.get("BENCH_SCENE_DUR_S", 1.0)),
                    max_order=int(os.environ.get("BENCH_SCENE_ORDER", 8)),
                )
        except Exception as e:
            scene_error = f"{type(e).__name__}: {e}"[:200]
    # live-flywheel lane: complete tap→train→publish→promote generations
    # closed on a loopback server with the co-resident trainer armed, plus
    # the staged→flip promotion latency (BENCH_PROMOTE=0 disables the lane)
    promote_ms = generations = promote_stats = promote_error = None
    if int(os.environ.get("BENCH_PROMOTE", 1)) > 0:
        try:
            with obs_events.stage("bench_promote"):
                promote_ms, generations, promote_stats = bench_promote()
        except Exception as e:
            promote_error = f"{type(e).__name__}: {e}"[:200]
    # causal-tracing seam cost: enabled-vs-disabled per-span delta, with
    # the disabled lane doubling as the measured proof of the strict-no-op
    # contract (always on — it costs milliseconds of pure host work)
    span_overhead = span_stats = span_error = None
    try:
        with obs_events.stage("bench_span"):
            span_overhead, span_stats = bench_span_overhead()
    except Exception as e:
        span_error = f"{type(e).__name__}: {e}"[:200]
    if done is not None:
        done.set()
    # BENCH_NP_DUR_S=0 skips the float64 NumPy baseline (CPU smoke runs —
    # the loop-per-(node,freq) reference costs minutes on a small host)
    np_dur_s = float(os.environ.get("BENCH_NP_DUR_S", 2.0))
    try:
        with obs_events.stage("bench_numpy"):
            rtf_np = bench_numpy(dur_s=np_dur_s) if np_dur_s > 0 else None
    except Exception:
        rtf_np = None
    vs = (r["rtf"] / rtf_np) if rtf_np else None
    # the roofline join (analysis/meter): per-stage MFU / HBM GB/s and
    # per-lane flop attribution — pure host-side tracing, and a failure
    # must degrade to a named error, never fail the bench
    meter = {"mfu_by_stage": None, "hbm_gbps_by_stage": None,
             "lane_mfu": None, "lane_flops": None, "workload": None,
             "cost_model_version": None, "meter_error": None}
    try:
        with obs_events.stage("bench_meter"):
            meter = _meter_join(r, batch, dur_s, rtf_scan, scan_stats,
                                serve_bps)
    except Exception as e:
        meter["meter_error"] = f"{type(e).__name__}: {e}"[:200]
    # the ACTIVE jax backend, recorded so `disco-obs compare` can refuse
    # to judge a CPU-fallback run against an on-TPU baseline (the
    # BENCH_r06 hazard: a silently-degraded backend poisons the r05
    # trajectory with a bogus "regression")
    import jax

    record = {
        "metric": "rtf_8node_mwf_enhancement",
        "backend": jax.default_backend(),
        "value": round(r["rtf"], 2),
        "unit": "x_realtime",
        "vs_baseline": round(vs, 2) if vs else None,
        "value_single_dispatch": round(r["rtf_single_dispatch"], 2),
        "solver_default": "power",
        "cov_impl": r.get("cov_impl"),
        "stft_impl": r.get("stft_impl"),
        "precision": r.get("precision"),
        "rtf_bf16": round(r["rtf_bf16"], 2) if r.get("rtf_bf16") else None,
        "bf16_max_rel_err": (round(r["bf16_max_rel_err"], 6)
                             if r.get("bf16_max_rel_err") is not None else None),
        "bf16_error": r.get("bf16_error"),
        "rtf_eigh_solver": round(r["rtf_eigh"], 2),
        "rtf_jacobi_solver": round(r["rtf_jacobi"], 2) if r.get("rtf_jacobi") else None,
        "jacobi_error": r.get("jacobi_error"),
        "rtf_fused_solver": round(r["rtf_fused"], 2) if r.get("rtf_fused") else None,
        "fused_error": r.get("fused_error"),
        "rtf_chained_clip": round(r["rtf_chained"], 2) if r.get("rtf_chained") else None,
        "chained_clip_error": r.get("chained_error"),
        "rtf_fused_step1": round(r["rtf_fused_step1"], 2) if r.get("rtf_fused_step1") else None,
        "fused_step1_error": r.get("fused_step1_error"),
        "solver_lanes": r.get("solver_lanes"),
        "rtf_covfused": round(r["rtf_covfused"], 2) if r.get("rtf_covfused") else None,
        "covfused_error": r.get("covfused_error"),
        "dispatch_overhead_ms": r["dispatch_overhead_ms"],
        "latency_ms_frame": round(lat_ms, 4) if lat_ms else None,
        "frame_budget_ms": round(budget_ms, 3) if budget_ms else None,
        "streaming_rtf": round(stream_rtf, 1) if stream_rtf else None,
        "streaming_error": streaming_error,
        "streaming_rtf_scan": round(rtf_scan, 1) if rtf_scan else None,
        "streaming_rtf_block": round(rtf_block, 1) if rtf_block else None,
        "blocks_per_dispatch": n_dispatch if rtf_scan else None,
        "dispatches_per_block": round(dpb, 4) if dpb is not None else None,
        "streaming_scan": scan_stats,
        "streaming_scan_error": scan_error,
        "corpus_clips_per_s": round(corpus_cps, 3) if corpus_cps else None,
        "corpus_pipeline": corpus_stats,
        "corpus_error": corpus_error,
        "serve_blocks_per_s": round(serve_bps, 2) if serve_bps else None,
        "serve_p95_ms": round(serve_p95, 3) if serve_p95 is not None else None,
        "serve_sessions": serve_stats,
        "serve_error": serve_error,
        "train_steps_per_s": round(train_sps, 3) if train_sps else None,
        "train_stats": train_stats,
        "train_error": train_error,
        "tap_blocks_per_s": round(tap_bps, 2) if tap_bps else None,
        "tap_stats": tap_stats,
        "tap_error": tap_error,
        "scenes_per_s": round(scenes_sps, 3) if scenes_sps else None,
        "scene_stats": scene_stats,
        "scene_error": scene_error,
        "tap_to_promotion_ms": (round(promote_ms, 1)
                                if promote_ms is not None else None),
        "flywheel_generations": generations,
        "model_promotions": generations,
        "promote_stats": promote_stats,
        "promote_error": promote_error,
        "span_overhead_ns": (round(span_overhead, 1)
                             if span_overhead is not None else None),
        "span_stats": span_stats,
        "span_error": span_error,
        "mfu": round(r["mfu"], 6) if r["mfu"] else None,
        "flops_per_clip": round(r["flops_per_clip"]) if r["flops_per_clip"] else None,
        "stage_ms": r["stage_ms"],
        "mfu_by_stage": meter["mfu_by_stage"],
        "hbm_gbps_by_stage": meter["hbm_gbps_by_stage"],
        "lane_mfu": meter["lane_mfu"],
        "lane_flops": meter["lane_flops"],
        "workload": meter["workload"],
        "cost_model_version": meter["cost_model_version"],
        "meter_error": meter["meter_error"],
        "notes": "value = DEFAULT pipeline (solver=power since round 4; rtf_eigh_solver is the reference-bit-matching lane; rtf_fused_solver = the VMEM-resident cov->whiten->Jacobi->filter solve (ops/mwf_ops.py); rtf_chained_clip = the ENTIRE per-clip chain — STFT, masks, both MWF steps, ISTFT — as ONE dispatched program (enhance/fused.py tango_clip_fused; stage_ms.chained_clip is its slope in ms, to set against the sum of the staged rows which each pay their own fenced dispatch on the tunnel); rtf_fused_step1 = the step-1 local MWF with ALL KxF pencils through the batch-in-lanes fused solve (compute_z_signals(solver='fused'); stage_ms.step1_fused_mwf vs stage_ms.step1_local_mwf is the like-for-like stage comparison against the default per-node power path); solver_lanes records each solve lane's resolved spec AND concrete impl post-ops.resolve, so records distinguish jacobi XLA from pallas from fused without re-running; cov_impl/stft_impl fields name the ACTIVE kernels behind the 'auto' defaults — fused pallas on TPU, DISCO_TPU_COV_IMPL/DISCO_TPU_STFT_IMPL override; the hot path is fused: one spec+magnitude STFT over the stacked y/s/n streams, irm masks from the emitted magnitudes, mask-folded covariance accumulation; precision names the default lane, rtf_bf16/bf16_max_rel_err the opt-in bf16 compute lane measured against it), on-device RTF via k-queued slope timing (tunnel adds ~80ms/dispatch, reported separately; value_single_dispatch includes it); stages timed as separate fenced programs (full pipeline fuses tighter); streaming_rtf_scan / streaming_rtf_block = tunnel-included realtime factors of the scanned super-tick (blocks_per_dispatch blocks per fenced dispatch, streaming_tango_scan) vs per-block block-recursive deployment, dispatches_per_block from the obs fence accounting; corpus_clips_per_s = end-to-end miniature-corpus throughput through the pipelined prefetch/dispatch/readback engine (load+scoring included); serve_blocks_per_s / serve_p95_ms = online-service continuous-batching throughput and request-latency p95 over loopback (BENCH_SERVE_SESSIONS concurrent streaming sessions, compile warm-up excluded; serve_queue_wait/dispatch p95s split admission wait from device time); train_steps_per_s = flywheel CRNN train-step throughput (reduced-width model pinned in train_stats, one fence over the async step chain); tap_blocks_per_s = host-side corpus-tap spool throughput (offer -> shard rotation -> atomic write); scenes_per_s = batched scenario-factory throughput (disco_tpu.scenes: B rooms' ISM RIRs + convolve + SNR mix + STFT/mask as ONE compiled program and ONE batched readback per batch — compile warmed outside the timed window, scene_stats.readbacks asserts the one-dispatch-per-batch contract the scene-check gate pins); tap_to_promotion_ms = live-flywheel promotion latency on a loopback server with the corpus tap, the co-resident trainer and the promotion controller all armed — served blocks tapped into shards -> trainer slices interleaved on the dispatch thread -> publish into the generation store -> canary swap at a block boundary -> SLO-gated canary window -> fleet adoption + atomic ACTIVE flip (p50 of the controller's own staged_t->flip observations; flywheel_generations counts the COMPLETE tap->train->publish->promote generations the live loop closed and doubles as the lane's liveness bit, model_promotions keeps the completed-rollout alias); span_overhead_ns = causal-tracing per-span cost, enabled (span bookkeeping + flight ring) minus disabled (the strict-no-op seam — span_stats.disabled_ns is the measured no-op, perf-check asserts it ~0); numpy baseline at 2s clips; MFU vs dense-f32 peak (pipeline is FFT/small-eig bound by design); mfu_by_stage/hbm_gbps_by_stage = measured stage_ms joined with the analytic disco-meter stage costs at this run's workload (analysis/meter/stages.py — conservative algorithmic flops under cost_model_version conventions, deliberately NOT the XLA cost_analysis flops behind mfu/flops_per_clip), lane_mfu/lane_flops attribute the streaming-scan window, serve block, and fused-solver lanes through the same model (disco-obs roofline renders the full verdict table from this record)",
    }
    # sideband first (mirror of the stdout record + final counter snapshot),
    # THEN the one stdout line — events go to the file, never stdout.
    obs_events.record("bench_result", stage="bench", **record)
    obs_events.record("counters", **obs_registry.snapshot())
    obs_events.disable()
    print(json.dumps(record))


if __name__ == "__main__":
    main()
