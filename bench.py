"""Headline benchmark: real-time factor of 8-node MWF (TANGO) speech
enhancement @16 kHz (BASELINE.md north star), with a FLOP model, MFU and a
per-stage wall-time breakdown (VERDICT round-1 item 4).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"flops_per_clip", "stage_ms", ...}.  ``value`` is audio-seconds enhanced per
wall-second (x realtime) for the jitted batched TPU pipeline; ``vs_baseline``
is the speedup over the float64 NumPy reference implementation (the
loop-per-(node,freq) formulas of reference tango.py:252-457) measured on this
same host at 2 s clip length (long enough to amortize NumPy setup; the
round-1 1 s extrapolation overstated the NumPy side's startup share).

FLOPs come from XLA's own cost model (``compiled.cost_analysis()['flops']``)
over the exact compiled program, not a hand count; MFU divides by the
device's peak dense-f32 throughput (override with BENCH_PEAK_TFLOPS).  The
pipeline is FFT- and small-hermitian-eig-dominated (257-point spectra,
C<=11 matrices), so it sits on the memory/latency side of the roofline, not
the MXU side — a LOW MFU with a HIGH RTF is the expected signature, and the
stage breakdown shows where the time actually goes.
"""
import json
import os
import time

import numpy as np

from disco_tpu.milestones import _fence, _scene

FS = 16000
K, C = 8, 4  # 8-node, 4 mics per node (north-star config)

# peak dense fp32 TFLOP/s by device kind (MXU peak; bf16 is ~2x these)
_PEAK_TFLOPS = {
    "TPU v4": 137.5,
    "TPU v5e": 98.0,
    "TPU v5 lite": 98.0,
    "TPU v5p": 229.5,
    "TPU v6e": 459.0,
    "cpu": 0.5,
}


def _peak_flops():
    import jax

    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = jax.devices()[0].device_kind
    for name, tf in _PEAK_TFLOPS.items():
        if name.lower() in kind.lower():
            return tf * 1e12
    return _PEAK_TFLOPS["cpu"] * 1e12


def _time_fn(fn, *args, iters=5):
    """Median fenced wall time of an already-compiled jitted callable."""
    fence = _fence
    fence(fn(*args))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fence(fn(*args))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def bench_jax(batch=16, dur_s=10.0, iters=5):
    """Returns (rtf, flops_per_clip, mfu, stage_ms)."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.enhance import compute_z_signals, oracle_masks, tango

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    yb = jnp.asarray(np.stack([y] * batch))
    sb = jnp.asarray(np.stack([s] * batch))
    nb = jnp.asarray(np.stack([n] * batch))

    @jax.jit
    def run(yb, sb, nb):
        def one(y, s, n):
            Y, S, N = stft(y), stft(s), stft(n)
            m = oracle_masks(S, N, "irm1")
            return tango(Y, S, N, m, m, policy="local").yf

        # Return the full enhanced spectra: jit outputs must be materialized,
        # so the timed program is exactly the production program.
        return jax.vmap(one)(yb, sb, nb)

    dt = _time_fn(run, yb, sb, nb, iters=iters)
    audio_s = batch * K * dur_s  # per-node enhanced outputs
    rtf = audio_s / dt

    # ---- FLOP model: XLA's cost analysis of the exact compiled program
    flops_total = None
    try:
        cost = jax.jit(run).lower(yb, sb, nb).compile().cost_analysis()
        if cost:
            flops_total = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass
    mfu = (flops_total / dt) / _peak_flops() if flops_total else None
    flops_per_clip = flops_total / batch if flops_total else None

    # ---- per-stage breakdown (each stage timed as its own fenced jitted
    # program on the same data; XLA fuses more aggressively inside the full
    # pipeline, so stages slightly over-add — noted in the JSON)
    jstft = jax.jit(lambda x: stft(x))
    Yb, Sb, Nb = jstft(yb), jstft(sb), jstft(nb)
    jmask = jax.jit(jax.vmap(lambda S, N: oracle_masks(S, N, "irm1")))
    Mb = jmask(Sb, Nb)
    jstep1 = jax.jit(
        jax.vmap(lambda Y, S, N, m: compute_z_signals(None, None, None, Y=Y, S=S, N=N, masks_z=m)["z_y"])
    )
    jfull = jax.jit(
        jax.vmap(lambda Y, S, N, m: tango(Y, S, N, m, m, policy="local").yf)
    )
    yf = jfull(Yb, Sb, Nb, Mb)
    jistft = jax.jit(lambda Z: istft(Z, length=L))

    t_stft = _time_fn(jstft, yb, iters=iters) * 3  # y, s, n streams
    t_mask = _time_fn(jmask, Sb, Nb, iters=iters)
    t_step1 = _time_fn(jstep1, Yb, Sb, Nb, Mb, iters=iters)
    t_full = _time_fn(jfull, Yb, Sb, Nb, Mb, iters=iters)
    t_istft = _time_fn(jistft, yf, iters=iters)
    stage_ms = {
        "stft_x3": round(t_stft * 1e3, 2),
        "masks": round(t_mask * 1e3, 2),
        "step1_local_mwf": round(t_step1 * 1e3, 2),
        "step2_exchange_mwf": round(max(t_full - t_step1, 0.0) * 1e3, 2),
        "istft": round(t_istft * 1e3, 2),
        "full_pipeline": round(dt * 1e3, 2),
    }
    return rtf, flops_per_clip, mfu, stage_ms


def bench_numpy(dur_s=2.0):
    from tests.reference_impls import tango_np

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, noise_scale=0.5)
    t0 = time.perf_counter()
    tango_np(np.asarray(y, np.float64), np.asarray(s, np.float64), np.asarray(n, np.float64))
    dt = time.perf_counter() - t0
    return K * dur_s / dt


def main():
    rtf, flops_per_clip, mfu, stage_ms = bench_jax()
    try:
        rtf_np = bench_numpy()
    except Exception:
        rtf_np = None
    vs = (rtf / rtf_np) if rtf_np else None
    print(
        json.dumps(
            {
                "metric": "rtf_8node_mwf_enhancement",
                "value": round(rtf, 2),
                "unit": "x_realtime",
                "vs_baseline": round(vs, 2) if vs else None,
                "mfu": round(mfu, 6) if mfu else None,
                "flops_per_clip": round(flops_per_clip) if flops_per_clip else None,
                "stage_ms": stage_ms,
                "notes": "stages timed as separate fenced programs (full pipeline fuses tighter); numpy baseline at 2s clips; MFU vs dense-f32 peak (pipeline is FFT/small-eig bound by design)",
            }
        )
    )


if __name__ == "__main__":
    main()
