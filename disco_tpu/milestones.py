"""The BASELINE.json milestone configurations as executable programs.

Each function builds its scenario, runs the jitted TPU pipeline, and returns
a metrics dict (real-time factor + SI-SDR deltas).  The scales default to
the BASELINE spec; every function takes size overrides so the test suite
exercises all of them end-to-end on CPU in seconds.

1. ``mvdr_single_clip``      — 1 node, 4 mics, rank-1 GEVD-MWF, one clip.
2. ``disco_mwf_4node``       — 4-node DISCO array, local MWF only (step 1).
3. ``tango_4node``           — 4-node two-step DANSE MWF (TASLP 2021 setup),
                               oracle or CRNN masks.
4. ``meetit_separation``     — 8-node array, 2 competing speakers, per-source
                               extraction (ICASSP 2021 setup).
5. ``batched_meetit_end_to_end`` — 64 rooms x 8 nodes: ISM RIR simulation +
                               convolution + enhancement as ONE jitted
                               program on one mesh.
6. ``streaming_latency``     — per-frame latency of the online two-step
                               pipeline per mask-for-z policy.

(The self-generated-corpus pipeline milestone lives in
``disco_tpu.milestones_corpus``.)

No reference counterpart as code: the five configurations are benchmark
harnesses sized from the SURVEY.md scenarios.
"""
from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from disco_tpu.core.dsp import istft, stft
from disco_tpu.core.metrics import si_sdr
from disco_tpu.enhance import compute_z_signals, oracle_masks, tango
from disco_tpu.sim.ism import fft_convolve, shoebox_rirs

FS = 16000


def _fence(x) -> float:
    """Host readback of one element — the reliable execution fence on
    tunneled device attachments, where block_until_ready() was measured
    returning in ~20us for a >100ms program.  jnp.real first: the tunnel
    cannot transfer complex dtypes.  Shared by bench.py.

    Every call ticks the obs fence counter (disco_tpu.obs.accounting): on
    the tunnel each fence is a fixed ~80 ms RPC, so the count IS the
    host-traffic cost model that `obs report` renders.  The readback runs
    under bounded retry (utils.resilience): a dropped RPC is retried
    in-process instead of killing the run — each attempt is a real
    round-trip, so each attempt ticks the counter."""
    from disco_tpu.utils.resilience import TRANSPORT_ERRORS, call_with_retries

    return call_with_retries(_fence_readback, x, retries=2, base_delay_s=0.25,
                             max_delay_s=1.0, label="fence",
                             retry_on=TRANSPORT_ERRORS)


def _fence_readback(x) -> float:
    """One un-retried fence attempt (the raw RPC).  ``utils.resilience.
    resilient_fence`` wraps THIS with caller-chosen budgets, so its retries
    do not stack on :func:`_fence`'s defaults.

    ``pre_fence`` is a chaos seam (``disco_tpu.runs.chaos``): the injected
    crash lands immediately before the readback — work enqueued on device,
    nothing fenced back — the exact window a tunnel drop hits an unprepared
    run."""
    from disco_tpu.obs import accounting
    from disco_tpu.runs import chaos

    chaos.tick("pre_fence")
    accounting.fence_tick()
    return float(jnp.real(jnp.ravel(x)[0]))


def _scene(K, C, L, seed=0, noise_scale=0.8):
    rng = np.random.default_rng(seed)
    src = rng.standard_normal(L)
    s = np.stack(
        [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)]) for _ in range(K)]
    ).astype(np.float32)
    n = noise_scale * rng.standard_normal((K, C, L)).astype(np.float32)
    return s + n, s, n


def _leaf(out):
    return jax.tree_util.tree_leaves(out)[0]


def _time_queued(fn, *args, k: int = 1, iters: int = 5):
    """Median wall time of k async-queued executions under ONE fence."""
    _fence(_leaf(fn(*args)))  # warm-up / compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(k)]
        _fence(_leaf(outs[-1]))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def _slope_time(fn, *args, k: int = 6, iters: int = 5):
    """(on-device per-exec seconds, single-dispatch seconds) via the
    k-queued slope: queue k programs, fence once, slope = (t_k - t_1)/(k-1).
    On the tunneled attachment a fenced dispatch pays a fixed ~50-80 ms RPC
    round-trip, so single-dispatch timings mostly measure the tunnel; the
    slope is the true on-device time (what a directly-attached chip sees).
    When RPC jitter swamps the signal (tk <= t1, non-positive slope), fall
    back to tk/k — a conservative upper bound that still amortizes the
    overhead k-fold — rather than reporting an absurdly small time."""
    t1 = _time_queued(fn, *args, k=1, iters=iters)
    tk = _time_queued(fn, *args, k=k, iters=iters)
    slope = (tk - t1) / (k - 1)
    if slope <= 0:
        slope = tk / k
    return slope, t1


def _timed(fn, *args, iters=3):
    """(out, on-device seconds, single-dispatch seconds) — the slope
    decomposition for the milestone configs (round-3 verdict weak #5: the
    single-clip milestones were reported tunnel-included only, leaving the
    ≥200x north-star comparison confounded with the ~50-80 ms per-launch
    RPC floor)."""
    out = fn(*args)
    _fence(_leaf(out))
    dt, dt1 = _slope_time(fn, *args, iters=iters)
    return out, dt, dt1


def _rtf_fields(audio_s, dt, dt1):
    """The decomposed milestone RTF triple: ``rtf`` = on-device (slope; the
    number a directly-attached v5e would see), ``rtf_single_dispatch`` =
    tunnel-included (the round-3 milestone convention), ``dispatch_ms`` =
    the fixed per-launch floor their difference implies."""
    return {
        "rtf": audio_s / dt,
        "rtf_single_dispatch": audio_s / dt1,
        "dispatch_ms": round(max(dt1 - dt, 0.0) * 1e3, 2),
    }


def mvdr_single_clip(dur_s=5.0, seed=0, iters=3):
    """Config 1: single 4-mic node, rank-1 GEVD-MWF on one clip."""
    from disco_tpu.beam.covariance import masked_covariances
    from disco_tpu.beam.filters import gevd_mwf
    from disco_tpu.core.masks import tf_mask

    L = int(dur_s * FS)
    y, s, n = _scene(1, 4, L, seed)

    @jax.jit
    def run(y, s, n):
        Y, S, N = stft(y[0]), stft(s[0]), stft(n[0])
        mask = tf_mask(S[0], N[0], "irm1")
        Rss, Rnn = masked_covariances(Y, mask)
        w, _ = gevd_mwf(Rss, Rnn, mu=1.0, rank=1)
        yf = jnp.einsum("fc,cft->ft", jnp.conj(w), Y)
        return istft(yf, length=y.shape[-1])

    enh, dt, dt1 = _timed(run, y, s, n, iters=iters)
    enh = np.asarray(enh)
    return {
        "config": "mvdr_single_clip",
        **_rtf_fields(dur_s, dt, dt1),
        "si_sdr_in": float(si_sdr(s[0, 0], y[0, 0])),
        "si_sdr_out": float(si_sdr(s[0, 0], enh)),
    }


def disco_mwf_4node(dur_s=5.0, K=4, C=4, seed=0, iters=3):
    """Config 2: 4-node DISCO array, local MWF only (TANGO step 1 — each
    node beamforms its own mics, no z exchange, oracle masks)."""
    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, seed)

    @jax.jit
    def run(y, s, n):
        out = compute_z_signals(y, s, n, mask_type="irm1")
        return istft(out["z_y"], length=y.shape[-1])

    enh, dt, dt1 = _timed(run, y, s, n, iters=iters)
    enh = np.asarray(enh)
    deltas = [float(si_sdr(s[k, 0], enh[k]) - si_sdr(s[k, 0], y[k, 0])) for k in range(K)]
    return {"config": "disco_mwf_4node", **_rtf_fields(K * dur_s, dt, dt1), "delta_si_sdr": deltas}


def tango_4node(dur_s=5.0, K=4, C=4, seed=0, iters=3, models=(None, None)):
    """Config 3: the full two-step DANSE-style distributed MWF (TASLP 2021).
    ``models``: (step1, step2) CRNN (module, variables) pairs or None for
    oracle masks."""
    from disco_tpu.enhance.driver import estimate_masks

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, seed)
    Y, S, N = stft(y), stft(s), stft(n)
    masks_z, mask_w = estimate_masks(Y, S, N, models, "irm1", K)

    @jax.jit
    def run(Y, S, N, masks_z, mask_w):
        res = tango(Y, S, N, masks_z, mask_w, policy="local")
        return istft(res.yf, length=L)

    enh, dt, dt1 = _timed(run, Y, S, N, masks_z, mask_w, iters=iters)
    enh = np.asarray(enh)
    deltas = [float(si_sdr(s[k, 0], enh[k]) - si_sdr(s[k, 0], y[k, 0])) for k in range(K)]
    return {"config": "tango_4node", **_rtf_fields(K * dur_s, dt, dt1), "delta_si_sdr": deltas}


def meetit_separation(dur_s=5.0, K=8, C=4, n_src=2, seed=0, iters=3):
    """Config 4: 8-node array, 2 competing speakers (ICASSP 2021): per-source
    oracle IRMs drive one TANGO pass per source; each speaker is evaluated at
    the node facing it (node k attends source k % n_src)."""
    rng = np.random.default_rng(seed)
    L = int(dur_s * FS)
    srcs = [rng.standard_normal(L) for _ in range(n_src)]
    imgs = np.stack(
        [
            np.stack(
                [np.stack([np.convolve(src, rng.standard_normal(8) * 0.5, mode="same") for _ in range(C)]) for _ in range(K)]
            )
            for src in srcs
        ]
    ).astype(np.float32)  # (n_src, K, C, L)
    y = imgs.sum(0)

    from disco_tpu.enhance import separate_sources

    @jax.jit
    def run(y, imgs):
        Y = stft(y)
        S_imgs = stft(imgs)
        est = separate_sources(Y, S_imgs)  # (n_src, K, F, T)
        return istft(est, length=y.shape[-1])

    est, dt, dt1 = _timed(run, y, imgs, iters=iters)
    est = np.asarray(est)
    deltas = []
    for k in range(K):
        si = k % n_src
        ref = imgs[si, k, 0]
        deltas.append(float(si_sdr(ref, est[si, k]) - si_sdr(ref, y[k, 0])))
    return {"config": "meetit_separation", **_rtf_fields(K * dur_s, dt, dt1), "delta_si_sdr": deltas}


def batched_meetit_end_to_end(
    n_rooms=64, K=8, C=2, dur_s=2.0, max_order=10, rir_len=2048, seed=0, iters=1
):
    """Config 5: ISM room simulation + convolution + two-step enhancement for
    ``n_rooms`` rooms as ONE jitted program — simulation and enhancement
    share the mesh/device (the north-star end-to-end config).

    Geometry is sampled host-side (rejection sampling stays out of jit,
    SURVEY.md §7 hard-part 5); everything after the draw runs on device.
    """
    rng = np.random.default_rng(seed)
    L = int(dur_s * FS)
    M = K * C

    dims = rng.uniform([4, 4, 2.5], [8, 6, 3], size=(n_rooms, 3)).astype(np.float32)
    mics = (dims[:, None, :] * rng.uniform(0.2, 0.8, size=(n_rooms, M, 3))).astype(np.float32)
    srcs = (dims[:, None, :] * rng.uniform(0.2, 0.8, size=(n_rooms, 2, 3))).astype(np.float32)
    alphas = rng.uniform(0.3, 0.6, size=(n_rooms,)).astype(np.float32)
    dry = rng.standard_normal((n_rooms, 2, L)).astype(np.float32)

    @jax.jit
    def run(dims, srcs, mics, alphas, dry):
        def one_room(dim, src, mic, alpha, sig):
            rirs = shoebox_rirs(dim, src, mic, alpha, max_order=max_order, rir_len=rir_len)
            imgs = fft_convolve(sig[:, None, :], rirs, out_len=L)  # (2, M, L)
            s_img, n_img = imgs[0], imgs[1]
            y = (s_img + n_img).reshape(K, C, L)
            s = s_img.reshape(K, C, L)
            n = n_img.reshape(K, C, L)
            Y, S, N = stft(y), stft(s), stft(n)
            m = oracle_masks(S, N, "irm1")
            res = tango(Y, S, N, m, m, policy="local")
            return istft(res.yf, length=L), s
        return jax.vmap(one_room)(dims, srcs, mics, alphas, dry)

    (enh, s_ref), dt, dt1 = _timed(run, dims, srcs, mics, alphas, dry, iters=iters)
    enh = np.asarray(enh)
    s_ref = np.asarray(s_ref)
    # SI-SDR of the enhanced output vs the clean image at each node's ref mic
    sdrs = [
        float(si_sdr(s_ref[r, k, 0], enh[r, k]))
        for r in range(min(n_rooms, 4))
        for k in range(K)
    ]
    return {
        "config": "batched_meetit_end_to_end",
        **_rtf_fields(n_rooms * K * dur_s, dt, dt1),
        "rooms": n_rooms,
        "mean_si_sdr_out": float(np.mean(sdrs)),
    }


def streaming_latency(dur_s=5.0, K=4, C=4, update_every=4, seed=0, iters=3, policies=("local", "distant", "none")):
    """Per-frame processing latency of the online (streaming) TANGO — the
    raison d'être of streaming mode, now measured (VERDICT round-1 weak #5).

    Reports, per mask-for-z policy: wall-clock per STFT frame for the
    full K-node two-step online pipeline, the real-time budget (one frame
    = hop/fs = 16 ms), and the resulting real-time factor.  Algorithmic
    latency is one block (``update_every`` frames) of filter staleness; the
    pipeline itself is causal (each frame is filtered with the most recent
    refresh, never future data).
    """
    from disco_tpu.core.masks import tf_mask
    from disco_tpu.enhance.streaming import streaming_tango

    L = int(dur_s * FS)
    y, s, n = _scene(K, C, L, seed)
    Y, S, N = stft(y), stft(s), stft(n)
    masks = jax.vmap(lambda Sk, Nk: tf_mask(Sk[0], Nk[0], "irm1"))(S, N)
    T = Y.shape[-1]
    frame_budget_ms = 1e3 * 256 / FS  # hop / fs

    out = {"config": "streaming_latency", "frames": T, "update_every": update_every,
           "frame_budget_ms": round(frame_budget_ms, 3), "policies": {}}
    for policy in policies:
        @jax.jit
        def run(Y, mz, mw):
            return streaming_tango(Y, mz, mw, update_every=update_every, policy=policy)["yf"]

        _, dt, dt1 = _timed(run, Y, masks, masks, iters=iters)
        per_frame_ms = 1e3 * dt / T
        out["policies"][policy] = {
            "per_frame_ms": round(per_frame_ms, 4),
            "rtf": round(frame_budget_ms / per_frame_ms, 1),
            "dispatch_ms": round(max(dt1 - dt, 0.0) * 1e3, 2),
        }
    return out


def run_all(tiny: bool = False):
    """All milestone configs (1-5 + streaming latency); ``tiny=True``
    shrinks every scale for CPU test runs."""
    if tiny:
        return [
            mvdr_single_clip(dur_s=1.0, iters=1),
            disco_mwf_4node(dur_s=1.0, iters=1),
            tango_4node(dur_s=1.0, iters=1),
            meetit_separation(dur_s=1.0, K=4, C=2, iters=1),
            batched_meetit_end_to_end(n_rooms=2, K=2, C=2, dur_s=0.5, max_order=4, rir_len=1024, iters=1),
            streaming_latency(dur_s=1.0, K=2, C=2, iters=1),
        ]
    return [
        mvdr_single_clip(),
        disco_mwf_4node(),
        tango_4node(),
        meetit_separation(),
        batched_meetit_end_to_end(),
        streaming_latency(),
    ]


if __name__ == "__main__":
    import json

    for res in run_all():
        print(json.dumps(res))
