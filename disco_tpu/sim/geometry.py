"""Scenario sampling: room geometry, node/source placement, mic arrays.

Capability parity with reference ``dataset_utils/room_setups.py`` (the
``RandomRoomSetup:7`` / ``MeetingRoomSetup:255`` / ``LivingRoomSetup:386`` /
``MeetitSetup:454`` classes).  Rejection sampling is control-flow heavy and
cheap, so it stays host-side NumPy (SURVEY.md §7 step 5); the sampled
geometry feeds the batched TPU ISM kernel (``disco_tpu.sim.ism``).

Differences from the reference, by design:
* randomness flows through an explicit ``numpy.random.Generator`` (the
  reference mutates the global seed),
* a bounded number of *whole-configuration* retries with a clear error
  instead of an unbounded ``while`` loop,
* ``d_nw`` et al. keep the reference's exact constraint semantics, including
  LivingRoom's reinterpretation of ``d_mw`` as a *maximum* wall distance
  (room_setups.py:395-402).
"""
from __future__ import annotations

import dataclasses

import numpy as np

MAX_TRIALS = 100  # the reference's per-placement retry bound (room_setups.py:123)


def circular_array_2d(center, n_mics: int, phi0: float, radius: float) -> np.ndarray:
    """(2, n_mics) positions on a circle — pra.circular_2D_array semantics
    (room_setups.py:228-231)."""
    ang = phi0 + 2.0 * np.pi * np.arange(n_mics) / n_mics
    return np.asarray(center)[:, None] + radius * np.stack([np.cos(ang), np.sin(ang)])


def eyring_absorption(rt60: float, length: float, width: float, height: float) -> float:
    """Uniform wall absorption from RT60 via the reference's Eyring-like fit
    ``alpha = 1 - exp((1.7e-5·RT60 - 0.1611)·V/(RT60·S))`` (room_setups.py:92)."""
    vol = length * width * height
    sur = 2 * (length * width + length * height + width * height)
    return 1.0 - np.exp((1.7e-5 * rt60 - 0.1611) * vol / (rt60 * sur))


def _uniform(rng, lo, hi):
    return lo + (hi - lo) * rng.random()


@dataclasses.dataclass
class RoomSetup:
    """Sampled configuration: everything the simulator needs."""

    length: float
    width: float
    height: float
    alpha: float
    beta: float  # RT60 in seconds
    nodes_centers: np.ndarray  # (n_nodes, 3)
    source_positions: np.ndarray  # (n_sources, 3)
    mic_positions: np.ndarray  # (3, total_mics) — pra layout

    @property
    def room_dim(self) -> np.ndarray:
        return np.array([self.length, self.width, self.height])

    def plot(self):
        """Top-view Figure of the sampled configuration — room outline, node
        centers, microphones and sources (the ``plot_room`` observability
        helper of reference room_setups.py:238-253; the from-saved-infos
        variant is ``disco_tpu.enhance.inference.plot_conf``).  Returns the
        matplotlib Figure — save with ``fig.savefig(...)``."""
        from disco_tpu.utils.plotting import draw_room_topview

        return draw_room_topview(
            self.length, self.width, self.mic_positions, self.source_positions,
            self.nodes_centers,
        )


class RandomRoomSetup:
    """Uniformly random nodes + sources under min-distance constraints
    (room_setups.py:7-236)."""

    def __init__(
        self,
        l_range, w_range, h_range, beta_range,
        n_sensors_per_node, d_mw, d_mn, d_nn, z_range_m,
        d_rnd_mics,
        n_sources, d_ss, d_sn, d_sw, z_range_s,
        rng=None, **kwargs,
    ):
        self.sensors_per_node = list(n_sensors_per_node)
        self.n_nodes = len(self.sensors_per_node)
        self.d_mw, self.d_mn = d_mw, d_mn
        self.d_nw = d_mw + d_mn
        self.d_rnd_mics = d_rnd_mics
        self.d_nn = d_nn
        self.n_sources = n_sources
        self.d_ss, self.d_sn, self.d_sw = d_ss, d_sn, d_sw
        self.z_range_m, self.z_range_s = z_range_m, z_range_s
        self.l_range, self.w_range, self.h_range, self.beta_range = l_range, w_range, h_range, beta_range
        self.rng = np.random.default_rng() if rng is None else rng
        # Sampled state (populated by create_room_setup)
        self.length = self.width = self.height = self.alpha = self.beta = None
        self.nodes_centers = self.source_positions = self.microphones_positions = None

    # -- room ---------------------------------------------------------------
    def set_room_dimensions(self):
        """Sample (length, width, height, alpha, beta) (room_setups.py:81-94)."""
        length = _uniform(self.rng, *self.l_range)
        width = _uniform(self.rng, *self.w_range)
        height = _uniform(self.rng, *self.h_range)
        beta = _uniform(self.rng, *self.beta_range)
        alpha = eyring_absorption(beta, length, width, height)
        return length, width, height, alpha, beta

    # -- nodes --------------------------------------------------------------
    def _sample_node_xy(self):
        return (
            _uniform(self.rng, self.d_nw, self.length - self.d_nw),
            _uniform(self.rng, self.d_nw, self.width - self.d_nw),
        )

    def get_nodes_centers(self):
        """Nodes ≥ d_nw from walls, pairwise ≥ d_nn apart in the xy plane
        (room_setups.py:96-134)."""
        centers = np.zeros((self.n_nodes, 3))
        x0, y0 = self._sample_node_xy()
        centers[0] = x0, y0, _uniform(self.rng, *self.z_range_m)
        n_trials = 0
        for i in range(1, self.n_nodes):
            x, y = self._sample_node_xy()
            z = _uniform(self.rng, *self.z_range_m)
            while (
                np.any(np.sum((centers[:i, :2] - [x, y]) ** 2, axis=1) < self.d_nn**2)
                and n_trials < MAX_TRIALS
            ):
                x, y = self._sample_node_xy()
                n_trials += 1
            if n_trials >= MAX_TRIALS:
                return centers, n_trials
            centers[i] = x, y, z
            n_trials = 0
        return centers, n_trials

    # -- sources ------------------------------------------------------------
    def _sample_source_xy(self):
        return (
            _uniform(self.rng, self.d_sw, self.length - self.d_sw),
            _uniform(self.rng, self.d_sw, self.width - self.d_sw),
        )

    def get_source_positions(self):
        """Sources ≥ d_sw from walls, ≥ d_sn from every node, ≥ d_ss from
        each other (room_setups.py:162-211)."""
        pos = np.zeros((self.n_sources, 3))
        n_trials = 0
        for i in range(self.n_sources):
            x, y = self._sample_source_xy()
            z = _uniform(self.rng, *self.z_range_s)
            while (
                (
                    np.any(np.sum((pos[:i, :2] - [x, y]) ** 2, axis=1) < self.d_ss**2)
                    or np.any(np.sum((self.nodes_centers[:, :2] - [x, y]) ** 2, axis=1) < self.d_sn**2)
                )
                and n_trials < MAX_TRIALS
            ):
                x, y = self._sample_source_xy()
                n_trials += 1
            if n_trials >= MAX_TRIALS:
                return pos, n_trials
            pos[i] = x, y, z
            n_trials = 0
        return pos, n_trials

    def get_random_mics_positions(self):
        """Two extra mics ≥ d_rnd_mics apart (the diffuse-noise pair,
        room_setups.py:136-160)."""
        m1 = [*self._sample_node_xy(), _uniform(self.rng, *self.z_range_m)]
        m2x, m2y = self._sample_node_xy()
        while np.hypot(m1[0] - m2x, m1[1] - m2y) < self.d_rnd_mics:
            m2x, m2y = self._sample_node_xy()
        return m1, [m2x, m2y, m1[2]]

    # -- mics ---------------------------------------------------------------
    def add_circular_microphones(self):
        """Circular sub-array of radius d_mn at each node center, random
        phase, constant z (room_setups.py:213-236).  (3, total_mics)."""
        total = int(np.sum(self.sensors_per_node))
        mics = np.zeros((3, total))
        at = 0
        for i in range(self.n_nodes):
            m = self.sensors_per_node[i]
            mics[:2, at : at + m] = circular_array_2d(
                self.nodes_centers[i][:2], m, np.pi / 2 * self.rng.random(), self.d_mn
            )
            mics[2, at : at + m] = self.nodes_centers[i][2]
            at += m
        return mics

    # -- driver -------------------------------------------------------------
    def create_room_setup(self, max_config_trials: int = 1000) -> RoomSetup:
        """Rejection-sample a full configuration (room_setups.py:57-79)."""
        for _ in range(max_config_trials):
            self.length, self.width, self.height, self.alpha, self.beta = self.set_room_dimensions()
            centers, t_nodes = self.get_nodes_centers()
            if t_nodes >= MAX_TRIALS:
                continue
            self.nodes_centers = centers
            sources, t_src = self.get_source_positions()
            if t_src >= MAX_TRIALS:
                continue
            self.source_positions = sources
            self.microphones_positions = self.add_circular_microphones()
            return RoomSetup(
                self.length, self.width, self.height, self.alpha, self.beta,
                self.nodes_centers, self.source_positions, self.microphones_positions,
            )
        raise RuntimeError("no valid room configuration found; relax the constraints")


class MeetingRoomSetup(RandomRoomSetup):
    """Nodes on a round table, two sources around it (room_setups.py:255-383)."""

    def __init__(self, r_range, d_nt_range, d_st_range, phi_ss_range=None, phi_ss_choice=None, **kwargs):
        super().__init__(**kwargs)
        self.r_range = r_range
        self.d_nt_range, self.d_st_range = d_nt_range, d_st_range
        self.phi_ss_range, self.phi_ss_choice = phi_ss_range, phi_ss_choice
        self.d_nt = self.d_st = self.phi_t = None
        self.table_center = self.table_radius = None
        self.d_max = None

    def get_table_position(self):
        """(room_setups.py:285-304)."""
        r = _uniform(self.rng, *self.r_range)
        self.d_max = min(self.d_nt_range[1], r - self.d_mn)
        self.d_nt = self.d_max / 2
        self.d_st = _uniform(self.rng, self.d_st_range[0], self.d_max)
        dt_min = self.d_sw + self.d_st + r
        x_t = _uniform(self.rng, dt_min, self.length - dt_min)
        y_t = _uniform(self.rng, dt_min, self.width - dt_min)
        z_t = _uniform(self.rng, *self.z_range_m)
        self.table_center = (x_t, y_t, z_t)
        self.table_radius = r
        return self.table_center, self.table_radius

    def get_nodes_angles(self):
        """(room_setups.py:328-336)."""
        angles = self.phi_t + np.linspace(
            0, 2 * (self.n_nodes - 1) * np.pi / self.n_nodes, self.n_nodes
        )
        proj = np.array([np.cos(angles), np.sin(angles)]).T
        return angles, proj

    def get_nodes_centers(self):
        """Nodes on the table with a random radial jitter (room_setups.py:306-326)."""
        centers = np.zeros((self.n_nodes, 3))
        table_center, table_radius = self.get_table_position()
        self.phi_t = 2 * np.pi / self.n_nodes * self.rng.random()
        centers[:, :2] = circular_array_2d(
            table_center[:2], self.n_nodes, self.phi_t, table_radius - self.d_nt
        ).T
        proj = self.get_nodes_angles()[1]
        radial = -self.d_nt + (self.d_max - self.d_nt_range[0]) * self.rng.random((self.n_nodes, 1))
        centers[:, :2] += radial * proj
        centers[:, 2] = table_center[2]
        return centers, 0

    def get_source_positions(self):
        """Two sources at table_radius + d_st, constrained relative angle
        (room_setups.py:338-366)."""
        phi_st = 2 * np.pi * self.rng.random()
        d = self.table_radius + self.d_st
        if self.phi_ss_range is not None:
            phi_ss = _uniform(self.rng, *self.phi_ss_range)
        elif self.phi_ss_choice is not None:
            phi_ss = self.phi_ss_choice[self.rng.integers(len(self.phi_ss_choice))]
        else:
            raise AttributeError("either phi_ss_range or phi_ss_choice must be given")
        pos = np.zeros((2, 3))
        for i, phi in enumerate((self.phi_t + phi_st, self.phi_t + phi_st + phi_ss)):
            pos[i] = (
                self.table_center[0] + d * np.cos(phi),
                self.table_center[1] + d * np.sin(phi),
                _uniform(self.rng, *self.z_range_s),
            )
        return pos, 0


class LivingRoomSetup(RandomRoomSetup):
    """Three nodes near three distinct walls + one free node; d_mw is the
    MAX wall distance here (room_setups.py:386-451)."""

    D_MW_MIN = 0.02  # hard-coded minimal mic-wall distance (room_setups.py:401)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.d_nw = self.d_mw - self.d_mn

    def get_nodes_centers(self):
        centers = np.zeros((self.n_nodes, 3))
        d_max = self.d_nw
        d_min = self.D_MW_MIN + self.d_mn
        # One candidate near each of the four walls; keep a random three.
        vert_x = np.array([
            _uniform(self.rng, d_min, d_max),
            _uniform(self.rng, self.length - d_max, self.length - d_max + (d_max - d_min)),
        ])
        vert_y = d_min + (self.width - d_min) * self.rng.random(2)
        hori_x = d_min + (self.length - d_min) * self.rng.random(2)
        hori_y = np.array([
            _uniform(self.rng, d_min, d_max),
            _uniform(self.rng, self.width - d_max, self.width - d_max + (d_max - d_min)),
        ])
        z = self.z_range_m[0] + (self.z_range_m[1] - self.z_range_m[0]) * self.rng.random(4)
        candidates = np.array([
            [vert_x[0], vert_y[0], z[0]],
            [vert_x[1], vert_y[1], z[1]],
            [hori_x[0], hori_y[0], z[2]],
            [hori_x[1], hori_y[1], z[3]],
        ])
        centers[:3] = self.rng.permutation(candidates)[:3]
        # Remaining nodes: free placement under the pairwise constraint.
        n_trials = 0
        for i in range(3, self.n_nodes):
            x, y = self._sample_node_xy()
            zi = _uniform(self.rng, *self.z_range_m)
            while (
                np.any(np.sum((centers[:i, :2] - [x, y]) ** 2, axis=1) < self.d_nn**2)
                and n_trials < MAX_TRIALS
            ):
                x, y = self._sample_node_xy()
                n_trials += 1
            if n_trials >= MAX_TRIALS:
                return centers, n_trials
            centers[i] = x, y, zi
            n_trials = 0
        return centers, n_trials


class MeetitSetup(MeetingRoomSetup):
    """Sources directly facing equally spaced nodes (room_setups.py:454-483)."""

    def get_source_positions(self):
        pos = np.zeros((self.n_nodes, 3))
        pos[:, :2] = circular_array_2d(
            self.table_center[:2], self.n_nodes, self.phi_t, self.table_radius + self.d_st
        ).T
        pos[:, 2] = [_uniform(self.rng, *self.z_range_s) for _ in range(self.n_nodes)]
        n_trials = 0
        if (
            np.any(pos[:, :2] <= self.d_sw)
            or np.any(pos[:, 0] >= self.length - self.d_sw)
            or np.any(pos[:, 1] >= self.width - self.d_sw)
        ):
            n_trials = MAX_TRIALS
        return pos, n_trials
