"""Signal setups: pick, scale and VAD the source material for a scene.

Capability parity with reference ``dataset_utils/signal_setups.py``
(``SpeechAndNoiseSetup:6``, ``InterferentSpeakersSetup:157``), host-side.
The list-based design survives: WAV files come from pre-shuffled lists so
parallel corpus shards never collide (signal_setups.py:9-12).

Differences by design: explicit ``numpy.random.Generator``; audio I/O
through ``disco_tpu.io`` (soundfile is not in this image); the VAD is the
JAX ``vad_oracle_batch`` kernel evaluated host-side.
"""
from __future__ import annotations

import numpy as np

from disco_tpu.core.masks import vad_oracle_batch
from disco_tpu.core.sigproc import noise_from_signal, stack_talkers
from disco_tpu.io import read_wav


def _vad(x):
    return np.asarray(vad_oracle_batch(np.asarray(x, np.float32), thr=0.001))


def normalize_to_var(signal, var_tar):
    """Scale so the VAD-active samples have variance ``var_tar``, then
    recompute the VAD (signal_setups.py:62-67).  Returns (signal, vad)."""
    vad = _vad(signal)
    active = signal[vad == 1]
    if active.size == 0:
        return signal, vad
    signal = signal * np.sqrt(var_tar / np.var(active))
    return signal, _vad(signal)


class SpeechAndNoiseSetup:
    """Target-speech + noise material picker (signal_setups.py:6-154)."""

    def __init__(
        self,
        target_list,
        talkers_list,
        noises_dict,
        duration_range,
        var_tar,
        snr_dry_range,
        snr_cnv_range,
        min_delta_snr,
        rng=None,
        read_fn=read_wav,
    ):
        self.target_list = list(target_list)
        self.ssn_list = list(talkers_list)
        self.noises_dict = {k: list(v) for k, v in noises_dict.items()}
        self.duration_range = duration_range
        self.target_duration = None
        self.var_tar = var_tar
        self.snr_dry_range = np.atleast_2d(np.asarray(snr_dry_range))
        self.snr_cnv_range = snr_cnv_range
        self.min_delta_snr = min_delta_snr
        self.source_snr = np.zeros(self.snr_dry_range.shape[0])
        self.rng = np.random.default_rng() if rng is None else rng
        self.read_fn = read_fn

    def get_target_segment(self, target_file):
        """Load, trim to max duration, variance-normalize over VAD-active
        samples, prepend 1 s of silence (signal_setups.py:42-73).

        Returns (signal, vad, fs); (None, None, fs) if shorter than the
        minimum duration — callers redraw (convolve_signals.py:229-233)."""
        min_dur, max_dur = self.duration_range
        signal, fs = self.read_fn(target_file)
        signal = np.asarray(signal, np.float64)[: int(max_dur * fs)]
        signal = signal - np.mean(signal)
        sig_duration = len(signal) / fs
        if sig_duration < min_dur:
            self.target_duration = sig_duration + 1
            return None, None, fs
        signal, vad = normalize_to_var(signal, self.var_tar)
        self.target_duration = sig_duration + 1
        return (
            np.concatenate((np.zeros(fs), signal)),
            np.concatenate((np.zeros(fs), vad)),
            fs,
        )

    def get_noise_segment(self, n_type, duration):
        """Noise material: a category from noises_dict, an interferent
        talker, or synthesized SSN (signal_setups.py:75-105).

        Returns (noise, file, start, vad, fs)."""
        fs = 16000
        if n_type.lower() in self.noises_dict:
            n, fs, n_file, n_start = self._read_random_signal(n_type.lower(), duration)
            vad = _vad(n) if n_type.lower() == "interferent_talker" else None
            return n, n_file, n_start, vad, fs
        if n_type == "SSN":
            tlk, fs, _ = stack_talkers(self.ssn_list, duration, None, nb_tlk=5, rng=self.rng, read_fn=self.read_fn)
            ssn = noise_from_signal(tlk, rng=self.rng)
            return ssn[: int(duration * fs)], None, None, None, fs
        raise ValueError(f"Unknown noise type {n_type!r}")

    def _read_random_signal(self, n_type, duration):
        """Random file + random circular start offset (signal_setups.py:107-138)."""
        assert duration > 0, "Duration should be strictly positive"
        noise_list = self.noises_dict[n_type]
        max_trials = max(100, 2 * len(noise_list))
        for _ in range(max_trials):
            pick = int(self.rng.integers(0, len(noise_list)))
            sig, fs = self.read_fn(noise_list[pick])
            if len(sig) / fs >= duration:
                start = int(len(sig) * self.rng.random())
                rolled = np.roll(sig, len(sig) - start)
                y = np.asarray(rolled[: int(duration * fs)], np.float64)
                return y - np.mean(y), fs, noise_list[pick], start
        raise ValueError(
            f"Failed to find a file lasting more than {duration} s. Please choose a shorter duration"
        )

    def get_random_dry_snr(self):
        """Per-source uniform SNR draw (signal_setups.py:140-154)."""
        lo = self.snr_dry_range[:, 0]
        hi = self.snr_dry_range[:, 1]
        self.source_snr = lo + (hi - lo) * self.rng.random(len(lo))
        return self.source_snr


class InterferentSpeakersSetup:
    """All sources are distinct interfering speakers
    (signal_setups.py:157-213).  Speaker identity is the third-from-last
    path component (the LibriSpeech `{speaker}/{chapter}/{utt}.wav` layout)."""

    def __init__(
        self,
        speakers_list,
        duration_range,
        var_tar,
        snr_dry_range,
        snr_cnv_range,
        min_delta_snr,
        rng=None,
        read_fn=read_wav,
    ):
        self.speakers_list = list(speakers_list)
        self.duration_range = duration_range
        self.speakers_ids, self.speakers_files = [], []
        self.var_tar = var_tar
        self.snr_dry_range = np.atleast_2d(np.asarray(snr_dry_range))
        self.snr_cnv_range = snr_cnv_range
        self.min_delta_snr = min_delta_snr
        self.source_snr = np.zeros(self.snr_dry_range.shape[0])
        self.fs = None
        self.rng = np.random.default_rng() if rng is None else rng
        self.read_fn = read_fn

    def reset(self):
        """Forget used speakers (new room)."""
        self.speakers_ids, self.speakers_files = [], []

    def get_signal(self, duration):
        """A normalized segment from a speaker not yet used in this room
        (signal_setups.py:175-213).  Returns (signal, vad)."""
        assert duration > 0, "Duration should be strictly positive"
        max_trials = 100
        for _ in range(max_trials):
            pick = str(self.rng.choice(self.speakers_list))
            speaker_id = pick.split("/")[-3]
            if speaker_id in self.speakers_ids:
                continue
            sig, fs = self.read_fn(pick)
            if len(sig) / fs < duration:
                continue
            y = np.asarray(sig[: int(duration * fs)], np.float64)
            y -= np.mean(y)
            y, vad = normalize_to_var(y, self.var_tar)
            self.speakers_ids.append(speaker_id)
            self.speakers_files.append(pick)
            self.fs = fs
            return y, vad
        raise ValueError(
            f"Failed to find an unused speaker with >= {duration} s of audio"
        )
