from disco_tpu.sim.defaults import RoomDefaults, SignalDefaults, make_setup
from disco_tpu.sim.geometry import (
    LivingRoomSetup,
    MeetingRoomSetup,
    MeetitSetup,
    RandomRoomSetup,
    RoomSetup,
    circular_array_2d,
    eyring_absorption,
)
from disco_tpu.sim.signals import (
    InterferentSpeakersSetup,
    SpeechAndNoiseSetup,
    normalize_to_var,
)
from disco_tpu.sim.ism import (
    fft_convolve,
    image_lattice,
    rir_bucket,
    rir_length_for,
    shoebox_rir,
    shoebox_rirs,
    shoebox_rirs_batched,
)

__all__ = [
    "RoomDefaults",
    "SignalDefaults",
    "make_setup",
    "RandomRoomSetup",
    "MeetingRoomSetup",
    "LivingRoomSetup",
    "MeetitSetup",
    "RoomSetup",
    "circular_array_2d",
    "eyring_absorption",
    "shoebox_rir",
    "shoebox_rirs",
    "shoebox_rirs_batched",
    "fft_convolve",
    "rir_bucket",
    "rir_length_for",
    "image_lattice",
    "SpeechAndNoiseSetup",
    "InterferentSpeakersSetup",
    "normalize_to_var",
]
