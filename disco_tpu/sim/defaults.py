"""Canonical scenario constants of the DISCO / MEETIT corpora.

These reproduce the hard-coded room/signal parameters of reference
``gen_disco/convolve_signals.py:361-369,377-401,404-409`` and
``gen_meetit/convolve_signals.py`` as one typed place (SURVEY.md §5.6: one
config tree replacing argparse + module constants + yaml)."""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class RoomDefaults:
    l_range: tuple = (3, 8)
    w_range: tuple = (3, 5)
    h_range: tuple = (2.5, 3)
    beta_range: tuple = (0.3, 0.6)  # RT60 seconds
    n_sensors_per_node: tuple = (4, 4, 4, 4)
    d_mw: float = 0.5
    d_mn: float = 0.05  # circular sub-array radius: 5 cm
    d_nn: float = 0.5
    d_rnd_mics: float = 1.0
    n_sources: int = 2
    d_ss: float = 0.5
    d_sn: float = 0.5
    d_sw: float = 0.5
    z_range_m: tuple = (0.7, 2)
    z_range_s: tuple = (1.20, 2)
    # Meeting/meetit extras (convolve_signals.py:370)
    r_range: tuple = (0.5, 1)
    d_nt_range: tuple = (0.05, 0.20)
    d_st_range: tuple = (0, 0.50)
    phi_ss_range: tuple = (np.pi / 8, 15 * np.pi / 8)
    max_order: int = 20  # ISM reflection order (convolve_signals.py:245)
    fs: int = 16000


@dataclasses.dataclass(frozen=True)
class SignalDefaults:
    """(convolve_signals.py:404-409)"""

    duration_range: tuple = (5, 10)
    var_tar_db: float = -23.0
    snr_dry_range: tuple = ((0, 0),)
    snr_cnv_range: tuple = (-10, 15)
    min_delta_snr: float = 0.0
    lead_silence_s: float = 1.0  # prepended second of silence (signal_setups.py:70)
    train_pad_s: float = 11.0  # train clips padded to 11 s (convolve_signals.py:275-279)


def make_setup(scenario: str, rng=None, **overrides):
    """Build the scenario's room sampler with the reference's per-scenario
    z-ranges (convolve_signals.py:377-401)."""
    from disco_tpu.sim.geometry import (
        LivingRoomSetup,
        MeetingRoomSetup,
        MeetitSetup,
        RandomRoomSetup,
    )

    d = dataclasses.asdict(RoomDefaults())
    for k in ("max_order", "fs"):
        d.pop(k)
    d.update(overrides)
    common = dict(
        l_range=d["l_range"], w_range=d["w_range"], h_range=d["h_range"],
        beta_range=d["beta_range"], n_sensors_per_node=d["n_sensors_per_node"],
        d_mw=d["d_mw"], d_mn=d["d_mn"], d_nn=d["d_nn"], d_rnd_mics=d["d_rnd_mics"],
        n_sources=d["n_sources"], d_ss=d["d_ss"], d_sn=d["d_sn"], d_sw=d["d_sw"],
        rng=rng,
    )
    table = dict(
        r_range=d["r_range"], d_nt_range=d["d_nt_range"],
        d_st_range=d["d_st_range"], phi_ss_range=d["phi_ss_range"],
    )
    if scenario == "meeting":
        return MeetingRoomSetup(z_range_m=(0.7, 0.8), z_range_s=(1.15, 1.30), **table, **common)
    if scenario == "meetit":
        return MeetitSetup(z_range_m=(0.7, 0.8), z_range_s=(1.15, 1.30), **table, **common)
    if scenario == "living":
        return LivingRoomSetup(z_range_m=(0.7, 0.95), z_range_s=(1.20, 2), **common)
    if scenario == "random":
        return RandomRoomSetup(z_range_m=d["z_range_m"], z_range_s=d["z_range_s"], **common)
    raise ValueError(f"unknown scenario {scenario!r}")
