"""Batched image-source-method (ISM) room impulse responses on TPU.

The reference delegates RIR computation to pyroomacoustics' C++ ``libroom``
engine (``gen_disco/convolve_signals.py:84-99`` calls
``room.image_source_model(use_libroom=True)`` + ``compute_rir`` on a
``pra.ShoeBox(max_order=20)``).  This module is the compiled, performance-
class equivalent (SURVEY.md §2.9): the Allen & Berkley shoebox ISM as one
fused XLA program —

* image enumeration for ``|n|+|l|+|m| <= max_order`` is a *static* lattice
  (computed once per ``max_order`` on host, ~12k images at order 20),
* per-image positions / reflection counts / distances / amplitudes are one
  broadcast batch over (images, mics),
* the fractional-delay injection is a windowed-sinc (81-tap Hann, the
  libroom convention) scatter-add into the RIR buffer,

and the whole thing ``vmap``s over sources, mics and rooms — a 64-room ×
8-node MEETIT batch is one device launch (BASELINE.md milestone config 5).

Conventions matched to pyroomacoustics: sound speed c = 343 m/s, uniform
wall energy absorption ``alpha`` (reflection coefficient sqrt(1-alpha)),
amplitude 1/(4·pi·d), fs 16 kHz.
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

C_SOUND = 343.0
FDL = 81  # fractional-delay filter length (libroom's windowed-sinc taps)


@lru_cache(maxsize=None)
def image_lattice(max_order: int) -> tuple[np.ndarray, np.ndarray]:
    """Static image lattice for the shoebox ISM.

    Returns (lattice, parity):
      lattice: (n_img, 3) int — the (n, l, m) cell indices,
      parity:  (n_img, 3) int in {0, 1} — the (u, v, w) mirror parities,
    enumerating every image with total reflection count
    ``|n-u|+|n| + |l-v|+|l| + |m-w|+|m| <= max_order`` (Allen & Berkley 1979;
    the sum-order truncation libroom applies).
    """
    rng = np.arange(-max_order, max_order + 1)
    cells = np.stack(np.meshgrid(rng, rng, rng, indexing="ij"), -1).reshape(-1, 3)
    par = np.stack(np.meshgrid([0, 1], [0, 1], [0, 1], indexing="ij"), -1).reshape(-1, 3)
    lat = np.repeat(cells, len(par), axis=0)
    pr = np.tile(par, (len(cells), 1))
    n_refl = np.abs(lat - pr).sum(-1) + np.abs(lat).sum(-1)
    keep = n_refl <= max_order
    return lat[keep].astype(np.int32), pr[keep].astype(np.int32)


@partial(jax.jit, static_argnames=("max_order", "rir_len", "fs"))
def shoebox_rir(
    room_dim: jnp.ndarray,
    source: jnp.ndarray,
    mics: jnp.ndarray,
    alpha,
    max_order: int = 20,
    rir_len: int = 8192,
    fs: int = 16000,
) -> jnp.ndarray:
    """RIRs from one source to M mics in a shoebox room.

    Args:
      room_dim: (3,) room dimensions [Lx, Ly, Lz] in meters.
      source: (3,) source position.
      mics: (M, 3) microphone positions.
      alpha: scalar energy absorption of all walls (the Eyring-calibrated
        value of reference room_setups.py:92).
      max_order: maximum total reflection count (reference uses 20,
        convolve_signals.py:245).
      rir_len: output length in samples (static under jit; images arriving
        later are dropped, as a finite libroom RIR does).

    Returns:
      (M, rir_len) float32 RIRs.
    """
    lat_np, par_np = image_lattice(max_order)
    lat = jnp.asarray(lat_np, jnp.float32)  # (I, 3)
    par = jnp.asarray(par_np, jnp.float32)
    n_refl = jnp.sum(jnp.abs(lat - par), -1) + jnp.sum(jnp.abs(lat), -1)  # (I,)

    # Image positions: x_im = (1-2u)·x_s + 2 n L   (per axis).
    img = (1.0 - 2.0 * par) * source[None, :] + 2.0 * lat * room_dim[None, :]  # (I, 3)
    beta = jnp.sqrt(jnp.maximum(1.0 - alpha, 0.0))
    amp_refl = beta**n_refl  # (I,)

    d = jnp.linalg.norm(img[None, :, :] - mics[:, None, :], axis=-1)  # (M, I)
    d = jnp.maximum(d, 1e-3)
    amp = amp_refl[None, :] / (4.0 * jnp.pi * d)  # (M, I)
    delay = d * (fs / C_SOUND)  # fractional samples

    # Windowed-sinc fractional delay: each image injects FDL taps centered
    # on its (fractional) delay.
    half = FDL // 2
    t0 = jnp.floor(delay).astype(jnp.int32)  # integer part
    frac = delay - t0
    taps = jnp.arange(-half, half + 1, dtype=jnp.float32)  # (FDL,)
    arg = taps[None, None, :] - frac[..., None]  # (M, I, FDL)
    win = 0.5 * (1.0 + jnp.cos(jnp.pi * arg / (half + 1)))
    win = jnp.where(jnp.abs(arg) <= half + 1, win, 0.0)
    sinc = jnp.sinc(arg) * win
    vals = amp[..., None] * sinc  # (M, I, FDL)

    idx = t0[..., None] + taps.astype(jnp.int32)[None, None, :]  # (M, I, FDL)
    # Out-of-range taps (negative or beyond rir_len) are routed to a
    # sacrificial slot.
    oob = (idx < 0) | (idx >= rir_len)
    idx = jnp.where(oob, rir_len, idx)
    vals = jnp.where(oob, 0.0, vals)

    def scatter_one(vals_m, idx_m):
        buf = jnp.zeros(rir_len + 1, jnp.float32)
        return buf.at[idx_m.reshape(-1)].add(vals_m.reshape(-1))[:rir_len]

    return jax.vmap(scatter_one)(vals, idx)


@partial(jax.jit, static_argnames=("max_order", "rir_len", "fs"))
def shoebox_rirs(room_dim, sources, mics, alpha, max_order: int = 20, rir_len: int = 8192, fs: int = 16000):
    """(S, 3) sources × (M, 3) mics -> (S, M, rir_len) RIRs; one launch."""
    return jax.vmap(
        lambda src: shoebox_rir(room_dim, src, mics, alpha, max_order=max_order, rir_len=rir_len, fs=fs)
    )(sources)


def rir_bucket(
    beta: float,
    room_dim=None,
    max_order: int = 20,
    fs: int = 16000,
    margin: float = 1.3,
    quantum: int = 256,
) -> tuple[int, int]:
    """The canonical static ``(max_order, rir_len)`` bucket for one scene.

    This is the ONE place the RIR-buffer policy lives (the reference lets
    pyroomacoustics size the RIR from the actual image set,
    ``gen_disco/convolve_signals.py:84-99``; a static-shape compile needs the
    length picked up front).  Two bounds are combined:

    * the RT60 bound — ``beta * margin`` seconds of tail (the historical
      ``rir_length_for`` policy), and
    * the order-coverage bound — when ``room_dim`` is given, the arrival
      time of the farthest order-``max_order`` image,
      ``|(2*max_order + 1) * room_dim| / c``, plus the FDL half-width.
      A buffer longer than that only holds zeros, so the bucket is clamped
      to it: ``rir_len`` never outruns what ``max_order`` can fill (the
      DL006 fix — previously the margin clamped ``rir_len`` independently
      of ``max_order``).

    ``rir_len`` is rounded up to ``quantum`` so nearby scenes share a
    compiled program; the batched engine passes a coarser quantum to bound
    its bucket count.  Returns ``(max_order, rir_len)``.
    """
    rt60_len = int(np.ceil(float(beta) * margin * fs))
    rir_len = rt60_len
    if room_dim is not None:
        dim = np.asarray(room_dim, np.float64).reshape(-1, 3)
        # Farthest image position per axis is (2*max_order + 1) * L_ax (the
        # mic sits inside the room, so distance is bounded by the image
        # position norm); arrival sample = d * fs / c, plus half the
        # windowed-sinc support.
        far = float(np.max(np.linalg.norm((2 * max_order + 1) * dim, axis=-1)))
        order_len = int(np.ceil(far * fs / C_SOUND)) + FDL // 2 + 1
        rir_len = min(rir_len, order_len)
    rir_len = max(rir_len, FDL)
    rir_len = int(np.ceil(rir_len / quantum) * quantum)
    return max_order, rir_len


def rir_length_for(beta: float, fs: int = 16000, margin: float = 1.3) -> int:
    """A static RIR length comfortably covering an RT60 of ``beta`` seconds.

    Delegates to :func:`rir_bucket`, the one canonical rir_len/max_order
    policy (without a ``room_dim`` the order-coverage clamp is skipped, so
    this reproduces the historical RT60-only sizing byte-for-byte).
    """
    return rir_bucket(beta, None, fs=fs, margin=margin)[1]


@partial(jax.jit, static_argnames=("max_order", "rir_len", "fs"))
def shoebox_rirs_batched(
    room_dims: jnp.ndarray,
    sources: jnp.ndarray,
    mics: jnp.ndarray,
    alphas: jnp.ndarray,
    max_order: int = 20,
    rir_len: int = 8192,
    fs: int = 16000,
) -> jnp.ndarray:
    """A (B,) batch of rooms — B × S sources × M mics in ONE program.

    ``vmap`` of :func:`shoebox_rirs` over a leading scene axis: the image
    lattice stays one static host-side constant shared by every room, and
    the scatter-adds for all ``B * S * M`` RIRs fuse into a single XLA
    launch.  The reference simulates rooms one ``pra.ShoeBox`` at a time
    (``gen_disco/convolve_signals.py:84-99``); on a tunnel where each
    fenced dispatch costs ~80 ms, batching the scene axis is what makes a
    100k-scene corpus tractable (ROADMAP item 4).

    Args:
      room_dims: (B, 3) room dimensions.
      sources: (B, S, 3) source positions per room.
      mics: (B, M, 3) mic positions per room.
      alphas: (B,) wall energy absorption per room.
      max_order/rir_len: the static bucket — pick via :func:`rir_bucket`
        (shared across the batch; every scene in a batch must agree).

    Returns:
      (B, S, M, rir_len) float32 RIRs.
    """
    return jax.vmap(
        lambda dim, src, mc, al: shoebox_rirs(
            dim, src, mc, al, max_order=max_order, rir_len=rir_len, fs=fs
        )
    )(room_dims, sources, mics, alphas)


@partial(jax.jit, static_argnames=("out_len",))
def fft_convolve(signals: jnp.ndarray, rirs: jnp.ndarray, out_len: int) -> jnp.ndarray:
    """Batched linear convolution via rFFT (the compiled equivalent of the
    reference's per-channel ``np.convolve`` loops, convolve_signals.py:161
    and ``room.simulate``).

    Args:
      signals: (..., L) float.
      rirs: (..., R) float, broadcast-compatible leading axes.
      out_len: static output length (<= L + R - 1); typically L.

    Returns:
      (..., out_len) float32.
    """
    L = signals.shape[-1]
    R = rirs.shape[-1]
    n = L + R - 1
    nfft = 1 << (n - 1).bit_length()
    out = jnp.fft.irfft(
        jnp.fft.rfft(signals, nfft) * jnp.fft.rfft(rirs, nfft), nfft
    )[..., :out_len]
    return out.astype(jnp.float32)
