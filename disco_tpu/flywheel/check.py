"""``make flywheel-check`` — the serve→train flywheel gate (tenth gate).

Proves the whole loop end to end, hermetically (CPU backend forced by the
Makefile, 8 virtual devices via ``XLA_FLAGS``, loopback sockets only, ONE
jax process, compile cache off, zero SIGKILLs):

1. **Tap**: loopback serve traffic with the corpus tap on — every
   delivered block is spooled with zero drops, serving keeps its
   one-batched-readback-per-tick invariant, every rotated shard passes
   its integrity probe and the manifest ledger's verified replay.
2. **Chaos**: an injected :class:`~disco_tpu.runs.chaos.ChaosCrash` at
   the ``mid_write`` seam inside a shard write dies like a process death
   — **no torn shard may survive at a final path** (the atomic-write
   invariant), the manifest never records the victim, and a planted
   truncated shard is skipped loudly (``warning`` event +
   ``shards_skipped`` counter) by the dataset, never fed to training.
3. **Resume**: the shard dataset's batch stream is deterministic per
   (seed, epoch), and a :class:`~disco_tpu.runs.RunLedger`-armed epoch
   replays to zero duplicate shards after completion — verified resume on
   the training *input* side.
4. **Training parity**: the data-parallel ``train_step``
   (``NamedSharding(mesh, P('batch'))``, replicated params, donated
   TrainState) is **bit-exact** against the single-device oracle on the
   1-device mesh, and within a documented tolerance
   (:data:`MESH_LOSS_RTOL` — cross-shard reduction reassociation) on the
   8-virtual-device mesh; a short ``fit`` run on the mesh pins the
   ChunkPrefetcher batch feed (overlap gauges recorded) and the explicit
   ``epochs_done`` checkpoint field.

No reference counterpart: the reference has neither serving nor any
loop from deployment traffic back into training (SURVEY.md §2).
"""
from __future__ import annotations

import json
import sys
import tempfile
import threading
from pathlib import Path

K, C, U = 4, 2, 4
BLOCK = 2 * U

#: documented tolerance of the N>1-device data-parallel loss vs the
#: single-device oracle: the per-shard partial sums of the batch-mean loss
#: (and of the all-reduced gradients) reassociate across devices, so the
#: match is exact math under a different reduction order — same contract
#: shape as the bf16 lane's documented oracle tolerances (PR 9), measured
#: comfortably below this bound on the gate's workload.  The 1-device mesh
#: has no cross-device reduction and must be bit-exact.
MESH_LOSS_RTOL = 2e-4

WIN = BLOCK // 2     # training windows: two per tapped full block
TRAIN_BATCH = 8      # divisible by the 8-device mesh batch axis
TRAIN_STEPS = 6


def _scene(seed, L=16000):
    import numpy as np

    from disco_tpu.core.dsp import stft

    rng = np.random.default_rng(seed)
    Y = np.asarray(stft(rng.standard_normal((K, C, L)).astype(np.float32)))
    F, T = Y.shape[-2:]
    m = rng.uniform(0.05, 0.95, size=(K, F, T)).astype(np.float32)
    return Y, m


def _tiny_model(n_freq: int):
    from disco_tpu.nn.crnn import build_crnn

    return build_crnn(
        n_ch=1, win_len=WIN, n_freq=n_freq,
        cnn_filters=(4,), pool_kernels=((1, 4),), conv_padding=((0, 1),),
        rnn_units=(16,), ff_units=(n_freq,), rnn_dropouts=0.0,
    )


def _check_tap_serve(failures: list, tap_dir: Path) -> dict:
    """Experiment 1: loopback serve traffic with the tap on."""
    from disco_tpu.flywheel import CorpusTap, list_shards, probe_shard, read_shard
    from disco_tpu.obs.accounting import device_get_count
    from disco_tpu.runs.ledger import RunLedger
    from disco_tpu.serve import EnhanceServer, ServeClient, SessionConfig

    scenes = [_scene(61), _scene(62)]
    F = scenes[0][0].shape[-2]
    n_blocks = sum(-(-Y.shape[-1] // BLOCK) for Y, _ in scenes)

    tap = CorpusTap(tap_dir, records_per_shard=3)
    srv = EnhanceServer(max_sessions=4, tap=tap)
    addr = srv.start()
    gets0 = device_get_count()
    errors: list = []

    def worker(i):
        Y, m = scenes[i]
        try:
            cl = ServeClient(addr)
            cl.open(SessionConfig(n_nodes=K, mics_per_node=C, n_freq=F,
                                  block_frames=BLOCK, update_every=U),
                    session_id=f"fly{i}")
            cl.enhance_clip(Y, m, m)
            cl.close()
            cl.shutdown()
        except Exception as e:
            errors.append(f"client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(scenes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    gets = device_get_count() - gets0
    ticks = srv.scheduler.ticks_with_work
    srv.stop()
    stats = tap.close()
    failures.extend(errors)

    if gets != ticks:
        failures.append(
            f"tap-serve: {gets} batched readbacks for {ticks} ticks — the "
            "tap broke the one-device_get_tree-per-tick invariant"
        )
    if stats["blocks_dropped"]:
        failures.append(f"tap-serve: {stats['blocks_dropped']} blocks dropped "
                        "at gate load")
    if stats["blocks_accepted"] != n_blocks:
        failures.append(
            f"tap-serve: spooled {stats['blocks_accepted']} blocks, expected "
            f"{n_blocks} (one per delivered block)"
        )
    shards = list_shards(tap_dir)
    if len(shards) < 2:
        failures.append(f"tap-serve: expected >= 2 rotated shards, got {len(shards)}")
    n_records = 0
    for sp in shards:
        if not probe_shard(sp):
            failures.append(f"tap-serve: shard fails its probe: {sp}")
        else:
            n_records += len(read_shard(sp)[1])
    if n_records != stats["blocks_accepted"]:
        failures.append(
            f"tap-serve: shards hold {n_records} records, tap accepted "
            f"{stats['blocks_accepted']} — blocks lost between spool and disk"
        )
    done, requeued = RunLedger(tap_dir / "manifest.jsonl").verified_done(requeue=False)
    if len(done) != len(shards) or requeued:
        failures.append(
            f"tap-serve: manifest verifies {len(done)}/{len(shards)} shards "
            f"done ({len(requeued)} requeued) — digests drifted"
        )
    return {"blocks": n_blocks, "shards": len(shards), "ticks": ticks,
            "n_freq": F}


def _check_chaos_torn_shard(failures: list, tap_dir: Path) -> dict:
    """Experiment 2: mid_write chaos + a planted truncated shard."""
    from disco_tpu.flywheel import (
        ShardDataset,
        list_shards,
        probe_shard,
        read_shard,
        write_shard,
    )
    from disco_tpu.io.atomic import TMP_SUFFIX
    from disco_tpu.obs.metrics import REGISTRY as obs_registry
    from disco_tpu.runs import chaos

    before = list_shards(tap_dir)
    if not before:
        # a tap regression upstream: report it as a finding so experiment
        # 1's failures still print, instead of dying on before[0]
        failures.append("chaos: no shards on disk to run the crash "
                        "experiment against (see tap-serve failures)")
        return {"batches_with_torn_present": 0, "skipped": 0}
    victim = tap_dir / "tap-900000.shard.msgpack"
    _meta, records = read_shard(before[0])
    chaos.configure("mid_write", after=1)
    try:
        write_shard(victim, records)
        failures.append("chaos: mid_write crash never fired in write_shard")
    except chaos.ChaosCrash:
        pass
    finally:
        chaos.disable()
    if victim.exists():
        failures.append(
            "chaos: a shard reached its final path through a mid-write crash "
            "(atomic-write invariant broken)"
        )
    litter = [str(p) for p in tap_dir.rglob(f"*{TMP_SUFFIX}.*")]
    if litter:
        failures.append(f"chaos: shard temp litter left on unwind: {litter}")
    if list_shards(tap_dir) != before:
        failures.append("chaos: the shard listing changed across the crash")

    # the same write lands fine once the 'process' is back
    write_shard(victim, records)
    if not probe_shard(victim):
        failures.append("chaos: post-crash rewrite of the shard fails its probe")

    # a torn shard at a final path (truncated behind the writer's back —
    # e.g. filesystem damage) must be skipped loudly, never trained on
    torn = tap_dir / "tap-900001.shard.msgpack"
    raw = victim.read_bytes()
    torn.write_bytes(raw[: len(raw) // 2])  # disco-lint: disable=DL004 -- deliberately planting a torn artifact; the gate asserts the reader rejects it
    if probe_shard(torn):
        failures.append("chaos: a truncated shard passes probe_shard")
    ds = ShardDataset(tap_dir, win_len=WIN, seed=0)
    skipped0 = obs_registry.peek_counter("shards_skipped")
    n_batches = sum(1 for _ in ds.batches(TRAIN_BATCH, epoch=0))
    skipped = obs_registry.peek_counter("shards_skipped") - skipped0
    if skipped != 1:
        failures.append(
            f"chaos: dataset skipped {skipped} shards, expected exactly the "
            "planted torn one"
        )
    if n_batches == 0:
        failures.append("chaos: dataset yielded nothing with intact shards present")
    torn.unlink()
    victim.unlink()  # keep later experiments on the tapped shards only
    if list_shards(tap_dir) != before:
        failures.append("chaos: experiment residue left in the tap dir")
    return {"batches_with_torn_present": n_batches, "skipped": skipped}


def _check_dataset_resume(failures: list, tap_dir: Path, scratch: Path) -> dict:
    """Experiment 3: deterministic stream + ledger-verified epoch resume."""
    import numpy as np

    from disco_tpu.flywheel import ShardDataset

    ds = ShardDataset(tap_dir, win_len=WIN, seed=11)
    a = list(ds.batches(TRAIN_BATCH, epoch=0))
    b = list(ds.batches(TRAIN_BATCH, epoch=0))
    if len(a) == 0:
        failures.append("resume: dataset yields no batches")
    if len(a) != len(b) or not all(
        np.array_equal(xa, xb) and np.array_equal(ya, yb)
        for (xa, ya), (xb, yb) in zip(a, b)
    ):
        failures.append("resume: the (seed, epoch) batch stream is not deterministic")

    led = scratch / "dataset_ledger.jsonl"
    first = list(ds.batches(TRAIN_BATCH, epoch=0, ledger=led))
    again = list(ds.batches(TRAIN_BATCH, epoch=0, ledger=led))
    if len(first) != len(a):
        failures.append("resume: the ledger-armed epoch differs from the bare one")
    if again:
        failures.append(
            f"resume: a completed epoch replayed {len(again)} batches — "
            "verified resume must skip every consumed shard"
        )
    return {"batches_per_epoch": len(a)}


def _check_training_parity(failures: list, tap_dir: Path, scratch: Path,
                           n_freq: int) -> dict:
    """Experiment 4: mesh-vs-single-device loss parity + the fit seams."""
    import jax
    import numpy as np

    from disco_tpu.flywheel import ShardDataset
    from disco_tpu.nn.training import (
        create_train_state,
        load_checkpoint,
        make_step_fns,
        replicate_to_mesh,
    )
    from disco_tpu.parallel.mesh import make_mesh

    if jax.default_backend() != "cpu":
        failures.append(f"training: backend {jax.default_backend()!r}; the gate "
                        "is CPU-only by contract")
        return {}
    n_dev = len(jax.devices())
    ds = ShardDataset(tap_dir, win_len=WIN, seed=3)
    batches = list(ds.batches(TRAIN_BATCH, epoch=0))[:TRAIN_STEPS]
    if len(batches) < 2:
        failures.append(f"training: only {len(batches)} batches available")
        return {}
    model, tx = _tiny_model(n_freq)

    def run(mesh):
        t_step, _ = make_step_fns(model, "all", mesh=mesh)
        state = create_train_state(model, tx, batches[0][0][:1], seed=5)
        if mesh is not None:
            state = replicate_to_mesh(state, mesh)
        losses = []
        for x, y in batches:
            state, loss = t_step(state, x, y)
            losses.append(loss)
        return np.asarray([float(v) for v in losses]), state

    oracle, s_single = run(None)
    mesh1 = make_mesh(n_node=1, n_batch=1, devices=np.array(jax.devices()[:1]))
    one_dev, s_mesh1 = run(mesh1)
    if not np.array_equal(oracle, one_dev):
        failures.append(
            f"training: 1-device-mesh losses differ from the single-device "
            f"oracle (max abs diff {np.abs(oracle - one_dev).max():g}) — the "
            "degraded-mesh path must be bit-exact"
        )
    p_single = np.asarray(jax.tree_util.tree_leaves(s_single.params)[0])
    p_mesh1 = np.asarray(jax.tree_util.tree_leaves(s_mesh1.params)[0])
    if not np.array_equal(p_single, p_mesh1):
        failures.append("training: 1-device-mesh params drift from the oracle")

    sharded = None
    if n_dev >= 2:
        mesh_n = make_mesh(n_node=1, n_batch=n_dev)
        sharded, _ = run(mesh_n)
        rel = np.abs(sharded - oracle) / np.maximum(np.abs(oracle), 1e-12)
        if rel.max() > MESH_LOSS_RTOL:
            failures.append(
                f"training: {n_dev}-device losses off by rel {rel.max():g} > "
                f"documented MESH_LOSS_RTOL={MESH_LOSS_RTOL:g}"
            )
    else:
        failures.append(
            f"training: only {n_dev} device(s) — run via `make flywheel-check` "
            "(XLA_FLAGS forces 8 virtual CPU devices)"
        )

    # the fit seams: ChunkPrefetcher batch feed (overlap gauges), ledger'd
    # shard consumption, mesh lane, explicit epochs_done in the checkpoint
    from disco_tpu.nn.training import fit
    from disco_tpu.obs.metrics import REGISTRY as obs_registry

    mesh_fit = make_mesh(n_node=1, n_batch=n_dev) if n_dev >= 2 else mesh1
    state = create_train_state(model, tx, batches[0][0][:1], seed=5)
    state, tr, va, run_name = fit(
        model, state,
        ds.batch_fn(TRAIN_BATCH, shuffle=True,
                    ledger=scratch / "fit_ledger.jsonl"),
        ds.batch_fn(TRAIN_BATCH, shuffle=False),
        n_epochs=2, save_path=scratch / "models", verbose=False,
        mesh=mesh_fit,
    )
    gauges = obs_registry.snapshot()["gauges"]
    for g in ("prefetch_stall_ms", "overlap_efficiency"):
        if gauges.get(g) is None:
            failures.append(f"training: fit never recorded the {g} gauge — "
                            "the ChunkPrefetcher batch feed is not wired")
    ckpt = scratch / "models" / f"{run_name}_model.msgpack"
    if not ckpt.exists():
        failures.append("training: fit saved no checkpoint")
    else:
        fresh = create_train_state(model, tx, batches[0][0][:1], seed=5)
        _, tr_hist, _ = load_checkpoint(ckpt, fresh)
        if len(tr_hist) == 0 or len(tr_hist) > 2:
            failures.append(
                f"training: checkpoint epochs_done restored {len(tr_hist)} "
                "epochs, expected 1..2"
            )
    return {
        "devices": n_dev,
        "steps": len(batches),
        "oracle_loss": float(oracle[-1]),
        "mesh_loss": float(sharded[-1]) if sharded is not None else None,
        "mesh_loss_rtol": MESH_LOSS_RTOL,
        "fit_epochs": int(np.count_nonzero(tr)),
    }


def main(argv=None) -> int:
    """Run the flywheel gate (``make flywheel-check``); exit 1 on failure.

    No reference counterpart (module docstring)."""
    import os

    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    from disco_tpu import obs

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        obs_log = tmp / "flywheel_check.jsonl"
        with obs.recording(obs_log):
            obs.write_manifest(tool="flywheel-check")
            tap_dir = tmp / "tap"
            served = _check_tap_serve(failures, tap_dir)
            chaos_stats = _check_chaos_torn_shard(failures, tap_dir)
            resume = _check_dataset_resume(failures, tap_dir, tmp)
            training = _check_training_parity(failures, tap_dir, tmp,
                                              served["n_freq"])
            obs.record("counters", **obs.REGISTRY.snapshot())
        events = obs.read_events(obs_log)  # schema-validating read

        if not any(e["kind"] == "tap" and e["attrs"].get("action") == "shard"
                   for e in events):
            failures.append("event log missing tap shard-rotation events")
        if not any(e["kind"] == "warning" and "corrupt shard" in
                   str(e["attrs"].get("reason", "")) for e in events):
            failures.append("event log missing the corrupt-shard warning")

    if failures:
        for f in failures:
            print(f"flywheel-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "flywheel_check": "ok",
        "served_blocks": served["blocks"],
        "shards": served["shards"],
        "batches_per_epoch": resume["batches_per_epoch"],
        "devices": training.get("devices"),
        "train_steps": training.get("steps"),
        "oracle_loss": training.get("oracle_loss"),
        "mesh_loss": training.get("mesh_loss"),
        "mesh_loss_rtol": training.get("mesh_loss_rtol"),
        "jax_processes": 1,
        "sigkills_issued": 0,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
