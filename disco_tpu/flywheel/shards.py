"""Training-shard files: the on-disk format of the serve→train flywheel.

A shard is one self-describing msgpack file holding a bounded list of
served-traffic records — each record is one delivered streaming block's
``(noisy Y, enhanced yf, mask_z, mask_w)`` arrays plus its session/seq
metadata.  Arrays travel in the wire codec of
:mod:`disco_tpu.serve.protocol` (``encode_array``: complex dtypes split
into two real byte strings — msgpack has no complex type), so a shard is
readable by any numpy+stdlib process; nothing in this module may import
jax (the tap's writer thread runs it — disco-lint DL005 pins the import
graph).

Integrity is layered the same way as the serve session checkpoints
(``serve/session.py``): the record payload carries an embedded sha256 of
its own bytes, the file is placed with the tmp+fsync+``os.replace``
protocol of :mod:`disco_tpu.io.atomic` (a crash mid-write — the
``mid_write`` chaos seam fires inside — can never leave a torn shard at
the final path), and :func:`probe_shard` is the validate-before-trust
read the dataset and the manifest ledger use: a truncated or tampered
shard reads as *not a shard*, never as silently-wrong training data.

No reference counterpart: the reference trains from a pre-generated
corpus and has no serving layer to tap (SURVEY.md §2).
"""
from __future__ import annotations

import hashlib
from pathlib import Path

import msgpack
import numpy as np

from disco_tpu.serve.protocol import decode_array, encode_array

#: bump on incompatible shard-format changes; readers reject unknown
#: versions loudly instead of misparsing
SHARD_VERSION = 1

#: final-path suffix of every flywheel shard (``tap-000001.shard.msgpack``)
SHARD_SUFFIX = ".shard.msgpack"

#: the array fields every record carries (the tap's post-readback payload)
RECORD_ARRAYS = ("Y", "yf", "mask_z", "mask_w")


class ShardError(ValueError):
    """A shard file is truncated, tampered with, or not a shard at all."""


def unit_shard(name: str) -> str:
    """Ledger work-unit id of one shard (manifest + dataset-resume records).

    No reference counterpart (module docstring)."""
    return f"shard:{name}"


def _pack(obj):
    """Msgpack-ready structure: numpy arrays via the complex-safe wire
    codec, numpy scalars to python, containers walked."""
    if isinstance(obj, np.ndarray):
        return encode_array(obj)
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_pack(v) for v in obj]
    return obj


def _unpack(obj):
    if isinstance(obj, dict):
        if obj.get("__nd__") == 1:
            return decode_array(obj)
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v) for v in obj]
    return obj


def write_shard(path, records, meta: dict | None = None) -> Path:
    """Write one shard atomically; returns the final path.

    ``records``: list of dicts, each carrying the :data:`RECORD_ARRAYS`
    numpy arrays (complex ``Y``/``yf`` included — the codec splits them)
    plus arbitrary scalar metadata (``session``, ``seq``, ``t``).  The
    record bytes are digested (sha256) into the envelope, and the whole
    payload goes through :func:`disco_tpu.io.atomic.atomic_write` — the
    ``mid_write`` chaos seam fires between payload and rename, which is
    exactly what lets ``make flywheel-check`` prove no torn shard can
    survive a crash.

    No reference counterpart (module docstring).
    """
    from disco_tpu.io.atomic import atomic_write

    records_bytes = msgpack.packb([_pack(r) for r in records], use_bin_type=True)
    payload = msgpack.packb(
        {
            "version": SHARD_VERSION,
            "meta": _pack(dict(meta or {})),
            "n_records": len(records),
            "records": records_bytes,
            "records_sha256": hashlib.sha256(records_bytes).hexdigest(),
        },
        use_bin_type=True,
    )
    path = Path(path)
    with atomic_write(path) as fh:
        fh.write(payload)
    return path


def read_shard(path) -> tuple[dict, list]:
    """Load one shard as ``(meta, records)`` with full validation.

    Raises :class:`ShardError` on unreadable/truncated msgpack, an unknown
    version, a record-digest mismatch (torn or tampered payload) or a
    record-count mismatch — the dataset's corrupt-shard skip and the
    :func:`probe_shard` integrity probe both stand on this being strict.

    No reference counterpart (module docstring).
    """
    path = Path(path)
    try:
        d = msgpack.unpackb(path.read_bytes(), raw=False, strict_map_key=False)
    except Exception as e:
        raise ShardError(f"{path}: not a readable shard: {e}") from None
    if not isinstance(d, dict) or d.get("version") != SHARD_VERSION:
        raise ShardError(
            f"{path}: unknown shard version "
            f"{d.get('version') if isinstance(d, dict) else d!r}"
        )
    records_bytes = d.get("records")
    digest = d.get("records_sha256")
    if not isinstance(records_bytes, bytes) or not digest:
        raise ShardError(f"{path}: shard missing records payload/digest")
    if hashlib.sha256(records_bytes).hexdigest() != digest:
        raise ShardError(
            f"{path}: records digest mismatch — shard corrupt, refusing to "
            "feed it to training"
        )
    try:
        records = _unpack(msgpack.unpackb(records_bytes, raw=False,
                                          strict_map_key=False))
    except Exception as e:
        raise ShardError(f"{path}: bad shard records: {e}") from None
    if not isinstance(records, list) or len(records) != int(d.get("n_records", -1)):
        raise ShardError(
            f"{path}: record count mismatch "
            f"({len(records) if isinstance(records, list) else '?'} vs "
            f"declared {d.get('n_records')})"
        )
    return _unpack(d.get("meta") or {}), records


def probe_shard(path) -> bool:
    """True iff ``path`` holds a complete, digest-consistent shard — the
    validate-before-trust probe (``io.atomic`` probe family shape).

    No reference counterpart (module docstring)."""
    try:
        read_shard(path)
        return True
    except Exception:
        return False


def list_shards(shard_dir) -> list[Path]:
    """Sorted final-path shard files under ``shard_dir`` (non-recursive —
    a tap dir holds its shards flat next to the manifest ledger).  Pure
    discovery: no integrity check (the dataset probes as it reads, so a
    corrupt entry is skipped loudly there, not hidden here).

    No reference counterpart (module docstring)."""
    return sorted(Path(shard_dir).glob(f"*{SHARD_SUFFIX}"))
