"""The corpus tap: serve traffic → training shards, without touching serving.

The serve scheduler's post-readback seam sees, for every delivered block,
exactly the tuple the CRNN mask estimator is starved for — the noisy
mixture STFT block ``Y``, the enhanced output ``yf`` and the step-1/2
masks, all already host-resident numpy (they crossed the boundary in the
tick's ONE batched readback).  :class:`CorpusTap` spools those tuples onto
a bounded queue drained by a background writer thread that rotates
self-describing shard files (:mod:`disco_tpu.flywheel.shards`) and records
each finished shard in a manifest ledger (:class:`disco_tpu.runs.RunLedger`
— digested ``done`` records, so resume verifies shards before trusting
them).

Discipline (the :class:`~disco_tpu.enhance.pipeline.ChunkPrefetcher`
rules, applied in reverse direction):

* the writer thread is **host-only** — msgpack + numpy + ``io.atomic``,
  never jax (disco-lint DL005 pins this module jax-free: a second thread
  entering jax would contend for the one chip claim);
* :meth:`CorpusTap.offer` **never blocks and never raises**: a full queue
  drops the block and ticks ``tap_dropped`` — serving NEVER backpressures
  on its own telemetry tap, and a tap bug must not evict a session;
* an injected :class:`~disco_tpu.runs.chaos.ChaosCrash` on the writer
  thread (the ``mid_write`` seam inside the atomic shard write) is
  stashed and re-raised at :meth:`close` — a simulated process death
  kills the run like a real one, it is never swallowed.

Counters: ``tap_blocks`` (accepted), ``tap_dropped`` (overflow),
``tap_shards_written``, ``tap_errors``; shard rotations record a ``tap``
obs event.  All rendered by ``disco-obs report``.

No reference counterpart: the reference pipeline is strictly offline and
discards nothing because it serves nothing (SURVEY.md §2).
"""
from __future__ import annotations

import queue as queue_mod
import threading
import time
from pathlib import Path

import numpy as np

from disco_tpu.flywheel.shards import SHARD_SUFFIX, unit_shard, write_shard
from disco_tpu.obs import events as obs_events
from disco_tpu.obs import trace as obs_trace
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.runs.ledger import RunLedger

#: manifest ledger file name inside a tap directory
MANIFEST_NAME = "manifest.jsonl"

_CLOSE = object()


class CorpusTap:
    """Bounded, never-blocking spool from the serve post-readback seam to
    rotated training shards under ``tap_dir``.

    Args:
      tap_dir: shard + manifest directory (created if missing).
      max_queue_blocks: bound on spooled-but-unwritten blocks; offers past
        it drop-and-count (``tap_dropped``) instead of blocking serving.
      records_per_shard: rotation threshold — a shard is finalized (atomic
        write + manifest ``done`` record with digest) every this many
        accepted blocks, and once more at :meth:`close` for the remainder.
      start: start the writer thread immediately (the default).  Tests and
        the overflow experiment of ``make flywheel-check`` pass ``False``
        to fill the queue deterministically, then call :meth:`start`.

    No reference counterpart (module docstring).
    """

    def __init__(self, tap_dir, *, max_queue_blocks: int = 256,
                 records_per_shard: int = 64, start: bool = True):
        if max_queue_blocks < 1 or records_per_shard < 1:
            raise ValueError("tap bounds must be >= 1")
        self.tap_dir = Path(tap_dir)
        self.tap_dir.mkdir(parents=True, exist_ok=True)
        self.records_per_shard = records_per_shard
        self.ledger = RunLedger(self.tap_dir / MANIFEST_NAME)
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=max_queue_blocks)
        self._buf: list[dict] = []
        # resume numbering after any shards already on disk: a restarted
        # server over the same tap dir (crash recovery, the resident
        # trainer's endurance campaign) must append, never overwrite shard
        # 1 — an overwrite would also void the manifest's recorded digest
        self._shard_seq = max(
            (int(p.name[len("tap-"):len("tap-") + 6])
             for p in self.tap_dir.glob(f"tap-??????{SHARD_SUFFIX}")),
            default=0)
        self._closing = False
        self._crashed: BaseException | None = None
        self._lock = threading.Lock()
        #: instance-local accounting (the registry counters are process
        #: global and shared across taps; stats() must be per-tap)
        self.accepted = 0
        self.dropped = 0
        self.shards_written = 0
        self._thread: threading.Thread | None = None
        if start:
            self.start()

    # -- producer side (the scheduler's dispatch thread) ---------------------
    def offer(self, session_id: str, seq: int, Y, mask_z, mask_w, yf,
              trace=None) -> bool:
        """Spool one delivered block; True when accepted.

        Non-blocking and exception-free by contract: a full queue (or a
        closing tap) drops the block, ticks ``tap_dropped`` and returns
        False — the dispatch thread that calls this between a readback and
        the next tick must never stall or unwind because of the tap.

        ``trace``: the delivered block's causal-trace context
        (``obs.trace.SpanCtx``) — the ``tap`` hop is recorded as the block
        enters the spool and the advanced trace/span ids are embedded in
        the shard record, so a training batch can be traced back to the
        client block that produced it.  None (untraced block / tracing
        off) costs nothing.

        No reference counterpart (module docstring).
        """
        if self._closing:
            self.dropped += 1
            obs_registry.counter("tap_dropped").inc()
            return False
        record = {
            "session": str(session_id),
            "seq": int(seq),
            "t": time.time(),
            "Y": np.asarray(Y),
            "yf": np.asarray(yf),
            "mask_z": np.asarray(mask_z),
            "mask_w": np.asarray(mask_w),
        }
        tap_ctx = None
        if trace is not None and obs_trace.enabled():
            # mint-then-commit: the span id must live in the record (it is
            # about to be queued away), but the EVENT is recorded only if
            # the spool accepts — a dropped block must never log a 'tap'
            # hop it did not take
            tap_ctx = obs_trace.SpanCtx(trace=trace.trace,
                                        span=obs_trace.new_id())
            record["trace"] = tap_ctx.to_wire()
        try:
            self._q.put_nowait(record)
        except queue_mod.Full:
            self.dropped += 1
            obs_registry.counter("tap_dropped").inc()
            return False
        if tap_ctx is not None:
            obs_trace.record_span("tap", tap_ctx, parent=trace.span,
                                  session=str(session_id), seq=int(seq))
        self.accepted += 1
        obs_registry.counter("tap_blocks").inc()
        return True

    # -- writer side (the tap thread) ----------------------------------------
    def start(self) -> None:
        """Start the background writer thread (idempotent).

        No reference counterpart (module docstring)."""
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="disco-flywheel-tap", daemon=True
            )
            self._thread.start()

    def _run(self):
        try:
            while True:
                try:
                    item = self._q.get(timeout=0.05)
                except queue_mod.Empty:
                    if self._closing:
                        break
                    continue
                if item is _CLOSE:
                    break
                self._buf.append(item)
                if len(self._buf) >= self.records_per_shard:
                    self._rotate()
            if self._buf:
                self._rotate()
        except Exception as e:
            # a tap bug is telemetry, not an outage: count it, say it, stop
            # writing — serving continues untouched
            obs_registry.counter("tap_errors").inc()
            obs_events.record("warning", stage="flywheel",
                              reason=f"tap writer died: {type(e).__name__}: {e}")
        except BaseException as e:  # ChaosCrash: a simulated process death
            # must kill the run — re-raised at close().  Under the lock:
            # close() reads-and-clears the stash, and a writer that
            # outlived its join timeout must never tear that exchange
            with self._lock:
                self._crashed = e

    def _rotate(self):
        """Finalize the buffered records as one shard: atomic write, then
        the manifest ``done`` record carrying the shard's digest."""
        self._shard_seq += 1
        name = f"tap-{self._shard_seq:06d}{SHARD_SUFFIX}"
        path = self.tap_dir / name
        records, self._buf = self._buf, []
        sessions = sorted({r["session"] for r in records})
        write_shard(path, records, meta={
            "created_t": time.time(),
            "sessions": sessions,
            "source": "serve-tap",
        })
        self.ledger.mark_done(unit_shard(name), artifact_paths=[path],
                              n_records=len(records))
        self.shards_written += 1
        obs_registry.counter("tap_shards_written").inc()
        obs_events.record("tap", stage="flywheel", action="shard",
                          shard=name, n_records=len(records),
                          sessions=len(sessions))

    # -- lifecycle -----------------------------------------------------------
    def close(self, timeout_s: float = 30.0) -> dict:
        """Flush and stop: drain the queue, finalize the remainder shard,
        join the writer, close the manifest.  Re-raises a stashed
        :class:`~disco_tpu.runs.chaos.ChaosCrash` from the writer thread
        (a simulated death must surface, never be absorbed by cleanup).
        Returns :meth:`stats`.  Idempotent.

        No reference counterpart (module docstring).
        """
        self._closing = True
        if self._thread is None and not self._q.empty():
            # never-started tap (the start=False test seam) with spooled
            # blocks: run the writer now so close() still flushes them
            self.start()
        thread = self._thread
        if thread is not None:
            # unblock a writer parked on an empty queue
            try:
                self._q.put_nowait(_CLOSE)
            except queue_mod.Full:
                pass
            thread.join(timeout=timeout_s)
            if thread.is_alive():
                obs_registry.counter("tap_errors").inc()
                obs_events.record(
                    "warning", stage="flywheel",
                    reason=f"tap writer still flushing after close({timeout_s:g}s)",
                )
        self.ledger.close()
        obs_events.record("tap", stage="flywheel", action="close",
                          **self.stats())
        with self._lock:
            crash, self._crashed = self._crashed, None
        if crash is not None:
            raise crash
        return self.stats()

    def stats(self) -> dict:
        """Per-tap accounting: accepted/dropped blocks, shards written.

        No reference counterpart (module docstring)."""
        return {
            "tap_dir": str(self.tap_dir),
            "blocks_accepted": self.accepted,
            "blocks_dropped": self.dropped,
            "shards_written": self.shards_written,
        }
