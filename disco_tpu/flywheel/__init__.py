"""disco_tpu.flywheel — the serve→train learning loop (ROADMAP item 5).

The serve subsystem checkpoints session state but used to discard the one
signal the CRNN mask estimator is starved for: real (noisy, enhanced,
mask) traffic.  This package closes the loop end to end:

* :mod:`disco_tpu.flywheel.tap`     — :class:`CorpusTap`, an opt-in,
  never-blocking spool on the serve scheduler's post-readback seam that
  rotates delivered blocks into shard files on a host-only background
  thread (overflow drops-and-counts; serving never backpressures).
* :mod:`disco_tpu.flywheel.shards`  — the self-describing atomic shard
  format (complex-split wire codec, embedded sha256, ``probe_shard``)
  plus the manifest-ledger unit ids.
* :mod:`disco_tpu.flywheel.dataset` — :class:`ShardDataset`, the
  streaming reader: deterministic seeded shuffle, ``RunLedger`` verified
  resume, corrupt-shard skip-with-warning, ``fit``-ready batch callables.
* :mod:`disco_tpu.flywheel.check`   — ``make flywheel-check``, the tenth
  hermetic gate: loopback serve traffic with the tap on → clean shard
  digests → a ``mid_write`` chaos crash that must leave no torn shard →
  dataset resume → data-parallel training with loss parity against the
  single-device oracle.
* :mod:`disco_tpu.flywheel.resident` — :class:`ResidentTrainer`, the
  co-resident trainer: bounded train-step slices interleaved on the
  serve scheduler's dispatch thread (one jax process, one chip claim),
  ledger-restartable, ladder-throttled, publishing generations through
  the promote store on a cadence; drilled by ``make endure-check``.

The training side (mesh-sharded ``NamedSharding(mesh, P("batch"))`` data
parallelism and the opt-in bf16 lane) lives in
:mod:`disco_tpu.nn.training` — this package only produces its input.

All three non-check modules are importable jax-free (disco-lint DL005):
the tap's writer thread runs next to the one chip-claiming process and
must never enter jax.

No reference counterpart: the reference has neither a serving layer nor
any path from deployment traffic back into training (SURVEY.md §2).
"""
from disco_tpu.flywheel.dataset import ShardDataset, peek_geometry, unit_shard_epoch
from disco_tpu.flywheel.resident import ResidentTrainer
from disco_tpu.flywheel.shards import (
    RECORD_ARRAYS,
    SHARD_SUFFIX,
    SHARD_VERSION,
    ShardError,
    list_shards,
    probe_shard,
    read_shard,
    unit_shard,
    write_shard,
)
from disco_tpu.flywheel.tap import MANIFEST_NAME, CorpusTap

__all__ = [
    "CorpusTap",
    "MANIFEST_NAME",
    "RECORD_ARRAYS",
    "ResidentTrainer",
    "SHARD_SUFFIX",
    "SHARD_VERSION",
    "ShardDataset",
    "ShardError",
    "list_shards",
    "peek_geometry",
    "probe_shard",
    "read_shard",
    "unit_shard",
    "unit_shard_epoch",
    "write_shard",
]
