"""Streaming shard dataset: tapped serve traffic → CRNN training batches.

Reads the shard files a :class:`~disco_tpu.flywheel.tap.CorpusTap` wrote
and windows them into exactly the (x, y) batch convention the training
stack already consumes (``nn/data.DiscoDataset`` item shape, reference
dnn/data/datasets.py:102-162): ``x`` is the reference-mic magnitude STFT
window ``(win_len, F)`` float32 of one node, ``y`` the matching step-1
mask window — the tap's ``mask_z`` is the mask the serve client actually
used, so training on it closes the loop on real traffic.

Three production properties, each pinned by ``tests/test_flywheel.py``:

* **Deterministic seeded shuffle** — shard order is a permutation drawn
  from ``(seed, epoch)`` and the window order inside a shard from
  ``(seed, epoch, shard name)``, so two runs with one seed see identical
  batch streams (what makes the flywheel gate's mesh-vs-single-device
  loss parity meaningful), and a resumed run sees the SAME per-shard
  order regardless of which shards were already consumed.
* **Ledger resume** — with a :class:`~disco_tpu.runs.RunLedger`, every
  shard's consumption is an ``in_flight``→``done`` record (unit
  ``shard:<name>:epoch:<e>``, artifacts = the shard digest), and
  :meth:`ShardDataset.batches` skips shards whose record verifies — the
  verified-resume story of the corpus driver, applied to training input.
* **Corrupt-shard skip** — a shard failing :func:`~disco_tpu.flywheel.
  shards.read_shard` validation is skipped with a ``warning`` obs event
  and the ``shards_skipped`` counter, never silently truncating an epoch
  into wrong-but-plausible gradients.

Host-only module (numpy + stdlib): batches feed the jitted train step
through ``utils.transfer.prefetch_to_device`` on the training side; the
reader itself must stay importable jax-free (disco-lint DL005).

No reference counterpart: the reference trains from pre-generated .npy
lists (dnn/utils.py:74-140); a served-traffic dataset is flywheel-only.
"""
from __future__ import annotations

import zlib
from pathlib import Path

import numpy as np

from disco_tpu.flywheel.shards import ShardError, list_shards, read_shard
from disco_tpu.obs import events as obs_events
from disco_tpu.obs import trace as obs_trace
from disco_tpu.obs.metrics import REGISTRY as obs_registry


def unit_shard_epoch(name: str, epoch: int) -> str:
    """Ledger work-unit id of one shard's consumption in one epoch.

    No reference counterpart (module docstring)."""
    return f"shard:{name}:epoch:{int(epoch)}"


class ShardDataset:
    """Windowed training batches over a directory of flywheel shards.

    Args:
      shard_dir: the tap directory (shards + manifest).
      win_len: frames per training window; must fit inside one tapped
        block (serve blocks are short — the windows never cross block
        boundaries, matching the reference's per-segment windowing).
      win_hop: window hop (default ``win_len`` — non-overlapping).
      ref_mic: the node channel whose magnitude becomes the input.
      seed: base seed of every deterministic draw.

    No reference counterpart (module docstring).
    """

    def __init__(self, shard_dir, *, win_len: int = 8, win_hop: int | None = None,
                 ref_mic: int = 0, seed: int = 0):
        if win_len < 1:
            raise ValueError(f"win_len must be >= 1, got {win_len}")
        self.shard_dir = Path(shard_dir)
        self.win_len = int(win_len)
        self.win_hop = int(win_hop) if win_hop else self.win_len
        self.ref_mic = int(ref_mic)
        self.seed = int(seed)

    def shard_paths(self) -> list[Path]:
        """Sorted shard files currently on disk (discovery only; integrity
        is checked as each shard is read).

        No reference counterpart (module docstring)."""
        return list_shards(self.shard_dir)

    # -- windowing -----------------------------------------------------------
    def _shard_windows(self, path: Path, epoch: int, shuffle: bool = True):
        """(xs, ys) window stacks of one shard — in the shard's
        deterministic per-epoch order when ``shuffle``, in natural
        (record, node, frame) order otherwise (the validation stream must
        be identical every epoch); None when the shard is corrupt
        (skipped loudly)."""
        try:
            _meta, records = read_shard(path)
        except ShardError as e:
            obs_registry.counter("shards_skipped").inc()
            obs_events.record("warning", stage="flywheel", path=str(path),
                              reason=f"corrupt shard skipped: {e}")
            return None
        xs, ys = [], []
        tracing = obs_trace.enabled()
        for rec in records:
            if tracing and rec.get("trace") is not None:
                # the chain's last hop: this served block's tuple became
                # training input.  The span chains under the tap hop whose
                # ids the shard record carries, closing client→train
                # end-to-end (one span per traced record — bounded by
                # records_per_shard, and only while tracing is on).
                obs_trace.span(
                    "train_batch", obs_trace.from_wire(rec["trace"]),
                    shard=path.name, epoch=int(epoch),
                    session=rec.get("session"), seq=rec.get("seq"),
                )
            Y, mz = rec["Y"], rec["mask_z"]
            mag = np.abs(np.asarray(Y)[:, self.ref_mic]).astype(np.float32)
            K, _F, T = mag.shape
            for k in range(K):
                for t0 in range(0, T - self.win_len + 1, self.win_hop):
                    # (F, win) -> (win, F): the DiscoDataset item convention
                    xs.append(mag[k, :, t0:t0 + self.win_len].T)
                    ys.append(np.asarray(mz, np.float32)[k, :, t0:t0 + self.win_len].T)
        if not xs:
            return None
        if not shuffle:
            return np.stack(xs), np.stack(ys)
        order = self._shard_rng(path.name, epoch).permutation(len(xs))
        return (np.stack([xs[i] for i in order]),
                np.stack([ys[i] for i in order]))

    def _shard_rng(self, name: str, epoch: int) -> np.random.Generator:
        """Per-(shard, epoch) rng keyed by NAME, not position: resuming a
        partially-consumed epoch must reproduce each remaining shard's
        window order exactly, whatever was already consumed."""
        return np.random.default_rng(
            [self.seed, int(epoch), zlib.crc32(name.encode())]
        )

    # -- the batch stream ----------------------------------------------------
    def batches(self, batch_size: int, *, epoch: int = 0, shuffle: bool = True,
                ledger=None, drop_last: bool = True,
                recent: int | None = None):
        """Yield ``(x, y)`` numpy batches for one epoch.

        Batches never cross shard boundaries (the streaming property: one
        shard resident at a time), shard order is the ``(seed, epoch)``
        permutation when ``shuffle`` and the sorted order otherwise, and
        ``drop_last`` drops each shard's ragged tail batch so the jitted
        step sees ONE batch shape per run (the compile-bucket discipline).

        ``ledger``: a :class:`~disco_tpu.runs.RunLedger` (or path) arms
        verified resume — consumed shards are recorded per epoch and
        skipped when their digest still matches on replay.

        ``recent``: sliding-window corpus — consume only the newest this
        many shards (by shard number) this epoch.  A continuous trainer
        over an ever-growing tap directory needs it: without a window each
        epoch re-reads the WHOLE history, so epoch cost grows linearly
        with uptime and training eventually falls behind serving.

        No reference counterpart (module docstring).
        """
        from disco_tpu.runs.ledger import RunLedger

        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        own_ledger = ledger is not None and not isinstance(ledger, RunLedger)
        if own_ledger:
            ledger = RunLedger(ledger)
        try:
            done: set = set()
            if ledger is not None:
                done, _requeued = ledger.verified_done()
            paths = self.shard_paths()
            if recent is not None:
                if int(recent) < 1:
                    raise ValueError(f"recent must be >= 1, got {recent}")
                paths = paths[-int(recent):]
            if shuffle:
                order = np.random.default_rng([self.seed, int(epoch)]).permutation(len(paths))
                paths = [paths[i] for i in order]
            for path in paths:
                unit = unit_shard_epoch(path.name, epoch)
                if unit in done:
                    continue
                windows = self._shard_windows(path, epoch, shuffle=shuffle)
                if windows is None:
                    continue
                if ledger is not None:
                    ledger.mark_in_flight(unit)
                xs, ys = windows
                n = len(xs)
                for start in range(0, n, batch_size):
                    if drop_last and start + batch_size > n:
                        break
                    yield xs[start:start + batch_size], ys[start:start + batch_size]
                if ledger is not None:
                    ledger.mark_done(unit, artifact_paths=[path], n_windows=n)
        finally:
            if own_ledger:
                # a path-opened ledger is this generator's to close — one
                # leaked handle per epoch would EMFILE a long training run
                ledger.close()

    def batch_fn(self, batch_size: int, *, shuffle: bool = True,
                 ledger=None, drop_last: bool = True):
        """A ``fit``-compatible zero-arg callable: each call is one epoch's
        fresh batch iterator, with the epoch counter advancing per call
        (so every epoch reshuffles deterministically — the
        ``train_batches`` contract of :func:`disco_tpu.nn.training.fit`).

        The callable exposes ``set_start_epoch(n)`` — the resume protocol
        ``fit`` drives: on a ``resume_from`` run the dataset epoch counter
        must restart at the TRAINING epoch being resumed, or (a) the
        shuffle order replays the wrong epochs and (b) with a reused
        ``ledger`` the already-consumed ``shard:*:epoch:<e>`` units of the
        pre-crash epochs would make the first resumed epochs yield ZERO
        batches — silently training on nothing.

        No reference counterpart (module docstring).
        """
        from disco_tpu.runs.ledger import RunLedger

        if ledger is not None and not isinstance(ledger, RunLedger):
            # one ledger handle for the whole run, not one per epoch
            ledger = RunLedger(ledger)
        state = {"epoch": 0}

        def make():
            epoch = state["epoch"]
            state["epoch"] += 1
            return self.batches(batch_size, epoch=epoch, shuffle=shuffle,
                                ledger=ledger, drop_last=drop_last)

        def set_start_epoch(epoch: int) -> None:
            state["epoch"] = int(epoch)

        make.set_start_epoch = set_start_epoch
        return make

    def peek_geometry(self) -> dict | None:
        """(n_nodes, n_freq, block_frames) of the first readable shard —
        what ``disco-train --shards`` sizes the model from; None when no
        intact shard exists.

        No reference counterpart (module docstring)."""
        return peek_geometry(self.shard_dir)


def peek_geometry(shard_dir) -> dict | None:
    """Module-level twin of :meth:`ShardDataset.peek_geometry` — callers
    sizing a model BEFORE choosing window parameters (``disco-train
    --shards``) need the geometry without constructing a dataset first.

    No reference counterpart (module docstring)."""
    for path in list_shards(shard_dir):
        try:
            _meta, records = read_shard(path)
        except ShardError:
            continue
        if records:
            Y = np.asarray(records[0]["Y"])
            return {"n_nodes": int(Y.shape[0]),
                    "mics_per_node": int(Y.shape[1]),
                    "n_freq": int(Y.shape[2]),
                    "block_frames": int(Y.shape[3])}
    return None
