"""The co-resident trainer: continuous training inside the serve process.

The flywheel's training half used to be a separate ``disco-train --shards``
invocation — impossible to run next to a live server under the environment
contract (ONE jax process owns the chip; a second python process blocks on
the claim).  :class:`ResidentTrainer` closes that gap by running training
*inside* the serve process as bounded step slices interleaved on the
scheduler's existing dispatch thread: every scheduler tick, after serving
work is dispatched, the trainer advances at most ``steps_per_tick`` train
steps.  No new thread touches jax — the single-chip-claim contract
(``disco-race`` role map) is preserved by construction, and the dispatch
thread stays the only place device work originates.

Three contracts, each drilled by ``make endure-check`` (the sixteenth
gate) and pinned by ``tests/test_resident.py``:

* **Ladder-aware** — when the degradation ladder reports a rung at or
  above ``throttle_rung`` the trainer runs ZERO steps that tick (serve
  overload must never be amplified by training compute): a paused/resumed
  transition is a ``train_throttled`` obs event and every skipped tick
  ticks the ``train_throttled_ticks`` counter, so ``disco-obs slo`` stays
  green while training runs.
* **Crash-restartable** — the epoch loop mirrors
  :func:`disco_tpu.nn.training.fit` incrementally: per-shard consumption
  rides :meth:`~disco_tpu.flywheel.dataset.ShardDataset.batches`'s
  ledger-verified units (``shard:<name>:epoch:<e>``), each finished epoch
  is an atomic checkpoint + ``epoch:<e>`` done record, and each publish is
  its own ``publish:<e>`` unit bracketing the staging call.  A crash at
  ANY seam (``mid_epoch`` after the train pass, ``pre_publish`` after the
  checkpoint but before staging, ``between_generations`` after a
  generation lands) resumes from the ledger with zero re-consumed shard
  units and no torn checkpoint or generation — an interrupted publish is
  re-staged idempotently (same weights → same digest → same generation).
* **Rollout-safe** — publishing goes through the same
  :func:`~disco_tpu.nn.training.publish_checkpoint` refusal seam as
  ``fit``; an epoch that saw zero batches never publishes (the weights
  did not change), and a re-staged unchanged checkpoint is deduped by
  digest so a demoted candidate is never republished unchanged.

The trainer itself never opens sockets, spawns threads or takes locks:
``step`` is only ever called from the dispatch thread (or from the main
thread in a standalone/gate harness), and ``close`` from the server's
shutdown path signals through a plain flag — the same flag-only
cross-thread discipline as ``runs.interrupt``.

Module import stays jax-free (disco-lint DL005): jax and the training
stack load lazily on the first real step.

No reference counterpart: the reference trains once, offline, in its own
process (SURVEY.md §2.9); a trainer co-resident with a serving loop is
flywheel-only.
"""
from __future__ import annotations

from pathlib import Path

import numpy as np

from disco_tpu.flywheel.dataset import ShardDataset, peek_geometry
from disco_tpu.obs import events as obs_events
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.runs import chaos
from disco_tpu.runs.ledger import RunLedger, unit_epoch

#: Checkpoint file name under ``train_dir`` (one rolling atomic file — the
#: resume source of truth together with the ledger).
CKPT_NAME = "resident_model.msgpack"

#: Ledger file name under ``train_dir``.
LEDGER_NAME = "ledger.jsonl"

_EXHAUSTED = object()


def unit_publish(epoch) -> str:
    """Ledger work-unit id of one epoch's generation publish — bracketing
    the staging call so a crash between checkpoint and store is resumable
    (an ``in_flight`` publish unit is re-staged on restart, idempotently).

    No reference counterpart (module docstring)."""
    return f"publish:{int(epoch)}"


class ResidentTrainer:
    """Incremental co-resident trainer over a flywheel shard directory.

    Args:
      shard_dir: the CorpusTap output directory to train from (shards are
        re-listed every epoch, so freshly tapped traffic joins the next
        epoch automatically).
      train_dir: working directory for the trainer's ledger and rolling
        checkpoint (created on demand).
      promote_dir: generation store root to publish into (None = train
        without publishing).
      arch: ``build_crnn`` kwargs (doubles as the generation-store arch
        record, the ``disco-train --shards`` convention); None = sized
        from the shards' geometry on first step.
      batch_size / win_len / seed: dataset + init knobs
        (:class:`~disco_tpu.flywheel.dataset.ShardDataset`).
      steps_per_tick: train-step budget per :meth:`step` call — the
        interleaving grain against serve dispatch.
      publish_every: publish cadence in epochs (1 = every eligible epoch).
      publish: ``'improved'`` (best-so-far train loss, the ``fit`` gate)
        or ``'always'`` (every cadence epoch — what the endurance gate
        uses to produce a deterministic generation stream).
      throttle_rung: ladder rung at/above which a tick trains zero steps.
      max_epochs: stop training after this many completed epochs
        (None = run as long as the server does).
      recent_shards: sliding-window corpus — each epoch consumes only the
        newest this many shards (None = the whole directory).  A resident
        trainer over a live tap NEEDS a window: the directory grows for as
        long as the server serves, so an unwindowed epoch re-reads the
        entire history and training falls ever further behind serving.
      precision: training compute lane (``'f32'``/``'bf16'``).
      dataset: an alternative training feed replacing the
        :class:`~disco_tpu.flywheel.dataset.ShardDataset` over
        ``shard_dir`` — anything with the same ``batches`` /
        ``peek_geometry`` surface (the scenario factory's
        :class:`~disco_tpu.scenes.stream.SceneStream` is the intended
        plug: training never starves on thin serve traffic because its
        corpus is simulated on demand).

    No reference counterpart (module docstring).
    """

    def __init__(self, shard_dir, train_dir, *, promote_dir=None,
                 arch: dict | None = None, batch_size: int = 8,
                 win_len: int | None = None, seed: int = 0,
                 steps_per_tick: int = 4, publish_every: int = 1,
                 publish: str = "improved", throttle_rung: int = 1,
                 max_epochs: int | None = None,
                 recent_shards: int | None = None, precision: str = "f32",
                 dataset=None):
        if steps_per_tick < 1:
            raise ValueError(f"steps_per_tick must be >= 1, got {steps_per_tick}")
        if recent_shards is not None and int(recent_shards) < 1:
            raise ValueError(f"recent_shards must be >= 1, got {recent_shards}")
        if publish_every < 1:
            raise ValueError(f"publish_every must be >= 1, got {publish_every}")
        if publish not in ("improved", "always"):
            raise ValueError(f"publish must be 'improved' or 'always', got {publish!r}")
        if throttle_rung < 0:
            raise ValueError(f"throttle_rung must be >= 0, got {throttle_rung}")
        self.shard_dir = Path(shard_dir)
        self.train_dir = Path(train_dir)
        self.promote_dir = Path(promote_dir) if promote_dir is not None else None
        self.batch_size = int(batch_size)
        self.steps_per_tick = int(steps_per_tick)
        self.publish_every = int(publish_every)
        self.publish = publish
        self.throttle_rung = int(throttle_rung)
        self.max_epochs = max_epochs
        self.recent_shards = None if recent_shards is None else int(recent_shards)
        self.precision = precision
        self.seed = int(seed)
        self._arch = dict(arch) if arch is not None else None
        self._win_len = int(win_len) if win_len is not None else None
        self._feed = dataset       # None = ShardDataset over shard_dir
        self._ready = False
        self._closed = False       # flag-only close signal (server shutdown)
        self._failed = None        # first training Exception — trainer parks
        self._throttled = False
        self._waiting_for_shards = False
        self._ledger: RunLedger | None = None
        self._dataset: ShardDataset | None = None
        self._model = None
        self._state = None
        self._train_step = None
        self._iter = None          # current epoch's batch generator
        self._epoch = 0
        self._epoch_in_flight = False   # epoch:<e> marked (lazily, on batch 1)
        self._resumed_in_flight = False  # epoch resumed from an in_flight unit
        self._tr = None            # device-resident running loss sum
        self._nb = 0               # steps this epoch
        self._steps_total = 0
        self._epochs_done = 0
        self._published = 0
        self._pending_publish: int | None = None  # replayed in_flight publish
        self._last_published_gen: str | None = None
        self._train_losses: list = []
        self._gate = None

    # -- the per-tick slice --------------------------------------------------
    def step(self, *, tick_no: int = 0, rung: int = 0) -> int:
        """Advance training by at most ``steps_per_tick`` train steps;
        returns the number of steps actually run.  The ONLY entry point
        that touches jax — call it from the dispatch thread (or the main
        thread in a standalone harness), never from both.

        ``rung``: the degradation ladder's current rung — at or above
        ``throttle_rung`` this tick trains nothing (the ladder-aware
        contract; serve SLOs outrank training progress).

        A :class:`~disco_tpu.runs.chaos.ChaosCrash` from the trainer's
        seams propagates (a simulated process death must kill the server's
        dispatch loop exactly like a serve-side crash); any ordinary
        ``Exception`` parks the trainer permanently with a ``fault`` obs
        event instead — a training bug must never take serving down.

        No reference counterpart (module docstring)."""
        if self._closed or self._failed is not None:
            return 0
        if rung >= self.throttle_rung:
            obs_registry.counter("train_throttled_ticks").inc()
            if not self._throttled:
                self._throttled = True
                obs_events.record("train_throttled", stage="resident",
                                  action="paused", rung=int(rung),
                                  tick=int(tick_no))
            return 0
        if self._throttled:
            self._throttled = False
            obs_events.record("train_throttled", stage="resident",
                              action="resumed", rung=int(rung),
                              tick=int(tick_no))
        try:
            return self._slice()
        except chaos.ChaosCrash:
            raise
        except Exception as e:  # park, loudly — serving must survive
            self._failed = e
            obs_registry.counter("train_errors").inc()
            obs_events.record("fault", stage="resident", fault="train_error",
                              error=f"{type(e).__name__}: {e}")
            return 0

    def _slice(self) -> int:
        if not self._ensure_ready():
            return 0
        if self._pending_publish is not None:
            # crash landed between the checkpoint and the store — finish
            # the interrupted publish before anything else, including the
            # max_epochs early-out (idempotent by digest, so a publish
            # that DID land is a no-op re-stage)
            epoch, self._pending_publish = self._pending_publish, None
            self._do_publish(epoch, resumed=True)
        if self.max_epochs is not None and self._epochs_done >= self.max_epochs:
            return 0
        steps = 0
        while steps < self.steps_per_tick:
            if self._iter is None:
                self._iter = self._dataset.batches(
                    self.batch_size, epoch=self._epoch, shuffle=True,
                    ledger=self._ledger, recent=self.recent_shards)
            batch = next(self._iter, _EXHAUSTED)
            if batch is _EXHAUSTED:
                self._iter = None
                if self._epoch_in_flight or self._resumed_in_flight:
                    # one epoch boundary per tick: checkpoint + publish are
                    # the slice's whole budget
                    self._finish_epoch()
                    return steps
                # nothing consumable yet (no shards, or all already
                # consumed for this epoch) — wait for fresh traffic
                # WITHOUT burning an epoch number or a ledger unit
                if not self._waiting_for_shards:
                    self._waiting_for_shards = True
                    obs_events.record("note", stage="resident",
                                      reason="resident trainer idle: no "
                                             "unconsumed shards for epoch "
                                             f"{self._epoch}")
                return steps
            self._waiting_for_shards = False
            if not self._epoch_in_flight:
                # lazy in_flight mark: an epoch only exists once it has a
                # batch (an idle server must not grow the ledger)
                self._ledger.mark_in_flight(unit_epoch(self._epoch))
                self._epoch_in_flight = True
            import jax.numpy as jnp

            x, y = batch
            self._state, loss = self._train_step(
                self._state, jnp.asarray(x), jnp.asarray(y))
            self._tr = self._tr + loss
            self._nb += 1
            self._steps_total += 1
            steps += 1
        return steps

    # -- lazy init + ledger resume -------------------------------------------
    def _ensure_ready(self) -> bool:
        """First-step initialization: size the model, build step fns,
        restore the checkpoint, replay the ledger.  Returns False (and
        stays cheap to re-call) while no intact shard exists to size the
        model from."""
        if self._ready:
            return True
        if self._arch is None:
            geom = (self._feed.peek_geometry() if self._feed is not None
                    else peek_geometry(self.shard_dir))
            if geom is None:
                if not self._waiting_for_shards:
                    self._waiting_for_shards = True
                    obs_events.record("note", stage="resident",
                                      reason="resident trainer idle: no "
                                             "intact shards to size the "
                                             "model from")
                return False
            from disco_tpu.config import TrainConfig

            # an injected feed windows at ITS OWN win_len — the model must
            # match the windows it will actually be fed, not the feed's
            # full block length
            feed_win = getattr(self._feed, "win_len", None)
            win_len = self._win_len or feed_win or geom["block_frames"]
            self._arch = dict(n_ch=1, win_len=win_len,
                              n_freq=geom["n_freq"],
                              learning_rate=TrainConfig().lr,
                              ff_units=(geom["n_freq"],))
        self._waiting_for_shards = False
        win_len = self._win_len or int(self._arch["win_len"])
        # The feed seam: an injected dataset (e.g. scenes.SceneStream)
        # replaces the tapped-shard reader wholesale — same batches()
        # contract, so the epoch/ledger machinery below is untouched.
        self._dataset = self._feed if self._feed is not None else ShardDataset(
            self.shard_dir, win_len=win_len, seed=self.seed)
        self.train_dir.mkdir(parents=True, exist_ok=True)
        self._ledger = RunLedger(self.train_dir / LEDGER_NAME)

        import jax.numpy as jnp

        from disco_tpu.nn.crnn import build_crnn
        from disco_tpu.nn.training import (
            SaveAndStop,
            create_train_state,
            load_checkpoint,
            make_step_fns,
        )

        self._model, tx = build_crnn(**self._arch)
        sample = jnp.zeros(
            (1, int(self._arch.get("n_ch", 1)), win_len,
             int(self._arch["n_freq"])), jnp.float32)
        self._state = create_train_state(self._model, tx, sample,
                                         seed=self.seed)
        self._train_step, _ = make_step_fns(self._model,
                                            precision=self.precision)
        self._gate = SaveAndStop(patience=np.inf, mode="min")

        latest = self._ledger.replay()
        done_epochs, inflight_epochs = set(), set()
        for unit, rec in latest.items():
            if unit.startswith("epoch:"):
                e = int(unit.split(":", 1)[1])
                if rec["state"] == "done":
                    done_epochs.add(e)
                elif rec["state"] == "in_flight":
                    inflight_epochs.add(e)
            elif unit.startswith("publish:"):
                e = int(unit.split(":", 1)[1])
                if rec["state"] == "in_flight":
                    self._pending_publish = e
                elif rec["state"] == "done":
                    gen = (rec.get("attrs") or {}).get("gen")
                    if gen and not (rec.get("attrs") or {}).get("deduped"):
                        self._published += 1
                        self._last_published_gen = gen
        self._epoch = max(done_epochs | inflight_epochs) + 1 if done_epochs | inflight_epochs else 0
        if inflight_epochs and max(inflight_epochs) not in done_epochs:
            # crash mid-epoch: re-enter the interrupted epoch — its
            # already-done shard units verify and are skipped, so only the
            # remainder (possibly nothing) is consumed, never a duplicate
            self._epoch = max(inflight_epochs)
            self._resumed_in_flight = True
        self._epochs_done = len(done_epochs)

        ckpt = self.ckpt_path
        if ckpt.is_file():
            self._state, train_hist, _val = load_checkpoint(ckpt, self._state)
            self._train_losses = [float(v) for v in train_hist]
            for v in self._train_losses:
                self._gate.save_model_query(v)  # re-prime best-so-far
        self._tr, self._nb = jnp.zeros(()), 0
        if self._epoch or self._pending_publish is not None:
            obs_events.record(
                "run_resume", stage="resident", epoch=int(self._epoch),
                epochs_done=int(self._epochs_done),
                mid_epoch=bool(self._resumed_in_flight),
                pending_publish=self._pending_publish)
        self._ready = True
        return True

    # -- epoch boundary -------------------------------------------------------
    def _finish_epoch(self) -> None:
        import jax.numpy as jnp

        from disco_tpu.io.atomic import file_digest
        from disco_tpu.nn.training import save_checkpoint

        epoch, nb = self._epoch, self._nb
        # mid_epoch chaos seam (the fit() seam, interleaved): train pass
        # complete, nothing persisted — resume must redo NOTHING (shard
        # units are durable) and duplicate nothing
        chaos.tick("mid_epoch", epoch=int(epoch))
        train_loss = float(self._tr) / nb if nb else 0.0
        if nb == 0:
            obs_registry.counter("train_empty_epochs").inc()
            obs_events.record(
                "warning", stage="resident", epoch=int(epoch),
                reason="resident epoch closed with ZERO training batches "
                       "(mid-epoch resume with every shard already "
                       "consumed, or shards drained mid-epoch)")
        while len(self._train_losses) <= epoch:
            self._train_losses.append(0.0)
        self._train_losses[epoch] = train_loss
        improved = self._gate.save_model_query(train_loss) if nb else False
        losses = np.asarray(self._train_losses)
        save_checkpoint(self.ckpt_path, self._state, losses, losses,
                        epochs_done=int(epoch) + 1)
        obs_registry.counter("train_steps").inc(nb)
        obs_registry.gauge("train_loss").set(train_loss)
        obs_events.record("epoch", stage="resident", epoch=int(epoch),
                          train_loss=train_loss, steps=int(nb),
                          improved=bool(improved))
        # state-only epoch record (the fit() convention: the rolling
        # checkpoint is shared mutable state later epochs overwrite, so it
        # rides as informational attrs, never as a voiding artifact digest)
        self._ledger.record(
            unit_epoch(epoch), "done", train_loss=train_loss, steps=int(nb),
            improved=bool(improved), ckpt=str(self.ckpt_path),
            ckpt_digest=file_digest(self.ckpt_path))
        self._epochs_done += 1
        self._epoch = epoch + 1
        self._epoch_in_flight = False
        self._resumed_in_flight = False
        self._tr, self._nb = jnp.zeros(()), 0
        if self._publish_due(epoch, improved, nb):
            self._ledger.mark_in_flight(unit_publish(epoch))
            self._do_publish(epoch)

    def _publish_due(self, epoch: int, improved: bool, nb: int) -> bool:
        if self.promote_dir is None or nb == 0:
            return False  # zero-batch epochs changed nothing — never stage
        if (epoch + 1) % self.publish_every:
            return False
        return True if self.publish == "always" else improved

    def _do_publish(self, epoch: int, resumed: bool = False) -> None:
        """Stage the rolling checkpoint as a generation, bracketed by the
        ``publish:<epoch>`` ledger unit and the ``pre_publish`` /
        ``between_generations`` chaos seams."""
        from disco_tpu.nn.training import publish_checkpoint
        from disco_tpu.promote.store import PublishRefused

        # pre_publish chaos seam: the checkpoint and its epoch record are
        # durable, the generation is not — the restart re-stages it
        chaos.tick("pre_publish", epoch=int(epoch))
        try:
            gen = publish_checkpoint(
                self.promote_dir, self.ckpt_path, arch=self._arch,
                ledger=self._ledger, source=f"resident:epoch:{int(epoch)}")
        except PublishRefused as e:
            self._ledger.mark_failed(unit_publish(epoch), error=str(e))
            obs_events.record("generation", stage="resident",
                              action="refused", epoch=int(epoch),
                              unit=e.unit, reason=str(e))
            return
        deduped = gen.gen_id == self._last_published_gen
        # state-only done record (artifacts=None): the generation file is
        # owned by the store and may legitimately be GC'd later
        # (GenerationStore.collect) — digesting it here would void the
        # publish record on the next verified replay
        self._ledger.record(
            unit_publish(epoch), "done", gen=gen.gen_id,
            serial=int(gen.serial), deduped=deduped, resumed=resumed)
        if not deduped:
            self._published += 1
            self._last_published_gen = gen.gen_id
            obs_registry.counter("generations_published").inc()
            obs_events.record("generation", stage="resident",
                              action="published", gen=gen.gen_id,
                              serial=int(gen.serial), epoch=int(epoch),
                              resumed=resumed)
        # between_generations chaos seam: the clean boundary — everything
        # durable, nothing in flight
        chaos.tick("between_generations", gen=gen.gen_id, epoch=int(epoch))

    # -- lifecycle -------------------------------------------------------------
    @property
    def ckpt_path(self) -> Path:
        """The rolling atomic checkpoint file under ``train_dir``.

        No reference counterpart (module docstring)."""
        return self.train_dir / CKPT_NAME

    def stats(self) -> dict:
        """Progress snapshot for run summaries and the endurance gate.

        No reference counterpart (module docstring)."""
        return {
            "epochs_done": int(self._epochs_done),
            "steps_total": int(self._steps_total),
            "generations_published": int(self._published),
            "epoch": int(self._epoch),
            "throttled": bool(self._throttled),
            "failed": f"{type(self._failed).__name__}: {self._failed}"
                      if self._failed is not None else None,
        }

    def close(self) -> None:
        """Stop stepping and release the ledger handle.  Safe from any
        thread and idempotent — a plain flag stops the next slice, and
        the ledger's own lock covers the handle close (no trainer lock).

        No reference counterpart (module docstring)."""
        self._closed = True
        if self._ledger is not None:
            self._ledger.close()
