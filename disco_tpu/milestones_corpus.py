"""Self-generated-corpus milestone (VERDICT round-1 item 5): the complete
reference workflow — disco-gen → disco-mix → z-export → CRNN training →
disco-tango — on corpus-shaped data produced by the framework's OWN
generation pipeline, reporting ΔSI-SDR for oracle and trained-CRNN masks.

The build environment carries no LibriSpeech/Freesound material, so the
speech tree is synthesized (amplitude-modulated noise in the LibriSpeech
directory layout — the same stand-in the test suite uses); everything
downstream of it is the real pipeline: ISM room simulation, SNR-gated
mixing, per-RIR idempotent file layout, list building, training, and the
enhancement driver with its full metric set.  This replaces the round-1
practice of benchmarking milestones 2-4 on ad-hoc `_scene` arrays
(VERDICT weak #8).
"""
from __future__ import annotations

import numpy as np

FS = 16000


def _speechlike(rng, n: int, fs: int = FS, f0_base: float = 140.0) -> np.ndarray:
    """Harmonic speech-like signal: pitched harmonic source with a wandering
    f0, two random formant resonances per 'syllable', a small aspiration
    noise floor, and a pause-structured envelope.

    Round-3 finding: the earlier stand-in (amplitude-modulated WHITE noise)
    is spectrally identical to the SSN noise it is mixed against, so the
    IRM is nearly unpredictable from mixture spectra and a mask CRNN
    trained on such a corpus collapses to the mean mask (held-out deltas
    go negative — see exp/convergence_result_flatspec.json).  Harmonic
    structure is what makes the reference's mask-learning task work, so
    the stand-in must have it too."""
    from scipy.signal import lfilter

    t = np.arange(n) / fs
    # wandering pitch: slow vibrato + per-utterance drift
    f0 = f0_base * (1.0 + 0.15 * np.sin(2 * np.pi * 2.7 * t + rng.uniform(0, 7)))
    phase = 2 * np.pi * np.cumsum(f0) / fs
    src = np.zeros(n)
    for h in range(1, 13):  # sawtooth-ish rolloff
        src += np.sin(h * phase + rng.uniform(0, 7)) / h
    # syllabic segments: each gets its own 2 formant resonators
    out = np.zeros(n)
    seg = int(0.22 * fs)
    for a in range(0, n, seg):
        b = min(a + seg, n)
        x = src[a:b] + 0.1 * rng.standard_normal(b - a)  # aspiration floor
        for fmt in rng.uniform([350, 900], [900, 2600]):
            r = 0.97
            th = 2 * np.pi * fmt / fs
            x = lfilter([1.0 - r], [1.0, -2 * r * np.cos(th), r * r], x)
        out[a:b] = x
    env = (np.sin(2 * np.pi * rng.uniform(1.0, 1.6) * t + rng.uniform(0, 7)) > -0.3).astype(np.float64)
    out = env * out
    peak = np.max(np.abs(out))
    return 0.4 * out / (peak + 1e-9)


def synth_speech_tree(root, n_speakers: int = 3, dur_s: float = 6.0, seed: int = 0):
    """LibriSpeech-shaped tree of synthetic harmonic speech-like signals
    (see :func:`_speechlike`), covering the three splits disco-gen globs."""
    from disco_tpu.io import write_wav

    rng = np.random.default_rng(seed)
    n = int(dur_s * FS)
    for i in range(n_speakers):
        spk = str(19 + 7 * i)
        f0_base = 110.0 * 2 ** rng.uniform(0.0, 0.8)  # per-speaker register
        for split in ("train-clean-100", "train-clean-360", "test-clean"):
            d = root / split / spk / "1"
            d.mkdir(parents=True, exist_ok=True)
            write_wav(d / f"{spk}-1-0001.wav", _speechlike(rng, n, f0_base=f0_base), FS)
    return root


def _delta_from_results(res: dict) -> dict:
    """Mean output-minus-input deltas over nodes, both BSS metric families."""
    return {
        "delta_sdr_512tap": float(np.mean(res["sdr_cnv"] - res["sdr_in_cnv"])),
        "delta_si_sdr": float(np.mean(res["si_sdr_cnv"] - res["si_sdr_in_cnv"])),
        "delta_stoi": float(np.mean(res["delta_stoi_cnv"])),
    }


def corpus_milestone(
    workdir,
    n_rirs: int = 4,
    n_epochs: int = 8,
    scenario: str = "random",
    noise: str = "ssn",
    max_order: int = 8,
    seed: int = 0,
):
    """Run the full generate→mix→z→train→enhance pipeline under ``workdir``
    and score oracle vs trained-CRNN TANGO on the generated material
    (train-set scoring: the tiny corpus has no held-out split).

    Returns a dict with ``tango_4node_oracle`` and ``tango_4node_crnn``
    entries (mean over nodes and RIRs of output-minus-input SDR / SI-SDR /
    STOI deltas) — the config-3/4 numbers produced from real pipeline data.
    """
    from pathlib import Path

    from disco_tpu.cli import gen_disco, get_z, mix, tango, train
    from disco_tpu.enhance.driver import aggregate_results

    workdir = Path(workdir)
    speech = synth_speech_tree(workdir / "libri", seed=seed)
    data = workdir / "dataset"

    gen_disco.main([
        "--dset", "train", "--scenario", scenario, "--rirs", "1", str(n_rirs),
        "--dir_out", str(data), "--librispeech", str(speech),
        "--max_order", str(max_order), "--seed", str(30 + seed),
    ])
    mix.main([
        "--rirs", "1", str(n_rirs), "--scenario", scenario, "--noise", noise,
        "--dir", str(data), "--snr", "0", "6",
    ])
    for rir in range(1, n_rirs + 1):
        get_z.main([
            "--rir", str(rir), "--scenario", scenario, "--noise", noise,
            "--dataset", str(data), "--sav_dir", "oracle",
        ])

    models_dir = workdir / "models"
    # train.py's n_files is EXCLUSIVE (reference convention: 11001 for
    # 11000 rirs), so n_rirs + 1 trains on every generated RIR
    mc_name = train.main([
        "--scene", scenario, "--noise", noise, "--n_files", str(n_rirs + 1),
        "--path_data", str(data), "--save_path", str(models_dir),
        "--n_epochs", str(n_epochs), "--batch_size", "32", "--zsigs", "zs_hat",
    ])
    sc_name = train.main([
        "--scene", scenario, "--noise", noise, "--n_files", str(n_rirs + 1),
        "--path_data", str(data), "--save_path", str(models_dir),
        "--n_epochs", str(n_epochs), "--batch_size", "32", "--single_channel",
    ])

    out_oracle = workdir / "results_oracle"
    out_crnn = workdir / "results_crnn"
    for rir in range(1, n_rirs + 1):
        tango.main([
            "--rir", str(rir), "--scenario", scenario, "--noise", noise,
            "--dataset", str(data), "--out_root", str(out_oracle), "--sav_dir", "o",
        ])
        tango.main([
            "--rir", str(rir), "--scenario", scenario, "--noise", noise,
            "--dataset", str(data), "--out_root", str(out_crnn), "--sav_dir", "c",
            "--mods", str(models_dir / f"{sc_name}_model.msgpack"),
            str(models_dir / f"{mc_name}_model.msgpack"),
        ])

    agg_oracle = aggregate_results(out_oracle / "OIM", kind="tango", noise=noise)
    agg_crnn = aggregate_results(out_crnn / "OIM", kind="tango", noise=noise)
    return {
        "config": "corpus_pipeline",
        "rirs": n_rirs,
        "epochs": n_epochs,
        "tango_4node_oracle": _delta_from_results(agg_oracle),
        "tango_4node_crnn": _delta_from_results(agg_crnn),
    }


def meetit_corpus_milestone(
    workdir,
    n_rirs: int = 2,
    n_src: int = 2,
    max_order: int = 8,
    seed: int = 0,
):
    """MEETIT on real pipeline data: generate meeting-room mixtures with the
    disco-gen-meetit CLI, then run mask-driven separation on the SAVED
    artifacts (mix STFTs + per-source IRMs — the corpus→separation bridge of
    the ICASSP 2021 use case) and score each source AT ITS OWN NODE against
    the saved clean convolved images (the reference's evaluation semantics).

    Returns the config-4 numbers from generated corpus material: headline
    ΔSI-SIR (interference rejection — the own-node mixture is already
    source-dominated, so SIR is where separation shows) plus ΔSI-SDR,
    each estimate-minus-mixture-baseline, averaged over sources and RIRs.
    """
    from pathlib import Path

    from disco_tpu.cli import gen_meetit
    from disco_tpu.core.dsp import istft
    from disco_tpu.core.metrics import si_bss, si_sdr
    from disco_tpu.datagen.meetit import load_meetit_sample, node_channel_bounds
    from disco_tpu.enhance import separate_with_masks
    from disco_tpu.io import DatasetLayout, read_wav

    workdir = Path(workdir)
    speech = synth_speech_tree(workdir / "libri", n_speakers=3 * n_src, seed=seed)
    data = workdir / "meetit"

    gen_meetit.main([
        "--dset", "test", "--rirs", "1", str(n_rirs), "--n_src", str(n_src),
        "--dir_out", str(data), "--librispeech", str(speech),
        "--max_order", str(max_order), "--duration", "2", "3",
        "--seed", str(30 + seed),
    ])

    layout = DatasetLayout(str(data), "meetit", "test")
    mics_per_node = [4] * n_src
    bounds = node_channel_bounds(mics_per_node)
    deltas = []
    for rir in range(1, n_rirs + 1):
        Y, masks = load_meetit_sample(layout, rir, mics_per_node)
        est = np.asarray(separate_with_masks(Y, masks, policy="distant"))
        # Source s scored at ITS OWN node s — the reference's evaluation
        # semantics (each source directly faces one node; per-source SIR is
        # computed at that node, gen_meetit/convolve_signals.py:140-148).
        # The mixture there is already source-dominated, so the headline
        # number is INTERFERENCE REJECTION (ΔSI-SIR via the saved clean
        # images); ΔSI-SDR is reported alongside.
        for s in range(n_src):
            ref_ch = int(bounds[s]) + 1
            imgs = np.stack([
                np.asarray(
                    read_wav(layout.base / "wav" / "clean" / "cnv" / f"{rir}_S-{j + 1}_Ch-{ref_ch}.wav")[0],
                    np.float64,
                )
                for j in range(n_src)
            ], axis=1)  # (n_samples, n_src) targets for si_bss
            T_samples = imgs.shape[0]
            ref = imgs[:, s]
            est_t = np.asarray(istft(est[s, s], length=T_samples), np.float64)
            mix_t = np.asarray(istft(Y[s, 0], length=T_samples), np.float64)
            _, sir_out, _ = si_bss(est_t, imgs, s)
            _, sir_in, _ = si_bss(mix_t, imgs, s)
            deltas.append({
                "si_sdr": float(si_sdr(ref, est_t) - si_sdr(ref, mix_t)),
                "si_sir": float(sir_out - sir_in),
            })
    sdrs = [d["si_sdr"] for d in deltas]
    sirs = [d["si_sir"] for d in deltas]
    return {
        "config": "meetit_corpus_separation",
        "rirs": n_rirs,
        "n_src": n_src,
        "delta_si_sir_mean": float(np.mean(sirs)),
        "delta_si_sir_min": float(np.min(sirs)),
        "delta_si_sdr_mean": float(np.mean(sdrs)),
        "delta_si_sdr_min": float(np.min(sdrs)),
        "pairs_scored": len(deltas),
    }


def main(argv=None):
    """``disco-milestones-corpus`` console entry point."""
    import argparse
    import json
    import tempfile

    p = argparse.ArgumentParser(description="generate→mix→train→enhance corpus milestone")
    p.add_argument("--workdir", default=None, help="working directory (default: temp)")
    p.add_argument("--rirs", type=int, default=4)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--scenario", default="random")
    p.add_argument("--noise", default="ssn")
    p.add_argument("--meetit", action="store_true",
                   help="also run the MEETIT separation milestone on generated corpus material")
    args = p.parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="disco_corpus_milestone_")
    out = corpus_milestone(workdir, n_rirs=args.rirs, n_epochs=args.epochs,
                           scenario=args.scenario, noise=args.noise)
    print(json.dumps(out))
    if args.meetit:
        out_m = meetit_corpus_milestone(workdir, n_rirs=args.rirs)
        print(json.dumps(out_m))
        out = {"disco": out, "meetit": out_m}
    return out


if __name__ == "__main__":
    main()
