"""Array / dict / string / YAML helpers.

Capability parity with the reference's ``disco_theque/misc_utils.py``
(/root/reference/disco_theque/misc_utils.py:7-160): node<->channel mapping
for heterogeneous array geometries, dict-of-arrays concatenation, selector
matrices used by the beamformer glue, zero-trimming, histogram/CI plotting
helpers, run naming and the space-separated-ints YAML convention.

Everything is host-side numpy (these are corpus/plot/config helpers, not
device code).
"""
from __future__ import annotations

import secrets
import string

import numpy as np
import yaml


def get_node_from_channel(ch: int, arr_geo) -> int:
    """Node index owning flat channel ``ch`` for a mics-per-node geometry
    (misc_utils.py:7-16).  E.g. geometry [4, 4, 4, 4], ch 5 -> node 1."""
    mics_cum = np.cumsum(arr_geo)
    return int(np.argmax(ch < mics_cum))


def channel_range_of_node(node: int, arr_geo) -> tuple[int, int]:
    """Half-open flat-channel range [start, stop) of ``node`` — the inverse
    mapping of :func:`get_node_from_channel`."""
    cum = np.concatenate(([0], np.cumsum(arr_geo)))
    return int(cum[node]), int(cum[node + 1])


def find_unmatched_dim(arr1, arr2):
    """Indices of axes where the two (equal-ndim) arrays' shapes differ
    (misc_utils.py:19-27)."""
    return (np.array(arr1.shape) - np.array(arr2.shape) != 0).nonzero()


def concatenate_dicts(dict_list):
    """Concatenate same-keyed dicts of arrays; each key is concatenated along
    its first mismatching axis, or axis 0 when shapes fully match
    (misc_utils.py:30-46)."""
    out = dict_list[0].copy()
    for other in dict_list[1:]:
        for k in out:
            mism = np.array(find_unmatched_dim(out[k], other[k]))
            axis = int(mism[0][0]) if mism.size else 0
            out[k] = np.concatenate((out[k], other[k]), axis=axis)
    return out


def repeat_matrix(a, nb_repeats: int):
    """Stack a 2-D matrix with itself ``nb_repeats`` times along a new third
    axis (misc_utils.py:49-57; Fortran-order reshape semantics)."""
    return np.tile(a, (1, nb_repeats)).reshape((a.shape[0], a.shape[1], -1), order="F")


def truncated_eye(N: int, j: int, k: int = 0):
    """N x N matrix with ``j`` consecutive ones on diagonal ``k``
    (misc_utils.py:60-72) — the channel-selector used by the beamformer glue."""
    return np.diag(np.concatenate((np.ones(j), np.zeros(N - j))), k=k)


def trim_2d_array(mat, axis: int = 0, trim: str = "fb"):
    """Drop all-zero leading ('f') / trailing ('b') slices of a 2-D array
    along the *other* axis (misc_utils.py:75-100)."""
    assert trim in ("f", "b", "fb"), "`trim` can only be 'f', 'b' or 'fb'."
    nonzero = ~(mat == 0).all(axis=axis)
    start = int(np.argmax(nonzero)) if "f" in trim else 0
    stop = len(nonzero) - int(np.argmax(nonzero[::-1])) if "b" in trim else mat.shape[1 - axis]
    return mat[start:stop, :] if axis else mat[:, start:stop]


def bar_data(x_edges, x, y):
    """Bin ``y`` by ``x`` against bin upper edges; per-bin nan-mean and 95% CI
    for bar plots (misc_utils.py:103-115)."""
    from disco_tpu.core.metrics import ci_wp

    bins = [[] for _ in range(len(x_edges))]
    for xi, yi in zip(x, y):
        bins[int(np.argmax(~(xi > np.asarray(x_edges))))].append(yi)
    means = np.array([np.nanmean(b) if b else np.nan for b in bins])
    cis = np.array([ci_wp(np.asarray(b)) if b else np.nan for b in bins])
    return means, cis


def get_random_string(length: int) -> str:
    """Random [A-Za-z0-9] run-name string (misc_utils.py:118-128)."""
    chars = string.ascii_letters + string.digits
    return "".join(secrets.choice(chars) for _ in range(length))


def integerize(values):
    """The reference's YAML convention (misc_utils.py:144-160): strings of
    space-separated ints become int arrays, 'None' becomes None, other spaced
    strings split into lists; applied recursively to dicts."""
    if isinstance(values, dict):
        return {k: integerize(v) for k, v in values.items()}
    if isinstance(values, str):
        try:
            return np.array(values.split(" "), dtype=int)
        except ValueError:
            if values == "None":
                return None
            if " " in values:
                return values.split(" ")
    return values


def yaml2dict(yaml_file):
    """Load a YAML file and :func:`integerize` every value
    (misc_utils.py:131-141)."""
    with open(yaml_file) as fh:
        params = yaml.safe_load(fh)
    return {k: integerize(v) for k, v in params.items()}
