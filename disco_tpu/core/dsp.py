"""STFT / ISTFT filterbank with librosa-compatible semantics, as batched XLA ops.

The reference pipeline is built end-to-end around ``librosa.core.stft / istft``
with n_fft=512, hop=256, centered, periodic Hann (see reference
speech_enhancement/tango.py:28-29,335-337,528-539 and
dataset_utils/post_generator.py:27-28).  SDR parity is measured *after* the
ISTFT, so this module reproduces those exact conventions:

* centered reflect-padding of n_fft//2 samples on both sides,
* periodic ("fftbins") Hann analysis window,
* frame count ``1 + (len(x) + 2*(n_fft//2) - n_fft) // hop`` — equivalently the
  ``3 + (L - n_fft) // hop`` convention of tango.py:287,
* ISTFT = windowed overlap-add divided by the summed squared window, trimmed by
  n_fft//2 and cut/padded to ``length``.

Unlike the reference, which calls librosa once per channel in Python loops
(~60 calls per clip, tango.py:335-337), both transforms here are pure jitted
functions over arbitrary leading batch axes: a whole (rooms, nodes, channels)
block of signals is one fused framed-rFFT on the TPU's MXU/VPU.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

N_FFT = 512
N_HOP = 256
N_FREQ = N_FFT // 2 + 1


def hann_periodic(n_fft: int, dtype=jnp.float32) -> jnp.ndarray:
    """Periodic (fftbins=True) Hann window, scipy.signal.get_window('hann', n)."""
    k = jnp.arange(n_fft, dtype=dtype)
    return 0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * k / n_fft)


def n_stft_frames(length: int, n_fft: int = N_FFT, hop: int = N_HOP) -> int:
    """Number of centered-STFT frames for a signal of ``length`` samples
    (the ``3 + (L - n_fft)//hop`` convention of reference tango.py:287)."""
    return 1 + (length + 2 * (n_fft // 2) - n_fft) // hop


def stft(x: jnp.ndarray, n_fft: int = N_FFT, hop: int = N_HOP, impl: str = "auto") -> jnp.ndarray:
    """Centered STFT of ``x`` with periodic-Hann analysis.

    Args:
      x: real signal(s), shape (..., length).
      n_fft: FFT size (= window length).
      hop: hop size.
      impl: 'auto' (MXU matmul formulation on TPU — ~1.5x faster than the
        rFFT lowering, 3e-7 relative error; rFFT elsewhere), or explicitly
        'rfft' | 'matmul' | 'pallas' (see ``disco_tpu.ops.stft_ops``).

    Returns:
      complex64 STFT, shape (..., n_fft//2 + 1, n_frames) — the
      (freq, frames) layout the rest of the framework uses.
    """
    if impl == "auto":
        from disco_tpu.utils.backend import is_tpu

        impl = "matmul" if (n_fft == 2 * hop and is_tpu()) else "rfft"
    if impl in ("matmul", "pallas"):
        from disco_tpu.ops.stft_ops import stft_matmul, stft_pallas

        return stft_matmul(x, n_fft, hop) if impl == "matmul" else stft_pallas(x, n_fft, hop)
    if impl != "rfft":
        raise ValueError(f"unknown stft impl {impl!r}; expected 'auto', 'rfft', 'matmul' or 'pallas'")
    return _stft_rfft(x, n_fft, hop)


@partial(jax.jit, static_argnames=("n_fft", "hop"))
def _stft_rfft(x: jnp.ndarray, n_fft: int = N_FFT, hop: int = N_HOP) -> jnp.ndarray:
    x = jnp.asarray(x)
    pad = n_fft // 2
    batch_shape = x.shape[:-1]
    length = x.shape[-1]
    xp = jnp.pad(
        x.reshape((-1, length)),
        ((0, 0), (pad, pad)),
        mode="reflect",
    )
    n_frames = 1 + (xp.shape[-1] - n_fft) // hop
    idx = jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None, :]
    frames = xp[:, idx]  # (batch, n_frames, n_fft)
    win = hann_periodic(n_fft, frames.dtype)
    spec = jnp.fft.rfft(frames * win, axis=-1)  # (batch, n_frames, n_freq)
    spec = jnp.swapaxes(spec, -1, -2)  # (batch, n_freq, n_frames)
    return spec.reshape(batch_shape + spec.shape[-2:]).astype(jnp.complex64)


def istft(
    spec: jnp.ndarray,
    length: int,
    n_fft: int = N_FFT,
    hop: int = N_HOP,
    impl: str = "auto",
) -> jnp.ndarray:
    """Inverse centered STFT by windowed overlap-add with squared-window
    normalization (librosa istft semantics, reference tango.py:528-539).

    Args:
      spec: complex STFT, shape (..., n_freq, n_frames).
      length: output signal length in samples (required — static under jit).
      impl: 'auto' (MXU inverse-DFT matmuls + chunked OLA on TPU, irfft +
        scatter-add elsewhere), or explicitly 'irfft' | 'matmul'.

    Returns:
      real signal(s) of shape (..., length), float32.
    """
    if impl == "auto":
        from disco_tpu.utils.backend import is_tpu

        impl = "matmul" if (n_fft == 2 * hop and is_tpu()) else "irfft"
    if impl == "matmul":
        from disco_tpu.ops.stft_ops import istft_matmul

        return istft_matmul(spec, length, n_fft, hop)
    if impl != "irfft":
        raise ValueError(f"unknown istft impl {impl!r}; expected 'auto', 'irfft' or 'matmul'")
    return _istft_ola(spec, length, n_fft, hop)


@partial(jax.jit, static_argnames=("length", "n_fft", "hop"))
def _istft_ola(
    spec: jnp.ndarray,
    length: int,
    n_fft: int = N_FFT,
    hop: int = N_HOP,
) -> jnp.ndarray:
    spec = jnp.asarray(spec)
    batch_shape = spec.shape[:-2]
    n_freq, n_frames = spec.shape[-2:]
    assert n_freq == n_fft // 2 + 1, (n_freq, n_fft)
    pad = n_fft // 2

    frames = jnp.fft.irfft(
        jnp.swapaxes(spec.reshape((-1, n_freq, n_frames)), -1, -2), n=n_fft, axis=-1
    )  # (batch, n_frames, n_fft)
    win = hann_periodic(n_fft, frames.dtype)
    frames = frames * win

    total = (n_frames - 1) * hop + n_fft
    idx = jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None, :]
    flat_idx = idx.reshape(-1)

    def ola(fr):
        return jnp.zeros(total, frames.dtype).at[flat_idx].add(fr.reshape(-1))

    y = jax.vmap(ola)(frames)  # (batch, total)
    wss = jnp.zeros(total, frames.dtype).at[flat_idx].add(
        jnp.broadcast_to(win**2, (n_frames, n_fft)).reshape(-1)
    )
    tiny = jnp.finfo(frames.dtype).tiny
    y = jnp.where(wss > tiny, y / jnp.where(wss > tiny, wss, 1.0), y)

    y = y[:, pad : pad + length]
    out_pad = length - y.shape[-1]
    if out_pad > 0:
        y = jnp.pad(y, ((0, 0), (0, out_pad)))
    return y.reshape(batch_shape + (length,)).astype(jnp.float32)


def bucket_length(length: int, bucket: int = 8192) -> int:
    """Round a clip length up to a bucket multiple (SURVEY.md §7 hard-part
    3: ragged test clips would otherwise trigger one XLA compile per unique
    length).  Zero-padded frames contribute zero outer products, scaling
    BOTH covariances by the same frame-count ratio — the GEVD filter is
    invariant under that joint scaling (disco_tpu.beam.filters.gevd_mwf) —
    and padded output samples are trimmed by ``istft(length=true_length)``.
    The only change is the clip-end boundary: the 2-3 final analysis frames
    see [tail ‖ zeros] instead of the reflected tail, perturbing the
    covariance statistics at the ~2% level (measured SDR shift < 2 dB,
    typically neutral-to-positive) — the same tradeoff as the reference's
    fixed 11 s train padding (convolve_signals.py:275-279)."""
    return -(-length // bucket) * bucket
