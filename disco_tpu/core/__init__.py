from disco_tpu.core.mathx import (
    db2lin,
    lin2db,
    cart2pol,
    pol2cart,
    floor_to_multiple,
    round_to_base,
    my_mse,
    next_pow_2,
    WelfordsOnlineAlgorithm,
)
from disco_tpu.core.dsp import stft, istft, n_stft_frames, N_FFT, N_HOP, N_FREQ
from disco_tpu.core.masks import tf_mask, vad_oracle_batch, vad_to_mask
from disco_tpu.core import metrics, miscx, sigproc

__all__ = [
    "db2lin",
    "lin2db",
    "cart2pol",
    "pol2cart",
    "floor_to_multiple",
    "round_to_base",
    "my_mse",
    "next_pow_2",
    "WelfordsOnlineAlgorithm",
    "stft",
    "istft",
    "n_stft_frames",
    "N_FFT",
    "N_HOP",
    "N_FREQ",
    "tf_mask",
    "vad_oracle_batch",
    "vad_to_mask",
]
