"""Signal-level helpers: third-octave filterbanks, band-importance weights,
SNR scaling, speech-shaped noise, talker stacking, windowing.

Capability parity with reference ``disco_theque/sigproc_utils.py``
(third_octave_filterbank:90, fw_snr:120 — the fw_snr itself lives in
``disco_tpu.core.metrics``, increase_to_snr:194, stack_talkers:227,
noise_from_signal:257, third_octave_band:282).  These are host-side corpus /
evaluation utilities; the hot per-sample DSP lives in ``core.dsp`` /
``core.masks``.

The reference's filterbank depends on the ``acoustics`` package's
``OctaveBand`` for band edges; here the edges are the base-2 third-octave
ratios ``fc·2^(±1/6)`` documented in the reference's own ``third_octave_band``
(sigproc_utils.py:282-316) — within 0.04% of acoustics' base-10 convention.
"""
from __future__ import annotations

import numpy as np

from disco_tpu.core.mathx import next_pow_2

__all__ = [
    "third_octave_band",
    "third_octave_filterbank",
    "band_importance",
    "sliding_window",
    "frame_vad",
    "increase_to_snr",
    "noise_from_signal",
    "stack_talkers",
]

# ANSI band-importance weights (Pavlovic 1994), as tabulated in the reference
# (sigproc_utils.py:141-153): (weights*1e4, center frequencies) for wideband
# (fs/2 > 4500 Hz) and narrowband material.
_BIF_WIDE_I = np.array(
    [83, 95, 150, 289, 440, 578, 653, 711, 818, 844, 882, 898, 868, 844, 771, 527, 364, 185]
) * 1e-4
_BIF_WIDE_F = np.array(
    [160, 200, 250, 315, 400, 500, 630, 800, 1000, 1250, 1600, 2000, 2500, 3150, 4000, 5000, 6300, 8000]
)
_BIF_NARROW_I = np.array(
    [128, 320, 320, 447, 447, 639, 639, 767, 959, 1182, 1214, 1086, 1086, 757]
) * 1e-4
_BIF_NARROW_F = np.array(
    [200, 250, 315, 400, 500, 630, 800, 1000, 1250, 1600, 2000, 2500, 3150, 4000]
)


def band_importance(fs):
    """Band-importance weights and third-octave center frequencies kept below
    Nyquist (the band-selection logic of sigproc_utils.py:140-155)."""
    r = 2 ** (1 / 6)
    if fs / 2 > 4500:
        I, F = _BIF_WIDE_I, _BIF_WIDE_F
    else:
        I, F = _BIF_NARROW_I, _BIF_NARROW_F
    n = int(np.sum(F * r < fs / 2))
    return I[:n].copy(), F[:n].copy()


def third_octave_band(ref_freq=1000, i_band=None, n_band=18):
    """Center/lower/upper frequencies of a third-octave bank centered at
    ``ref_freq`` (sigproc_utils.py:282-316): fc = f0·2^(k/3), fl/fu = fc·2^(∓1/6)."""
    if i_band is not None:
        k = i_band
    else:
        k = np.arange(-np.floor((n_band - 1) / 2), np.floor(n_band / 2 + 1))
    fc = 2 ** (np.asarray(k) / 3) * ref_freq
    return fc, fc * 2 ** (-1 / 6), fc * 2 ** (1 / 6)


def third_octave_filterbank(F, fs, order=8):
    """Butterworth bandpass coefficient rows for third-octave bands centered
    at ``F`` (sigproc_utils.py:90-115).  Returns (b, a), each (len(F), 2·order+1)."""
    import scipy.signal

    F = np.asarray(F, np.float64)
    n = len(F)
    b = np.zeros((n, 2 * order + 1))
    a = np.zeros((n, 2 * order + 1))
    for i in range(n):
        lo, hi = F[i] * 2 ** (-1 / 6), F[i] * 2 ** (1 / 6)
        b[i], a[i] = scipy.signal.butter(
            order, np.array([lo, hi]) * 2 / fs, btype="bandpass", output="ba"
        )
    return b, a


def sliding_window(x, win_len, win_hop, axis=-1):
    """Overlapping windows of ``x``: shape (n_win, win_len) for 1-D input.
    (The helper metrics.py:159 imports but the reference never shipped.)"""
    x = np.moveaxis(np.asarray(x), axis, -1)
    n_win = 1 + (x.shape[-1] - win_len) // win_hop
    idx = np.arange(n_win)[:, None] * win_hop + np.arange(win_len)[None, :]
    return x[..., idx]


def frame_vad(vad, win_len, win_hop):
    """Downsample a sample-level VAD to one 0/1 value per analysis window
    (majority vote — the ``db_utils.frame_vad`` the reference imports but
    never shipped, metrics.py:145)."""
    w = sliding_window(np.asarray(vad, np.float64), win_len, win_hop)
    return (np.mean(w, axis=-1) >= 0.5).astype(np.float64)


def increase_to_snr(x, n, snr_out, vad_tar=None, vad_noi=None, weight=False, fs=None):
    """Scale noise ``n`` so SNR(x, n·scale) == ``snr_out`` dB
    (sigproc_utils.py:194-226).  With ``weight=True`` the SNR is the
    frequency-weighted one and scaling is applied in amplitude dB."""
    x = np.asarray(x)
    n = np.asarray(n)
    if weight:
        from disco_tpu.core.metrics import fw_snr

        _, snr_0, _ = fw_snr(x, n, fs, vad_tar=vad_tar, vad_noi=vad_noi)
        return n * 10 ** ((snr_0 - snr_out) / 20)
    var_x = np.var(x[vad_tar != 0]) if vad_tar is not None else np.var(x[x != 0])
    var_n = np.var(n[vad_noi != 0]) if vad_noi is not None else np.var(n[n != 0])
    return n * np.sqrt(10 ** (-snr_out / 10) * var_x / var_n)


def noise_from_signal(x, rng=None):
    """Speech-shaped noise: same magnitude spectrum as ``x``, random phase
    (sigproc_utils.py:257-279).  ``rng`` is an optional np.random.Generator
    for reproducibility (the reference uses the global numpy state)."""
    rng = np.random.default_rng() if rng is None else rng
    x = np.asarray(x)
    n_x = x.shape[-1]
    n_fft = next_pow_2(n_x)
    X = np.fft.rfft(x, next_pow_2(n_fft))
    noise_mag = np.abs(X) * np.exp(2j * np.pi * rng.random(X.shape[-1]))
    return np.real(np.fft.irfft(noise_mag, n_fft))[:n_x]


def stack_talkers(tlk_list, dur_min, speaker, nb_tlk=5, fs=16000, rng=None, read_fn=None):
    """Concatenate ≥``nb_tlk`` random talkers (≠ ``speaker``) until at least
    ``dur_min`` seconds (sigproc_utils.py:227-254).

    ``read_fn(path) -> (signal, fs)`` defaults to :func:`disco_tpu.io.read_wav`.
    Returns (signal, fs, newline-joined list of file stems used).
    """
    import os
    import re

    if read_fn is None:
        from disco_tpu.io import read_wav as read_fn
    rng = np.random.default_rng() if rng is None else rng
    i_tlk = 0
    tlk_tot = np.array([])
    str_files = ""
    while len(tlk_tot) < int(dur_min * fs) or i_tlk < nb_tlk:
        pick = int(rng.integers(0, len(tlk_list)))
        spk_tmp = re.split("/", str(tlk_list[pick]))[-1].split("-")[0]
        if spk_tmp != speaker:
            tlk_tmp, fs = read_fn(tlk_list[pick])
            tlk_tot = np.hstack((tlk_tot, tlk_tmp))
            i_tlk += 1
            str_files += os.path.basename(str(tlk_list[pick])).rsplit(".", 1)[0] + "\n"
    return tlk_tot, fs, str_files
