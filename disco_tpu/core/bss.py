"""BSS-eval source-separation metrics (Vincent, Gribonval & Fevotte, "Performance
measurement in blind audio source separation", IEEE TASLP 2006).

The reference scores enhancement with mir_eval's ``bss_eval_sources``
(reference tango.py:552-567), which admits a ``filt_len``-tap (512 by
convention) time-invariant FIR filtering of each reference source as
allowed distortion.  The scale-invariant family (``core.metrics.si_bss``,
Le Roux et al. 2019) admits only a scalar gain, so the two families are
*different metrics*: paper-table comparability (TASLP 2021) requires this
filtered-projection variant.  mir_eval is an undeclared dependency of the
reference and is not bundled here; the algorithm is implemented natively
from the published decomposition, and pinned in ``tests/test_bss.py``
against an independent brute-force least-squares oracle.

Definitions, for estimate e and references s_1..s_n (all length ``T``),
with P_W the orthogonal projection onto span{s_i delayed by 0..L-1 : i in W}:

    s_target = P_{j}(e)                 (target + admissible filtering)
    e_interf = P_{all}(e) - P_{j}(e)    (other-source leakage)
    e_artif  = e - P_{all}(e)           (everything else)

    SDR = 10 log10 ||s_target||^2 / ||e_interf + e_artif||^2
    SIR = 10 log10 ||s_target||^2 / ||e_interf||^2
    SAR = 10 log10 ||s_target + e_interf||^2 / ||e_artif||^2

All math is host-side float64, like every evaluation-time metric in this
package (the reference asserts f64 in metrics.py:376-377).
"""
from __future__ import annotations

import itertools

import numpy as np
import scipy.linalg
import scipy.signal

__all__ = ["bss_eval_sources", "bss_eval_one", "BssEval", "DEFAULT_FILT_LEN"]

DEFAULT_FILT_LEN = 512  # mir_eval's convention, used by the reference


def _gram(c, srcs, flen):
    """Assemble the block-Toeplitz Gram matrix over the given source subset:
    block (i, j) has entry [tau, tau'] = c[i, j, tau' - tau]."""
    n = len(srcs)
    G = np.empty((n * flen, n * flen))
    for a, i in enumerate(srcs):
        for b, j in enumerate(srcs):
            # Block entry [tau, tau'] = c_ij(tau - tau'); first column is
            # c_ij(tau), first row is c_ij(-tau') = c_ji(tau').
            col = c[i, j, :flen]
            row = c[j, i, :flen]
            G[a * flen : (a + 1) * flen, b * flen : (b + 1) * flen] = scipy.linalg.toeplitz(col, row)
    return G


def _factor_gram(G):
    """Factor the (SPD up to rank deficiency) Gram once: a Cholesky factor
    when it exists, plus the raw matrix for the lstsq fallback (silent or
    colinear references)."""
    try:
        return (scipy.linalg.cho_factor(G), G)
    except (np.linalg.LinAlgError, scipy.linalg.LinAlgError):
        return (None, G)


def _solve_coeffs(factor, d_cat):
    cho, G = factor
    if cho is not None:
        coef = scipy.linalg.cho_solve(cho, d_cat)
        if np.all(np.isfinite(coef)):
            return coef
    return np.linalg.lstsq(G, d_cat, rcond=None)[0]


class _Projector:
    """Least-squares FIR projector onto delayed spans of a fixed reference
    set.  Grams (full set and each single source) are built and factored
    once, then reused for every estimated source — the expensive part is
    per-reference-set, not per-estimate."""

    def __init__(self, refs, flen):
        self.refs = refs
        self.flen = flen
        self.nsrc, self.T = refs.shape
        self._n_fft = 1 << int(self.T + flen - 1).bit_length()
        self._R = np.fft.rfft(refs, self._n_fft, axis=1)
        # c[i, j, k] = sum_u refs[i, u] * refs[j, u + k], k stored mod n_fft
        self._c = np.fft.irfft(np.conj(self._R)[:, None, :] * self._R[None, :, :], self._n_fft, axis=-1)
        self._G = {}

    def project(self, est, srcs):
        """Projection of ``est`` onto span{refs[i] delayed 0..flen-1 : i in
        srcs}, returned with length T + flen - 1."""
        flen = self.flen
        # d[i, k] = sum_u refs[i, u] * est[u + k], k = 0..flen-1
        E = np.fft.rfft(est, self._n_fft)
        d = np.fft.irfft(np.conj(self._R) * E[None, :], self._n_fft, axis=-1)[:, :flen]
        key = tuple(srcs)
        if key not in self._G:
            self._G[key] = _factor_gram(_gram(self._c, srcs, flen))
        d_cat = np.concatenate([d[i] for i in srcs])
        coef = _solve_coeffs(self._G[key], d_cat).reshape(len(srcs), flen)
        proj = np.zeros(self.T + flen - 1)
        for a, i in enumerate(srcs):
            proj += scipy.signal.fftconvolve(self.refs[i], coef[a])[: self.T + flen - 1]
        return proj


def _safe_db(num, den):
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(10 * np.log10(num / den))


def _decompose(proj: _Projector, est, j):
    """(SDR, SIR, SAR) of ``est`` as an estimate of source ``j``."""
    flen, T = proj.flen, proj.T
    s_target = proj.project(est, [j])
    p_all = proj.project(est, list(range(proj.nsrc)))
    e_interf = p_all - s_target
    e_artif = -p_all
    e_artif[:T] += est
    sdr = _safe_db(np.sum(s_target**2), np.sum((e_interf + e_artif) ** 2))
    sir = _safe_db(np.sum(s_target**2), np.sum(e_interf**2))
    sar = _safe_db(np.sum((s_target + e_interf) ** 2), np.sum(e_artif**2))
    return sdr, sir, sar


class BssEval:
    """Reusable scorer: several estimates against ONE reference set.

    The Gram build + factorization is per-reference-set (the expensive part
    for 512 taps: a (nsrc*512)^2 block-Toeplitz solve); each ``score`` then
    costs one FFT correlation and two triangular solves.  Use this instead
    of repeated :func:`bss_eval_one` when scoring in/out/mid estimates
    against the same references, as the enhancement driver does."""

    def __init__(self, reference_sources, filt_len: int = DEFAULT_FILT_LEN):
        refs = np.atleast_2d(np.asarray(reference_sources, np.float64))
        self._proj = _Projector(refs, filt_len)

    def score(self, estimate, j: int = 0):
        """(SDR, SIR, SAR) of ``estimate`` as an estimate of source ``j``."""
        return _decompose(self._proj, np.asarray(estimate, np.float64), j)


def bss_eval_one(reference_sources, estimate, j: int = 0, filt_len: int = DEFAULT_FILT_LEN):
    """(SDR, SIR, SAR) of a single ``estimate`` against reference source
    ``j`` — the one entry the reference keeps from each of its
    ``bss_eval_sources(..., compute_permutation=False)[...][0]`` calls
    (tango.py:551-567), without paying for the discarded rows."""
    return BssEval(reference_sources, filt_len).score(estimate, j)


def bss_eval_sources(reference_sources, estimated_sources, compute_permutation: bool = True,
                     filt_len: int = DEFAULT_FILT_LEN):
    """SDR / SIR / SAR with ``filt_len``-tap filtered-reference projection —
    the metric family of mir_eval's ``bss_eval_sources`` as the reference
    uses it (tango.py:552-567, ``bss(refs, ests, compute_permutation=False)``).

    Args:
      reference_sources: (nsrc, nsampl) true sources.
      estimated_sources: (nsrc, nsampl) estimates.
      compute_permutation: when True, try every source permutation and keep
        the one with the best mean SIR (mir_eval semantics); when False,
        score estimate i against reference i.
      filt_len: admissible distortion filter length in taps.

    Returns:
      (sdr, sir, sar, perm): float64 arrays of shape (nsrc,); ``perm[i]`` is
      the reference index scored against estimate i.
    """
    refs = np.atleast_2d(np.asarray(reference_sources, np.float64))
    ests = np.atleast_2d(np.asarray(estimated_sources, np.float64))
    assert refs.shape == ests.shape, (refs.shape, ests.shape)
    nsrc = refs.shape[0]
    proj = _Projector(refs, filt_len)

    if not compute_permutation:
        vals = np.array([_decompose(proj, ests[i], i) for i in range(nsrc)])
        return vals[:, 0], vals[:, 1], vals[:, 2], np.arange(nsrc)

    table = np.full((nsrc, nsrc, 3), np.nan)
    for i in range(nsrc):
        for j in range(nsrc):
            table[i, j] = _decompose(proj, ests[i], j)
    best, best_sir = tuple(range(nsrc)), -np.inf
    for perm in itertools.permutations(range(nsrc)):
        mean_sir = np.mean([table[i, perm[i], 1] for i in range(nsrc)])
        # NaN SIRs (e.g. an all-zero estimate) never beat best_sir, so the
        # identity initialization keeps the degenerate case well-defined.
        if mean_sir > best_sir:
            best, best_sir = perm, mean_sir
    perm = np.array(best)
    picked = np.array([table[i, perm[i]] for i in range(nsrc)])
    return picked[:, 0], picked[:, 1], picked[:, 2], perm
